"""Chunked sharded ingest: byte-range parallel parse straight into row shards.

Reference: water/parser/ParseDataset.java:127 forkParseDataset — ParseDataset
is an MRTask over ~4 MB byte chunks of FileVecs: each map parses ONE chunk
where it lives, and two cheap distributed rounds resolve categorical domains
(:518 GatherCategoricalDomainsTask) and rewrite per-chunk codes
(:475 UpdateCategoricalChunksTask). No node ever stages a whole column.

TPU-native analog (ROADMAP item 4, the last ShardedFrame producer):

- **splitter** — one vectorized byte scan per file finds every RECORD
  boundary: newlines with an even count of quote bytes before them
  (RFC-4180 ``""`` escapes keep the parity correct, and no multi-byte UTF-8
  sequence can contain the 0x0A/quote bytes, so the byte-level scan is
  exact). Chunk edges snap to the next record end past each ~4 MB mark, so
  quoted embedded newlines, CRLF endings and multi-byte characters can
  never be split mid-record. The same scan yields exact per-chunk row
  counts, so the frame's padded row layout — and therefore which byte
  ranges each process owns — is known BEFORE any parse work runs.
- **worker pool** — chunks parse concurrently on host threads (pandas' C
  engine releases the GIL), so one large CSV fans out across every core
  instead of the old one-thread-per-file rule.
- **two-pass resolution** — chunks return per-chunk stats (categorical
  local domains + local codes, NA/row counts); the reduce is a cheap
  sorted union, after which per-chunk codes are REWRITTEN into the global
  domain (the GatherCategoricalDomains / UpdateCategoricalChunks rounds).
- **shard-tail assembly** — every chunk's rows land directly in the
  per-shard host buffers of their owning row shard; the device column is
  built with ``jax.make_array_from_callback`` over those buffers, so NO
  whole-column host buffer ever exists and each process materializes only
  its addressable shards. ``device_put`` of early columns overlaps host
  parse of later chunks (async dispatch), and bounded per-chunk buffers
  keep the host footprint flat ("Memory Safe Computations with XLA
  Compiler", PAPERS.md).
- **streaming append** — :func:`append_csv` rides the same chunk-tail
  machinery for ``POST /3/ParseStream``: a micro-batch parses with the
  frame's schema and every column extends through ONE fused device concat
  program (old shard rows + batch + pad, categorical codes remapped on
  device when new labels grow the sorted domain), with rollups merged
  incrementally instead of recomputed.

Counters make the zero-gather contract assertable (the ``gathered_rows``
analog): ``coordinator_ingest_bytes`` counts bytes staged as whole-column
host buffers inside ingest (the legacy/fallback paths) and must stay 0 on
the chunked path — ``GET /3/Metrics`` serves the ``h2o3_ingest_*`` family.

Multi-process note: when every column is device-typed (numeric/time), each
process parses ONLY the byte ranges overlapping its addressable shards;
frames with categorical/string columns parse all chunks on every process
(domains resolve identically without collectives) until the domain
all-reduce lands (gloo env limit, ROADMAP).
"""

from __future__ import annotations

import functools
import io
import os
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from h2o3_tpu.core.frame import (Column, NA_CAT, T_CAT, T_INT, T_NUM, T_STR,
                                 T_TIME, code_dtype, numeric_store_dtype)


class ChunkLayoutError(Exception):
    """A chunk parsed to a different row count than the splitter's record
    scan promised (non-RFC quoting, embedded quote bytes in unquoted
    fields, ...) — the caller falls back to the monolithic path, which
    handles such files exactly as before."""


# -- per-process ingest counters (the gathered_rows analog) ------------------

_LOCK = threading.Lock()
_CHUNKS = 0
_CHUNK_ROWS = 0
_COORD_BYTES = 0
_STREAM_APPENDS = 0
_STREAM_ROWS = 0
_OVERLAP = 0.0


def note_chunks(n: int) -> None:
    global _CHUNKS
    with _LOCK:
        _CHUNKS += int(n)


def note_chunk_rows(n: int) -> None:
    """Rows that entered the frame through the chunked sharded path."""
    global _CHUNK_ROWS
    with _LOCK:
        _CHUNK_ROWS += int(n)


def note_coordinator_bytes(n: int) -> None:
    """Bytes an ingest path staged as a WHOLE-column host buffer before
    device_put (legacy monolithic assembly, columnar/compressed fallbacks,
    lazy-parquet column loads) — the exceptional path the chunked pipeline
    exists to empty."""
    global _COORD_BYTES
    with _LOCK:
        _COORD_BYTES += int(n)


def note_stream_append(rows: int) -> None:
    global _STREAM_APPENDS, _STREAM_ROWS
    with _LOCK:
        _STREAM_APPENDS += 1
        _STREAM_ROWS += int(rows)


def set_overlap_ratio(r: float) -> None:
    global _OVERLAP
    with _LOCK:
        _OVERLAP = float(r)


def counters() -> dict:
    with _LOCK:
        return {"chunks": _CHUNKS, "chunk_rows": _CHUNK_ROWS,
                "coordinator_ingest_bytes": _COORD_BYTES,
                "stream_appends": _STREAM_APPENDS,
                "stream_rows": _STREAM_ROWS,
                "overlap_ratio": _OVERLAP}


def reset_counters() -> None:
    global _CHUNKS, _CHUNK_ROWS, _COORD_BYTES, _STREAM_APPENDS, _STREAM_ROWS
    global _OVERLAP
    with _LOCK:
        _CHUNKS = _CHUNK_ROWS = _COORD_BYTES = 0
        _STREAM_APPENDS = _STREAM_ROWS = 0
        _OVERLAP = 0.0


# -- knobs (sanctioned accessors — analysis KNOB_HELPERS entries) ------------

def enabled() -> bool:
    """Master switch for the chunked sharded ingest path
    (H2O_TPU_INGEST_CHUNKED, default on). Off = the legacy monolithic
    parse+concat assembly, kept for A/B verification. The legacy path
    prefers the native C parser for all-numeric CSVs, which emits NaN
    rows for blank lines where pandas (and the chunked path) skip them —
    a pre-existing native-vs-pandas divergence, so the A/B is bitwise
    except blank lines in all-numeric files."""
    return os.environ.get("H2O_TPU_INGEST_CHUNKED", "1").lower() not in (
        "0", "false", "off")


def chunk_bytes() -> int:
    """Target byte-range size (H2O_TPU_INGEST_CHUNK_BYTES, default 4 MB —
    the reference FileVec chunk size); record alignment may stretch a
    chunk past it. Clamped to >= 1 KB."""
    try:
        v = int(os.environ.get("H2O_TPU_INGEST_CHUNK_BYTES", str(4 << 20)))
    except ValueError:
        v = 4 << 20
    return max(v, 1024)


def ingest_workers() -> int:
    """Parse worker threads (H2O_TPU_INGEST_WORKERS, default
    min(16, cores)). The pandas C engine releases the GIL in its hot
    loop, so threads scale across cores without fork overhead."""
    try:
        v = int(os.environ.get("H2O_TPU_INGEST_WORKERS", "0"))
    except ValueError:
        v = 0
    if v <= 0:
        v = min(16, os.cpu_count() or 1)
    return max(v, 1)


def parquet_batch() -> int:
    """Adjacent lazy-parquet columns fetched per first-touch read
    (H2O_TPU_INGEST_PARQUET_BATCH, default 8)."""
    try:
        v = int(os.environ.get("H2O_TPU_INGEST_PARQUET_BATCH", "8"))
    except ValueError:
        v = 8
    return max(v, 1)


# ---------------------------------------------------------------------------
# splitter: vectorized record-boundary scan
# ---------------------------------------------------------------------------

@dataclass
class ByteChunk:
    path: str
    start: int          # byte offset, inclusive
    end: int            # byte offset, exclusive (a record end)
    row_offset: int     # logical frame row of this chunk's first record
    nrows: int          # non-blank data records inside (start, end]


# splitter scan window: EVERYTHING the splitter holds stays O(window),
# not O(file) — the memory-safe design the chunked pipeline exists for
# must hold in the splitter too (a 20 GB file must not allocate 20 GB of
# byte masks). Chunk boundaries are emitted incrementally per window
# (ISSUE 20), so the old O(records × 8B) record-position index (~1% of
# file size at 100-byte records — real memory at ~1B-record files, the
# recorded ROADMAP item-4 remainder) is gone: the resident state between
# windows is a handful of scalars plus the emitted chunk list itself.
_SCAN_WINDOW = 64 << 20


def split_file(path: str, setup, cbytes: int
               ) -> Tuple[List[Tuple[int, int, int]], int]:
    """-> ([(start, end, nrows)...], total_data_rows) for one CSV file.

    One streaming pass in fixed byte windows. Per window: find the
    quote-parity-even newlines (a record-end newline is preceded by an
    EVEN number of quote bytes — a running carry tracks parity across
    windows), classify blank records (empty, or a lone ``\\r`` — what
    pandas' skip_blank_lines drops), then close every chunk whose byte
    target lands inside the window. Chunk edges land ONLY on record
    ends, so no quoted newline, CRLF pair or multi-byte UTF-8 sequence
    ever splits; zero-row spans (runs of blank lines) merge into their
    neighbor. Carried state between windows: the quote-parity carry, the
    open chunk's start byte and pending row count — never a per-record
    array."""
    size = os.path.getsize(path)
    if size == 0:
        return [], 0
    quote_char = getattr(setup, "quote_char", '"')
    q = ord(quote_char) if quote_char else 0
    mm = np.memmap(path, dtype=np.uint8, mode="r")
    try:
        header_done = setup.check_header != 1
        chunks: List[Tuple[int, int, int]] = []
        pos: Optional[int] = 0 if header_done else None  # open chunk start
        pending = 0        # data rows in the open chunk from prior windows
        total = 0
        prev_end = 0       # start byte of the next record
        carry = 0          # quote bytes seen before this window
        for base in range(0, size, _SCAN_WINDOW):
            win = np.asarray(mm[base:base + _SCAN_WINDOW])
            nl = np.flatnonzero(win == 0x0A).astype(np.int64)
            if q:
                qloc = np.flatnonzero(win == q).astype(np.int64)
                before = carry + np.searchsorted(qloc, nl)
                nl = nl[(before & 1) == 0]
                carry += len(qloc)
            ends_w = nl + base + 1
            has_nl = np.ones(len(ends_w), bool)
            if base + len(win) >= size and \
                    (len(ends_w) == 0 or int(ends_w[-1]) != size):
                # unterminated tail record ends at EOF
                ends_w = np.append(ends_w, np.int64(size))
                has_nl = np.append(has_nl, False)
            if len(ends_w) == 0:
                continue
            starts_w = np.empty(len(ends_w), np.int64)
            starts_w[0] = prev_end
            starts_w[1:] = ends_w[:-1]
            prev_end = int(ends_w[-1])
            content = ends_w - starts_w - has_nl
            first_byte = np.asarray(mm[np.minimum(starts_w, size - 1)])
            blank_w = (content == 0) | ((content == 1)
                                        & (first_byte == 0x0D))
            if not header_done:
                nb = np.flatnonzero(~blank_w)
                if len(nb) == 0:
                    continue           # header record not in this window
                h = int(nb[0])
                header_done = True
                pos = int(ends_w[h])   # data starts after the header
                ends_w = ends_w[h + 1:]
                blank_w = blank_w[h + 1:]
                if len(ends_w) == 0:
                    continue
            data_ends_w = ends_w[~blank_w]
            total += int(len(data_ends_w))
            # close every chunk whose byte target has a record end here
            while True:
                target = pos + cbytes
                if target > int(ends_w[-1]):
                    break
                i = int(np.searchsorted(ends_w, target))
                end = int(ends_w[i])
                nr = pending + int(
                    np.searchsorted(data_ends_w, end, side="right")
                    - np.searchsorted(data_ends_w, pos, side="right"))
                pending = 0
                if nr > 0:
                    chunks.append((pos, end, nr))
                elif chunks:
                    # blank-only span: fold into the previous chunk
                    s0, _e0, n0 = chunks[-1]
                    chunks[-1] = (s0, end, n0)
                pos = end
            pending += int(len(data_ends_w)
                           - np.searchsorted(data_ends_w, pos,
                                             side="right"))
        if total == 0:
            return [], 0
        if pos is not None and pos < prev_end:
            # final partial chunk up to the last record end (== EOF)
            if pending > 0:
                chunks.append((pos, prev_end, pending))
            elif chunks:
                s0, _e0, n0 = chunks[-1]
                chunks[-1] = (s0, prev_end, n0)
        return chunks, total
    finally:
        del mm


# ---------------------------------------------------------------------------
# chunk parser (pandas C engine over one byte range)
# ---------------------------------------------------------------------------

def _parse_chunk(path: str, start: int, end: int, setup
                 ) -> Dict[str, np.ndarray]:
    """Parse one byte range; the header is never inside a chunk (the
    splitter starts chunk 0 after it). T_TIME columns come back RAW
    (object strings): pandas' to_datetime infers the format from the
    WHOLE column, so per-chunk conversion of ambiguous dates (01/02/2020
    vs 13/01/2020) could silently diverge from the monolithic path — the
    resolve pass converts once, column-wide."""
    with open(path, "rb") as f:
        f.seek(start)
        buf = f.read(end - start)
    return _parse_chunk_bytes(buf, setup, raw_time=True)


def _parse_chunk_bytes(buf: bytes, setup,
                       raw_time: bool = False) -> Dict[str, np.ndarray]:
    """Parse raw record bytes with EXACTLY the monolithic path's read_csv
    arguments (the shared parser.csv_read_kwargs block) so per-token
    conversion is bitwise-identical — used by byte-range chunks and
    /3/ParseStream micro-batches."""
    import pandas as pd

    from h2o3_tpu.ingest.parser import csv_read_kwargs

    # python string storage, global + idempotent — same rationale as
    # _parse_csv_host: pandas-3 arrow-backed strings have segfaulted under
    # concurrent thread-pool parses
    pd.set_option("mode.string_storage", "python")
    df = pd.read_csv(io.BytesIO(buf), header=None,
                     **csv_read_kwargs(setup))
    from h2o3_tpu.ingest.parser import _dt_to_ms

    out: Dict[str, np.ndarray] = {}
    for name, t in zip(setup.column_names, setup.column_types):
        s = df[name]
        if t in (T_CAT, T_STR):
            out[name] = s.to_numpy(dtype=object)
        elif t == T_TIME:
            out[name] = (s.to_numpy(dtype=object) if raw_time
                         else _dt_to_ms(pd.to_datetime(s, errors="coerce")))
        else:
            out[name] = s.to_numpy(dtype=np.float64)
    return out


def _resolve_time_column(parts: List[Tuple[int, np.ndarray]],
                         total: int) -> np.ndarray:
    """Whole-column datetime conversion for a T_TIME column's chunk parts
    (raw object strings in row order): ONE pd.to_datetime over the full
    column so format inference sees exactly what the monolithic path's
    did — per-chunk inference could read ambiguous dates differently."""
    import pandas as pd

    from h2o3_tpu.ingest.parser import _dt_to_ms

    obj = np.empty(total, object)
    for off, arr in sorted(parts, key=lambda t: t[0]):
        obj[off:off + len(arr)] = arr
    ms = _dt_to_ms(pd.to_datetime(pd.Series(obj), errors="coerce"))
    # honesty: this IS a whole-column host buffer — time columns are the
    # documented carve-out from the zero-coordinator-bytes contract
    note_coordinator_bytes(ms.nbytes)
    return ms


def _intern_chunk(a: np.ndarray) -> Tuple[List[str], np.ndarray]:
    """Per-chunk categorical interning: local sorted domain + local codes,
    semantically identical to core.frame._intern_domain (None/NaN/"" are
    NA, domain sorted lexicographically) but vectorized through
    ``pd.factorize`` — the python-loop interning was the serial, GIL-bound
    hot spot that ate the chunk pool's parallelism. read_csv object
    columns hold only str/NaN, so factorize's sorted uniques ARE
    sorted(set(str values))."""
    import pandas as pd

    s = pd.Series(a, dtype=object)
    na = s.isna().to_numpy() | (s == "").to_numpy()
    codes, uniq = pd.factorize(s.where(~na, None), sort=True)
    return [str(u) for u in uniq], codes.astype(np.int32)


def _remap_codes(gdom: List[str], dom: List[str],
                 codes: np.ndarray) -> np.ndarray:
    """Rewrite one chunk's LOCAL codes into the global sorted domain (the
    UpdateCategoricalChunksTask round): host-only lookup-table gather, NA
    (-1) passes through."""
    if not dom:
        return codes
    lut = np.searchsorted(np.asarray(gdom), np.asarray(dom)).astype(np.int32)
    return np.where(codes < 0, np.int32(NA_CAT),
                    lut[np.clip(codes, 0, len(dom) - 1)])


def _grow_domain(old_dom: List[str], batch_obj: np.ndarray
                 ) -> Tuple[List[str], np.ndarray, np.ndarray]:
    """Streaming-append domain resolution: -> (new sorted domain, batch
    codes in it, perm mapping old code -> new code). Keeping the domain
    SORTED (old codes renumbered on device via perm) makes the appended
    frame bitwise what a cold parse of the concatenated data produces."""
    from h2o3_tpu.core.frame import _intern_domain

    bdom, bcodes_local = _intern_domain(batch_obj)
    new_dom = sorted(set(old_dom) | set(bdom))
    bcodes = _remap_codes(new_dom, bdom, bcodes_local)
    if old_dom:
        perm = np.searchsorted(np.asarray(new_dom),
                               np.asarray(old_dom)).astype(np.int32)
    else:
        perm = np.zeros(1, np.int32)
    return new_dom, bcodes, perm


# ---------------------------------------------------------------------------
# shard-tail assembly
# ---------------------------------------------------------------------------

def _shard_fill_dtype(ctype: str, card: int):
    if ctype == T_CAT:
        return NA_CAT, code_dtype(card)
    return np.nan, numeric_store_dtype(ctype)


def _write_rows(bufs: dict, shard_rows: int, addressable: set, row0: int,
                arr: np.ndarray, fill, dtype) -> None:
    """Scatter a chunk's column slice into its owning per-shard buffers
    (allocating lazily); rows outside this process's addressable shards
    are skipped."""
    i = 0
    n = len(arr)
    while i < n:
        r = row0 + i
        s = r // shard_rows
        lo = r - s * shard_rows
        take = min(shard_rows - lo, n - i)
        if s in addressable:
            b = bufs.get(s)
            if b is None:
                b = bufs[s] = np.full(shard_rows, fill, dtype)
            b[lo:lo + take] = arr[i:i + take].astype(dtype)
        i += take


def _device_from_shards(cl, padded: int, shard_rows: int, bufs: dict,
                        fill, dtype):
    """Row-sharded device array from per-shard host buffers — the
    no-whole-column device_put. Async per-shard H2D; missing shards (rows
    this process never parsed on the numeric-only multi-process path that
    also happen to be all-pad) fill with the NA sentinel."""
    import jax

    sh = cl.row_sharding()

    def cb(idx):
        sl = idx[0]
        s = (sl.start or 0) // shard_rows
        b = bufs.get(s)
        if b is None:
            b = np.full(shard_rows, fill, dtype)
        return b

    return jax.make_array_from_callback((padded,), sh, cb)


# ---------------------------------------------------------------------------
# the chunked parse pipeline
# ---------------------------------------------------------------------------

def eligible(paths: Sequence[str], setup) -> bool:
    """The chunked path needs byte-addressable uncompressed CSV text and a
    resolved schema; anything else keeps the legacy path (and counts its
    bytes as coordinator_ingest_bytes)."""
    if not enabled():
        return False
    if setup.parse_type != "CSV":
        return False
    if not setup.column_names or not setup.column_types:
        return False
    if len(setup.column_names) != len(setup.column_types):
        return False
    for p in paths:
        if p.endswith(".gz") or p.endswith(".zip") or not os.path.isfile(p):
            return False
    return True


def parse_csv_sharded(paths: Sequence[str], setup
                      ) -> Optional[Dict[str, Column]]:
    """Full pipeline: split -> pooled chunk parse -> domain resolve ->
    shard-tail device assembly. Returns {name: Column} in setup column
    order, or None when the input is ineligible / empty (caller keeps the
    legacy path). Raises :class:`ChunkLayoutError` when a chunk's parsed
    row count contradicts the splitter's scan (caller falls back)."""
    from concurrent.futures import ThreadPoolExecutor, as_completed

    from h2o3_tpu.core.runtime import cluster
    from h2o3_tpu.obs import metrics as obs_metrics
    from h2o3_tpu.obs import tracing

    if not eligible(paths, setup):
        return None
    cl = cluster()
    names = list(setup.column_names)
    types = list(setup.column_types)
    cbytes = chunk_bytes()

    t_wall0 = time.perf_counter()
    with tracing.span("ingest_split", files=len(paths)):
        chunks: List[ByteChunk] = []
        total = 0
        for p in paths:
            ch, rows = split_file(p, setup, cbytes)
            off = total
            for (s, e, nr) in ch:
                chunks.append(ByteChunk(p, s, e, off, nr))
                off += nr
            total += rows
    t_split = time.perf_counter() - t_wall0
    if total == 0:
        return None

    from h2o3_tpu.core.sharded_frame import shard_geometry

    padded = cl.pad_rows(total)
    shard_rows, addressable = shard_geometry(cl, padded)

    # byte-range ownership: numeric-only frames parse only the chunks
    # overlapping this process's shards; cat/str/time frames parse
    # everything (domains and whole-column datetime inference resolve
    # identically everywhere without a collective)
    import jax

    only_numeric = all(t in (T_NUM, T_INT) for t in types)
    if jax.process_count() > 1 and only_numeric:
        lo_hi = sorted((s * shard_rows, (s + 1) * shard_rows)
                       for s in addressable)
        my_chunks = [c for c in chunks
                     if any(c.row_offset < hi and c.row_offset + c.nrows > lo
                            for lo, hi in lo_hi)]
        # count only the rows this process LANDS: a boundary-straddling
        # chunk parses on two processes but each owns a disjoint subset,
        # and the cluster-summed chunk_rows must equal the frame's rows
        counted_rows = sum(
            max(0, min(c.row_offset + c.nrows, hi) - max(c.row_offset, lo))
            for c in my_chunks for lo, hi in lo_hi)
    else:
        my_chunks = list(chunks)
        counted_rows = sum(c.nrows for c in my_chunks)

    num_bufs: Dict[str, dict] = {n: {} for n, t in zip(names, types)
                                 if t in (T_NUM, T_INT)}
    # CSV numerics land as T_NUM like the monolithic path (from_numpy on
    # a float64 array); times stay T_TIME — the dtype rule follows the
    # NORMALIZED ctype so bf16 opt-in matches from_numpy exactly
    num_ct = {n: (T_TIME if t == T_TIME else T_NUM)
              for n, t in zip(names, types) if t in (T_NUM, T_INT, T_TIME)}
    num_layout = {n: _shard_fill_dtype(ct, 0) for n, ct in num_ct.items()}
    cat_parts: Dict[str, list] = {n: [] for n, t in zip(names, types)
                                  if t == T_CAT}
    str_parts: Dict[str, list] = {n: [] for n, t in zip(names, types)
                                  if t == T_STR}
    time_parts: Dict[str, list] = {n: [] for n, t in zip(names, types)
                                   if t == T_TIME}

    def work(c: ByteChunk):
        t0 = time.perf_counter()
        try:
            cols = _parse_chunk(c.path, c.start, c.end, setup)
        except Exception as e:   # noqa: BLE001 — a mis-split chunk (non-
            # RFC quoting defeating the record scan) can make pandas raise
            # mid-record ParserErrors the row-count check never sees; ANY
            # chunk-parse failure routes to the monolithic fallback, which
            # either parses the file fine or surfaces the real error
            raise ChunkLayoutError(
                f"{c.path}[{c.start}:{c.end}] failed to parse as a "
                f"record-aligned chunk ({type(e).__name__}: {e}) — "
                f"falling back to the monolithic path") from e
        got = len(cols[names[0]]) if names else 0
        if got != c.nrows:
            raise ChunkLayoutError(
                f"{c.path}[{c.start}:{c.end}] parsed {got} rows, splitter "
                f"promised {c.nrows} (non-RFC quoting?) — falling back to "
                f"the monolithic path")
        interned = {n: _intern_chunk(cols[n]) for n in cat_parts}
        return cols, interned, time.perf_counter() - t0

    def _consume(c: ByteChunk, cols, interned, dt: float) -> None:
        nonlocal t_parse_serial
        t_parse_serial += dt
        obs_metrics.observe("h2o3_ingest_parse_seconds", dt)
        for nm in num_bufs:
            fill, dt_ = num_layout[nm]
            _write_rows(num_bufs[nm], shard_rows, addressable,
                        c.row_offset, cols[nm], fill, dt_)
        for nm in cat_parts:
            dom, codes = interned[nm]
            cat_parts[nm].append((c.row_offset, dom, codes))
        for nm in str_parts:
            str_parts[nm].append((c.row_offset, cols[nm]))
        for nm in time_parts:
            time_parts[nm].append((c.row_offset, cols[nm]))

    t_parse_serial = 0.0
    with tracing.span("ingest_parse", chunks=len(my_chunks), rows=total):
        workers = min(ingest_workers(), max(len(my_chunks), 1))
        with ThreadPoolExecutor(max_workers=workers) as pool:
            futs = {pool.submit(work, c): c for c in my_chunks}
            try:
                for fut in as_completed(futs):
                    cols, interned, dt = fut.result()
                    _consume(futs[fut], cols, interned, dt)
                    del cols, interned     # bounded per-chunk buffers
            except ChunkLayoutError:
                # don't let the with-exit's shutdown(wait=True) parse
                # every still-queued chunk of a file that's headed for
                # the monolithic fallback anyway
                pool.shutdown(wait=False, cancel_futures=True)
                raise

    t1 = time.perf_counter()
    cat_bufs: Dict[str, dict] = {}
    domains: Dict[str, List[str]] = {}
    with tracing.span("ingest_resolve", cats=len(cat_parts),
                      times=len(time_parts)):
        for nm, parts in time_parts.items():
            ms = _resolve_time_column(parts, total)
            fill, dt_ = num_layout[nm]
            bufs: dict = {}
            _write_rows(bufs, shard_rows, addressable, 0, ms, fill, dt_)
            num_bufs[nm] = bufs
        for nm, parts in cat_parts.items():
            gdom_set = set()
            for _off, dom, _codes in parts:
                gdom_set.update(dom)
            gdom = sorted(gdom_set)
            domains[nm] = gdom
            fill, cdt = _shard_fill_dtype(T_CAT, len(gdom))
            bufs: dict = {}
            for off, dom, codes in sorted(parts):
                g = _remap_codes(gdom, dom, codes)
                _write_rows(bufs, shard_rows, addressable, off, g, fill,
                            cdt)
            cat_bufs[nm] = bufs
    t_resolve = time.perf_counter() - t1

    t2 = time.perf_counter()
    out: Dict[str, Column] = {}
    with tracing.span("ingest_ship", columns=len(names), rows=total):
        for nm, t in zip(names, types):
            if t in (T_NUM, T_INT, T_TIME):
                fill, dt_ = num_layout[nm]
                data = _device_from_shards(cl, padded, shard_rows,
                                           num_bufs[nm], fill, dt_)
                out[nm] = Column.from_device(data, num_ct[nm], total)
            elif t == T_CAT:
                dom = domains[nm]
                fill, cdt = _shard_fill_dtype(T_CAT, len(dom))
                data = _device_from_shards(cl, padded, shard_rows,
                                           cat_bufs[nm], fill, cdt)
                out[nm] = Column.from_device(data, T_CAT, total, domain=dom)
            else:
                parts = sorted(str_parts[nm])
                obj = np.empty(total, object)
                for off, arr in parts:
                    obj[off:off + len(arr)] = arr
                out[nm] = Column(None, T_STR, total, host_data=obj)
    t_ship = time.perf_counter() - t2
    t_total = time.perf_counter() - t_wall0

    note_chunks(len(my_chunks))
    note_chunk_rows(counted_rows)
    serial = t_split + t_parse_serial + t_resolve + t_ship
    ratio = 1.0 - t_total / max(serial, 1e-9)
    set_overlap_ratio(min(max(ratio, 0.0), 1.0))
    return out


# ---------------------------------------------------------------------------
# streaming append (POST /3/ParseStream)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=64)
def _append_fast_fn(old_padded: int, new_padded: int, b: int, out_dt: str,
                    is_cat: bool, mesh):
    """(old, batch, n) -> grown row-sharded column: capacity extends with
    sentinel fill when the padded size grew, then the batch lands at
    traced row ``n`` via dynamic_update_slice. Because ``n`` is TRACED,
    the compile key is only (padded sizes, batch size, dtype) — a steady
    micro-batch stream re-hits one compiled program until the padded
    capacity actually crosses a shard-granule boundary (the old
    static-(n,b) keys recompiled on EVERY append). Rows [n, old_padded)
    are already the sentinel by the padding convention, so preserving
    them is the old explicit head-slice+pad bitwise."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    odt = jnp.dtype(out_dt)

    def fn(old, batch, n):
        x = old
        if new_padded != old_padded:
            fill = (jnp.int32(NA_CAT).astype(odt) if is_cat
                    else jnp.full((), jnp.float32(np.nan), odt))
            x = jnp.concatenate(
                [x, jnp.full((new_padded - old_padded,), fill, odt)])
        # n + b <= pad_rows(n + b) == new_padded, so the start never clamps
        return jax.lax.dynamic_update_slice(x, batch.astype(odt), (n,))

    from h2o3_tpu.core.sharded_frame import ROW_AXIS

    return jax.jit(fn, out_shardings=NamedSharding(mesh, P(ROW_AXIS)))


@functools.lru_cache(maxsize=64)
def _append_cat_fn(n: int, b: int, new_padded: int, in_dt: str, out_dt: str,
                   remap_len: int, mesh):
    """Categorical variant: old codes remap through `perm` (old code ->
    code in the grown SORTED domain) on device, batch codes are already
    global, pad is the NA sentinel."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    odt = jnp.dtype(out_dt)

    def fn(old, batch, perm):
        codes = old.astype(jnp.int32)
        safe = jnp.clip(codes, 0, max(remap_len - 1, 0))
        head = jnp.where(codes < 0, jnp.int32(NA_CAT), perm[safe])[:n]
        pad = jnp.full((new_padded - n - b,), jnp.int32(NA_CAT), odt)
        return jnp.concatenate([head.astype(odt), batch.astype(odt), pad])

    from h2o3_tpu.core.sharded_frame import ROW_AXIS

    return jax.jit(fn, out_shardings=NamedSharding(mesh, P(ROW_AXIS)))


def _merge_rollups(old, batch: np.ndarray, is_cat: bool):
    """Incremental rollup fold: combine a column's cached Rollups with the
    micro-batch's host stats (Chan/Welford merge) so streaming appends
    never re-reduce the whole column."""
    from h2o3_tpu.ops.rollups import Rollups

    if is_cat:
        valid = batch >= 0
        x = batch[valid].astype(np.float32)
    else:
        valid = ~np.isnan(batch)
        x = batch[valid].astype(np.float32)
    n2 = int(valid.sum())
    na2 = int(len(batch) - n2)
    nz2 = int((x != 0).sum())
    rows = old.rows + n2
    na = old.na_count + na2
    nz = old.nz_count + nz2
    if n2 == 0:
        return Rollups(old.min, old.max, old.mean, old.sigma, na, nz,
                       rows)
    s2 = float(np.sum(x, dtype=np.float32))
    ss2 = float(np.sum(x * x, dtype=np.float32))
    mn2, mx2 = float(x.min()), float(x.max())
    if old.rows == 0:
        mean = s2 / n2
        var = max(ss2 / n2 - mean * mean, 0.0)
        sigma = float(np.sqrt(var * n2 / (n2 - 1))) if n2 > 1 else 0.0
        return Rollups(mn2, mx2, mean, sigma, na, nz, rows)
    s1 = old.mean * old.rows
    var1 = (old.sigma ** 2) * (old.rows - 1) / old.rows \
        if old.rows > 1 else 0.0
    ss1 = (var1 + old.mean ** 2) * old.rows
    mean = (s1 + s2) / rows
    var = max((ss1 + ss2) / rows - mean * mean, 0.0)
    sigma = float(np.sqrt(var * rows / (rows - 1))) if rows > 1 else 0.0
    return Rollups(min(old.min, mn2), max(old.max, mx2), mean, sigma, na,
                   nz, rows)


def stream_separator(frame, separator: Optional[str] = None) -> str:
    """The separator a micro-batch parses with: explicit request arg,
    else the separator the frame was ORIGINALLY imported with (a
    tab-separated frame must not need every /3/ParseStream call to
    repeat it), else ','."""
    opts = getattr(frame, "_parse_opts", None) or {}
    return separator or opts.get("separator") or ","


def _stream_setup(frame, separator: Optional[str] = None):
    """ParseSetup for a micro-batch: the frame's schema PLUS the parse
    options the frame was originally imported with (parser.parse records
    them as ``frame._parse_opts``) — a frame parsed with custom
    ``na_strings`` or a non-comma separator must read streamed tokens
    exactly as a cold parse of the concatenated data would."""
    from h2o3_tpu.ingest.parse_setup import ParseSetup

    names = frame.names
    for n in names:
        c = frame.col(n)
        if c.ctype == T_CAT and c.domain is None:
            # integer-coded cat with no label domain: batch TOKENS cannot
            # be interned into it, and _grow_domain's empty-old-domain perm
            # would silently remap every existing code — refuse instead
            raise ValueError(
                f"cannot stream-append: column {n!r} is categorical with "
                f"no domain (integer-coded); batch labels cannot be "
                f"resolved against it")
    setup = ParseSetup(separator=stream_separator(frame, separator),
                       check_header=-1, column_names=list(names),
                       column_types=[frame.col(n).ctype for n in names])
    opts = getattr(frame, "_parse_opts", None) or {}
    if opts.get("na_strings"):
        setup.na_strings = list(opts["na_strings"])
    if opts.get("quote_char"):
        setup.quote_char = opts["quote_char"]
    return setup


def _check_arity(text: str, setup) -> None:
    """Every record must carry EXACTLY the frame's column count: pandas
    would otherwise silently consume an extra leading field as the index
    (shifting the whole row) or NA-fill short rows — a streaming client's
    stray delimiter must be a clean error, never quiet corruption."""
    import csv

    ncols = len(setup.column_names)
    # skipinitialspace matches csv_read_kwargs: '1.5, "a,b"' is 2 fields
    # to the pandas parser and must be 2 fields here too
    rdr = csv.reader(io.StringIO(text), delimiter=setup.separator,
                     quotechar=setup.quote_char or '"',
                     skipinitialspace=True)
    # csv's default 128 KB field cap would false-reject large quoted
    # fields pandas parses fine; the cap is module-global, so raise it
    # rather than scope it (restoring would race concurrent validates)
    if csv.field_size_limit() < (64 << 20):
        csv.field_size_limit(64 << 20)
    try:
        for i, row in enumerate(rdr):
            if not row:
                continue                # blank line (pandas skip semantics)
            if len(row) != ncols:
                raise ValueError(
                    f"stream batch row {i + 1} has {len(row)} fields but "
                    f"the frame has {ncols} columns (rows must be "
                    f"header-less, columns in frame order)")
    except csv.Error as e:              # NUL bytes, unreadable quoting —
        # a malformed batch must be a clean client error, never a 500
        raise ValueError(f"stream batch failed the CSV field scan: {e}") \
            from e


def validate_batch(frame, text: str,
                   separator: Optional[str] = None) -> None:
    """Preflight a /3/ParseStream micro-batch BEFORE the oplog broadcast:
    arity per record, then a full parse under the frame's schema. A bad
    batch (stray delimiter, non-numeric token in a numeric column) must
    surface as a clean client error on the coordinator — raising inside
    every follower's mirrored replay would fail the whole cloud. Raises
    ValueError with the reason."""
    setup = _stream_setup(frame, separator)
    _check_arity(text, setup)
    data = text if text.endswith("\n") else text + "\n"
    try:
        _parse_chunk_bytes(data.encode("utf-8"), setup)
    except ValueError:
        raise
    except Exception as e:              # pandas ParserError and friends
        raise ValueError(
            f"batch does not parse under the frame's schema "
            f"({type(e).__name__}: {e})") from e


def _extend_time_host(old: np.ndarray, batch_ms: np.ndarray) -> np.ndarray:
    """Grow a T_TIME column's exact epoch-millis host copy (kept for
    datetime/int-sourced frames, e.g. parquet): rapids time prims prefer
    this buffer over the f32 device store, whose ~2e5 ms granularity at
    modern epochs would shift EVERY pre-existing timestamp if one append
    dropped it. float64 ms values are exact integers (< 2^53), so the
    datetime64[ms] round-trip is lossless; NaN batch entries land NaT."""
    old_dt = (old.astype("datetime64[ms]") if old.dtype.kind == "M"
              else old.astype(np.int64).astype("datetime64[ms]"))
    b = np.full(len(batch_ms), np.datetime64("NaT"), "datetime64[ms]")
    ok = ~np.isnan(batch_ms.astype(np.float64))
    b[ok] = batch_ms[ok].astype(np.int64).astype("datetime64[ms]")
    return np.concatenate([old_dt, b])


# appends serialize process-wide: the REST server is threaded and a
# single-process cloud has no op turnstile, so two concurrent
# /3/ParseStream requests reading the same base columns would each build
# n+b twins and the second swap would silently drop the first batch
_APPEND_LOCK = threading.Lock()


def append_csv(frame, text: str,
               separator: Optional[str] = None) -> int:
    """Stream-append a CSV micro-batch (rows only, NO header, columns in
    frame order) to an installed frame: every column grows through one
    fused device concat into its new shard tail, domains stay SORTED
    (old codes remapped on device when new labels arrive — bitwise what a
    cold parse of the concatenated data produces), and cached rollups
    merge incrementally. Returns the number of appended rows.

    T_TIME caveat: the batch's datetimes convert with per-batch format
    inference — ambiguous non-ISO formats should be avoided in streams
    (the cold-parse twin infers over the whole column)."""
    with _APPEND_LOCK:
        return _append_csv_locked(frame, text, separator)


def _append_csv_locked(frame, text: str,
                       separator: Optional[str]) -> int:
    import jax.numpy as jnp

    from h2o3_tpu.core.runtime import cluster
    from h2o3_tpu.obs import tracing

    names = frame.names
    if not names:
        raise ValueError("cannot stream-append to an empty frame")
    cols = [frame.col(n) for n in names]
    setup = _stream_setup(frame, separator)
    _check_arity(text, setup)
    data = text if text.endswith("\n") else text + "\n"
    # ride the chunk parser verbatim (same pandas args as any other chunk)
    batch = _parse_chunk_bytes(data.encode("utf-8"), setup)
    b = len(batch[names[0]])
    if b == 0:
        return 0
    cl = cluster()
    n = frame.nrows
    new_n = n + b
    new_padded = cl.pad_rows(new_n)

    new_cols: Dict[str, Column] = {}
    with tracing.span("ingest_stream_append", rows=b, total=new_n):
        for nm, c in zip(names, cols):
            had_rollups = c._rollups
            batch_stats = None      # host values feeding the rollup merge
            if c.ctype == T_STR:
                obj = np.empty(new_n, object)
                obj[:n] = c.host_data[:n]
                obj[n:] = batch[nm]
                newc = Column(None, T_STR, new_n, host_data=obj)
            elif c.ctype == T_CAT:
                old_dom = list(c.domain or [])
                new_dom, bcodes, perm = _grow_domain(old_dom, batch[nm])
                out_dt = code_dtype(len(new_dom))
                old_data = c.data
                old_padded = old_data.shape[0]  # shape is host metadata
                if new_dom == old_dom and \
                        np.dtype(out_dt) == old_data.dtype:
                    # steady state (no new labels): the traced-n fast
                    # path — zero compiles while padded capacity holds
                    fn = _append_fast_fn(old_padded, new_padded, b,
                                         str(np.dtype(out_dt)), True,
                                         cl.mesh)
                    data_new = fn(old_data, bcodes.astype(out_dt),
                                  jnp.int32(n))
                else:
                    fn = _append_cat_fn(n, b, new_padded,
                                        str(old_data.dtype),
                                        str(np.dtype(out_dt)),
                                        max(len(old_dom), 1), cl.mesh)
                    data_new = fn(old_data, bcodes.astype(out_dt),
                                  jnp.asarray(perm))
                newc = Column.from_device(data_new, T_CAT, new_n,
                                          domain=new_dom)
                batch_stats = bcodes.astype(np.int32)
                if had_rollups is not None and old_dom != new_dom:
                    # old codes were renumbered into the grown domain:
                    # min/max/mean over CODES are stale — recompute lazily
                    had_rollups = None
            else:
                old_data = c.data
                out_dt = str(old_data.dtype)
                bvals = batch[nm].astype(old_data.dtype)
                fn = _append_fast_fn(old_data.shape[0], new_padded,
                                     b, out_dt, False, cl.mesh)
                data_new = fn(old_data, bvals, jnp.int32(n))
                newc = Column.from_device(data_new, c.ctype, new_n)
                if c.ctype == T_TIME and c.host_data is not None and \
                        c.host_data.dtype.kind in "Mi":
                    newc.host_data = _extend_time_host(c.host_data[:n],
                                                       batch[nm])
                # merge stats over the STORAGE-dtype values (bvals), not
                # the raw float64 batch: on bf16 opt-in clusters the
                # column holds quantized values and the rollups must
                # describe what a recompute would see
                batch_stats = bvals
            if had_rollups is not None and batch_stats is not None:
                newc._rollups = _merge_rollups(had_rollups, batch_stats,
                                               c.ctype == T_CAT)
            new_cols[nm] = newc
        frame.swap_columns(new_cols)
    note_stream_append(b)
    return b
