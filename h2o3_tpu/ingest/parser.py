"""Parse orchestration: files -> typed host buffers -> sharded device Frame.

Reference: water/parser/ParseDataset.java:127 forkParseDataset — an MRTask
over the byte-chunks of FileVecs where each map parses one 4MB chunk to
NewChunks, then two more distributed rounds union + renumber categorical
domains (:518 GatherCategoricalDomainsTask, :475 UpdateCategoricalChunksTask).

TPU-native: CSV files ride the CHUNKED SHARDED pipeline (ingest/chunked.py)
— record-aligned ~4 MB byte ranges parse concurrently across cores, per-chunk
domain stats reduce cheaply, and every chunk's rows land directly in their
owning row shard's buffers (``make_array_from_callback``), so no whole
column is ever staged on one host (``coordinator_ingest_bytes`` stays 0; on
multi-process clouds each process parses only numeric byte ranges it owns).
Non-CSV / compressed formats keep the legacy monolithic path (host parse →
per-column concat → device_put), whose staged bytes the counter records."""

from __future__ import annotations

import glob as _glob
import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from h2o3_tpu.core.frame import Column, Frame, NA_CAT, T_CAT, T_NUM, T_STR, T_TIME
from h2o3_tpu.frame_factory import H2OFrame
from h2o3_tpu.ingest.parse_setup import ParseSetup, guess_setup, open_stream
from h2o3_tpu.utils import log


def parse_setup(paths, **kw) -> ParseSetup:
    p = paths[0] if isinstance(paths, (list, tuple)) else paths
    return guess_setup(p, **kw)


def csv_read_kwargs(setup: ParseSetup) -> dict:
    """The ONE pandas read_csv argument block — shared verbatim by the
    monolithic path below and the chunked byte-range parser
    (ingest/chunked._parse_chunk_bytes). The chunked path's bitwise
    contract with its monolithic fallback depends on per-token conversion
    being identical, so NA handling / dtype rules must change HERE, never
    in one caller. Header handling is the caller's (chunks never contain
    the header; the whole-file read consumes it)."""
    na = [s for s in setup.na_strings if s != ""]
    # T_TIME reads as RAW string tokens: per-chunk (or per-file) pandas
    # type inference could hand numeric-looking date tokens ('20200101')
    # to to_datetime as floats — epoch-ns garbage, and DIFFERENT garbage
    # depending on which tokens share a chunk. Forcing str makes both
    # paths convert the same tokens column-wide.
    return dict(
        sep=setup.separator, names=setup.column_names,
        quotechar=setup.quote_char or '"',
        na_values=na, keep_default_na=True, skipinitialspace=True,
        dtype={n: (object if t in (T_CAT, T_STR)
                   else (str if t == T_TIME else np.float64))
               for n, t in zip(setup.column_names, setup.column_types)},
        engine="c",
    )


def _note_parse_opts(fr, setup: ParseSetup) -> None:
    """Record the parse options streaming appends must reuse: a frame
    imported with custom ``na_strings`` (or quote char) must read
    /3/ParseStream tokens exactly as a cold parse of the concatenated
    data would (ingest/chunked._stream_setup reads this back)."""
    fr._parse_opts = {"na_strings": list(setup.na_strings),
                      "quote_char": setup.quote_char,
                      "separator": setup.separator}


def _parse_csv_host(path: str, setup: ParseSetup) -> Dict[str, np.ndarray]:
    """Parse one file into host columns. Tries the native C++ parser first
    (h2o3_tpu/native/csv_parser.cpp), falls back to pandas/numpy."""
    from h2o3_tpu.native.loader import native_parse_csv

    cols = native_parse_csv(path, setup)
    if cols is not None:
        return cols
    import pandas as pd

    # python string storage + object dtype: pandas 3's arrow-backed
    # StringDtype construction has segfaulted on REST worker threads under
    # concurrent XLA activity. Set the option GLOBALLY (idempotent): a scoped
    # option_context would race when the thread-pool parses files
    # concurrently — one thread's __exit__ restores arrow storage while
    # another is still inside read_csv
    pd.set_option("mode.string_storage", "python")
    df = pd.read_csv(
        path, header=0 if setup.check_header == 1 else None,
        **csv_read_kwargs(setup),
    )
    out = {}
    for name, t in zip(setup.column_names, setup.column_types):
        s = df[name]
        if t in (T_CAT, T_STR):
            out[name] = s.to_numpy(dtype=object)
        elif t == T_TIME:
            out[name] = _dt_to_ms(pd.to_datetime(s, errors="coerce"))
        else:
            out[name] = s.to_numpy(dtype=np.float64)
    return out


def _dt_to_ms(dt_series) -> np.ndarray:
    """datetime series -> float64 epoch-MILLIS with NaN for NaT. The T_TIME
    column convention everywhere (rapids time prims, MOJO export) is ms.
    The raw int64 view's unit follows the series dtype (ns in pandas 2, us
    in pandas 3) — casting to datetime64[ms] first pins the unit."""
    ms = (dt_series.astype("datetime64[ms]").astype("int64")
          .to_numpy().astype(np.float64))
    ms[dt_series.isna().to_numpy()] = np.nan
    return ms


def _parse_one(path: str, setup: ParseSetup):
    """-> (cols, names, types) for one file, dispatched on parse_type."""
    from h2o3_tpu.ingest import formats

    pt = setup.parse_type
    if pt == "CSV":
        return _parse_csv_host(path, setup), list(setup.column_names), \
            list(setup.column_types)
    if pt in ("PARQUET", "ORC", "FEATHER"):
        cols, names, types = formats.parse_columnar_host(path, pt)
    elif pt == "ARFF":
        cols, names, types = formats.parse_arff_host(path)
    elif pt == "SVMLight":
        cols, names, types = formats.parse_svmlight_host(path)
    elif pt == "AVRO":
        from h2o3_tpu.ingest.avro import parse_avro_host

        cols, names, types = parse_avro_host(path)
    elif pt == "XLSX":
        cols, names, types = formats.parse_xlsx_host(path)
    else:
        raise ValueError(f"unknown parse_type {pt!r}")
    # honor user col_types overrides carried on the setup (the CSV path
    # applies them at read time; here the file's own schema parsed first)
    if setup.column_types and len(setup.column_types) == len(types):
        for i, nm in enumerate(names):
            want = setup.column_types[i]
            if want != types[i]:
                cols[nm] = formats.coerce_col(cols[nm], types[i], want)
        types = list(setup.column_types)
    return cols, names, types


def parse(paths: Sequence[str], setup: ParseSetup,
          destination_frame: Optional[str] = None) -> H2OFrame:
    """Multi-file parse: files parse CONCURRENTLY on host threads (pandas'
    C engine, pyarrow and the native C++ parser all release the GIL in
    their hot loops — the ParseDataset fork-join analog), then each column
    concatenates and ships. `Column.from_numpy`'s device_put is async, so
    the H2D transfer of early columns overlaps host work on later ones
    (SURVEY.md §7 hard part 7: parse/H2D overlap)."""
    from h2o3_tpu import persist

    paths = persist.resolve_all(list(paths))
    if setup.parse_type == "CSV" and setup.check_header == 1 and len(paths) > 1:
        # every file must carry the SAME header row as the first file —
        # pandas would silently rename mismatched columns to the setup's
        # names otherwise. Compared against file 0's own header (not
        # setup.column_names, which the user may have overridden)
        import csv as _csv

        def _hdr(p):
            with open_stream(p) as f:
                first = f.readline().rstrip("\n")
            return [c.strip() for c in
                    next(_csv.reader([first], delimiter=setup.separator))]

        hdr0 = _hdr(paths[0])
        for p in paths[1:]:
            hdr = _hdr(p)
            if hdr != hdr0:
                raise ValueError(f"column mismatch across files: {p} has "
                                 f"{hdr}, expected {hdr0}")
    if setup.parse_type == "CSV":
        # the chunked sharded pipeline (ingest/chunked.py): byte-range
        # parallel parse straight into row shards, zero coordinator bytes.
        # None = ineligible (compressed/remote-only/empty) — legacy path;
        # ChunkLayoutError = the record scan disagreed with the parser
        # (non-RFC quoting) — the monolithic path handles those exactly
        # as before
        from h2o3_tpu.ingest import chunked

        try:
            got = chunked.parse_csv_sharded(paths, setup)
        except chunked.ChunkLayoutError as e:
            log.warn(str(e))
            got = None
        if got is not None:
            fr = H2OFrame(destination_frame=destination_frame)
            for name in setup.column_names:
                fr.add(name, got[name])
            _note_parse_opts(fr, setup)
            log.info(f"parsed {len(paths)} file(s) chunked -> "
                     f"{fr.nrows}x{fr.ncols} [{fr.frame_id}]")
            return fr
    if len(paths) == 1:
        results = [_parse_one(paths[0], setup)]
    else:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=min(8, len(paths))) as pool:
            results = list(pool.map(lambda p: _parse_one(p, setup), paths))
    if setup.parse_type == "SVMLight" and len(results) > 1:
        # sparse files densify to their own max feature index; unify widths
        # (zero-default) before the cross-file consistency check
        widest = max(results, key=lambda r: len(r[1]))
        wnames, wtypes = widest[1], widest[2]
        for cols_i, n_i, _t in results:
            nr = len(cols_i[n_i[0]]) if n_i else 0
            for nm in wnames[len(n_i):]:
                cols_i[nm] = np.zeros(nr, np.float64)
            n_i[:] = wnames
            _t[:] = wtypes
    _, names, types = results[0]
    for p, (_, n_i, t_i) in zip(paths[1:], results[1:]):
        if n_i != names:
            raise ValueError(
                f"column mismatch across files: {p} has {n_i}, "
                f"expected {names}")
        if t_i != types:
            raise ValueError(
                f"column type mismatch across files: {p} has {t_i}, "
                f"expected {types}")
    # user col_names renames apply to every format (the CSV reader honors
    # them at read time; columnar/ARFF/SVMLight files carry their own names,
    # renamed here position-for-position)
    final_names = (list(setup.column_names)
                   if setup.column_names and len(setup.column_names) == len(names)
                   else list(names))
    fr = H2OFrame(destination_frame=destination_frame)
    from h2o3_tpu.ingest import chunked as _chunked

    for name, final, t in zip(names, final_names, types):
        parts = [r[0][name] for r in results]
        arr = np.concatenate(parts) if len(parts) > 1 else parts[0]
        if t != T_STR:
            # whole-column host staging before device_put: the legacy
            # monolithic assembly — the bytes the chunked path zeroes
            # (object arrays count their pointer bytes; the real string
            # payload is host-resident either way)
            _chunked.note_coordinator_bytes(arr.nbytes)
        if t == T_CAT:
            fr.add(final, Column.from_numpy(arr, ctype=T_CAT))
        elif t == T_STR:
            fr.add(final, Column.from_numpy(arr.astype(object)))
        elif t == T_TIME:
            fr.add(final, Column.from_numpy(arr, ctype=T_TIME))
        else:
            fr.add(final, Column.from_numpy(arr))
    _note_parse_opts(fr, setup)
    log.info(f"parsed {len(paths)} file(s) -> {fr.nrows}x{fr.ncols} [{fr.frame_id}]")
    return fr


def import_file(path: str, destination_frame: Optional[str] = None,
                header: int = 0, sep: Optional[str] = None,
                col_names: Optional[List[str]] = None,
                col_types=None, na_strings=None, **kw) -> H2OFrame:
    """h2o.import_file parity (h2o-py/h2o/h2o.py import_file): resolves
    remote URIs through the persist registry (water/persist/PersistManager
    .java importFiles), then globs/dirs, guesses setup, parses."""
    from h2o3_tpu import persist

    if persist.is_remote(path):
        paths = [persist.resolve(path)]      # fetched to the local cache
    else:
        paths = sorted(_glob.glob(path)) if any(ch in path for ch in "*?[") else [path]
        if len(paths) == 1 and os.path.isdir(paths[0]):
            paths = sorted(
                os.path.join(paths[0], f) for f in os.listdir(paths[0])
                if not f.startswith(".")
            )
    if not paths:
        raise FileNotFoundError(path)
    ct = None
    if isinstance(col_types, dict):
        ct = col_types
    elif isinstance(col_types, (list, tuple)):
        ct = {i: t for i, t in enumerate(col_types)}
    setup = guess_setup(paths[0], column_types=ct, na_strings=na_strings,
                        header=(1 if header == 1 else (-1 if header == -1 else None)),
                        separator=sep)
    if col_names:
        setup.column_names = list(col_names)
    return parse(paths, setup, destination_frame=destination_frame)


upload_file = import_file  # same machinery in-process


class _ParquetBatchLoader:
    """Shared first-touch loader for one lazily-opened Parquet frame: the
    first touched column reads a window of ADJACENT still-pending columns
    through ONE column-pruned ``read_table`` (H2O_TPU_INGEST_PARQUET_BATCH
    wide) and caches the others' padded buffers, so N first touches cost
    ceil(N / batch) file opens instead of N re-open/re-reads."""

    def __init__(self, path: str, n: int, padded: int,
                 pending: List[Tuple[str, str]]):
        self._path = path
        self._n = n
        self._padded = padded
        self._pending = list(pending)          # (name, ctype), file order
        self._cache: Dict[str, np.ndarray] = {}
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._inflight: set = set()            # names in a window being read

    def load(self, name: str, ctype: str) -> np.ndarray:
        from h2o3_tpu.core.frame import pad_numeric_host
        from h2o3_tpu.ingest import chunked, formats

        with self._lock:
            while True:
                buf = self._cache.pop(name, None)
                if buf is not None:
                    return buf
                if name not in self._inflight:
                    break
                # another thread's window read covers this column: wait
                # for its install instead of issuing a duplicate read
                self._cond.wait()
            idx = next((i for i, (nm, _t) in enumerate(self._pending)
                        if nm == name), None)
            if idx is None:
                batch = [(name, ctype)]        # re-load after eviction
            else:
                batch = self._pending[idx:idx + chunked.parquet_batch()]
                del self._pending[idx:idx + len(batch)]
            self._inflight.update(nm for nm, _ in batch)
        import pyarrow.parquet as pq

        # the disk read runs OUTSIDE the lock (Column.data keeps slow loads
        # outside its swap lock for the same reason): concurrent fault-ins
        # of OTHER windows must not serialize behind this one
        got: Dict[str, np.ndarray] = {}
        try:
            tbl = pq.read_table(self._path, columns=[nm for nm, _ in batch])
            cols, _types = formats.arrow_to_host_cols(tbl)
            for nm, ct in batch:
                b = pad_numeric_host(cols[nm], self._n, self._padded, ct)
                chunked.note_coordinator_bytes(b.nbytes)
                got[nm] = b
        finally:
            with self._lock:
                self._inflight.difference_update(nm for nm, _ in batch)
                for nm, b in got.items():
                    if nm != name:
                        self._cache[nm] = b
                # bounded prefetch: never-touched neighbors must not pin a
                # wide frame's data in host RAM forever (an evicted entry
                # re-reads as a single column; a waiter orphaned by a
                # FAILED read retries it the same way)
                cap = max(4 * chunked.parquet_batch(), 16)
                while len(self._cache) > cap:
                    self._cache.pop(next(iter(self._cache)))
                self._cond.notify_all()
        return got[name]


def lazy_import_parquet(path: str,
                        destination_frame: Optional[str] = None) -> H2OFrame:
    """File-backed Frame over a Parquet file (water/fvec/FileVec.java
    analog): numeric/time columns stay ON DISK until first touched — open a
    frame wider than HBM, column-prune, and only the touched columns
    materialize (through the normal padded-shard path). Categorical/string
    columns load eagerly (their domains are frame metadata); first-touch
    numeric loads BATCH through one shared column-pruned read
    (_ParquetBatchLoader) instead of re-opening the file per column."""
    from h2o3_tpu import persist
    from h2o3_tpu.core.runtime import cluster
    from h2o3_tpu.ingest import formats

    local = persist.resolve(path)
    import pyarrow.parquet as pq

    # metadata-only reads: no file handle kept open past this point
    n = pq.read_metadata(local).num_rows
    schema = pq.read_schema(local)
    names = [f.name for f in schema]
    types = [formats._arrow_field_type(f.type) for f in schema]
    padded = cluster().pad_rows(n)
    fr = H2OFrame(destination_frame=destination_frame)
    # categorical/string columns load eagerly in ONE column-pruned read
    eager = [nm for nm, t in zip(names, types) if t in (T_CAT, T_STR)]
    eager_cols = {}
    if eager:
        from h2o3_tpu.ingest import chunked

        tbl = pq.read_table(local, columns=eager)
        eager_cols, _types = formats.arrow_to_host_cols(tbl)
        for nm in eager:
            # whole-column host staging — counted like every other
            # coordinator-side assembly (object arrays count pointer bytes)
            chunked.note_coordinator_bytes(eager_cols[nm].nbytes)
    lazy = _ParquetBatchLoader(
        local, n, padded,
        [(nm, t) for nm, t in zip(names, types) if t not in (T_CAT, T_STR)])
    for name, t in zip(names, types):
        if t in (T_CAT, T_STR):
            fr.add(name, Column.from_numpy(
                eager_cols[name], ctype=t if t == T_CAT else None))
            continue

        def loader(col=name, ct=t):
            return lazy.load(col, ct)

        fr.add(name, Column.file_backed(loader, t, n))
    log.info(f"lazy-opened parquet {n}x{len(names)} [{fr.frame_id}] "
             f"(numeric columns load on first touch, batched)")
    return fr
