"""Parse orchestration: files -> typed host buffers -> sharded device Frame.

Reference: water/parser/ParseDataset.java:127 forkParseDataset — an MRTask
over the byte-chunks of FileVecs where each map parses one 4MB chunk to
NewChunks, then two more distributed rounds union + renumber categorical
domains (:518 GatherCategoricalDomainsTask, :475 UpdateCategoricalChunksTask).

TPU-native: the host parses (optionally via the C++ fast parser in
h2o3_tpu/native, else numpy), producing typed columns; categorical interning
happens in one host pass (single-process) or one gather at the coordinator
(multi-host); the result is device_put row-sharded straight into HBM —
overlap of parse and H2D transfer is the multi-host input-pipeline hot path
(SURVEY.md §7 hard part 7)."""

from __future__ import annotations

import glob as _glob
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from h2o3_tpu.core.frame import Column, Frame, NA_CAT, T_CAT, T_NUM, T_STR, T_TIME
from h2o3_tpu.frame_factory import H2OFrame
from h2o3_tpu.ingest.parse_setup import ParseSetup, guess_setup, open_stream
from h2o3_tpu.utils import log


def parse_setup(paths, **kw) -> ParseSetup:
    p = paths[0] if isinstance(paths, (list, tuple)) else paths
    return guess_setup(p, **kw)


def _parse_csv_host(path: str, setup: ParseSetup) -> Dict[str, np.ndarray]:
    """Parse one file into host columns. Tries the native C++ parser first
    (h2o3_tpu/native/csv_parser.cpp), falls back to pandas/numpy."""
    from h2o3_tpu.native.loader import native_parse_csv

    cols = native_parse_csv(path, setup)
    if cols is not None:
        return cols
    import pandas as pd

    na = [s for s in setup.na_strings if s != ""]
    # python string storage + object dtype: pandas 3's arrow-backed
    # StringDtype construction has segfaulted on REST worker threads under
    # concurrent XLA activity; option_context keeps the override scoped
    with pd.option_context("mode.string_storage", "python"):
        df = pd.read_csv(
            path, sep=setup.separator,
            header=0 if setup.check_header == 1 else None,
            names=setup.column_names,
            na_values=na, keep_default_na=True, skipinitialspace=True,
            dtype={n: (object if t in (T_CAT, T_STR) else np.float64)
                   for n, t in zip(setup.column_names, setup.column_types) if t != T_TIME},
            engine="c",
        )
    out = {}
    for name, t in zip(setup.column_names, setup.column_types):
        s = df[name]
        if t in (T_CAT, T_STR):
            out[name] = s.to_numpy(dtype=object)
        elif t == T_TIME:
            out[name] = pd.to_datetime(s, errors="coerce").astype("int64").to_numpy()
        else:
            out[name] = s.to_numpy(dtype=np.float64)
    return out


def parse(paths: Sequence[str], setup: ParseSetup,
          destination_frame: Optional[str] = None) -> H2OFrame:
    host_cols: Dict[str, List[np.ndarray]] = {n: [] for n in setup.column_names}
    for p in paths:
        parsed = _parse_csv_host(p, setup)
        for n in setup.column_names:
            host_cols[n].append(parsed[n])
    fr = H2OFrame(destination_frame=destination_frame)
    for name, t in zip(setup.column_names, setup.column_types):
        arr = np.concatenate(host_cols[name]) if len(host_cols[name]) > 1 else host_cols[name][0]
        if t == T_CAT:
            fr.add(name, Column.from_numpy(arr, ctype=T_CAT))
        elif t == T_STR:
            fr.add(name, Column.from_numpy(arr.astype(object)))
        elif t == T_TIME:
            fr.add(name, Column.from_numpy(arr, ctype=T_TIME))
        else:
            fr.add(name, Column.from_numpy(arr))
    log.info(f"parsed {len(paths)} file(s) -> {fr.nrows}x{fr.ncols} [{fr.frame_id}]")
    return fr


def import_file(path: str, destination_frame: Optional[str] = None,
                header: int = 0, sep: Optional[str] = None,
                col_names: Optional[List[str]] = None,
                col_types=None, na_strings=None, **kw) -> H2OFrame:
    """h2o.import_file parity (h2o-py/h2o/h2o.py import_file): resolves
    globs/dirs, guesses setup, parses."""
    paths = sorted(_glob.glob(path)) if any(ch in path for ch in "*?[") else [path]
    if len(paths) == 1 and os.path.isdir(paths[0]):
        paths = sorted(
            os.path.join(paths[0], f) for f in os.listdir(paths[0])
            if not f.startswith(".")
        )
    if not paths:
        raise FileNotFoundError(path)
    ct = None
    if isinstance(col_types, dict):
        ct = col_types
    elif isinstance(col_types, (list, tuple)):
        ct = {i: t for i, t in enumerate(col_types)}
    setup = guess_setup(paths[0], column_types=ct, na_strings=na_strings,
                        header=(1 if header == 1 else (-1 if header == -1 else None)),
                        separator=sep)
    if col_names:
        setup.column_names = list(col_names)
    return parse(paths, setup, destination_frame=destination_frame)


upload_file = import_file  # same machinery in-process
