"""DecryptionTool registry — transparent decryption during parse.

Reference: water/parser/DecryptionTool.java:1 (+ GenericDecryptionTool,
NullDecryptionTool): /3/DecryptionSetup registers a tool under a key; Parse
pipes file bytes through it before format detection.

Built in: the null tool (passthrough — reference default). AES cipher specs
need the optional `cryptography` package; without it registration of an AES
tool raises an actionable error rather than silently storing a no-op.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

_TOOLS: Dict[str, dict] = {}

NULL_TOOL = "water.parser.NullDecryptionTool"


def register_tool(tool_id: str, tool_class: str, params: dict) -> str:
    """Register a decryption tool; returns the tool id."""
    if tool_class in ("", NULL_TOOL, "null"):
        _TOOLS[tool_id] = {"class": NULL_TOOL, "params": dict(params)}
        return tool_id
    try:
        from cryptography.hazmat.primitives.ciphers import Cipher  # noqa: F401
    except ImportError:
        raise ValueError(
            f"decryption tool {tool_class!r} needs the 'cryptography' "
            "package on the server; only the null (passthrough) tool is "
            "built in") from None
    _TOOLS[tool_id] = {"class": tool_class, "params": dict(params)}
    return tool_id


def get_tool(tool_id: Optional[str]) -> Optional[Callable[[bytes], bytes]]:
    """Decryptor function for a registered tool id (None → passthrough)."""
    if not tool_id:
        return None
    ent = _TOOLS.get(tool_id)
    if ent is None:
        raise KeyError(f"decryption tool {tool_id!r} not registered")
    if ent["class"] == NULL_TOOL:
        return lambda data: data
    raise NotImplementedError(
        f"cipher tool {ent['class']!r} registered but no cipher backend "
        "wired — install 'cryptography'")
