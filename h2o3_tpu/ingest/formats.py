"""Columnar & structured format readers: Parquet / ORC / Feather (pyarrow),
ARFF, SVMLight.

Reference: h2o-parsers/h2o-parquet-parser/ (VecParquetReader walks row
groups into NewChunks), h2o-parsers/h2o-orc-parser/, water/parser/ARFFParser
.java, water/parser/SVMLightParser.java.

TPU-native design: columnar files are already typed and column-major — the
exact layout the device Frame wants — so readers go straight from the
format's column vectors to host numpy (zero row-wise materialization), and
`Column.from_numpy` shards them onto the mesh. Types map: floating/int →
f32 columns, dictionary/string → enum via the normal interning path,
timestamp → int64 epoch-millis T_TIME, bool → 0/1 numeric."""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional, Tuple

import numpy as np

from h2o3_tpu.core.frame import T_CAT, T_NUM, T_STR, T_TIME

# extensions -> parse type (ParseSetup._parse_type analog)
COLUMNAR_EXT = {".parquet": "PARQUET", ".pq": "PARQUET", ".orc": "ORC",
                ".feather": "FEATHER", ".arrow": "FEATHER"}
STRUCTURED_EXT = {".arff": "ARFF", ".svm": "SVMLight",
                  ".svmlight": "SVMLight"}


# legacy BIFF .xls only: a 1997 binary format whose decoder (POI/xlrd)
# this image lacks; .xlsx and .avro parse natively (round 4)
GATED_EXT = {".xls": "XLS"}
NATIVE_BINARY_EXT = {".xlsx": "XLSX", ".avro": "AVRO"}


def detect_parse_type(path: str) -> Optional[str]:
    """Extension -> parse type; None = fall back to CSV text sniffing.
    Raises for known-binary formats whose decoders are not present
    (surfaced as HTTP 501 by the REST layer)."""
    from h2o3_tpu.errors import CapabilityGate

    ext = os.path.splitext(path)[1].lower()
    if ext in GATED_EXT:
        # fail fast with the reason — sniffing these binaries as CSV would
        # produce garbage columns (reference: legacy XlsParser rides POI)
        raise CapabilityGate(
            f"{GATED_EXT[ext]} (legacy BIFF) parsing needs a decoder "
            "library not present in this environment (xlrd). Save as "
            ".xlsx or CSV and import that instead.")
    return (COLUMNAR_EXT.get(ext) or STRUCTURED_EXT.get(ext)
            or NATIVE_BINARY_EXT.get(ext))


# ---------------------------------------------------------------------------
# pyarrow-backed columnar formats
# ---------------------------------------------------------------------------

def _read_arrow_table(path: str, parse_type: str):
    import pyarrow as pa

    if parse_type == "PARQUET":
        import pyarrow.parquet as pq

        return pq.read_table(path)
    if parse_type == "ORC":
        import pyarrow.orc as orc

        return orc.read_table(path)
    if parse_type == "FEATHER":
        # Feather V2 IS the Arrow IPC file format (feather.read_table is
        # deprecated in favor of this)
        try:
            with pa.ipc.open_file(path) as r:
                return r.read_all()
        except pa.ArrowInvalid:
            import pyarrow.feather as feather    # Feather V1 fallback

            return feather.read_table(path)
    raise ValueError(parse_type)


def arrow_to_host_cols(table) -> Tuple[Dict[str, np.ndarray], List[str]]:
    """pyarrow Table -> (host column arrays, column types)."""
    import pyarrow as pa

    cols: Dict[str, np.ndarray] = {}
    types: List[str] = []
    for name, col in zip(table.column_names, table.columns):
        t = col.type
        if pa.types.is_dictionary(t):
            col = col.cast(t.value_type)
            t = col.type
        if pa.types.is_timestamp(t) or pa.types.is_date(t):
            ms = col.cast(pa.timestamp("ms")).cast(pa.int64())
            arr = ms.to_numpy(zero_copy_only=False).astype(np.float64)
            mask = np.asarray(col.is_null().combine_chunks())
            arr[mask] = np.nan
            cols[name] = arr
            types.append(T_TIME)
        elif pa.types.is_boolean(t):
            arr = col.cast(pa.float64()).to_numpy(zero_copy_only=False)
            cols[name] = np.asarray(arr, np.float64)
            types.append(T_NUM)
        elif pa.types.is_integer(t) or pa.types.is_floating(t) \
                or pa.types.is_decimal(t):
            arr = col.cast(pa.float64()).to_numpy(zero_copy_only=False)
            cols[name] = np.asarray(arr, np.float64)
            types.append(T_NUM)
        elif pa.types.is_string(t) or pa.types.is_large_string(t):
            pd_arr = col.to_pandas()
            obj = pd_arr.to_numpy(dtype=object)
            obj[pd_arr.isna().to_numpy()] = None
            cols[name] = obj
            types.append(T_CAT)
        else:
            # lists/structs/binary: stringified (reference skips with warn)
            obj = np.array([None if v is None else str(v)
                            for v in col.to_pylist()], object)
            cols[name] = obj
            types.append(T_STR)
    return cols, types


def parse_columnar_host(path: str, parse_type: str
                        ) -> Tuple[Dict[str, np.ndarray], List[str], List[str]]:
    """-> (cols, names, types)."""
    table = _read_arrow_table(path, parse_type)
    cols, types = arrow_to_host_cols(table)
    return cols, list(table.column_names), types


def coerce_col(arr: np.ndarray, t_from: str, t_to: str) -> np.ndarray:
    """Apply a user type override (h2o-py col_types) to an already-parsed
    host column: numeric -> enum renders labels (integral floats drop the
    '.0', matching the CSV path's string view of the same data); object ->
    numeric parses with NaN on failure."""
    if t_to in (T_CAT, T_STR) and t_from in (T_NUM, T_TIME):
        out = np.empty(len(arr), object)
        for i, v in enumerate(arr):
            if v is None or (isinstance(v, float) and np.isnan(v)):
                out[i] = None
            else:
                fv = float(v)
                out[i] = str(int(fv)) if fv == int(fv) else str(fv)
        return out
    if t_to in (T_NUM, T_TIME) and t_from in (T_CAT, T_STR):
        out = np.empty(len(arr), np.float64)
        for i, v in enumerate(arr):
            try:
                out[i] = float(v)
            except (TypeError, ValueError):
                out[i] = np.nan
        return out
    return arr


def _arrow_field_type(t) -> str:
    import pyarrow as pa

    if pa.types.is_dictionary(t):
        t = t.value_type
    if pa.types.is_timestamp(t) or pa.types.is_date(t):
        return T_TIME
    if pa.types.is_boolean(t) or pa.types.is_integer(t) \
            or pa.types.is_floating(t) or pa.types.is_decimal(t):
        return T_NUM
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return T_CAT
    return T_STR


def columnar_schema(path: str, parse_type: str) -> Tuple[List[str], List[str]]:
    """Schema-only read for ParseSetup guessing (cheap for Parquet/ORC)."""
    if parse_type == "PARQUET":
        import pyarrow.parquet as pq

        schema = pq.read_schema(path)
    elif parse_type == "ORC":
        import pyarrow.orc as orc

        schema = orc.ORCFile(path).schema
    else:
        import pyarrow as pa

        try:
            with pa.ipc.open_file(path) as r:    # Feather V2 = IPC: no data read
                schema = r.schema
        except pa.ArrowInvalid:
            schema = _read_arrow_table(path, parse_type).schema
    return ([f.name for f in schema],
            [_arrow_field_type(f.type) for f in schema])


# ---------------------------------------------------------------------------
# ARFF (water/parser/ARFFParser.java behavior: @attribute typed header,
# @data CSV body; {a,b,c} nominal specs -> enum)
# ---------------------------------------------------------------------------

_ARFF_ATTR = re.compile(r"@attribute\s+('(?:[^']*)'|\"(?:[^\"]*)\"|\S+)\s+(.+)",
                        re.IGNORECASE)


def _scan_arff(path: str, want_data: bool):
    from h2o3_tpu.ingest.parse_setup import open_stream

    names: List[str] = []
    types: List[str] = []
    data_lines: List[str] = []
    in_data = False
    with open_stream(path) as f:
        for ln in f:
            s = ln.strip()
            if not s or s.startswith("%"):
                continue
            if in_data:
                data_lines.append(s)
                continue
            low = s.lower()
            if low.startswith("@data"):
                if not want_data:
                    break
                in_data = True
            elif low.startswith("@attribute"):
                m = _ARFF_ATTR.match(s)
                if not m:
                    raise ValueError(f"bad ARFF attribute line: {s!r}")
                nm, spec = m.group(1).strip("'\""), m.group(2).strip()
                names.append(nm)
                sl = spec.lower()
                if spec.startswith("{"):
                    types.append(T_CAT)
                elif sl.startswith(("numeric", "real", "integer")):
                    types.append(T_NUM)
                elif sl.startswith("date"):
                    types.append(T_TIME)
                else:
                    types.append(T_STR)
    if not names:
        raise ValueError(f"no @attribute declarations in {path}")
    return names, types, data_lines


def arff_header(path: str) -> Tuple[List[str], List[str]]:
    names, types, _ = _scan_arff(path, want_data=False)
    return names, types


def parse_arff_host(path: str) -> Tuple[Dict[str, np.ndarray], List[str], List[str]]:
    names, types, data_lines = _scan_arff(path, want_data=True)
    import csv as _csv

    rows = list(_csv.reader(data_lines))
    ncols = len(names)
    cols: Dict[str, np.ndarray] = {}
    for i, (nm, t) in enumerate(zip(names, types)):
        vals = [r[i].strip() if i < len(r) else "" for r in rows]
        if t == T_NUM:
            cols[nm] = np.array([float(v) if v not in ("", "?") else np.nan
                                 for v in vals], np.float64)
        elif t == T_TIME:
            import pandas as pd

            from h2o3_tpu.ingest.parser import _dt_to_ms

            cols[nm] = _dt_to_ms(pd.to_datetime(
                pd.Series(vals).replace("?", None), errors="coerce"))
        else:
            cols[nm] = np.array([None if v in ("", "?") else v.strip("'\"")
                                 for v in vals], object)
    return cols, names, types


# ---------------------------------------------------------------------------
# SVMLight (water/parser/SVMLightParser.java: "label idx:val idx:val ...",
# 1-based indices, zero-default sparse -> dense here, the device layout)
# ---------------------------------------------------------------------------

def parse_svmlight_host(path: str) -> Tuple[Dict[str, np.ndarray], List[str], List[str]]:
    from h2o3_tpu.ingest.parse_setup import open_stream

    labels: List[float] = []
    entries: List[List[Tuple[int, float]]] = []
    max_idx = 0
    with open_stream(path) as f:
        for ln in f:
            s = ln.split("#", 1)[0].strip()
            if not s:
                continue
            toks = s.split()
            labels.append(float(toks[0]))
            row = []
            for tk in toks[1:]:
                if tk.startswith("qid:"):
                    continue
                idx, val = tk.split(":", 1)
                i = int(idx)
                if i < 1:
                    raise ValueError(f"SVMLight indices are 1-based, got {i}")
                row.append((i, float(val)))
                max_idx = max(max_idx, i)
            entries.append(row)
    n = len(labels)
    dense = np.zeros((n, max_idx), np.float64)
    for r, row in enumerate(entries):
        for i, v in row:
            dense[r, i - 1] = v
    names = ["C1"] + [f"C{i+2}" for i in range(max_idx)]
    cols = {"C1": np.asarray(labels, np.float64)}
    for i in range(max_idx):
        cols[names[i + 1]] = dense[:, i]
    return cols, names, [T_NUM] * len(names)


# ---------------------------------------------------------------------------
# XLSX (stdlib zip + XML — reference: h2o XlsxParser via POI-like decode)
# ---------------------------------------------------------------------------

def _xlsx_col_index(ref: str) -> int:
    """Cell ref 'BC12' -> zero-based column index."""
    idx = 0
    for ch in ref:
        if not ch.isalpha():
            break
        idx = idx * 26 + (ord(ch.upper()) - ord("A") + 1)
    return idx - 1


def xlsx_header(path: str, sample_rows: int = 100
                ) -> Tuple[List[str], List[str]]:
    """Names + sampled types without keeping the data (ParseSetup tier)."""
    cols, names, types = parse_xlsx_host(path, max_rows=sample_rows)
    return names, types


def parse_xlsx_host(path: str, max_rows: Optional[int] = None
                    ) -> Tuple[Dict[str, np.ndarray], List[str],
                               List[str]]:
    """First worksheet of an .xlsx workbook -> (cols, names, types); row 1
    is the header (the reference's XlsParser contract)."""
    import xml.etree.ElementTree as ET
    import zipfile

    NS = "{http://schemas.openxmlformats.org/spreadsheetml/2006/main}"
    with zipfile.ZipFile(path) as z:
        shared: List[str] = []
        if "xl/sharedStrings.xml" in z.namelist():
            root = ET.fromstring(z.read("xl/sharedStrings.xml"))
            for si in root:
                shared.append("".join(t.text or ""
                                      for t in si.iter(NS + "t")))
        sheets = sorted(n for n in z.namelist()
                        if n.startswith("xl/worksheets/sheet"))
        if not sheets:
            raise ValueError(f"{path!r}: no worksheets")
        root = ET.fromstring(z.read(sheets[0]))
        # honor r attributes: Excel omits empty rows/cells from the XML,
        # so both row index and column index come from the refs, with
        # sequential fallbacks when a writer drops them
        rowmap: Dict[int, Dict[int, Optional[str]]] = {}
        ncols = 0
        next_row = 1
        for row in root.iter(NS + "row"):
            if max_rows is not None and len(rowmap) > max_rows:
                break            # ParseSetup tier: sample only
            ri = int(row.get("r", next_row))
            next_row = ri + 1
            cells: Dict[int, Optional[str]] = {}
            next_ci = 0
            for c in row.iter(NS + "c"):
                ref = c.get("r")
                ci = _xlsx_col_index(ref) if ref else next_ci
                next_ci = ci + 1
                t = c.get("t")
                vel = c.find(NS + "v")
                if vel is not None:
                    val = vel.text
                    if t == "s" and val is not None:
                        val = shared[int(val)]
                elif c.find(NS + "is") is not None:
                    val = "".join(tt.text or ""
                                  for tt in c.find(NS + "is").iter(NS + "t"))
                else:
                    val = None
                cells[ci] = val
                ncols = max(ncols, ci + 1)
            rowmap[ri] = cells
        if not rowmap:
            grid: List[Dict[int, Optional[str]]] = []
        else:
            first, last = min(rowmap), max(rowmap)
            grid = [rowmap.get(i, {}) for i in range(first, last + 1)]
    if not grid:
        raise ValueError(f"{path!r}: empty worksheet")
    header = [str(grid[0].get(j) or f"C{j + 1}") for j in range(ncols)]
    body = grid[1:]
    if max_rows is not None:
        body = body[:max_rows]
    cols: Dict[str, np.ndarray] = {}
    types: List[str] = []
    for j, name in enumerate(header):
        raw = [r.get(j) for r in body]
        numeric = True
        vals = np.full(len(raw), np.nan)
        for i, v in enumerate(raw):
            if v is None or v == "":
                continue
            try:
                vals[i] = float(v)
            except ValueError:
                numeric = False
                break
        if numeric:
            cols[name] = vals
            types.append("real")
        else:
            cols[name] = np.asarray(["" if v is None else str(v)
                                     for v in raw], object)
            types.append("enum")
    return cols, header, types
