"""ParseSetup — separator/header/type guessing from a sample.

Reference: water/parser/ParseSetup.java — samples the first chunk, guesses
separator by column-count stability, header by first-row typeability, and
per-column types by vote over sampled values (NUM < TIME < CAT < STR
escalation)."""

from __future__ import annotations

import csv as _csv
import gzip
import io
import os
import re
import zipfile
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from h2o3_tpu.core.frame import T_CAT, T_NUM, T_STR, T_TIME

_SEPS = [",", "\t", ";", "|", " "]
_TIME_RE = re.compile(r"^\d{4}-\d{2}-\d{2}([ T]\d{2}:\d{2}(:\d{2})?)?$")
_NUM_RE = re.compile(r"^[+-]?(\d+\.?\d*|\.\d+)([eE][+-]?\d+)?$")
# max unique strings before a column escalates CAT -> STR
MAX_CAT_DOMAIN = 10_000_000  # H2O Categorical.MAX_CATEGORICAL_COUNT analog


@dataclass
class ParseSetup:
    separator: str = ","
    check_header: int = 1  # 1 = has header, -1 = none (H2O convention)
    column_names: List[str] = field(default_factory=list)
    column_types: List[str] = field(default_factory=list)
    na_strings: List[str] = field(default_factory=lambda: ["", "NA", "N/A", "nan", "NaN", "null"])
    skipped_columns: List[int] = field(default_factory=list)
    quote_char: str = '"'
    # CSV / PARQUET / ORC / FEATHER / ARFF / SVMLight
    # (ParseSetup._parse_type analog; drives the reader dispatch)
    parse_type: str = "CSV"

    def to_dict(self) -> dict:
        return {
            "separator": ord(self.separator),
            "check_header": self.check_header,
            "column_names": self.column_names,
            "column_types": self.column_types,
            "na_strings": self.na_strings,
            "parse_type": self.parse_type,
        }


def open_stream(path: str):
    """Transparent decompression (water/parser/ZipUtil.java parity)."""
    if path.endswith(".gz"):
        return io.TextIOWrapper(gzip.open(path, "rb"), errors="replace")
    if path.endswith(".zip"):
        zf = zipfile.ZipFile(path)
        inner = zf.namelist()[0]
        return io.TextIOWrapper(zf.open(inner), errors="replace")
    return open(path, "r", errors="replace")


def _sniff_sep(sample_lines: List[str]) -> str:
    best, best_score = ",", -1
    for sep in _SEPS:
        counts = [len(next(_csv.reader([ln], delimiter=sep), [])) for ln in sample_lines if ln.strip()]
        if not counts:
            continue
        ncols = max(set(counts), key=counts.count)
        if ncols < 2:
            score = 0
        else:
            score = sum(1 for c in counts if c == ncols) * ncols
        if score > best_score:
            best, best_score = sep, score
    return best


def _classify(tok: str, na_strings) -> str:
    if tok in na_strings:
        return "na"
    if _NUM_RE.match(tok):
        return T_NUM
    if _TIME_RE.match(tok):
        return T_TIME
    return T_STR


def guess_setup(path: str, sample_rows: int = 1000,
                column_types: Optional[Dict[str, str]] = None,
                na_strings: Optional[List[str]] = None,
                header: Optional[int] = None,
                separator: Optional[str] = None) -> ParseSetup:
    # non-CSV formats carry their own schema: no text sampling
    from h2o3_tpu.ingest import formats

    ptype = formats.detect_parse_type(path)
    if ptype is not None:
        setup = ParseSetup(parse_type=ptype)
        if ptype in formats.COLUMNAR_EXT.values():
            setup.column_names, setup.column_types = \
                formats.columnar_schema(path, ptype)
        elif ptype == "ARFF":
            setup.column_names, setup.column_types = formats.arff_header(path)
        elif ptype == "AVRO":
            from h2o3_tpu.ingest.avro import avro_schema

            setup.column_names, setup.column_types = avro_schema(path)
        elif ptype == "XLSX":
            setup.column_names, setup.column_types = \
                formats.xlsx_header(path)
        # SVMLight: width only known after a full scan; filled at parse time
        if column_types and setup.column_types:
            _apply_type_overrides(setup.column_types, setup.column_names,
                                  column_types)
        return setup
    setup = ParseSetup()
    if na_strings:
        setup.na_strings = list(na_strings) + [""]
    with open_stream(path) as f:
        lines = []
        for _ in range(sample_rows + 1):
            ln = f.readline()
            if not ln:
                break
            lines.append(ln.rstrip("\n"))
    if not lines:
        raise ValueError(f"empty file {path}")
    setup.separator = separator or _sniff_sep(lines[:50])
    rows = list(_csv.reader(lines, delimiter=setup.separator, quotechar=setup.quote_char))
    rows = [r for r in rows if r]
    first, rest = rows[0], rows[1:] or [rows[0]]

    # header guess: first row all-string while data rows have numbers
    first_types = [_classify(t.strip(), setup.na_strings) for t in first]
    data_has_num = any(_classify(t.strip(), setup.na_strings) == T_NUM for r in rest[:20] for t in r)
    if header is not None:
        setup.check_header = header
    else:
        setup.check_header = 1 if (all(t == T_STR for t in first_types) and data_has_num) else -1

    ncols = max(len(r) for r in rows)
    if setup.check_header == 1:
        setup.column_names = [c.strip() or f"C{i+1}" for i, c in enumerate(first)]
        data_rows = rest
    else:
        setup.column_names = [f"C{i+1}" for i in range(ncols)]
        data_rows = rows
    while len(setup.column_names) < ncols:
        setup.column_names.append(f"C{len(setup.column_names)+1}")

    # per-column type vote (ParseSetup type escalation)
    votes = [dict(num=0, time=0, str=0, na=0) for _ in range(ncols)]
    uniq: List[set] = [set() for _ in range(ncols)]
    for r in data_rows:
        for i in range(ncols):
            tok = r[i].strip() if i < len(r) else ""
            t = _classify(tok, setup.na_strings)
            if t == "na":
                votes[i]["na"] += 1
            elif t == T_NUM:
                votes[i]["num"] += 1
            elif t == T_TIME:
                votes[i]["time"] += 1
            else:
                votes[i]["str"] += 1
                if len(uniq[i]) <= 1000:
                    uniq[i].add(tok)
    types = []
    for i in range(ncols):
        v = votes[i]
        total = v["num"] + v["time"] + v["str"]
        if total == 0:
            types.append(T_NUM)
        elif v["str"] > 0:
            # strings present: enum unless huge cardinality relative to sample
            nun = len(uniq[i])
            types.append(T_CAT if nun <= 0.95 * max(v["str"], 1) or nun <= 20 else T_STR)
        elif v["time"] > v["num"]:
            types.append(T_TIME)
        else:
            types.append(T_NUM)
    # user overrides (by name or index)
    if column_types:
        _apply_type_overrides(types, setup.column_names, column_types)
    setup.column_types = types
    return setup


def _apply_type_overrides(types: List[str], names: List[str],
                          column_types: Dict) -> None:
    for k, t in column_types.items():
        t = {"numeric": T_NUM, "real": T_NUM, "int": T_NUM, "enum": T_CAT,
             "factor": T_CAT, "string": T_STR, "time": T_TIME}.get(t, t)
        if isinstance(k, int):
            types[k] = t
        elif k in names:
            types[names.index(k)] = t
