"""SQL table import over Python DB-API drivers.

Reference: h2o-core/src/main/java/water/jdbc/SQLManager.java —
import_sql_table / import_sql_select fan out range-partitioned SELECTs over
JDBC and land chunks in Vecs.

TPU-native: the DB read is host I/O (never device work), so the driver is
whatever DB-API module matches the URL scheme — sqlite ships with Python;
postgres/mysql resolve to psycopg2/mysql-connector when installed, with
actionable errors otherwise. Rows fetch column-wise into typed numpy and
ship through the normal sharded-Frame path."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from h2o3_tpu.core.frame import Column, T_CAT
from h2o3_tpu.frame_factory import H2OFrame


def _connect(connection_url: str, username: Optional[str],
             password: Optional[str]):
    url = connection_url
    if url.startswith("jdbc:"):        # accept JDBC-style spellings
        url = url[len("jdbc:"):]
    scheme = url.split(":", 1)[0].lower()
    if scheme == "sqlite":
        import sqlite3

        # sqlite:///path/to.db or sqlite:/path
        path = url.split("://", 1)[-1] if "://" in url else url.split(":", 1)[1]
        return sqlite3.connect(path)
    if scheme in ("postgresql", "postgres"):
        try:
            import psycopg2
        except ImportError:
            raise ImportError(
                "postgresql:// URLs need psycopg2, which is not installed "
                "in this environment (SQLManager.java analog is driver-"
                "pluggable; sqlite works out of the box)") from None
        return psycopg2.connect(url, user=username, password=password)
    if scheme == "mysql":
        try:
            import mysql.connector
        except ImportError:
            raise ImportError(
                "mysql:// URLs need mysql-connector-python, which is not "
                "installed; sqlite works out of the box") from None
        from urllib.parse import urlparse

        u = urlparse(url)
        return mysql.connector.connect(
            host=u.hostname, port=u.port or 3306, user=username,
            password=password, database=u.path.lstrip("/"))
    raise ValueError(f"unsupported SQL scheme {scheme!r} "
                     "(sqlite/postgresql/mysql)")


def import_sql_select(connection_url: str, select_query: str,
                      username: Optional[str] = None,
                      password: Optional[str] = None,
                      destination_frame: Optional[str] = None) -> H2OFrame:
    """h2o.import_sql_select parity: run the query, type the result columns
    (numeric stays numeric; everything else interns as enum), build a
    row-sharded Frame."""
    conn = _connect(connection_url, username, password)
    try:
        cur = conn.cursor()
        cur.execute(select_query)
        names = [d[0] for d in cur.description]
        rows = cur.fetchall()
    finally:
        conn.close()
    n = len(rows)
    fr = H2OFrame(destination_frame=destination_frame)
    for j, name in enumerate(names):
        vals = [r[j] for r in rows]
        numeric = all(v is None or isinstance(v, (int, float)) for v in vals)
        if numeric:
            arr = np.array([np.nan if v is None else float(v) for v in vals],
                           np.float64)
            fr.add(name, Column.from_numpy(arr))
        else:
            arr = np.array([None if v is None else str(v) for v in vals],
                           object)
            fr.add(name, Column.from_numpy(arr, ctype=T_CAT))
    from h2o3_tpu.utils import log

    log.info(f"imported SQL result -> {n}x{len(names)} [{fr.frame_id}]")
    return fr


def import_sql_table(connection_url: str, table: str,
                     columns: Optional[Sequence[str]] = None,
                     username: Optional[str] = None,
                     password: Optional[str] = None,
                     destination_frame: Optional[str] = None) -> H2OFrame:
    """h2o.import_sql_table parity (SQLManager.java importSqlTable)."""
    if not table.replace("_", "").replace(".", "").isalnum():
        raise ValueError(f"suspicious table name {table!r}")
    cols = ", ".join(columns) if columns else "*"
    return import_sql_select(connection_url, f"SELECT {cols} FROM {table}",
                             username=username, password=password,
                             destination_frame=destination_frame)
