"""Versioned artifact manifest: the schema-validated front door.

Reference: hex/genmodel `model.ini` + MOJO zip layout — a self-describing
container a dependency-free runtime introspects before touching payloads.
Here the manifest is JSON (``manifest.json`` in the artifact directory)
naming every payload file with its sha256, so the loader can (a) reject a
tampered/truncated artifact before any bytes reach an unpickler and
(b) refuse future format versions instead of misreading them.

Every read goes through :func:`read_manifest` (structural validation) and
:func:`read_payload` (checksum-verified bytes) — there is deliberately no
"just open the file" path.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Any, Dict, List, Optional

FORMAT = "h2o3-tpu-aot-artifact"
FORMAT_VERSION = 1
MANIFEST_NAME = "manifest.json"


class ArtifactError(ValueError):
    """Malformed / tampered / incompatible artifact."""


def sha256_bytes(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def file_entry(name: str, data: bytes) -> Dict[str, Any]:
    return {"name": name, "sha256": sha256_bytes(data), "bytes": len(data)}


def write_payload(art_dir: str, name: str, data: bytes) -> Dict[str, Any]:
    """Write one payload file atomically and return its manifest entry."""
    path = os.path.join(art_dir, name)
    tmp = path + ".part"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)
    return file_entry(name, data)


def _check_name(name: str) -> str:
    """Payload names are bare filenames inside the artifact dir — a
    manifest must not be able to point the loader outside it."""
    if not name or os.path.basename(name) != name or name.startswith("."):
        raise ArtifactError(f"illegal payload file name {name!r}")
    return name


def read_payload(art_dir: str, entry: Dict[str, Any]) -> bytes:
    """Checksum-verified payload read; raises ArtifactError on mismatch,
    truncation, or a manifest entry pointing outside the directory."""
    if not isinstance(entry, dict) or not entry.get("name") \
            or not entry.get("sha256"):
        raise ArtifactError(f"malformed payload entry {entry!r}")
    path = os.path.join(art_dir, _check_name(str(entry["name"])))
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError as e:
        raise ArtifactError(f"payload {entry['name']!r} unreadable: {e}") \
            from None
    if sha256_bytes(data) != entry["sha256"]:
        raise ArtifactError(
            f"payload {entry['name']!r} checksum mismatch — artifact is "
            "corrupt or was tampered with")
    return data


# required manifest keys -> type check (None = any JSON value)
_SCHEMA = {
    "format": str,
    "format_version": int,
    "algo": str,
    "model_category": str,
    "model_checksum": str,
    "nclasses": int,
    "per_class_trees": bool,
    "max_depth": int,
    "init_f": float,
    "names": list,
    "domains": dict,
    "post": dict,
    "default_threshold": float,
    "files": dict,
    "buckets": list,
    "executables": list,
    "stablehlo": list,
}


def new_manifest(**fields) -> Dict[str, Any]:
    m = {"format": FORMAT, "format_version": FORMAT_VERSION,
         "created_ts": time.time()}
    m.update(fields)
    return m


def write_manifest(art_dir: str, manifest: Dict[str, Any]) -> str:
    validate(manifest)
    path = os.path.join(art_dir, MANIFEST_NAME)
    tmp = path + ".part"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return path


def validate(m: Any) -> Dict[str, Any]:
    if not isinstance(m, dict):
        raise ArtifactError("manifest is not a JSON object")
    if m.get("format") != FORMAT:
        raise ArtifactError(
            f"not an {FORMAT} artifact (format={m.get('format')!r})")
    ver = m.get("format_version")
    if not isinstance(ver, int) or ver > FORMAT_VERSION or ver < 1:
        raise ArtifactError(
            f"artifact format_version {ver!r} is not supported by this "
            f"runtime (supports 1..{FORMAT_VERSION}) — export/load version "
            "mismatch")
    for key, typ in _SCHEMA.items():
        if key not in m:
            raise ArtifactError(f"manifest missing required key {key!r}")
        if typ is float and isinstance(m[key], int):
            continue                      # JSON ints are acceptable floats
        if typ is not None and not isinstance(m[key], typ):
            raise ArtifactError(
                f"manifest key {key!r} has type {type(m[key]).__name__}, "
                f"expected {typ.__name__}")
    mt = m.get("model_type", "forest")
    if mt not in ("forest", "glm", "pipeline"):
        raise ArtifactError(
            f"unsupported model_type {mt!r} (this runtime loads 'forest', "
            "'glm' and 'pipeline' artifacts)")
    if mt == "glm":
        if not isinstance(m.get("glm"), dict):
            raise ArtifactError("glm artifact manifest missing its 'glm' "
                                "configuration block")
        if "glm" not in m["files"]:
            raise ArtifactError("glm artifact manifest names no 'glm' "
                                "payload file")
    elif mt == "pipeline":
        p = m.get("pipeline")
        if not isinstance(p, dict):
            raise ArtifactError("pipeline artifact manifest missing its "
                                "'pipeline' block")
        if not isinstance(p.get("inputs"), list) or not p["inputs"]:
            raise ArtifactError("pipeline artifact declares no raw "
                                "inputs")
        if p.get("inner") not in ("forest", "glm"):
            raise ArtifactError(
                f"pipeline artifact wraps unsupported inner model "
                f"{p.get('inner')!r}")
        if "pipeline" not in m["files"]:
            raise ArtifactError("pipeline artifact manifest names no "
                                "'pipeline' payload file")
    elif "forest" not in m["files"]:
        raise ArtifactError("forest artifact manifest names no 'forest' "
                            "payload file")
    for entry in list(m["files"].values()) + list(m["executables"]) \
            + list(m["stablehlo"]):
        if not isinstance(entry, dict) or "name" not in entry \
                or "sha256" not in entry:
            raise ArtifactError(f"malformed file entry {entry!r}")
        _check_name(str(entry["name"]))
    return m


def read_manifest(art_dir: str) -> Dict[str, Any]:
    path = os.path.join(art_dir, MANIFEST_NAME)
    try:
        with open(path, encoding="utf-8") as f:
            raw = f.read()
    except OSError as e:
        raise ArtifactError(f"no readable {MANIFEST_NAME} in {art_dir!r}: "
                            f"{e}") from None
    try:
        m = json.loads(raw)
    except ValueError as e:
        raise ArtifactError(f"{MANIFEST_NAME} is not valid JSON: {e}") \
            from None
    return validate(m)


def exec_entries_for_backend(m: Dict[str, Any],
                             fingerprint: str) -> List[Dict[str, Any]]:
    """Serialized-executable entries usable on this backend (fingerprint
    match); an artifact exported elsewhere yields [] and the loader falls
    back to the StableHLO path."""
    return [e for e in m.get("executables", [])
            if e.get("backend") == fingerprint]


def stablehlo_entry(m: Dict[str, Any], bucket: int) -> Optional[Dict[str, Any]]:
    for e in m.get("stablehlo", []):
        if int(e.get("bucket", -1)) == int(bucket):
            return e
    return None
