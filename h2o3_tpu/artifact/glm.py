"""GLM artifact packing + AOT lowering — the first non-forest artifact
class (ROADMAP item 2c starter).

The exported program IS ``models/glm._glm_predict`` — the exact jit
program in-process serving runs (DataInfo.expand's impute/one-hot/
standardize, the intercept-augmented matmul, the linkinv) lowered per row
bucket over per-column inputs (int32 categorical codes, float32 numerics,
NA as negative/NaN). Bitwise identity to ``GLMModel.predict`` is by
construction, not re-implementation; the DataInfo moments are program
constants, beta rides as an argument from the npz payload.

Scope (refused with a clear reason otherwise): gaussian-family regression,
binomial and multinomial GLMs without interactions, offset columns or the
ordinal link — the shapes the expand/matmul/linkinv program covers
standalone.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Dict, Optional, Tuple

import numpy as np

GLM_FILE = "glm.npz"


def supports_glm_export(model) -> Optional[str]:
    """None when `model` is an exportable GLM; otherwise the reason."""
    from h2o3_tpu.models.glm import GLMModel

    if not isinstance(model, GLMModel):
        return f"{type(model).__name__} is not a GLM"
    if model.beta is None or model.dinfo is None:
        return "model has no trained coefficients"
    if model.linkname == "ordinal":
        return "ordinal GLMs are not artifact-exportable yet"
    if model._parms.get("interactions"):
        return ("GLMs with interaction columns expand frames at adapt "
                "time and cannot ride the standalone program")
    if model._parms.get("offset_column"):
        return ("GLMs with an offset column need per-request offsets the "
                "standalone artifact cannot carry")
    return None


def pack_glm(model) -> Dict[str, np.ndarray]:
    """Dense arrays for a trained GLM — the whole payload is arrays
    (allow_pickle=False end to end, like the forest npz)."""
    d = model.dinfo
    return {
        "beta": np.asarray(model.beta, np.float32),
        "cat_modes": np.asarray(d.cat_modes, np.int32),
        "impute_values": np.asarray(d.impute_values, np.float32),
        "num_means": np.asarray(d.num_means, np.float32),
        "num_sigmas": np.asarray(d.num_sigmas, np.float32),
        "cards": np.asarray(d.cards, np.int64),
    }


def glm_meta(model) -> Dict[str, Any]:
    """The static (shape-defining) configuration the fused program is
    specialized on; rides in the manifest's ``glm`` block."""
    d = model.dinfo
    return {"use_all_factor_levels": bool(d.use_all_factor_levels),
            "standardize": bool(d.standardize),
            "linkname": str(model.linkname),
            "link_power": float(model.link_power),
            "nclasses": int(model._output.nclasses),
            "n_cat": len(d.cat_names),
            "n_num": len(d.num_names),
            "cards": [int(c) for c in d.cards]}


def glm_checksum(model) -> str:
    """Content hash of everything that shapes the fused GLM program
    (packed arrays + static meta) — same discipline as
    packer.model_checksum for forests."""
    h = hashlib.sha256()
    arrays = pack_glm(model)
    for name in sorted(arrays):
        a = np.ascontiguousarray(arrays[name])
        h.update(name.encode())
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    h.update(json.dumps(glm_meta(model), sort_keys=True).encode())
    return h.hexdigest()


def lower_glm_bucket(bucket: int, model):
    """Lowered (not yet compiled) GLM scoring program for one row bucket.

    This lowers ``models/glm._glm_predict`` ITSELF — the exact jit
    program in-process serving runs (expand + intercept matmul + linkinv,
    with the DataInfo moments closed over as program constants) — so the
    artifact's outputs are bitwise-identical to ``GLMModel.predict`` by
    construction, not by re-implementation (the program is batch-size
    stable, so any bucket matches any padded in-process row count).
    Canonical per-column input dtypes: int32 categorical codes
    (``astype(int32)`` makes the narrow in-frame dtypes equivalent),
    float32 numerics; the runner packs to the same."""
    import jax

    from h2o3_tpu.models.glm import _glm_predict

    d = model.dinfo
    K = int(model._output.nclasses)
    structs = tuple(jax.ShapeDtypeStruct((int(bucket),), np.int32)
                    for _ in d.cat_names) + \
        tuple(jax.ShapeDtypeStruct((int(bucket),), np.float32)
              for _ in d.num_names)
    beta_s = jax.ShapeDtypeStruct(np.asarray(model.beta).shape, np.float32)
    # offset rides as the same concrete 0.0 scalar _predict_raw passes
    return _glm_predict.lower(structs, beta_s, 0.0, expand=d.expand,
                              linkname=model.linkname,
                              link_power=model.link_power,
                              nclasses=K if K > 2 else 1)


def compile_glm_bucket(bucket: int, model
                       ) -> Tuple[Any, Optional[bytes], str, Any]:
    """AOT-compile the GLM program for one row bucket; returns
    (compiled, blob_or_None, stablehlo_text, kept_arg_indices_or_None) —
    the GLM twin of aot.compile_bucket, ledger family "artifact"."""
    from h2o3_tpu.artifact import aot
    from h2o3_tpu.obs import compiles

    d = model.dinfo
    lowered = lower_glm_bucket(bucket, model)
    text = lowered.as_text()
    compiled = compiles.compile_lowered(
        "artifact", lowered,
        signature=("artifact_glm", int(bucket),
                   int(model._output.nclasses), str(model.linkname)),
        program=f"artifact_glm_bucket_{int(bucket)}")
    nargs = len(d.cat_names) + len(d.num_names) + 2   # cols + beta + offset
    return (compiled, aot.serialize_exec_blob(compiled), text,
            aot.kept_arg_indices(compiled, text, nargs))
