"""Pipeline artifact exporter: munge→score as ONE standalone program.

The MOJO-pipeline story (PAPER.md §L8) for the AOT lineage: where a
forest/GLM artifact scores TRAINING-SHAPED feature rows, a *pipeline*
artifact (manifest ``model_type="pipeline"``) ships the captured Rapids
feature plan fused with the model core, so ``h2o3_genmodel.aot`` scores
RAW untransformed rows — the engineered features are computed inside the
same XLA program as the bin+traverse (forest) or expand+matmul+linkinv
(GLM) core, bitwise-identical to in-process pipeline serving.

Everything rides the existing artifact container: sha256-gated payloads,
per-bucket AOT executable + StableHLO fallback, single-device lowering.
The plan itself (SSA snapshot of the spliced expression trees) is written
as ``pipeline.json`` — the auditable record of WHAT was fused; the
runner never interprets it, it executes the shipped program.

Export refuses what cannot be reproduced bitwise in one program:

- feature expressions containing compiler-rewrite boundaries (``/ ^ %
  intDiv``, or a multiply feeding an add/sub) — in-process these split
  into separate cached sub-programs, and fusing them into one standalone
  lowering would license exactly the FMA/reassociation rewrites the
  split exists to prevent;
- raw inputs that are not float32 numerics or integer-coded
  categoricals (the
  raw-row packer produces float32; integer-typed numeric columns take
  a different arithmetic path in-process);
- unnamed or name-colliding leaf columns (the raw-row schema must be a
  plain name→column mapping).
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, List, Optional

import numpy as np

from h2o3_tpu.artifact import aot, manifest, packer
from h2o3_tpu.artifact.manifest import ArtifactError
from h2o3_tpu.core.frame import T_CAT
from h2o3_tpu.rapids import fusion

PIPELINE_FILE = "pipeline.json"


# ---------------------------------------------------------------------------
# capture + eligibility
# ---------------------------------------------------------------------------

def capture_for_export(model, frame):
    """(Capture, inner) for a model over a frame carrying a PENDING lazy
    feature pipeline; raises ArtifactError with the refusal reason."""
    from h2o3_tpu import pipeline as pl
    from h2o3_tpu.models.glm import GLMModel

    if isinstance(model, GLMModel):
        from h2o3_tpu.artifact.glm import supports_glm_export

        why = supports_glm_export(model) or pl.glm_eligible(model, frame)
        if why:
            raise ArtifactError(f"cannot export pipeline for {model.key}: "
                                f"{why}")
        d = model.dinfo
        got = pl._owning_planner(frame, d.predictor_names)
        if got is None:
            raise ArtifactError(
                f"cannot export pipeline for {model.key}: the frame "
                "carries no pending lazy Rapids feature for this model's "
                "predictors (export BEFORE anything observes the deferred "
                "columns)")
        planner, _n = got
        with planner._lock:
            cap = pl._capture_pipe(frame, d.predictor_names, planner)
        if cap is None:
            raise ArtifactError(
                f"cannot export pipeline for {model.key}: a pending "
                "feature does not fuse (sorts/slices and non-fusible ops "
                "stay on the staged path)")
        return cap, "glm"

    from h2o3_tpu import scoring

    if not scoring.supports(model):
        raise ArtifactError(
            f"cannot export pipeline for {model.key}: not a fused-path "
            "forest model (GBM/DRF/XGBoost) or GLM")
    session = scoring.session_for(model)
    cap = pl.capture_forest(session, frame)
    if cap is None:
        raise ArtifactError(
            f"cannot export pipeline for {model.key}: the frame does not "
            "splice onto the model (needs >= 1 pending lazy Rapids "
            "feature, concrete columns matching the training schema "
            "exactly, and a fusible expression per engineered feature)")
    return cap, "forest"


def check_exportable(cap) -> None:
    """Refuse captures whose one-program lowering could not be bitwise."""
    plan = cap.plan
    for leaf in plan.leaves:
        if isinstance(leaf, fusion.Plan):
            raise ArtifactError(
                "pipeline features contain compiler-rewrite boundaries "
                "(/ ^ % intDiv, or a multiply feeding an add/sub); "
                "in-process these run as separate programs and cannot be "
                "fused bitwise into one standalone program — simplify the "
                "feature expressions or precompute those terms")
    names = []
    for i, leaf in enumerate(plan.leaves):
        nm = cap.names_by_token.get(leaf.token)
        if not nm:
            raise ArtifactError(
                "every raw input of a pipeline artifact must be a "
                "uniquely-named frame column (an unnamed or ambiguously "
                "named leaf cannot enter the raw-row schema)")
        names.append(nm)
        dt = str(plan.leaf_dtypes[i])
        if plan.leaf_ctypes[i] == T_CAT:
            # code width is immaterial: codes only feed comparisons and
            # table gathers, so int8 in-process == int32 in the artifact
            if not dt.startswith("int"):
                raise ArtifactError(
                    f"categorical input {nm!r} has dtype {dt}; pipeline "
                    "artifacts require integer level codes")
        elif dt != "float32":
            raise ArtifactError(
                f"numeric input {nm!r} has dtype {dt}; pipeline artifacts "
                "score float32 raw rows, and integer-typed columns take a "
                "different arithmetic path in-process — cast the source "
                "column to real first")
    if len(set(names)) != len(names):
        raise ArtifactError(
            "two distinct raw input columns share a name — the raw-row "
            f"schema must be unambiguous (inputs: {names})")


# ---------------------------------------------------------------------------
# plan snapshot (pipeline.json) — the auditable SSA record
# ---------------------------------------------------------------------------

def _tree_json(node):
    if isinstance(node, tuple):
        return [_tree_json(c) for c in node]
    return node


def _inputs_of(cap) -> List[Dict[str, Any]]:
    plan = cap.plan
    out = []
    for i, leaf in enumerate(plan.leaves):
        nm = cap.names_by_token.get(leaf.token)
        cat = plan.leaf_ctypes[i] == T_CAT
        out.append({"name": nm, "kind": "cat" if cat else "num",
                    "domain": list(leaf.domain or []) if cat else None})
    return out


def _plan_payload(cap, inner: str) -> bytes:
    plan = cap.plan
    doc = {
        "inner": inner,
        "signature": plan.signature,
        "root": _tree_json(plan.root),
        "inputs": _inputs_of(cap),
        "consts": [float(v) for v in plan.consts],
        "spliced_nodes": int(cap.spliced),
    }
    return json.dumps(doc, indent=1, sort_keys=True).encode("utf-8")


# ---------------------------------------------------------------------------
# lowering — feature plan + model core in one single-device program
# ---------------------------------------------------------------------------

def _scorer_fn(cap, inner: str, model):
    """run(Xr, offset) over a (bucket, R) float32 raw matrix: re-derive
    typed leaf columns (cat codes via the same NaN→-1 rule the raw-row
    packer uses), evaluate every feature expression with the shared
    elementwise tracers, and run the model core — constants baked in, so
    the standalone runner needs no device arguments."""
    import jax.numpy as jnp

    from h2o3_tpu.ops import elementwise as E

    plan = cap.plan
    ctypes = list(plan.leaf_ctypes)
    feats = plan.root[1:]
    const_dev = [jnp.float32(float(v)) for v in plan.consts]

    if inner == "forest":
        arrays = packer.pack_forest(model.forest, model.spec)
        meta = packer.forest_meta(model.forest, model.spec)
        edges, is_cat, fargs = packer.scoring_inputs(arrays)
        init = (arrays["init_class"] if "init_class" in arrays
                else np.float32(meta["init_f"]))
        edges_c = jnp.asarray(edges)
        is_cat_c = jnp.asarray(is_cat)
        init_c = jnp.asarray(init)
        fargs_c = tuple(jnp.asarray(a) for a in fargs)
        max_depth = int(meta["max_depth"])
        K = (int(meta["nclasses"])
             if (int(meta["nclasses"]) > 2 or meta["per_class_trees"])
             else 1)
    else:
        d = model.dinfo
        beta_c = jnp.asarray(np.asarray(model.beta, np.float32))
        K = int(model._output.nclasses)
        catset = set(d.cat_names)
        pred_names = list(d.predictor_names)

    def run(Xr, offset):
        cols = []
        for i, ct in enumerate(ctypes):
            x = Xr[:, i]
            cols.append(jnp.where(jnp.isnan(x), -1.0, x)
                        .astype(jnp.int32) if ct == T_CAT else x)

        def ev(node):
            k = node[0]
            if k == "L":
                c = cols[node[1]]
                return (E.cat_to_f32_expr(c)
                        if ctypes[node[1]] == T_CAT else c)
            if k == "K":
                return const_dev[node[1]]
            if k == "bin":
                return E.binop_expr(node[1], ev(node[2]), ev(node[3]))
            if k == "log":
                return E.logical_expr(node[1], ev(node[2]), ev(node[3]))
            if k == "un":
                return E.unop_expr(node[1], ev(node[2]))
            if k == "ifelse":
                return E.ifelse_expr(ev(node[1]), ev(node[2]), ev(node[3]))
            if k == "isna":
                return E.isna_expr(ev(node[1]))
            raise AssertionError(f"bad pipeline node {k!r}")

        if inner == "forest":
            from h2o3_tpu.models.tree.compressed import _fused_margins

            parts = [cols[f[1]].astype(jnp.float32) if f[0] == "L"
                     else ev(f) for f in feats]
            X = jnp.stack(parts, axis=-1)
            return _fused_margins(X, edges_c, is_cat_c, init_c, *fargs_c,
                                  max_depth, K)

        from h2o3_tpu.models.glm import _glm_predict

        arrs = []
        for i, name in enumerate(pred_names):
            f = feats[i]
            if name in catset:
                arrs.append(cols[f[1]])        # int32 codes, concrete
            else:
                arrs.append(cols[f[1]] if f[0] == "L" else ev(f))
        return _glm_predict(
            tuple(arrs), beta_c, offset, expand=d.expand,
            linkname=model.linkname,
            link_power=(model.link_power if K <= 2 else 0.0),
            nclasses=K if K > 2 else 1)

    return run


def compile_pipeline_bucket(bucket: int, cap, inner: str, model,
                            sig_hash: str):
    """AOT-compile one bucket of the fused pipeline; returns (compiled,
    blob_or_None, stablehlo_text, kept_arg_indices_or_None)."""
    import jax

    from h2o3_tpu.obs import compiles

    R = len(cap.plan.leaves)
    fn = jax.jit(_scorer_fn(cap, inner, model))
    lowered = fn.lower(
        jax.ShapeDtypeStruct((int(bucket), R), np.float32),
        jax.ShapeDtypeStruct((), np.float32))
    text = lowered.as_text()
    compiled = compiles.compile_lowered(
        "artifact", lowered,
        signature=("artifact_pipeline", int(bucket), inner, sig_hash),
        program=f"artifact_pipeline_bucket_{int(bucket)}")
    return (compiled, aot.serialize_exec_blob(compiled), text,
            aot.kept_arg_indices(compiled, text, 2))


# ---------------------------------------------------------------------------
# export
# ---------------------------------------------------------------------------

def export_pipeline(model, frame, out_dir: str,
                    buckets: Optional[List[int]] = None) -> Dict[str, Any]:
    """Export the lazy feature pipeline feeding `frame` fused with
    `model` as a standalone pipeline artifact; returns the manifest.
    Capture is read-only — the pending DAG survives the export and the
    frame can still be scored in-process afterwards."""
    from h2o3_tpu.artifact import export as model_export
    from h2o3_tpu.artifact import glm as artifact_glm
    from h2o3_tpu.models.glm import GLMModel

    cap, inner = capture_for_export(model, frame)
    check_exportable(cap)
    buckets = sorted({int(b) for b in
                      (buckets or model_export.default_buckets())
                      if int(b) > 0})
    if not buckets:
        raise ArtifactError("at least one positive row bucket is required")
    os.makedirs(out_dir, exist_ok=True)

    if inner == "glm":
        inner_checksum = artifact_glm.glm_checksum(model)
        model_arrays = artifact_glm.pack_glm(model)
        model_file = ("glm", artifact_glm.GLM_FILE)
        o = model._output
        cat = o.model_category
        post = {"kind": ("glm_binomial" if cat == "Binomial"
                         else "glm_multinomial" if cat == "Multinomial"
                         else "glm_regression")}
        nclasses = int(artifact_glm.glm_meta(model)["nclasses"])
        per_class, max_depth, init_f, n_trees = False, 0, 0.0, 0
    else:
        inner_checksum = packer.model_checksum(model.forest, model.spec)
        model_arrays = packer.pack_forest(model.forest, model.spec)
        model_file = ("forest", model_export.FOREST_FILE)
        meta = packer.forest_meta(model.forest, model.spec)
        o = model._output
        post = model_export._post_spec(model)
        nclasses = int(meta["nclasses"])
        per_class = bool(meta["per_class_trees"])
        max_depth = int(meta["max_depth"])
        init_f = float(meta["init_f"])
        n_trees = int(meta["n_trees"])

    sig_hash = hashlib.sha256(
        (inner_checksum + "|" + cap.plan.signature).encode()).hexdigest()
    plan_entry = manifest.write_payload(out_dir, PIPELINE_FILE,
                                        _plan_payload(cap, inner))
    model_entry = manifest.write_payload(out_dir, model_file[1],
                                         packer.dump_npz(model_arrays))
    fingerprint = aot.backend_fingerprint(single_device=True)
    execs, hlos = [], []
    for b in buckets:
        _compiled, blob, text, kept = compile_pipeline_bucket(
            b, cap, inner, model, sig_hash)
        if blob is not None:
            e = manifest.write_payload(out_dir, f"exec_b{b}.bin", blob)
            e.update(bucket=b, backend=fingerprint)
            execs.append(e)
        h = manifest.write_payload(out_dir, f"hlo_b{b}.mlir",
                                   text.encode("utf-8"))
        h.update(bucket=b, kept_args=kept)
        hlos.append(h)

    inputs = _inputs_of(cap)
    names = [i["name"] for i in inputs]
    domains = {i["name"]: list(i["domain"]) for i in inputs
               if i["kind"] == "cat"}
    m = manifest.new_manifest(
        model_type="pipeline",
        algo=str(model.algo_name),
        model_key=str(model.key),
        model_category=str(o.model_category),
        model_checksum=sig_hash,
        nclasses=nclasses,
        per_class_trees=per_class,
        max_depth=max_depth,
        init_f=init_f,
        n_trees=n_trees,
        names=names,
        response_name=o.response_name,
        response_domain=list(o.response_domain or []) or None,
        domains=domains,
        post=post,
        default_threshold=model_export._default_threshold(model),
        pipeline={
            "inner": inner,
            "inputs": inputs,
            "signature": cap.plan.signature,
            "spliced_nodes": int(cap.spliced),
            "inner_model_checksum": inner_checksum,
        },
        glm=(artifact_glm.glm_meta(model)
             if isinstance(model, GLMModel) else None) or {},
        files={"pipeline": plan_entry, model_file[0]: model_entry},
        buckets=buckets,
        executables=execs,
        stablehlo=hlos,
    )
    manifest.write_manifest(out_dir, m)
    from h2o3_tpu.utils import timeline

    timeline.record("artifact", "export_pipeline", model=str(model.key),
                    dir=out_dir, buckets=len(buckets),
                    executables=len(execs), inner=inner,
                    spliced=int(cap.spliced))
    return m
