"""Packed-constant codecs: forest/spec arrays and tree-progress chunks.

One layout serves three consumers:

- the artifact exporter packs a trained forest + its BinSpec into ONE
  ``forest.npz`` (``allow_pickle=False`` end to end — arrays are the whole
  payload, nothing executable);
- the standalone runner (h2o3_genmodel.aot) re-hydrates the scoring inputs
  from that npz with numpy alone;
- the durable-job-progress store appends per-tree training state as
  incremental *chunk* files of the same npz discipline, so a tree
  checkpoint writes only the trees grown since the previous save instead
  of re-serializing the whole forest (the recorded PR-5 O(forest) cost).
"""

from __future__ import annotations

import hashlib
import io
import json
from typing import Any, Dict, List, Sequence, Tuple

import numpy as np


# ---------------------------------------------------------------------------
# forest + spec <-> npz
# ---------------------------------------------------------------------------

def pack_forest(forest, spec) -> Dict[str, np.ndarray]:
    """Dense arrays for a CompressedForest + BinSpec (the MOJO payload
    layout, kept field-compatible with models/mojo.py so the two portable
    formats never drift)."""
    arrays = {
        "feat": np.asarray(forest.feat, np.int32),
        "thresh_bin": np.asarray(forest.thresh_bin, np.int32),
        "na_left": np.asarray(forest.na_left).astype(np.int8),
        "left": np.asarray(forest.left, np.int32),
        "right": np.asarray(forest.right, np.int32),
        "leaf_val": np.asarray(forest.leaf_val, np.float32),
        "cat_split": np.asarray(forest.cat_split, np.int32),
        "cat_table": np.asarray(forest.cat_table).astype(np.int8),
        "tree_class": np.asarray(forest.tree_class, np.int32),
        "na_bins": np.asarray(forest.na_bins, np.int32),
        "spec_nbins": np.asarray(spec.nbins, np.int64),
        "spec_is_cat": np.asarray(spec.is_cat).astype(np.int8),
        "spec_cards": np.asarray(spec.cards, np.int64),
        "spec_edges_flat": (np.concatenate(
            [np.asarray(e, np.float64) for e in spec.edges])
            if spec.edges else np.zeros(0)),
        "spec_edges_len": np.asarray([len(e) for e in spec.edges], np.int64),
    }
    if forest.init_class is not None:
        arrays["init_class"] = np.asarray(forest.init_class, np.float32)
    return arrays


def forest_meta(forest, spec) -> Dict[str, Any]:
    return {"max_depth": int(forest.max_depth),
            "init_f": float(forest.init_f),
            "nclasses": int(forest.nclasses),
            "per_class_trees": bool(forest.per_class_trees),
            "n_trees": int(forest.n_trees),
            "spec_names": list(spec.names)}


def dump_npz(arrays: Dict[str, np.ndarray]) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def load_npz(data: bytes) -> Dict[str, np.ndarray]:
    with np.load(io.BytesIO(data), allow_pickle=False) as z:
        return {k: np.asarray(z[k]) for k in z.files}


def model_checksum(forest, spec) -> str:
    """Content hash of everything that shapes the fused scoring program:
    the packed arrays plus the scalar forest meta. The persistent compile
    cache and the artifact manifest both key on it, so a retrained model
    under the same DKV key can never be served a stale executable."""
    h = hashlib.sha256()
    arrays = pack_forest(forest, spec)
    for name in sorted(arrays):
        a = np.ascontiguousarray(arrays[name])
        h.update(name.encode())
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    h.update(json.dumps(forest_meta(forest, spec), sort_keys=True).encode())
    return h.hexdigest()


def padded_edges(edges_flat: np.ndarray, edges_len: np.ndarray,
                 F: int) -> np.ndarray:
    """(F, emax) float32 +inf-padded edge matrix — the exact construction
    ScoringSession.__init__ feeds the fused program, so binning in the
    standalone runner is bitwise-identical to in-process serving."""
    lens = [int(v) for v in np.asarray(edges_len).reshape(-1)]
    emax = max(lens, default=0) or 1
    ep = np.full((F, emax), np.inf, np.float32)
    pos = 0
    for i, ln in enumerate(lens):
        ep[i, :ln] = np.asarray(edges_flat[pos: pos + ln], np.float32)
        pos += ln
    return ep


def scoring_inputs(arrays: Dict[str, np.ndarray]
                   ) -> Tuple[np.ndarray, np.ndarray, tuple]:
    """(edges_padded, is_cat, forest_arg_tuple) in the fused program's
    argument order — shared by the server-side loader and the standalone
    runner."""
    F = int(arrays["spec_is_cat"].shape[0])
    edges = padded_edges(arrays["spec_edges_flat"], arrays["spec_edges_len"],
                         F)
    is_cat = arrays["spec_is_cat"].astype(bool)
    forest_args = (
        arrays["feat"], arrays["thresh_bin"], arrays["na_left"].astype(bool),
        arrays["left"], arrays["right"],
        arrays["leaf_val"].astype(np.float32),
        arrays["cat_split"], arrays["cat_table"].astype(bool),
        arrays["tree_class"], arrays["na_bins"])
    return edges, is_cat, forest_args


# ---------------------------------------------------------------------------
# tree-progress chunks (append-only job-progress suffix files)
# ---------------------------------------------------------------------------

def pack_tree_chunk(packs: Sequence[np.ndarray],
                    leaf_vals: Sequence[np.ndarray],
                    leaf_wys: Sequence[np.ndarray]) -> bytes:
    """One suffix chunk = the per-tree tables for a contiguous run of
    newly-grown trees, stacked (every tree of a run shares its shapes) and
    npz-encoded. ``n`` rides along so a reader can sanity-check the stack."""
    n = len(packs)
    if not (n == len(leaf_vals) == len(leaf_wys)):
        raise ValueError("tree chunk lists disagree in length")
    return dump_npz({
        "n": np.asarray([n], np.int64),
        "packs": np.stack([np.asarray(p) for p in packs]),
        "leaf_vals": np.stack([np.asarray(v, np.float32)
                               for v in leaf_vals]),
        "leaf_wys": np.stack([np.asarray(w, np.float32) for w in leaf_wys]),
    })


def unpack_tree_chunk(data: bytes
                      ) -> Tuple[List[np.ndarray], List[np.ndarray],
                                 List[np.ndarray]]:
    arrays = load_npz(data)
    n = int(arrays["n"][0])
    if any(arrays[k].shape[0] != n for k in ("packs", "leaf_vals",
                                             "leaf_wys")):
        raise ValueError("torn tree chunk: stack lengths disagree with n")
    return ([arrays["packs"][i] for i in range(n)],
            [arrays["leaf_vals"][i] for i in range(n)],
            [arrays["leaf_wys"][i] for i in range(n)])
