"""Persistent fused-program compile cache.

The bucketed serving fast path compiles one program per (model, bucket).
Before this module every server restart re-paid that compile spike. Now
each compiled executable is serialized (compat shims) into an on-disk
cache keyed by ``(model checksum, bucket, variant, backend fingerprint)``
under ``$H2O_TPU_COMPILE_CACHE_DIR`` — shared across processes and server
restarts (put it on shared storage for multi-process clouds, exactly like
the oplog checkpoint dir), so a warm restart compiles ZERO fused programs.

Unset env disables the disk tier (sessions still hold executables in
memory for their lifetime). Writes are atomic (tmp + rename), reads are
checksum-free by design — the key embeds the model checksum, and a
corrupt blob simply fails deserialization and falls back to a compile.

The module also owns the fused-compile counter the warm-restart test (and
bench cold-start stage) assert on: ``note_compile()`` increments ONLY when
an actual XLA compilation ran.
"""

from __future__ import annotations

import hashlib
import os
import threading
from typing import Any, Optional

_LOCK = threading.Lock()
_STATS = {"compiles": 0, "disk_hits": 0, "disk_misses": 0, "stores": 0,
          "load_failures": 0, "compile_ms_total": 0.0}


def cache_dir() -> Optional[str]:
    """Cache root (env ``H2O_TPU_COMPILE_CACHE_DIR``); None disables the
    persistent tier."""
    d = os.environ.get("H2O_TPU_COMPILE_CACHE_DIR", "").strip()
    return d or None


def enabled() -> bool:
    return cache_dir() is not None


def cache_key(model_checksum: str, bucket: int, variant: str = "mesh",
              fingerprint: Optional[str] = None) -> str:
    """Filename-safe key. `variant` separates program families compiled
    from the same forest (mesh-sharded serving vs degraded-local vs the
    artifact's single-device lowering)."""
    if fingerprint is None:
        from h2o3_tpu.artifact import aot

        fingerprint = aot.backend_fingerprint()
    raw = f"{model_checksum}|b{int(bucket)}|{variant}|{fingerprint}"
    return hashlib.sha256(raw.encode()).hexdigest()


def _path(key: str) -> Optional[str]:
    d = cache_dir()
    if d is None:
        return None
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, f"xc_{key}.bin")


_PHASE_LOAD_SEEN = False


def load(key: str) -> Optional[Any]:
    """Loaded executable for `key`, or None (disabled / miss / unloadable
    blob — the caller compiles). The FIRST executable deserialize of the
    process runs inside the ``compile_cache_load`` lifecycle phase: it
    talks to the backend, so a wedged tunnel wedges HERE at warm-start —
    the phase tracker's deadline and timeline event make that visible
    instead of silent. Later serving-time loads skip the phase so they
    cannot flood the bounded phase history (the boot records must
    survive a long-lived server)."""
    global _PHASE_LOAD_SEEN

    path = _path(key)
    if path is None:
        return None
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError:
        with _LOCK:
            _STATS["disk_misses"] += 1
        return None
    try:
        from h2o3_tpu.artifact import aot

        if not _PHASE_LOAD_SEEN:
            _PHASE_LOAD_SEEN = True
            from h2o3_tpu.obs import phases

            with phases.enter("compile_cache_load", key=key[:16]):
                exe = aot.load_exec_blob(blob)
        else:
            exe = aot.load_exec_blob(blob)
    except Exception:   # noqa: BLE001 — any unloadable blob = miss
        with _LOCK:
            _STATS["load_failures"] += 1
        return None
    with _LOCK:
        _STATS["disk_hits"] += 1
    return exe


def store(key: str, compiled) -> bool:
    """Best-effort serialize + atomic write; False when disabled or this
    backend cannot serialize executables."""
    path = _path(key)
    if path is None:
        return False
    try:
        from h2o3_tpu.artifact import aot

        blob = aot.serialize_exec_blob(compiled)
        if blob is None:
            return False
        tmp = f"{path}.{os.getpid()}.part"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
    except Exception:   # noqa: BLE001 — the cache must never fail serving
        return False
    with _LOCK:
        _STATS["stores"] += 1
    return True


def note_compile(ms: float = 0.0) -> None:
    """Record one actual fused-program XLA compilation. Since the compile
    ledger landed, ``obs/compiles.py`` is the ONLY caller (enforced by
    the `compile-ledger` analysis pass): the ledger times the compile
    itself and feeds this counter the SAME milliseconds it recorded in
    the per-program row, so ``compile_ms_total`` can never drift from
    the ledger (it used to be caller-self-reported)."""
    with _LOCK:
        _STATS["compiles"] += 1
        _STATS["compile_ms_total"] += float(ms)


def fused_compile_count() -> int:
    with _LOCK:
        return _STATS["compiles"]


def stats() -> dict:
    with _LOCK:
        out = dict(_STATS)
    out["dir"] = cache_dir()
    out["enabled"] = enabled()
    return out


def reset_stats() -> None:
    """Zero the counters (tests / warm-restart drills)."""
    with _LOCK:
        for k in _STATS:
            _STATS[k] = 0
