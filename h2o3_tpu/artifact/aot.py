"""AOT lowering/compilation of the fused scoring program.

Reference grounding: "Automatic Full Compilation of Julia Programs and ML
Models to Cloud TPUs" (PAPERS.md) — ship the *compiled program*, not the
model interpreter. Per (model, bucket) the exporter lowers the fused
bin+traverse+init program once with ``jax.jit(...).lower(...).compile()``
and serializes the executable (``jax.experimental.serialize_executable``
via compat.py); the StableHLO text of the same lowering rides along as the
portable fallback for targets whose backend cannot deserialize the binary.

Artifact executables are deliberately lowered SINGLE-DEVICE (no mesh
sharding): the standalone serving tier is one process per replica, and a
single-device program loads on any topology. The in-server compile cache
(compile_cache.py) snapshots mesh-sharded executables instead — its
fingerprint covers the mesh, so the two never mix.
"""

from __future__ import annotations

import io
import pickle
from typing import Any, Dict, Optional, Tuple

import numpy as np

BLOB_VERSION = 1


def backend_fingerprint(single_device: bool = False) -> str:
    """String identity of the XLA target an executable was compiled for.
    Cache keys and artifact entries carry it; a mismatch means 'recompile
    here', never 'try to load anyway'."""
    import jax

    d = jax.devices()[0]
    parts = [
        "jax=" + jax.__version__,
        "platform=" + str(d.platform),
        "kind=" + str(getattr(d, "device_kind", "?")),
    ]
    if single_device:
        parts.append("devices=1")
    else:
        parts += [f"devices={jax.device_count()}",
                  f"processes={jax.process_count()}"]
    return ";".join(parts)


def fused_fn(max_depth: int, nclasses: int, per_class: bool):
    """The one fused scoring program (models/tree/compressed.py) — single
    source of truth for both in-process serving and artifact export."""
    from h2o3_tpu.models.tree.compressed import _fused_score_fn

    return _fused_score_fn(max_depth, nclasses, per_class)


def _arg_structs(bucket: int, edges: np.ndarray, is_cat: np.ndarray,
                 init: np.ndarray, forest_args: tuple):
    """ShapeDtypeStructs for one bucket's lowering (no shardings — the
    artifact program targets a single device)."""
    import jax
    import jax.numpy as jnp

    def s(a):
        a = np.asarray(a)
        return jax.ShapeDtypeStruct(a.shape, a.dtype)

    F = int(is_cat.shape[0])
    return (jax.ShapeDtypeStruct((int(bucket), F), jnp.float32), s(edges),
            s(is_cat), s(init)) + tuple(s(a) for a in forest_args)


def lower_bucket(bucket: int, meta: Dict[str, Any], edges, is_cat, init,
                 forest_args):
    """Lowered (not yet compiled) fused program for one row bucket."""
    fn = fused_fn(int(meta["max_depth"]), int(meta["nclasses"]),
                  bool(meta["per_class_trees"]))
    return fn.lower(*_arg_structs(bucket, edges, is_cat, init, forest_args))


def serialize_exec_blob(compiled) -> Optional[bytes]:
    """Executable -> self-contained blob (None when this jax cannot
    serialize executables). The blob is a pickle of
    ``{v, payload, in_tree, out_tree}`` — loaded ONLY through
    :func:`load_exec_blob`'s restricted unpickler."""
    from h2o3_tpu import compat

    got = compat.serialize_compiled(compiled)
    if got is None:
        return None
    payload, in_tree, out_tree = got
    return pickle.dumps({"v": BLOB_VERSION, "payload": payload,
                         "in_tree": in_tree, "out_tree": out_tree},
                        protocol=pickle.HIGHEST_PROTOCOL)


class _ExecBlobUnpickler(pickle.Unpickler):
    """Executable blobs hold bytes + jax PyTreeDefs and nothing else; any
    other global reference is an attack, not a format evolution."""

    _PREFIXES = ("jax.", "jaxlib.", "numpy.")
    _MODULES = {"jax", "jaxlib", "numpy"}

    def find_class(self, module, name):
        if module in self._MODULES or \
                any(module.startswith(p) for p in self._PREFIXES):
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"executable blob references disallowed type {module}.{name}")


def load_exec_blob(blob: bytes):
    """Blob -> callable loaded executable. Raises on version mismatch,
    disallowed pickle globals, or a backend that cannot deserialize it —
    callers treat every raise as a cache/fallback miss."""
    from h2o3_tpu import compat

    d = _ExecBlobUnpickler(io.BytesIO(blob)).load()
    if not isinstance(d, dict) or d.get("v") != BLOB_VERSION:
        raise ValueError(f"unsupported executable blob version "
                         f"{d.get('v') if isinstance(d, dict) else '?'}")
    return compat.deserialize_compiled(d["payload"], d["in_tree"],
                                       d["out_tree"])


def kept_arg_indices(compiled, text: str, nargs: int):
    """Indices of the Python-level args the lowered program actually takes.
    jit prunes unused args from the XLA signature (e.g. tree_class when
    K == 1); the serialized-executable path carries that mapping itself,
    but the raw StableHLO fallback executes the MLIR main directly and
    must filter its argument list. Returns a sorted list, or None when the
    mapping cannot be established on this jax (the runner then skips the
    HLO fallback with a clear error instead of mis-binding buffers)."""
    import re

    kept = getattr(getattr(compiled, "_executable", None), "_kept_var_idx",
                   None)
    if kept:
        return sorted(int(i) for i in kept)
    m = re.search(r"@main\((.*?)\)\s*->", text, re.S)
    if m is not None and m.group(1).count("%arg") == nargs:
        return list(range(nargs))
    return None


def compile_bucket(bucket: int, meta: Dict[str, Any], edges, is_cat, init,
                   forest_args) -> Tuple[Any, Optional[bytes], str, Any]:
    """AOT-compile one bucket; returns (compiled, blob_or_None, stablehlo
    text, kept_arg_indices_or_None)."""
    from h2o3_tpu.obs import compiles

    lowered = lower_bucket(bucket, meta, edges, is_cat, init, forest_args)
    text = lowered.as_text()
    # ledger chokepoint (family "artifact"): the exporter's per-bucket
    # compile cost lands on /3/Runtime next to the serving compiles
    compiled = compiles.compile_lowered(
        "artifact", lowered,
        signature=("artifact", int(bucket), int(meta.get("max_depth", 0)),
                   int(meta.get("nclasses", 0))),
        program=f"artifact_bucket_{int(bucket)}")
    nargs = 4 + len(forest_args)
    return (compiled, serialize_exec_blob(compiled), text,
            kept_arg_indices(compiled, text, nargs))
