"""Server-side artifact import: artifact dir -> servable forest model.

The inverse of export.py for the serving tier: a cloud that receives an
artifact (shared filesystem / object store via persist/) re-hydrates the
full SharedTreeModel — forest, BinSpec, distribution, labeling threshold —
and installs it under a DKV key, after which it serves through the SAME
fused bucketed fast path as a locally-trained model (and its executables
land in the warm compile cache on first dispatch).

Every byte read here is checksum-gated by the manifest
(manifest.read_payload); the npz payload is loaded with
``allow_pickle=False``. Nothing in an artifact can reach a pickle VM on
this path.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from h2o3_tpu.artifact import manifest, packer
from h2o3_tpu.artifact.export import FOREST_FILE
from h2o3_tpu.artifact.manifest import ArtifactError


def load_model(art_dir: str, model_id: Optional[str] = None,
               install: bool = True):
    """Load the artifact at `art_dir` into a Model (installed under
    `model_id` or the exported key; `install=False` builds + fully
    validates without touching the DKV — the REST import route uses it as
    its pre-broadcast check so a payload-corrupt artifact can never kill
    follower replay loops). Raises ArtifactError on any corruption,
    version mismatch, or unsupported algo; nothing is registered in the
    DKV until validation has completed."""
    from h2o3_tpu import persist
    from h2o3_tpu.core.dkv import DKV, Key
    from h2o3_tpu.models.distribution import get_distribution
    from h2o3_tpu.models.model import Model, ModelCategory
    from h2o3_tpu.models.mojo import _model_class, _threshold_metrics
    from h2o3_tpu.models.tree.binning import BinSpec
    from h2o3_tpu.models.tree.compressed import CompressedForest
    from h2o3_tpu.models.tree.shared_tree import SharedTreeModel

    art_dir = persist.resolve(art_dir)
    m = manifest.read_manifest(art_dir)
    mt = m.get("model_type", "forest")
    if mt == "glm":
        return _load_glm(art_dir, m, model_id, install)
    if mt == "pipeline":
        raise ArtifactError(
            "pipeline artifacts bind a munge plan to a model and have no "
            "in-cluster frame to run it against — score raw rows "
            "standalone with h2o3_genmodel.aot instead, or import the "
            "wrapped model from its own forest/glm artifact")
    if mt != "forest":
        raise ArtifactError(
            f"artifact model_type {mt!r} cannot be imported into a "
            "serving cloud (forest and glm artifacts import)")
    arrays = packer.load_npz(
        manifest.read_payload(art_dir, m["files"]["forest"]))
    try:
        cls = _model_class(str(m["algo"]))
    except Exception as e:   # noqa: BLE001 — unknown algo is a user error
        raise ArtifactError(f"artifact algo {m['algo']!r} is not loadable "
                            f"here: {e}") from None
    if not issubclass(cls, SharedTreeModel):
        raise ArtifactError(
            f"artifact algo {m['algo']!r} is not a forest model")

    model = cls.__new__(cls)
    Model.__init__(model, parms={})
    # Model.__init__ auto-installs under a fresh key: withdraw it NOW so a
    # validation failure below cannot leak a half-constructed model into
    # /3/Models (it is re-installed under the final key once valid)
    DKV.remove(str(model.key))
    model._distribution = None

    lens = arrays["spec_edges_len"]
    flat = arrays["spec_edges_flat"]
    edges, pos = [], 0
    for ln in lens:
        edges.append(np.asarray(flat[pos: pos + int(ln)], np.float32))
        pos += int(ln)
    spec_names = list(m["names"])
    if len(spec_names) != int(arrays["spec_is_cat"].shape[0]):
        raise ArtifactError("manifest names disagree with packed spec width")
    model.spec = BinSpec(spec_names, arrays["spec_is_cat"].astype(bool),
                         arrays["spec_nbins"], edges, arrays["spec_cards"])
    forest = CompressedForest(
        arrays["feat"], arrays["thresh_bin"], arrays["na_left"].astype(bool),
        arrays["left"], arrays["right"],
        arrays["leaf_val"].astype(np.float32), arrays["cat_split"],
        arrays["cat_table"].astype(bool), arrays["tree_class"],
        arrays["na_bins"], max_depth=int(m["max_depth"]),
        init_f=float(m["init_f"]), nclasses=int(m["nclasses"]))
    if "init_class" in arrays:
        forest.init_class = np.asarray(arrays["init_class"], np.float32)
    model.forest = forest
    if packer.model_checksum(forest, spec=model.spec) != m["model_checksum"]:
        raise ArtifactError("model checksum mismatch — the packed forest "
                            "does not match the manifest")

    dist = (m.get("distribution") or {}).get("name")
    if dist:
        model._distribution = get_distribution(
            dist, tweedie_power=float(
                (m.get("distribution") or {}).get("tweedie_power") or 1.5))

    o = model._output
    o.names = spec_names
    o.domains = {k: list(v) for k, v in (m.get("domains") or {}).items()}
    o.response_name = m.get("response_name")
    o.response_domain = list(m.get("response_domain") or []) or None
    o.model_category = str(m["model_category"])
    if o.model_category == ModelCategory.Binomial:
        o.training_metrics = _threshold_metrics(
            float(m["default_threshold"]))

    dest = str(model_id or m.get("model_key")
               or f"artifact_model_{m['model_checksum'][:12]}")
    model._key = Key(dest)
    if install:
        model.install()
        from h2o3_tpu.utils import timeline

        timeline.record("artifact", "import", model=dest, dir=art_dir,
                        n_trees=int(m.get("n_trees", forest.n_trees)))
    return model


def _load_glm(art_dir: str, m: Dict[str, Any], model_id: Optional[str],
              install: bool):
    """GLM artifact -> servable GLMModel: DataInfo rebuilt from the packed
    moments npz + manifest layout, checksum-verified against the manifest
    before anything reaches the DKV. The re-hydrated model serves through
    the SAME ``_glm_predict`` program the exporter lowered, so its
    predictions are bitwise-identical to the artifact's standalone output
    by construction."""
    from h2o3_tpu.artifact import glm as artifact_glm
    from h2o3_tpu.core.dkv import DKV, Key
    from h2o3_tpu.models.data_info import DataInfo
    from h2o3_tpu.models.glm import GLMModel
    from h2o3_tpu.models.model import Model, ModelCategory
    from h2o3_tpu.models.mojo import _threshold_metrics

    arrays = packer.load_npz(
        manifest.read_payload(art_dir, m["files"]["glm"]))
    meta = m.get("glm") or {}
    names = list(m["names"])
    n_cat, n_num = int(meta.get("n_cat", -1)), int(meta.get("n_num", -1))
    if n_cat < 0 or n_num < 0 or n_cat + n_num != len(names):
        raise ArtifactError(
            "glm artifact layout is inconsistent: manifest names "
            f"({len(names)}) != n_cat + n_num ({n_cat}+{n_num})")

    model = GLMModel.__new__(GLMModel)
    Model.__init__(model, parms={})
    # Model.__init__ auto-installs under a fresh key: withdraw it NOW so a
    # validation failure below cannot leak a half-constructed model
    DKV.remove(str(model.key))
    model.beta = np.asarray(arrays["beta"], np.float32)
    model.linkname = str(meta.get("linkname", "identity"))
    model.link_power = float(meta.get("link_power", 0.0))
    model.null_deviance = float("nan")
    model.residual_deviance = float("nan")
    model.aic = float("nan")
    model.iterations = 0
    model.p_values = None
    model.std_errors = None

    doms = {k: list(v) for k, v in (m.get("domains") or {}).items()}
    d = DataInfo.__new__(DataInfo)
    d.response_name = m.get("response_name")
    d.weights_name = None
    d.offset_name = None
    d.standardize = bool(meta.get("standardize", True))
    d.missing_values_handling = "MeanImputation"
    d.cat_names = names[:n_cat]          # categoricals first (layout rule)
    d.num_names = names[n_cat:]
    d.predictor_names = list(names)
    for n in d.cat_names:
        if n not in doms:
            raise ArtifactError(
                f"glm artifact names categorical predictor {n!r} but "
                "carries no domain for it")
    d.domains = {n: doms[n] for n in d.cat_names}
    d.cards = [int(c) for c in meta.get(
        "cards", [len(d.domains[n]) for n in d.cat_names])]
    d.num_means = np.asarray(arrays["num_means"], np.float32)
    d.num_sigmas = np.asarray(arrays["num_sigmas"], np.float32)
    d.cat_modes = np.asarray(arrays["cat_modes"], np.int32)
    d.impute_values = np.asarray(arrays["impute_values"], np.float32)
    d._recompute_layout(bool(meta.get("use_all_factor_levels", False)))
    model.dinfo = d
    if model.beta.shape[0] != d.fullN + 1:
        raise ArtifactError(
            f"glm artifact beta length {model.beta.shape[0]} does not "
            f"match the expanded layout ({d.fullN}+intercept)")

    o = model._output
    o.names = names
    o.domains = doms
    o.response_name = m.get("response_name")
    o.response_domain = list(m.get("response_domain") or []) or None
    o.model_category = str(m["model_category"])
    if int(meta.get("nclasses", o.nclasses)) != o.nclasses:
        raise ArtifactError(
            "glm artifact nclasses disagrees with its response domain")
    # checksum spans packed arrays AND the rebuilt meta (glm_meta reads
    # dinfo + _output), so it proves the whole re-hydration round-trips
    if artifact_glm.glm_checksum(model) != m["model_checksum"]:
        raise ArtifactError("model checksum mismatch — the packed glm "
                            "payload does not match the manifest")
    if o.model_category == ModelCategory.Binomial:
        o.training_metrics = _threshold_metrics(
            float(m["default_threshold"]))

    dest = str(model_id or m.get("model_key")
               or f"artifact_model_{m['model_checksum'][:12]}")
    model._key = Key(dest)
    if install:
        model.install()
        from h2o3_tpu.utils import timeline

        timeline.record("artifact", "import", model=dest, dir=art_dir,
                        algo="glm")
    return model


def describe(art_dir: str) -> Dict[str, Any]:
    """Validated manifest summary (REST GET surface) — no payload loads
    beyond the manifest itself."""
    from h2o3_tpu import persist

    m = manifest.read_manifest(persist.resolve(art_dir))
    return {k: m.get(k) for k in (
        "format", "format_version", "algo", "model_key", "model_category",
        "model_checksum", "nclasses", "n_trees", "max_depth", "buckets",
        "default_threshold", "created_ts")} | {
        "executables": [{"bucket": e.get("bucket"),
                         "backend": e.get("backend"),
                         "bytes": e.get("bytes")}
                        for e in m.get("executables", [])],
        "stablehlo_buckets": [e.get("bucket")
                              for e in m.get("stablehlo", [])],
        "n_features": len(m.get("names") or []),
    }
