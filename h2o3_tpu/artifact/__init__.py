"""h2o3_tpu.artifact — standalone AOT scoring artifacts (MOJO2-for-TPU).

H2O-3's killer deployment feature is the dependency-free MOJO/POJO scoring
artifact (PAPER.md §2.9). This subsystem is its TPU-native equivalent:

- :mod:`export`        — trained forest model -> self-contained artifact
  directory: versioned manifest, packed constants (``forest.npz``), and an
  AOT-compiled fused scoring executable per row bucket (plus StableHLO
  text as the portable fallback).
- :mod:`loader`        — artifact dir -> servable in-cluster model
  (the REST import route), checksum-gated end to end.
- :mod:`compile_cache` — persistent fused-program compile cache keyed by
  (model checksum, bucket, backend fingerprint) under
  ``$H2O_TPU_COMPILE_CACHE_DIR``: a warm server restart compiles zero
  fused programs.
- :mod:`manifest` / :mod:`packer` / :mod:`aot` — the shared codecs.

The matching *standalone* runtime lives in :mod:`h2o3_genmodel.aot`: it
loads an artifact with numpy + jax alone (no h2o3_tpu import, restricted
unpickler for executable blobs) and scores CSV/ndarray input
bitwise-identically to in-process serving.
"""

from h2o3_tpu.artifact.export import export_model, supports_export
from h2o3_tpu.artifact.loader import describe, load_model
from h2o3_tpu.artifact.manifest import (FORMAT, FORMAT_VERSION,
                                        ArtifactError)
from h2o3_tpu.artifact.pipeline import export_pipeline

__all__ = ["export_model", "supports_export", "export_pipeline",
           "load_model", "describe", "ArtifactError", "FORMAT",
           "FORMAT_VERSION"]
