"""Artifact exporter: trained model -> self-contained AOT scoring dir.

The MOJO2-for-TPU (PAPER.md §2.9 deployment story): a directory holding

- ``manifest.json``    — versioned, schema-validated, checksums for all
- ``forest.npz``       — packed forest + BinSpec constants (no pickle)
- ``exec_b{N}.bin``    — AOT-compiled fused scoring executable per row
                         bucket (single-device lowering; loadable only on
                         a matching backend fingerprint)
- ``hlo_b{N}.mlir``    — the SAME lowering as StableHLO text: the portable
                         fallback any jax/XLA target can compile

that the thin runner (``h2o3_genmodel.aot``) scores from with ZERO
training-stack imports. Export is coordinator-local: lowering/compiling
runs no collectives, so it is safe without an oplog broadcast.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

import numpy as np

from h2o3_tpu.artifact import aot, manifest, packer
from h2o3_tpu.artifact.manifest import ArtifactError

FOREST_FILE = "forest.npz"


def supports_export(model) -> Optional[str]:
    """None when `model` can be exported (the fused-path forest family,
    or a standalone-scorable GLM — the first non-forest class); otherwise
    the reason string. Structural check only — export does not care
    whether the serving fast path is env-enabled right now."""
    from h2o3_tpu.artifact.glm import supports_glm_export
    from h2o3_tpu.models.glm import GLMModel
    from h2o3_tpu.models.tree.shared_tree import SharedTreeModel

    if isinstance(model, GLMModel):
        return supports_glm_export(model)
    if not isinstance(model, SharedTreeModel):
        return (f"{type(model).__name__} is not a SharedTree forest model "
                "or a GLM; AOT artifacts cover the fused scoring family "
                "(GBM/DRF/XGBoost) and GLM — use MOJO export for other "
                "algos")
    if model.forest is None or model.spec is None:
        return "model has no trained forest"
    if type(model)._predict_raw is not SharedTreeModel._predict_raw:
        return (f"{type(model).__name__} overrides _predict_raw (custom "
                "post-processing) and cannot ride the fused program")
    return None


def _post_spec(model) -> Dict[str, Any]:
    """Margin -> raw post-processing recipe the runner replays with the
    identical jnp ops as SharedTreeModel._margin_to_raw."""
    from h2o3_tpu.models.model import ModelCategory

    cat = model._output.model_category
    if cat == ModelCategory.Binomial:
        return {"kind": "binomial"}
    if cat == ModelCategory.Multinomial:
        return {"kind": "multinomial"}
    dist = getattr(model, "_distribution", None)
    name = getattr(dist, "name", "gaussian") if dist is not None else \
        "gaussian"
    linkinv = "exp" if name in ("poisson", "gamma", "tweedie") else "identity"
    return {"kind": "regression", "linkinv": linkinv}


def _default_threshold(model) -> float:
    tm = model._output.training_metrics
    aucd = getattr(tm, "auc_data", None)
    return float(aucd.max_f1_threshold) if aucd is not None else 0.5


def default_buckets() -> List[int]:
    from h2o3_tpu.scoring import _env_buckets

    return sorted(_env_buckets())


def _export_glm(model, out_dir: str, buckets: List[int]) -> Dict[str, Any]:
    """GLM artifact (model_type="glm"): packed coefficients/moments npz +
    an AOT-compiled fused expand+matmul+linkinv program per row bucket
    (+ StableHLO fallback) — the first non-forest class through this
    exporter. Forest-specific manifest keys carry inert defaults so ONE
    schema covers both classes."""
    from h2o3_tpu.artifact import aot, glm

    arrays = glm.pack_glm(model)
    meta = glm.glm_meta(model)
    checksum = glm.glm_checksum(model)
    entry = manifest.write_payload(out_dir, glm.GLM_FILE,
                                   packer.dump_npz(arrays))
    fingerprint = aot.backend_fingerprint(single_device=True)
    execs, hlos = [], []
    for b in buckets:
        _compiled, blob, text, kept = glm.compile_glm_bucket(b, model)
        if blob is not None:
            e = manifest.write_payload(out_dir, f"exec_b{b}.bin", blob)
            e.update(bucket=b, backend=fingerprint)
            execs.append(e)
        h = manifest.write_payload(out_dir, f"hlo_b{b}.mlir",
                                   text.encode("utf-8"))
        h.update(bucket=b, kept_args=kept)
        hlos.append(h)

    o = model._output
    cat = o.model_category
    post = {"kind": ("glm_binomial" if cat == "Binomial"
                     else "glm_multinomial" if cat == "Multinomial"
                     else "glm_regression")}
    names = list(model.dinfo.predictor_names)
    m = manifest.new_manifest(
        model_type="glm",
        algo=str(model.algo_name),
        model_key=str(model.key),
        model_category=str(cat),
        model_checksum=checksum,
        nclasses=int(meta["nclasses"]),
        per_class_trees=False,
        max_depth=0,
        init_f=0.0,
        n_trees=0,
        names=names,
        response_name=o.response_name,
        response_domain=list(o.response_domain or []) or None,
        domains={k: list(v) for k, v in model.dinfo.domains.items()},
        post=post,
        default_threshold=_default_threshold(model),
        glm=meta,
        files={"glm": entry},
        buckets=buckets,
        executables=execs,
        stablehlo=hlos,
    )
    manifest.write_manifest(out_dir, m)
    from h2o3_tpu.utils import timeline

    timeline.record("artifact", "export", model=str(model.key),
                    dir=out_dir, buckets=len(buckets),
                    executables=len(execs))
    return m


def export_model(model, out_dir: str,
                 buckets: Optional[List[int]] = None) -> Dict[str, Any]:
    """Write the artifact directory for `model`; returns the manifest."""
    why = supports_export(model)
    if why:
        raise ArtifactError(f"cannot export {model.key}: {why}")
    buckets = sorted({int(b) for b in (buckets or default_buckets())
                      if int(b) > 0})
    if not buckets:
        raise ArtifactError("at least one positive row bucket is required")
    os.makedirs(out_dir, exist_ok=True)
    from h2o3_tpu.models.glm import GLMModel

    if isinstance(model, GLMModel):
        return _export_glm(model, out_dir, buckets)

    forest, spec = model.forest, model.spec
    arrays = packer.pack_forest(forest, spec)
    meta = packer.forest_meta(forest, spec)
    checksum = packer.model_checksum(forest, spec)
    forest_entry = manifest.write_payload(out_dir, FOREST_FILE,
                                          packer.dump_npz(arrays))

    edges, is_cat, forest_args = packer.scoring_inputs(arrays)
    init = (arrays["init_class"] if "init_class" in arrays
            else np.float32(meta["init_f"]))
    fingerprint = aot.backend_fingerprint(single_device=True)
    execs, hlos = [], []
    for b in buckets:
        _compiled, blob, text, kept = aot.compile_bucket(
            b, meta, edges, is_cat, init, forest_args)
        if blob is not None:
            e = manifest.write_payload(out_dir, f"exec_b{b}.bin", blob)
            e.update(bucket=b, backend=fingerprint)
            execs.append(e)
        h = manifest.write_payload(out_dir, f"hlo_b{b}.mlir",
                                   text.encode("utf-8"))
        h.update(bucket=b, kept_args=kept)
        hlos.append(h)

    o = model._output
    m = manifest.new_manifest(
        algo=str(model.algo_name),
        model_key=str(model.key),
        model_category=str(o.model_category),
        model_checksum=checksum,
        nclasses=int(meta["nclasses"]),
        per_class_trees=bool(meta["per_class_trees"]),
        max_depth=int(meta["max_depth"]),
        init_f=float(meta["init_f"]),
        n_trees=int(meta["n_trees"]),
        names=list(o.names),
        response_name=o.response_name,
        response_domain=list(o.response_domain or []) or None,
        domains={k: list(v) for k, v in (o.domains or {}).items()},
        post=_post_spec(model),
        default_threshold=_default_threshold(model),
        distribution={
            "name": getattr(getattr(model, "_distribution", None), "name",
                            None),
            "tweedie_power": float(getattr(
                getattr(model, "_distribution", None), "power", 1.5)),
        },
        files={"forest": forest_entry},
        buckets=buckets,
        executables=execs,
        stablehlo=hlos,
    )
    manifest.write_manifest(out_dir, m)
    from h2o3_tpu.utils import timeline

    timeline.record("artifact", "export", model=str(model.key),
                    dir=out_dir, buckets=len(buckets),
                    executables=len(execs))
    return m
