"""Unsupervised algos: KMeans, PCA, SVD, GLRM, Aggregator.

Mirrors reference pyunits testdir_algos/kmeans + pca with sklearn/numpy as
the golden-math oracle."""

import numpy as np
import pytest

from h2o3_tpu.core.frame import Frame


def _blob_data(n=3000, seed=0):
    rng = np.random.default_rng(seed)
    centers = np.array([[0.0, 0.0], [8.0, 8.0], [-8.0, 8.0]])
    X = np.concatenate([rng.normal(c, 1.0, size=(n // 3, 2)) for c in centers])
    rng.shuffle(X)
    return Frame.from_numpy(X, names=["a", "b"]), centers


def test_kmeans_recovers_blobs(cl):
    from h2o3_tpu.models.kmeans import KMeans

    fr, true_centers = _blob_data()
    m = KMeans(k=3, standardize=False, max_iterations=20, seed=7).train(
        training_frame=fr)
    got = np.sort(np.round(m.centers_raw).astype(int), axis=0)
    want = np.sort(true_centers.astype(int), axis=0)
    assert np.allclose(got, want, atol=1)
    mm = m._output.training_metrics
    assert mm.tot_withinss < 0.05 * mm.totss
    assert abs(mm.totss - (mm.tot_withinss + mm.betweenss)) < 1e-2 * mm.totss


def test_kmeans_predict_and_sizes(cl):
    from h2o3_tpu.models.kmeans import KMeans

    fr, _ = _blob_data()
    m = KMeans(k=3, standardize=True, seed=7).train(training_frame=fr)
    pred = m.predict(fr)
    lab = pred.col("predict").to_numpy()
    assert set(np.unique(lab)) <= {0, 1, 2}
    sizes = np.bincount(lab, minlength=3)
    assert all(abs(s - 1000) < 100 for s in sizes)


def test_kmeans_estimate_k(cl):
    from h2o3_tpu.models.kmeans import KMeans

    fr, _ = _blob_data()
    m = KMeans(estimate_k=True, max_k=8, standardize=False, seed=3).train(
        training_frame=fr)
    assert m.k == 3


def test_kmeans_init_methods(cl):
    from h2o3_tpu.models.kmeans import KMeans

    fr, _ = _blob_data(n=600)
    for init in ("Random", "PlusPlus", "Furthest"):
        m = KMeans(k=3, init=init, standardize=False, seed=11).train(
            training_frame=fr)
        mm = m._output.training_metrics
        assert mm.tot_withinss < 0.1 * mm.totss, init


def test_pca_matches_numpy(cl):
    from h2o3_tpu.models.pca import PCA

    rng = np.random.default_rng(5)
    X = rng.normal(size=(2000, 6)) @ rng.normal(size=(6, 6))
    fr = Frame.from_numpy(X, names=[f"c{i}" for i in range(6)])
    m = PCA(k=3, transform="DEMEAN", pca_method="GramSVD").train(training_frame=fr)

    Xc = X - X.mean(0)
    _, s, Vt = np.linalg.svd(Xc, full_matrices=False)
    want_sd = s[:3] / np.sqrt(len(X) - 1)
    # eigenvectors up to sign
    for j in range(3):
        v_ref = Vt[j] * np.sign(Vt[j][np.argmax(np.abs(Vt[j]))])
        assert np.allclose(np.abs(m.eigenvectors[:, j]), np.abs(v_ref), atol=1e-3)
    assert np.allclose(m.std_deviation[:3] * np.sqrt(len(X)/(len(X)-1)), want_sd * np.sqrt(len(X)/(len(X)-1)), rtol=2e-3)
    scores = m.predict(fr)
    sc = scores.to_numpy()
    # projected variance matches eigenvalues
    assert np.allclose(sc.var(0, ddof=1), want_sd ** 2, rtol=5e-3)


def test_pca_randomized_close_to_exact(cl):
    from h2o3_tpu.models.pca import PCA

    rng = np.random.default_rng(6)
    X = rng.normal(size=(1500, 8))
    X[:, 0] *= 10
    fr = Frame.from_numpy(X, names=[f"c{i}" for i in range(8)])
    exact = PCA(k=2, transform="DEMEAN", pca_method="GramSVD").train(training_frame=fr)
    rand = PCA(k=2, transform="DEMEAN", pca_method="Randomized", seed=1).train(training_frame=fr)
    assert np.allclose(exact.std_deviation, rand.std_deviation, rtol=1e-3)


def test_svd_reconstruction(cl):
    from h2o3_tpu.models.svd import SVD

    rng = np.random.default_rng(7)
    X = rng.normal(size=(500, 5))
    fr = Frame.from_numpy(X, names=[f"c{i}" for i in range(5)])
    m = SVD(nv=5, transform="NONE", svd_method="GramSVD").train(training_frame=fr)
    _, s, _ = np.linalg.svd(X, full_matrices=False)
    assert np.allclose(np.sort(m.d)[::-1], s, rtol=1e-3)
    u = m.predict(fr).to_numpy()
    # X ≈ U D Vt
    recon = u @ np.diag(m.d) @ m.v.T
    assert np.allclose(recon, X, atol=1e-2)


def test_aggregator_compresses(cl):
    from h2o3_tpu.models.aggregator import Aggregator

    fr, _ = _blob_data(n=3000)
    m = Aggregator(target_num_exemplars=100, rel_tol_num_exemplars=0.5).train(
        training_frame=fr)
    agg = m.aggregated_frame()
    assert agg is not None
    assert 20 <= agg.nrows <= 200
    assert abs(agg.col("counts").to_numpy().sum() - 3000) < 1
    # exemplars cover all three blobs
    ex = np.column_stack([agg.col("a").to_numpy(), agg.col("b").to_numpy()])
    for c in ([0, 0], [8, 8], [-8, 8]):
        assert (np.linalg.norm(ex - np.asarray(c), axis=1) < 3).any()


def test_extended_isolation_forest(cl):
    from h2o3_tpu.models.extended_isofor import ExtendedIsolationForest

    rng = np.random.default_rng(9)
    X = rng.normal(size=(2000, 4))
    X[:40] += 8.0                          # planted anomalies
    fr = Frame.from_numpy(X, names=list("abcd"))
    m = ExtendedIsolationForest(ntrees=60, sample_size=128, extension_level=3,
                                seed=1).train(training_frame=fr)
    pred = m.predict(fr)
    score = pred.col("predict").to_numpy()
    assert score[:40].mean() > score[40:].mean() + 0.1
