"""CoxPH, GAM, RuleFit tests."""

import numpy as np
import pytest

from h2o3_tpu.core.frame import Column, Frame, T_CAT


def test_coxph_recovers_beta(cl):
    from h2o3_tpu.models.coxph import CoxPH

    rng = np.random.default_rng(0)
    n = 3000
    X = rng.normal(size=(n, 2))
    beta_true = np.array([0.8, -0.5])
    # exponential survival times with rate exp(x beta); random censoring
    t_event = rng.exponential(1.0 / np.exp(X @ beta_true))
    t_cens = rng.exponential(2.0, n)
    time = np.minimum(t_event, t_cens)
    event = (t_event <= t_cens).astype(float)
    fr = Frame.from_numpy(np.column_stack([X, time, event]),
                          names=["x1", "x2", "time", "event"])
    m = CoxPH(stop_column="time", ties="efron").train(y="event", training_frame=fr)
    assert abs(m.coefficients["x1"] - 0.8) < 0.1
    assert abs(m.coefficients["x2"] + 0.5) < 0.1
    assert m.concordance > 0.6
    assert m.loglik > m.loglik_null
    # breslow close to efron with few ties
    mb = CoxPH(stop_column="time", ties="breslow").train(y="event", training_frame=fr)
    assert abs(mb.coefficients["x1"] - m.coefficients["x1"]) < 0.05


def test_gam_fits_nonlinear(cl):
    from h2o3_tpu.models.gam import GAM
    from h2o3_tpu.models.glm import GLM

    rng = np.random.default_rng(1)
    n = 3000
    x = rng.uniform(-3, 3, n)
    z = rng.normal(size=n)
    y = np.sin(x) * 2 + 0.5 * z + 0.1 * rng.normal(size=n)
    fr = Frame.from_numpy(np.column_stack([x, z, y]), names=["x", "z", "y"])
    gam = GAM(gam_columns=["x"], num_knots=8, family="gaussian").train(
        y="y", training_frame=fr)
    glm = GLM(family="gaussian").train(y="y", training_frame=fr)
    # spline captures the sine; linear GLM leaves the curvature on the table
    assert gam._output.training_metrics.r2 > 0.9
    assert gam._output.training_metrics.r2 > glm._output.training_metrics.r2 + 0.15
    pred = gam.predict(fr)
    assert pred.nrows == n


def test_rulefit_binomial(cl):
    from h2o3_tpu.models.rulefit import RuleFit

    rng = np.random.default_rng(2)
    n = 2000
    X = rng.uniform(-1, 1, size=(n, 3))
    # rule-structured truth: x0>0 & x1>0 → mostly YES
    p = np.where((X[:, 0] > 0) & (X[:, 1] > 0), 0.9, 0.15)
    y = np.where(rng.random(n) < p, "Y", "N")
    fr = Frame.from_numpy(X, names=["x0", "x1", "x2"])
    fr.add("y", Column.from_numpy(y, ctype=T_CAT))
    m = RuleFit(max_rule_length=2, min_rule_length=2,
                rule_generation_ntrees=20, seed=3).train(y="y", training_frame=fr)
    assert m._output.training_metrics.auc > 0.8
    top = m.rule_importance()[:10]
    assert any("x0" in r["rule"] or "x1" in r["rule"] for r in top)
    pred = m.predict(fr)
    assert "predict" in pred.names


def test_psvm_nonlinear_boundary(cl):
    from h2o3_tpu.models.psvm import PSVM

    rng = np.random.default_rng(4)
    n = 1500
    X = rng.normal(size=(n, 2))
    r2 = (X ** 2).sum(axis=1)
    y = np.where(r2 < 1.2, "in", "out")      # circular boundary
    fr = Frame.from_numpy(X, names=["x1", "x2"])
    fr.add("y", Column.from_numpy(y, ctype=T_CAT))
    m = PSVM(hyper_param=5.0, seed=1).train(y="y", training_frame=fr)
    assert m._output.training_metrics.auc > 0.95
    pred = m.predict(fr)
    acc = (pred.col("predict").values() == y).mean()
    assert acc > 0.9
    assert m.svs_count > 0


def test_coxph_left_truncation(cl):
    """start_column shrinks early risk sets; with entry times the estimate
    stays consistent while ignoring them would bias it."""
    from h2o3_tpu.models.coxph import CoxPH

    rng = np.random.default_rng(5)
    n = 8000
    x = rng.normal(size=n)
    t_event = rng.exponential(1.0 / np.exp(0.7 * x))
    entry = rng.exponential(0.5, n)                  # independent study entry
    obs = t_event > entry                            # truncation selection
    x, t_event, entry = x[obs], t_event[obs], entry[obs]
    fr = Frame.from_numpy(
        np.column_stack([x, entry, t_event, np.ones(obs.sum())]),
        names=["x", "entry", "time", "event"])
    m = CoxPH(stop_column="time", start_column="entry").train(
        y="event", training_frame=fr)
    assert abs(m.coefficients["x"] - 0.7) < 0.1
    # ignoring entry on truncated data is biased
    m2 = CoxPH(stop_column="time").train(
        y="event", training_frame=fr.subframe(["x", "time", "event"]))
    assert abs(m2.coefficients["x"] - 0.7) > abs(m.coefficients["x"] - 0.7)


def test_drf_early_stop_keeps_scale(cl):
    """Truncated forests must still average, not shrink (review fix)."""
    from h2o3_tpu.models.tree.drf import DRF

    rng = np.random.default_rng(6)
    X = rng.normal(size=(2000, 3))
    y = 5.0 + X[:, 0]
    fr = Frame.from_numpy(np.column_stack([X, y]), names=["a", "b", "c", "y"])
    m = DRF(ntrees=100, max_depth=4, stopping_rounds=2, score_tree_interval=2,
            stopping_tolerance=0.2, seed=7).train(y="y", training_frame=fr)
    pred = m.predict(fr).col("predict").to_numpy()
    assert abs(pred.mean() - 5.0) < 0.3


def test_gam_thinplate_and_knots(cl):
    """bs=1 thin-plate basis + get_knot_locations (hex/gam bs types)."""
    import numpy as np

    from h2o3_tpu.core.frame import Column, Frame
    from h2o3_tpu.models.gam import GAM

    rng = np.random.default_rng(11)
    n = 800
    x = rng.uniform(-3, 3, n)
    y = np.sin(x) + rng.normal(0, 0.15, n)
    fr = Frame()
    fr.add("x", Column.from_numpy(x))
    fr.add("y", Column.from_numpy(y))
    m = GAM(gam_columns=["x"], num_knots=8, bs=1, scale=0.001).train(
        y="y", training_frame=fr)
    pred = m.predict(fr).col("predict").to_numpy()
    assert np.mean((pred - np.sin(x)) ** 2) < 0.05   # captures the nonlinearity
    ks = m.get_knot_locations("x")
    assert len(ks) == 8 and ks == sorted(ks)
    assert m.bs_types["x"] == 1
    import pytest

    with pytest.raises(ValueError, match="unsupported"):
        GAM(gam_columns=["x"], bs=7).train(y="y", training_frame=fr)


def test_coxph_stratified(cl):
    """stratify_by (CoxPH.java stratification): per-stratum risk sets and
    baseline hazards; beta close to the data-generating coefficients even
    when strata have very different baselines."""
    import numpy as np

    from h2o3_tpu.core.frame import Column, Frame, T_CAT
    from h2o3_tpu.models.coxph import CoxPH

    rng = np.random.default_rng(11)
    n = 800
    x1 = rng.normal(size=n)
    site = np.asarray(["s1", "s2"])[rng.integers(0, 2, n)]
    base = np.where(site == "s1", 1.0, 6.0)   # wildly different baselines
    t = rng.exponential(1.0 / (base * np.exp(0.9 * x1)))
    event = np.where(rng.random(n) < 0.85, "1", "0")   # some censoring
    fr = Frame.from_numpy(np.stack([x1, t], 1), names=["x1", "time"])
    fr.add("site", Column.from_numpy(site, ctype=T_CAT))
    fr.add("event", Column.from_numpy(event, ctype=T_CAT))
    m = CoxPH(stop_column="time", stratify_by=["site"]).train(
        y="event", training_frame=fr)
    b = m.coefficients["x1"]
    assert abs(b - 0.9) < 0.15, b
    # per-stratum cumulative hazard: (stratum, time, cumhaz), both strata
    bh = m.baseline_hazard
    assert bh.shape[1] == 3 and len(np.unique(bh[:, 0])) == 2
    # hazard resets per stratum (strictly increasing within each)
    for s in np.unique(bh[:, 0]):
        ch = bh[bh[:, 0] == s, 2]
        assert np.all(np.diff(ch) > 0)
    assert np.isfinite(m.concordance) and m.concordance > 0.6
    # the unstratified fit on the same data is badly biased: stratification
    # must beat it by a wide margin
    m0 = CoxPH(stop_column="time", ignored_columns=["site"]).train(
        y="event", training_frame=fr)
    assert abs(m0.coefficients["x1"] - 0.9) > abs(b - 0.9)


def test_coxph_stratify_requires_categorical(cl):
    import numpy as np
    import pytest

    from h2o3_tpu.core.frame import Column, Frame, T_CAT
    from h2o3_tpu.models.coxph import CoxPH

    rng = np.random.default_rng(1)
    fr = Frame.from_numpy(rng.normal(size=(50, 2)), names=["x1", "time"])
    fr.add("event", Column.from_numpy(np.asarray(["1"] * 50), ctype=T_CAT))
    with pytest.raises(ValueError):
        CoxPH(stop_column="time", stratify_by=["x1"]).train(
            y="event", training_frame=fr)


def test_gam_spline_families(cl):
    """bs=2 monotone I-splines and bs=3 M-splines (hex/gam NBSplines):
    the monotone basis must produce a nondecreasing fitted curve on
    monotone data; M-splines fit as well as cr on smooth data."""
    import numpy as np

    from h2o3_tpu.core.frame import Column, Frame
    from h2o3_tpu.models.gam import GAM

    rng = np.random.default_rng(21)
    n = 600
    x = rng.uniform(-3, 3, n)
    y = np.log1p(np.exp(2 * x)) + rng.normal(0, 0.15, n)   # monotone + noise
    fr = Frame.from_numpy(np.stack([x, y], 1), names=["x", "y"])
    m_iso = GAM(gam_columns=["x"], bs=[2], num_knots=[8], scale=[0.001],
                family="gaussian").train(y="y", training_frame=fr)
    grid = np.linspace(-2.9, 2.9, 80)
    gfr = Frame.from_numpy(grid.reshape(-1, 1), names=["x"])
    fit = np.asarray(m_iso.predict(gfr).col("predict").to_numpy(), float)
    viol = np.minimum(np.diff(fit), 0.0)
    assert np.abs(viol).max() < 1e-3, "I-spline fit must be monotone"
    err = float(np.mean((fit - np.log1p(np.exp(2 * grid))) ** 2))
    assert err < 0.1, err

    m_ms = GAM(gam_columns=["x"], bs=[3], num_knots=[8], scale=[0.001],
               family="gaussian").train(y="y", training_frame=fr)
    fit_ms = np.asarray(m_ms.predict(gfr).col("predict").to_numpy(), float)
    err_ms = float(np.mean((fit_ms - np.log1p(np.exp(2 * grid))) ** 2))
    assert err_ms < 0.1, err_ms


def test_psvm_sv_surface(cl):
    """PSVMModelOutput parity (psvm/PSVM.java:139): svs/bsv counts, rho,
    and per-row alpha coefficients with the KKT sign structure."""
    import numpy as np

    from h2o3_tpu.core.dkv import DKV
    from h2o3_tpu.core.frame import Column, Frame, T_CAT
    from h2o3_tpu.models.psvm import PSVM

    rng = np.random.default_rng(4)
    n = 800
    X = rng.normal(size=(n, 2))
    y = np.where((X ** 2).sum(axis=1) < 1.2, "in", "out")
    fr = Frame.from_numpy(X, names=["x1", "x2"])
    fr.add("y", Column.from_numpy(y, ctype=T_CAT))
    m = PSVM(hyper_param=5.0, seed=1).train(y="y", training_frame=fr)
    assert 0 < m.svs_count < n
    assert 0 <= m.bsv_count <= m.svs_count
    assert np.isfinite(m.rho)
    alpha = np.asarray(DKV.get(m.alpha_key).col("alpha").to_numpy())
    assert alpha.shape[0] == n
    nz = alpha != 0
    assert abs(int(nz.sum()) - m.svs_count) <= 2
    d = m.to_dict()
    assert {"svs_count", "bsv_count", "rho", "alpha_key"} <= d.keys()
