"""GLM tests: IRLS vs sklearn, families, elastic net, CV, metrics.

Mirrors reference pyunits testdir_algos/glm (e.g. pyunit_glm_binomial.py)
with sklearn as the golden-math oracle instead of R."""

import numpy as np
import pytest

from h2o3_tpu.core.frame import Frame
from h2o3_tpu.models.glm import GLM


def _reg_data(n=4000, p=5, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, p))
    beta = np.arange(1, p + 1, dtype=float)
    y = X @ beta + 2.5 + rng.normal(0, 0.1, n)
    fr = Frame.from_numpy(np.column_stack([X, y]),
                          names=[f"x{i}" for i in range(p)] + ["y"])
    return fr, beta


def _bin_data(n=4000, p=4, seed=1):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, p))
    beta = np.array([1.0, -2.0, 0.5, 0.0])
    logits = X @ beta - 0.5
    y = (rng.random(n) < 1 / (1 + np.exp(-logits))).astype(float)
    fr = Frame.from_numpy(np.column_stack([X, y]),
                          names=[f"x{i}" for i in range(p)] + ["y"])
    return fr, beta


def test_gaussian_matches_ols(cl):
    fr, beta = _reg_data()
    m = GLM(family="gaussian", lambda_=0.0, standardize=False).train(
        y="y", training_frame=fr)
    coef = m.coef()
    for i, b in enumerate(beta):
        assert abs(coef[f"x{i}"] - b) < 0.01
    assert abs(coef["Intercept"] - 2.5) < 0.01
    assert m._output.training_metrics.rmse < 0.15
    assert m._output.training_metrics.r2 > 0.99


def test_gaussian_standardized_same_predictions(cl):
    fr, _ = _reg_data()
    m1 = GLM(family="gaussian", lambda_=0.0, standardize=True).train(y="y", training_frame=fr)
    m2 = GLM(family="gaussian", lambda_=0.0, standardize=False).train(y="y", training_frame=fr)
    p1 = m1.predict(fr).col("predict").to_numpy()
    p2 = m2.predict(fr).col("predict").to_numpy()
    np.testing.assert_allclose(p1, p2, atol=1e-2)


def test_binomial_vs_sklearn(cl):
    from sklearn.linear_model import LogisticRegression

    fr, _ = _bin_data()
    m = GLM(family="binomial", lambda_=0.0, standardize=False).train(
        y="y", training_frame=fr)
    X = fr.subframe(["x0", "x1", "x2", "x3"]).to_numpy()
    yv = fr.col("y").to_numpy()
    sk = LogisticRegression(C=1e6, max_iter=1000).fit(X, yv)
    coef = m.coef()
    for i in range(4):
        assert abs(coef[f"x{i}"] - sk.coef_[0][i]) < 0.05, (coef, sk.coef_)
    mm = m._output.training_metrics
    assert mm.auc > 0.85
    assert mm.logloss < 0.5


def test_binomial_enum_response(cl, airlines_csv):
    import h2o3_tpu

    fr = h2o3_tpu.import_file(airlines_csv)
    m = GLM(family="binomial").train(y="IsDepDelayed", training_frame=fr)
    mm = m._output.training_metrics
    assert mm.auc > 0.60
    pred = m.predict(fr)
    assert pred.col("predict").domain == ["NO", "YES"]
    assert {"NO", "YES"} <= set(pred.names)


def test_elastic_net_shrinks(cl):
    fr, beta = _bin_data()
    dense = GLM(family="binomial", lambda_=0.0).train(y="y", training_frame=fr)
    sparse = GLM(family="binomial", alpha=1.0, lambda_=0.05).train(y="y", training_frame=fr)
    # the truly-zero coefficient x3 must be driven to (near) zero by L1,
    # while the unregularized fit keeps real signal coefficients nonzero
    assert abs(sparse.coef_norm()["x3"]) < 1e-3
    assert abs(dense.coef_norm()["x1"]) > 0.1


def test_poisson(cl):
    rng = np.random.default_rng(3)
    n = 3000
    x = rng.normal(size=n)
    mu = np.exp(0.3 * x + 1.0)
    y = rng.poisson(mu).astype(float)
    fr = Frame.from_numpy(np.column_stack([x, y]), names=["x", "y"])
    m = GLM(family="poisson", lambda_=0.0, standardize=False).train(y="y", training_frame=fr)
    c = m.coef()
    assert abs(c["x"] - 0.3) < 0.05
    assert abs(c["Intercept"] - 1.0) < 0.05


def test_multinomial(cl):
    rng = np.random.default_rng(4)
    n = 3000
    X = rng.normal(size=(n, 2))
    logits = np.stack([X[:, 0], X[:, 1], -X[:, 0] - X[:, 1]], axis=1)
    y = np.array([rng.choice(3, p=np.exp(l) / np.exp(l).sum()) for l in logits])
    import pandas as pd

    df = pd.DataFrame({"x0": X[:, 0], "x1": X[:, 1],
                       "y": pd.Categorical.from_codes(y, ["a", "b", "c"])})
    fr = Frame.from_pandas(df)
    m = GLM(family="multinomial", lambda_=0.0).train(y="y", training_frame=fr)
    mm = m._output.training_metrics
    assert mm.logloss < 1.0
    assert mm.cm.table.shape == (3, 3)
    pred = m.predict(fr)
    assert set(pred.names) == {"predict", "a", "b", "c"}
    acc = (pred.col("predict").to_numpy() == y).mean()
    assert acc > 0.55


def test_cv_metrics(cl):
    fr, _ = _bin_data(n=2000)
    m = GLM(family="binomial", nfolds=3, seed=42).train(y="y", training_frame=fr)
    assert m._output.cross_validation_metrics is not None
    assert m._output.cross_validation_metrics.auc > 0.8
    assert len(m._output.cv_fold_metrics) == 3


def test_p_values(cl):
    fr, beta = _bin_data()
    m = GLM(family="binomial", lambda_=0.0, compute_p_values=True,
            standardize=False).train(y="y", training_frame=fr)
    assert m.p_values is not None
    # x3 has true coefficient 0 -> insignificant; x1 strong -> significant
    names = m.dinfo.coef_names()
    pv = {n: m.p_values[i] for i, n in enumerate(names)}
    assert pv["x1"] < 0.001
    assert pv["x3"] > 0.01


def test_weights_column(cl):
    fr, _ = _reg_data(n=1000)
    w = np.ones(1000)
    w[:500] = 0.0  # first half ignored
    fr.add("w", __import__("h2o3_tpu").core.frame.Column.from_numpy(w))
    m = GLM(family="gaussian", lambda_=0.0, weights_column="w").train(y="y", training_frame=fr)
    assert m._output.training_metrics.nobs == 500


class TestOrdinalGLM:
    """family='ordinal': proportional-odds cumulative logit
    (hex/glm GLMParameters.Family.ordinal)."""

    def test_recovers_ordered_structure(self, cl):
        import numpy as np

        from h2o3_tpu.core.frame import Column, Frame
        from h2o3_tpu.models.glm import GLM

        rng = np.random.default_rng(3)
        n = 1500
        x = rng.standard_normal(n)
        eta = 2.0 * x
        u = rng.logistic(0, 1, n)
        lat = eta + u
        yv = np.digitize(lat, [-1.0, 1.0])          # 3 ordered levels
        fr = Frame()
        fr.add("x", Column.from_numpy(x))
        # the DOMAIN (code) order defines the ordinal order, exactly as in
        # the reference — labels must sort in the true level order
        fr.add("y", Column.from_numpy(np.array(["l0_lo", "l1_mid", "l2_hi"])[yv],
                                      ctype="enum"))
        m = GLM(family="ordinal", seed=1).train(y="y", training_frame=fr)
        raw = m._predict_raw(m.adapt_test(fr))
        probs = np.asarray(raw["probs"])[:n]
        # rows sum to one, all finite
        np.testing.assert_allclose(probs.sum(1), 1.0, atol=1e-5)
        dom = m._output.response_domain
        hi = dom.index("l2_hi")
        lo = dom.index("l0_lo")
        top = probs[x > 1.5]
        bot = probs[x < -1.5]
        assert top[:, hi].mean() > 0.6 > top[:, lo].mean()
        assert bot[:, lo].mean() > 0.6 > bot[:, hi].mean()
        # proportional odds: single beta + K-1 thresholds
        assert m.beta.shape[0] == 1 + 2
        # metrics flow through the multinomial machinery
        assert np.isfinite(float(m._output.training_metrics.logloss))

    def test_requires_3_levels(self, cl):
        import numpy as np

        from h2o3_tpu.core.frame import Column, Frame
        from h2o3_tpu.models.glm import GLM

        fr = Frame()
        fr.add("x", Column.from_numpy(np.arange(50, dtype=np.float64)))
        fr.add("y", Column.from_numpy(np.array(["a", "b"] * 25), ctype="enum"))
        import pytest

        with pytest.raises(ValueError, match="3 ordered levels"):
            GLM(family="ordinal").train(y="y", training_frame=fr)


class TestGLMInteractions:
    """interactions param -> expanded pairwise columns (hex/DataInfo
    interaction vec semantics), consistent between train and score."""

    def test_num_num_interaction_recovers_product_term(self, cl):
        import numpy as np

        from h2o3_tpu.core.frame import Column, Frame
        from h2o3_tpu.models.glm import GLM

        rng = np.random.default_rng(2)
        n = 1500
        a, b = rng.standard_normal((2, n))
        y = 1.0 * a - 0.5 * b + 2.0 * a * b + rng.normal(0, 0.05, n)
        fr = Frame()
        fr.add("a", Column.from_numpy(a))
        fr.add("b", Column.from_numpy(b))
        fr.add("y", Column.from_numpy(y))
        plain = GLM(family="gaussian", lambda_=0.0).train(y="y", training_frame=fr)
        inter = GLM(family="gaussian", lambda_=0.0,
                    interactions=["a", "b"]).train(y="y", training_frame=fr)
        coefs = inter.coef()
        assert abs(coefs["a:b"] - 2.0) < 0.05
        # scoring a RAW frame re-expands identically
        pred = inter.predict(fr).col("predict").to_numpy()
        assert np.mean((pred - y) ** 2) < 0.01
        assert float(inter._output.training_metrics.mse) < \
            float(plain._output.training_metrics.mse) / 10

    def test_enum_num_interaction(self, cl):
        import numpy as np

        from h2o3_tpu.core.frame import Column, Frame
        from h2o3_tpu.models.glm import GLM

        rng = np.random.default_rng(3)
        n = 1200
        g = np.array(["u", "v"], object)[rng.integers(0, 2, n)]
        x = rng.standard_normal(n)
        y = np.where(g == "u", 2.0 * x, -1.0 * x) + rng.normal(0, 0.05, n)
        fr = Frame()
        fr.add("g", Column.from_numpy(g, ctype="enum"))
        fr.add("x", Column.from_numpy(x))
        fr.add("y", Column.from_numpy(y))
        m = GLM(family="gaussian", lambda_=0.0,
                interactions=["g", "x"]).train(y="y", training_frame=fr)
        pred = m.predict(fr).col("predict").to_numpy()
        assert np.mean((pred - y) ** 2) < 0.01   # per-level slopes captured


def test_interaction_missing_test_level_scores_zero(cl):
    """A training enum level absent from the test frame yields all-zero
    interaction indicators, not NA backfill."""
    import numpy as np

    from h2o3_tpu.core.frame import Column, Frame
    from h2o3_tpu.models.glm import GLM

    rng = np.random.default_rng(5)
    n = 900
    g = np.array(["u", "v"], object)[rng.integers(0, 2, n)]
    x = rng.standard_normal(n)
    y = np.where(g == "u", 2.0 * x, -1.0 * x) + rng.normal(0, 0.05, n)
    fr = Frame()
    fr.add("g", Column.from_numpy(g, ctype="enum"))
    fr.add("x", Column.from_numpy(x))
    fr.add("y", Column.from_numpy(y))
    m = GLM(family="gaussian", lambda_=0.0,
            interactions=["g", "x"]).train(y="y", training_frame=fr)
    # test frame with ONLY level u
    fu = Frame()
    xu = np.linspace(-2, 2, 50)
    fu.add("g", Column.from_numpy(np.array(["u"] * 50, object), ctype="enum"))
    fu.add("x", Column.from_numpy(xu))
    pred = m.predict(fu).col("predict").to_numpy()
    assert np.all(np.isfinite(pred))
    np.testing.assert_allclose(pred, 2.0 * xu, atol=0.1)   # u-slope only
