"""Test harness: simulate an 8-device TPU pod on CPU.

Mirrors the reference's multi-JVM localhost clouds (multiNodeUtils.sh,
water.TestUtil.stall_till_cloudsize) — here the 'cloud' is a virtual
8-device mesh forced onto the host CPU, so every distributed code path
(shard_map, psum, sharded device_put) executes with real partitioning."""

import os

# jax may already be imported by the environment's sitecustomize, so set the
# flag env AND update jax.config (effective until backend init, which is lazy).
# H2O_TPU_TEST_REAL=1 keeps the real accelerator backend instead — the
# opt-in for the real-silicon test tiers (test_pallas_hist
# TestRealTpuLowering), which are unreachable under the forced-CPU mesh.
_REAL = bool(os.environ.get("H2O_TPU_TEST_REAL"))
if not _REAL:
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
    os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

if not _REAL:
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cl():
    import h2o3_tpu

    return h2o3_tpu.init()


@pytest.fixture()
def leak_check():
    """DKV key-leak guard (reference: water/runner/CheckKeysTask.java —
    tests fail if they leak keys)."""
    from h2o3_tpu.core.dkv import DKV

    before = set(DKV.keys())
    yield
    after = set(DKV.keys())
    leaked = after - before
    # frames/models created inside the test body are expected; this fixture
    # is opt-in for tests that promise cleanliness
    assert not leaked, f"leaked DKV keys: {sorted(leaked)[:10]}"


@pytest.fixture(scope="session")
def airlines_csv(tmp_path_factory):
    """Small airlines-like synthetic CSV for parse/train tests."""
    rng = np.random.default_rng(42)
    n = 2000
    p = tmp_path_factory.mktemp("data") / "airlines.csv"
    dows = np.array(["Mon", "Tue", "Wed", "Thu", "Fri", "Sat", "Sun"])
    carriers = np.array(["AA", "UA", "DL", "WN"])
    dist = rng.integers(50, 3000, n)
    dep = rng.integers(0, 2400, n)
    delay = (dist * 0.01 + (dep > 1800) * 30 + rng.normal(0, 20, n)) > 25
    with open(p, "w") as f:
        f.write("DayOfWeek,Carrier,Distance,DepTime,IsDepDelayed\n")
        for i in range(n):
            f.write(f"{dows[i % 7]},{carriers[i % 4]},{dist[i]},{dep[i]},{'YES' if delay[i] else 'NO'}\n")
    return str(p)


# -- smoke tier (VERDICT r4 weak #8): `pytest -m smoke` runs a <2-minute
# verification subset so every change gets a cheap end-to-end gate before
# the full 45-file suite. Curated fast modules; everything they cover
# (frame core, parse, GLM, trees-lite via rapids, REST basics, reference
# MOJO parity) runs in well under the driver's watchdog windows.
_SMOKE_MODULES = {"test_core", "test_glm", "test_rapids", "test_java_mojo",
                  "test_h2or_client", "test_narrow_dtypes"}


# tier-1 budget ordering: the ROADMAP tier-1 run is time-boxed (870 s), so
# cheap host-dominated modules run FIRST and the compile-heavy device
# trainers (tree/DL/AutoML fits, subprocess clouds) run LAST — a truncated
# run banks every fast test's result instead of burning the budget on the
# first few expensive modules in alphabetical order. Stable sort: original
# file order is kept within each cost class.
_HEAVY_MODULES = [
    # many passing tests per second of training — earliest of the tail
    # (test_sharded_frame/test_serving_qps train small GBMs, so they ride
    # the head of the heavy tail: the pure-host cheap modules still bank
    # their dots first)
    "test_sharded_frame", "test_serving_qps",
    "test_job_resume", "test_trees", "test_checkpoint", "test_genmodel",
    "test_artifact", "test_mojo",
    "test_mojo_families", "test_explain", "test_ensemble",
    "test_survival_gam_rulefit", "test_grid", "test_search_resume",
    # long single fits / many submodels
    "test_automl", "test_automl_bindings", "test_deep_trees",
    "test_deeplearning", "test_pallas_hist",
    # 2-process localhost clouds: minutes per test, run dead last
    "test_multiprocess",
]


# individual tests whose cost class differs from their module's: the
# consistency suite is millisecond text scans EXCEPT its behavioral
# data-plane guard, which trains a tiny GBM — that one item rides with
# the sharded suite at the head of the heavy tail instead of dragging
# compile work into the cheap-first phase.
# (test_obs deliberately stays OUT of _HEAVY_MODULES: the observability
# suite trains nothing — its one forest-backed assertion lives in
# test_sharded_frame's REST test — so it banks dots in the cheap phase.)
_HEAVY_ITEMS = {
    "test_fused_paths_never_gather_columns_to_coordinator":
        "test_sharded_frame",
    "test_multi_entry_flush_is_one_dispatch_per_bucket":
        "test_sharded_frame",
    # ISSUE-15: the two ingest guards that train a tiny GBM ride the
    # heavy tail; the rest of test_ingest_chunked (pure host parses)
    # stays in the cheap phase
    "test_ingest_never_stages_whole_columns_on_coordinator":
        "test_sharded_frame",
    "test_streaming_append_bitwise_vs_cold_parse":
        "test_sharded_frame",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.module.__name__ in _SMOKE_MODULES:
            item.add_marker(pytest.mark.smoke)
    rank = {m: i for i, m in enumerate(_HEAVY_MODULES, start=1)}

    def key(item):
        mod = _HEAVY_ITEMS.get(item.name, item.module.__name__)
        return rank.get(mod, 0)

    items.sort(key=key)
