"""Serving at production QPS (ISSUE 13).

- **Coalesced flush**: a multi-entry micro-batch flush on the sharded
  path costs ONE fused dispatch per row bucket (device-side concat of
  per-entry shard-packed matrices; the recorded PR-7 per-entry-dispatch
  trade-off is gone), bitwise-identical to per-entry scoring, with
  ``gathered_rows`` still 0.
- **Fused explainability**: leaf assignment and staged probabilities run
  through the ScoringSession's fused bucketed bin+leaf programs and stay
  bitwise-identical to the eager ``bin_columns + leaf_index`` path; the
  ``/4`` async route rides the fused coalescing path and matches the
  eager predict bitwise over real HTTP (contributions likewise).
- **SLO-adaptive admission**: ``H2O_TPU_SCORE_SLO_MS`` derives per-model
  inflight limits from the observed latency ring (AIMD), sheds with 429 +
  drain-rate-derived Retry-After, and the saturation soak (slow marker)
  holds p99 within the SLO with ZERO fused recompiles
  (compile-ledger-asserted).
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from h2o3_tpu.core.frame import Column, Frame

pytestmark = pytest.mark.serving


def _train_frame(n=1500, seed=0):
    rng = np.random.default_rng(seed)
    fr = Frame()
    x1 = rng.standard_normal(n)
    x2 = rng.standard_normal(n)
    x1[::11] = np.nan
    g = np.array(["a", "b", "c"])[rng.integers(0, 3, n)]
    fr.add("x1", Column.from_numpy(x1))
    fr.add("x2", Column.from_numpy(x2))
    fr.add("g", Column.from_numpy(g, ctype="enum"))
    logit = np.where(np.isnan(x1), 0.0, 1.2 * x1) - x2 + (g == "a") * 0.5
    y = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "Y", "N")
    fr.add("y", Column.from_numpy(y, ctype="enum"))
    return fr


def _score_frame(n, seed, with_nas=True):
    rng = np.random.default_rng(seed)
    fr = Frame()
    x1 = rng.standard_normal(n)
    if with_nas:
        x1[::7] = np.nan
    fr.add("x1", Column.from_numpy(x1))
    fr.add("x2", Column.from_numpy(rng.standard_normal(n)))
    fr.add("g", Column.from_numpy(
        np.array(["a", "b", "c"])[rng.integers(0, 3, n)], ctype="enum"))
    return fr


@pytest.fixture(scope="module")
def gbm(cl):
    from h2o3_tpu.models.tree.gbm import GBM

    return GBM(ntrees=6, max_depth=3, seed=1).train(
        y="y", training_frame=_train_frame())


def _assert_frames_bitwise(a, b, n):
    assert a.names == b.names
    for name in a.names:
        av = np.asarray(a.col(name).data)[:n]
        bv = np.asarray(b.col(name).data)[:n]
        assert np.array_equal(av, bv, equal_nan=True), name


# ---------------------------------------------------------------------------
# coalesced flush: one fused dispatch per bucket per flush
# ---------------------------------------------------------------------------

class TestCoalescedFlush:
    def test_multi_entry_flush_costs_one_dispatch(self, cl, gbm):
        """5 sharded-eligible entries totalling < one bucket → exactly ONE
        fused dispatch, per-entry results bitwise-identical to individual
        predicts, gathered_rows untouched."""
        from h2o3_tpu import scoring
        from h2o3_tpu.core import sharded_frame

        frames = [_score_frame(60 + 37 * i, 40 + i) for i in range(5)]
        refs = [gbm.predict(fr) for fr in frames]
        sess = scoring.session_for(gbm)
        for fr in frames:
            sess.predict(fr)               # warm the buckets involved
        before_dp = sharded_frame.counters()
        scoring.reset_dispatch_counters()
        out = sess.predict_batch([(fr, None, False) for fr in frames])
        dc = scoring.dispatch_counters()
        after_dp = sharded_frame.counters()
        assert dc.get("sharded") == 1, dc
        assert "host" not in dc and "local" not in dc
        assert after_dp["gathered_rows"] == before_dp["gathered_rows"]
        for fr, ref, (pred, _mm) in zip(frames, refs, out):
            _assert_frames_bitwise(ref, pred, fr.nrows)

    def test_coalesced_flush_chunks_at_bucket_ladder(self, cl, gbm,
                                                     monkeypatch):
        """Entries whose total exceeds the largest bucket chunk at it —
        dispatches == ceil(total/maxb), still far below one per entry,
        and every entry's slice stays bitwise."""
        import os

        from h2o3_tpu import scoring

        os.environ["H2O_TPU_SCORE_BUCKETS"] = "256"
        try:
            sess = scoring.ScoringSession(gbm)
            frames = [_score_frame(100, 50 + i) for i in range(6)]
            refs = [gbm.predict(fr) for fr in frames]
            sess.predict(frames[0])        # warm the single bucket
            scoring.reset_dispatch_counters()
            out = sess.predict_batch([(fr, None, False) for fr in frames])
            dc = scoring.dispatch_counters()
            # 600 logical rows over 256-row buckets → 3 chunks (not 6
            # per-entry dispatches)
            assert dc.get("sharded") == 3, dc
            for fr, ref, (pred, _mm) in zip(frames, refs, out):
                _assert_frames_bitwise(ref, pred, fr.nrows)
        finally:
            del os.environ["H2O_TPU_SCORE_BUCKETS"]

    def test_dispatch_accounting_surfaces(self, cl, gbm):
        """Per-model dispatches land in the session stats and the
        process-wide counters feed h2o3_score_dispatches_total; the flush
        histogram records the batch width."""
        from h2o3_tpu import scoring
        from h2o3_tpu.obs import metrics as obs_metrics

        sess = scoring.session_for(gbm)
        frames = [_score_frame(64, 70 + i) for i in range(3)]
        sess.predict_batch([(fr, None, False) for fr in frames])
        snap = [e for e in scoring.metrics_snapshot()
                if e["model"] == str(gbm.key)][0]
        assert snap["dispatches"] >= 1
        assert "dispatches_per_flush" in snap
        m = obs_metrics.REGISTRY.get("h2o3_score_dispatches_total")
        samples = m.snapshot()["samples"]
        assert any(s["labels"].get("path") == "sharded" and s["value"] >= 1
                   for s in samples), samples
        h = obs_metrics.REGISTRY.get("h2o3_score_flush_requests")
        hs = h.snapshot()["samples"]
        assert hs and hs[0]["count"] >= 1


# ---------------------------------------------------------------------------
# fused explainability outputs
# ---------------------------------------------------------------------------

class TestFusedExplainability:
    def test_leaf_matrix_bitwise_vs_eager(self, cl, gbm):
        from h2o3_tpu import scoring

        fr = _score_frame(333, 80)
        adapted = gbm.adapt_test(fr)
        sess = scoring.session_for(gbm)
        leaf_f = sess.leaf_matrix(adapted, fr.nrows)
        binned = gbm.spec.bin_columns(adapted)
        leaf_e = np.asarray(gbm.forest.leaf_index(binned))[: fr.nrows]
        assert np.array_equal(leaf_f, leaf_e)
        # host-packed fallback (plane off) is bitwise too
        import os

        os.environ["H2O_TPU_SHARDED_PLANE"] = "0"
        try:
            sess2 = scoring.ScoringSession(gbm)
            leaf_h = sess2.leaf_matrix(gbm.adapt_test(fr), fr.nrows)
        finally:
            del os.environ["H2O_TPU_SHARDED_PLANE"]
        assert np.array_equal(leaf_h, leaf_e)

    @pytest.mark.parametrize("la_type", ["Path", "Node_ID"])
    def test_leaf_assignment_matches_legacy(self, cl, gbm, monkeypatch,
                                            la_type):
        fr = _score_frame(150, 81)
        fused = gbm.predict_leaf_node_assignment(fr, type=la_type)
        monkeypatch.setenv("H2O_TPU_SCORE_FAST", "0")   # legacy eager path
        legacy = gbm.predict_leaf_node_assignment(fr, type=la_type)
        _assert_frames_bitwise(legacy, fused, fr.nrows)

    def test_staged_proba_matches_legacy(self, cl, gbm, monkeypatch):
        fr = _score_frame(140, 82)
        fused = gbm.staged_predict_proba(fr)
        monkeypatch.setenv("H2O_TPU_SCORE_FAST", "0")
        legacy = gbm.staged_predict_proba(fr)
        _assert_frames_bitwise(legacy, fused, fr.nrows)

    def test_leaf_matrix_multiprocess_ineligible_uses_eager_path(
            self, cl, gbm, monkeypatch):
        """On a simulated multi-process cloud, a frame the sharded view
        refuses must NOT take the host-gather fallback (it would pull
        non-addressable columns) — leaf_matrix keeps the eager
        device-side pass, in lockstep like predict_batch's generic
        fallback, and stays bitwise."""
        import jax

        from h2o3_tpu import scoring

        fr = _score_frame(130, 87)
        adapted = gbm.adapt_test(fr)
        ref = np.asarray(gbm.forest.leaf_index(
            gbm.spec.bin_columns(adapted)))[: fr.nrows]
        sess = scoring.ScoringSession(gbm)
        monkeypatch.setenv("H2O_TPU_SHARDED_PLANE", "0")   # view refuses
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        scoring.reset_dispatch_counters()
        leaf = sess.leaf_matrix(adapted, fr.nrows)
        monkeypatch.undo()
        assert np.array_equal(leaf, ref)
        # proof the eager path ran: no fused leaf program was dispatched
        # (and _features' host gather — which would np.asarray a
        # non-addressable column on a real cloud — was never entered)
        assert not scoring.dispatch_counters(), scoring.dispatch_counters()

    def test_leaf_programs_use_explain_family(self, cl, gbm):
        """Fused leaf compiles land in the compile ledger under the
        'explain' family (and count as cached-family compiles)."""
        from h2o3_tpu import scoring
        from h2o3_tpu.obs import compiles

        sess = scoring.ScoringSession(gbm)
        fr = _score_frame(90, 83)
        before = compiles.family_table().get("explain", {}).get(
            "compiles", 0)
        sess.leaf_matrix(gbm.adapt_test(fr), fr.nrows)
        after = compiles.family_table()["explain"]["compiles"]
        assert after == before + 1


# ---------------------------------------------------------------------------
# /4 async route + contributions over real HTTP
# ---------------------------------------------------------------------------

def _post(base, path):
    req = urllib.request.Request(base + path, data=b"", method="POST")
    with urllib.request.urlopen(req, timeout=120) as r:
        return json.loads(r.read())


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=60) as r:
        return json.loads(r.read())


class TestRestExplainabilityAndV4:
    @pytest.fixture(scope="class")
    def srv(self, cl):
        from h2o3_tpu.api.server import start_server

        srv = start_server(port=0)
        yield srv
        srv.stop()

    def test_v4_async_route_rides_fused_path_bitwise(self, cl, gbm, srv):
        from h2o3_tpu.core.dkv import DKV

        fr = _score_frame(210, 84)
        fr._key = type(fr._key)("v4_fused_in.hex")
        fr.install()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            ref = gbm.predict(fr)
            out = _post(base, f"/4/Predictions/models/{gbm.key}/frames/"
                              f"{fr.key}")
            job_key = out["job"]["key"]["name"]
            dest = out["dest"]["name"]
            deadline = time.time() + 120
            while time.time() < deadline:
                st = _get(base, f"/3/Jobs/{job_key}")["jobs"][0]
                if st["status"] not in ("CREATED", "RUNNING"):
                    break
                time.sleep(0.05)
            assert st["status"] == "DONE", st
            pred = DKV.get(dest)
            assert pred is not None
            _assert_frames_bitwise(ref, pred, fr.nrows)
        finally:
            fr.delete()

    def test_v4_saturation_sheds_synchronous_429(self, cl, gbm, srv,
                                                 monkeypatch):
        """A /4 request the admission gate would shed must get the
        synchronous 429 + Retry-After at the handler — a failed async
        job would carry no backoff hint."""
        from h2o3_tpu import admission

        fr = _score_frame(64, 88)
        fr._key = type(fr._key)("v4_shed_in.hex")
        fr.install()
        monkeypatch.setenv("H2O_TPU_SCORE_SLO_MS", "50")
        try:
            base = f"http://127.0.0.1:{srv.port}"
            admission.CONTROLLER.reset()
            # saturate the gate: limit-consuming holders + a slow ring
            for _ in range(32):
                admission.CONTROLLER.note_latency(str(gbm.key), 5000.0)
            g = admission.CONTROLLER._gate(str(gbm.key))
            with g.cond:
                g.inflight = admission.CONTROLLER._limit(g)
            try:
                with pytest.raises(urllib.error.HTTPError) as ei:
                    _post(base, f"/4/Predictions/models/{gbm.key}/frames/"
                                f"{fr.key}")
                assert ei.value.code == 429
                assert ei.value.headers.get("Retry-After") is not None
            finally:
                with g.cond:
                    g.inflight = 0
        finally:
            fr.delete()
            admission.CONTROLLER.reset()

    def test_v3_contributions_match_eager(self, cl, gbm, srv):
        from h2o3_tpu.core.dkv import DKV

        fr = _score_frame(120, 85)
        fr._key = type(fr._key)("contrib_in.hex")
        fr.install()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            ref = gbm.predict_contributions(fr)
            out = _post(base, f"/3/Predictions/models/{gbm.key}/frames/"
                              f"{fr.key}?predict_contributions=true")
            pred = DKV.get(out["predictions_frame"]["name"])
            _assert_frames_bitwise(ref, pred, fr.nrows)
        finally:
            fr.delete()

    def test_v3_leaf_and_staged_rest_bitwise(self, cl, gbm, srv,
                                             monkeypatch):
        from h2o3_tpu.core.dkv import DKV

        fr = _score_frame(110, 86)
        fr._key = type(fr._key)("leaf_in.hex")
        fr.install()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            monkeypatch.setenv("H2O_TPU_SCORE_FAST", "0")
            ref_leaf = gbm.predict_leaf_node_assignment(fr, type="Path")
            ref_staged = gbm.staged_predict_proba(fr)
            monkeypatch.delenv("H2O_TPU_SCORE_FAST")
            out = _post(base, f"/3/Predictions/models/{gbm.key}/frames/"
                              f"{fr.key}?leaf_node_assignment=true")
            _assert_frames_bitwise(
                ref_leaf, DKV.get(out["predictions_frame"]["name"]),
                fr.nrows)
            out = _post(base, f"/3/Predictions/models/{gbm.key}/frames/"
                              f"{fr.key}?predict_staged_proba=true")
            _assert_frames_bitwise(
                ref_staged, DKV.get(out["predictions_frame"]["name"]),
                fr.nrows)
        finally:
            fr.delete()


# ---------------------------------------------------------------------------
# SLO-adaptive admission (unit)
# ---------------------------------------------------------------------------

class TestSloAdmission:
    def test_disabled_by_default(self, monkeypatch):
        from h2o3_tpu.admission import AdmissionController

        monkeypatch.delenv("H2O_TPU_SCORE_SLO_MS", raising=False)
        monkeypatch.delenv("H2O_TPU_SCORE_MAX_INFLIGHT", raising=False)
        ctl = AdmissionController()
        with ctl.slot("m"):
            pass
        assert ctl.admitted == 0          # gate disabled: zero overhead

    def test_aimd_decreases_on_breach(self, monkeypatch):
        from h2o3_tpu.admission import AdmissionController

        monkeypatch.setenv("H2O_TPU_SCORE_SLO_MS", "50")
        ctl = AdmissionController()
        for _ in range(64):
            ctl.note_latency("m", 500.0)
        assert ctl.derived_limits()["m"] == 1

    def test_aimd_grows_only_under_pressure(self, monkeypatch):
        from h2o3_tpu.admission import AdmissionController

        monkeypatch.setenv("H2O_TPU_SCORE_SLO_MS", "100")
        ctl = AdmissionController()
        # fast traffic, NO pressure: limit stays at its seed
        for _ in range(64):
            ctl.note_latency("idle", 2.0)
        seed = ctl.derived_limits()["idle"]
        g = ctl._gate("busy")
        for i in range(64):
            with g.cond:
                g.inflight = ctl._limit(g)     # fake demand pressure
            ctl.note_latency("busy", 2.0)
        with g.cond:
            g.inflight = 0
        assert ctl.derived_limits()["idle"] == seed
        assert ctl.derived_limits()["busy"] > seed

    def test_static_knob_caps_derived_limit(self, monkeypatch):
        from h2o3_tpu.admission import AdmissionController

        monkeypatch.setenv("H2O_TPU_SCORE_SLO_MS", "100")
        monkeypatch.setenv("H2O_TPU_SCORE_MAX_INFLIGHT", "2")
        ctl = AdmissionController()
        g = ctl._gate("m")
        for _ in range(64):
            with g.cond:
                g.inflight = 2
            ctl.note_latency("m", 1.0)
        with g.cond:
            g.inflight = 0
        assert ctl.derived_limits()["m"] <= 2

    def test_queue_time_gate_sheds_429_with_derived_retry_after(
            self, monkeypatch):
        from h2o3_tpu.admission import (AdmissionController,
                                        AdmissionRejected)

        monkeypatch.setenv("H2O_TPU_SCORE_SLO_MS", "100")
        ctl = AdmissionController()
        for _ in range(32):
            ctl.note_latency("m", 4000.0)      # mean 4s >> 100ms SLO
        limit = ctl.derived_limits()["m"]
        started = threading.Event()
        release = threading.Event()

        def hold():
            with ctl.slot("m"):
                started.set()
                release.wait(timeout=30)

        holders = [threading.Thread(target=hold) for _ in range(limit)]
        for t in holders:
            t.start()
        started.wait(timeout=10)
        time.sleep(0.1)
        try:
            with pytest.raises(AdmissionRejected) as ei:
                with ctl.slot("m"):
                    pass
            assert ei.value.status == 429
            # drain-rate-derived: backlog × mean / limit = 1 × 4s / 1 = 4s,
            # NOT the old constant 1s
            assert ei.value.retry_after_s >= 2.0
            assert ctl.shed_slo == 1
        finally:
            release.set()
            for t in holders:
                t.join()

    def test_snapshot_carries_slo_block(self, monkeypatch):
        from h2o3_tpu.admission import AdmissionController

        monkeypatch.setenv("H2O_TPU_SCORE_SLO_MS", "123")
        ctl = AdmissionController()
        ctl.note_latency("m", 10.0)
        snap = ctl.snapshot()
        assert snap["slo_ms"] == 123.0
        assert snap["models"]["m"]["limit"] >= 1
        assert "p99_ms" in snap["models"]["m"]


# ---------------------------------------------------------------------------
# saturation soak (slow; real HTTP)
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestSaturationSoak:
    def test_soak_holds_p99_within_slo_while_shedding(self, cl, gbm,
                                                      monkeypatch):
        """Drive sustained concurrency past the adaptive limit: requests
        that are served stay within the latency SLO at p99, the overflow
        sheds as 429 with a Retry-After, and the soak compiles ZERO new
        fused scoring programs (compile-ledger-asserted)."""
        from h2o3_tpu import admission, scoring
        from h2o3_tpu.api.server import start_server
        from h2o3_tpu.obs import compiles

        fr = _score_frame(128, 90)
        fr._key = type(fr._key)("soak_in.hex")
        fr.install()
        srv = start_server(port=0)
        try:
            base = (f"http://127.0.0.1:{srv.port}/3/Predictions/models/"
                    f"{gbm.key}/frames/{fr.key}")

            def one():
                req = urllib.request.Request(base, data=b"",
                                             method="POST")
                t0 = time.perf_counter()
                try:
                    with urllib.request.urlopen(req, timeout=120) as r:
                        json.loads(r.read())
                    return ("ok", time.perf_counter() - t0, None)
                except urllib.error.HTTPError as e:
                    return ("http", time.perf_counter() - t0,
                            (e.code, e.headers.get("Retry-After")))

            # warm every program, then size the SLO from observed latency.
            # Coalesced flushes land in the bucket matching the FLUSH's
            # total rows, so warm the whole ladder (a warm production
            # server holds all bucket executables — from traffic or the
            # persistent compile cache) before asserting zero recompiles.
            sess = scoring.session_for(gbm)
            for warm_n in (100, 500, 2000, 10000):
                sess.predict(_score_frame(warm_n, 200 + warm_n))
            for _ in range(3):
                st, dt, _x = one()
                assert st == "ok"
            base_ms = dt * 1000.0
            slo = max(2500.0, 40 * base_ms)
            monkeypatch.setenv("H2O_TPU_SCORE_SLO_MS", str(slo))
            monkeypatch.setenv("H2O_TPU_SCORE_QUEUE_CAP", "2")
            admission.CONTROLLER.reset()
            ledger0 = compiles.family_table().get("scoring", {}).get(
                "compiles", 0)
            sess_compiles0 = scoring.session_for(gbm).fused_compiles

            results = []
            res_lock = threading.Lock()
            stop = time.time() + 6.0

            def client():
                # a real client honors Retry-After; hammering without
                # backoff would measure GIL starvation of the in-process
                # server, not the admission behavior under load
                while time.time() < stop:
                    r = one()
                    with res_lock:
                        results.append(r)
                    if r[0] == "http" and r[2][1]:
                        time.sleep(min(float(r[2][1]), 0.25))

            ths = [threading.Thread(target=client) for _ in range(16)]
            for t in ths:
                t.start()
            for t in ths:
                t.join()

            ok_lat = sorted(dt for st, dt, _x in results if st == "ok")
            rejects = [x for st, _dt, x in results if st == "http"]
            assert ok_lat, "soak served nothing"
            assert rejects, "soak never shed — not saturated"
            assert all(code in (429, 503) and ra is not None
                       for code, ra in rejects), rejects[:5]
            p99 = ok_lat[min(len(ok_lat) - 1,
                             int(len(ok_lat) * 0.99))] * 1000.0
            assert p99 <= slo, (p99, slo, len(ok_lat), len(rejects))
            # zero fused recompiles during the soak (the warm-bucket
            # contract: saturation must not thrash the compile caches)
            assert compiles.family_table()["scoring"]["compiles"] == \
                ledger0
            assert scoring.session_for(gbm).fused_compiles == \
                sess_compiles0
            # at least one 429 carries the drain-derived Retry-After
            assert any(int(ra) >= 1 for _c, ra in rejects)
        finally:
            srv.stop()
            fr.delete()
            admission.CONTROLLER.reset()
