"""Cloud supervision tier: acknowledged oplog, bounded waits, retry with
backoff, and the HEALTHY/DEGRADED/FAILED state machine (ISSUE 3).

Reference: water/RPC.java retries every remote task with exponential
backoff; water/HeartBeatThread.java turns a silent node death into an
explicit cloud event. The 2-process gloo tier is env-flaky on this jax
build, so these tests drive the FULL protocol — publish/replay/ack/error/
heartbeat/supervise — deterministically inside one process: the cloud KV
is `distributed.memory_kv()` (a dict), the topology is monkeypatched to
look like a 2-process cloud, and `failure.inject()` supplies the crashes
a real dead peer would.
"""

import json
import re
import threading
import time
import urllib.error
import urllib.request

import pytest

from h2o3_tpu.core import failure
from h2o3_tpu.parallel import ckpt
from h2o3_tpu.parallel import distributed as D
from h2o3_tpu.parallel import oplog, retry, supervisor

pytestmark = pytest.mark.chaos


@pytest.fixture()
def mem_cloud(monkeypatch):
    """Simulated 2-process cloud: dict-backed KV + coordinator topology.
    jax itself stays single-process (device programs run locally), which
    is exactly what makes the protocol paths deterministic here."""
    with D.memory_kv() as kv:
        monkeypatch.setattr(D, "process_count", lambda: 2)
        monkeypatch.setattr(D, "is_coordinator", lambda: True)
        monkeypatch.setenv("H2O_TPU_RETRY_BASE_MS", "1")
        # bound every ack wait so a test bug can never park a thread on
        # the production 300 s default (tests override per-case as needed)
        monkeypatch.setenv("H2O_TPU_OP_ACK_TIMEOUT_S", "30")
        # checkpointing off by default: tests that exercise it opt in (a
        # surprise 'checkpoint' op would shift every seq assertion here)
        monkeypatch.setenv("H2O_TPU_OPLOG_CHECKPOINT_OPS", "0")
        # synchronous checkpoints: the ckpt op lands at a deterministic
        # seq and the chaos fault injections hit the op they target (the
        # async path has its own dedicated test)
        monkeypatch.setenv("H2O_TPU_OPLOG_CKPT_ASYNC", "0")
        # these tests drive every transition BY HAND — the autonomous
        # watchdog would race them (its own tests re-enable it)
        monkeypatch.setenv("H2O_TPU_AUTO_RECOVER", "0")
        failure.set_incarnation(0)
        D.reset_leadership()
        oplog._DEMOTED = False
        oplog.reset()
        supervisor.reset()
        yield kv
        ckpt.wait_idle()       # never leak an in-flight ckpt across tests
    failure.set_incarnation(0)
    D.reset_leadership()
    oplog._DEMOTED = False
    oplog.reset()
    supervisor.reset()


# ---------------------------------------------------------------------------
# retry.py
# ---------------------------------------------------------------------------

class TestRetry:
    def test_succeeds_after_transient_failures(self):
        calls, slept = [], []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "ok"

        assert retry.retry_call(flaky, retries=4, base_s=0.001,
                                sleep=slept.append) == "ok"
        assert len(calls) == 3 and len(slept) == 2

    def test_exhaustion_raises_original_error(self):
        slept = []
        with pytest.raises(OSError, match="always"):
            retry.retry_call(lambda: (_ for _ in ()).throw(OSError("always")),
                             retries=3, base_s=0.001, sleep=slept.append)
        assert len(slept) == 2          # attempts-1 backoffs

    def test_retry_on_filters_exception_types(self):
        calls = []

        def boom():
            calls.append(1)
            raise ValueError("not retryable here")

        with pytest.raises(ValueError):
            retry.retry_call(boom, retries=5, retry_on=(OSError,),
                             sleep=lambda s: None)
        assert len(calls) == 1          # no retries for non-matching type

    def test_backoff_doubles_and_caps(self):
        ds = list(retry.backoff_delays(attempts=6, base_s=0.01, max_s=0.05,
                                       jitter=0.0))
        assert ds == [0.01, 0.02, 0.04, 0.05, 0.05]

    def test_backoff_jitter_bounded(self):
        for d, nominal in zip(retry.backoff_delays(attempts=4, base_s=0.01,
                                                   max_s=10.0, jitter=0.5),
                              (0.01, 0.02, 0.04)):
            assert 0.5 * nominal <= d <= 1.5 * nominal

    def test_adaptive_poll_grows_and_resets(self):
        slept = []
        p = retry.AdaptivePoll(min_s=0.001, max_s=0.25, sleep=slept.append)
        for _ in range(12):
            p.wait()
        assert slept[0] == pytest.approx(0.001)
        assert slept[-1] == pytest.approx(0.25)       # capped cold
        assert all(b >= a for a, b in zip(slept, slept[1:]))
        p.reset()
        assert p.current_s == pytest.approx(0.001)    # hot again


# ---------------------------------------------------------------------------
# publish: lost-put rollback + retry (satellite 1)
# ---------------------------------------------------------------------------

class TestPublish:
    def test_lost_kv_put_raises_and_rolls_back_seq(self, mem_cloud,
                                                   monkeypatch):
        monkeypatch.setenv("H2O_TPU_RETRY_MAX", "2")
        monkeypatch.setattr(D, "kv_put", lambda k, v: False)
        with pytest.raises(oplog.OplogPublishError, match="op 0"):
            oplog.publish("noop", {})
        # slot rolled back: nothing at seq 0, and the next publish (with a
        # working KV) re-claims 0 — the follower sees a gapless sequence
        monkeypatch.undo()
        monkeypatch.setenv("H2O_TPU_RETRY_BASE_MS", "1")
        assert oplog.publish("noop", {}) == 0
        assert "oplog/0" in mem_cloud

    def test_injected_put_loss_rolls_back_and_caller_retry_lands(
            self, mem_cloud):
        """A HARD put loss (transport retries exhausted) raises with the
        slot rolled back; a caller retrying the publish — the scoring
        micro-batcher's pattern — gets the SAME slot, so the follower
        still sees a gapless sequence."""
        with failure.inject("oplog.kv_put", times=1):
            seq = retry.retry_call(oplog.publish, "noop", {},
                                   retry_on=(oplog.OplogPublishError,),
                                   base_s=0.001)
        assert seq == 0
        assert json.loads(mem_cloud["oplog/0"])["kind"] == "noop"

    def test_publish_faultpoint_fails_cleanly(self, mem_cloud):
        with failure.inject("oplog.publish", times=1):
            with pytest.raises(failure.InjectedFault):
                oplog.publish("noop", {})
        assert oplog.publish("noop", {}) == 0         # nothing was claimed


# ---------------------------------------------------------------------------
# turn(): bounded turnstile wait + slot abandonment (satellite 2)
# ---------------------------------------------------------------------------

class TestTurnDeadline:
    def test_dead_predecessor_raises_instead_of_hanging(self, mem_cloud,
                                                        monkeypatch):
        monkeypatch.setenv("H2O_TPU_OP_ACK_TIMEOUT_S", "0")  # isolate turnstile
        oplog.publish("noop", {})            # seq 0: holder never turns
        seq1 = oplog.publish("noop", {})
        t0 = time.monotonic()
        with pytest.raises(oplog.OplogTurnTimeout, match="stuck at op 0"):
            with oplog.turn(seq1, timeout_s=0.3):
                pass
        assert time.monotonic() - t0 < 5.0   # bounded, not the old forever

    def test_timed_out_waiter_releases_never_entered_head(self, mem_cloud,
                                                          monkeypatch):
        """A head holder that died between publish and turn must not cost
        every later op its own full deadline: the first timed-out waiter
        releases the head slot too, neutralizes both ops to noops in the
        KV, and degrades the cloud."""
        monkeypatch.setenv("H2O_TPU_OP_ACK_TIMEOUT_S", "0")
        for _ in range(3):
            oplog.publish("noop", {})
        with pytest.raises(oplog.OplogTurnTimeout, match="head slot 0"):
            with oplog.turn(1, timeout_s=0.2):       # 0 never turned
                pass
        # both abandoned ops are neutralized so a lagging follower
        # replays nothing the coordinator never ran
        for s in (0, 1):
            assert json.loads(mem_cloud[f"oplog/{s}"])["kind"] == "noop"
        assert supervisor.state() == supervisor.DEGRADED
        # op 2 enters IMMEDIATELY — no serial re-pay of the deadline
        t0 = time.monotonic()
        ran = []
        with oplog.turn(2, timeout_s=5.0):
            ran.append(2)
        assert ran == [2] and time.monotonic() - t0 < 1.0

    def test_late_arriving_holder_of_abandoned_slot_refuses(self, mem_cloud,
                                                            monkeypatch):
        """The presumed-dead holder shows up after all: it must refuse to
        execute out of broadcast order (its op is already a noop) and
        hand the turnstile onward instead of stalling it."""
        monkeypatch.setenv("H2O_TPU_OP_ACK_TIMEOUT_S", "0")
        for _ in range(2):
            oplog.publish("noop", {})
        with pytest.raises(oplog.OplogTurnTimeout):
            with oplog.turn(1, timeout_s=0.2):
                pass
        with pytest.raises(oplog.OplogTurnTimeout, match="abandoned"):
            with oplog.turn(0, timeout_s=5.0):       # the late holder
                raise AssertionError("abandoned op must not execute")
        # and the turnstile moved on: a fresh op proceeds instantly
        seq = oplog.publish("noop", {})
        with oplog.turn(seq, timeout_s=5.0):
            pass

    def test_slow_executing_head_is_left_alone(self, mem_cloud,
                                               monkeypatch):
        """A head holder INSIDE its turn (long device program) is alive —
        a timed-out waiter abandons only itself, never the head."""
        monkeypatch.setenv("H2O_TPU_OP_ACK_TIMEOUT_S", "0")
        oplog.publish("noop", {})
        seq1 = oplog.publish("noop", {})
        entered = threading.Event()
        release = threading.Event()
        done = []

        def slow_head():
            with oplog.turn(0, timeout_s=5.0):
                entered.set()
                release.wait(10)
            done.append(0)

        t = threading.Thread(target=slow_head, daemon=True)
        t.start()
        assert entered.wait(5)
        with pytest.raises(oplog.OplogTurnTimeout) as ei:
            with oplog.turn(seq1, timeout_s=0.2):
                pass
        assert "head slot" not in str(ei.value)      # head NOT released
        release.set()
        t.join(10)
        assert done == [0]                           # head completed fine

    def test_none_ticket_stays_free(self):
        with oplog.turn(None):               # single-process path: no-op
            pass


# ---------------------------------------------------------------------------
# ack protocol + follower loop
# ---------------------------------------------------------------------------

class TestAcks:
    def test_follower_acks_each_replay(self, mem_cloud):
        t = threading.Thread(
            target=lambda: oplog.follower_loop(idle_timeout_s=10),
            daemon=True)
        t.start()
        for _ in range(3):
            seq = oplog.broadcast("noop", {})
            with oplog.turn(seq, timeout_s=10):
                pass                          # exit waits for the ack
        assert {f"oplog/ack/{i}/0" for i in range(3)} <= set(mem_cloud)
        oplog.publish("shutdown", {})
        t.join(timeout=10)
        assert not t.is_alive()

    def test_wait_acks_timeout_degrades_cloud(self, mem_cloud):
        oplog.publish("noop", {})            # no follower running
        t0 = time.monotonic()
        with pytest.raises(failure.CloudUnhealthyError, match="0/1"):
            oplog.wait_acks(0, timeout_s=0.3)
        assert time.monotonic() - t0 < 5.0
        assert supervisor.state() == supervisor.DEGRADED
        # the degrade is HELD: a wedged peer that keeps beating must not
        # instantly re-arm the cloud on the next heartbeat evaluation
        now = time.time()
        for p in (0, 1):
            mem_cloud[f"h2o3/heartbeat/{p}"] = json.dumps({"ts": now,
                                                           "proc": p})
        assert supervisor.evaluate() == supervisor.DEGRADED
        # ... and recovers once the hold ages out
        with supervisor._LOCK:
            supervisor._STATE["hold_until"] = time.time() - 1
        assert supervisor.evaluate() == supervisor.HEALTHY

    def test_wait_acks_bails_fast_when_cloud_already_failed(self,
                                                           mem_cloud):
        """A replay crash on ANOTHER op must fail this op's ack wait
        immediately with that diagnosis — not a generic timeout 300s
        later."""
        supervisor.fail("follower replay of op 3 crashed",
                        "Traceback ...\nOtherOpBoom")
        t0 = time.monotonic()
        with pytest.raises(failure.CloudUnhealthyError,
                           match="OtherOpBoom"):
            oplog.wait_acks(7, timeout_s=300.0)
        assert time.monotonic() - t0 < 5.0

    def test_wait_acks_surfaces_remote_traceback(self, mem_cloud):
        mem_cloud["oplog/error/0"] = json.dumps(
            {"kind": "train", "trace": "Traceback ...\nBoomError: kaput"})
        with pytest.raises(failure.CloudUnhealthyError,
                           match="BoomError: kaput") as ei:
            oplog.wait_acks(0, timeout_s=5)
        assert "BoomError" in ei.value.remote_trace
        assert supervisor.state() == supervisor.FAILED

    def test_replay_crash_error_key_before_death(self, mem_cloud):
        oplog.publish("noop", {})
        with failure.inject("oplog.replay", times=1):
            with pytest.raises(failure.InjectedFault):
                oplog.follower_loop(idle_timeout_s=5)
        rec = json.loads(mem_cloud["oplog/error/0"])
        assert rec["kind"] == "noop"
        assert "injected fault: oplog.replay" in rec["trace"]

    def test_lost_ack_hits_timeout_not_error_path(self, mem_cloud):
        oplog.publish("noop", {})
        with failure.inject("oplog.ack", times=1):
            with pytest.raises(failure.InjectedFault):
                oplog.follower_loop(idle_timeout_s=5)
        assert "oplog/error/0" not in mem_cloud   # replay itself succeeded
        with pytest.raises(failure.CloudUnhealthyError, match="acks"):
            oplog.wait_acks(0, timeout_s=0.2)

    def test_lost_ack_write_is_loud_and_nonfatal(self, mem_cloud,
                                                 monkeypatch):
        """A follower whose ack WRITE is lost (kv_put budget exhausted)
        must not silently proceed — the coordinator would stall the full
        ack timeout and then degrade with a misleading 'follower dead'
        diagnosis. It records a NON-fatal error (the replay succeeded:
        states did not diverge) and dies; wait_acks surfaces the true
        story immediately and the cloud DEGRADES rather than
        sticky-FAILs."""
        monkeypatch.setenv("H2O_TPU_RETRY_MAX", "2")
        real = D.kv_put
        monkeypatch.setattr(
            D, "kv_put",
            lambda k, v: False if k.startswith("oplog/ack/")
            else real(k, v))
        oplog.publish("noop", {})
        with pytest.raises(oplog.OplogAckError, match="could not write"):
            oplog.follower_loop(idle_timeout_s=5)
        rec = json.loads(mem_cloud["oplog/error/0"])
        assert rec["kind"] == "ack" and rec["fatal"] is False
        t0 = time.monotonic()
        with pytest.raises(failure.CloudUnhealthyError, match="non-fatal"):
            oplog.wait_acks(0, timeout_s=30)
        assert time.monotonic() - t0 < 5.0            # no 30 s stall
        assert supervisor.state() == supervisor.DEGRADED
        assert supervisor.evaluate() == supervisor.DEGRADED  # not FAILED

    def test_transient_ack_loss_absorbed_by_retry(self, mem_cloud,
                                                  monkeypatch):
        """One blipped ack write is absorbed by _ack's second retry round:
        the ack lands, no error record appears, wait_acks returns."""
        real = D.kv_put
        fails = {"left": 1}

        def flaky(k, v):
            if k.startswith("oplog/ack/") and fails["left"]:
                fails["left"] -= 1
                return False
            return real(k, v)

        monkeypatch.setattr(D, "kv_put", flaky)
        oplog.publish("noop", {})
        oplog.publish("shutdown", {})
        assert oplog.follower_loop(idle_timeout_s=5) == 1
        assert "oplog/ack/0/0" in mem_cloud
        assert "oplog/error/0" not in mem_cloud
        oplog.wait_acks(0, timeout_s=5)               # ack landed: no raise

    def test_stale_ack_cannot_satisfy_a_reclaimed_slot(self, mem_cloud):
        """Indeterminate put: op 0's kv_put reported lost (slot rolled
        back) but the follower acked SOMETHING under seq 0. A different
        op reclaiming the slot must not be satisfied by that stale ack —
        acks match on the op identity token, not the slot number."""
        with failure.inject("oplog.kv_put", times=1):
            with pytest.raises(oplog.OplogPublishError):
                oplog.publish("noop", {})
        mem_cloud["oplog/ack/0/1"] = json.dumps(
            {"proc": 1, "ts": time.time(), "op_id": "the-lost-op"})
        assert oplog.publish("noop", {"fresh": True}) == 0   # reclaimed
        with pytest.raises(failure.CloudUnhealthyError, match="0/1"):
            oplog.wait_acks(0, timeout_s=0.3)

    def test_abandoned_slot_already_replayed_fails_cloud(self, mem_cloud,
                                                         monkeypatch):
        """If a follower ALREADY replayed an op whose turnstile slot gets
        abandoned, the divergence is certain (the follower ran a program
        the coordinator never will): sticky FAILED, not a held degrade."""
        monkeypatch.setenv("H2O_TPU_OP_ACK_TIMEOUT_S", "0")
        oplog.publish("noop", {})            # head; holder never arrives
        seq1 = oplog.publish("noop", {})
        op0 = json.loads(mem_cloud["oplog/0"])
        mem_cloud["oplog/ack/0/1"] = json.dumps(
            {"proc": 1, "ts": time.time(), "op_id": op0["op_id"]})
        with pytest.raises(oplog.OplogTurnTimeout):
            with oplog.turn(seq1, timeout_s=0.2):
                pass
        assert supervisor.state() == supervisor.FAILED
        assert "diverged" in supervisor.status()["reason"]

    def test_follower_idle_timeout_error_path(self, mem_cloud):
        t0 = time.monotonic()
        with pytest.raises(TimeoutError, match="idle for 0.2s at op 0"):
            oplog.follower_loop(idle_timeout_s=0.2)
        assert time.monotonic() - t0 < 5.0


# ---------------------------------------------------------------------------
# supervisor state machine
# ---------------------------------------------------------------------------

class TestSupervisor:
    def test_stale_heartbeat_degrades_then_recovers(self, mem_cloud):
        now = time.time()
        mem_cloud["h2o3/heartbeat/0"] = json.dumps({"ts": now, "proc": 0})
        mem_cloud["h2o3/heartbeat/1"] = json.dumps({"ts": now - 1000,
                                                    "proc": 1})
        assert supervisor.evaluate() == supervisor.DEGRADED
        st = supervisor.status()
        assert "stale heartbeat" in st["reason"] and "[1]" in st["reason"]
        with pytest.raises(failure.CloudUnhealthyError):
            oplog.broadcast("noop", {})      # degraded: refused fast
        # the peer comes back: beats refresh, the cloud recovers
        mem_cloud["h2o3/heartbeat/1"] = json.dumps({"ts": time.time(),
                                                    "proc": 1})
        assert supervisor.evaluate() == supervisor.HEALTHY
        assert oplog.broadcast("noop", {}) == 0      # serving again

    def test_never_beaten_follower_degrades_after_grace(self, mem_cloud,
                                                        monkeypatch):
        """A follower that died at STARTUP has no stale heartbeat row to
        trip on — its absence past the staleness window must degrade the
        cloud all the same."""
        now = time.time()
        mem_cloud["h2o3/heartbeat/0"] = json.dumps({"ts": now, "proc": 0})
        assert supervisor.evaluate() == supervisor.HEALTHY   # inside grace
        monkeypatch.setattr(supervisor, "_FIRST_EVAL_TS", now - 100)
        assert supervisor.evaluate() == supervisor.DEGRADED
        assert "never heartbeat" in supervisor.status()["reason"]
        # the missing peer finally boots and beats: cloud recovers
        mem_cloud["h2o3/heartbeat/1"] = json.dumps({"ts": time.time(),
                                                    "proc": 1})
        assert supervisor.evaluate() == supervisor.HEALTHY

    def test_replay_error_fails_cloud_permanently(self, mem_cloud):
        mem_cloud["oplog/error/4"] = json.dumps({"kind": "predict",
                                                 "trace": "tb"})
        assert supervisor.evaluate() == supervisor.FAILED
        # FAILED is sticky: fresh heartbeats do NOT recover a diverged cloud
        now = time.time()
        for p in (0, 1):
            mem_cloud[f"h2o3/heartbeat/{p}"] = json.dumps({"ts": now,
                                                           "proc": p})
        del mem_cloud["oplog/error/4"]
        assert supervisor.evaluate() == supervisor.FAILED

    def test_failed_cloud_fails_inflight_jobs_with_trace(self, mem_cloud):
        from h2o3_tpu.core.job import Job

        ev = threading.Event()
        job = Job(description="wedged collective")
        job.start(lambda j: ev.wait(10), background=True)
        try:
            supervisor.fail("follower replay of op 7 crashed",
                            "Traceback ...\nRemoteBoom: dead peer")
            assert job.status == Job.FAILED
            assert "RemoteBoom: dead peer" in job.exception
        finally:
            ev.set()
        time.sleep(0.05)                     # worker unwinds...
        assert job.status == Job.FAILED      # ...but cannot resurrect DONE

    def test_created_job_failed_by_supervisor_never_runs(self, mem_cloud):
        """A job failed while still CREATED (cloud died between submit
        and thread start) must honor the verdict, not resurrect itself
        to RUNNING and execute against a dead cloud."""
        from h2o3_tpu.core.job import Job

        job = Job(description="doomed before start")
        supervisor.fail("cloud died pre-start", "pre-start trace")
        assert job.status == Job.FAILED
        ran = []
        job.start(lambda j: ran.append(1), background=False)
        assert ran == []
        assert job.status == Job.FAILED
        assert "pre-start trace" in job.exception

    def test_cluster_health_staleness_boundary(self, mem_cloud):
        now = time.time()
        mem_cloud["h2o3/heartbeat/0"] = json.dumps({"ts": now - 29.0,
                                                    "proc": 0})
        mem_cloud["h2o3/heartbeat/1"] = json.dumps({"ts": now - 31.0,
                                                    "proc": 1})
        rows = failure.cluster_health(stale_after_s=30.0)
        by_proc = {r["process"]: r for r in rows}
        assert by_proc[0]["healthy"] is True       # just inside the window
        assert by_proc[1]["healthy"] is False      # just past it
        assert by_proc[1]["age_s"] > by_proc[0]["age_s"]

    def test_heartbeat_faultpoint_drops_beat(self, mem_cloud):
        with failure.inject("failure.heartbeat", times=1):
            with pytest.raises(failure.InjectedFault):
                failure.heartbeat()
        assert failure.heartbeat()           # next beat lands
        assert "h2o3/heartbeat/0" in mem_cloud

    def test_recover_check_is_atomic_with_hold(self, mem_cloud,
                                               monkeypatch):
        """evaluate() must hold the state lock ACROSS its hold_until check
        and the recover() transition: a degrade(hold_s=...) landing from
        another thread (an ack-timeout handler recording fresh wedged-peer
        evidence) can then never slip between the two and be erased
        together with its hold."""
        supervisor.degrade("old evidence")               # hold expired
        now = time.time()
        for p in (0, 1):
            mem_cloud[f"h2o3/heartbeat/{p}"] = json.dumps({"ts": now,
                                                           "proc": p})
        lock_held_during_recover = []
        real = supervisor.recover

        def spying(*a, **k):
            got = []

            def probe():
                ok = supervisor._LOCK.acquire(timeout=0.2)
                if ok:
                    supervisor._LOCK.release()
                got.append(ok)

            t = threading.Thread(target=probe)
            t.start()
            t.join()
            lock_held_during_recover.append(not got[0])
            return real(*a, **k)

        monkeypatch.setattr(supervisor, "recover", spying)
        assert supervisor.evaluate() == supervisor.HEALTHY
        assert lock_held_during_recover == [True]


# ---------------------------------------------------------------------------
# distributed KV fallbacks (satellite 4)
# ---------------------------------------------------------------------------

class _LegacyKVClient:
    """jax client without allow_overwrite: set raises on existing keys."""

    def __init__(self):
        self.store = {}

    def key_value_set(self, key, value, allow_overwrite=None):
        if allow_overwrite is not None:
            raise TypeError("no allow_overwrite kwarg")
        if key in self.store:
            raise RuntimeError("ALREADY_EXISTS")
        self.store[key] = value

    def key_value_try_get(self, key):
        if key not in self.store:
            raise KeyError(key)
        return self.store[key]

    def key_value_delete(self, key):
        self.store.pop(key, None)


class TestKVFallbacks:
    def test_kv_put_overwrite_retry_fallback(self, monkeypatch):
        c = _LegacyKVClient()
        monkeypatch.setattr(D, "_kv_client", lambda: c)
        monkeypatch.setenv("H2O_TPU_RETRY_BASE_MS", "1")
        assert D.kv_put("k", "v1") is True           # fresh key
        assert D.kv_put("k", "v2") is True           # delete+retry upsert
        assert c.store["k"] == "v2"

    def test_kv_put_concurrent_winner_counts_as_success(self, monkeypatch):
        c = _LegacyKVClient()

        def stubborn_set(key, value, allow_overwrite=None):
            if allow_overwrite is not None:
                raise TypeError("no kwarg")
            # a concurrent writer always beats us to the slot
            c.store.setdefault(key, "theirs")
            raise RuntimeError("ALREADY_EXISTS")

        monkeypatch.setattr(c, "key_value_set", stubborn_set)
        monkeypatch.setattr(D, "_kv_client", lambda: c)
        monkeypatch.setenv("H2O_TPU_RETRY_BASE_MS", "1")
        assert D.kv_put("k", "mine") is True         # a value IS in place
        assert c.store["k"] == "theirs"

    def test_kv_put_real_loss_returns_false(self, monkeypatch):
        c = _LegacyKVClient()

        def losing_set(key, value, allow_overwrite=None):
            if allow_overwrite is not None:
                raise TypeError("no kwarg")
            raise RuntimeError("ALREADY_EXISTS")     # and nothing lands

        monkeypatch.setattr(c, "key_value_set", losing_set)
        monkeypatch.setattr(D, "_kv_client", lambda: c)
        monkeypatch.setenv("H2O_TPU_RETRY_MAX", "2")
        monkeypatch.setenv("H2O_TPU_RETRY_BASE_MS", "1")
        assert D.kv_put("k", "v") is False


# ---------------------------------------------------------------------------
# scoring micro-batcher: retry + degraded-mode local serving
# ---------------------------------------------------------------------------

class _FakeKeyed:
    def __init__(self, key):
        self.key = key


class TestScoringSupervision:
    def _pending(self):
        from h2o3_tpu import scoring

        return scoring._Pending(_FakeKeyed("fr"), None, False)

    def test_flush_retries_lost_broadcast(self, mem_cloud, monkeypatch):
        from h2o3_tpu import scoring

        attempts = []

        def flaky_broadcast(kind, payload):
            attempts.append(kind)
            if len(attempts) == 1:
                raise oplog.OplogPublishError("lost")
            return None

        monkeypatch.setattr(oplog, "broadcast", flaky_broadcast)
        monkeypatch.setattr(scoring, "execute_batch",
                            lambda m, e, local_only=False: [("PRED", None)])
        ent = self._pending()
        scoring.ScoreBatcher._flush(_FakeKeyed("m"), [ent])
        assert attempts == ["score_batch", "score_batch"]
        assert ent.error is None and ent.pred == "PRED"

    def test_degrade_race_during_broadcast_falls_back_local(
            self, mem_cloud, monkeypatch):
        """The cloud degrades BETWEEN the batcher's state snapshot and the
        broadcast's own fail-fast check: scoring must fall back to local
        serving, not 503 the whole batch."""
        from h2o3_tpu import scoring

        def degrading_broadcast(kind, payload):
            raise failure.CloudUnhealthyError("degraded mid-flight")

        monkeypatch.setattr(oplog, "broadcast", degrading_broadcast)
        seen = {}

        def exec_local(m, entries, local_only=False):
            seen["local_only"] = local_only
            return [("PRED", None)]

        monkeypatch.setattr(scoring, "execute_batch", exec_local)
        ent = self._pending()
        scoring.ScoreBatcher._flush(_FakeKeyed("m"), [ent])
        assert seen["local_only"] is True
        assert ent.error is None and ent.pred == "PRED"

    def test_degraded_cloud_serves_locally_without_broadcast(
            self, mem_cloud, monkeypatch):
        from h2o3_tpu import scoring

        supervisor.degrade("peer went quiet")
        seen = {}

        def no_broadcast(kind, payload):
            raise AssertionError("degraded flush must not broadcast")

        monkeypatch.setattr(oplog, "broadcast", no_broadcast)

        def exec_local(m, entries, local_only=False):
            seen["local_only"] = local_only
            return [("PRED", None)]

        monkeypatch.setattr(scoring, "execute_batch", exec_local)
        ent = self._pending()
        scoring.ScoreBatcher._flush(_FakeKeyed("m"), [ent])
        assert seen["local_only"] is True
        assert ent.error is None and ent.pred == "PRED"
        # local serving forked the coordinator's DKV from the follower's:
        # fresh heartbeats must NOT auto-recover this cloud anymore
        now = time.time()
        for p in (0, 1):
            mem_cloud[f"h2o3/heartbeat/{p}"] = json.dumps({"ts": now,
                                                           "proc": p})
        assert supervisor.evaluate() == supervisor.DEGRADED
        assert "restart the cloud" in supervisor.status()["reason"]


# ---------------------------------------------------------------------------
# REST surface: lifecycle wiring + end-to-end chaos (acceptance criteria)
# ---------------------------------------------------------------------------

def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=30) as r:
        return json.loads(r.read())


def _post(base, path, data):
    body = "&".join(f"{k}={urllib.request.quote(str(v))}"
                    for k, v in data.items()).encode()
    req = urllib.request.Request(base + path, data=body, method="POST")
    with urllib.request.urlopen(req, timeout=60) as r:
        return json.loads(r.read())


def _wait_job(base, key, timeout_s=60.0):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        j = _get(base, f"/3/Jobs/{urllib.request.quote(key, safe='')}")
        j = j["jobs"][0]
        if j["status"] not in ("CREATED", "RUNNING"):
            return j
        time.sleep(0.05)
    raise AssertionError(f"job {key} still running after {timeout_s}s")


@pytest.fixture()
def chaos_csv(tmp_path):
    import numpy as np

    rng = np.random.default_rng(0)
    p = tmp_path / "chaos.csv"
    with open(p, "w") as f:
        f.write("x,y\n")
        for _ in range(200):
            x = rng.normal()
            f.write(f"{x:.5f},{'YN'[int(x > 0)]}\n")
    return str(p)


class TestRestSupervision:
    def test_heartbeat_and_supervisor_autostart_multiprocess(
            self, cl, mem_cloud, monkeypatch):
        """Satellite 3 regression: start_server on a multi-process cloud
        wires the beater + supervisor; stop() tears both down."""
        from h2o3_tpu.api.server import start_server

        monkeypatch.setenv("H2O_TPU_SUPERVISE_INTERVAL_S", "0.05")
        srv = start_server(port=0)
        try:
            hb, sup = srv.heartbeat_thread, srv.supervisor
            assert hb is not None and sup is not None
            deadline = time.time() + 10
            while time.time() < deadline and \
                    "h2o3/heartbeat/0" not in mem_cloud:
                time.sleep(0.02)
            assert "h2o3/heartbeat/0" in mem_cloud    # /3/Cloud liveness
            assert _get(f"http://127.0.0.1:{srv.port}",
                        "/3/CloudStatus")["state"] == "HEALTHY"
        finally:
            srv.stop()
        assert srv.heartbeat_thread is None and srv.supervisor is None
        assert hb._stop.is_set() and sup._stop.is_set()

    def test_no_duplicate_beater_when_runtime_already_beats(
            self, cl, mem_cloud, monkeypatch):
        """On a real multi-process cloud core.runtime already runs the
        beater on every process — start_server must not stack a second
        one on the coordinator."""
        from h2o3_tpu.api.server import start_server
        from h2o3_tpu.core import runtime

        monkeypatch.setenv("H2O_TPU_SUPERVISE_INTERVAL_S", "3600")
        sentinel = failure.HeartbeatThread(interval_s=3600)
        monkeypatch.setattr(runtime._CLUSTER, "_heartbeat", sentinel)
        srv = start_server(port=0)
        try:
            assert srv.heartbeat_thread is None       # runtime's suffices
            assert srv.supervisor is not None
        finally:
            srv.stop()

    def test_restarted_cloud_server_rederives_state_from_evidence(
            self, cl, mem_cloud, monkeypatch):
        """A re-started cloud must not inherit the previous incarnation's
        sticky FAILED verdict — but persistent error keys in the KV must
        immediately re-derive it."""
        from h2o3_tpu.api.server import start_server

        monkeypatch.setenv("H2O_TPU_SUPERVISE_INTERVAL_S", "3600")
        supervisor.fail("old incarnation crashed", "stale trace")
        srv = start_server(port=0)          # fresh KV: verdict cleared
        try:
            assert supervisor.state() == supervisor.HEALTHY
        finally:
            srv.stop()
        # same restart but the error key SURVIVED (same coordination
        # service): the synchronous first evaluate() re-fails immediately
        supervisor.fail("old incarnation crashed", "stale trace")
        mem_cloud["oplog/error/2"] = json.dumps({"kind": "train",
                                                 "trace": "still here"})
        srv = start_server(port=0)
        try:
            assert supervisor.state() == supervisor.FAILED
            assert "op 2" in supervisor.status()["reason"]
        finally:
            srv.stop()

    def test_single_process_server_skips_supervision_threads(self, cl):
        from h2o3_tpu.api.server import start_server

        srv = start_server(port=0)
        try:
            assert srv.heartbeat_thread is None and srv.supervisor is None
            out = _get(f"http://127.0.0.1:{srv.port}", "/3/Cloud")
            assert out["cloud_status"] == "HEALTHY"
        finally:
            srv.stop()

    def test_replay_crash_fails_job_with_remote_trace(self, cl, mem_cloud,
                                                      monkeypatch,
                                                      chaos_csv):
        """Acceptance: an injected follower replay crash surfaces on the
        coordinator as a FAILED job carrying the remote traceback within
        the ack timeout — the pre-supervision oplog would have sat in the
        unbounded publish/turn waits forever."""
        from h2o3_tpu.api.server import start_server

        monkeypatch.setenv("H2O_TPU_OP_ACK_TIMEOUT_S", "20")
        monkeypatch.setenv("H2O_TPU_SUPERVISE_INTERVAL_S", "0.05")
        srv = start_server(port=0)
        base = f"http://127.0.0.1:{srv.port}"

        def doomed_follower():
            # the injected crash is the POINT — die like a real follower
            # would, without tripping pytest's unhandled-thread warning
            with pytest.raises(failure.InjectedFault):
                oplog.follower_loop(idle_timeout_s=30)

        follower = threading.Thread(target=doomed_follower, daemon=True)
        try:
            with failure.inject("oplog.replay", times=1):
                follower.start()
                out = _post(base, "/3/Parse",
                            {"source_frames": f'["{chaos_csv}"]',
                             "destination_frame": "chaos.hex"})
                job = _wait_job(base, out["job"]["key"]["name"])
            assert job["status"] == "FAILED"
            assert "injected fault: oplog.replay" in (job["exception"] or "")
            assert "remote traceback" in (job["exception"] or "")
            # the supervisor folded the error key into cloud state ...
            st = _get(base, "/3/CloudStatus")
            assert st["state"] == "FAILED"
            assert st["oplog_errors"] and \
                "oplog.replay" in st["oplog_errors"][0]["trace"]
            cloud = _get(base, "/3/Cloud")
            assert cloud["cloud_status"] == "FAILED"
            assert cloud["cloud_healthy"] is False
            # ... and new multi-process ops are refused fast with a 503
            t0 = time.monotonic()
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(base, "/3/Parse",
                      {"source_frames": f'["{chaos_csv}"]',
                       "destination_frame": "chaos2.hex"})
            assert ei.value.code == 503
            assert time.monotonic() - t0 < 10.0
            body = json.loads(ei.value.read())
            assert "FAILED" in body.get("msg", "")
        finally:
            srv.stop()
            follower.join(timeout=5)
            # drain the failed job's worker thread (the supervisor marks
            # the Job FAILED while its thread may still be mid-parse) so
            # no straggler outlives this test's cloud epoch
            from h2o3_tpu.core.dkv import DKV

            jobj = DKV.get(job["key"]["name"]) if "job" in locals() else None
            th = getattr(jobj, "_thread", None)
            if th is not None:
                th.join(timeout=30)

    def test_cloudstatus_reflects_stale_heartbeat_transitions(
            self, cl, mem_cloud, monkeypatch):
        """Acceptance: GET /3/CloudStatus walks HEALTHY -> DEGRADED ->
        HEALTHY as a peer's heartbeat goes stale and returns."""
        from h2o3_tpu.api.server import start_server

        monkeypatch.setenv("H2O_TPU_SUPERVISE_INTERVAL_S", "3600")
        srv = start_server(port=0)          # evaluate() driven by the test
        base = f"http://127.0.0.1:{srv.port}"
        try:
            now = time.time()
            mem_cloud["h2o3/heartbeat/1"] = json.dumps({"ts": now,
                                                        "proc": 1})
            supervisor.evaluate()
            assert _get(base, "/3/CloudStatus")["state"] == "HEALTHY"
            mem_cloud["h2o3/heartbeat/1"] = json.dumps({"ts": now - 999,
                                                        "proc": 1})
            supervisor.evaluate()
            st = _get(base, "/3/CloudStatus")
            assert st["state"] == "DEGRADED"
            assert "stale heartbeat" in st["reason"]
            assert any(not r["healthy"] for r in st["process_health"])
            mem_cloud["h2o3/heartbeat/1"] = json.dumps({"ts": time.time(),
                                                        "proc": 1})
            supervisor.evaluate()
            st = _get(base, "/3/CloudStatus")
            assert st["state"] == "HEALTHY"
            trans = [(t["from"], t["to"]) for t in st["transitions"]]
            assert ("HEALTHY", "DEGRADED") in trans
            assert ("DEGRADED", "HEALTHY") in trans
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# checkpoint + compaction (ISSUE 4 tentpole 1)
# ---------------------------------------------------------------------------

def _live_oplog_keys(kv):
    slots = [k for k in kv if re.fullmatch(r"oplog/\d+", k)]
    acks = [k for k in kv if k.startswith("oplog/ack/")]
    return slots, acks


class TestCheckpointCompaction:
    def test_footprint_stays_o_interval_after_many_ops(self, mem_cloud,
                                                       monkeypatch,
                                                       tmp_path):
        """Acceptance: after N >> interval acknowledged ops, live oplog/*
        keys stay O(interval) — the acked prefix is truncated at every
        checkpoint instead of living in the KV forever."""
        monkeypatch.setenv("H2O_TPU_OPLOG_CHECKPOINT_OPS", "8")
        monkeypatch.setenv("H2O_TPU_OPLOG_CKPT_DIR", str(tmp_path))
        t = threading.Thread(
            target=lambda: oplog.follower_loop(idle_timeout_s=15),
            daemon=True)
        t.start()
        for i in range(50):
            seq = oplog.broadcast("noop", {"i": i})
            with oplog.turn(seq, timeout_s=15):
                pass
        slots, acks = _live_oplog_keys(mem_cloud)
        # 50 user ops (+ interleaved checkpoint ops) went through; only
        # the un-truncated tail may remain
        assert len(slots) <= 2 * 8, sorted(slots)
        assert len(acks) <= 2 * 8, sorted(acks)
        assert ckpt.latest_seq() is not None and ckpt.latest_seq() >= 32
        # checkpoint records themselves are GCd (H2O_TPU_OPLOG_CKPT_KEEP)
        assert len([k for k in mem_cloud
                    if k.startswith("oplog/ckpt/")]) <= ckpt.keep_ckpts()
        assert supervisor.evaluate() != supervisor.FAILED
        oplog.publish("shutdown", {})
        t.join(timeout=15)
        assert not t.is_alive()

    def test_checkpoint_restores_dkv_control_plane(self, cl, mem_cloud,
                                                   monkeypatch, tmp_path):
        """A checkpoint carries the DKV control plane: an object installed
        before the snapshot comes back via load_latest (the rejoin
        restore path), and the resume cursor points past the ckpt op."""
        from h2o3_tpu.core.dkv import DKV

        monkeypatch.setenv("H2O_TPU_OPLOG_CHECKPOINT_OPS", "2")
        monkeypatch.setenv("H2O_TPU_OPLOG_CKPT_DIR", str(tmp_path))
        DKV.put("ckpt_probe_key", {"hello": 1})
        t = threading.Thread(
            target=lambda: oplog.follower_loop(idle_timeout_s=10),
            daemon=True)
        t.start()
        try:
            for i in range(3):
                seq = oplog.broadcast("noop", {"i": i})
                with oplog.turn(seq, timeout_s=10):
                    pass
            assert ckpt.latest_seq() is not None
            DKV.remove("ckpt_probe_key")
            next_seq, snap = ckpt.load_latest()
            assert DKV.get("ckpt_probe_key") == {"hello": 1}
            assert next_seq == ckpt.latest_seq() + 1
            assert "ckpt_probe_key" in snap["dkv"]["objects"]
        finally:
            DKV.remove("ckpt_probe_key")
            oplog.publish("shutdown", {})
            t.join(timeout=10)

    def test_checkpoint_failure_never_fails_the_user_op(self, mem_cloud,
                                                        monkeypatch,
                                                        tmp_path):
        """A failed snapshot write is logged and retried at the next
        interval — the op that crossed the threshold still succeeds."""
        monkeypatch.setenv("H2O_TPU_OPLOG_CHECKPOINT_OPS", "2")
        monkeypatch.setenv("H2O_TPU_OPLOG_CKPT_DIR", str(tmp_path))
        t = threading.Thread(
            target=lambda: oplog.follower_loop(idle_timeout_s=10),
            daemon=True)
        t.start()
        try:
            with failure.inject("ckpt.write", times=1):
                for i in range(2):
                    seq = oplog.broadcast("noop", {"i": i})
                    with oplog.turn(seq, timeout_s=10):
                        pass          # 2nd turn triggers the doomed ckpt
            assert ckpt.latest_seq() is None          # write was injected
            for i in range(2):
                seq = oplog.broadcast("noop", {"i": i})
                with oplog.turn(seq, timeout_s=10):
                    pass
            assert ckpt.latest_seq() is not None      # next interval landed
        finally:
            oplog.publish("shutdown", {})
            t.join(timeout=10)

    def test_async_checkpoint_does_not_block_crossing_op(self, mem_cloud,
                                                         monkeypatch,
                                                         tmp_path):
        """With H2O_TPU_OPLOG_CKPT_ASYNC (the production default) the user
        op that crosses the interval threshold returns while the snapshot
        is still in flight on the background thread; the checkpoint and
        truncation land shortly after (ckpt.wait_idle joins them)."""
        monkeypatch.setenv("H2O_TPU_OPLOG_CHECKPOINT_OPS", "2")
        monkeypatch.setenv("H2O_TPU_OPLOG_CKPT_ASYNC", "1")
        monkeypatch.setenv("H2O_TPU_OPLOG_CKPT_DIR", str(tmp_path))
        gate = threading.Event()
        real_write = ckpt.write_checkpoint

        def gated_write(seq):
            gate.wait(10)              # park the snapshot until released
            return real_write(seq)

        monkeypatch.setattr(ckpt, "write_checkpoint", gated_write)
        t = threading.Thread(
            target=lambda: oplog.follower_loop(idle_timeout_s=15),
            daemon=True)
        t.start()
        try:
            for i in range(2):
                seq = oplog.broadcast("noop", {"i": i})
                with oplog.turn(seq, timeout_s=15):
                    pass               # 2nd op's turn tail spawns the ckpt
            # the crossing op is DONE while the snapshot is still parked
            # behind the gate: async checkpointing never billed it
            assert ckpt.latest_seq() is None
            gate.set()
            assert ckpt.wait_idle(timeout_s=15)
            assert ckpt.latest_seq() == 2            # ops 0,1 then ckpt op
            slots, acks = _live_oplog_keys(mem_cloud)
            assert not slots and not acks            # prefix truncated
        finally:
            gate.set()
            oplog.publish("shutdown", {})
            t.join(timeout=15)

    def test_ops_acked_during_inflight_ckpt_still_count(self, mem_cloud,
                                                        monkeypatch,
                                                        tmp_path):
        """User ops acknowledged while an async checkpoint is still
        truncating must count toward the NEXT interval — dropping them
        would stretch the effective interval past
        H2O_TPU_OPLOG_CHECKPOINT_OPS under load and break the documented
        O(interval) bound on live oplog keys."""
        monkeypatch.setenv("H2O_TPU_OPLOG_CHECKPOINT_OPS", "2")
        monkeypatch.setenv("H2O_TPU_OPLOG_CKPT_ASYNC", "1")
        monkeypatch.setenv("H2O_TPU_OPLOG_CKPT_DIR", str(tmp_path))
        gate, entered = threading.Event(), threading.Event()
        real_trunc = ckpt.truncate_through

        def gated_trunc(seq):
            entered.set()
            gate.wait(10)              # park the compaction tail
            return real_trunc(seq)

        monkeypatch.setattr(ckpt, "truncate_through", gated_trunc)
        t = threading.Thread(
            target=lambda: oplog.follower_loop(idle_timeout_s=15),
            daemon=True)
        t.start()
        try:
            for i in range(2):
                seq = oplog.broadcast("noop", {"i": i})
                with oplog.turn(seq, timeout_s=15):
                    pass
            assert entered.wait(10)    # ckpt op acked, truncation parked
            # a full interval's worth of user ops acks while the first
            # checkpoint is still in flight
            for i in range(2):
                seq = oplog.broadcast("noop", {"i": i})
                with oplog.turn(seq, timeout_s=15):
                    pass
            gate.set()
            assert ckpt.wait_idle(timeout_s=15)
            first = ckpt.latest_seq()
            assert first == 2                        # ops 0,1 then ckpt op
            # the next acked op crosses the (already-reached) threshold:
            # checkpoint 2 fires — the in-flight window lost no counts
            seq = oplog.broadcast("noop", {"final": True})
            with oplog.turn(seq, timeout_s=15):
                pass
            assert ckpt.wait_idle(timeout_s=15)
            assert ckpt.latest_seq() > first
        finally:
            gate.set()
            oplog.publish("shutdown", {})
            t.join(timeout=15)

    def test_demoted_excoordinator_checkpoint_refuses(self, mem_cloud):
        """A stalled ex-coordinator's in-flight checkpoint thread resuming
        after a standby won the epoch must not publish (at a stale seq) or
        truncate the shared KV — same gate broadcast() enforces."""
        oplog._DEMOTED = True
        assert ckpt.checkpoint_now() is None
        assert oplog.current_seq() == 0              # nothing published

    def test_truncation_mid_wait_is_not_an_ack_timeout(self, mem_cloud,
                                                       monkeypatch,
                                                       tmp_path):
        """A wait_acks(N) poller racing the compactor must treat a
        truncated prefix as satisfied: truncation only runs after the
        covering checkpoint op was fully acked, so op N's vanished ack
        records prove success — timing out (and degrading the cloud) for
        a fully-acknowledged op would be a false alarm."""
        monkeypatch.setenv("H2O_TPU_OPLOG_CKPT_DIR", str(tmp_path))
        seq = oplog.publish("noop", {})
        oplog._ack(seq, json.loads(mem_cloud[f"oplog/{seq}"])["op_id"])
        # the compactor truncates the acked prefix between two of the
        # waiter's polls: the ack record disappears
        ckpt.truncate_through(seq)
        assert f"oplog/ack/{seq}/0" not in mem_cloud
        t0 = time.monotonic()
        oplog.wait_acks(seq, timeout_s=5)            # returns, no raise
        assert time.monotonic() - t0 < 2.0
        assert supervisor.state() == supervisor.HEALTHY


# ---------------------------------------------------------------------------
# incarnations + follower readmission (ISSUE 4 tentpole 2)
# ---------------------------------------------------------------------------

class TestIncarnations:
    def test_stale_incarnation_ack_rejected(self, mem_cloud):
        """A proc that rejoined at incarnation 1 must ack with inc >= 1:
        an ack its dead predecessor (inc 0) left behind — even with the
        RIGHT op identity token — cannot satisfy wait_acks."""
        oplog._write_rejoin(0, 1, "caught_up", 0)
        seq = oplog.publish("noop", {})
        op_id = json.loads(mem_cloud[f"oplog/{seq}"])["op_id"]
        mem_cloud[f"oplog/ack/{seq}/0"] = json.dumps(
            {"proc": 0, "ts": time.time(), "op_id": op_id, "inc": 0})
        with pytest.raises(failure.CloudUnhealthyError, match="0/1"):
            oplog.wait_acks(seq, timeout_s=0.3)
        # the fresh incarnation's ack does satisfy it
        mem_cloud[f"oplog/ack/{seq}/0"] = json.dumps(
            {"proc": 0, "ts": time.time(), "op_id": op_id, "inc": 1})
        oplog.wait_acks(seq, timeout_s=5)

    def test_heartbeat_carries_incarnation(self, mem_cloud):
        failure.set_incarnation(3)
        failure.heartbeat()
        rows = failure.cluster_health()
        assert rows[0]["incarnation"] == 3


class TestRejoinRecovery:
    def test_full_loop_crash_rejoin_recover_new_op(self, cl, mem_cloud,
                                                   monkeypatch, tmp_path):
        """Acceptance (ISSUE 4): follower replay crash -> cloud FAILED ->
        follower rejoins from the checkpoint (fresh incarnation, suffix
        re-replayed, error evidence superseded) -> supervisor walks
        FAILED -> RECOVERING -> HEALTHY, reported via GET /3/CloudStatus
        -> a NEW multi-process op (oplog broadcast) succeeds."""
        from h2o3_tpu.api.server import start_server

        monkeypatch.setenv("H2O_TPU_OPLOG_CHECKPOINT_OPS", "4")
        monkeypatch.setenv("H2O_TPU_OPLOG_CKPT_DIR", str(tmp_path))
        monkeypatch.setenv("H2O_TPU_OP_ACK_TIMEOUT_S", "15")
        monkeypatch.setenv("H2O_TPU_SUPERVISE_INTERVAL_S", "3600")
        srv = start_server(port=0)
        base = f"http://127.0.0.1:{srv.port}"
        try:
            # phase 1: healthy op stream deep enough to land a checkpoint
            def doomed():
                with pytest.raises(failure.InjectedFault):
                    oplog.follower_loop(idle_timeout_s=15)

            t1 = threading.Thread(target=doomed, daemon=True)
            t1.start()
            for i in range(5):
                seq = oplog.broadcast("noop", {"i": i})
                with oplog.turn(seq, timeout_s=15):
                    pass
            assert ckpt.latest_seq() is not None
            # phase 2: follower killed mid-replay -> FAILED
            with failure.inject("oplog.replay", times=1):
                seq = oplog.broadcast("noop", {"crash": True})
                with pytest.raises(failure.CloudUnhealthyError,
                                   match="injected fault"):
                    with oplog.turn(seq, timeout_s=15):
                        pass
            t1.join(timeout=10)
            assert supervisor.state() == supervisor.FAILED
            assert _get(base, "/3/CloudStatus")["state"] == "FAILED"
            with pytest.raises(failure.CloudUnhealthyError):
                oplog.broadcast("noop", {})          # refused while down
            # phase 3: the follower restarts and rejoins from the ckpt
            cursor = oplog.rejoin()
            assert cursor == oplog.current_seq()     # crashed op included
            assert failure.incarnation() == 1
            assert not oplog.error_records()         # evidence superseded
            assert supervisor.evaluate() == supervisor.HEALTHY
            st = _get(base, "/3/CloudStatus")
            assert st["state"] == "HEALTHY"
            trans = [(t["from"], t["to"]) for t in st["transitions"]]
            assert ("FAILED", "RECOVERING") in trans
            assert ("RECOVERING", "HEALTHY") in trans
            assert st["checkpoint_seq"] is not None
            rows = {r["process"]: r for r in st["process_health"]}
            assert rows[0]["incarnation"] == 1
            assert rows[0]["ack_lag"] == 0
            assert st["rejoins"][0]["phase"] == "caught_up"
            # phase 4: NEW multi-process ops are accepted and complete
            t2 = threading.Thread(
                target=lambda: oplog.follower_loop(idle_timeout_s=15,
                                                   start_seq=cursor),
                daemon=True)
            t2.start()
            seq = oplog.broadcast("noop", {"post_recovery": True})
            with oplog.turn(seq, timeout_s=15):
                pass                                  # acked by inc 1
            oplog.publish("shutdown", {})
            t2.join(timeout=15)
            assert not t2.is_alive()
        finally:
            srv.stop()

    def test_rejoin_crash_records_error_and_refails(self, mem_cloud,
                                                    monkeypatch, tmp_path):
        """A follower killed AGAIN mid-rejoin-replay surfaces the true
        story (error key) and the cloud re-FAILs instead of reporting a
        phantom recovery."""
        monkeypatch.setenv("H2O_TPU_OPLOG_CKPT_DIR", str(tmp_path))
        oplog.publish("noop", {})
        supervisor.fail("follower died", "tb")
        with failure.inject("oplog.rejoin.replay", times=1):
            with pytest.raises(failure.InjectedFault):
                oplog.rejoin()
        assert oplog.error_records()
        assert supervisor.evaluate() == supervisor.FAILED
        # second restart completes the rejoin; cloud recovers
        cursor = oplog.rejoin()
        assert cursor == 1
        assert supervisor.evaluate() == supervisor.HEALTHY
        assert failure.incarnation() == 2

    def test_recovering_waits_for_caught_up_phase(self, mem_cloud):
        """A rejoin record still in phase 'replaying' moves the cloud to
        RECOVERING but NOT to HEALTHY — new ops stay refused until the
        suffix replay completes."""
        supervisor.fail("follower died", "tb")
        failure.set_incarnation(1)
        failure.heartbeat()
        oplog._write_rejoin(0, 1, "replaying", 0)
        assert supervisor.evaluate() == supervisor.RECOVERING
        with pytest.raises(failure.CloudUnhealthyError, match="RECOVERING"):
            oplog.broadcast("noop", {})
        oplog._write_rejoin(0, 1, "caught_up", 0)
        assert supervisor.evaluate() == supervisor.HEALTHY

    def test_rejoin_gate_is_incarnation_not_wallclock(self, mem_cloud):
        """FAILED -> RECOVERING is gated on an incarnation STRICTLY newer
        than the one on record at fail() time: a leftover rejoin record
        from a previous recovery must not re-trigger the arc, and a
        genuinely fresh rejoin stamped by a skewed clock (ts 'before' the
        failure) must not be blocked by it."""
        # a previous recovery left proc 0's inc-1 rejoin record standing
        failure.set_incarnation(1)
        failure.heartbeat()
        oplog._write_rejoin(0, 1, "caught_up", 0)
        supervisor.fail("follower died again", "tb")
        assert supervisor.evaluate() == supervisor.FAILED   # stale record
        # the restarted follower rejoins at inc 2, but its host clock runs
        # an hour behind the coordinator's
        failure.set_incarnation(2)
        failure.heartbeat()
        oplog._write_rejoin(0, 2, "caught_up", 0)
        k = f"{oplog._REJOIN_PREFIX}0"
        rec = json.loads(mem_cloud[k])
        rec["ts"] -= 3600.0
        mem_cloud[k] = json.dumps(rec)
        assert supervisor.evaluate() == supervisor.HEALTHY

    def test_second_real_restart_rejoins_strictly_newer(self, mem_cloud,
                                                        monkeypatch,
                                                        tmp_path):
        """A REAL process restart boots with the local incarnation counter
        at 0. The second crash/restart cycle must still produce an
        incarnation strictly newer than the one on cloud record at
        failure time — otherwise the FAILED -> RECOVERING gate can never
        be satisfied again and the cloud is permanently down."""
        monkeypatch.setenv("H2O_TPU_OPLOG_CKPT_DIR", str(tmp_path))
        oplog.publish("noop", {})
        supervisor.fail("follower died", "tb")
        oplog.rejoin()
        assert failure.incarnation() == 1
        assert supervisor.evaluate() == supervisor.HEALTHY
        # crash again; the restarted process forgot its local counter
        supervisor.fail("follower died again", "tb2")
        failure.set_incarnation(0)
        oplog.rejoin()
        assert failure.incarnation() == 2    # seeded from cloud evidence
        assert supervisor.evaluate() == supervisor.HEALTHY

    def test_recovering_blocked_by_never_beaten_process(self, mem_cloud):
        """RECOVERING -> HEALTHY is also blocked by a peer that died
        leaving NO heartbeat row — same never-beat signal as the degrade
        path: absence past the staleness window."""
        supervisor.fail("follower 0 replay crashed", "tb")
        failure.set_incarnation(1)
        failure.heartbeat()
        oplog._write_rejoin(0, 1, "caught_up", 0)
        supervisor._FIRST_EVAL_TS = time.time() - 3600   # long past grace
        assert supervisor.evaluate() == supervisor.RECOVERING
        # the absent process finally beats: recovery completes
        mem_cloud["h2o3/heartbeat/1"] = json.dumps(
            {"ts": time.time(), "proc": 1, "inc": 0})
        assert supervisor.evaluate() == supervisor.HEALTHY

    def test_recovering_blocked_by_other_stale_process(self, mem_cloud):
        """RECOVERING -> HEALTHY demands the WHOLE cluster be live, not
        just the processes with rejoin records: a second follower that
        went silent during the outage (stale beat, no rejoin of its own)
        must keep new ops refused instead of letting each one burn the
        full ack timeout against a dead peer."""
        supervisor.fail("follower 0 replay crashed", "tb")
        mem_cloud["h2o3/heartbeat/1"] = json.dumps(
            {"ts": time.time() - 3600, "proc": 1, "inc": 0})
        failure.set_incarnation(1)
        failure.heartbeat()
        oplog._write_rejoin(0, 1, "caught_up", 0)
        assert supervisor.evaluate() == supervisor.RECOVERING
        with pytest.raises(failure.CloudUnhealthyError):
            oplog.broadcast("noop", {})
        # the silent process comes back: recovery completes
        mem_cloud["h2o3/heartbeat/1"] = json.dumps(
            {"ts": time.time(), "proc": 1, "inc": 0})
        assert supervisor.evaluate() == supervisor.HEALTHY

    def test_jobs_failed_once_stay_failed_across_recovery(self, mem_cloud):
        """Jobs in flight when the cloud died are failed ONCE (externally,
        with the remote trace); a later recovery never resurrects them."""
        from h2o3_tpu.core.job import Job

        ev = threading.Event()
        job = Job(description="in flight at failure")
        job.start(lambda j: ev.wait(10), background=True)
        try:
            supervisor.fail("follower died", "RemoteBoom")
            assert job.status == Job.FAILED
            assert job.failed_externally is True
        finally:
            ev.set()
        failure.set_incarnation(1)
        failure.heartbeat()
        oplog._write_rejoin(0, 1, "caught_up", 0)
        assert supervisor.evaluate() == supervisor.HEALTHY
        assert job.status == Job.FAILED              # still failed
        assert "RemoteBoom" in job.exception


# ---------------------------------------------------------------------------
# standby-coordinator handoff (ISSUE 4 tentpole 3)
# ---------------------------------------------------------------------------

@pytest.fixture()
def standby_cloud(monkeypatch):
    """Simulated 2-process cloud where THIS process (jax index 0) is a
    FOLLOWER: the epoch record names process 1 as leader. is_coordinator
    stays REAL (leader-based) so the election can flip it."""
    with D.memory_kv() as kv:
        monkeypatch.setattr(D, "process_count", lambda: 2)
        monkeypatch.setenv("H2O_TPU_RETRY_BASE_MS", "1")
        monkeypatch.setenv("H2O_TPU_OP_ACK_TIMEOUT_S", "30")
        monkeypatch.setenv("H2O_TPU_OPLOG_CHECKPOINT_OPS", "0")
        monkeypatch.setenv("H2O_TPU_OPLOG_CKPT_ASYNC", "0")
        monkeypatch.setenv("H2O_TPU_AUTO_RECOVER", "0")
        failure.set_incarnation(0)
        D.write_epoch_record(0, 1)
        D.set_leader(1, 0)
        oplog._DEMOTED = False
        oplog.reset()
        supervisor.reset()
        yield kv
    failure.set_incarnation(0)
    D.reset_leadership()
    oplog._DEMOTED = False
    oplog.reset()
    supervisor.reset()


def _gbm_and_frame(seed=7):
    import numpy as np

    from h2o3_tpu.core.frame import Column, Frame
    from h2o3_tpu.models.tree.gbm import GBM

    rng = np.random.default_rng(seed)
    n = 400
    fr = Frame()
    x1 = rng.standard_normal(n)
    x2 = rng.standard_normal(n)
    fr.add("x1", Column.from_numpy(x1))
    fr.add("x2", Column.from_numpy(x2))
    y = np.where(x1 - 0.5 * x2 > 0, "Y", "N")
    fr.add("y", Column.from_numpy(y, ctype="enum"))
    model = GBM(ntrees=5, max_depth=3, seed=1).train(y="y",
                                                     training_frame=fr)
    score = Frame()
    score.add("x1", Column.from_numpy(rng.standard_normal(64)))
    score.add("x2", Column.from_numpy(rng.standard_normal(64)))
    return model, score


class TestHandoff:
    def test_election_refused_inside_grace_or_when_not_winner(
            self, cl, standby_cloud, monkeypatch):
        monkeypatch.setenv("H2O_TPU_ELECTION_GRACE_S", "60")
        now = time.time()
        # the leader is still beating: no election
        standby_cloud["h2o3/heartbeat/1"] = json.dumps({"ts": now,
                                                        "proc": 1})
        failure.heartbeat()
        with pytest.raises(oplog.ElectionLost, match="inside the election"):
            oplog.assume_coordination()
        assert not D.is_coordinator()
        # the leader itself never runs an election
        D.set_leader(0, 0)
        D.write_epoch_record(0, 0)
        with pytest.raises(oplog.ElectionLost, match="already leads"):
            oplog.assume_coordination()

    def test_follower_assumes_epoch_and_serves_scoring_bitwise(
            self, cl, standby_cloud, monkeypatch, tmp_path):
        """Acceptance (ISSUE 4): the coordinator dies with a score_batch
        op in flight; the surviving follower (which replayed + acked it)
        wins the deterministic election, seals the oplog past it, writes
        epoch 1, re-binds the REST server, and serves a scoring request
        whose predictions are BITWISE-identical to the pre-handoff
        replay's."""
        import numpy as np

        from h2o3_tpu import scoring
        from h2o3_tpu.api import server as api_server
        from h2o3_tpu.core.dkv import DKV

        monkeypatch.setenv("H2O_TPU_ELECTION_GRACE_S", "5")
        monkeypatch.setenv("H2O_TPU_SUPERVISE_INTERVAL_S", "3600")
        model, score_fr = _gbm_and_frame()
        DKV.put(str(score_fr.key), score_fr)
        # the old coordinator published a score_batch op; we are the
        # follower replaying it (the in-flight op at the handoff boundary)
        standby_cloud["oplog/0"] = json.dumps({
            "kind": "score_batch", "op_id": "inflight-op",
            "payload": {"model": str(model.key),
                        "requests": [{"frame": str(score_fr.key),
                                      "destination_frame": "pred_before",
                                      "with_metrics": False}]}})
        with pytest.raises(TimeoutError):
            oplog.follower_loop(idle_timeout_s=0.3)   # replays op 0, acks
        assert "oplog/ack/0/0" in standby_cloud
        before = DKV.get("pred_before")
        assert before is not None
        before_vals = {c: np.asarray(before.col(c).data).copy()
                       for c in before.names}
        # the coordinator goes silent past the election grace
        standby_cloud["h2o3/heartbeat/1"] = json.dumps(
            {"ts": time.time() - 999, "proc": 1})
        failure.heartbeat()
        srv = api_server.assume_coordination(port=0, caught_up_seq=1)
        try:
            assert D.is_coordinator() and D.epoch() == 1
            rec = D.epoch_record()
            assert rec["epoch"] == 1 and rec["leader"] == 0
            sealed = json.loads(standby_cloud["oplog/sealed/0"])
            assert sealed["next_seq"] == 1           # past the acked op
            base = f"http://127.0.0.1:{srv.port}"
            st = _get(base, "/3/CloudStatus")
            assert st["epoch"] == 1 and st["leader"] == 0
            # the dead ex-coordinator degrades the cloud, but scoring is
            # the surface that keeps serving (coordinator-local)
            out = _post(base, f"/3/Predictions/models/"
                        f"{urllib.request.quote(str(model.key), safe='')}"
                        f"/frames/"
                        f"{urllib.request.quote(str(score_fr.key), safe='')}",
                        {"predictions_frame": "pred_after"})
            after = DKV.get(out["predictions_frame"]["name"])
            assert after is not None
            for c in before.names:
                av = np.asarray(after.col(c).data)
                bv = before_vals[c]
                assert np.array_equal(av[: len(bv)], bv[: len(av)]), c
        finally:
            srv.stop()
            DKV.remove("pred_before")
            DKV.remove("pred_after")
            DKV.remove(str(score_fr.key))
            DKV.remove(str(model.key))
            scoring.purge()

    def test_returned_ex_coordinator_demotes_on_newer_epoch(
            self, cl, standby_cloud):
        """The old coordinator comes back from a stall to find a standby
        leading a newer epoch: it adopts the record, refuses to run
        multi-process ops, and the supervisor says why."""
        D.set_leader(0, 0)                 # we BELIEVE we lead epoch 0
        D.write_epoch_record(2, 1)         # but proc 1 took epoch 2
        assert oplog.maybe_demote() is not None
        assert not D.is_coordinator() and D.epoch() == 2
        with pytest.raises(failure.CloudUnhealthyError, match="demoted"):
            oplog.broadcast("noop", {})
        assert "demoted" in supervisor.status()["reason"]

    def test_concurrent_election_loser_stands_down(self, cl, standby_cloud,
                                                   monkeypatch):
        """Two standbys race an election and both write epoch 1 (the epoch
        record is a last-writer-wins upsert). The one whose claim was
        overwritten must detect it on the read-back and stand down — NOT
        proceed to serve as a second coordinator under the same epoch."""
        monkeypatch.setenv("H2O_TPU_ELECTION_GRACE_S", "1")
        standby_cloud["h2o3/heartbeat/1"] = json.dumps(
            {"ts": time.time() - 999, "proc": 1})     # old leader dead
        failure.heartbeat()
        real_write = D.write_epoch_record

        def racing_write(epoch_no, leader_proc):
            ok = real_write(epoch_no, leader_proc)
            # a concurrent standby's claim lands on top of ours
            real_write(epoch_no, 2)
            return ok

        monkeypatch.setattr(D, "write_epoch_record", racing_write)
        with pytest.raises(oplog.ElectionLost, match="concurrent election"):
            oplog.assume_coordination()
        # the loser adopted the winner's record and is NOT coordinator
        assert D.leader() == 2 and D.epoch() == 1
        assert not D.is_coordinator()

    def test_same_epoch_leader_overwrite_demotes(self, cl, standby_cloud):
        """Residual split-brain window: both racing standbys pass their
        read-back before the other's overwrite lands, so both briefly
        believe they lead epoch 1. The periodic maybe_demote must catch
        the same-epoch leader mismatch and demote the overwritten one."""
        D.set_leader(0, 1)                 # we BELIEVE we lead epoch 1
        D.write_epoch_record(1, 2)         # but proc 2's claim won the KV
        assert oplog.maybe_demote() is not None
        assert not D.is_coordinator()
        assert D.leader() == 2 and D.epoch() == 1
        with pytest.raises(failure.CloudUnhealthyError, match="demoted"):
            oplog.broadcast("noop", {})
        # matching view + record is a no-op (no demotion churn)
        D.write_epoch_record(1, 2)
        assert oplog.maybe_demote() is None


# ---------------------------------------------------------------------------
# satellites: typed shard error + fetch_remote retry
# ---------------------------------------------------------------------------

class TestSatelliteFixes:
    def test_shard_unavailable_error_names_owner_and_remedy(self):
        err = failure.ShardUnavailableError("cannot score frame f1",
                                            owners=[1, 2])
        assert isinstance(err, failure.CloudUnhealthyError)   # -> HTTP 503
        assert err.owners == [1, 2]
        assert "process(es) [1, 2]" in str(err)
        assert "Remediation" in str(err) and "rejoin" in str(err)

    def test_fetch_remote_retries_dropped_blob_read(self, mem_cloud,
                                                    monkeypatch):
        """An announced key whose blob read drops once is retried with
        backoff instead of failing the caller on the first blip."""
        import base64
        import pickle

        from h2o3_tpu.core.dkv import DKV

        value = {"model": "meta"}
        blob = base64.b64encode(pickle.dumps(value)).decode()
        mem_cloud["h2o3/dkv/meta/K1"] = json.dumps({"type": "dict",
                                                    "proc": 1,
                                                    "replicated": True})
        calls = {"n": 0}

        def flaky_get(key, timeout_ms=5000):
            if key == "h2o3/dkv/blob/K1":
                calls["n"] += 1
                return None if calls["n"] == 1 else blob
            return mem_cloud.get(key)

        monkeypatch.setattr(D, "kv_get", flaky_get)
        try:
            assert DKV.fetch_remote("K1") == value
            assert calls["n"] == 2                   # dropped once, retried
        finally:
            DKV.remove("K1")

    def test_fetch_remote_unannounced_key_does_not_retry(self, mem_cloud,
                                                         monkeypatch):
        """A key with NO cloud-wide announcement is genuinely absent:
        fetch_remote must not burn the backoff budget on it."""
        from h2o3_tpu.core.dkv import DKV

        calls = {"n": 0}

        def counting_get(key, timeout_ms=5000):
            calls["n"] += 1
            return None

        monkeypatch.setattr(D, "kv_get", counting_get)
        assert DKV.fetch_remote("nope") is None
        assert calls["n"] == 1


# ---------------------------------------------------------------------------
# autonomous recovery watchdog (ISSUE 5): one recovery action per tick,
# zero operator intervention — plus the Job.fail() race fix and the
# checkpoint-dir GC satellites
# ---------------------------------------------------------------------------

from h2o3_tpu.parallel import watchdog  # noqa: E402


class _Killed(Exception):
    """Stands in for the coordinator process dying mid-train."""


class TestWatchdogTicks:
    def test_disabled_by_env_takes_no_action(self, mem_cloud):
        """mem_cloud pins H2O_TPU_AUTO_RECOVER=0 (manual drills): the
        watchdog must observe it and do nothing."""
        watchdog.reset()
        wd = watchdog.Watchdog(interval=3600, follow=False)
        assert wd.tick() == "disabled"
        st = watchdog.status()
        assert st["enabled"] is False and st["ticks"] == 0

    def test_follower_stands_by_while_leader_beats(self, standby_cloud,
                                                   monkeypatch):
        monkeypatch.setenv("H2O_TPU_AUTO_RECOVER", "1")
        monkeypatch.setenv("H2O_TPU_ELECTION_GRACE_S", "60")
        watchdog.reset()
        standby_cloud["h2o3/heartbeat/1"] = json.dumps({"ts": time.time(),
                                                        "proc": 1})
        failure.heartbeat()
        wd = watchdog.Watchdog(interval=3600, follow=False)
        assert wd.tick() == "follower (leader alive)"
        assert not D.is_coordinator()
        assert watchdog.status()["elections"] == 0

    def test_demoted_ex_coordinator_auto_rejoins(self, mem_cloud,
                                                 monkeypatch, tmp_path):
        """A demoted ex-coordinator no longer waits for an operator's
        rejoin(): the next watchdog tick readmits it as a follower."""
        monkeypatch.setenv("H2O_TPU_AUTO_RECOVER", "1")
        monkeypatch.setenv("H2O_TPU_OPLOG_CKPT_DIR", str(tmp_path))
        watchdog.reset()
        oplog._DEMOTED = True
        wd = watchdog.Watchdog(interval=3600, follow=False)
        tag = wd.tick()
        assert tag.startswith("rejoined (demoted")
        assert not oplog.demoted()
        assert failure.incarnation() == 1
        assert oplog.rejoin_records()[0]["phase"] == "caught_up"
        assert watchdog.status()["rejoins"] == 1

    def test_crashed_follower_auto_rejoins(self, standby_cloud,
                                           monkeypatch, tmp_path):
        """A follower whose replay loop crashed is nudged through the
        existing rejoin path instead of staying dead."""
        monkeypatch.setenv("H2O_TPU_AUTO_RECOVER", "1")
        monkeypatch.setenv("H2O_TPU_ELECTION_GRACE_S", "60")
        monkeypatch.setenv("H2O_TPU_OPLOG_CKPT_DIR", str(tmp_path))
        watchdog.reset()
        standby_cloud["h2o3/heartbeat/1"] = json.dumps({"ts": time.time(),
                                                        "proc": 1})
        failure.heartbeat()
        oplog._REPLAY_CRASHED = True
        wd = watchdog.Watchdog(interval=3600, follow=False)
        assert wd.tick() == "rejoined (crashed follower)"
        assert not oplog.replay_crashed()
        assert failure.incarnation() == 1

    def test_no_leader_heartbeat_is_not_silence_during_boot(
            self, standby_cloud, monkeypatch):
        """A follower's watchdog can start before the coordinator's first
        beat lands in the KV: the missing row must not count as
        grace-elapsed silence, or every cloud boot risks a spurious
        takeover."""
        monkeypatch.setenv("H2O_TPU_AUTO_RECOVER", "1")
        monkeypatch.setenv("H2O_TPU_ELECTION_GRACE_S", "60")
        watchdog.reset()
        failure.heartbeat()               # we beat; the leader has no row
        wd = watchdog.Watchdog(interval=3600, follow=False)
        assert wd.tick() == "follower (no leader evidence yet)"
        assert not D.is_coordinator()
        assert watchdog.status()["elections"] == 0

    def test_tick_never_raises(self, standby_cloud, monkeypatch):
        """A transient KV fault inside a tick must not kill recovery for
        good: the error is recorded and the next tick retries."""
        monkeypatch.setenv("H2O_TPU_AUTO_RECOVER", "1")
        watchdog.reset()
        monkeypatch.setattr(oplog, "maybe_demote", lambda: 1 / 0)
        wd = watchdog.Watchdog(interval=3600, follow=False)
        assert wd.tick() == "error"
        assert "ZeroDivisionError" in watchdog.status()["last_error"]

    def test_resume_skips_exhausted_and_non_external_jobs(
            self, mem_cloud, monkeypatch, tmp_path):
        """A job that keeps dying is parked after MAX_ATTEMPTS dispatches,
        and a job the WORKER crashed (not the cloud) is never resurrected
        — only externally-failed jobs with durable progress come back."""
        from h2o3_tpu.core.dkv import DKV
        from h2o3_tpu.core.job import Job

        monkeypatch.setenv("H2O_TPU_AUTO_RECOVER", "1")
        monkeypatch.setenv("H2O_TPU_OPLOG_CKPT_DIR", str(tmp_path))
        watchdog.reset()
        exhausted = Job(description="poisoned")
        exhausted.fail("cloud FAILED")
        exhausted.attempt = watchdog.MAX_ATTEMPTS
        exhausted.resume_spec = {"algo": "gbm", "params": {},
                                 "training_frame": "nope", "y": "y"}
        ckpt.save_job_progress(str(exhausted.key), 4,
                               exhausted.resume_spec, {"phase": "x"})
        local_crash = Job(description="worker bug")
        local_crash.begin()
        local_crash.fail_local("trainer raised")   # NOT failed_externally
        ckpt.save_job_progress(str(local_crash.key), 2,
                               {"algo": "gbm", "params": {},
                                "training_frame": "nope", "y": "y"},
                               {"phase": "x"})
        try:
            assert watchdog.resume_failed_jobs() == []
            assert exhausted.status == Job.FAILED
            assert local_crash.status == Job.FAILED
            # both records were GCd: the parked job is dead for good, the
            # worker-crashed one is the client's to resubmit — neither may
            # leak its (potentially huge) progress file forever
            assert ckpt.load_job_progress(str(exhausted.key)) is None
            assert ckpt.load_job_progress(str(local_crash.key)) is None
            assert ckpt.job_progress_records() == []
        finally:
            for j in (exhausted, local_crash):
                ckpt.delete_job_progress(str(j.key))
                DKV.remove(str(j.key))


class TestJobCheckpointSurvival:
    def test_unpickled_inflight_job_fails_externally(self):
        """A job restored from a control-plane checkpoint has no worker
        thread by construction: restoring it still-RUNNING would park it
        in that state forever (the watchdog rightly leaves RUNNING jobs
        alone). It must come back FAILED+failed_externally — i.e. a
        resume candidate."""
        import pickle as _pickle

        from h2o3_tpu.core.dkv import DKV
        from h2o3_tpu.core.job import Job

        jobs = []
        for st in (Job.CREATED, Job.RUNNING, Job.RESUMING):
            job = Job(description=f"inflight {st}")
            jobs.append(job)
            job.status = st
            back = _pickle.loads(_pickle.dumps(job))
            assert back.status == Job.FAILED and back.failed_externally, st
            assert "in flight" in back.exception
        done = Job(description="done")
        jobs.append(done)
        done.begin()
        done.complete()
        back = _pickle.loads(_pickle.dumps(done))
        assert back.status == Job.DONE and not back.failed_externally
        for j in jobs:
            DKV.remove(str(j.key))


class TestJobFailRace:
    """Satellite: fail() and the worker's own completion interleave — the
    status lock must make the verdict single-writer."""

    def _job(self):
        from h2o3_tpu.core.job import Job

        return Job(description="race probe")

    def _drop(self, *jobs):
        from h2o3_tpu.core.dkv import DKV

        for j in jobs:
            DKV.remove(str(j.key))

    def test_external_fail_beats_completion(self):
        from h2o3_tpu.core.job import Job

        job = self._job()
        try:
            assert job.begin()
            job.fail("cloud FAILED under the build")
            assert not job.complete()           # verdict kept
            assert job.status == Job.FAILED and job.failed_externally
            assert "cloud FAILED" in job.exception
        finally:
            self._drop(job)

    def test_completion_beats_late_external_fail(self):
        from h2o3_tpu.core.job import Job

        job = self._job()
        try:
            assert job.begin()
            assert job.complete()
            job.fail("too late")                # no-op once terminal
            assert job.status == Job.DONE
            assert not job.failed_externally and job.exception is None
        finally:
            self._drop(job)

    def test_begin_refused_after_external_fail(self):
        job = self._job()
        try:
            job.fail("dead before the worker started")
            assert not job.begin()              # don't run on a dead cloud
        finally:
            self._drop(job)

    def test_concurrent_fail_and_complete_single_verdict(self):
        """Race the two writers for real: whatever the interleaving, the
        final state is exactly one of the two consistent verdicts — never
        DONE-with-external-failure or FAILED-without-the-flag."""
        from h2o3_tpu.core.job import Job

        jobs = []
        for _ in range(50):
            job = self._job()
            jobs.append(job)
            assert job.begin()
            barrier = threading.Barrier(2)

            def failer():
                barrier.wait()
                job.fail("external")

            def completer():
                barrier.wait()
                job.complete()

            ts = [threading.Thread(target=failer),
                  threading.Thread(target=completer)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            ok_done = job.status == Job.DONE and not job.failed_externally \
                and job.exception is None
            ok_failed = job.status == Job.FAILED and job.failed_externally \
                and job.exception == "external"
            assert ok_done or ok_failed, (job.status, job.failed_externally)
        self._drop(*jobs)

    def test_stale_dispatch_thread_cannot_clobber_resumed_job(self):
        """A worker wedged in a dead collective outlives the external
        FAILED and the watchdog's restart: when it finally unwinds, its
        late exception (or result) must not touch the resumed dispatch —
        the generation guard in Job.start keeps verdicts single-writer
        across dispatches too."""
        from h2o3_tpu.core.job import Job

        job = self._job()
        try:
            wedge = threading.Event()

            def wedged(j):
                wedge.wait(10)               # "stuck in a dead collective"
                raise RuntimeError("late abort from the old dispatch")

            job.start(wedged, background=True)
            t1 = job._thread
            job.fail("cloud FAILED")         # supervisor's verdict
            assert job.restart(resumed_from_iteration=2)
            go = threading.Event()

            def fresh(j):
                go.wait(10)
                return "model"

            job.start(fresh, background=True)
            wedge.set()                      # stale thread unwinds NOW
            t1.join(timeout=5)
            assert job.status == Job.RUNNING  # untouched by the old thread
            go.set()
            deadline = time.time() + 5
            while job.status == Job.RUNNING and time.time() < deadline:
                time.sleep(0.01)
            assert job.status == Job.DONE and job.attempt == 2
            assert job.result == "model"
        finally:
            self._drop(job)

    def test_restart_has_a_single_winner(self):
        """Two recovery passes racing restart() on one job must produce
        exactly one RESUMING dispatch."""
        from h2o3_tpu.core.job import Job

        job = self._job()
        try:
            job.begin()
            job.fail("cloud FAILED")
            barrier = threading.Barrier(2)
            wins = []

            def racer():
                barrier.wait()
                wins.append(job.restart(resumed_from_iteration=4))

            ts = [threading.Thread(target=racer) for _ in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert sorted(wins) == [False, True]
            assert job.status == Job.RESUMING
            assert job.attempt == 2
            assert job.resumed_from_iteration == 4
        finally:
            self._drop(job)


class TestCheckpointGC:
    def test_keep_knob_bounds_snapshots(self, mem_cloud, monkeypatch,
                                        tmp_path):
        """Only the newest H2O_TPU_OPLOG_CKPT_KEEP snapshots survive a
        newer fully-acked checkpoint — KV records AND files."""
        monkeypatch.setenv("H2O_TPU_OPLOG_CKPT_DIR", str(tmp_path))
        monkeypatch.setenv("H2O_TPU_OPLOG_CKPT_KEEP", "3")
        for s in range(6):
            ckpt.write_checkpoint(s)
        assert [s for s, _ in ckpt.records()] == [3, 4, 5]
        names = sorted(p.name for p in tmp_path.glob("ckpt_*.pkl"))
        assert names == [f"ckpt_{s:012d}.pkl" for s in (3, 4, 5)]

    def test_keep_zero_disables_gc(self, mem_cloud, monkeypatch, tmp_path):
        monkeypatch.setenv("H2O_TPU_OPLOG_CKPT_DIR", str(tmp_path))
        monkeypatch.setenv("H2O_TPU_OPLOG_CKPT_KEEP", "0")
        for s in range(4):
            ckpt.write_checkpoint(s)
        assert [s for s, _ in ckpt.records()] == [0, 1, 2, 3]

    def test_mid_restore_snapshot_is_pinned(self, mem_cloud, monkeypatch,
                                            tmp_path):
        """GC must not delete the snapshot a rejoining follower is
        mid-restore on: its standing rejoin record (phase 'replaying')
        names the restore cursor. Once the rejoin completes, the next
        checkpoint sweeps it."""
        monkeypatch.setenv("H2O_TPU_OPLOG_CKPT_DIR", str(tmp_path))
        monkeypatch.setenv("H2O_TPU_OPLOG_CKPT_KEEP", "1")
        ckpt.write_checkpoint(0)
        # proc 1 starts restoring from ckpt 0 (cursor == its next_seq)
        mem_cloud["oplog/rejoin/1"] = json.dumps(
            {"proc": 1, "inc": 1, "phase": "replaying", "seq": 1,
             "ts": time.time()})
        for s in (1, 2):
            ckpt.write_checkpoint(s)
        assert [s for s, _ in ckpt.records()] == [0, 2]   # 0 pinned, 1 GCd
        assert (tmp_path / "ckpt_000000000000.pkl").exists()
        assert not (tmp_path / "ckpt_000000000001.pkl").exists()
        # the rejoin completes: the pin lifts at the next checkpoint
        mem_cloud["oplog/rejoin/1"] = json.dumps(
            {"proc": 1, "inc": 1, "phase": "caught_up", "seq": 1,
             "ts": time.time()})
        ckpt.write_checkpoint(3)
        assert [s for s, _ in ckpt.records()] == [3]
        assert sorted(tmp_path.glob("ckpt_0*.pkl"))[-1].name \
            == "ckpt_000000000003.pkl"


class TestAutonomousArc:
    def test_kill_elect_rejoin_resume_bitwise_over_rest(
            self, cl, standby_cloud, monkeypatch, tmp_path):
        """Acceptance (ISSUE 5): the coordinator is killed mid-GBM-train;
        with NO manual assume_coordination()/rejoin() calls the watchdog
        elects this standby (REST re-binds), the restarted ex-coordinator
        rejoins as a follower, the interrupted job resumes from its last
        durable iteration under its ORIGINAL key, and the resumed model's
        REST predictions are bitwise-identical to the uninterrupted
        baseline's."""
        import numpy as np

        from h2o3_tpu import scoring
        from h2o3_tpu.api import server as api_server
        from h2o3_tpu.core.dkv import DKV
        from h2o3_tpu.core.frame import Column, Frame
        from h2o3_tpu.core.job import Job
        from h2o3_tpu.models.model_builder import ModelBuilder
        from h2o3_tpu.models.tree.gbm import GBM

        monkeypatch.setenv("H2O_TPU_AUTO_RECOVER", "1")
        monkeypatch.setenv("H2O_TPU_ELECTION_GRACE_S", "0.2")
        monkeypatch.setenv("H2O_TPU_HEARTBEAT_STALE_S", "60")
        monkeypatch.setenv("H2O_TPU_SUPERVISE_INTERVAL_S", "3600")
        monkeypatch.setenv("H2O_TPU_JOB_CKPT_ITERS", "2")
        monkeypatch.setenv("H2O_TPU_OPLOG_CKPT_DIR", str(tmp_path))
        monkeypatch.setenv("H2O_TPU_OP_ACK_TIMEOUT_S", "15")
        watchdog.reset()

        rng = np.random.default_rng(17)
        n = 320
        fr = Frame()
        x1, x2 = rng.standard_normal(n), rng.standard_normal(n)
        fr.add("x1", Column.from_numpy(x1))
        fr.add("x2", Column.from_numpy(x2))
        fr.add("y", Column.from_numpy(
            np.where(x1 - 0.5 * x2 > 0, "Y", "N"), ctype="enum"))
        score = Frame()
        score.add("x1", Column.from_numpy(rng.standard_normal(64)))
        score.add("x2", Column.from_numpy(rng.standard_normal(64)))
        DKV.put(str(fr.key), fr)
        DKV.put(str(score.key), score)
        params = dict(ntrees=8, max_depth=3, seed=11)
        baseline = GBM(**params).train(y="y", training_frame=fr)

        # -- the doomed coordinator's build: durable progress, then death
        job = Job(description="GBM Model Build")
        job.resume_spec = {"algo": "gbm", "params": dict(params),
                           "training_frame": str(fr.key), "y": "y",
                           "model_id": "resumed_model",
                           "description": job.description}
        doomed = GBM(**params)
        doomed._progress_job = job
        orig_tick = ModelBuilder._tick_job_progress

        def tick_boom(self, done, fn):
            orig_tick(self, done, fn)
            if done >= 4:
                raise _Killed()

        monkeypatch.setattr(ModelBuilder, "_tick_job_progress", tick_boom)
        with pytest.raises(_Killed):
            doomed.train(y="y", training_frame=fr)
        monkeypatch.setattr(ModelBuilder, "_tick_job_progress", orig_tick)
        assert ckpt.load_job_progress(str(job.key))["iteration"] == 4
        # the Job object lived on the dead coordinator: this standby has
        # only the durable progress record (+ file) to work from
        DKV.remove(str(job.key))
        if doomed.job is not None:
            DKV.remove(str(doomed.job.key))

        # the coordinator goes silent past the election grace
        standby_cloud["h2o3/heartbeat/1"] = json.dumps(
            {"ts": time.time() - 999, "proc": 1})
        failure.heartbeat()

        # stand in for the rejoined ex-coordinator's replay duty: ack every
        # broadcast op at its post-restart incarnation
        stop_acks = threading.Event()

        def acker():
            while not stop_acks.is_set():
                for k in list(standby_cloud.keys()):
                    m = re.fullmatch(r"oplog/(\d+)", k)
                    if not m:
                        continue
                    ak = f"oplog/ack/{m.group(1)}/1"
                    if ak in standby_cloud:
                        continue
                    try:
                        rec = json.loads(standby_cloud[k])
                    except (ValueError, TypeError):
                        continue
                    standby_cloud[ak] = json.dumps(
                        {"proc": 1, "ts": time.time(),
                         "op_id": rec.get("op_id"), "inc": 1})
                time.sleep(0.005)

        ack_thread = threading.Thread(target=acker, daemon=True)
        ack_thread.start()

        srv_box = {}

        def elect():
            srv_box["srv"] = api_server.assume_coordination(port=0)

        wd = watchdog.Watchdog(interval=0.05, elect=elect, follow=False)
        t0 = time.monotonic()
        wd.start()
        try:
            deadline = time.monotonic() + 15
            while not D.is_coordinator() and time.monotonic() < deadline:
                time.sleep(0.01)
            assert D.is_coordinator() and D.epoch() == 1
            assert time.monotonic() - t0 < 10     # election fired promptly
            assert watchdog.status()["elections"] >= 1
            assert "srv" in srv_box               # REST re-bound by the wd
            # the ex-coordinator restarts and rejoins as a follower:
            # fresh beat + readmission record at incarnation 1
            standby_cloud["h2o3/heartbeat/1"] = json.dumps(
                {"ts": time.time(), "proc": 1, "inc": 1})
            standby_cloud["oplog/rejoin/1"] = json.dumps(
                {"proc": 1, "inc": 1, "phase": "caught_up", "seq": 0,
                 "ts": time.time()})
            base = f"http://127.0.0.1:{srv_box['srv'].port}"
            jk = urllib.request.quote(str(job.key), safe="")
            j = None
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                failure.heartbeat()
                try:
                    got = _get(base, f"/3/Jobs/{jk}")["jobs"]
                except urllib.error.HTTPError:
                    got = []                      # not recreated yet
                j = got[0] if got else None
                if j is not None and j["status"] == "DONE":
                    break
                time.sleep(0.05)
            assert j is not None and j["status"] == "DONE", j
            assert j["attempt"] == 2              # original + one resume
            assert j["resumed_from_iteration"] == 4
            st = _get(base, "/3/CloudStatus")
            assert st["state"] == supervisor.HEALTHY
            assert st["watchdog"]["jobs_resumed"] >= 1
            assert st["epoch"] == 1 and st["leader"] == 0
            # bitwise: score baseline and resumed model through the SAME
            # REST path and compare the prediction frames
            for mid, dest in ((str(baseline.key), "pred_base"),
                              ("resumed_model", "pred_resumed")):
                _post(base, f"/3/Predictions/models/"
                      f"{urllib.request.quote(mid, safe='')}/frames/"
                      f"{urllib.request.quote(str(score.key), safe='')}",
                      {"predictions_frame": dest})
            pb, pr = DKV.get("pred_base"), DKV.get("pred_resumed")
            assert pb is not None and pr is not None
            assert pb.names == pr.names
            for c in pb.names:
                assert np.array_equal(np.asarray(pb.col(c).data),
                                      np.asarray(pr.col(c).data)), c
        finally:
            wd.stop()
            stop_acks.set()
            ack_thread.join(timeout=5)
            srv = srv_box.get("srv")
            if srv is not None:
                srv.stop()
            scoring.purge()
            for k in ("pred_base", "pred_resumed", "resumed_model",
                      str(job.key), str(fr.key), str(score.key),
                      str(baseline.key)):
                DKV.remove(k)


    def test_kill_mid_automl_watchdog_resumes_leaderboard_over_rest(
            self, cl, standby_cloud, monkeypatch, tmp_path):
        """Acceptance (ISSUE 18): the coordinator dies mid-AutoML with two
        members durably done (trained TWO-WIDE — the overlap gauge is the
        concurrency evidence); with zero manual recovery calls the
        watchdog elects this standby, re-dispatches the search under the
        ORIGINAL AutoML job key, and the leaderboard completes over REST
        with the attempt counter carried."""
        import numpy as np

        from h2o3_tpu.api import server as api_server
        from h2o3_tpu.automl import search
        from h2o3_tpu.automl.automl import H2OAutoML
        from h2o3_tpu.core.dkv import DKV
        from h2o3_tpu.core.frame import Column, Frame
        from h2o3_tpu.core.job import Job

        monkeypatch.setenv("H2O_TPU_AUTO_RECOVER", "1")
        monkeypatch.setenv("H2O_TPU_ELECTION_GRACE_S", "0.2")
        monkeypatch.setenv("H2O_TPU_HEARTBEAT_STALE_S", "60")
        monkeypatch.setenv("H2O_TPU_SUPERVISE_INTERVAL_S", "3600")
        monkeypatch.setenv("H2O_TPU_OPLOG_CKPT_DIR", str(tmp_path))
        monkeypatch.setenv("H2O_TPU_OP_ACK_TIMEOUT_S", "15")
        monkeypatch.setenv("H2O_TPU_SEARCH_CONCURRENCY", "2")
        watchdog.reset()
        search.reset_stats()

        rng = np.random.default_rng(5)
        n = 400
        fr = Frame()
        x1, x2 = rng.standard_normal(n), rng.standard_normal(n)
        fr.add("x1", Column.from_numpy(x1))
        fr.add("x2", Column.from_numpy(x2))
        fr.add("y", Column.from_numpy(
            np.where(x1 - 0.5 * x2 > 0, "Y", "N"), ctype="enum"))
        DKV.put(str(fr.key), fr)

        project = "arc_automl"
        aml = H2OAutoML(max_models=3, nfolds=0, seed=42,
                        include_algos=["glm", "gbm"],
                        project_name=project)
        job = Job(description="AutoML", dest=project)
        aml._search_job = job

        # -- the doomed coordinator's search. It ran its members two-wide
        # while it was the cloud's only process (admission-sized width is
        # a single-process feature; mirrored clouds walk serial by
        # design); the standby attached just before the crash.
        monkeypatch.setattr(D, "process_count", lambda: 1)
        settled = {"n": 0}
        orig = search.SearchEngine._build_one

        def dying(self, m, build_fn, score_fn=None):
            if settled["n"] >= 2:
                raise _Killed()
            settled["n"] += 1
            return orig(self, m, build_fn, score_fn)

        monkeypatch.setattr(search.SearchEngine, "_build_one", dying)
        with pytest.raises(_Killed):
            aml.train(y="y", training_frame=fr)
        monkeypatch.setattr(search.SearchEngine, "_build_one", orig)
        monkeypatch.setattr(D, "process_count", lambda: 2)
        data = ckpt.load_search_state(str(job.key))
        assert data is not None
        done0 = sum(1 for m in data["state"]["members"].values()
                    if m["status"] == "done")
        assert done0 == 2
        assert search.stats()["overlap"] >= 2     # trainings overlapped
        # the Job object (and the doomed process's models) died with the
        # coordinator: durable search state is all that survives
        DKV.remove(str(job.key))

        # the coordinator goes silent past the election grace
        standby_cloud["h2o3/heartbeat/1"] = json.dumps(
            {"ts": time.time() - 999, "proc": 1})
        failure.heartbeat()

        # stand in for the rejoined ex-coordinator's replay duty
        stop_acks = threading.Event()

        def acker():
            while not stop_acks.is_set():
                for k in list(standby_cloud.keys()):
                    m = re.fullmatch(r"oplog/(\d+)", k)
                    if not m:
                        continue
                    ak = f"oplog/ack/{m.group(1)}/1"
                    if ak in standby_cloud:
                        continue
                    try:
                        rec = json.loads(standby_cloud[k])
                    except (ValueError, TypeError):
                        continue
                    standby_cloud[ak] = json.dumps(
                        {"proc": 1, "ts": time.time(),
                         "op_id": rec.get("op_id"), "inc": 1})
                time.sleep(0.005)

        ack_thread = threading.Thread(target=acker, daemon=True)
        ack_thread.start()

        srv_box = {}

        def elect():
            srv_box["srv"] = api_server.assume_coordination(port=0)

        wd = watchdog.Watchdog(interval=0.05, elect=elect, follow=False)
        wd.start()
        try:
            deadline = time.monotonic() + 15
            while not D.is_coordinator() and time.monotonic() < deadline:
                time.sleep(0.01)
            assert D.is_coordinator()
            assert "srv" in srv_box
            standby_cloud["h2o3/heartbeat/1"] = json.dumps(
                {"ts": time.time(), "proc": 1, "inc": 1})
            standby_cloud["oplog/rejoin/1"] = json.dumps(
                {"proc": 1, "inc": 1, "phase": "caught_up", "seq": 0,
                 "ts": time.time()})
            base = f"http://127.0.0.1:{srv_box['srv'].port}"
            jk = urllib.request.quote(str(job.key), safe="")
            j = None
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                failure.heartbeat()
                try:
                    got = _get(base, f"/3/Jobs/{jk}")["jobs"]
                except urllib.error.HTTPError:
                    got = []                      # not recreated yet
                j = got[0] if got else None
                if j is not None and j["status"] == "DONE":
                    break
                time.sleep(0.05)
            assert j is not None and j["status"] == "DONE", j
            assert j["attempt"] == 2              # original + one resume
            assert j["resumed_from_iteration"] == done0
            # the finished leaderboard under the ORIGINAL project key
            automl = _get(base, f"/99/AutoML/{project}")
            assert len(automl["leaderboard"]["models"]) >= 3
            st = _get(base, "/3/CloudStatus")
            assert st["state"] == supervisor.HEALTHY
            assert st["watchdog"]["searches_resumed"] >= 1
            assert st["search"]["stats"]["searches_resumed"] >= 1
            assert st["search"]["stats"]["members_done"] >= 3
            assert st["search"]["states"] == []   # superseded on finish
            # overlap + resume counters over the metrics surface
            with urllib.request.urlopen(base + "/3/Metrics",
                                        timeout=30) as r:
                text = r.read().decode()
            series = {}
            for ln in text.splitlines():
                if ln.startswith("h2o3_search_"):
                    parts = ln.split()
                    name = parts[0].split("{")[0]
                    series[name] = max(series.get(name, 0.0),
                                       float(parts[-1]))
            assert series.get("h2o3_search_members_overlap", 0) >= 2
            assert series.get("h2o3_search_resumed_total", 0) >= 1
        finally:
            wd.stop()
            stop_acks.set()
            ack_thread.join(timeout=5)
            srv = srv_box.get("srv")
            if srv is not None:
                srv.stop()
            aml2 = DKV.get(project)
            for m in list(getattr(aml2, "models", [])) + \
                    list(getattr(aml, "models", [])):
                DKV.remove(str(m.key))
            for k in (project, str(job.key), str(fr.key)):
                DKV.remove(k)


# ---------------------------------------------------------------------------
# chaos soak: sustained injected loss under a streaming op sequence
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestChaosSoak:
    def test_streaming_ops_survive_periodic_kv_loss(self, mem_cloud):
        """200 broadcast/replay/ack rounds with a lost KV put injected
        every 5th publish: the retry budget absorbs every loss, the
        follower sees a gapless sequence, and the cloud stays HEALTHY."""
        applied = []
        t = threading.Thread(
            target=lambda: oplog.follower_loop(
                idle_timeout_s=30, on_op=lambda k, p: applied.append(p["i"])),
            daemon=True)
        t.start()
        for i in range(200):
            if i % 5 == 0:
                failure._FAULTS["oplog.kv_put"] = 1
            # hard put losses roll the slot back; the caller-level retry
            # (the micro-batcher pattern) re-claims the SAME slot
            seq = retry.retry_call(oplog.broadcast, "noop", {"i": i},
                                   retry_on=(oplog.OplogPublishError,),
                                   base_s=0.001)
            assert seq == i
            with oplog.turn(seq, timeout_s=30):
                pass
        oplog.publish("shutdown", {})
        t.join(timeout=30)
        assert not t.is_alive()
        assert applied == list(range(200))
        assert supervisor.evaluate() != supervisor.FAILED
