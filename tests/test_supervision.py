"""Cloud supervision tier: acknowledged oplog, bounded waits, retry with
backoff, and the HEALTHY/DEGRADED/FAILED state machine (ISSUE 3).

Reference: water/RPC.java retries every remote task with exponential
backoff; water/HeartBeatThread.java turns a silent node death into an
explicit cloud event. The 2-process gloo tier is env-flaky on this jax
build, so these tests drive the FULL protocol — publish/replay/ack/error/
heartbeat/supervise — deterministically inside one process: the cloud KV
is `distributed.memory_kv()` (a dict), the topology is monkeypatched to
look like a 2-process cloud, and `failure.inject()` supplies the crashes
a real dead peer would.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from h2o3_tpu.core import failure
from h2o3_tpu.parallel import distributed as D
from h2o3_tpu.parallel import oplog, retry, supervisor

pytestmark = pytest.mark.chaos


@pytest.fixture()
def mem_cloud(monkeypatch):
    """Simulated 2-process cloud: dict-backed KV + coordinator topology.
    jax itself stays single-process (device programs run locally), which
    is exactly what makes the protocol paths deterministic here."""
    with D.memory_kv() as kv:
        monkeypatch.setattr(D, "process_count", lambda: 2)
        monkeypatch.setattr(D, "is_coordinator", lambda: True)
        monkeypatch.setenv("H2O_TPU_RETRY_BASE_MS", "1")
        # bound every ack wait so a test bug can never park a thread on
        # the production 300 s default (tests override per-case as needed)
        monkeypatch.setenv("H2O_TPU_OP_ACK_TIMEOUT_S", "30")
        oplog.reset()
        supervisor.reset()
        yield kv
    oplog.reset()
    supervisor.reset()


# ---------------------------------------------------------------------------
# retry.py
# ---------------------------------------------------------------------------

class TestRetry:
    def test_succeeds_after_transient_failures(self):
        calls, slept = [], []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "ok"

        assert retry.retry_call(flaky, retries=4, base_s=0.001,
                                sleep=slept.append) == "ok"
        assert len(calls) == 3 and len(slept) == 2

    def test_exhaustion_raises_original_error(self):
        slept = []
        with pytest.raises(OSError, match="always"):
            retry.retry_call(lambda: (_ for _ in ()).throw(OSError("always")),
                             retries=3, base_s=0.001, sleep=slept.append)
        assert len(slept) == 2          # attempts-1 backoffs

    def test_retry_on_filters_exception_types(self):
        calls = []

        def boom():
            calls.append(1)
            raise ValueError("not retryable here")

        with pytest.raises(ValueError):
            retry.retry_call(boom, retries=5, retry_on=(OSError,),
                             sleep=lambda s: None)
        assert len(calls) == 1          # no retries for non-matching type

    def test_backoff_doubles_and_caps(self):
        ds = list(retry.backoff_delays(attempts=6, base_s=0.01, max_s=0.05,
                                       jitter=0.0))
        assert ds == [0.01, 0.02, 0.04, 0.05, 0.05]

    def test_backoff_jitter_bounded(self):
        for d, nominal in zip(retry.backoff_delays(attempts=4, base_s=0.01,
                                                   max_s=10.0, jitter=0.5),
                              (0.01, 0.02, 0.04)):
            assert 0.5 * nominal <= d <= 1.5 * nominal

    def test_adaptive_poll_grows_and_resets(self):
        slept = []
        p = retry.AdaptivePoll(min_s=0.001, max_s=0.25, sleep=slept.append)
        for _ in range(12):
            p.wait()
        assert slept[0] == pytest.approx(0.001)
        assert slept[-1] == pytest.approx(0.25)       # capped cold
        assert all(b >= a for a, b in zip(slept, slept[1:]))
        p.reset()
        assert p.current_s == pytest.approx(0.001)    # hot again


# ---------------------------------------------------------------------------
# publish: lost-put rollback + retry (satellite 1)
# ---------------------------------------------------------------------------

class TestPublish:
    def test_lost_kv_put_raises_and_rolls_back_seq(self, mem_cloud,
                                                   monkeypatch):
        monkeypatch.setenv("H2O_TPU_RETRY_MAX", "2")
        monkeypatch.setattr(D, "kv_put", lambda k, v: False)
        with pytest.raises(oplog.OplogPublishError, match="op 0"):
            oplog.publish("noop", {})
        # slot rolled back: nothing at seq 0, and the next publish (with a
        # working KV) re-claims 0 — the follower sees a gapless sequence
        monkeypatch.undo()
        monkeypatch.setenv("H2O_TPU_RETRY_BASE_MS", "1")
        assert oplog.publish("noop", {}) == 0
        assert "oplog/0" in mem_cloud

    def test_injected_put_loss_rolls_back_and_caller_retry_lands(
            self, mem_cloud):
        """A HARD put loss (transport retries exhausted) raises with the
        slot rolled back; a caller retrying the publish — the scoring
        micro-batcher's pattern — gets the SAME slot, so the follower
        still sees a gapless sequence."""
        with failure.inject("oplog.kv_put", times=1):
            seq = retry.retry_call(oplog.publish, "noop", {},
                                   retry_on=(oplog.OplogPublishError,),
                                   base_s=0.001)
        assert seq == 0
        assert json.loads(mem_cloud["oplog/0"])["kind"] == "noop"

    def test_publish_faultpoint_fails_cleanly(self, mem_cloud):
        with failure.inject("oplog.publish", times=1):
            with pytest.raises(failure.InjectedFault):
                oplog.publish("noop", {})
        assert oplog.publish("noop", {}) == 0         # nothing was claimed


# ---------------------------------------------------------------------------
# turn(): bounded turnstile wait + slot abandonment (satellite 2)
# ---------------------------------------------------------------------------

class TestTurnDeadline:
    def test_dead_predecessor_raises_instead_of_hanging(self, mem_cloud,
                                                        monkeypatch):
        monkeypatch.setenv("H2O_TPU_OP_ACK_TIMEOUT_S", "0")  # isolate turnstile
        oplog.publish("noop", {})            # seq 0: holder never turns
        seq1 = oplog.publish("noop", {})
        t0 = time.monotonic()
        with pytest.raises(oplog.OplogTurnTimeout, match="stuck at op 0"):
            with oplog.turn(seq1, timeout_s=0.3):
                pass
        assert time.monotonic() - t0 < 5.0   # bounded, not the old forever

    def test_timed_out_waiter_releases_never_entered_head(self, mem_cloud,
                                                          monkeypatch):
        """A head holder that died between publish and turn must not cost
        every later op its own full deadline: the first timed-out waiter
        releases the head slot too, neutralizes both ops to noops in the
        KV, and degrades the cloud."""
        monkeypatch.setenv("H2O_TPU_OP_ACK_TIMEOUT_S", "0")
        for _ in range(3):
            oplog.publish("noop", {})
        with pytest.raises(oplog.OplogTurnTimeout, match="head slot 0"):
            with oplog.turn(1, timeout_s=0.2):       # 0 never turned
                pass
        # both abandoned ops are neutralized so a lagging follower
        # replays nothing the coordinator never ran
        for s in (0, 1):
            assert json.loads(mem_cloud[f"oplog/{s}"])["kind"] == "noop"
        assert supervisor.state() == supervisor.DEGRADED
        # op 2 enters IMMEDIATELY — no serial re-pay of the deadline
        t0 = time.monotonic()
        ran = []
        with oplog.turn(2, timeout_s=5.0):
            ran.append(2)
        assert ran == [2] and time.monotonic() - t0 < 1.0

    def test_late_arriving_holder_of_abandoned_slot_refuses(self, mem_cloud,
                                                            monkeypatch):
        """The presumed-dead holder shows up after all: it must refuse to
        execute out of broadcast order (its op is already a noop) and
        hand the turnstile onward instead of stalling it."""
        monkeypatch.setenv("H2O_TPU_OP_ACK_TIMEOUT_S", "0")
        for _ in range(2):
            oplog.publish("noop", {})
        with pytest.raises(oplog.OplogTurnTimeout):
            with oplog.turn(1, timeout_s=0.2):
                pass
        with pytest.raises(oplog.OplogTurnTimeout, match="abandoned"):
            with oplog.turn(0, timeout_s=5.0):       # the late holder
                raise AssertionError("abandoned op must not execute")
        # and the turnstile moved on: a fresh op proceeds instantly
        seq = oplog.publish("noop", {})
        with oplog.turn(seq, timeout_s=5.0):
            pass

    def test_slow_executing_head_is_left_alone(self, mem_cloud,
                                               monkeypatch):
        """A head holder INSIDE its turn (long device program) is alive —
        a timed-out waiter abandons only itself, never the head."""
        monkeypatch.setenv("H2O_TPU_OP_ACK_TIMEOUT_S", "0")
        oplog.publish("noop", {})
        seq1 = oplog.publish("noop", {})
        entered = threading.Event()
        release = threading.Event()
        done = []

        def slow_head():
            with oplog.turn(0, timeout_s=5.0):
                entered.set()
                release.wait(10)
            done.append(0)

        t = threading.Thread(target=slow_head, daemon=True)
        t.start()
        assert entered.wait(5)
        with pytest.raises(oplog.OplogTurnTimeout) as ei:
            with oplog.turn(seq1, timeout_s=0.2):
                pass
        assert "head slot" not in str(ei.value)      # head NOT released
        release.set()
        t.join(10)
        assert done == [0]                           # head completed fine

    def test_none_ticket_stays_free(self):
        with oplog.turn(None):               # single-process path: no-op
            pass


# ---------------------------------------------------------------------------
# ack protocol + follower loop
# ---------------------------------------------------------------------------

class TestAcks:
    def test_follower_acks_each_replay(self, mem_cloud):
        t = threading.Thread(
            target=lambda: oplog.follower_loop(idle_timeout_s=10),
            daemon=True)
        t.start()
        for _ in range(3):
            seq = oplog.broadcast("noop", {})
            with oplog.turn(seq, timeout_s=10):
                pass                          # exit waits for the ack
        assert {f"oplog/ack/{i}/0" for i in range(3)} <= set(mem_cloud)
        oplog.publish("shutdown", {})
        t.join(timeout=10)
        assert not t.is_alive()

    def test_wait_acks_timeout_degrades_cloud(self, mem_cloud):
        oplog.publish("noop", {})            # no follower running
        t0 = time.monotonic()
        with pytest.raises(failure.CloudUnhealthyError, match="0/1"):
            oplog.wait_acks(0, timeout_s=0.3)
        assert time.monotonic() - t0 < 5.0
        assert supervisor.state() == supervisor.DEGRADED
        # the degrade is HELD: a wedged peer that keeps beating must not
        # instantly re-arm the cloud on the next heartbeat evaluation
        now = time.time()
        for p in (0, 1):
            mem_cloud[f"h2o3/heartbeat/{p}"] = json.dumps({"ts": now,
                                                           "proc": p})
        assert supervisor.evaluate() == supervisor.DEGRADED
        # ... and recovers once the hold ages out
        with supervisor._LOCK:
            supervisor._STATE["hold_until"] = time.time() - 1
        assert supervisor.evaluate() == supervisor.HEALTHY

    def test_wait_acks_bails_fast_when_cloud_already_failed(self,
                                                           mem_cloud):
        """A replay crash on ANOTHER op must fail this op's ack wait
        immediately with that diagnosis — not a generic timeout 300s
        later."""
        supervisor.fail("follower replay of op 3 crashed",
                        "Traceback ...\nOtherOpBoom")
        t0 = time.monotonic()
        with pytest.raises(failure.CloudUnhealthyError,
                           match="OtherOpBoom"):
            oplog.wait_acks(7, timeout_s=300.0)
        assert time.monotonic() - t0 < 5.0

    def test_wait_acks_surfaces_remote_traceback(self, mem_cloud):
        mem_cloud["oplog/error/0"] = json.dumps(
            {"kind": "train", "trace": "Traceback ...\nBoomError: kaput"})
        with pytest.raises(failure.CloudUnhealthyError,
                           match="BoomError: kaput") as ei:
            oplog.wait_acks(0, timeout_s=5)
        assert "BoomError" in ei.value.remote_trace
        assert supervisor.state() == supervisor.FAILED

    def test_replay_crash_error_key_before_death(self, mem_cloud):
        oplog.publish("noop", {})
        with failure.inject("oplog.replay", times=1):
            with pytest.raises(failure.InjectedFault):
                oplog.follower_loop(idle_timeout_s=5)
        rec = json.loads(mem_cloud["oplog/error/0"])
        assert rec["kind"] == "noop"
        assert "injected fault: oplog.replay" in rec["trace"]

    def test_lost_ack_hits_timeout_not_error_path(self, mem_cloud):
        oplog.publish("noop", {})
        with failure.inject("oplog.ack", times=1):
            with pytest.raises(failure.InjectedFault):
                oplog.follower_loop(idle_timeout_s=5)
        assert "oplog/error/0" not in mem_cloud   # replay itself succeeded
        with pytest.raises(failure.CloudUnhealthyError, match="acks"):
            oplog.wait_acks(0, timeout_s=0.2)

    def test_lost_ack_write_is_loud_and_nonfatal(self, mem_cloud,
                                                 monkeypatch):
        """A follower whose ack WRITE is lost (kv_put budget exhausted)
        must not silently proceed — the coordinator would stall the full
        ack timeout and then degrade with a misleading 'follower dead'
        diagnosis. It records a NON-fatal error (the replay succeeded:
        states did not diverge) and dies; wait_acks surfaces the true
        story immediately and the cloud DEGRADES rather than
        sticky-FAILs."""
        monkeypatch.setenv("H2O_TPU_RETRY_MAX", "2")
        real = D.kv_put
        monkeypatch.setattr(
            D, "kv_put",
            lambda k, v: False if k.startswith("oplog/ack/")
            else real(k, v))
        oplog.publish("noop", {})
        with pytest.raises(oplog.OplogAckError, match="could not write"):
            oplog.follower_loop(idle_timeout_s=5)
        rec = json.loads(mem_cloud["oplog/error/0"])
        assert rec["kind"] == "ack" and rec["fatal"] is False
        t0 = time.monotonic()
        with pytest.raises(failure.CloudUnhealthyError, match="non-fatal"):
            oplog.wait_acks(0, timeout_s=30)
        assert time.monotonic() - t0 < 5.0            # no 30 s stall
        assert supervisor.state() == supervisor.DEGRADED
        assert supervisor.evaluate() == supervisor.DEGRADED  # not FAILED

    def test_transient_ack_loss_absorbed_by_retry(self, mem_cloud,
                                                  monkeypatch):
        """One blipped ack write is absorbed by _ack's second retry round:
        the ack lands, no error record appears, wait_acks returns."""
        real = D.kv_put
        fails = {"left": 1}

        def flaky(k, v):
            if k.startswith("oplog/ack/") and fails["left"]:
                fails["left"] -= 1
                return False
            return real(k, v)

        monkeypatch.setattr(D, "kv_put", flaky)
        oplog.publish("noop", {})
        oplog.publish("shutdown", {})
        assert oplog.follower_loop(idle_timeout_s=5) == 1
        assert "oplog/ack/0/0" in mem_cloud
        assert "oplog/error/0" not in mem_cloud
        oplog.wait_acks(0, timeout_s=5)               # ack landed: no raise

    def test_stale_ack_cannot_satisfy_a_reclaimed_slot(self, mem_cloud):
        """Indeterminate put: op 0's kv_put reported lost (slot rolled
        back) but the follower acked SOMETHING under seq 0. A different
        op reclaiming the slot must not be satisfied by that stale ack —
        acks match on the op identity token, not the slot number."""
        with failure.inject("oplog.kv_put", times=1):
            with pytest.raises(oplog.OplogPublishError):
                oplog.publish("noop", {})
        mem_cloud["oplog/ack/0/1"] = json.dumps(
            {"proc": 1, "ts": time.time(), "op_id": "the-lost-op"})
        assert oplog.publish("noop", {"fresh": True}) == 0   # reclaimed
        with pytest.raises(failure.CloudUnhealthyError, match="0/1"):
            oplog.wait_acks(0, timeout_s=0.3)

    def test_abandoned_slot_already_replayed_fails_cloud(self, mem_cloud,
                                                         monkeypatch):
        """If a follower ALREADY replayed an op whose turnstile slot gets
        abandoned, the divergence is certain (the follower ran a program
        the coordinator never will): sticky FAILED, not a held degrade."""
        monkeypatch.setenv("H2O_TPU_OP_ACK_TIMEOUT_S", "0")
        oplog.publish("noop", {})            # head; holder never arrives
        seq1 = oplog.publish("noop", {})
        op0 = json.loads(mem_cloud["oplog/0"])
        mem_cloud["oplog/ack/0/1"] = json.dumps(
            {"proc": 1, "ts": time.time(), "op_id": op0["op_id"]})
        with pytest.raises(oplog.OplogTurnTimeout):
            with oplog.turn(seq1, timeout_s=0.2):
                pass
        assert supervisor.state() == supervisor.FAILED
        assert "diverged" in supervisor.status()["reason"]

    def test_follower_idle_timeout_error_path(self, mem_cloud):
        t0 = time.monotonic()
        with pytest.raises(TimeoutError, match="idle for 0.2s at op 0"):
            oplog.follower_loop(idle_timeout_s=0.2)
        assert time.monotonic() - t0 < 5.0


# ---------------------------------------------------------------------------
# supervisor state machine
# ---------------------------------------------------------------------------

class TestSupervisor:
    def test_stale_heartbeat_degrades_then_recovers(self, mem_cloud):
        now = time.time()
        mem_cloud["h2o3/heartbeat/0"] = json.dumps({"ts": now, "proc": 0})
        mem_cloud["h2o3/heartbeat/1"] = json.dumps({"ts": now - 1000,
                                                    "proc": 1})
        assert supervisor.evaluate() == supervisor.DEGRADED
        st = supervisor.status()
        assert "stale heartbeat" in st["reason"] and "[1]" in st["reason"]
        with pytest.raises(failure.CloudUnhealthyError):
            oplog.broadcast("noop", {})      # degraded: refused fast
        # the peer comes back: beats refresh, the cloud recovers
        mem_cloud["h2o3/heartbeat/1"] = json.dumps({"ts": time.time(),
                                                    "proc": 1})
        assert supervisor.evaluate() == supervisor.HEALTHY
        assert oplog.broadcast("noop", {}) == 0      # serving again

    def test_never_beaten_follower_degrades_after_grace(self, mem_cloud,
                                                        monkeypatch):
        """A follower that died at STARTUP has no stale heartbeat row to
        trip on — its absence past the staleness window must degrade the
        cloud all the same."""
        now = time.time()
        mem_cloud["h2o3/heartbeat/0"] = json.dumps({"ts": now, "proc": 0})
        assert supervisor.evaluate() == supervisor.HEALTHY   # inside grace
        monkeypatch.setattr(supervisor, "_FIRST_EVAL_TS", now - 100)
        assert supervisor.evaluate() == supervisor.DEGRADED
        assert "never heartbeat" in supervisor.status()["reason"]
        # the missing peer finally boots and beats: cloud recovers
        mem_cloud["h2o3/heartbeat/1"] = json.dumps({"ts": time.time(),
                                                    "proc": 1})
        assert supervisor.evaluate() == supervisor.HEALTHY

    def test_replay_error_fails_cloud_permanently(self, mem_cloud):
        mem_cloud["oplog/error/4"] = json.dumps({"kind": "predict",
                                                 "trace": "tb"})
        assert supervisor.evaluate() == supervisor.FAILED
        # FAILED is sticky: fresh heartbeats do NOT recover a diverged cloud
        now = time.time()
        for p in (0, 1):
            mem_cloud[f"h2o3/heartbeat/{p}"] = json.dumps({"ts": now,
                                                           "proc": p})
        del mem_cloud["oplog/error/4"]
        assert supervisor.evaluate() == supervisor.FAILED

    def test_failed_cloud_fails_inflight_jobs_with_trace(self, mem_cloud):
        from h2o3_tpu.core.job import Job

        ev = threading.Event()
        job = Job(description="wedged collective")
        job.start(lambda j: ev.wait(10), background=True)
        try:
            supervisor.fail("follower replay of op 7 crashed",
                            "Traceback ...\nRemoteBoom: dead peer")
            assert job.status == Job.FAILED
            assert "RemoteBoom: dead peer" in job.exception
        finally:
            ev.set()
        time.sleep(0.05)                     # worker unwinds...
        assert job.status == Job.FAILED      # ...but cannot resurrect DONE

    def test_created_job_failed_by_supervisor_never_runs(self, mem_cloud):
        """A job failed while still CREATED (cloud died between submit
        and thread start) must honor the verdict, not resurrect itself
        to RUNNING and execute against a dead cloud."""
        from h2o3_tpu.core.job import Job

        job = Job(description="doomed before start")
        supervisor.fail("cloud died pre-start", "pre-start trace")
        assert job.status == Job.FAILED
        ran = []
        job.start(lambda j: ran.append(1), background=False)
        assert ran == []
        assert job.status == Job.FAILED
        assert "pre-start trace" in job.exception

    def test_cluster_health_staleness_boundary(self, mem_cloud):
        now = time.time()
        mem_cloud["h2o3/heartbeat/0"] = json.dumps({"ts": now - 29.0,
                                                    "proc": 0})
        mem_cloud["h2o3/heartbeat/1"] = json.dumps({"ts": now - 31.0,
                                                    "proc": 1})
        rows = failure.cluster_health(stale_after_s=30.0)
        by_proc = {r["process"]: r for r in rows}
        assert by_proc[0]["healthy"] is True       # just inside the window
        assert by_proc[1]["healthy"] is False      # just past it
        assert by_proc[1]["age_s"] > by_proc[0]["age_s"]

    def test_heartbeat_faultpoint_drops_beat(self, mem_cloud):
        with failure.inject("failure.heartbeat", times=1):
            with pytest.raises(failure.InjectedFault):
                failure.heartbeat()
        assert failure.heartbeat()           # next beat lands
        assert "h2o3/heartbeat/0" in mem_cloud

    def test_recover_check_is_atomic_with_hold(self, mem_cloud,
                                               monkeypatch):
        """evaluate() must hold the state lock ACROSS its hold_until check
        and the recover() transition: a degrade(hold_s=...) landing from
        another thread (an ack-timeout handler recording fresh wedged-peer
        evidence) can then never slip between the two and be erased
        together with its hold."""
        supervisor.degrade("old evidence")               # hold expired
        now = time.time()
        for p in (0, 1):
            mem_cloud[f"h2o3/heartbeat/{p}"] = json.dumps({"ts": now,
                                                           "proc": p})
        lock_held_during_recover = []
        real = supervisor.recover

        def spying(*a, **k):
            got = []

            def probe():
                ok = supervisor._LOCK.acquire(timeout=0.2)
                if ok:
                    supervisor._LOCK.release()
                got.append(ok)

            t = threading.Thread(target=probe)
            t.start()
            t.join()
            lock_held_during_recover.append(not got[0])
            return real(*a, **k)

        monkeypatch.setattr(supervisor, "recover", spying)
        assert supervisor.evaluate() == supervisor.HEALTHY
        assert lock_held_during_recover == [True]


# ---------------------------------------------------------------------------
# distributed KV fallbacks (satellite 4)
# ---------------------------------------------------------------------------

class _LegacyKVClient:
    """jax client without allow_overwrite: set raises on existing keys."""

    def __init__(self):
        self.store = {}

    def key_value_set(self, key, value, allow_overwrite=None):
        if allow_overwrite is not None:
            raise TypeError("no allow_overwrite kwarg")
        if key in self.store:
            raise RuntimeError("ALREADY_EXISTS")
        self.store[key] = value

    def key_value_try_get(self, key):
        if key not in self.store:
            raise KeyError(key)
        return self.store[key]

    def key_value_delete(self, key):
        self.store.pop(key, None)


class TestKVFallbacks:
    def test_kv_put_overwrite_retry_fallback(self, monkeypatch):
        c = _LegacyKVClient()
        monkeypatch.setattr(D, "_kv_client", lambda: c)
        monkeypatch.setenv("H2O_TPU_RETRY_BASE_MS", "1")
        assert D.kv_put("k", "v1") is True           # fresh key
        assert D.kv_put("k", "v2") is True           # delete+retry upsert
        assert c.store["k"] == "v2"

    def test_kv_put_concurrent_winner_counts_as_success(self, monkeypatch):
        c = _LegacyKVClient()

        def stubborn_set(key, value, allow_overwrite=None):
            if allow_overwrite is not None:
                raise TypeError("no kwarg")
            # a concurrent writer always beats us to the slot
            c.store.setdefault(key, "theirs")
            raise RuntimeError("ALREADY_EXISTS")

        monkeypatch.setattr(c, "key_value_set", stubborn_set)
        monkeypatch.setattr(D, "_kv_client", lambda: c)
        monkeypatch.setenv("H2O_TPU_RETRY_BASE_MS", "1")
        assert D.kv_put("k", "mine") is True         # a value IS in place
        assert c.store["k"] == "theirs"

    def test_kv_put_real_loss_returns_false(self, monkeypatch):
        c = _LegacyKVClient()

        def losing_set(key, value, allow_overwrite=None):
            if allow_overwrite is not None:
                raise TypeError("no kwarg")
            raise RuntimeError("ALREADY_EXISTS")     # and nothing lands

        monkeypatch.setattr(c, "key_value_set", losing_set)
        monkeypatch.setattr(D, "_kv_client", lambda: c)
        monkeypatch.setenv("H2O_TPU_RETRY_MAX", "2")
        monkeypatch.setenv("H2O_TPU_RETRY_BASE_MS", "1")
        assert D.kv_put("k", "v") is False


# ---------------------------------------------------------------------------
# scoring micro-batcher: retry + degraded-mode local serving
# ---------------------------------------------------------------------------

class _FakeKeyed:
    def __init__(self, key):
        self.key = key


class TestScoringSupervision:
    def _pending(self):
        from h2o3_tpu import scoring

        return scoring._Pending(_FakeKeyed("fr"), None, False)

    def test_flush_retries_lost_broadcast(self, mem_cloud, monkeypatch):
        from h2o3_tpu import scoring

        attempts = []

        def flaky_broadcast(kind, payload):
            attempts.append(kind)
            if len(attempts) == 1:
                raise oplog.OplogPublishError("lost")
            return None

        monkeypatch.setattr(oplog, "broadcast", flaky_broadcast)
        monkeypatch.setattr(scoring, "execute_batch",
                            lambda m, e, local_only=False: [("PRED", None)])
        ent = self._pending()
        scoring.ScoreBatcher._flush(_FakeKeyed("m"), [ent])
        assert attempts == ["score_batch", "score_batch"]
        assert ent.error is None and ent.pred == "PRED"

    def test_degrade_race_during_broadcast_falls_back_local(
            self, mem_cloud, monkeypatch):
        """The cloud degrades BETWEEN the batcher's state snapshot and the
        broadcast's own fail-fast check: scoring must fall back to local
        serving, not 503 the whole batch."""
        from h2o3_tpu import scoring

        def degrading_broadcast(kind, payload):
            raise failure.CloudUnhealthyError("degraded mid-flight")

        monkeypatch.setattr(oplog, "broadcast", degrading_broadcast)
        seen = {}

        def exec_local(m, entries, local_only=False):
            seen["local_only"] = local_only
            return [("PRED", None)]

        monkeypatch.setattr(scoring, "execute_batch", exec_local)
        ent = self._pending()
        scoring.ScoreBatcher._flush(_FakeKeyed("m"), [ent])
        assert seen["local_only"] is True
        assert ent.error is None and ent.pred == "PRED"

    def test_degraded_cloud_serves_locally_without_broadcast(
            self, mem_cloud, monkeypatch):
        from h2o3_tpu import scoring

        supervisor.degrade("peer went quiet")
        seen = {}

        def no_broadcast(kind, payload):
            raise AssertionError("degraded flush must not broadcast")

        monkeypatch.setattr(oplog, "broadcast", no_broadcast)

        def exec_local(m, entries, local_only=False):
            seen["local_only"] = local_only
            return [("PRED", None)]

        monkeypatch.setattr(scoring, "execute_batch", exec_local)
        ent = self._pending()
        scoring.ScoreBatcher._flush(_FakeKeyed("m"), [ent])
        assert seen["local_only"] is True
        assert ent.error is None and ent.pred == "PRED"
        # local serving forked the coordinator's DKV from the follower's:
        # fresh heartbeats must NOT auto-recover this cloud anymore
        now = time.time()
        for p in (0, 1):
            mem_cloud[f"h2o3/heartbeat/{p}"] = json.dumps({"ts": now,
                                                           "proc": p})
        assert supervisor.evaluate() == supervisor.DEGRADED
        assert "restart the cloud" in supervisor.status()["reason"]


# ---------------------------------------------------------------------------
# REST surface: lifecycle wiring + end-to-end chaos (acceptance criteria)
# ---------------------------------------------------------------------------

def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=30) as r:
        return json.loads(r.read())


def _post(base, path, data):
    body = "&".join(f"{k}={urllib.request.quote(str(v))}"
                    for k, v in data.items()).encode()
    req = urllib.request.Request(base + path, data=body, method="POST")
    with urllib.request.urlopen(req, timeout=60) as r:
        return json.loads(r.read())


def _wait_job(base, key, timeout_s=60.0):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        j = _get(base, f"/3/Jobs/{urllib.request.quote(key, safe='')}")
        j = j["jobs"][0]
        if j["status"] not in ("CREATED", "RUNNING"):
            return j
        time.sleep(0.05)
    raise AssertionError(f"job {key} still running after {timeout_s}s")


@pytest.fixture()
def chaos_csv(tmp_path):
    import numpy as np

    rng = np.random.default_rng(0)
    p = tmp_path / "chaos.csv"
    with open(p, "w") as f:
        f.write("x,y\n")
        for _ in range(200):
            x = rng.normal()
            f.write(f"{x:.5f},{'YN'[int(x > 0)]}\n")
    return str(p)


class TestRestSupervision:
    def test_heartbeat_and_supervisor_autostart_multiprocess(
            self, cl, mem_cloud, monkeypatch):
        """Satellite 3 regression: start_server on a multi-process cloud
        wires the beater + supervisor; stop() tears both down."""
        from h2o3_tpu.api.server import start_server

        monkeypatch.setenv("H2O_TPU_SUPERVISE_INTERVAL_S", "0.05")
        srv = start_server(port=0)
        try:
            hb, sup = srv.heartbeat_thread, srv.supervisor
            assert hb is not None and sup is not None
            deadline = time.time() + 10
            while time.time() < deadline and \
                    "h2o3/heartbeat/0" not in mem_cloud:
                time.sleep(0.02)
            assert "h2o3/heartbeat/0" in mem_cloud    # /3/Cloud liveness
            assert _get(f"http://127.0.0.1:{srv.port}",
                        "/3/CloudStatus")["state"] == "HEALTHY"
        finally:
            srv.stop()
        assert srv.heartbeat_thread is None and srv.supervisor is None
        assert hb._stop.is_set() and sup._stop.is_set()

    def test_no_duplicate_beater_when_runtime_already_beats(
            self, cl, mem_cloud, monkeypatch):
        """On a real multi-process cloud core.runtime already runs the
        beater on every process — start_server must not stack a second
        one on the coordinator."""
        from h2o3_tpu.api.server import start_server
        from h2o3_tpu.core import runtime

        monkeypatch.setenv("H2O_TPU_SUPERVISE_INTERVAL_S", "3600")
        sentinel = failure.HeartbeatThread(interval_s=3600)
        monkeypatch.setattr(runtime._CLUSTER, "_heartbeat", sentinel)
        srv = start_server(port=0)
        try:
            assert srv.heartbeat_thread is None       # runtime's suffices
            assert srv.supervisor is not None
        finally:
            srv.stop()

    def test_restarted_cloud_server_rederives_state_from_evidence(
            self, cl, mem_cloud, monkeypatch):
        """A re-started cloud must not inherit the previous incarnation's
        sticky FAILED verdict — but persistent error keys in the KV must
        immediately re-derive it."""
        from h2o3_tpu.api.server import start_server

        monkeypatch.setenv("H2O_TPU_SUPERVISE_INTERVAL_S", "3600")
        supervisor.fail("old incarnation crashed", "stale trace")
        srv = start_server(port=0)          # fresh KV: verdict cleared
        try:
            assert supervisor.state() == supervisor.HEALTHY
        finally:
            srv.stop()
        # same restart but the error key SURVIVED (same coordination
        # service): the synchronous first evaluate() re-fails immediately
        supervisor.fail("old incarnation crashed", "stale trace")
        mem_cloud["oplog/error/2"] = json.dumps({"kind": "train",
                                                 "trace": "still here"})
        srv = start_server(port=0)
        try:
            assert supervisor.state() == supervisor.FAILED
            assert "op 2" in supervisor.status()["reason"]
        finally:
            srv.stop()

    def test_single_process_server_skips_supervision_threads(self, cl):
        from h2o3_tpu.api.server import start_server

        srv = start_server(port=0)
        try:
            assert srv.heartbeat_thread is None and srv.supervisor is None
            out = _get(f"http://127.0.0.1:{srv.port}", "/3/Cloud")
            assert out["cloud_status"] == "HEALTHY"
        finally:
            srv.stop()

    def test_replay_crash_fails_job_with_remote_trace(self, cl, mem_cloud,
                                                      monkeypatch,
                                                      chaos_csv):
        """Acceptance: an injected follower replay crash surfaces on the
        coordinator as a FAILED job carrying the remote traceback within
        the ack timeout — the pre-supervision oplog would have sat in the
        unbounded publish/turn waits forever."""
        from h2o3_tpu.api.server import start_server

        monkeypatch.setenv("H2O_TPU_OP_ACK_TIMEOUT_S", "20")
        monkeypatch.setenv("H2O_TPU_SUPERVISE_INTERVAL_S", "0.05")
        srv = start_server(port=0)
        base = f"http://127.0.0.1:{srv.port}"

        def doomed_follower():
            # the injected crash is the POINT — die like a real follower
            # would, without tripping pytest's unhandled-thread warning
            with pytest.raises(failure.InjectedFault):
                oplog.follower_loop(idle_timeout_s=30)

        follower = threading.Thread(target=doomed_follower, daemon=True)
        try:
            with failure.inject("oplog.replay", times=1):
                follower.start()
                out = _post(base, "/3/Parse",
                            {"source_frames": f'["{chaos_csv}"]',
                             "destination_frame": "chaos.hex"})
                job = _wait_job(base, out["job"]["key"]["name"])
            assert job["status"] == "FAILED"
            assert "injected fault: oplog.replay" in (job["exception"] or "")
            assert "remote traceback" in (job["exception"] or "")
            # the supervisor folded the error key into cloud state ...
            st = _get(base, "/3/CloudStatus")
            assert st["state"] == "FAILED"
            assert st["oplog_errors"] and \
                "oplog.replay" in st["oplog_errors"][0]["trace"]
            cloud = _get(base, "/3/Cloud")
            assert cloud["cloud_status"] == "FAILED"
            assert cloud["cloud_healthy"] is False
            # ... and new multi-process ops are refused fast with a 503
            t0 = time.monotonic()
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(base, "/3/Parse",
                      {"source_frames": f'["{chaos_csv}"]',
                       "destination_frame": "chaos2.hex"})
            assert ei.value.code == 503
            assert time.monotonic() - t0 < 10.0
            body = json.loads(ei.value.read())
            assert "FAILED" in body.get("msg", "")
        finally:
            srv.stop()
            follower.join(timeout=5)
            # drain the failed job's worker thread (the supervisor marks
            # the Job FAILED while its thread may still be mid-parse) so
            # no straggler outlives this test's cloud epoch
            from h2o3_tpu.core.dkv import DKV

            jobj = DKV.get(job["key"]["name"]) if "job" in locals() else None
            th = getattr(jobj, "_thread", None)
            if th is not None:
                th.join(timeout=30)

    def test_cloudstatus_reflects_stale_heartbeat_transitions(
            self, cl, mem_cloud, monkeypatch):
        """Acceptance: GET /3/CloudStatus walks HEALTHY -> DEGRADED ->
        HEALTHY as a peer's heartbeat goes stale and returns."""
        from h2o3_tpu.api.server import start_server

        monkeypatch.setenv("H2O_TPU_SUPERVISE_INTERVAL_S", "3600")
        srv = start_server(port=0)          # evaluate() driven by the test
        base = f"http://127.0.0.1:{srv.port}"
        try:
            now = time.time()
            mem_cloud["h2o3/heartbeat/1"] = json.dumps({"ts": now,
                                                        "proc": 1})
            supervisor.evaluate()
            assert _get(base, "/3/CloudStatus")["state"] == "HEALTHY"
            mem_cloud["h2o3/heartbeat/1"] = json.dumps({"ts": now - 999,
                                                        "proc": 1})
            supervisor.evaluate()
            st = _get(base, "/3/CloudStatus")
            assert st["state"] == "DEGRADED"
            assert "stale heartbeat" in st["reason"]
            assert any(not r["healthy"] for r in st["process_health"])
            mem_cloud["h2o3/heartbeat/1"] = json.dumps({"ts": time.time(),
                                                        "proc": 1})
            supervisor.evaluate()
            st = _get(base, "/3/CloudStatus")
            assert st["state"] == "HEALTHY"
            trans = [(t["from"], t["to"]) for t in st["transitions"]]
            assert ("HEALTHY", "DEGRADED") in trans
            assert ("DEGRADED", "HEALTHY") in trans
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# chaos soak: sustained injected loss under a streaming op sequence
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestChaosSoak:
    def test_streaming_ops_survive_periodic_kv_loss(self, mem_cloud):
        """200 broadcast/replay/ack rounds with a lost KV put injected
        every 5th publish: the retry budget absorbs every loss, the
        follower sees a gapless sequence, and the cloud stays HEALTHY."""
        applied = []
        t = threading.Thread(
            target=lambda: oplog.follower_loop(
                idle_timeout_s=30, on_op=lambda k, p: applied.append(p["i"])),
            daemon=True)
        t.start()
        for i in range(200):
            if i % 5 == 0:
                failure._FAULTS["oplog.kv_put"] = 1
            # hard put losses roll the slot back; the caller-level retry
            # (the micro-batcher pattern) re-claims the SAME slot
            seq = retry.retry_call(oplog.broadcast, "noop", {"i": i},
                                   retry_on=(oplog.OplogPublishError,),
                                   base_s=0.001)
            assert seq == i
            with oplog.turn(seq, timeout_s=30):
                pass
        oplog.publish("shutdown", {})
        t.join(timeout=30)
        assert not t.is_alive()
        assert applied == list(range(200))
        assert supervisor.evaluate() != supervisor.FAILED
