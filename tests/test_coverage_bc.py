"""Coverage sweep B/C: SQL import, Cleaner HBM eviction, SegmentModels,
Word2Vec CBOW, extension SPI + Rapids UDFs, DL model averaging.

Reference: water/jdbc/SQLManager.java, water/Cleaner.java,
hex/segments/SegmentModels.java, hex/word2vec/Word2Vec.java (CBOW),
water/ExtensionManager.java, hex/deeplearning/DeepLearningTask.java.
"""

import os
import sqlite3

import numpy as np
import pytest

from h2o3_tpu.core.frame import Column, Frame


class TestSQLImport:
    def test_sqlite_table(self, cl, tmp_path):
        from h2o3_tpu.ingest.sql import import_sql_select, import_sql_table

        db = str(tmp_path / "t.db")
        conn = sqlite3.connect(db)
        conn.execute("CREATE TABLE obs (x REAL, grp TEXT, n INTEGER)")
        conn.executemany("INSERT INTO obs VALUES (?,?,?)",
                         [(i * 0.5, "ab"[i % 2], i) for i in range(100)])
        conn.commit()
        conn.close()
        fr = import_sql_table(f"sqlite:///{db}", "obs")
        assert fr.nrows == 100 and fr.names == ["x", "grp", "n"]
        assert fr.col("grp").domain == ["a", "b"]
        assert float(fr.col("n").to_numpy().sum()) == sum(range(100))
        fr2 = import_sql_select(f"sqlite:///{db}",
                                "SELECT x FROM obs WHERE n < 10")
        assert fr2.nrows == 10

    def test_gated_drivers(self, cl):
        from h2o3_tpu.ingest.sql import import_sql_table

        with pytest.raises(ImportError, match="psycopg2"):
            import_sql_table("postgresql://host/db", "t")


class TestCleaner:
    def test_evict_and_fault_back(self, cl):
        from h2o3_tpu.core import cleaner

        fr = Frame()
        x = np.arange(4000, dtype=np.float64)
        fr.add("x", Column.from_numpy(x))
        fr.install()
        try:
            c = fr.col("x")
            before = c.device_nbytes
            assert before > 0
            freed = c.evict()
            assert freed == before and c.is_evicted
            # access faults it back in, values intact
            np.testing.assert_allclose(c.to_numpy(), x)
            assert not c.is_evicted
        finally:
            fr.delete()

    def test_sweep_lru_order(self, cl):
        from h2o3_tpu.core import cleaner

        fr = Frame()
        fr.add("cold", Column.from_numpy(np.ones(2000)))
        fr.add("hot", Column.from_numpy(np.ones(2000)))
        fr.install()
        try:
            # evict the world so only our freshly-touched columns are
            # device-resident (other test modules leave frames in DKV)
            cleaner.sweep(1 << 60)
            _ = fr.col("cold").data          # touch both, then re-touch hot
            _ = fr.col("hot").data
            _ = fr.col("hot").data
            freed = cleaner.sweep(4)         # tiny target: evict ONE column
            assert freed > 0
            assert fr._cols["cold"].is_evicted
            assert not fr._cols["hot"].is_evicted
        finally:
            fr.delete()


class TestSegmentModels:
    def test_per_segment_training(self, cl):
        from h2o3_tpu.models.segments import train_segments
        from h2o3_tpu.models.tree.gbm import GBM

        rng = np.random.default_rng(6)
        n = 900
        seg = np.array(["s1", "s2", "s3"], object)[rng.integers(0, 3, n)]
        x = rng.standard_normal(n)
        y = np.where(rng.random(n) < 1 / (1 + np.exp(-2 * x)), "Y", "N")
        fr = Frame()
        fr.add("seg", Column.from_numpy(seg, ctype="enum"))
        fr.add("x", Column.from_numpy(x))
        fr.add("y", Column.from_numpy(y, ctype="enum"))
        sm = train_segments(GBM, {"ntrees": 3, "max_depth": 3, "seed": 1},
                            fr, ["seg"], y="y")
        assert len(sm) == 3
        assert all(r["status"] == "SUCCEEDED" for r in sm.rows)
        t = sm.as_frame()
        assert sorted(t.col("seg")) == ["s1", "s2", "s3"]
        # per-segment model is fetchable and excludes the segment column
        from h2o3_tpu.core.dkv import DKV

        m = DKV.get(sm.rows[0]["model_id"])
        assert "seg" not in m._output.names

    def test_segment_failure_captured(self, cl):
        from h2o3_tpu.models.segments import train_segments
        from h2o3_tpu.models.glm import GLM

        fr = Frame()
        fr.add("seg", Column.from_numpy(np.array(["a", "b"] * 20, object),
                                        ctype="enum"))
        fr.add("y", Column.from_numpy(np.ones(40)))   # constant response
        # GLM on a constant response with no predictors errors per segment
        sm = train_segments(GLM, {"family": "gaussian"}, fr, ["seg"], y="y")
        assert len(sm) == 2
        assert all(r["status"] in ("SUCCEEDED", "FAILED") for r in sm.rows)


class TestCBOW:
    def test_cbow_trains_and_embeds(self, cl):
        from h2o3_tpu.models.word2vec import Word2Vec

        rng = np.random.default_rng(0)
        words = []
        for _ in range(300):
            words += ["king", "queen", "royal", None]
            words += ["cat", "dog", "pet", None]
        fr = Frame()
        fr.add("w", Column.from_numpy(np.array(words, object)))
        m = Word2Vec(word_model="CBOW", vec_size=16, epochs=3,
                     min_word_freq=2, seed=1).train(training_frame=fr)
        assert m.word_vec("king") is not None
        syn = m.find_synonyms("king", count=3)
        assert syn          # embeds exist and are queryable

    def test_skipgram_still_default(self, cl):
        from h2o3_tpu.models.word2vec import Word2Vec

        assert Word2Vec.default_params()["word_model"] == "SkipGram"


class TestExtensions:
    def test_extension_hook_runs(self, cl):
        from h2o3_tpu import extensions

        seen = []
        extensions.register_extension("unittest-ext", lambda c: seen.append(c))
        assert seen and seen[0] is cl
        assert "unittest-ext" in extensions.extensions()

    def test_rapids_udf(self, cl):
        from h2o3_tpu import extensions
        from h2o3_tpu.rapids import exec_rapids

        extensions.register_udf("double_it", lambda x: x * 2)
        fr = Frame()
        fr.add("v", Column.from_numpy(np.arange(10, dtype=np.float64)))
        fr.install()
        out = exec_rapids(f"(udf.double_it {fr.key})")
        np.testing.assert_allclose(out.col(0).to_numpy(),
                                   np.arange(10) * 2)


class TestDLModelAveraging:
    def test_local_sgd_with_periodic_averaging(self, cl):
        from h2o3_tpu.models.deeplearning import DeepLearning

        rng = np.random.default_rng(2)
        n = 800
        X = rng.standard_normal((n, 4))
        y = np.where(rng.random(n) < 1 / (1 + np.exp(-2 * X[:, 0])), "Y", "N")
        fr = Frame.from_numpy(X, names=["a", "b", "c", "d"])
        fr.add("y", Column.from_numpy(y, ctype="enum"))
        m = DeepLearning(epochs=3, hidden=[8], mini_batch_size=32,
                         train_samples_per_iteration=2048,   # ~8 local steps
                         seed=5).train(y="y", training_frame=fr)
        assert float(m._output.training_metrics.auc) > 0.6
        p = m.predict(fr).col("Y").to_numpy()
        assert np.all(np.isfinite(p))
        # SGD-with-schedule optimizer carries an int step counter: the
        # averaging pmean must not float-ify it (scan carry contract)
        m2 = DeepLearning(epochs=2, hidden=[8], mini_batch_size=32,
                          adaptive_rate=False, rate=0.01,
                          train_samples_per_iteration=2048,
                          seed=5).train(y="y", training_frame=fr)
        assert np.isfinite(float(m2._output.training_metrics.auc))


class TestIsotonicAndCalibration:
    def test_pava_monotone_fit(self, cl):
        from h2o3_tpu.models.isotonic import IsotonicRegression, pava

        rng = np.random.default_rng(8)
        n = 1200
        x = rng.uniform(-3, 3, n)
        y = np.tanh(x) + rng.normal(0, 0.3, n)
        fr = Frame()
        fr.add("x", Column.from_numpy(x))
        fr.add("y", Column.from_numpy(y))
        m = IsotonicRegression().train(y="y", training_frame=fr)
        # fitted values are non-decreasing
        assert np.all(np.diff(m.thresholds_y) >= -1e-12)
        pred = m.predict(fr).col("predict").to_numpy()
        # monotone in x and close to tanh
        order = np.argsort(x)
        assert np.all(np.diff(pred[order]) >= -1e-5)
        assert np.mean((pred - np.tanh(x)) ** 2) < 0.05
        # out-of-range clips
        fr2 = Frame()
        fr2.add("x", Column.from_numpy(np.array([-100.0, 100.0])))
        p2 = m.predict(fr2).col("predict").to_numpy()
        assert p2[0] == pytest.approx(m.thresholds_y[0], abs=1e-5)
        assert p2[1] == pytest.approx(m.thresholds_y[-1], abs=1e-5)

    def test_tree_calibration(self, cl):
        from h2o3_tpu.models.tree.gbm import GBM

        rng = np.random.default_rng(9)
        n = 1500
        x = rng.standard_normal(n)
        y = np.where(rng.random(n) < 1 / (1 + np.exp(-2 * x)), "Y", "N")
        fr = Frame()
        fr.add("x", Column.from_numpy(x))
        fr.add("y", Column.from_numpy(y, ctype="enum"))
        tr_idx = np.arange(0, n, 2)
        cal_idx = np.arange(1, n, 2)
        from h2o3_tpu.ops.filters import take_rows

        tr, cal = take_rows(fr, tr_idx), take_rows(fr, cal_idx)
        m = GBM(ntrees=10, max_depth=3, seed=1, calibrate_model=True,
                calibration_frame=cal).train(y="y", training_frame=tr)
        pred = m.predict(cal)
        assert "cal_Y" in pred.names and "cal_N" in pred.names
        pc = pred.col("cal_Y").to_numpy()
        assert np.all((pc >= 0) & (pc <= 1))
        # calibrated probabilities track outcomes at least as well (logloss)
        yb = (cal.col("y").to_numpy() ==
              m._output.response_domain.index("Y")).astype(float)
        praw = pred.col("Y").to_numpy()
        ll = lambda p: -np.mean(yb * np.log(np.clip(p, 1e-9, 1)) +  # noqa: E731
                                (1 - yb) * np.log(np.clip(1 - p, 1e-9, 1)))
        assert ll(pc) <= ll(praw) + 0.02

    def test_isotonic_calibration_method(self, cl):
        from h2o3_tpu.models.tree.gbm import GBM
        from h2o3_tpu.ops.filters import take_rows

        rng = np.random.default_rng(10)
        n = 1000
        x = rng.standard_normal(n)
        y = np.where(rng.random(n) < 1 / (1 + np.exp(-2 * x)), "Y", "N")
        fr = Frame()
        fr.add("x", Column.from_numpy(x))
        fr.add("y", Column.from_numpy(y, ctype="enum"))
        tr = take_rows(fr, np.arange(0, n, 2))
        cal = take_rows(fr, np.arange(1, n, 2))
        m = GBM(ntrees=5, max_depth=3, seed=1, calibrate_model=True,
                calibration_frame=cal,
                calibration_method="IsotonicRegression").train(
            y="y", training_frame=tr)
        pc = m.predict(cal).col("cal_Y").to_numpy()
        assert np.all(np.isfinite(pc)) and pc.min() >= 0 and pc.max() <= 1

    def test_calibrate_requires_frame(self, cl):
        from h2o3_tpu.models.tree.gbm import GBM

        fr = Frame()
        fr.add("x", Column.from_numpy(np.arange(100, dtype=np.float64)))
        fr.add("y", Column.from_numpy(
            np.array(["Y", "N"] * 50, object), ctype="enum"))
        with pytest.raises(ValueError, match="calibration_frame"):
            GBM(ntrees=2, calibrate_model=True).train(y="y", training_frame=fr)
