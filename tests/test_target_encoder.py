"""TargetEncoder semantics vs reference TargetEncoderHelper arithmetic.

Reference: ai/h2o/targetencoding/TargetEncoderHelper.java —
getBlendedValue (:256): λ = 1/(1+e^((k−n)/f)); enc = λ·post + (1−λ)·prior;
holdout: None / LeaveOneOut / KFold. Plus the AutoML preprocessing hook
(ai.h2o.automl.preprocessing.TargetEncoding) and the
GET /3/TargetEncoderTransform REST contract.
"""

import numpy as np
import pytest

from h2o3_tpu.core.frame import Column, Frame


@pytest.fixture()
def tframe(cl):
    rng = np.random.default_rng(5)
    n = 600
    g = np.array(["a", "b", "c"])[rng.integers(0, 3, n)]
    rates = {"a": 0.8, "b": 0.5, "c": 0.2}
    y = np.array(["Y" if rng.random() < rates[v] else "N" for v in g])
    fr = Frame()
    fr.add("g", Column.from_numpy(g, ctype="enum"))
    fr.add("x", Column.from_numpy(rng.normal(size=n)))
    fr.add("y", Column.from_numpy(y, ctype="enum"))
    return fr, g, y


def _counts(g, y):
    import collections

    num = collections.Counter()
    den = collections.Counter()
    for gi, yi in zip(g, y):
        num[gi] += (yi == "Y")
        den[gi] += 1
    return num, den


def test_plain_encoding_matches_means(tframe):
    from h2o3_tpu.models.target_encoder import TargetEncoder

    fr, g, y = tframe
    te = TargetEncoder(noise=0.0).train(y="y", training_frame=fr)
    out = te.transform(fr)
    vals = out.col("g_te").to_numpy()
    num, den = _counts(g, y)
    for lvl in "abc":
        want = num[lvl] / den[lvl]
        got = vals[g == lvl]
        np.testing.assert_allclose(got, want, atol=1e-6)


def test_blending_formula(tframe):
    from h2o3_tpu.models.target_encoder import TargetEncoder

    fr, g, y = tframe
    k, f = 35.0, 25.0
    te = TargetEncoder(blending=True, inflection_point=k, smoothing=f,
                       noise=0.0).train(y="y", training_frame=fr)
    out = te.transform(fr)
    vals = out.col("g_te").to_numpy()
    num, den = _counts(g, y)
    prior = sum(num.values()) / sum(den.values())
    for lvl in "abc":
        n = den[lvl]
        lam = 1.0 / (1.0 + np.exp((k - n) / f))    # TargetEncoderHelper.java:256
        want = lam * (num[lvl] / n) + (1 - lam) * prior
        np.testing.assert_allclose(vals[g == lvl], want, atol=1e-6)


def test_leave_one_out(tframe):
    from h2o3_tpu.models.target_encoder import TargetEncoder

    fr, g, y = tframe
    te = TargetEncoder(data_leakage_handling="LeaveOneOut",
                       noise=0.0).train(y="y", training_frame=fr)
    out = te.transform(fr, as_training=True)
    vals = out.col("g_te").to_numpy()
    num, den = _counts(g, y)
    # row i's own target must be excluded
    for i in [0, 10, 100]:
        lvl, yi = g[i], (y[i] == "Y")
        want = (num[lvl] - yi) / (den[lvl] - 1)
        np.testing.assert_allclose(vals[i], want, atol=1e-6)
    # non-training transform still uses full stats
    out2 = te.transform(fr)
    v2 = out2.col("g_te").to_numpy()
    assert not np.allclose(vals, v2)


def test_kfold_out_of_fold(tframe):
    from h2o3_tpu.models.target_encoder import TargetEncoder

    fr, g, y = tframe
    rng = np.random.default_rng(1)
    folds = rng.integers(0, 3, fr.nrows)
    fr.add("fold", Column.from_numpy(folds.astype(np.float64)))
    te = TargetEncoder(data_leakage_handling="KFold", fold_column="fold",
                       noise=0.0).train(y="y", training_frame=fr)
    out = te.transform(fr, as_training=True)
    vals = out.col("g_te").to_numpy()
    for i in [3, 33, 333]:
        lvl, fo = g[i], folds[i]
        mask = (g == lvl) & (folds != fo)
        want = (y[mask] == "Y").mean()
        np.testing.assert_allclose(vals[i], want, atol=1e-6)


def test_unseen_level_gets_prior(tframe, cl):
    from h2o3_tpu.models.target_encoder import TargetEncoder

    fr, g, y = tframe
    te = TargetEncoder(noise=0.0).train(y="y", training_frame=fr)
    test = Frame()
    test.add("g", Column.from_numpy(np.array(["zz", "a"]), ctype="enum"))
    test.add("x", Column.from_numpy(np.zeros(2)))
    out = te.transform(test)
    vals = out.col("g_te").to_numpy()
    num, den = _counts(g, y)
    prior = sum(num.values()) / sum(den.values())
    np.testing.assert_allclose(vals[0], prior, atol=1e-6)
    np.testing.assert_allclose(vals[1], num["a"] / den["a"], atol=1e-6)


def test_noise_only_on_training(tframe):
    from h2o3_tpu.models.target_encoder import TargetEncoder

    fr, g, y = tframe
    te = TargetEncoder(noise=0.05, seed=3).train(y="y", training_frame=fr)
    a = te.transform(fr).col("g_te").to_numpy()
    b = te.transform(fr).col("g_te").to_numpy()
    np.testing.assert_allclose(a, b)        # non-training: deterministic
    c = te.transform(fr, as_training=True).col("g_te").to_numpy()
    assert not np.allclose(a, c)            # training: noise applied


def test_phantom_entry_resolved(cl):
    import h2o3_tpu

    cls = h2o3_tpu.H2OTargetEncoderEstimator
    assert cls.algo_name == "targetencoder"


def test_automl_te_preprocessing(cl):
    from h2o3_tpu.automl.automl import H2OAutoML

    rng = np.random.default_rng(0)
    n = 800
    g = np.array(["a", "b", "c", "d"])[rng.integers(0, 4, n)]
    x = rng.normal(size=n)
    rates = {"a": 0.85, "b": 0.6, "c": 0.4, "d": 0.15}
    y = np.array(["Y" if rng.random() < rates[v] else "N" for v in g])
    fr = Frame()
    fr.add("g", Column.from_numpy(g, ctype="enum"))
    fr.add("x", Column.from_numpy(x))
    fr.add("y", Column.from_numpy(y, ctype="enum"))
    aml = H2OAutoML(max_models=2, nfolds=2, seed=11,
                    include_algos=["glm", "gbm"],
                    preprocessing=["target_encoding"]).train(
        y="y", training_frame=fr)
    assert aml.te_model is not None
    assert len(aml.models) >= 1
    lead = aml.leader
    assert "g_te" in lead._output.names


def test_te_rest_transform(tframe):
    import json
    import urllib.request

    from h2o3_tpu.api.server import start_server
    from h2o3_tpu.models.target_encoder import TargetEncoder

    fr, g, y = tframe
    fr.install()
    te = TargetEncoder(noise=0.0).train(y="y", training_frame=fr)
    srv = start_server(port=0)
    try:
        url = (f"http://127.0.0.1:{srv.port}/3/TargetEncoderTransform"
               f"?model={te.key}&frame={fr.key}&blending=false")
        with urllib.request.urlopen(url) as r:
            out = json.loads(r.read())
        assert out["name"]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/3/Frames/{out['name']}") as r:
            fj = json.loads(r.read())["frames"][0]
        assert any(c["label"] == "g_te" for c in fj["columns"])
    finally:
        srv.stop()
