"""Round-4 ingest closure: native Avro + XLSX parsers and a working
s3:// persist path (mock-endpoint test proving the registry + SigV4
client end-to-end).

Reference: h2o-parsers/h2o-avro-parser/ (AvroParser.java),
h2o XlsxParser, h2o-persist-s3/PersistS3.java."""

import http.server
import io
import threading
import zipfile

import numpy as np
import pytest

import h2o3_tpu as h2o


def test_avro_import_roundtrip(tmp_path, cl):
    from h2o3_tpu.ingest.avro import write_avro

    path = str(tmp_path / "data.avro")
    n = 500
    rng = np.random.default_rng(1)
    xs = rng.normal(size=n)
    gs = ["red", "green", "blue"]
    cols = {"x": [float(v) for v in xs],
            "g": [gs[i % 3] for i in range(n)],
            "k": [int(i) for i in range(n)],
            "maybe": [None if i % 7 == 0 else float(i) for i in range(n)]}
    write_avro(path, cols, [
        {"name": "x", "type": "double"},
        {"name": "g", "type": "string"},
        {"name": "k", "type": "long"},
        {"name": "maybe", "type": ["null", "double"]}], codec="deflate")
    fr = h2o.import_file(path)
    assert fr.nrows == n
    assert fr.names == ["x", "g", "k", "maybe"]
    np.testing.assert_allclose(np.asarray(fr.col("x").to_numpy())[:10],
                               xs[:10], rtol=1e-6)
    m = np.asarray(fr.col("maybe").to_numpy())
    assert np.isnan(m[0]) and np.isnan(m[7])
    assert abs(float(np.nanmean(m)) - np.nanmean(
        [np.nan if i % 7 == 0 else i for i in range(n)])) < 1e-2


def _make_xlsx(path, header, rows):
    """Hand-built minimal xlsx (zip of sheet XML + shared strings)."""
    NS = 'xmlns="http://schemas.openxmlformats.org/spreadsheetml/2006/main"'
    shared, sidx = [], {}

    def sref(s):
        if s not in sidx:
            sidx[s] = len(shared)
            shared.append(s)
        return sidx[s]

    def cell(r, cidx, v):
        col = ""
        ci = cidx + 1
        while ci:
            ci, rem = divmod(ci - 1, 26)
            col = chr(65 + rem) + col
        ref = f"{col}{r}"
        if isinstance(v, str):
            return f'<c r="{ref}" t="s"><v>{sref(v)}</v></c>'
        return f'<c r="{ref}"><v>{v}</v></c>'

    body = []
    body.append("<row r=\"1\">" + "".join(
        cell(1, j, h) for j, h in enumerate(header)) + "</row>")
    for i, row in enumerate(rows):
        body.append(f'<row r="{i + 2}">' + "".join(
            cell(i + 2, j, v) for j, v in enumerate(row) if v is not None)
            + "</row>")
    sheet = (f'<?xml version="1.0"?><worksheet {NS}><sheetData>'
             + "".join(body) + "</sheetData></worksheet>")
    sst = (f'<?xml version="1.0"?><sst {NS} count="{len(shared)}" '
           f'uniqueCount="{len(shared)}">'
           + "".join(f"<si><t>{s}</t></si>" for s in shared) + "</sst>")
    with zipfile.ZipFile(path, "w") as z:
        z.writestr("xl/worksheets/sheet1.xml", sheet)
        z.writestr("xl/sharedStrings.xml", sst)
        z.writestr("[Content_Types].xml", "<Types/>")
    return path


def test_xlsx_import(tmp_path, cl):
    path = str(tmp_path / "book.xlsx")
    _make_xlsx(path, ["name", "value", "n"],
               [["alpha", 1.5, 10], ["beta", 2.5, 20],
                ["gamma", None, 30], ["alpha", 4.0, 40]])
    fr = h2o.import_file(path)
    assert fr.names == ["name", "value", "n"]
    assert fr.nrows == 4
    v = np.asarray(fr.col("value").to_numpy())
    assert np.isnan(v[2]) and v[3] == 4.0
    assert fr.col("name").domain is not None     # strings -> enum


def test_xls_legacy_still_gated(tmp_path, cl):
    from h2o3_tpu.errors import CapabilityGate
    from h2o3_tpu.ingest.formats import detect_parse_type

    with pytest.raises(CapabilityGate):
        detect_parse_type("old.xls")


class _S3Mock(http.server.BaseHTTPRequestHandler):
    """Path-style S3 endpoint: GET /bucket/key serves canned bytes and
    records the request headers for the signing assertion."""

    store = {}
    seen = []

    def do_GET(self):
        type(self).seen.append(dict(self.headers))
        body = self.store.get(self.path)
        if body is None:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):
        pass


def test_s3_import_via_mock_endpoint(tmp_path, cl, monkeypatch):
    csv = b"a,b\n1,x\n2,y\n3,x\n"
    _S3Mock.store = {"/mybucket/data/test.csv": csv}
    _S3Mock.seen = []
    srv = http.server.HTTPServer(("127.0.0.1", 0), _S3Mock)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        monkeypatch.setenv("H2O_TPU_S3_ENDPOINT",
                           f"http://127.0.0.1:{srv.server_port}")
        monkeypatch.setenv("AWS_ACCESS_KEY_ID", "AKIATEST")
        monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "secret")
        fr = h2o.import_file("s3://mybucket/data/test.csv")
        assert fr.nrows == 3
        assert fr.names == ["a", "b"]
        # the request carried a complete SigV4 authorization header
        auth = _S3Mock.seen[0].get("Authorization", "")
        assert auth.startswith("AWS4-HMAC-SHA256 Credential=AKIATEST/")
        assert "Signature=" in auth
        hdrs = {k.lower(): v for k, v in _S3Mock.seen[0].items()}
        assert hdrs.get("x-amz-content-sha256")
    finally:
        srv.shutdown()


def test_s3_anonymous_when_no_creds(tmp_path, cl, monkeypatch):
    csv = b"q\n1\n2\n"
    _S3Mock.store = {"/pub/open.csv": csv}
    _S3Mock.seen = []
    srv = http.server.HTTPServer(("127.0.0.1", 0), _S3Mock)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        monkeypatch.setenv("H2O_TPU_S3_ENDPOINT",
                           f"http://127.0.0.1:{srv.server_port}")
        monkeypatch.delenv("AWS_ACCESS_KEY_ID", raising=False)
        monkeypatch.delenv("AWS_SECRET_ACCESS_KEY", raising=False)
        fr = h2o.import_file("s3://pub/open.csv")
        assert fr.nrows == 2
        assert "Authorization" not in _S3Mock.seen[0]
    finally:
        srv.shutdown()
