"""Rapids statement-fusion + lazy-session suite (ISSUEs 10 + 14).

Covers: (1) the fused-vs-eager bitwise-equivalence property over
randomized AST chains (elementwise/filter/reduce/ifelse compositions,
NA paths — the fused path must be indistinguishable from op-at-a-time
evaluation); (2) the compile-cache contract (structure-only signatures,
zero compiles warm, persistent tier across a simulated restart); (3) the
sharded-data-plane guard (``gathered_rows == 0`` on fused statements and
on enum-keyed group-by / device-join inputs, with numeric-key group-by
and host joins as the counted demoted path); (4) the Session refcount
token fix; (5) the h2o3_rapids_* observability surface, including the
traced-statement zero-added-syncs assertion; (6) the LAZY session
engine (rapids/planner.py): randomized chained multi-statement sessions
lazy-vs-eager bitwise (incl. CSE dedup, dead temps, overwrites and the
SSA pinning regression), deferral/flush counter semantics, and the
fused sort+selection window; (7) the device relational prims
(segmented-scan rank_within_groupby, device difflag1, device sort) vs
their host-walk references across NaN ordering, ties and descending
keys, with ``gathered_rows == 0`` counter-asserted.
"""

import gc

import numpy as np
import pytest

from h2o3_tpu.core.frame import Column, Frame, T_CAT
from h2o3_tpu.rapids import Session, exec_rapids
from h2o3_tpu.rapids import fusion, planner

pytestmark = pytest.mark.rapids

FR = "fusion_test_fr"


@pytest.fixture()
def sess(cl):
    s = Session("fusion_t")
    yield s
    s.end()


@pytest.fixture()
def fr(cl):
    rng = np.random.default_rng(11)
    f = Frame(key=FR)
    a = rng.standard_normal(40)
    a[[3, 17, 29]] = np.nan                   # NA paths are first-class
    f.add("a", Column.from_numpy(a))
    f.add("b", Column.from_numpy(rng.standard_normal(40)))
    c = rng.uniform(-2.0, 2.0, 40)
    c[7] = np.nan
    f.add("c", Column.from_numpy(c))
    f.add("g", Column.from_numpy(
        np.asarray(["x", "y", "z", "y"] * 10, object), ctype=T_CAT))
    f.install()
    yield f
    f.delete()


def _both(stmt, sess):
    """Evaluate one statement fused and eager; returns (fused, eager)."""
    with fusion.force(True):
        vf = exec_rapids(stmt, sess)
    with fusion.force(False):
        ve = exec_rapids(stmt, sess)
    return vf, ve


def _col_equal(vf, ve):
    af = np.asarray(vf.col(0).to_numpy())
    ae = np.asarray(ve.col(0).to_numpy())
    assert af.dtype == ae.dtype
    assert np.array_equal(af, ae, equal_nan=True), (af, ae)
    assert vf.names == ve.names


# ---------------------------------------------------------------------------
# randomized equivalence property
# ---------------------------------------------------------------------------

_BINS = ["+", "-", "*", "/"]
_CMPS = ["<", ">", "<=", ">=", "==", "!="]
_UNS = ["abs", "sqrt", "floor", "ceiling", "sign", "exp", "log"]


def _gen(rng, depth):
    """Random fusible expression string (leaves: frame columns incl. the
    NA-carrying and enum ones, plus literals)."""
    if depth <= 0:
        if rng.random() < 0.6:
            i = int(rng.integers(0, 4))
            return f"(cols {FR} [{i}])"
        return f"{rng.uniform(-2, 2):.3f}"
    roll = rng.random()
    if roll < 0.35:
        op = _BINS[rng.integers(0, len(_BINS))]
        return f"({op} {_gen(rng, depth - 1)} {_gen(rng, depth - 1)})"
    if roll < 0.5:
        op = _CMPS[rng.integers(0, len(_CMPS))]
        return f"({op} {_gen(rng, depth - 1)} {_gen(rng, depth - 1)})"
    if roll < 0.62:
        op = "&" if rng.random() < 0.5 else "|"
        # logical needs a column ref on at least one side
        return f"({op} (> (cols {FR} [0]) 0) {_gen(rng, depth - 1)})"
    if roll < 0.78:
        op = _UNS[rng.integers(0, len(_UNS))]
        return f"({op} (+ {_gen(rng, depth - 1)} (cols {FR} [1])))"
    if roll < 0.9:
        return (f"(ifelse (> (cols {FR} [{int(rng.integers(0, 3))}]) 0) "
                f"{_gen(rng, depth - 1)} {_gen(rng, depth - 1)})")
    return f"(is.na (+ (cols {FR} [0]) {_gen(rng, depth - 1)}))"


@pytest.mark.parametrize("seed", range(24))
def test_randomized_chain_equivalence(seed, cl, fr, sess):
    rng = np.random.default_rng(seed)
    stmt = _gen(rng, int(rng.integers(2, 5)))
    while not stmt.startswith("("):           # root must be a compute node
        stmt = _gen(rng, 3)
    before = fusion.counters()["fused_programs"]
    vf, ve = _both(stmt, sess)
    _col_equal(vf, ve)
    assert fusion.counters()["fused_programs"] > before, (
        f"statement {stmt!r} did not take the fused path")


def test_reducer_equivalence(cl, fr, sess):
    for red in ("mean", "sum", "min", "max", "sd", "var", "naCnt",
                "any", "all"):
        stmt = f"({red} (* (+ (cols {FR} [0]) (cols {FR} [1])) 0.5))"
        with fusion.force(True):
            vf = exec_rapids(stmt, sess)
        with fusion.force(False):
            ve = exec_rapids(stmt, sess)
        assert vf == ve or (vf != vf and ve != ve), (red, vf, ve)


def test_rows_filter_equivalence(cl, fr, sess):
    stmt = (f"(rows {FR} (& (> (+ (cols {FR} [0]) (cols {FR} [1])) 0) "
            f"(< (cols {FR} [2]) 1)))")
    vf, ve = _both(stmt, sess)
    assert vf.nrows == ve.nrows
    for n in vf.names:
        cf, ce = vf.col(n), ve.col(n)
        if cf.is_categorical:
            assert list(cf.values()) == list(ce.values())
        else:
            assert np.array_equal(cf.to_numpy(), ce.to_numpy(),
                                  equal_nan=True)


def test_all_na_and_enum_paths(cl, sess):
    f = Frame(key="fusion_na_fr")
    f.add("a", Column.from_numpy(np.full(16, np.nan)))
    f.add("g", Column.from_numpy(
        np.asarray(["u", "v"] * 8, object), ctype=T_CAT))
    f.install()
    try:
        for stmt in (
                "(+ (cols fusion_na_fr [0]) 1)",
                "(is.na (cols fusion_na_fr [0]))",
                "(ifelse (is.na (cols fusion_na_fr [0])) "
                "(cols fusion_na_fr [1]) 0)",
                "(== (cols fusion_na_fr [1]) 1)",   # enum codes as numerics
        ):
            vf, ve = _both(stmt, sess)
            _col_equal(vf, ve)
    finally:
        f.delete()


def test_mask_multiply_na_propagation(cl, fr, sess):
    """0*NaN / 1*NaN must stay NaN through fused mask arithmetic — the
    XLA simplifier's multiply(convert(pred), x) -> select(pred, x, 0)
    rewrite would silently drop it inside one program (the reason
    isna_expr emits a select; this is the regression pin)."""
    for mask in (f"(is.na (cols {FR} [0]))",
                 f"(== (cols {FR} [1]) 0)",
                 f"(& (> (cols {FR} [1]) 0) (< (cols {FR} [1]) 9))"):
        for stmt in (f"(* {mask} (cols {FR} [2]))",
                     f"(+ (cols {FR} [0]) (* {mask} (cols {FR} [2])))"):
            vf, ve = _both(stmt, sess)
            _col_equal(vf, ve)


def test_assigned_statement_fuses(cl, fr, sess):
    """(tmp= ...) roots fuse their RHS — the evaluator offers the inner
    compute node, so assignment costs no fusion opportunity. (Lazy
    deferral pinned off: this is the EAGER-path contract; the lazy
    engine's own counter semantics live in TestLazySession.)"""
    from h2o3_tpu.rapids import planner

    before = fusion.counters()["fused_programs"]
    with planner.force(False), fusion.force(True):
        out = exec_rapids(
            f"(tmp= fusion_assigned (* (+ (cols {FR} [0]) 1) 2))", sess)
    assert fusion.counters()["fused_programs"] == before + 1
    with fusion.force(False):
        ref = exec_rapids(f"(* (+ (cols {FR} [0]) 1) 2)", sess)
    assert np.array_equal(out.col(0).to_numpy(), ref.col(0).to_numpy(),
                          equal_nan=True)


# ---------------------------------------------------------------------------
# lazy session engine (ISSUE 14): chained statements lazy-vs-eager bitwise
# ---------------------------------------------------------------------------

def _gen_chain(rng, n_stmts, prefix):
    """Random chained session: tmp= statements over frame columns AND
    earlier temps (single-col Id refs), with overwrites sprinkled in.
    Returns (statements, live_keys)."""
    temps = []
    stmts = []

    def leaf(depth):
        roll = rng.random()
        if roll < 0.5:
            return f"(cols {FR} [{int(rng.integers(0, 3))}])"
        if roll < 0.8 and temps:
            return temps[int(rng.integers(0, len(temps)))]
        return f"{rng.uniform(-2, 2):.3f}"

    def expr(depth):
        if depth <= 0:
            l = leaf(depth)
            return l if l.startswith("(") or l.lstrip("-")[0].isalpha() \
                else f"(+ {l} (cols {FR} [0]))"
        roll = rng.random()
        if roll < 0.45:
            op = _BINS[rng.integers(0, len(_BINS))]
            return f"({op} {expr(depth - 1)} {leaf(depth)})"
        if roll < 0.6:
            op = _CMPS[rng.integers(0, len(_CMPS))]
            return f"({op} {expr(depth - 1)} {leaf(depth)})"
        if roll < 0.75:
            op = _UNS[rng.integers(0, len(_UNS))]
            return f"({op} {expr(depth - 1)})"
        return (f"(ifelse (> {expr(depth - 1)} 0) "
                f"{leaf(depth)} {expr(depth - 1)})")

    for i in range(n_stmts):
        if temps and rng.random() < 0.25:
            key = temps[int(rng.integers(0, len(temps)))]   # overwrite
        else:
            key = f"{prefix}_t{i}"
        stmts.append(f"(tmp= {key} {expr(int(rng.integers(1, 4)))})")
        if key not in temps:
            temps.append(key)
    return stmts, temps


class TestLazySession:
    def _run_chain(self, stmts, keys, lazy: bool, sess):
        with planner.force(lazy), fusion.force(lazy):
            for s in stmts:
                exec_rapids(s, sess)
            return {k: np.asarray(exec_rapids(k, sess).col(0).to_numpy())
                    for k in keys}

    @pytest.mark.parametrize("seed", range(12))
    def test_randomized_chained_sessions_bitwise(self, seed, cl, fr):
        """The ISSUE-14 acceptance property: a whole deferred session —
        CSE, dead temps from overwrites, inlined intermediates — must be
        bitwise indistinguishable from op-at-a-time eager evaluation of
        the same statements, for EVERY live temp."""
        rng = np.random.default_rng(1000 + seed)
        stmts, keys = _gen_chain(rng, int(rng.integers(3, 8)), f"lz{seed}")
        s_lazy = Session(f"lz{seed}")
        s_eager = Session(f"le{seed}")
        try:
            lazy = self._run_chain(stmts, keys, True, s_lazy)
            # lazy first, then eager re-assigns the same keys eagerly
            eager = self._run_chain(stmts, keys, False, s_eager)
            for k in keys:
                assert lazy[k].dtype == eager[k].dtype
                assert np.array_equal(lazy[k], eager[k],
                                      equal_nan=True), (k, stmts)
        finally:
            s_lazy.end()
            s_eager.end()

    def test_deferral_and_flush_counters(self, cl, fr):
        s = Session("lz_count")
        try:
            with planner.force(True), fusion.force(True):
                c0 = planner.counters()
                exec_rapids(f"(tmp= lzc_a (+ (cols {FR} [0]) 1))", s)
                exec_rapids("(tmp= lzc_b (* lzc_a 2))", s)
                c1 = planner.counters()
                assert c1["deferred_statements"] == \
                    c0["deferred_statements"] + 2
                assert c1["deferred_pending"] >= c0["deferred_pending"] + 2
                progs0 = fusion.counters()["fused_programs"]
                v = exec_rapids("lzc_b", s).col(0).to_numpy()
                c2 = planner.counters()
                assert c2["flushes"] == c1["flushes"] + 1
                assert c2["deferred_pending"] == 0
                assert fusion.counters()["fused_programs"] > progs0
            with planner.force(False), fusion.force(False):
                ref = exec_rapids(
                    f"(* (+ (cols {FR} [0]) 1) 2)", Session("lz_ref"))
            assert np.array_equal(v, ref.col(0).to_numpy(), equal_nan=True)
        finally:
            s.end()

    def test_cse_dedup_identical_statements(self, cl, fr):
        """Two structurally identical deferred temps compute ONE program
        execution (counter-asserted) with bitwise-equal results."""
        s = Session("lz_cse")
        try:
            with planner.force(True), fusion.force(True):
                exec_rapids(f"(tmp= cse_a (* (+ (cols {FR} [0]) "
                            f"(cols {FR} [1])) 2))", s)
                exec_rapids(f"(tmp= cse_b (* (+ (cols {FR} [0]) "
                            f"(cols {FR} [1])) 2))", s)
                hits0 = planner.counters()["cse_hits"]
                va = exec_rapids("cse_a", s).col(0).to_numpy()
                vb = exec_rapids("cse_b", s).col(0).to_numpy()
            assert planner.counters()["cse_hits"] == hits0 + 1
            assert np.array_equal(va, vb, equal_nan=True)
        finally:
            s.end()

    def test_dead_temp_is_never_computed(self, cl, fr):
        """Overwritten/rm-ed temps with no live reader are eliminated:
        the flush runs zero programs for them."""
        s = Session("lz_dead")
        try:
            with planner.force(True), fusion.force(True):
                exec_rapids(f"(tmp= dead_x (exp (cols {FR} [0])))", s)
                exec_rapids("(rm dead_x)", s)
                exec_rapids(f"(tmp= dead_y (+ (cols {FR} [1]) 1))", s)
                d0 = planner.counters()["dead_temps_eliminated"]
                exec_rapids("dead_y", s).col(0).to_numpy()
                assert planner.counters()["dead_temps_eliminated"] == d0 + 1
        finally:
            s.end()

    def test_overwrite_preserves_ssa_inputs(self, cl, fr):
        """The satellite regression: assign temp -> overwrite the SAME
        temp with an RHS that reads it -> flush must compute from the
        ORIGINAL version (defer-time SSA snapshot), not the rebound
        key."""
        base = None
        with planner.force(False), fusion.force(False):
            base = exec_rapids(f"(* (+ (cols {FR} [0]) 1) 2)",
                               Session("lz_ssa_ref")).col(0).to_numpy()
        s = Session("lz_ssa")
        try:
            with planner.force(True), fusion.force(True):
                exec_rapids(f"(tmp= ssa_w (+ (cols {FR} [0]) 1))", s)
                exec_rapids("(tmp= ssa_w (* ssa_w 2))", s)   # reads v1
                out = exec_rapids("ssa_w", s).col(0).to_numpy()
            assert np.array_equal(out, base, equal_nan=True)
        finally:
            s.end()

    def test_deferred_inputs_are_pinned(self, cl, fr):
        """Defer over a session temp, rm the temp, flush: the node's
        snapshot still computes (refcount pin + hard refs)."""
        s = Session("lz_pin")
        try:
            with planner.force(False), fusion.force(False):
                exec_rapids(f"(tmp= pin_src (+ (cols {FR} [0]) "
                            f"(cols {FR} [1])))", s)
            src_col = s.temps["pin_src"].col(0)
            base_refs = s.column_refs(src_col)
            with planner.force(True), fusion.force(True):
                exec_rapids("(tmp= pin_out (* pin_src 3))", s)
                assert s.column_refs(src_col) == base_refs + 1
                exec_rapids("(rm pin_src)", s)
                out = exec_rapids("pin_out", s).col(0).to_numpy()
            assert s.column_refs(src_col) <= base_refs
            with planner.force(False), fusion.force(False):
                ref = exec_rapids(f"(* (+ (cols {FR} [0]) (cols {FR} [1]))"
                                  f" 3)", Session("lz_pin_ref"))
            assert np.array_equal(out, ref.col(0).to_numpy(),
                                  equal_nan=True)
        finally:
            s.end()

    def test_sort_selection_fuses_to_window(self, cl, fr):
        """sort -> head over a dead sort temp runs as ONE windowed
        sort+selection (counter-asserted), bitwise-identical to the
        materialized path, with zero gathered rows."""
        from h2o3_tpu.core import sharded_frame

        s = Session("lz_topk")
        try:
            with planner.force(True):
                exec_rapids(f"(tmp= tk_s (sort {FR} [0] [1]))", s)
                exec_rapids("(tmp= tk_h (rows tk_s [0:7]))", s)
                exec_rapids("(rm tk_s)", s)
                f0 = planner.counters()["fused_sort_selections"]
                g0 = sharded_frame.counters()["gathered_rows"]
                head = exec_rapids("tk_h", s)
                hv = {n: head.col(n).to_numpy() for n in head.names}
                assert planner.counters()["fused_sort_selections"] == f0 + 1
                assert sharded_frame.counters()["gathered_rows"] == g0
            with planner.force(False):
                ref = exec_rapids(f"(rows (sort {FR} [0] [1]) [0:7])",
                                  Session("lz_topk_ref"))
            assert head.nrows == ref.nrows == 7
            for n in ref.names:
                if ref.col(n).is_categorical:
                    assert list(head.col(n).values()) == \
                        list(ref.col(n).values())
                else:
                    assert np.array_equal(hv[n], ref.col(n).to_numpy(),
                                          equal_nan=True), n
        finally:
            s.end()

    def test_observation_statement_flushes_first(self, cl, fr):
        """A statement the planner cannot defer is an observation point:
        pending temps materialize BEFORE it runs (statement order)."""
        s = Session("lz_obs")
        try:
            with planner.force(True), fusion.force(True):
                exec_rapids(f"(tmp= obs_a (+ (cols {FR} [0]) 5))", s)
                assert planner.counters()["deferred_pending"] >= 1
                m = exec_rapids("(mean obs_a)", s)      # barrier: flush
                assert planner.counters()["deferred_pending"] == 0
            with planner.force(False), fusion.force(False):
                ref = exec_rapids(f"(mean (+ (cols {FR} [0]) 5))",
                                  Session("lz_obs_ref"))
            assert (m == ref) or (m != m and ref != ref)
        finally:
            s.end()

    def test_eager_replay_with_dead_intermediate_terminates(self, cl, fr):
        """Review regression: with fusion OFF (the emergency-rollback
        knob) an rm'd single-consumer intermediate must eager-replay
        cleanly — the flush used to mark it inlined, and the consumer's
        eager replay re-entered the flush through the lazy-leaf loader
        without bound."""
        s = Session("lz_replay")
        try:
            with planner.force(True), fusion.force(False):
                exec_rapids(f"(tmp= rp_a (+ (cols {FR} [0]) 1))", s)
                exec_rapids("(tmp= rp_b (* rp_a 2))", s)
                exec_rapids("(rm rp_a)", s)
                out = exec_rapids("rp_b", s).col(0).to_numpy()
            with planner.force(False), fusion.force(False):
                ref = exec_rapids(f"(* (+ (cols {FR} [0]) 1) 2)",
                                  Session("lz_replay_ref"))
            assert np.array_equal(out, ref.col(0).to_numpy(),
                                  equal_nan=True)
        finally:
            s.end()

    def test_failed_fused_execute_falls_back_without_recursion(
            self, cl, fr, monkeypatch):
        """Same recursion surface via the other trigger: execute_plan
        raising mid-flush (fusion ON, inline set populated) must degrade
        to eager replay with deps force-materialized."""
        s = Session("lz_replay2")
        try:
            with planner.force(True), fusion.force(True):
                exec_rapids(f"(tmp= rp2_a (+ (cols {FR} [0]) 1))", s)
                exec_rapids("(tmp= rp2_b (* rp2_a 2))", s)
                exec_rapids("(rm rp2_a)", s)
                monkeypatch.setattr(
                    fusion, "execute_plan",
                    lambda plan: (_ for _ in ()).throw(
                        RuntimeError("forced execute failure")))
                e0 = planner.counters()["eager_replays"]
                out = exec_rapids("rp2_b", s).col(0).to_numpy()
                assert planner.counters()["eager_replays"] > e0
            with planner.force(False), fusion.force(False):
                ref = exec_rapids(f"(* (+ (cols {FR} [0]) 1) 2)",
                                  Session("lz_replay2_ref"))
            assert np.array_equal(out, ref.col(0).to_numpy(),
                                  equal_nan=True)
        finally:
            s.end()

    def test_session_end_retires_without_compute(self, cl, fr):
        s = Session("lz_end")
        with planner.force(True):
            exec_rapids(f"(tmp= end_a (log (cols {FR} [2])))", s)
            e0 = planner.counters()["eager_replays"]
            p0 = fusion.counters()["fused_programs"]
            d0 = planner.counters()["dead_temps_eliminated"]
            s.end()
        assert planner.counters()["dead_temps_eliminated"] == d0 + 1
        assert planner.counters()["eager_replays"] == e0
        assert fusion.counters()["fused_programs"] == p0


# ---------------------------------------------------------------------------
# device relational prims vs host references (NaNs, ties, descending)
# ---------------------------------------------------------------------------

def _host_rank_reference(cols_g, cols_s, asc):
    """The exact pre-device host walk (lexsort + per-group counter that
    skips NA sort keys without advancing)."""
    n = len(cols_g[0]) if cols_g else len(cols_s[0])
    gkeys = [np.asarray(c) for c in cols_g]
    skeys = [np.asarray(c, np.float64) for c in cols_s]
    order_keys = []
    for k, a in zip(reversed(skeys), reversed(list(asc))):
        order_keys.append(k if a else -k)
    order = np.lexsort(tuple(order_keys) + tuple(reversed(gkeys)))
    rank = np.full(n, np.nan)
    prev_g = None
    r = 0
    for pos in order:
        gk = tuple(k[pos] for k in gkeys)
        if any(np.isnan(np.asarray(skeys)[:, pos])):
            continue
        if gk != prev_g:
            prev_g = gk
            r = 0
        r += 1
        rank[pos] = r
    return rank


class TestDeviceRelational:
    @pytest.fixture()
    def rk_fr(self, cl):
        rng = np.random.default_rng(7)
        n = 61                                     # odd: exercises padding
        f = Frame(key="rank_dev_fr")
        f.add("g", Column.from_numpy(
            np.asarray([["u", "v", "w"][i % 3] for i in range(n)],
                       object), ctype=T_CAT))
        gn = rng.integers(0, 3, n).astype(np.float64)
        gn[5] = np.nan                             # NaN group key
        f.add("gn", Column.from_numpy(gn))
        s1 = np.round(rng.standard_normal(n), 1)   # heavy ties
        s1[[2, 9, 33]] = np.nan                    # NA sort keys
        f.add("s1", Column.from_numpy(s1))
        f.add("s2", Column.from_numpy(rng.standard_normal(n)))
        f.install()
        yield f
        f.delete()

    @pytest.mark.parametrize("gsel,ssel,asc", [
        ([0], [2], [True]),                 # enum group, NA + ties
        ([0], [2], [False]),                # descending
        ([1], [2, 3], [True, False]),       # NaN group key, mixed dirs
        ([0, 1], [3], [True]),              # multi group keys
        ([], [2], [False]),                 # global rank, desc, NAs
    ])
    def test_rank_within_groupby_device_vs_host(self, cl, rk_fr, gsel,
                                                ssel, asc):
        from h2o3_tpu.core import sharded_frame
        from h2o3_tpu.ops import window

        g0 = sharded_frame.counters()["gathered_rows"]
        dev = window.rank_within_groupby_device(rk_fr, gsel, ssel, asc)
        assert dev is not None
        assert sharded_frame.counters()["gathered_rows"] == g0
        ref = _host_rank_reference(
            [np.asarray(rk_fr.col(i).to_numpy()) for i in gsel],
            [np.asarray(rk_fr.col(i).to_numpy(), np.float64)
             for i in ssel], asc)
        got = np.asarray(dev.to_numpy(), np.float64)
        assert np.array_equal(got, ref, equal_nan=True), (gsel, ssel, asc)

    def test_rank_prim_stays_device(self, cl, rk_fr, sess):
        from h2o3_tpu.core import sharded_frame

        g0 = sharded_frame.counters()["gathered_rows"]
        out = exec_rapids(
            '(rank_within_groupby rank_dev_fr [0] [2] [1] "rk" 0)', sess)
        assert sharded_frame.counters()["gathered_rows"] == g0
        ref = _host_rank_reference(
            [np.asarray(rk_fr.col(0).to_numpy())],
            [np.asarray(rk_fr.col(2).to_numpy(), np.float64)], [True])
        assert np.array_equal(np.asarray(out.col("rk").to_numpy(),
                                         np.float64), ref, equal_nan=True)

    def test_difflag1_device_bitwise(self, cl, rk_fr, sess):
        out = exec_rapids("(difflag1 (cols rank_dev_fr [2]))",
                          sess).col(0).to_numpy()
        x = np.asarray(rk_fr.col("s1").to_numpy(), np.float64)
        ref = np.concatenate([[np.nan], x[1:] - x[:-1]]).astype(np.float32)
        assert np.array_equal(out, ref, equal_nan=True)

    @pytest.mark.parametrize("asc", [[True], [False], [True, False],
                                     [False, False]])
    def test_device_sort_matches_numpy_lexsort(self, cl, rk_fr, asc):
        """Device sort (NaN keys last, stable ties, descending) against
        the numpy reference, with the permutation never leaving device
        (device_sorted_rows counter-asserted, gathered 0)."""
        from h2o3_tpu.core import sharded_frame
        from h2o3_tpu.ops.sort import sort_frame

        names = ["s1", "s2"][: len(asc)]
        c0 = sharded_frame.counters()
        out = sort_frame(rk_fr, names, ascending=asc)
        c1 = sharded_frame.counters()
        assert c1["device_sorted_rows"] == \
            c0["device_sorted_rows"] + rk_fr.nrows
        assert c1["gathered_rows"] == c0["gathered_rows"]
        keys = []
        for nm, a in zip(reversed(names), reversed(asc)):
            k = np.asarray(rk_fr.col(nm).to_numpy(), np.float64)
            keys.append(k if a else -k)
        order = np.lexsort(tuple(keys))
        for nm in rk_fr.names:
            ref = np.asarray(rk_fr.col(nm).to_numpy())[order]
            got = np.asarray(out.col(nm).to_numpy())
            assert np.array_equal(got, ref, equal_nan=True), nm

    def test_sort_window_equals_full_sort_slice(self, cl, rk_fr):
        from h2o3_tpu.ops.filters import slice_rows
        from h2o3_tpu.ops.sort import sort_frame

        full = slice_rows(sort_frame(rk_fr, ["s1"], ascending=[False]),
                          3, 11)
        win = sort_frame(rk_fr, ["s1"], ascending=[False], rows=(3, 11))
        assert win.nrows == full.nrows == 8
        for nm in rk_fr.names:
            assert np.array_equal(win.col(nm).to_numpy(),
                                  full.col(nm).to_numpy(),
                                  equal_nan=True), nm

    def test_inner_merge_keeps_indices_on_device(self, cl):
        """Inner device join: pair indices never staged on host —
        gathered stays 0 and the result matches the host-pair path."""
        from h2o3_tpu.core import sharded_frame
        from h2o3_tpu.ops.merge import merge

        l = Frame(key="mrg_dev_l")
        l.add("k", Column.from_numpy(np.arange(30, dtype=float) % 7))
        l.add("v", Column.from_numpy(np.arange(30, dtype=float)))
        r = Frame(key="mrg_dev_r")
        r.add("k", Column.from_numpy(np.asarray([0., 2., 4., 6.])))
        r.add("w", Column.from_numpy(np.asarray([10., 20., 30., 40.])))
        try:
            g0 = sharded_frame.counters()["gathered_rows"]
            out = merge(l, r)
            assert sharded_frame.counters()["gathered_rows"] == g0
            lk = np.asarray(l.col("k").to_numpy())
            hits = np.isin(lk, [0., 2., 4., 6.])
            assert out.nrows == int(hits.sum())
            wmap = {0.: 10., 2.: 20., 4.: 30., 6.: 40.}
            ok = np.asarray(out.col("k").to_numpy())
            ow = np.asarray(out.col("w").to_numpy())
            assert all(wmap[float(k)] == float(w) for k, w in zip(ok, ow))
        finally:
            l.delete()
            r.delete()


# ---------------------------------------------------------------------------
# compile-cache contract
# ---------------------------------------------------------------------------

def test_signature_cache_shares_programs_across_literals(cl, fr, sess):
    """Constants are traced arguments: statements that differ only in
    literals share ONE compiled program (AST shape × dtypes × rows
    bucket)."""
    with fusion.force(True):
        start = fusion.counters()
        exec_rapids(f"(+ (* (cols {FR} [0]) 3) (cols {FR} [1]))", sess)
        c0 = fusion.counters()
        exec_rapids(f"(+ (* (cols {FR} [0]) 99) (cols {FR} [1]))", sess)
        c1 = fusion.counters()
    assert c1["fused_programs_compiled"] == c0["fused_programs_compiled"]
    assert c1["compile_cache_hits"] > c0["compile_cache_hits"]
    # same segment count both times (the statement splits at the FMA
    # boundary, so it may be more than one program)
    assert (c1["fused_programs"] - c0["fused_programs"]
            == c0["fused_programs"] - start["fused_programs"])


def test_persistent_cache_survives_restart(cl, fr, sess, tmp_path,
                                           monkeypatch):
    """PR-6 persistent tier: drop the in-memory program cache (simulated
    process restart) — the statement shape reloads from disk and compiles
    ZERO programs."""
    from h2o3_tpu.artifact import compile_cache

    monkeypatch.setenv("H2O_TPU_COMPILE_CACHE_DIR", str(tmp_path))
    stmt = f"(- (* (cols {FR} [2]) 2) (cols {FR} [1]))"
    # cold in-memory state: every segment must compile (and store) under
    # the persistent tier, or the restart below would re-compile segments
    # that were warmed before the tier existed
    fusion.clear_programs()
    with fusion.force(True):
        exec_rapids(stmt, sess)
        if not any(p.name.startswith("xc_") for p in tmp_path.iterdir()):
            pytest.skip("this jax cannot serialize executables")
        fusion.clear_programs()
        c0 = fusion.counters()
        vf = exec_rapids(stmt, sess)
        c1 = fusion.counters()
    assert c1["fused_programs_compiled"] == c0["fused_programs_compiled"], \
        "a warm restart must compile zero fused programs"
    assert c1["compile_cache_hits"] > c0["compile_cache_hits"]
    with fusion.force(False):
        ve = exec_rapids(stmt, sess)
    _col_equal(vf, ve)


# ---------------------------------------------------------------------------
# sharded data-plane guard
# ---------------------------------------------------------------------------

class TestShardedGuard:
    def test_fused_statements_never_gather(self, cl, fr, sess):
        """The ISSUE acceptance counter: fused statements over sharded
        frames build everything from the columns' row shards in place —
        gathered_rows must not move, packed_rows covers the statement."""
        from h2o3_tpu.core import sharded_frame

        with fusion.force(True):
            exec_rapids(f"(+ (cols {FR} [0]) 1)", sess)   # warm compile
            before = sharded_frame.counters()
            exec_rapids(
                f"(ifelse (> (cols {FR} [0]) 0) (* (cols {FR} [1]) 2) "
                f"(- (cols {FR} [2]) 1))", sess)
            after = sharded_frame.counters()
        assert after["gathered_rows"] == before["gathered_rows"], (
            "a fused rapids statement pulled a column to the host")
        assert after["packed_rows"] >= before["packed_rows"] + fr.nrows

    def test_enum_groupby_input_never_gathers(self, cl, fr, sess):
        """Enum-keyed group-by consumes device codes + host domains: no
        column gather (the fused group-by input contract)."""
        from h2o3_tpu.core import sharded_frame

        before = sharded_frame.counters()
        exec_rapids(f'(GB {FR} [3] "mean" 0 "all" "nrow" 0 "all")', sess)
        after = sharded_frame.counters()
        assert after["gathered_rows"] == before["gathered_rows"]
        assert after["packed_rows"] >= before["packed_rows"] + fr.nrows

    def test_numeric_groupby_key_is_the_counted_demoted_path(self, cl, fr,
                                                             sess):
        from h2o3_tpu.core import sharded_frame

        before = sharded_frame.counters()
        exec_rapids(f'(GB {FR} [0] "mean" 1 "all")', sess)
        after = sharded_frame.counters()
        assert after["gathered_rows"] >= before["gathered_rows"] + fr.nrows

    def test_device_join_inputs_never_gather(self, cl, sess):
        """Numeric/enum-keyed merge consumes the key columns' own padded
        device buffers (sliced inside the compiled rank program) — no
        host staging of key columns."""
        from h2o3_tpu.core import sharded_frame
        from h2o3_tpu.ops.merge import merge

        l = Frame(key="fusion_join_l")
        l.add("k", Column.from_numpy(np.arange(24, dtype=float) % 6))
        l.add("v", Column.from_numpy(np.arange(24, dtype=float)))
        r = Frame(key="fusion_join_r")
        r.add("k", Column.from_numpy(np.arange(6, dtype=float)))
        r.add("w", Column.from_numpy(np.arange(6, dtype=float) * 10))
        try:
            before = sharded_frame.counters()
            out = merge(l, r)
            after = sharded_frame.counters()
            assert after["gathered_rows"] == before["gathered_rows"]
            assert after["packed_rows"] >= \
                before["packed_rows"] + l.nrows + r.nrows
            assert out.nrows == 24
        finally:
            l.delete()
            r.delete()


# ---------------------------------------------------------------------------
# Session refcounts (satellite: stable tokens, not id())
# ---------------------------------------------------------------------------

class TestSessionTokens:
    def test_column_refs_by_token(self, cl, fr):
        s = Session("tok_t")
        col = fr.col("a")
        s.assign("t1", fr)
        s.assign("t2", fr)
        assert s.column_refs(col) == 2
        s.remove("t1")
        assert s.column_refs(col) == 1
        s.end()
        assert s.column_refs(col) == 0

    def test_tokens_survive_gc_without_reuse(self, cl):
        """The id() bug this fix closes: a dead Column's identity must
        never be claimable by a new Column. Tokens are minted from a
        process counter, so even an id()-recycled object gets a fresh
        token and a zero refcount."""
        s = Session("tok_gc")
        f = Frame(key="tok_gc_fr")
        f.add("x", Column.from_numpy(np.arange(8, dtype=float)))
        tok_old = f.col("x").token
        s.assign("tmp_gc", f)
        assert s.refcnt.get(tok_old) == 1
        s.remove("tmp_gc")
        f.delete()
        del f
        gc.collect()
        fresh = Column.from_numpy(np.arange(8, dtype=float))
        assert fresh.token != tok_old
        assert s.column_refs(fresh) == 0
        assert fresh.token == fresh.token      # stable once minted
        s.end()


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------

class TestObservability:
    def test_rapids_metric_series_registered(self, cl):
        from h2o3_tpu.obs import metrics as obs_metrics

        names = set(obs_metrics.REGISTRY.names())
        for n in ("h2o3_rapids_statements_total",
                  "h2o3_rapids_fused_statements_total",
                  "h2o3_rapids_fused_programs_total",
                  "h2o3_rapids_fused_programs_compiled_total",
                  "h2o3_rapids_compile_cache_hits_total",
                  "h2o3_rapids_barrier_fallbacks_total",
                  "h2o3_rapids_host_materialized_cells_total",
                  "h2o3_rapids_fused_rows_total",
                  "h2o3_rapids_statement_seconds"):
            assert n in names, n

    def test_host_fallback_prims_are_counted(self, cl, fr, sess):
        before = fusion.counters()["barrier_fallbacks"]
        exec_rapids(f"(toupper (cols {FR} [3]))", sess)
        assert fusion.counters()["barrier_fallbacks"] == before + 1

    def test_host_matrix_cells_are_counted(self, cl, fr, sess):
        before = fusion.counters()["host_materialized_cells"]
        exec_rapids(f"(t {FR})", sess)          # transpose host-materializes
        assert fusion.counters()["host_materialized_cells"] >= \
            before + fr.nrows * fr.ncols

    def test_traced_statement_spans_and_zero_added_syncs(self, cl, fr,
                                                         sess):
        """Parse/plan/execute/fused_dispatch child spans land on the
        active trace; the proof that tracing changed nothing: zero new
        fused compiles (warm shape) and zero gathered rows while
        traced."""
        from h2o3_tpu.core import sharded_frame
        from h2o3_tpu.obs import tracing

        stmt = f"(* (+ (cols {FR} [0]) (cols {FR} [1])) 2)"
        with fusion.force(True):
            exec_rapids(stmt, sess)              # warm the program
            compiles0 = fusion.counters()["fused_programs_compiled"]
            gathered0 = sharded_frame.counters()["gathered_rows"]
            with tracing.root_span("rapids_test") as root:
                trace_id = root.ctx()["trace_id"]
                exec_rapids(stmt, sess)
        assert fusion.counters()["fused_programs_compiled"] == compiles0
        assert sharded_frame.counters()["gathered_rows"] == gathered0
        names = {s["name"] for s in tracing.get_trace(trace_id)}
        assert {"parse", "plan", "execute", "fused_dispatch"} <= names, \
            names

    def test_statement_counters_move(self, cl, fr, sess):
        c0 = fusion.counters()
        with fusion.force(True):
            exec_rapids(f"(+ (cols {FR} [0]) (cols {FR} [1]))", sess)
        c1 = fusion.counters()
        assert c1["statements"] == c0["statements"] + 1
        assert c1["fused_statements"] == c0["fused_statements"] + 1
        assert c1["fused_rows"] >= c0["fused_rows"] + fr.nrows

    def test_disabled_fusion_is_pure_eager(self, cl, fr, sess):
        c0 = fusion.counters()["fused_programs"]
        with fusion.force(False):
            exec_rapids(f"(+ (cols {FR} [0]) 1)", sess)
        assert fusion.counters()["fused_programs"] == c0
