"""Rapids statement-fusion suite (ISSUE 10).

Covers: (1) the fused-vs-eager bitwise-equivalence property over
randomized AST chains (elementwise/filter/reduce/ifelse compositions,
NA paths — the fused path must be indistinguishable from op-at-a-time
evaluation); (2) the compile-cache contract (structure-only signatures,
zero compiles warm, persistent tier across a simulated restart); (3) the
sharded-data-plane guard (``gathered_rows == 0`` on fused statements and
on enum-keyed group-by / device-join inputs, with numeric-key group-by
and host joins as the counted demoted path); (4) the Session refcount
token fix; (5) the h2o3_rapids_* observability surface, including the
traced-statement zero-added-syncs assertion.
"""

import gc

import numpy as np
import pytest

from h2o3_tpu.core.frame import Column, Frame, T_CAT
from h2o3_tpu.rapids import Session, exec_rapids
from h2o3_tpu.rapids import fusion

pytestmark = pytest.mark.rapids

FR = "fusion_test_fr"


@pytest.fixture()
def sess(cl):
    s = Session("fusion_t")
    yield s
    s.end()


@pytest.fixture()
def fr(cl):
    rng = np.random.default_rng(11)
    f = Frame(key=FR)
    a = rng.standard_normal(40)
    a[[3, 17, 29]] = np.nan                   # NA paths are first-class
    f.add("a", Column.from_numpy(a))
    f.add("b", Column.from_numpy(rng.standard_normal(40)))
    c = rng.uniform(-2.0, 2.0, 40)
    c[7] = np.nan
    f.add("c", Column.from_numpy(c))
    f.add("g", Column.from_numpy(
        np.asarray(["x", "y", "z", "y"] * 10, object), ctype=T_CAT))
    f.install()
    yield f
    f.delete()


def _both(stmt, sess):
    """Evaluate one statement fused and eager; returns (fused, eager)."""
    with fusion.force(True):
        vf = exec_rapids(stmt, sess)
    with fusion.force(False):
        ve = exec_rapids(stmt, sess)
    return vf, ve


def _col_equal(vf, ve):
    af = np.asarray(vf.col(0).to_numpy())
    ae = np.asarray(ve.col(0).to_numpy())
    assert af.dtype == ae.dtype
    assert np.array_equal(af, ae, equal_nan=True), (af, ae)
    assert vf.names == ve.names


# ---------------------------------------------------------------------------
# randomized equivalence property
# ---------------------------------------------------------------------------

_BINS = ["+", "-", "*", "/"]
_CMPS = ["<", ">", "<=", ">=", "==", "!="]
_UNS = ["abs", "sqrt", "floor", "ceiling", "sign", "exp", "log"]


def _gen(rng, depth):
    """Random fusible expression string (leaves: frame columns incl. the
    NA-carrying and enum ones, plus literals)."""
    if depth <= 0:
        if rng.random() < 0.6:
            i = int(rng.integers(0, 4))
            return f"(cols {FR} [{i}])"
        return f"{rng.uniform(-2, 2):.3f}"
    roll = rng.random()
    if roll < 0.35:
        op = _BINS[rng.integers(0, len(_BINS))]
        return f"({op} {_gen(rng, depth - 1)} {_gen(rng, depth - 1)})"
    if roll < 0.5:
        op = _CMPS[rng.integers(0, len(_CMPS))]
        return f"({op} {_gen(rng, depth - 1)} {_gen(rng, depth - 1)})"
    if roll < 0.62:
        op = "&" if rng.random() < 0.5 else "|"
        # logical needs a column ref on at least one side
        return f"({op} (> (cols {FR} [0]) 0) {_gen(rng, depth - 1)})"
    if roll < 0.78:
        op = _UNS[rng.integers(0, len(_UNS))]
        return f"({op} (+ {_gen(rng, depth - 1)} (cols {FR} [1])))"
    if roll < 0.9:
        return (f"(ifelse (> (cols {FR} [{int(rng.integers(0, 3))}]) 0) "
                f"{_gen(rng, depth - 1)} {_gen(rng, depth - 1)})")
    return f"(is.na (+ (cols {FR} [0]) {_gen(rng, depth - 1)}))"


@pytest.mark.parametrize("seed", range(24))
def test_randomized_chain_equivalence(seed, cl, fr, sess):
    rng = np.random.default_rng(seed)
    stmt = _gen(rng, int(rng.integers(2, 5)))
    while not stmt.startswith("("):           # root must be a compute node
        stmt = _gen(rng, 3)
    before = fusion.counters()["fused_programs"]
    vf, ve = _both(stmt, sess)
    _col_equal(vf, ve)
    assert fusion.counters()["fused_programs"] > before, (
        f"statement {stmt!r} did not take the fused path")


def test_reducer_equivalence(cl, fr, sess):
    for red in ("mean", "sum", "min", "max", "sd", "var", "naCnt",
                "any", "all"):
        stmt = f"({red} (* (+ (cols {FR} [0]) (cols {FR} [1])) 0.5))"
        with fusion.force(True):
            vf = exec_rapids(stmt, sess)
        with fusion.force(False):
            ve = exec_rapids(stmt, sess)
        assert vf == ve or (vf != vf and ve != ve), (red, vf, ve)


def test_rows_filter_equivalence(cl, fr, sess):
    stmt = (f"(rows {FR} (& (> (+ (cols {FR} [0]) (cols {FR} [1])) 0) "
            f"(< (cols {FR} [2]) 1)))")
    vf, ve = _both(stmt, sess)
    assert vf.nrows == ve.nrows
    for n in vf.names:
        cf, ce = vf.col(n), ve.col(n)
        if cf.is_categorical:
            assert list(cf.values()) == list(ce.values())
        else:
            assert np.array_equal(cf.to_numpy(), ce.to_numpy(),
                                  equal_nan=True)


def test_all_na_and_enum_paths(cl, sess):
    f = Frame(key="fusion_na_fr")
    f.add("a", Column.from_numpy(np.full(16, np.nan)))
    f.add("g", Column.from_numpy(
        np.asarray(["u", "v"] * 8, object), ctype=T_CAT))
    f.install()
    try:
        for stmt in (
                "(+ (cols fusion_na_fr [0]) 1)",
                "(is.na (cols fusion_na_fr [0]))",
                "(ifelse (is.na (cols fusion_na_fr [0])) "
                "(cols fusion_na_fr [1]) 0)",
                "(== (cols fusion_na_fr [1]) 1)",   # enum codes as numerics
        ):
            vf, ve = _both(stmt, sess)
            _col_equal(vf, ve)
    finally:
        f.delete()


def test_mask_multiply_na_propagation(cl, fr, sess):
    """0*NaN / 1*NaN must stay NaN through fused mask arithmetic — the
    XLA simplifier's multiply(convert(pred), x) -> select(pred, x, 0)
    rewrite would silently drop it inside one program (the reason
    isna_expr emits a select; this is the regression pin)."""
    for mask in (f"(is.na (cols {FR} [0]))",
                 f"(== (cols {FR} [1]) 0)",
                 f"(& (> (cols {FR} [1]) 0) (< (cols {FR} [1]) 9))"):
        for stmt in (f"(* {mask} (cols {FR} [2]))",
                     f"(+ (cols {FR} [0]) (* {mask} (cols {FR} [2])))"):
            vf, ve = _both(stmt, sess)
            _col_equal(vf, ve)


def test_assigned_statement_fuses(cl, fr, sess):
    """(tmp= ...) roots fuse their RHS — the evaluator offers the inner
    compute node, so assignment costs no fusion opportunity."""
    before = fusion.counters()["fused_programs"]
    with fusion.force(True):
        out = exec_rapids(
            f"(tmp= fusion_assigned (* (+ (cols {FR} [0]) 1) 2))", sess)
    assert fusion.counters()["fused_programs"] == before + 1
    with fusion.force(False):
        ref = exec_rapids(f"(* (+ (cols {FR} [0]) 1) 2)", sess)
    assert np.array_equal(out.col(0).to_numpy(), ref.col(0).to_numpy(),
                          equal_nan=True)


# ---------------------------------------------------------------------------
# compile-cache contract
# ---------------------------------------------------------------------------

def test_signature_cache_shares_programs_across_literals(cl, fr, sess):
    """Constants are traced arguments: statements that differ only in
    literals share ONE compiled program (AST shape × dtypes × rows
    bucket)."""
    with fusion.force(True):
        start = fusion.counters()
        exec_rapids(f"(+ (* (cols {FR} [0]) 3) (cols {FR} [1]))", sess)
        c0 = fusion.counters()
        exec_rapids(f"(+ (* (cols {FR} [0]) 99) (cols {FR} [1]))", sess)
        c1 = fusion.counters()
    assert c1["fused_programs_compiled"] == c0["fused_programs_compiled"]
    assert c1["compile_cache_hits"] > c0["compile_cache_hits"]
    # same segment count both times (the statement splits at the FMA
    # boundary, so it may be more than one program)
    assert (c1["fused_programs"] - c0["fused_programs"]
            == c0["fused_programs"] - start["fused_programs"])


def test_persistent_cache_survives_restart(cl, fr, sess, tmp_path,
                                           monkeypatch):
    """PR-6 persistent tier: drop the in-memory program cache (simulated
    process restart) — the statement shape reloads from disk and compiles
    ZERO programs."""
    from h2o3_tpu.artifact import compile_cache

    monkeypatch.setenv("H2O_TPU_COMPILE_CACHE_DIR", str(tmp_path))
    stmt = f"(- (* (cols {FR} [2]) 2) (cols {FR} [1]))"
    # cold in-memory state: every segment must compile (and store) under
    # the persistent tier, or the restart below would re-compile segments
    # that were warmed before the tier existed
    fusion.clear_programs()
    with fusion.force(True):
        exec_rapids(stmt, sess)
        if not any(p.name.startswith("xc_") for p in tmp_path.iterdir()):
            pytest.skip("this jax cannot serialize executables")
        fusion.clear_programs()
        c0 = fusion.counters()
        vf = exec_rapids(stmt, sess)
        c1 = fusion.counters()
    assert c1["fused_programs_compiled"] == c0["fused_programs_compiled"], \
        "a warm restart must compile zero fused programs"
    assert c1["compile_cache_hits"] > c0["compile_cache_hits"]
    with fusion.force(False):
        ve = exec_rapids(stmt, sess)
    _col_equal(vf, ve)


# ---------------------------------------------------------------------------
# sharded data-plane guard
# ---------------------------------------------------------------------------

class TestShardedGuard:
    def test_fused_statements_never_gather(self, cl, fr, sess):
        """The ISSUE acceptance counter: fused statements over sharded
        frames build everything from the columns' row shards in place —
        gathered_rows must not move, packed_rows covers the statement."""
        from h2o3_tpu.core import sharded_frame

        with fusion.force(True):
            exec_rapids(f"(+ (cols {FR} [0]) 1)", sess)   # warm compile
            before = sharded_frame.counters()
            exec_rapids(
                f"(ifelse (> (cols {FR} [0]) 0) (* (cols {FR} [1]) 2) "
                f"(- (cols {FR} [2]) 1))", sess)
            after = sharded_frame.counters()
        assert after["gathered_rows"] == before["gathered_rows"], (
            "a fused rapids statement pulled a column to the host")
        assert after["packed_rows"] >= before["packed_rows"] + fr.nrows

    def test_enum_groupby_input_never_gathers(self, cl, fr, sess):
        """Enum-keyed group-by consumes device codes + host domains: no
        column gather (the fused group-by input contract)."""
        from h2o3_tpu.core import sharded_frame

        before = sharded_frame.counters()
        exec_rapids(f'(GB {FR} [3] "mean" 0 "all" "nrow" 0 "all")', sess)
        after = sharded_frame.counters()
        assert after["gathered_rows"] == before["gathered_rows"]
        assert after["packed_rows"] >= before["packed_rows"] + fr.nrows

    def test_numeric_groupby_key_is_the_counted_demoted_path(self, cl, fr,
                                                             sess):
        from h2o3_tpu.core import sharded_frame

        before = sharded_frame.counters()
        exec_rapids(f'(GB {FR} [0] "mean" 1 "all")', sess)
        after = sharded_frame.counters()
        assert after["gathered_rows"] >= before["gathered_rows"] + fr.nrows

    def test_device_join_inputs_never_gather(self, cl, sess):
        """Numeric/enum-keyed merge consumes the key columns' own padded
        device buffers (sliced inside the compiled rank program) — no
        host staging of key columns."""
        from h2o3_tpu.core import sharded_frame
        from h2o3_tpu.ops.merge import merge

        l = Frame(key="fusion_join_l")
        l.add("k", Column.from_numpy(np.arange(24, dtype=float) % 6))
        l.add("v", Column.from_numpy(np.arange(24, dtype=float)))
        r = Frame(key="fusion_join_r")
        r.add("k", Column.from_numpy(np.arange(6, dtype=float)))
        r.add("w", Column.from_numpy(np.arange(6, dtype=float) * 10))
        try:
            before = sharded_frame.counters()
            out = merge(l, r)
            after = sharded_frame.counters()
            assert after["gathered_rows"] == before["gathered_rows"]
            assert after["packed_rows"] >= \
                before["packed_rows"] + l.nrows + r.nrows
            assert out.nrows == 24
        finally:
            l.delete()
            r.delete()


# ---------------------------------------------------------------------------
# Session refcounts (satellite: stable tokens, not id())
# ---------------------------------------------------------------------------

class TestSessionTokens:
    def test_column_refs_by_token(self, cl, fr):
        s = Session("tok_t")
        col = fr.col("a")
        s.assign("t1", fr)
        s.assign("t2", fr)
        assert s.column_refs(col) == 2
        s.remove("t1")
        assert s.column_refs(col) == 1
        s.end()
        assert s.column_refs(col) == 0

    def test_tokens_survive_gc_without_reuse(self, cl):
        """The id() bug this fix closes: a dead Column's identity must
        never be claimable by a new Column. Tokens are minted from a
        process counter, so even an id()-recycled object gets a fresh
        token and a zero refcount."""
        s = Session("tok_gc")
        f = Frame(key="tok_gc_fr")
        f.add("x", Column.from_numpy(np.arange(8, dtype=float)))
        tok_old = f.col("x").token
        s.assign("tmp_gc", f)
        assert s.refcnt.get(tok_old) == 1
        s.remove("tmp_gc")
        f.delete()
        del f
        gc.collect()
        fresh = Column.from_numpy(np.arange(8, dtype=float))
        assert fresh.token != tok_old
        assert s.column_refs(fresh) == 0
        assert fresh.token == fresh.token      # stable once minted
        s.end()


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------

class TestObservability:
    def test_rapids_metric_series_registered(self, cl):
        from h2o3_tpu.obs import metrics as obs_metrics

        names = set(obs_metrics.REGISTRY.names())
        for n in ("h2o3_rapids_statements_total",
                  "h2o3_rapids_fused_statements_total",
                  "h2o3_rapids_fused_programs_total",
                  "h2o3_rapids_fused_programs_compiled_total",
                  "h2o3_rapids_compile_cache_hits_total",
                  "h2o3_rapids_barrier_fallbacks_total",
                  "h2o3_rapids_host_materialized_cells_total",
                  "h2o3_rapids_fused_rows_total",
                  "h2o3_rapids_statement_seconds"):
            assert n in names, n

    def test_host_fallback_prims_are_counted(self, cl, fr, sess):
        before = fusion.counters()["barrier_fallbacks"]
        exec_rapids(f"(toupper (cols {FR} [3]))", sess)
        assert fusion.counters()["barrier_fallbacks"] == before + 1

    def test_host_matrix_cells_are_counted(self, cl, fr, sess):
        before = fusion.counters()["host_materialized_cells"]
        exec_rapids(f"(t {FR})", sess)          # transpose host-materializes
        assert fusion.counters()["host_materialized_cells"] >= \
            before + fr.nrows * fr.ncols

    def test_traced_statement_spans_and_zero_added_syncs(self, cl, fr,
                                                         sess):
        """Parse/plan/execute/fused_dispatch child spans land on the
        active trace; the proof that tracing changed nothing: zero new
        fused compiles (warm shape) and zero gathered rows while
        traced."""
        from h2o3_tpu.core import sharded_frame
        from h2o3_tpu.obs import tracing

        stmt = f"(* (+ (cols {FR} [0]) (cols {FR} [1])) 2)"
        with fusion.force(True):
            exec_rapids(stmt, sess)              # warm the program
            compiles0 = fusion.counters()["fused_programs_compiled"]
            gathered0 = sharded_frame.counters()["gathered_rows"]
            with tracing.root_span("rapids_test") as root:
                trace_id = root.ctx()["trace_id"]
                exec_rapids(stmt, sess)
        assert fusion.counters()["fused_programs_compiled"] == compiles0
        assert sharded_frame.counters()["gathered_rows"] == gathered0
        names = {s["name"] for s in tracing.get_trace(trace_id)}
        assert {"parse", "plan", "execute", "fused_dispatch"} <= names, \
            names

    def test_statement_counters_move(self, cl, fr, sess):
        c0 = fusion.counters()
        with fusion.force(True):
            exec_rapids(f"(+ (cols {FR} [0]) (cols {FR} [1]))", sess)
        c1 = fusion.counters()
        assert c1["statements"] == c0["statements"] + 1
        assert c1["fused_statements"] == c0["fused_statements"] + 1
        assert c1["fused_rows"] >= c0["fused_rows"] + fr.nrows

    def test_disabled_fusion_is_pure_eager(self, cl, fr, sess):
        c0 = fusion.counters()["fused_programs"]
        with fusion.force(False):
            exec_rapids(f"(+ (cols {FR} [0]) 1)", sess)
        assert fusion.counters()["fused_programs"] == c0
