"""h2o-r wire-format replay: the exact HTTP transcript communication.R
produces for h2o.init → h2o.importFile → h2o.gbm → predict, byte-encoded
the way RCurl's curlPerform sends it (urlencoded POST bodies, R-style
TRUE/FALSE literals, .collapse.char ["a","b"] lists).

No Rscript exists in this image, so this is the recorded-transcript tier
(VERDICT r3 #9): every request/response field below is one the R client
actually reads, cited to the R source.

Reference: h2o-r/h2o-package/R/communication.R:49 (.h2o.doRawREST),
parse.R:62 (h2o.parseRaw), models.R:123 (.h2o.startModelJob),
models.R:679 (predict — v4 key/dest at top level), connection.R:465
(InitID session)."""

import json
import time
import urllib.parse
import urllib.request

import numpy as np
import pytest

from h2o3_tpu.api.server import start_server


@pytest.fixture(scope="module")
def base(cl):
    srv = start_server(port=0)
    yield f"http://127.0.0.1:{srv.port}"
    srv.stop()


def _get(base, path, params=None):
    url = base + path
    if params:
        # communication.R builds name=curlEscape(value) query strings
        url += "?" + "&".join(f"{k}={urllib.parse.quote(str(v), safe='')}"
                              for k, v in params.items())
    with urllib.request.urlopen(url, timeout=120) as r:
        return json.loads(r.read())


def _post(base, path, params=None):
    # curlPerform(postfields=queryString) — urlencoded body, no JSON
    body = "&".join(f"{k}={urllib.parse.quote(str(v), safe='')}"
                    for k, v in (params or {}).items()).encode()
    req = urllib.request.Request(
        base + path, data=body,
        headers={"Content-Type": "application/x-www-form-urlencoded"},
        method="POST")
    with urllib.request.urlopen(req, timeout=300) as r:
        return json.loads(r.read())


def _wait_job(base, job_key):
    """.h2o.__waitOnJob (communication.R:926): poll /3/Jobs/{key} reading
    jobs[[1]]$status until DONE."""
    for _ in range(600):
        res = _get(base, f"/3/Jobs/{urllib.parse.quote(job_key, safe='')}")
        status = res["jobs"][0]["status"]
        if status in ("DONE", "FAILED", "CANCELLED"):
            assert status == "DONE", res["jobs"][0]
            return res["jobs"][0]
        time.sleep(0.1)
    raise AssertionError("job did not finish")


@pytest.fixture(scope="module")
def csv_path(tmp_path_factory):
    rng = np.random.default_rng(7)
    p = tmp_path_factory.mktemp("rwire") / "r_data.csv"
    with open(p, "w") as f:
        f.write("x1,x2,g,y\n")
        for i in range(800):
            x1, x2 = rng.normal(), rng.normal()
            g = "abc"[i % 3]
            pr = 1 / (1 + np.exp(-(1.2 * x1 - x2 + (g == "a"))))
            f.write(f"{x1:.5f},{x2:.5f},{g},{'YN'[int(rng.random() < pr)]}\n")
    return str(p)


def test_h2or_full_transcript(base, csv_path):
    # -- h2o.init: clusterInfo + session (connection.R) -------------------
    cloud = _get(base, "/3/Cloud")
    assert cloud["cloud_healthy"] is True
    assert cloud["cloud_size"] >= 1
    assert "version" in cloud and isinstance(cloud["nodes"], list)
    sid = _get(base, "/3/InitID")["session_key"]
    assert sid

    # -- h2o.importFile (import.R -> parse.R) -----------------------------
    imp = _get(base, "/3/ImportFiles", {"path": csv_path})
    assert imp["destination_frames"], imp
    src = imp["destination_frames"][0]

    setup = _post(base, "/3/ParseSetup",
                  {"source_frames": f'["{src}"]'})
    assert setup["number_columns"] == 4
    col_names = "[" + ",".join(f'"{c}"' for c in setup["column_names"]) + "]"
    col_types = "[" + ",".join(f'"{t}"' for t in setup["column_types"]) + "]"
    parse = _post(base, "/3/Parse", {
        "source_frames": f'["{src}"]',
        "destination_frame": "r_data.hex",
        "parse_type": setup["parse_type"],
        "separator": setup["separator"],
        "number_columns": setup["number_columns"],
        "single_quotes": "FALSE",
        "column_names": col_names,
        "column_types": col_types,
        "check_header": setup["check_header"],
        "delete_on_done": "TRUE",
        "chunk_size": setup.get("chunk_size", 4194304),
        "blocking": "FALSE",
    })
    _wait_job(base, parse["job"]["key"]["name"])

    fr = _get(base, "/3/Frames/r_data.hex")
    f0 = fr["frames"][0]
    assert f0["rows"] == 800
    assert [c["label"] for c in f0["columns"]] == ["x1", "x2", "g", "y"]

    # -- h2o.gbm (.h2o.makeModelParams reads the builder schema first) ----
    builders = _get(base, "/3/ModelBuilders/gbm")
    params = builders["model_builders"]["gbm"]["parameters"]
    assert any(p["name"] == "ntrees" for p in params)
    assert all("type" in p for p in params)

    res = _post(base, "/3/ModelBuilders/gbm", {
        "training_frame": "r_data.hex",
        "response_column": "y",
        "ntrees": 5, "max_depth": 3, "seed": 1,
    })
    job_key = res["job"]["key"]["name"]        # models.R:131 res$job$key$name
    dest_key = res["job"]["dest"]["name"]      # models.R:132 res$job$dest$name
    _wait_job(base, job_key)

    model = _get(base, f"/3/Models/{dest_key}")
    m0 = model["models"][0]
    assert m0["model_id"]["name"] == dest_key
    assert m0["algo"] == "gbm"
    assert "output" in m0                      # R reads res$models[[1]]$output

    # -- predict (models.R:679: v4, key/dest at TOP level) ----------------
    pred = _post(base, f"/4/Predictions/models/{dest_key}/frames/r_data.hex")
    assert pred["key"]["name"]
    pdest = pred["dest"]["name"]
    _wait_job(base, pred["key"]["name"])
    pfr = _get(base, f"/3/Frames/{pdest}")
    labels = [c["label"] for c in pfr["frames"][0]["columns"]]
    assert labels[0] == "predict"

    # -- session teardown (connection.R:558 DELETE InitID) ----------------
    req = urllib.request.Request(base + "/3/InitID", method="DELETE")
    with urllib.request.urlopen(req, timeout=60) as r:
        assert r.status == 200
