"""AutoML tests (reference: h2o-automl pyunits)."""

import numpy as np
import pytest

from h2o3_tpu.core.frame import Column, Frame, T_CAT
from h2o3_tpu.automl import H2OAutoML


def test_automl_binomial_leaderboard(cl):
    rng = np.random.default_rng(0)
    n = 1200
    X = rng.normal(size=(n, 4))
    logit = 1.2 * X[:, 0] - X[:, 1] + 0.5 * X[:, 2]
    y = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "Y", "N")
    fr = Frame.from_numpy(X, names=["a", "b", "c", "d"])
    fr.add("y", Column.from_numpy(y, ctype=T_CAT))

    aml = H2OAutoML(max_models=4, nfolds=3, seed=7,
                    include_algos=["glm", "gbm", "drf", "xgboost", "stackedensemble"])
    aml.train(y="y", training_frame=fr)
    lb = aml.leaderboard
    # 4 base models + up to 2 ensembles
    assert len(lb) >= 4
    assert lb[0]["auc"] >= lb[-1]["auc"]
    assert aml.leader is not None
    assert lb[0]["auc"] > 0.75
    pred = aml.predict(fr)
    assert pred.nrows == n
    assert any("StackedEnsemble" in r["model_id"] for r in lb)
    assert any("built" in e["message"] for e in aml.event_log)


def test_automl_regression(cl):
    rng = np.random.default_rng(1)
    n = 1000
    X = rng.normal(size=(n, 3))
    y = 2 * X[:, 0] + X[:, 1] ** 2 + 0.1 * rng.normal(size=n)
    fr = Frame.from_numpy(np.column_stack([X, y]), names=["a", "b", "c", "y"])
    aml = H2OAutoML(max_models=3, nfolds=3, seed=1,
                    include_algos=["glm", "gbm"])
    aml.train(y="y", training_frame=fr)
    assert aml.leaderboard[0]["rmse"] < 1.0
