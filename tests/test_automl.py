"""AutoML tests (reference: h2o-automl pyunits)."""

import numpy as np
import pytest

from h2o3_tpu.core.frame import Column, Frame, T_CAT
from h2o3_tpu.automl import H2OAutoML


def test_automl_binomial_leaderboard(cl):
    rng = np.random.default_rng(0)
    n = 1200
    X = rng.normal(size=(n, 4))
    logit = 1.2 * X[:, 0] - X[:, 1] + 0.5 * X[:, 2]
    y = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "Y", "N")
    fr = Frame.from_numpy(X, names=["a", "b", "c", "d"])
    fr.add("y", Column.from_numpy(y, ctype=T_CAT))

    aml = H2OAutoML(max_models=4, nfolds=3, seed=7,
                    include_algos=["glm", "gbm", "drf", "xgboost", "stackedensemble"])
    aml.train(y="y", training_frame=fr)
    lb = aml.leaderboard
    # 4 base models + up to 2 ensembles
    assert len(lb) >= 4
    assert lb[0]["auc"] >= lb[-1]["auc"]
    assert aml.leader is not None
    assert lb[0]["auc"] > 0.75
    pred = aml.predict(fr)
    assert pred.nrows == n
    assert any("StackedEnsemble" in r["model_id"] for r in lb)
    assert any("built" in e["message"] for e in aml.event_log)


def test_automl_regression(cl):
    rng = np.random.default_rng(1)
    n = 1000
    X = rng.normal(size=(n, 3))
    y = 2 * X[:, 0] + X[:, 1] ** 2 + 0.1 * rng.normal(size=n)
    fr = Frame.from_numpy(np.column_stack([X, y]), names=["a", "b", "c", "y"])
    aml = H2OAutoML(max_models=3, nfolds=3, seed=1,
                    include_algos=["glm", "gbm"])
    aml.train(y="y", training_frame=fr)
    assert aml.leaderboard[0]["rmse"] < 1.0


def test_automl_exploitation_phase(cl):
    """Step registry + exploitation (ai.h2o.automl.modeling providers):
    the plan executes in group order and the exploitation step refines the
    best GBM with an annealed learning rate."""
    import numpy as np

    from h2o3_tpu.automl.automl import H2OAutoML
    from h2o3_tpu.core.frame import Column, Frame, T_CAT

    rng = np.random.default_rng(17)
    n = 600
    X = rng.normal(size=(n, 3))
    logit = 1.5 * X[:, 0] - X[:, 1]
    y = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "Y", "N")
    fr = Frame.from_numpy(X, names=["a", "b", "c"])
    fr.add("y", Column.from_numpy(y, ctype=T_CAT))
    aml = H2OAutoML(max_models=4, seed=7, nfolds=0,
                    include_algos=["GBM", "GLM"],
                    exclude_algos=["StackedEnsemble"])
    aml.train(y="y", training_frame=fr)
    names = [st["name"] for st in aml.modeling_plan]
    assert any(nm.startswith("exploit_gbm") for nm in names), names
    built = {st["name"]: st.get("model_id") for st in aml.modeling_plan
             if st.get("model_id")}
    assert any(nm.startswith("exploit_gbm") for nm in built), built
    # the exploitation model really anneals: lr half of the family best's
    exploit_id = next(v for k, v in built.items()
                      if k.startswith("exploit_gbm"))
    em = next(m for m in aml.models if str(m.key) == exploit_id)
    gbms = [m for m in aml.models
            if m.algo_name == "gbm" and str(m.key) != exploit_id]
    best_lr = [float(m._parms.get("learn_rate") or 0.1)
               for m in aml._ranked(gbms)][0]
    assert float(em._parms["learn_rate"]) == pytest.approx(best_lr / 2)
    # groups executed in order: defaults before grids before exploitation
    groups = [st["group"] for st in aml.modeling_plan]
    assert groups == sorted(groups)
