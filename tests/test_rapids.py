"""Rapids parser + evaluator tests (reference: water/rapids pyunits)."""

import numpy as np
import pytest

from h2o3_tpu.core.frame import Column, Frame, T_CAT
from h2o3_tpu.rapids import Session, exec_rapids


@pytest.fixture()
def sess():
    s = Session("t")
    yield s
    s.end()


@pytest.fixture()
def fr(cl):
    f = Frame(key="testfr")
    f.add("a", Column.from_numpy(np.arange(10, dtype=float)))
    f.add("b", Column.from_numpy(np.arange(10, dtype=float) * 2))
    f.add("g", Column.from_numpy(np.asarray(["x", "y"] * 5, object), ctype=T_CAT))
    f.install()
    yield f
    f.delete()


def test_arith_and_assign(sess, fr):
    out = exec_rapids("(tmp= res (+ (cols testfr [0]) 5))", sess)
    assert np.allclose(out.col(0).to_numpy(), np.arange(10) + 5)
    out2 = exec_rapids("(* (cols_py testfr 'a') (cols_py testfr 'b'))", sess)
    assert np.allclose(out2.col(0).to_numpy(), np.arange(10) * np.arange(10) * 2)


def test_rows_filter_and_slice(sess, fr):
    out = exec_rapids("(rows testfr (> (cols testfr [0]) 6))", sess)
    assert out.nrows == 3
    out2 = exec_rapids("(rows testfr [0:4])", sess)
    assert out2.nrows == 4
    out3 = exec_rapids("(rows testfr [1 3 5])", sess)
    assert np.allclose(out3.col("a").to_numpy(), [1, 3, 5])


def test_reducers(sess, fr):
    assert exec_rapids("(mean (cols testfr [0]))", sess) == pytest.approx(4.5)
    assert exec_rapids("(sum (cols testfr [1]))", sess) == pytest.approx(90.0)
    assert exec_rapids("(max (cols testfr [0]))", sess) == pytest.approx(9.0)
    assert exec_rapids("(nrow testfr)", sess) == 10.0


def test_groupby_prim(sess, fr):
    out = exec_rapids('(GB testfr [2] "mean" 0 "all" "nrow" 0 "all")', sess)
    df = {tuple(r) for r in np.column_stack(
        [out.col("g").values(), out.col("mean_a").to_numpy()])}
    assert ("x", 4.0) in df and ("y", 5.0) in df


def test_ifelse_isna_cumsum(sess, fr):
    out = exec_rapids("(cumsum (cols testfr [0]) 0)", sess)
    assert np.allclose(out.col(0).to_numpy(), np.cumsum(np.arange(10)))
    out2 = exec_rapids("(ifelse (> (cols testfr [0]) 4) 1 0)", sess)
    assert out2.col(0).to_numpy().sum() == 5


def test_string_and_factor(sess, fr):
    out = exec_rapids("(toupper (cols testfr [2]))", sess)
    assert set(out.col(0).domain) == {"X", "Y"}
    out2 = exec_rapids("(as.numeric (asfactor (cols testfr [0])))", sess)
    assert np.allclose(np.sort(out2.col(0).to_numpy()), np.arange(10))


def test_quantile_and_sort(sess, fr):
    out = exec_rapids("(quantile testfr [0.5] 'interpolated' _)", sess)
    assert "Probs" in out.names
    srt = exec_rapids("(sort testfr [1] [0])", sess)
    assert srt.col("b").to_numpy()[0] == 18.0  # descending


def test_lambda_apply(sess, fr):
    out = exec_rapids("({x . (+ x 1)} 41)", sess)
    assert out == 42.0


def test_colassign_and_append(sess, fr):
    out = exec_rapids("(append testfr (* (cols testfr [0]) 10) 'a10')", sess)
    assert "a10" in out.names
    assert np.allclose(out.col("a10").to_numpy(), np.arange(10) * 10)
