"""Tier-1-safe consistency guards: test/code drift detectors.

1. Every faultpoint a test arms (``failure.inject("name")`` /
   ``_FAULTS["name"]``) must exist as a ``faultpoint("name")`` call in
   ``h2o3_tpu/`` — a renamed faultpoint otherwise silently turns a chaos
   test into a no-op that "passes" without injecting anything.
2. The ``[tool.pytest.ini_options] markers`` list in pyproject.toml must
   stay in sync with the custom markers actually used under ``tests/``:
   a marker used but not declared breaks ``--strict-markers`` runs, a
   marker declared but never used is dead registry weight.
3. Every ``H2O_TPU_*`` env knob the framework reads must appear in
   README.md — an undocumented knob is an operator trap (the recovery
   runbook promises the full surface).
4. Metric-name registry guard (ISSUE 8): every metric registered in
   ``h2o3_tpu/`` exactly once, names matching ``^h2o3_[a-z0-9_]+$``, and
   the live registry agreeing with the source scan.
5. Timeline-kind enumeration guard (ISSUE 8): no free-form
   ``record(kind=...)`` drift — every recorded kind is declared in
   ``utils/timeline.py KINDS`` and no declared kind is dead.
6. Sharded-data-plane invariant (ISSUE 7): no call site under
   ``h2o3_tpu/`` may fetch a full column to the coordinator host inside
   the fused scoring or tree input path — asserted behaviorally via the
   ``gathered_rows`` counter staying 0 through a train + fused-score
   smoke on the 8-device mesh (the one non-text guard here; it is the
   counter the issue pins the invariant to).

All but #6 are pure text scans (plus cheap imports) — no devices,
milliseconds.
"""

import re
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
SRC = ROOT / "h2o3_tpu"
TESTS = ROOT / "tests"

# pytest's own marks + common third-party ones: not ours to declare
_BUILTIN_MARKS = {"parametrize", "skip", "skipif", "xfail", "usefixtures",
                  "filterwarnings", "timeout"}


def _py_sources(root):
    for p in sorted(root.rglob("*.py")):
        if "__pycache__" in p.parts:
            continue
        yield p, p.read_text(encoding="utf-8", errors="replace")


def test_faultpoints_armed_by_tests_exist_in_code():
    defined = set()
    for _p, text in _py_sources(SRC):
        defined |= set(re.findall(r"faultpoint\(\s*['\"]([^'\"]+)['\"]",
                                  text))
    armed = set()
    here = Path(__file__).resolve()
    for p, text in _py_sources(TESTS):
        if p.resolve() == here:
            continue                     # this guard's own docstring
        armed |= set(re.findall(r"\binject\(\s*['\"]([^'\"]+)['\"]", text))
        armed |= set(re.findall(r"_FAULTS\[\s*['\"]([^'\"]+)['\"]\s*\]",
                                text))
        # the inject/faultpoint MECHANISM self-tests define their own
        # throwaway faultpoints inline — those count as defined
        defined |= set(re.findall(r"faultpoint\(\s*['\"]([^'\"]+)['\"]",
                                  text))
    missing = armed - defined
    assert not missing, (
        f"tests arm faultpoint(s) {sorted(missing)} that no longer exist "
        f"in h2o3_tpu/ — a renamed faultpoint silently defuses its chaos "
        f"tests (defined: {sorted(defined)})")


def _declared_markers():
    text = (ROOT / "pyproject.toml").read_text()
    m = re.search(r"markers\s*=\s*\[(.*?)\]", text, re.S)
    assert m, "pyproject.toml has no [tool.pytest.ini_options] markers list"
    # each entry is "name: description" — take the leading identifier
    # (descriptions may contain nested quotes/colons/parens)
    return set(re.findall(r"['\"]\s*([A-Za-z_]\w*)\s*:", m.group(1)))


def _used_markers():
    used = set()
    for _p, text in _py_sources(TESTS):
        used |= set(re.findall(r"pytest\.mark\.(\w+)", text))
    return used - _BUILTIN_MARKS


def test_env_knobs_documented_in_readme():
    """Every H2O_TPU_* env var read anywhere in h2o3_tpu/ must be named in
    README.md (env tables / runbook). New knobs ship with their docs."""
    used = set()
    for _p, text in _py_sources(SRC):
        used |= set(re.findall(r"\bH2O_TPU_[A-Z0-9_]+\b", text))
    readme = (ROOT / "README.md").read_text(encoding="utf-8")
    documented = set(re.findall(r"\bH2O_TPU_[A-Z0-9_]+\b", readme))
    missing = used - documented
    assert not missing, (
        f"env knob(s) {sorted(missing)} are read in h2o3_tpu/ but not "
        "documented in README.md — add them to the env table (operators "
        "discover knobs there, not by grepping the source)")


def test_artifact_loads_are_restricted():
    """Every artifact/cache file read in ``h2o3_tpu/artifact/`` and
    ``h2o3_genmodel/`` must go through a restricted unpickler or a
    schema-validated manifest/npz path: no raw ``pickle.load(s)`` and no
    ``allow_pickle=True`` — a scoring artifact is untrusted input (it may
    arrive over shared storage or an upload route), and one raw load is a
    pickle-RCE door."""
    roots = [SRC / "artifact", ROOT / "h2o3_genmodel"]
    offenders = []
    for root in roots:
        for p, text in _py_sources(root):
            rel = p.relative_to(ROOT)
            for pat, why in (
                    (r"\bpickle\.loads?\(", "raw pickle.load(s)"),
                    (r"allow_pickle\s*=\s*True", "np.load(allow_pickle)")):
                for mm in re.finditer(pat, text):
                    line = text[: mm.start()].count("\n") + 1
                    offenders.append(f"{rel}:{line} — {why}")
    assert not offenders, (
        "artifact/genmodel load paths must use a restricted Unpickler "
        "subclass or allow_pickle=False npz/manifest reads; found: "
        + "; ".join(offenders))


def test_genmodel_runner_has_no_training_imports():
    """The standalone runtimes under ``h2o3_genmodel/`` must stay loadable
    without the framework: any ``import h2o3_tpu`` there would silently
    re-couple the dependency-free scoring artifact to the training
    stack."""
    offenders = []
    for p, text in _py_sources(ROOT / "h2o3_genmodel"):
        for mm in re.finditer(
                r"^\s*(?:import\s+h2o3_tpu|from\s+h2o3_tpu)", text, re.M):
            line = text[: mm.start()].count("\n") + 1
            offenders.append(f"{p.relative_to(ROOT)}:{line}")
    assert not offenders, (
        f"h2o3_genmodel imports the training stack at {offenders} — the "
        "standalone runners must depend on numpy/stdlib (+ jax for AOT) "
        "only")


def test_pyproject_markers_match_test_usage():
    declared = _declared_markers()
    used = _used_markers()
    undeclared = used - declared
    assert not undeclared, (
        f"marker(s) {sorted(undeclared)} are used under tests/ but not "
        "declared in pyproject.toml [tool.pytest.ini_options] markers — "
        "--strict-markers runs will fail")
    unused = declared - used
    assert not unused, (
        f"marker(s) {sorted(unused)} are declared in pyproject.toml but "
        "never used under tests/ — drop them or mark the tests")


def test_metric_names_registered_exactly_once():
    """ISSUE-8 guard (mirrors the faultpoint-name guard): every metric
    registration in h2o3_tpu/ uses a ``^h2o3_[a-z0-9_]+$`` name and no
    name is registered twice — a duplicate would raise at import in
    production, and a malformed name breaks Prometheus scrapes. All
    registrations live in obs/metrics.py's single install site by
    design; this guard pins that discipline against drift."""
    import collections

    pat = re.compile(
        r"\br\.(?:counter|gauge|histogram)(?:_fn)?\(\s*['\"]([^'\"]+)['\"]")
    names = collections.Counter()
    for p, text in _py_sources(SRC):
        for name in pat.findall(text):
            names[name] += 1
    assert names, "no metric registrations found under h2o3_tpu/"
    bad = [n for n in names if not re.match(r"^h2o3_[a-z0-9_]+$", n)]
    assert not bad, (f"metric name(s) {sorted(bad)} do not match "
                     "^h2o3_[a-z0-9_]+$ — Prometheus scrapes reject them")
    dup = sorted(n for n, c in names.items() if c > 1)
    assert not dup, (f"metric name(s) {dup} are registered more than once "
                     "— the registry raises on the second registration")
    assert len(names) >= 20, (
        f"only {len(names)} metrics registered — the cluster /3/Metrics "
        "surface promises >= 20 series")
    # behavioral half: the live registry agrees with the text scan
    from h2o3_tpu.obs import metrics as obs_metrics

    live = set(obs_metrics.REGISTRY.names())
    missing = set(names) - live
    assert not missing, (
        f"metric(s) {sorted(missing)} are registered in source but absent "
        "from the live registry (conditional registration?)")


def test_timeline_kinds_are_enumerated():
    """ISSUE-8 guard: every ``timeline.record(kind, ...)`` /
    ``timeline.task(kind, ...)`` call-site literal under h2o3_tpu/ must be
    in ``timeline.KINDS`` (free-form kind drift makes the ring
    un-queryable), and no declared kind may be dead — mirroring the
    marker-registry guard. 'rest' is emitted by the API layer's request
    ring merge rather than record(), so it is exempt from the usage
    half."""
    from h2o3_tpu.utils import timeline

    used = set()
    call_pat = re.compile(
        r"\btimeline\.(?:record|task)\(\s*['\"]([^'\"]+)['\"]")
    # timeline.py's own internal record() calls (module-local, unprefixed)
    bare_pat = re.compile(r"(?<![\w.])record\(\s*['\"]([^'\"]+)['\"]")
    for p, text in _py_sources(SRC):
        used |= set(call_pat.findall(text))
        if p.name == "timeline.py":
            used |= set(bare_pat.findall(text))
    unknown = used - timeline.KINDS
    assert not unknown, (
        f"timeline kind(s) {sorted(unknown)} are recorded in h2o3_tpu/ "
        "but not declared in utils/timeline.py KINDS — add them there "
        "(the enumeration is the ring's query surface)")
    dead = timeline.KINDS - used - {"rest"}
    assert not dead, (
        f"timeline kind(s) {sorted(dead)} are declared in KINDS but never "
        "recorded anywhere under h2o3_tpu/ — drop them or record them")


def test_rapids_prims_declare_fusibility_class():
    """ISSUE-10 guard (mirrors the timeline-KINDS guard): every registered
    Rapids prim must carry exactly one fusibility class from the closed
    enumeration {fusible, barrier, host} in rapids/fusion.PRIM_FUSION —
    a new prim without a declaration would silently land as an un-fused
    barrier the planner (and the barrier_fallbacks metric) cannot see.
    Dead classifications (names no prim registers) are drift too."""
    from h2o3_tpu.rapids import fusion
    from h2o3_tpu.rapids.eval import PRIMS

    registered = set(PRIMS)
    classified = set(fusion.PRIM_FUSION)
    missing = registered - classified
    assert not missing, (
        f"rapids prim(s) {sorted(missing)} are registered but declare no "
        "fusibility class — add them to rapids/fusion.py (fusible / "
        "barrier / host); unclassified prims can't be planned or counted")
    dead = classified - registered
    assert not dead, (
        f"fusibility class entries {sorted(dead)} name prims that are no "
        "longer registered — drop them from rapids/fusion.py")
    bad = {n: c for n, c in fusion.PRIM_FUSION.items()
           if c not in fusion.FUSION_CLASSES}
    assert not bad, f"fusibility classes outside the enumeration: {bad}"
    # the planner's root set must be a subset of the fusible class
    assert fusion.ROOT_OPS <= {n for n, c in fusion.PRIM_FUSION.items()
                               if c == fusion.FUSIBLE}


def test_fused_paths_never_gather_columns_to_coordinator():
    """ISSUE-7 guard: the fused scoring path and the tree-training input
    path must build their inputs from addressable row shards in place.
    Train a tiny GBM on the virtual 8-device mesh and score it through
    the fused session: the per-process ``gathered_rows`` counter (the one
    ``GET /3/ScoringMetrics`` serves under ``data_plane``) must not move,
    while ``packed_rows`` covers both the training bin pack and the
    scored request. A regression that re-introduces a coordinator column
    fetch anywhere under either path trips this immediately."""
    import numpy as np

    import h2o3_tpu
    from h2o3_tpu import scoring
    from h2o3_tpu.core import sharded_frame
    from h2o3_tpu.core.frame import Column, Frame
    from h2o3_tpu.models.tree.gbm import GBM

    h2o3_tpu.init()
    rng = np.random.default_rng(77)
    n = 512
    fr = Frame()
    x = rng.standard_normal(n)
    fr.add("x1", Column.from_numpy(x))
    fr.add("g", Column.from_numpy(
        np.array(["a", "b"])[rng.integers(0, 2, n)], ctype="enum"))
    fr.add("y", Column.from_numpy(
        np.where(rng.random(n) < 1 / (1 + np.exp(-x)), "Y", "N"),
        ctype="enum"))
    before = sharded_frame.counters()
    model = GBM(ntrees=2, max_depth=2, seed=7).train(
        y="y", training_frame=fr)
    sfr = Frame()
    sfr.add("x1", Column.from_numpy(rng.standard_normal(100)))
    sfr.add("g", Column.from_numpy(
        np.array(["a", "b"])[rng.integers(0, 2, 100)], ctype="enum"))
    scoring.ScoringSession(model).predict(sfr)
    after = sharded_frame.counters()
    assert after["gathered_rows"] == before["gathered_rows"], (
        "a fused scoring / tree input call site pulled full columns to "
        "the coordinator host (gathered_rows moved) — the sharded data "
        "plane contract is broken")
    assert after["packed_rows"] >= before["packed_rows"] + n + 100
