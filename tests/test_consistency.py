"""Tier-1 consistency guards, now backed by ONE invariant engine.

ISSUE 11 folded the four text guards that grew here across PRs 4-9
(faultpoint names, metric registry, timeline kinds, env-knob docs) into
``h2o3_tpu/analysis`` — a multi-pass static analyzer that also checks the
invariants those guards could not reach: mirrored-program divergence,
lock ordering, raw unpickling, compat routing and span sync hygiene.

This module is the tier-1 wiring:

1. the FULL analyzer must exit clean on the repo (zero non-baselined
   findings, zero baseline-hygiene problems) inside its 10 s budget —
   this single test carries the mirrored/lock/serialization/compat/sync
   invariants plus the four folded registry guards;
2. the registry passes also run individually so a drift failure names
   the offending pass directly instead of a wall of findings;
3. the guards that need live behavior stay here as tests: pytest-marker
   registry sync, the live metrics registry agreeing with the source
   scan, rapids fusibility declarations, the genmodel import firewall,
   and the sharded-data-plane ``gathered_rows`` smoke (the one non-text
   guard; conftest routes it to the heavy tail).

All text passes are stdlib-ast scans — no devices, milliseconds to
single-digit seconds.
"""

import re
import time
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parent.parent
SRC = ROOT / "h2o3_tpu"
TESTS = ROOT / "tests"

# pytest's own marks + common third-party ones: not ours to declare
_BUILTIN_MARKS = {"parametrize", "skip", "skipif", "xfail", "usefixtures",
                  "filterwarnings", "timeout"}


def _py_sources(root):
    for p in sorted(root.rglob("*.py")):
        if "__pycache__" in p.parts:
            continue
        yield p, p.read_text(encoding="utf-8", errors="replace")


# ---------------------------------------------------------------------------
# the invariant engine (h2o3_tpu/analysis) — tier-1 wiring
# ---------------------------------------------------------------------------

def test_static_analyzer_clean_within_budget():
    """``python -m h2o3_tpu.analysis`` equivalent: every pass over the
    whole repo, all findings either fixed or baselined-with-justification,
    and the full run inside the 10 s budget the issue pins."""
    from h2o3_tpu import analysis

    t0 = time.perf_counter()
    new, baselined, problems = analysis.run_repo(root=ROOT)
    dt = time.perf_counter() - t0
    assert not new, (
        "static analyzer found NEW invariant violations (fix them, or — "
        "sync-hygiene/compat-routing only — baseline with a "
        "justification):\n" + "\n".join(f.render() for f in new))
    assert not problems, (
        "baseline hygiene problems:\n"
        + "\n".join(f.render() for f in problems))
    assert dt < 10.0, (
        f"analyzer took {dt:.1f}s — the tier-1 budget is 10s; a pass "
        f"grew superlinear (check call-graph closure caching)")


@pytest.fixture(scope="module")
def actx():
    """One parsed-project context shared by the per-pass guards (the
    call-graph build dominates a pass run)."""
    from h2o3_tpu import analysis

    return analysis.make_context(ROOT)


@pytest.mark.parametrize("pass_name", ["faultpoints", "metric-registry",
                                       "timeline-kinds", "knob-docs",
                                       "compile-ledger"])
def test_registry_guard_pass(actx, pass_name):
    """The folded consistency guards (plus the ISSUE-12 compile-ledger
    chokepoint), one pass each, so drift failures name the responsible
    registry directly. (Covered by the full run above too — this is the
    readable failure mode.)"""
    from h2o3_tpu import analysis

    findings = analysis.run(actx, [pass_name])
    assert not findings, "\n".join(f.render() for f in findings)


# ---------------------------------------------------------------------------
# guards that need live behavior (not expressible as text passes)
# ---------------------------------------------------------------------------

def _declared_markers():
    text = (ROOT / "pyproject.toml").read_text()
    m = re.search(r"markers\s*=\s*\[(.*?)\]", text, re.S)
    assert m, "pyproject.toml has no [tool.pytest.ini_options] markers list"
    # each entry is "name: description" — take the leading identifier
    # (descriptions may contain nested quotes/colons/parens)
    return set(re.findall(r"['\"]\s*([A-Za-z_]\w*)\s*:", m.group(1)))


def _used_markers():
    used = set()
    for _p, text in _py_sources(TESTS):
        used |= set(re.findall(r"pytest\.mark\.(\w+)", text))
    return used - _BUILTIN_MARKS


def test_pyproject_markers_match_test_usage():
    declared = _declared_markers()
    used = _used_markers()
    undeclared = used - declared
    assert not undeclared, (
        f"marker(s) {sorted(undeclared)} are used under tests/ but not "
        "declared in pyproject.toml [tool.pytest.ini_options] markers — "
        "--strict-markers runs will fail")
    unused = declared - used
    assert not unused, (
        f"marker(s) {sorted(unused)} are declared in pyproject.toml but "
        "never used under tests/ — drop them or mark the tests")


def test_genmodel_runner_has_no_training_imports():
    """The standalone runtimes under ``h2o3_genmodel/`` must stay loadable
    without the framework: any ``import h2o3_tpu`` there would silently
    re-couple the dependency-free scoring artifact to the training
    stack."""
    offenders = []
    for p, text in _py_sources(ROOT / "h2o3_genmodel"):
        for mm in re.finditer(
                r"^\s*(?:import\s+h2o3_tpu|from\s+h2o3_tpu)", text, re.M):
            line = text[: mm.start()].count("\n") + 1
            offenders.append(f"{p.relative_to(ROOT)}:{line}")
    assert not offenders, (
        f"h2o3_genmodel imports the training stack at {offenders} — the "
        "standalone runners must depend on numpy/stdlib (+ jax for AOT) "
        "only")


def test_live_metric_registry_agrees_with_source_scan():
    """Behavioral half of the metric-registry pass: every metric the
    text scan sees is present in the LIVE registry after import
    (conditional registration would hide a series from /3/Metrics).
    Uses the PASS'S OWN pattern so the two halves cannot drift."""
    from h2o3_tpu.analysis.passes_registries import METRIC_REG_PAT

    names = set()
    for _p, text in _py_sources(SRC):
        names |= set(METRIC_REG_PAT.findall(text))
    assert names, "no metric registrations found under h2o3_tpu/"
    from h2o3_tpu.obs import metrics as obs_metrics

    live = set(obs_metrics.REGISTRY.names())
    missing = names - live
    assert not missing, (
        f"metric(s) {sorted(missing)} are registered in source but absent "
        "from the live registry (conditional registration?)")


def test_rapids_prims_declare_fusibility_class():
    """ISSUE-10 guard (mirrors the timeline-KINDS guard): every registered
    Rapids prim must carry exactly one fusibility class from the closed
    enumeration {fusible, barrier, host} in rapids/fusion.PRIM_FUSION —
    a new prim without a declaration would silently land as an un-fused
    barrier the planner (and the barrier_fallbacks metric) cannot see.
    Dead classifications (names no prim registers) are drift too."""
    from h2o3_tpu.rapids import fusion
    from h2o3_tpu.rapids.eval import PRIMS

    registered = set(PRIMS)
    classified = set(fusion.PRIM_FUSION)
    missing = registered - classified
    assert not missing, (
        f"rapids prim(s) {sorted(missing)} are registered but declare no "
        "fusibility class — add them to rapids/fusion.py (fusible / "
        "barrier / host); unclassified prims can't be planned or counted")
    dead = classified - registered
    assert not dead, (
        f"fusibility class entries {sorted(dead)} name prims that are no "
        "longer registered — drop them from rapids/fusion.py")
    bad = {n: c for n, c in fusion.PRIM_FUSION.items()
           if c not in fusion.FUSION_CLASSES}
    assert not bad, f"fusibility classes outside the enumeration: {bad}"
    # the planner's root set must be a subset of the fusible class
    assert fusion.ROOT_OPS <= {n for n, c in fusion.PRIM_FUSION.items()
                               if c == fusion.FUSIBLE}
    # the LAZY session planner's deferral surface: fusible roots plus the
    # two device barrier prims it models as DAG nodes — a reclassification
    # of either would silently change what defers
    for nm in ("sort", "rows"):
        assert fusion.PRIM_FUSION.get(nm) == fusion.BARRIER, (
            f"rapids/planner.py defers {nm!r} statements as device DAG "
            f"nodes; it must stay barrier-class, got "
            f"{fusion.PRIM_FUSION.get(nm)!r}")
    # the newly device-resident prims must never regress to host class
    # (their device paths are the lazy-session PR's acceptance surface)
    for nm in ("rank_within_groupby", "difflag1"):
        assert fusion.PRIM_FUSION.get(nm) == fusion.BARRIER, (
            f"{nm!r} is device-resident (ops/window.py); host class would "
            f"misreport it as a barrier_fallbacks exceptional path")


def test_fused_paths_never_gather_columns_to_coordinator():
    """ISSUE-7 guard: the fused scoring path and the tree-training input
    path must build their inputs from addressable row shards in place.
    Train a tiny GBM on the virtual 8-device mesh and score it through
    the fused session: the per-process ``gathered_rows`` counter (the one
    ``GET /3/ScoringMetrics`` serves under ``data_plane``) must not move,
    while ``packed_rows`` covers both the training bin pack and the
    scored request. A regression that re-introduces a coordinator column
    fetch anywhere under either path trips this immediately."""
    import numpy as np

    import h2o3_tpu
    from h2o3_tpu import scoring
    from h2o3_tpu.core import sharded_frame
    from h2o3_tpu.core.frame import Column, Frame
    from h2o3_tpu.models.tree.gbm import GBM

    h2o3_tpu.init()
    rng = np.random.default_rng(77)
    n = 512
    fr = Frame()
    x = rng.standard_normal(n)
    fr.add("x1", Column.from_numpy(x))
    fr.add("g", Column.from_numpy(
        np.array(["a", "b"])[rng.integers(0, 2, n)], ctype="enum"))
    fr.add("y", Column.from_numpy(
        np.where(rng.random(n) < 1 / (1 + np.exp(-x)), "Y", "N"),
        ctype="enum"))
    before = sharded_frame.counters()
    model = GBM(ntrees=2, max_depth=2, seed=7).train(
        y="y", training_frame=fr)
    sfr = Frame()
    sfr.add("x1", Column.from_numpy(rng.standard_normal(100)))
    sfr.add("g", Column.from_numpy(
        np.array(["a", "b"])[rng.integers(0, 2, 100)], ctype="enum"))
    scoring.ScoringSession(model).predict(sfr)
    after = sharded_frame.counters()
    assert after["gathered_rows"] == before["gathered_rows"], (
        "a fused scoring / tree input call site pulled full columns to "
        "the coordinator host (gathered_rows moved) — the sharded data "
        "plane contract is broken")
    assert after["packed_rows"] >= before["packed_rows"] + n + 100


def test_ingest_never_stages_whole_columns_on_coordinator(tmp_path):
    """ISSUE-15 guard (the ingest-side gathered_rows contract): a CSV
    import must ride the chunked sharded pipeline — every chunk's rows
    land directly in their owning row shard — and the whole
    import→train→score arc must leave ``coordinator_ingest_bytes``
    untouched. A regression that re-introduces the one-gather-at-the-
    coordinator assembly (the pre-ISSUE-15 docstring's own words) trips
    this immediately."""
    import numpy as np

    import h2o3_tpu
    from h2o3_tpu import scoring
    from h2o3_tpu.core.frame import Column, Frame
    from h2o3_tpu.ingest import chunked
    from h2o3_tpu.models.tree.gbm import GBM

    h2o3_tpu.init()
    rng = np.random.default_rng(99)
    n = 600
    p = tmp_path / "smoke.csv"
    with open(p, "w") as f:
        f.write("x1,g,y\n")
        for i in range(n):
            x = rng.normal()
            f.write(f"{x:.6f},{'ab'[i % 2]},{'Y' if x > 0 else 'N'}\n")
    before = chunked.counters()
    fr = h2o3_tpu.import_file(str(p), destination_frame="ingest_smoke")
    model = GBM(ntrees=2, max_depth=2, seed=5).train(
        y="y", training_frame=fr)
    sfr = Frame()
    sfr.add("x1", Column.from_numpy(rng.standard_normal(64)))
    sfr.add("g", Column.from_numpy(
        np.array(["a", "b"])[rng.integers(0, 2, 64)], ctype="enum"))
    scoring.ScoringSession(model).predict(sfr)
    after = chunked.counters()
    assert after["coordinator_ingest_bytes"] == \
        before["coordinator_ingest_bytes"], (
        "import→train→score staged whole ingest columns on the "
        "coordinator host — the chunked sharded ingest contract is "
        "broken")
    assert after["chunk_rows"] >= before["chunk_rows"] + n
    fr.delete()


def test_multi_entry_flush_is_one_dispatch_per_bucket():
    """ISSUE-13 guard: a multi-entry micro-batch flush on the sharded
    path must coalesce into exactly ONE fused dispatch per row bucket
    (device-side concat of the per-entry shard-packed matrices) with
    ``gathered_rows`` untouched — the serving tier's
    one-dispatch-per-flush contract. A regression back to the PR-7
    per-entry dispatch (or to a host gather) trips this immediately."""
    import numpy as np

    import h2o3_tpu
    from h2o3_tpu import scoring
    from h2o3_tpu.core import sharded_frame
    from h2o3_tpu.core.frame import Column, Frame
    from h2o3_tpu.models.tree.gbm import GBM

    h2o3_tpu.init()
    rng = np.random.default_rng(88)
    n = 512
    fr = Frame()
    x = rng.standard_normal(n)
    fr.add("x1", Column.from_numpy(x))
    fr.add("y", Column.from_numpy(
        np.where(rng.random(n) < 1 / (1 + np.exp(-x)), "Y", "N"),
        ctype="enum"))
    model = GBM(ntrees=2, max_depth=2, seed=8).train(
        y="y", training_frame=fr)

    def score_fr(m, seed):
        sfr = Frame()
        sfr.add("x1", Column.from_numpy(
            np.random.default_rng(seed).standard_normal(m)))
        return sfr

    sess = scoring.ScoringSession(model)
    frames = [score_fr(40 + 13 * i, 100 + i) for i in range(4)]
    sess.predict(frames[0])                 # warm the one bucket involved
    before = sharded_frame.counters()
    scoring.reset_dispatch_counters()
    sess.predict_batch([(f, None, False) for f in frames])
    dc = scoring.dispatch_counters()
    after = sharded_frame.counters()
    assert dc.get("sharded") == 1, (
        f"a 4-entry flush recorded {dc} fused dispatches — the "
        "coalesced one-dispatch-per-bucket contract is broken")
    assert after["gathered_rows"] == before["gathered_rows"], (
        "the coalesced flush gathered columns to the coordinator host")


def test_pipeline_splice_is_one_program_per_bucket_with_zero_gathers():
    """ISSUE-16 guard: a 3-statement lazy Rapids feature chain feeding a
    GBM predict must run as EXACTLY ONE ``pipeline``-family fused program
    for its row bucket — engineered Columns never materialize
    (``materialized_columns`` stays 0) and ``gathered_rows`` never moves.
    A regression that re-materializes the munge output (or re-splits the
    dispatch) trips this immediately."""
    import numpy as np

    import h2o3_tpu
    from h2o3_tpu import pipeline, scoring
    from h2o3_tpu.core import sharded_frame
    from h2o3_tpu.core.frame import Column, Frame
    from h2o3_tpu.models.tree.gbm import GBM
    from h2o3_tpu.obs import compiles
    from h2o3_tpu.rapids import Session, exec_rapids, fusion, planner

    h2o3_tpu.init()
    rng = np.random.default_rng(66)
    n = 500
    tr = Frame()
    x = rng.standard_normal(n)
    tr.add("x1", Column.from_numpy(x))
    tr.add("x2", Column.from_numpy(rng.standard_normal(n)))
    tr.add("y", Column.from_numpy(
        np.where(rng.random(n) < 1 / (1 + np.exp(-x)), "Y", "N"),
        ctype="enum"))
    model = GBM(ntrees=2, max_depth=2, seed=6).train(
        y="y", training_frame=tr)
    m = 300
    raw = Frame(key="consist_pipe_raw")
    raw.add("r1", Column.from_numpy(rng.standard_normal(m)))
    raw.add("r2", Column.from_numpy(rng.standard_normal(m)))
    raw.install()
    with planner.force(True), fusion.force(True), pipeline.force(True):
        s = Session("consist_pipe")
        # split-free 3-statement chain: one fused program, no sub-plans
        exec_rapids('(tmp= cp_a (+ (cols consist_pipe_raw [0]) 1))', s)
        exec_rapids('(tmp= cp_b (ifelse (> (cols consist_pipe_raw [1]) 0) '
                    '(cols consist_pipe_raw [1]) cp_a))', s)
        pf = exec_rapids('(tmp= cp_pf (colnames= (cbind cp_a cp_b) [0 1] '
                         '["x1" "x2"]))', s)
        rows_before = [r for r in compiles.ledger_rows()
                       if r["family"] == "pipeline"]
        gath_before = sharded_frame.counters()["gathered_rows"]
        pcount_before = pipeline.counters()
        scoring.session_for(model).predict(pf, key="consist_pipe_out")
        rows = [r for r in compiles.ledger_rows()
                if r["family"] == "pipeline"][len(rows_before):]
        pcount = pipeline.counters()
        s.end()
    assert len(rows) == 1, (
        f"a 3-statement chain + predict landed {len(rows)} pipeline "
        "ledger rows for its one row bucket — the one-program-per-bucket "
        "contract is broken")
    assert rows[0]["cache"] == "compile"
    assert pcount["fused_dispatches"] == \
        pcount_before["fused_dispatches"] + 1
    assert pcount["materialized_columns"] == \
        pcount_before["materialized_columns"], (
        "the fused munge→score path materialized an engineered Column")
    assert sharded_frame.counters()["gathered_rows"] == gath_before, (
        "the fused munge→score path gathered columns to the coordinator")
    model.delete()


def test_hist_lowering_enum_matches_bench_wire_encoding(monkeypatch):
    """ISSUE-17 guard: the histogram lowering enumeration is CLOSED and
    its tuple order is the bench wire encoding — dashboards float the
    ``H2O3_BENCH hist_lowering <index>`` aux line, so reordering or
    widening ``LOWERINGS`` silently re-labels historical numbers. Pins:
    (1) the enum's exact content+order, (2) lowering_code == the index
    and rejects non-members, (3) the bench aux printer actually reports
    through lowering_code(hist_report()['lowering']) from BOTH timed
    chains, (4) every env-forced decision lands inside the enum."""
    from h2o3_tpu.models.tree import pallas_hist

    assert pallas_hist.LOWERINGS == ("matmul", "scatter", "pallas")
    for i, name in enumerate(pallas_hist.LOWERINGS):
        assert pallas_hist.lowering_code(name) == i
    with pytest.raises(ValueError):
        pallas_hist.lowering_code("onehot")   # not a lowering

    rep = pallas_hist.hist_report()
    assert {"lowering", "tile_S"} <= set(rep)
    assert rep["lowering"] in pallas_hist.LOWERINGS

    bench_src = (SRC / "bench.py").read_text(encoding="utf-8")
    assert "hist_lowering" in bench_src and "hist_tile_S" in bench_src, \
        "bench chains must emit the hist aux lines"
    assert "lowering_code(rep['lowering'])" in bench_src, \
        "the aux line must go through the wire encoding, not a raw name"
    # both timed train stages report which lowering actually ran
    for stage in ("run_flagship", "run_drf_deep"):
        body = bench_src.split(f"def {stage}(")[1].split("\ndef ")[0]
        assert "_print_hist_aux()" in body, \
            f"{stage} must print the hist aux lines next to its metric"

    for mode, want in [("1", "pallas"), ("pallas", "pallas"),
                       ("scatter", "scatter"), ("", "matmul")]:
        if mode:
            monkeypatch.setenv("H2O_TPU_PALLAS_HIST", mode)
        else:
            monkeypatch.delenv("H2O_TPU_PALLAS_HIST", raising=False)
        got = pallas_hist.decide_lowering(8, 16, 32)
        assert got == want and got in pallas_hist.LOWERINGS
