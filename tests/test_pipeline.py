"""ISSUE-16: munge→score pipeline fusion + standalone pipeline artifacts.

Acceptance surface:

- a frame fed by a still-PENDING lazy Rapids feature pipeline scores
  through ONE fused ``pipeline``-family program per row bucket, with ZERO
  engineered Columns materialized (``pipeline_materialized_columns`` /
  ``materialized_columns`` counter-asserted), BITWISE-identical to the
  staged flush→adapt→score path — for GBM (binomial + multinomial) and
  GLM (binomial + multinomial + regression), NA paths included;
- frames the splice cannot hold (unseen categorical levels) fall back to
  the staged path and stay correct;
- an exported *pipeline artifact* scores RAW rows in a FRESH process
  (no h2o3_tpu import) bitwise-identically to in-process serving;
- a warm restart against ``$H2O_TPU_COMPILE_CACHE_DIR`` compiles ZERO
  ``pipeline``-family programs.
"""

import os
import pathlib
import subprocess
import sys

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu import pipeline, scoring
from h2o3_tpu.core.frame import Column, Frame
from h2o3_tpu.models.glm import GLM
from h2o3_tpu.models.tree.gbm import GBM
from h2o3_tpu.rapids import Session, exec_rapids
from h2o3_tpu.rapids import fusion, planner


def _bits(a):
    a = np.asarray(a)
    return a.view(np.uint32) if a.dtype == np.float32 else a


def _assert_frames_bitwise(a: Frame, b: Frame, n: int) -> None:
    assert list(a.names) == list(b.names)
    for nm in a.names:
        ca, cb = np.asarray(a.col(nm).data)[:n], np.asarray(b.col(nm).data)[:n]
        assert np.array_equal(_bits(ca), _bits(cb)), \
            f"column {nm!r} differs from the staged path"


def _train_frame(seed: int, n: int = 700, classes: int = 2) -> Frame:
    rng = np.random.default_rng(seed)
    fr = Frame()
    x1, x2 = rng.standard_normal(n), rng.standard_normal(n)
    g = np.array(["a", "b", "c"])[rng.integers(0, 3, n)]
    fr.add("x1", Column.from_numpy(x1))
    fr.add("x2", Column.from_numpy(x2))
    fr.add("g", Column.from_numpy(g, ctype="enum"))
    if classes == 0:                              # regression response
        fr.add("y", Column.from_numpy(
            1.3 * x1 - x2 + (g == "a") + 0.1 * rng.standard_normal(n)))
    elif classes == 2:
        logit = 1.2 * x1 - x2 + (g == "a") * 0.5
        fr.add("y", Column.from_numpy(
            np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "Y", "N"),
            ctype="enum"))
    else:
        score = np.stack([x1, -x2, 0.5 * x1 + x2
                          + (g == "b")], axis=-1)
        fr.add("y", Column.from_numpy(
            np.array(["c0", "c1", "c2"])[np.argmax(
                score + rng.gumbel(size=score.shape), axis=-1)],
            ctype="enum"))
    return fr


def _raw_frame(key: str, seed: int, m: int = 257) -> Frame:
    """Raw (un-engineered) serving rows: NaNs in r1, all 3 g levels."""
    rng = np.random.default_rng(seed + 1000)
    f = Frame(key=key)
    r1 = rng.standard_normal(m)
    r1[::9] = np.nan                                       # NA path
    f.add("r1", Column.from_numpy(r1))
    f.add("r2", Column.from_numpy(rng.standard_normal(m)))
    g = np.array(["a", "b", "c"])[rng.integers(0, 3, m)]
    g[:3] = ["a", "b", "c"]          # pin the training domain exactly
    f.add("g", Column.from_numpy(g, ctype="enum"))
    f.install()
    return f


def _engineer(sess: Session, p: str, rawkey: str, *, variant: int) -> Frame:
    """Lazy engineered frame x1/x2/g over the raw columns. variant 0 is
    split-free (exportable as one program); variant 1 contains a
    multiply-into-subtract — a compiler-rewrite boundary that becomes a
    separate cached sub-program (Plan leaf) in-process."""
    if variant == 0:
        exec_rapids(f'(tmp= {p}_x1 (+ (cols {rawkey} [0]) 0.5))', sess)
        exec_rapids(f'(tmp= {p}_x2 (ifelse (> (cols {rawkey} [1]) 0) '
                    f'(cols {rawkey} [1]) (cols {rawkey} [0])))', sess)
    else:
        exec_rapids(f'(tmp= {p}_x1 (- (* (cols {rawkey} [0]) 2) '
                    f'(cols {rawkey} [1])))', sess)
        exec_rapids(f'(tmp= {p}_x2 (+ (cols {rawkey} [1]) 1))', sess)
    return exec_rapids(
        f'(tmp= {p}_pf (colnames= (cbind {p}_x1 {p}_x2 '
        f'(cols {rawkey} [2])) [0 1 2] ["x1" "x2" "g"]))', sess)


# ---------------------------------------------------------------------------
# randomized property suite: pipeline-fused == staged, bitwise
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed,classes", [
    (0, 2), (1, 2), (2, 2), (3, 2), (4, 2), (5, 2),
    (6, 3), (7, 3), (8, 3),
])
def test_gbm_pipeline_bitwise_vs_staged(cl, seed, classes):
    """The tentpole contract for forests: predict over a pending feature
    DAG runs as ONE fused munge→score dispatch with zero engineered
    Columns materialized, and every output column is bitwise-identical
    to the staged flush→adapt→predict path."""
    tr = _train_frame(seed, classes=classes)
    model = GBM(ntrees=3, max_depth=3, seed=seed + 1).train(
        y="y", training_frame=tr)
    try:
        with planner.force(True), fusion.force(True), pipeline.force(True):
            s = Session(f"pl_gbm_{seed}")
            raw = _raw_frame(f"plraw_gbm_{seed}", seed)
            pf = _engineer(s, f"pg{seed}", str(raw.key), variant=seed % 2)
            ssn = scoring.session_for(model)
            before = pipeline.counters()
            fused = ssn.predict(pf, key=f"pl_gbm_out_{seed}")
            after = pipeline.counters()
            assert after["captures"] == before["captures"] + 1
            assert after["fused_dispatches"] > before["fused_dispatches"]
            assert after["spliced_nodes"] >= before["spliced_nodes"] + 2
            assert after["materialized_columns"] == \
                before["materialized_columns"], \
                "an engineered Column materialized on the fused path"
            with pipeline.force(False):
                staged = ssn.predict(pf, key=f"pl_gbm_ref_{seed}")
            _assert_frames_bitwise(fused, staged, raw.nrows)
            s.end()
    finally:
        model.delete()


@pytest.mark.parametrize("seed,classes", [
    (10, 2), (11, 2), (12, 2), (13, 3), (14, 3), (15, 0),
])
def test_glm_pipeline_bitwise_vs_staged(cl, seed, classes):
    """The GLM half of the splice: per-feature fused plans feed the
    linear-predictor core in ONE ``pipeline``-family program; bitwise
    against the staged path for binomial, multinomial and regression."""
    tr = _train_frame(seed, classes=classes)
    fam = {2: "binomial", 3: "multinomial", 0: "gaussian"}[classes]
    model = GLM(family=fam, lambda_=0.0).train(y="y", training_frame=tr)
    try:
        with planner.force(True), fusion.force(True), pipeline.force(True):
            s = Session(f"pl_glm_{seed}")
            raw = _raw_frame(f"plraw_glm_{seed}", seed)
            pf = _engineer(s, f"pl{seed}", str(raw.key), variant=seed % 2)
            before = pipeline.counters()
            fused = model.predict(pf, key=f"pl_glm_out_{seed}")
            after = pipeline.counters()
            assert after["captures"] == before["captures"] + 1
            assert after["fused_dispatches"] > before["fused_dispatches"]
            assert after["materialized_columns"] == \
                before["materialized_columns"]
            with pipeline.force(False):
                staged = model.predict(pf, key=f"pl_glm_ref_{seed}")
            _assert_frames_bitwise(fused, staged, raw.nrows)
            s.end()
    finally:
        model.delete()


def test_unseen_level_falls_back_to_staged(cl):
    """A raw categorical whose domain differs from training (unseen
    level) cannot splice — the predict must silently take the staged
    path and still be correct."""
    tr = _train_frame(21)
    model = GBM(ntrees=3, max_depth=3, seed=3).train(
        y="y", training_frame=tr)
    try:
        with planner.force(True), fusion.force(True), pipeline.force(True):
            s = Session("pl_unseen")
            rng = np.random.default_rng(77)
            m = 120
            raw = Frame(key="plraw_unseen")
            raw.add("r1", Column.from_numpy(rng.standard_normal(m)))
            raw.add("r2", Column.from_numpy(rng.standard_normal(m)))
            g = np.array(["a", "b", "c", "zz"])[rng.integers(0, 4, m)]
            g[:4] = ["a", "b", "c", "zz"]            # 4-level domain
            raw.add("g", Column.from_numpy(g, ctype="enum"))
            raw.install()
            pf = _engineer(s, "pu", "plraw_unseen", variant=0)
            ssn = scoring.session_for(model)
            before = pipeline.counters()
            got = ssn.predict(pf, key="pl_unseen_out")
            assert pipeline.counters()["captures"] == before["captures"], \
                "a domain-mismatched frame must not capture"
            with pipeline.force(False):
                ref = ssn.predict(pf, key="pl_unseen_ref")
            _assert_frames_bitwise(got, ref, m)
            s.end()
    finally:
        model.delete()


# ---------------------------------------------------------------------------
# warm restart: zero pipeline compiles
# ---------------------------------------------------------------------------

def test_warm_restart_compiles_zero_pipeline_programs(cl, tmp_path,
                                                      monkeypatch):
    """PR-6 persistent tier for the new family: populate
    $H2O_TPU_COMPILE_CACHE_DIR, drop every in-memory program (simulated
    restart), re-run the same pipeline predict — the ``pipeline`` family
    must compile ZERO programs and serve from the disk tier."""
    from h2o3_tpu.obs import compiles

    monkeypatch.setenv("H2O_TPU_COMPILE_CACHE_DIR", str(tmp_path))
    tr = _train_frame(31)
    model = GBM(ntrees=3, max_depth=3, seed=9).train(
        y="y", training_frame=tr)
    try:
        with planner.force(True), fusion.force(True), pipeline.force(True):
            s = Session("pl_warm_a")
            raw = _raw_frame("plraw_warm_a", 31)
            pf = _engineer(s, "pw", str(raw.key), variant=0)
            ssn = scoring.session_for(model)
            cold = ssn.predict(pf, key="pl_warm_cold")
            s.end()
            if not any(p.name.startswith("xc_")
                       for p in tmp_path.iterdir()):
                pytest.skip("this jax cannot serialize executables")
            # simulated restart: every memory tier dropped
            pipeline.clear_programs()
            fusion.clear_programs()
            before = compiles.family_table().get("pipeline", {})
            s2 = Session("pl_warm_b")
            raw2 = _raw_frame("plraw_warm_b", 31)     # identical data
            pf2 = _engineer(s2, "pw2", str(raw2.key), variant=0)
            warm = ssn.predict(pf2, key="pl_warm_warm")
            after = compiles.family_table()["pipeline"]
            assert after["compiles"] == before.get("compiles", 0), \
                "a warm restart must compile zero pipeline programs"
            assert after["hits_disk"] > before.get("hits_disk", 0)
            _assert_frames_bitwise(warm, cold, raw.nrows)
            s2.end()
    finally:
        model.delete()


# ---------------------------------------------------------------------------
# standalone pipeline artifacts: raw rows, fresh process, bitwise
# ---------------------------------------------------------------------------

_RUNNER = r"""
import sys
import numpy as np

assert "h2o3_tpu" not in sys.modules
from h2o3_genmodel.aot import load_artifact
assert "h2o3_tpu" not in sys.modules, "genmodel pulled in the framework"

inp = np.load(sys.argv[-2], allow_pickle=False)
cols = {}
for k in inp.files:
    if k.startswith("num_"):
        cols[k[4:]] = inp[k]
    elif k.startswith("cat_"):
        cols[k[4:]] = [None if v == "" else str(v) for v in inp[k]]
out = {}
for tag in ("gbm", "glm"):
    s = load_artifact(sys.argv[-4] if tag == "gbm" else sys.argv[-3])
    got = s.score(cols)
    for k, v in got.items():
        a = np.asarray(v)
        if a.dtype.kind in "fiu":
            out[f"{tag}_{k}"] = a
        else:
            out[f"{tag}_{k}"] = a.astype(str)
np.savez(sys.argv[-1], **out)
"""


def test_pipeline_artifact_scores_raw_rows_in_fresh_process(cl, tmp_path):
    """The deployment contract: ``export_pipeline`` for a GBM and a GLM
    over the SAME pending feature DAG; a fresh python process (no
    h2o3_tpu import) scores the RAW columns through h2o3_genmodel.aot
    bitwise-identically to the in-process fused predictions."""
    from h2o3_tpu.artifact.pipeline import export_pipeline

    tr = _train_frame(41)
    gbm = GBM(ntrees=3, max_depth=3, seed=5).train(
        y="y", training_frame=tr)
    glm = GLM(family="binomial", lambda_=0.0).train(
        y="y", training_frame=tr)
    refs = {}
    raw_np = {}
    try:
        for tag, model in (("gbm", gbm), ("glm", glm)):
            with planner.force(True), fusion.force(True), \
                    pipeline.force(True):
                s = Session(f"pl_art_{tag}")
                raw = _raw_frame(f"plraw_art_{tag}", 41)
                if not raw_np:
                    raw_np = {
                        "num_r1": np.asarray(raw.col("r1").to_numpy(),
                                             np.float32),
                        "num_r2": np.asarray(raw.col("r2").to_numpy(),
                                             np.float32),
                        "cat_g": np.asarray(
                            [raw.col("g").domain[int(c)]
                             for c in np.asarray(
                                 raw.col("g").data)[:raw.nrows]]),
                    }
                pf = _engineer(s, f"pa{tag}", str(raw.key), variant=0)
                export_pipeline(model, pf,
                                str(tmp_path / f"art_{tag}"),
                                buckets=[512])
                if tag == "gbm":
                    refs[tag] = scoring.session_for(model).predict(
                        pf, key=f"pl_art_out_{tag}")
                else:
                    refs[tag] = model.predict(pf, key=f"pl_art_out_{tag}")
                s.end()

        script = tmp_path / "runner.py"
        script.write_text(_RUNNER)
        in_npz = tmp_path / "raw_cols.npz"
        np.savez(in_npz, **raw_np)
        out_npz = tmp_path / "out.npz"
        root = str(pathlib.Path(__file__).resolve().parents[1])
        env = dict(os.environ,
                   PYTHONPATH=os.pathsep.join(
                       [root] + [p for p in
                                 os.environ.get("PYTHONPATH", "").split(
                                     os.pathsep) if p]))
        proc = subprocess.run(
            [sys.executable, str(script), str(tmp_path / "art_gbm"),
             str(tmp_path / "art_glm"), str(in_npz), str(out_npz)],
            capture_output=True, text=True, timeout=300, env=env)
        assert proc.returncode == 0, proc.stderr[-2000:]
        with np.load(out_npz, allow_pickle=False) as z:
            for tag in ("gbm", "glm"):
                ref = refs[tag]
                n = len(raw_np["num_r1"])
                dom = ref.col("predict").domain
                lab = [dom[int(i)]
                       for i in np.asarray(ref.col("predict").data)[:n]]
                assert lab == list(z[f"{tag}_predict"]), \
                    f"{tag}: standalone labels differ"
                for lvl in ("N", "Y"):
                    assert np.array_equal(
                        _bits(np.asarray(ref.col(lvl).data)[:n]),
                        _bits(z[f"{tag}_{lvl}"])), \
                        f"{tag} {lvl!r}: standalone probs not bitwise"
    finally:
        gbm.delete()
        glm.delete()


def test_export_refuses_rewrite_boundary_features(cl, tmp_path):
    """A feature with a multiply-feeding-subtract splits into separate
    programs in-process; exporting it as ONE standalone program would
    license the FMA rewrites the split prevents — the exporter must
    refuse with the reason rather than ship a non-bitwise artifact."""
    from h2o3_tpu.artifact import ArtifactError
    from h2o3_tpu.artifact.pipeline import export_pipeline

    tr = _train_frame(51)
    model = GBM(ntrees=2, max_depth=2, seed=2).train(
        y="y", training_frame=tr)
    try:
        with planner.force(True), fusion.force(True), pipeline.force(True):
            s = Session("pl_refuse")
            raw = _raw_frame("plraw_refuse", 51)
            pf = _engineer(s, "pr", str(raw.key), variant=1)  # FMA split
            with pytest.raises(ArtifactError, match="rewrite"):
                export_pipeline(model, pf, str(tmp_path / "art_refuse"),
                                buckets=[512])
            s.end()
    finally:
        model.delete()
