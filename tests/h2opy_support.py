"""Load GENUINE h2o-py (the reference client, /root/reference/h2o-py) for
compatibility tests — the SURVEY §7 north star is that stock h2o-py drives
this server unchanged.

h2o-py still imports the py2/3 compat package `future` (not installed here,
and irrelevant on py3); we register a minimal in-memory shim BEFORE adding
h2o-py to sys.path. No reference code is copied — the client is imported
in place, read-only.
"""

from __future__ import annotations

import sys
import types

H2OPY_PATH = "/root/reference/h2o-py"


def _install_future_shim():
    if "future" in sys.modules:
        return
    future = types.ModuleType("future")
    utils = types.ModuleType("future.utils")
    utils.PY2 = False
    utils.PY3 = True

    def with_metaclass(meta, *bases):
        # six.with_metaclass: a temporary metaclass that replaces itself
        class metaclass(meta):
            def __new__(cls, name, this_bases, d):
                return meta(name, bases, d)

        return type.__new__(metaclass, "temporary_class", (), {})

    utils.with_metaclass = with_metaclass
    # dict view helpers (on py3 these are just the bound methods)
    utils.viewitems = lambda d: d.items()
    utils.viewkeys = lambda d: d.keys()
    utils.viewvalues = lambda d: d.values()

    builtins_pkg = types.ModuleType("future.builtins")
    iterators = types.ModuleType("future.builtins.iterators")
    iterators.range, iterators.filter = range, filter
    iterators.map, iterators.zip = map, zip
    misc = types.ModuleType("future.builtins.misc")
    misc.chr, misc.input, misc.open = chr, input, open
    misc.next, misc.round, misc.super = next, round, super
    builtins_pkg.iterators = iterators
    builtins_pkg.misc = misc

    future.utils = utils
    future.builtins = builtins_pkg
    sys.modules["future"] = future
    sys.modules["future.utils"] = utils
    sys.modules["future.builtins"] = builtins_pkg
    sys.modules["future.builtins.iterators"] = iterators
    sys.modules["future.builtins.misc"] = misc

    if "imp" not in sys.modules:      # removed in py3.12; h2o-py probes
        imp = types.ModuleType("imp")  # pandas/numpy presence via find_module

        def find_module(name, path=None):
            import importlib.util

            spec = importlib.util.find_spec(name)
            if spec is None:
                raise ImportError(name)
            return None, spec.origin, ("", "", 5)

        imp.find_module = find_module
        sys.modules["imp"] = imp


def ensure_h2opy():
    """Import and return genuine h2o-py."""
    if "h2o" in sys.modules and hasattr(sys.modules["h2o"], "connect"):
        return sys.modules["h2o"]
    _install_future_shim()
    if H2OPY_PATH not in sys.path:
        sys.path.insert(0, H2OPY_PATH)
    import h2o  # noqa: PLC0415

    return h2o
