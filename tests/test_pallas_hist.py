"""Pallas histogram kernel: parity with the XLA one-hot-matmul path.

Runs in interpret mode on the CPU mesh (the kernel compiles natively on
TPU); GBM end-to-end under the flag must match the default path exactly —
both accumulate the same bf16 products in f32.
"""

import numpy as np
import pytest

from h2o3_tpu.core.frame import Column, Frame
from h2o3_tpu.models.tree import pallas_hist


class TestKernelParity:
    def test_matches_reference_accumulation(self, cl):
        import jax.numpy as jnp

        rng = np.random.default_rng(0)
        n, F, maxB, S = 512, 5, 12, 4
        binned = rng.integers(0, maxB, (n, F)).astype(np.int32)
        node = rng.integers(0, S, n).astype(np.int32)
        w = rng.random(n).astype(np.float32)
        y = rng.standard_normal(n).astype(np.float32)

        out = np.asarray(pallas_hist.hist_pallas(
            jnp.asarray(binned), jnp.asarray(node), jnp.asarray(w),
            jnp.asarray(y), F=F, maxB=maxB, S=S, blk=128))
        assert out.shape == (F * maxB, S * 3)

        # dense reference in float64 (bf16 one-hots are exact 0/1 so the
        # only rounding is the bf16 cast of V)
        import ml_dtypes

        vals = np.stack([w, w * y, w * y * y], -1).astype(np.float32)
        V = np.zeros((n, S * 3), np.float32)
        for r in range(n):
            V[r, node[r] * 3:(node[r] + 1) * 3] = vals[r]
        Vb = V.astype(ml_dtypes.bfloat16).astype(np.float64)
        expect = np.zeros((F * maxB, S * 3))
        for f in range(F):
            O = (binned[:, f][:, None] == np.arange(maxB)).astype(np.float64)
            expect[f * maxB:(f + 1) * maxB] = O.T @ Vb
        np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-5)

    def test_zero_weight_rows_drop(self, cl):
        import jax.numpy as jnp

        n, F, maxB, S = 256, 3, 8, 2
        rng = np.random.default_rng(1)
        binned = jnp.asarray(rng.integers(0, maxB, (n, F)), jnp.int32)
        node = jnp.asarray(rng.integers(0, S, n), jnp.int32)
        w = jnp.zeros(n, jnp.float32)
        y = jnp.asarray(rng.standard_normal(n), jnp.float32)
        out = np.asarray(pallas_hist.hist_pallas(
            binned, node, w, y, F=F, maxB=maxB, S=S, blk=64))
        assert np.all(out == 0)

    def test_ragged_rows_pad(self, cl):
        """n not a multiple of blk: pad rows carry w=0."""
        import jax.numpy as jnp

        n, F, maxB, S = 300, 2, 6, 2
        rng = np.random.default_rng(2)
        binned = jnp.asarray(rng.integers(0, maxB, (n, F)), jnp.int32)
        node = jnp.zeros(n, jnp.int32)
        w = jnp.ones(n, jnp.float32)
        y = jnp.ones(n, jnp.float32)
        out = np.asarray(pallas_hist.hist_pallas(
            binned, node, w, y, F=F, maxB=maxB, S=S, blk=128))
        # total weight per feature must equal n exactly
        for f in range(2):
            assert out[f * maxB:(f + 1) * maxB, 0].sum() == pytest.approx(n)


class TestEndToEnd:
    def test_gbm_same_model_under_flag(self, cl, monkeypatch):
        rng = np.random.default_rng(7)
        n = 600
        x = rng.standard_normal(n)
        g = np.array(["a", "b", "c"], object)[rng.integers(0, 3, n)]
        yv = np.where(rng.random(n) < 1 / (1 + np.exp(-(2 * x + (g == "a")))),
                      "Y", "N")

        def train():
            from h2o3_tpu.models.tree.gbm import GBM

            fr = Frame()
            fr.add("x", Column.from_numpy(x))
            fr.add("g", Column.from_numpy(g, ctype="enum"))
            fr.add("y", Column.from_numpy(yv, ctype="enum"))
            m = GBM(ntrees=4, max_depth=3, seed=3).train(
                y="y", training_frame=fr)
            return m.predict(fr).col("Y").to_numpy(), \
                float(m._output.training_metrics.auc)

        monkeypatch.delenv("H2O_TPU_PALLAS_HIST", raising=False)
        p_ref, auc_ref = train()
        monkeypatch.setenv("H2O_TPU_PALLAS_HIST", "1")
        p_pal, auc_pal = train()
        assert auc_pal == pytest.approx(auc_ref, abs=1e-6)
        np.testing.assert_allclose(p_pal, p_ref, atol=1e-6)


def _tpu_present():
    try:
        import jax

        return any(d.platform == "tpu" for d in jax.devices())
    except Exception:   # noqa: BLE001 — backend probe
        return False


@pytest.mark.skipif(not _tpu_present(),
                    reason="no TPU device (run with H2O_TPU_TEST_REAL=1 on "
                           "a TPU host — conftest forces CPU otherwise)")
class TestRealTpuLowering:
    """Mosaic lowering tier (VERDICT r4 item 2): interpret mode never
    exercises the TPU compiler, so compilability of the kernel on silicon
    gets its own test. Opt in with H2O_TPU_TEST_REAL=1 (the conftest pins
    the backend to the virtual CPU mesh by default)."""

    def test_kernel_compiles_and_matches_on_tpu(self):
        import jax
        import jax.numpy as jnp

        from h2o3_tpu.models.tree import pallas_hist

        rng = np.random.default_rng(3)
        n, F, maxB, S = 1024, 6, 16, 8
        binned = jnp.asarray(rng.integers(0, maxB, (n, F)), jnp.int32)
        node = jnp.asarray(rng.integers(0, S, n), jnp.int32)
        w = jnp.asarray(rng.random(n), jnp.float32)
        y = jnp.asarray(rng.standard_normal(n), jnp.float32)
        out = np.asarray(pallas_hist.hist_pallas(
            binned, node, w, y, F=F, maxB=maxB, S=S, blk=256))
        # parity vs the XLA one-hot matmul reference on the same device
        import ml_dtypes

        vals = np.stack([np.asarray(w), np.asarray(w) * np.asarray(y),
                         np.asarray(w) * np.asarray(y) ** 2], -1)
        V = np.zeros((n, S * 3), np.float32)
        nodes = np.asarray(node)
        for r in range(n):
            V[r, nodes[r] * 3:(nodes[r] + 1) * 3] = vals[r]
        Vb = V.astype(ml_dtypes.bfloat16).astype(np.float64)
        expect = np.zeros((F * maxB, S * 3))
        bn = np.asarray(binned)
        for f in range(F):
            for r in range(n):
                expect[f * maxB + bn[r, f]] += Vb[r]
        np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-4)
