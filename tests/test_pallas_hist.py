"""Pallas gather→accumulate histogram kernel: the ISSUE-17 contract.

Three pillars, all on the interpret-mode CPU mesh (the kernel compiles
natively on TPU — TestRealTpuLowering opts in):

1. BITWISE parity: ``hist_gather`` (the kernel) must equal
   ``hist_gather_xla`` (the structurally identical XLA twin) bit for
   bit — across categorical/numeric mixes, NA bins, dead rows, ragged
   row padding and every frontier-tiling boundary — and the budget
   planner's tiling must never move a bit (tiled ≡ untiled), so split
   decisions cannot depend on ``H2O_TPU_HIST_VMEM_MB``.
2. The auto microbenchmark persists its verdict: measured once,
   ``cached`` on the next cold-cache call with the same geometry.
3. The compile ledger: a train lands every compile under family
   ``tree``; a warm identical re-train compiles NOTHING.
"""

import json

import numpy as np
import pytest

from h2o3_tpu.core.frame import Column, Frame
from h2o3_tpu.models.tree import pallas_hist


def _case(seed, n, F, maxB, S, *, dead_frac=0.15, zero_w_frac=0.1,
          ragged_bins=False):
    """Synthetic rows mixing the real grower's edge shapes: a reserved
    NA bin (the last bin of every feature, overweighted), dead rows
    (node = -1: sampled-out / routed-to-leaf), zero-weight live rows,
    and optionally ragged per-feature bin counts (categorical cards)."""
    rng = np.random.default_rng(seed)
    if ragged_bins:
        nbins = rng.integers(2, maxB + 1, F).astype(np.int64)
    else:
        nbins = np.full(F, maxB, np.int64)
    offsets = np.concatenate([[0], np.cumsum(nbins)[:-1]]).astype(np.int32)
    TB = int(nbins.sum())
    binned = np.stack([rng.integers(0, nbins[f], n) for f in range(F)],
                      axis=1).astype(np.int32)
    # overweight the NA bin (last bin per feature) like real NA columns
    na_rows = rng.random(n) < 0.2
    binned[na_rows] = (nbins - 1)[None, :]
    node = rng.integers(0, S, n).astype(np.int32)
    node[rng.random(n) < dead_frac] = -1
    w = rng.random(n).astype(np.float32) + 0.25
    w[rng.random(n) < zero_w_frac] = 0.0
    y = rng.standard_normal(n).astype(np.float32)
    return binned, node, w, y, offsets, TB


def _f64_reference(binned, node, w, y, offsets, TB, S):
    out = np.zeros((S * TB, 3), np.float64)
    for r in range(binned.shape[0]):
        nd = node[r]
        if nd < 0 or w[r] == 0.0:
            continue
        for f in range(binned.shape[1]):
            i = nd * TB + offsets[f] + binned[r, f]
            out[i] += (w[r], w[r] * y[r], w[r] * y[r] * y[r])
    return out


class TestKernelParity:
    """hist_gather ≡ hist_gather_xla BITWISE (the parity contract that
    makes the auto microbench's `scatter` leg a faithful stand-in and
    keeps CPU tests meaningful for the TPU kernel)."""

    @pytest.mark.parametrize("seed,n,F,maxB,S,tile_S,blk,ragged", [
        (0, 1000, 5, 8, 12, None, 256, False),   # ragged rows (1000 % 256)
        (1, 512, 3, 6, 7, 2, 128, True),         # S % tile_S != 0, ragged bins
        (2, 768, 8, 16, 16, 4, 256, False),      # multi-tile, aligned
        (3, 300, 2, 4, 3, 1, 128, True),         # tile_S=1 (every node alone)
        (4, 256, 1, 32, 5, None, 256, False),    # single feature, wide bins
    ])
    def test_bitwise_vs_xla_twin(self, cl, seed, n, F, maxB, S, tile_S,
                                 blk, ragged):
        import jax.numpy as jnp

        binned, node, w, y, offsets, TB = _case(seed, n, F, maxB, S,
                                                ragged_bins=ragged)
        kw = dict(offsets=offsets, TB=TB, S=S, tile_S=tile_S, blk=blk)
        got = np.asarray(pallas_hist.hist_gather(
            jnp.asarray(binned), jnp.asarray(node), jnp.asarray(w),
            jnp.asarray(y), **kw))
        ref = np.asarray(pallas_hist.hist_gather_xla(
            jnp.asarray(binned), jnp.asarray(node), jnp.asarray(w),
            jnp.asarray(y), **kw))
        assert got.shape == (S * TB, 3)
        assert np.array_equal(got, ref), \
            f"kernel != XLA twin at {np.argwhere(got != ref)[:5]}"

    def test_float64_ground_truth(self, cl):
        import jax.numpy as jnp

        n, F, maxB, S = 600, 4, 8, 6
        binned, node, w, y, offsets, TB = _case(10, n, F, maxB, S,
                                                ragged_bins=True)
        got = np.asarray(pallas_hist.hist_gather(
            jnp.asarray(binned), jnp.asarray(node), jnp.asarray(w),
            jnp.asarray(y), offsets=offsets, TB=TB, S=S, blk=128))
        expect = _f64_reference(binned, node, w, y, offsets, TB, S)
        np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-5)

    def test_tiled_equals_untiled_bitwise(self, cl):
        """The budget planner's whole safety argument: masked w=0 adds
        are exact f32 identities, so ANY tile_S gives the same bits."""
        import jax.numpy as jnp

        n, F, maxB, S = 800, 6, 8, 16
        binned, node, w, y, offsets, TB = _case(11, n, F, maxB, S)
        args = (jnp.asarray(binned), jnp.asarray(node), jnp.asarray(w),
                jnp.asarray(y))
        kw = dict(offsets=offsets, TB=TB, S=S, blk=256)
        untiled = np.asarray(pallas_hist.hist_gather(*args, tile_S=S, **kw))
        for tile_S in (1, 2, 4, 8):
            tiled = np.asarray(pallas_hist.hist_gather(*args, tile_S=tile_S,
                                                       **kw))
            assert np.array_equal(tiled, untiled), f"tile_S={tile_S}"

    def test_dead_and_zero_weight_rows_drop(self, cl):
        import jax.numpy as jnp

        n, F, maxB, S = 256, 3, 8, 4
        binned, node, w, y, offsets, TB = _case(12, n, F, maxB, S)
        dead = (node < 0) | (w == 0.0)
        out = np.asarray(pallas_hist.hist_gather(
            jnp.asarray(binned), jnp.asarray(node), jnp.asarray(w),
            jnp.asarray(y), offsets=offsets, TB=TB, S=S, blk=64))
        # total accumulated weight == sum over live rows only, exactly
        live_w = np.sort(w[~dead].astype(np.float64))
        assert out[:, 0].sum() == pytest.approx(F * live_w.sum(), rel=1e-6)
        # all-dead input -> all-zero histogram
        out0 = np.asarray(pallas_hist.hist_gather(
            jnp.asarray(binned), jnp.full(n, -1, np.int32),
            jnp.asarray(w), jnp.asarray(y),
            offsets=offsets, TB=TB, S=S, blk=64))
        assert np.all(out0 == 0)

    def test_budget_planner_invariants(self):
        """plan_tiles: per-tile accumulator provably under budget,
        tiles cover the frontier, None only when a single slot can't
        fit (the scatter-fallback signal)."""
        for TB, S, budget in [(40, 12, 4096), (512, 64, 1 << 20),
                              (96, 1, 4096), (1024, 4096, 1 << 22)]:
            plan = pallas_hist.plan_tiles(TB, S, budget)
            assert plan is not None
            tile_S, n_tiles, S_pad = plan
            assert 12 * TB * tile_S <= budget      # fits the budget
            assert tile_S * n_tiles == S_pad >= S  # covers the frontier
        # one slot (12·TB bytes) over budget -> None, caller scatters
        assert pallas_hist.plan_tiles(1000, 8, budget=11999) is None
        # env-driven default path stays consistent with the explicit one
        assert pallas_hist.plan_tiles(40, 12) is not None


class TestAutoDecide:
    """=auto: one measured timing shot per (F, maxB, S, backend), then
    the persisted verdict — warm restarts must not re-pay the bench."""

    def _clear(self):
        pallas_hist._AUTO_CACHE.clear()

    def test_verdict_measured_then_cached(self, cl, tmp_path, monkeypatch):
        monkeypatch.setenv("H2O_TPU_COMPILE_CACHE_DIR", str(tmp_path))
        self._clear()
        v1 = pallas_hist.auto_decide(3, 4, 4, n_rows=256, reps=1)
        assert v1 in pallas_hist.LOWERINGS
        assert pallas_hist.hist_report()["auto_source"] == "measured"
        stored = list(tmp_path.glob("hist_auto_*.json"))
        assert len(stored) == 1, "verdict must persist to the cache dir"
        assert json.loads(stored[0].read_text())["lowering"] == v1
        # simulated restart: drop the in-memory verdict, keep the disk one
        self._clear()
        v2 = pallas_hist.auto_decide(3, 4, 4, n_rows=256, reps=1)
        assert v2 == v1
        assert pallas_hist.hist_report()["auto_source"] == "cached"
        self._clear()

    def test_corrupt_verdict_remeasures(self, cl, tmp_path, monkeypatch):
        monkeypatch.setenv("H2O_TPU_COMPILE_CACHE_DIR", str(tmp_path))
        self._clear()
        pallas_hist.auto_decide(2, 3, 2, n_rows=128, reps=1)
        (path,) = tmp_path.glob("hist_auto_*.json")
        path.write_text("{not json")
        self._clear()
        v = pallas_hist.auto_decide(2, 3, 2, n_rows=128, reps=1)
        assert v in pallas_hist.LOWERINGS
        assert pallas_hist.hist_report()["auto_source"] == "measured"
        # ...and the re-measured verdict healed the file
        assert json.loads(path.read_text())["lowering"] == v
        self._clear()

    def test_gather_beats_matmul_on_wide_frontiers(self, cl):
        """The acceptance bar: at S=512, F=32 the gather formulation
        (the XLA twin — same program the TPU kernel expresses) beats
        the one-hot matmul on the CPU mesh. Margin is ~9x locally; the
        assertion only requires it to WIN."""
        import time

        import jax
        import jax.numpy as jnp

        n, F, maxB, S = 8192, 32, 16, 512
        rng = np.random.default_rng(0)
        binned = jnp.asarray(rng.integers(0, maxB, (n, F)), jnp.int32)
        node = jnp.asarray(rng.integers(0, S, n), jnp.int32)
        w = jnp.ones(n, jnp.float32)
        y = jnp.asarray(rng.standard_normal(n), jnp.float32)
        offsets = np.arange(F, dtype=np.int32) * maxB
        TB = F * maxB

        @jax.jit
        def matmul_hist(binned, node, w, y):
            Ob = jnp.concatenate(
                [jax.nn.one_hot(binned[:, f], maxB, dtype=jnp.bfloat16)
                 for f in range(F)], axis=1)
            node_oh = jax.nn.one_hot(node, S, dtype=jnp.float32)
            vals = jnp.stack([w, w * y, w * y * y], axis=-1)
            V = (node_oh[:, :, None] * vals[:, None, :]).reshape(n, S * 3)
            return jnp.dot(Ob.T, V.astype(jnp.bfloat16),
                           preferred_element_type=jnp.float32)

        gather = jax.jit(lambda b, nd, w, y: pallas_hist.hist_gather_xla(
            b, nd, w, y, offsets=offsets, TB=TB, S=S))

        def best_of(fn, reps=3):
            fn(binned, node, w, y).block_until_ready()
            t = float("inf")
            for _ in range(reps):
                t0 = time.perf_counter()
                fn(binned, node, w, y).block_until_ready()
                t = min(t, time.perf_counter() - t0)
            return t

        t_mm, t_ga = best_of(matmul_hist), best_of(gather)
        assert t_ga < t_mm, \
            f"gather {t_ga * 1e3:.1f} ms must beat matmul {t_mm * 1e3:.1f} ms"


def _train_frame(seed=7, n=600):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal(n)
    g = np.array(["a", "b", "c"], object)[rng.integers(0, 3, n)]
    yv = np.where(rng.random(n) < 1 / (1 + np.exp(-(2 * x + (g == "a")))),
                  "Y", "N")
    fr = Frame()
    fr.add("x", Column.from_numpy(x))
    fr.add("g", Column.from_numpy(g, ctype="enum"))
    fr.add("y", Column.from_numpy(yv, ctype="enum"))
    return fr


def _train_predict(fr, **gbm_kw):
    from h2o3_tpu.models.tree.gbm import GBM

    kw = dict(ntrees=4, max_depth=3, seed=3)
    kw.update(gbm_kw)
    m = GBM(**kw).train(y="y", training_frame=fr)
    return (m.predict(fr).col("Y").to_numpy(),
            float(m._output.training_metrics.auc))


class TestEndToEnd:
    def test_gbm_identical_across_all_three_lowerings(self, cl, monkeypatch):
        """The three lowerings are interchangeable: pallas ≡ scatter
        BITWISE (twin contract survives the full train), and both match
        the matmul default to accumulation-order tolerance."""
        fr = _train_frame()
        monkeypatch.delenv("H2O_TPU_PALLAS_HIST", raising=False)
        p_mm, auc_mm = _train_predict(fr)
        monkeypatch.setenv("H2O_TPU_PALLAS_HIST", "1")
        p_pl, auc_pl = _train_predict(fr)
        monkeypatch.setenv("H2O_TPU_PALLAS_HIST", "scatter")
        p_sc, auc_sc = _train_predict(fr)

        assert np.array_equal(p_pl, p_sc), "pallas != scatter bitwise"
        assert auc_pl == pytest.approx(auc_mm, abs=1e-6)
        assert auc_sc == pytest.approx(auc_mm, abs=1e-6)
        np.testing.assert_allclose(p_pl, p_mm, atol=1e-6)

    def test_vmem_budget_never_moves_a_split(self, cl, monkeypatch):
        """Train under the default 64 MB budget and under a starvation
        budget (forcing maximal tiling / the scatter fallback): the
        models must be BITWISE identical — the planner only re-tiles
        exact-identity zero-adds. The grower's lru caches are cleared
        between runs so the second train genuinely re-plans under the
        new budget instead of reusing the first compiled program."""
        from h2o3_tpu.models.tree import device_tree

        fr = _train_frame(21)
        monkeypatch.setenv("H2O_TPU_PALLAS_HIST", "1")
        monkeypatch.delenv("H2O_TPU_HIST_VMEM_MB", raising=False)
        device_tree._grow_fn.cache_clear()
        p_wide, auc_wide = _train_predict(fr, seed=11)
        monkeypatch.setenv("H2O_TPU_HIST_VMEM_MB", "0.004")   # ~4 KB
        device_tree._grow_fn.cache_clear()
        p_tiny, auc_tiny = _train_predict(fr, seed=11)
        device_tree._grow_fn.cache_clear()

        assert np.array_equal(p_wide, p_tiny), \
            "VMEM budget changed the model — tiling moved a bit"
        assert auc_wide == auc_tiny


class TestLedgerRegression:
    """Every train-triggered compile lands under family `tree`; a warm
    re-train with identical params compiles ZERO new programs."""

    def test_cold_train_lands_tree_rows_warm_is_free(self, cl, monkeypatch):
        from h2o3_tpu.obs import compiles

        monkeypatch.delenv("H2O_TPU_PALLAS_HIST", raising=False)
        # unique geometry so this test always starts cold in-process:
        # depth 4 + n=731 is used nowhere else in the suite
        fr = _train_frame(seed=41, n=731)

        def tree_rows():
            return [r for r in compiles.ledger_rows()
                    if r.get("family") == "tree" and r["cache"] == "compile"]

        def fresh(prior):
            # the ledger deque is bounded (maxlen=512): under saturation
            # appends drop rows off the FRONT, so a count-based slice
            # would miss new rows — detect them by object identity
            prior_ids = {id(r) for r in prior}
            return [r for r in tree_rows() if id(r) not in prior_ids]

        before = tree_rows()
        _train_predict(fr, ntrees=2, max_depth=4, seed=5)
        cold = fresh(before)
        assert cold, "a cold train must compile tree-family programs"
        programs = {r.get("program") for r in cold}
        assert any(p and p.startswith("tree_grow") for p in programs), programs

        hits_before = compiles.family_table().get("tree", {}) \
                                             .get("hits_memory", 0)
        mid = tree_rows()
        _train_predict(fr, ntrees=2, max_depth=4, seed=5)   # identical
        assert not fresh(mid), \
            "warm identical re-train must compile nothing"
        hits_after = compiles.family_table()["tree"]["hits_memory"]
        assert hits_after > hits_before, \
            "warm re-train must serve from the memory tier"

    def test_tree_family_is_declared(self):
        from h2o3_tpu.obs import compiles

        assert "tree" in compiles.FAMILIES


def _tpu_present():
    try:
        import jax

        return any(d.platform == "tpu" for d in jax.devices())
    except Exception:   # noqa: BLE001 — backend probe
        return False


@pytest.mark.skipif(not _tpu_present(),
                    reason="no TPU device (run with H2O_TPU_TEST_REAL=1 on "
                           "a TPU host — conftest forces CPU otherwise)")
class TestRealTpuLowering:
    """Mosaic lowering tier: interpret mode never exercises the TPU
    compiler, so compilability of the gather kernel on silicon gets its
    own test. Opt in with H2O_TPU_TEST_REAL=1 (the conftest pins the
    backend to the virtual CPU mesh by default)."""

    def test_kernel_compiles_and_matches_on_tpu(self):
        import jax.numpy as jnp

        n, F, maxB, S = 1024, 6, 16, 8
        binned, node, w, y, offsets, TB = _case(3, n, F, maxB, S)
        out = np.asarray(pallas_hist.hist_gather(
            jnp.asarray(binned), jnp.asarray(node), jnp.asarray(w),
            jnp.asarray(y), offsets=offsets, TB=TB, S=S, blk=256))
        expect = _f64_reference(binned, node, w, y, offsets, TB, S)
        np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-4)
