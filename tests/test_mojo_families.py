"""Round-trip + standalone-runtime parity for the round-5 MOJO families:
pca / glrm / word2vec / stackedensemble / targetencoder / coxph
(VERDICT r4 #9; reference hex/genmodel/algos/{pca,glrm,word2vec,ensemble,
targetencoder,coxph}/)."""

import numpy as np
import pytest

import h2o3_genmodel as gm
from h2o3_tpu.core.frame import Column, Frame, T_CAT
from h2o3_tpu.models import mojo


def _num_frame(n=300, p=4, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, p))
    X[:, 1] = X[:, 0] * 0.9 + rng.normal(0, 0.1, n)   # correlated pair
    return Frame.from_numpy(X, names=[f"x{i}" for i in range(p)]), X


def test_pca_mojo_roundtrip_and_runtime(cl):
    from h2o3_tpu.models.pca import PCA

    fr, X = _num_frame()
    m = PCA(k=2, transform="STANDARDIZE", seed=1).train(training_frame=fr)
    want = m.predict(fr)
    loaded = mojo.read_mojo(mojo.export_mojo_bytes(m))
    got = loaded.predict(fr)
    for j in (1, 2):
        np.testing.assert_allclose(
            np.asarray(want.col(f"PC{j}").to_numpy(), np.float64),
            np.asarray(got.col(f"PC{j}").to_numpy(), np.float64), atol=1e-5)
    # standalone numpy runtime
    pred = gm.load_mojo(mojo.export_mojo_bytes(m))
    out = pred.score({f"x{i}": X[:, i] for i in range(4)})
    np.testing.assert_allclose(
        out["PC1"], np.asarray(want.col("PC1").to_numpy(), np.float64),
        atol=1e-4)


def test_glrm_mojo_roundtrip_and_runtime(cl):
    from h2o3_tpu.models.glrm import GLRM

    fr, X = _num_frame(n=200, seed=1)
    m = GLRM(k=2, loss="Quadratic", max_iterations=150, seed=1).train(
        training_frame=fr)
    want = m.predict(fr)
    loaded = mojo.read_mojo(mojo.export_mojo_bytes(m))
    got = loaded.predict(fr)
    for nm in want.names:
        np.testing.assert_allclose(
            np.asarray(want.col(nm).to_numpy(), np.float64),
            np.asarray(got.col(nm).to_numpy(), np.float64), atol=1e-4)
    pred = gm.load_mojo(mojo.export_mojo_bytes(m))
    raw = pred._scorer.raw_predict(
        gm.scorers.ColumnBlock.from_dict(
            {f"x{i}": X[:, i] for i in range(4)}))
    # reconstruction error of the runtime close to the server's
    recon_err = float(np.mean((raw["reconstruction"]
                               - pred._scorer.di.expand(
                                   gm.scorers.ColumnBlock.from_dict(
                                       {f"x{i}": X[:, i]
                                        for i in range(4)}))) ** 2))
    assert recon_err < 0.5


def test_word2vec_mojo_roundtrip_and_runtime(cl):
    from h2o3_tpu.models.word2vec import Word2Vec

    rng = np.random.default_rng(2)
    words = np.asarray(["alpha", "beta", "gamma", "delta"])[
        rng.integers(0, 4, 600)]
    fr = Frame()
    fr.add("w", Column.from_numpy(words, ctype=T_CAT))
    m = Word2Vec(vec_size=8, epochs=2, min_word_freq=2, window_size=2,
                 seed=1).train(training_frame=fr)
    loaded = mojo.read_mojo(mojo.export_mojo_bytes(m))
    assert loaded.vocab == m.vocab
    np.testing.assert_allclose(loaded.vectors, m.vectors, atol=0)
    # transform through the restored model matches the original
    tf0 = m.transform(fr).to_pandas()
    tf1 = loaded.transform(fr).to_pandas()
    np.testing.assert_allclose(tf0.to_numpy(float), tf1.to_numpy(float),
                               atol=0)
    # standalone runtime word_vec
    pred = gm.load_mojo(mojo.export_mojo_bytes(m))
    for w in m.vocab:
        np.testing.assert_allclose(pred._scorer.word_vec(w),
                                   m.word_vec(w), atol=0)


def test_ensemble_mojo_roundtrip_and_runtime(cl):
    from h2o3_tpu.models.ensemble import StackedEnsemble
    from h2o3_tpu.models.glm import GLM
    from h2o3_tpu.models.tree.gbm import GBM

    rng = np.random.default_rng(3)
    n = 400
    X = rng.normal(size=(n, 3))
    logit = 1.5 * X[:, 0] - X[:, 1]
    y = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "Y", "N")
    fr = Frame.from_numpy(X, names=["a", "b", "c"])
    fr.add("y", Column.from_numpy(y, ctype=T_CAT))
    gbm = GBM(ntrees=5, max_depth=3, seed=1, nfolds=3,
              keep_cross_validation_predictions=True).train(
        y="y", training_frame=fr)
    glm = GLM(family="binomial", seed=1, nfolds=3, lambda_=0.0,
              keep_cross_validation_predictions=True).train(
        y="y", training_frame=fr)
    se = StackedEnsemble(base_models=[gbm, glm], seed=1).train(
        y="y", training_frame=fr)
    want = se.predict(fr).to_pandas()
    loaded = mojo.read_mojo(mojo.export_mojo_bytes(se))
    got = loaded.predict(fr).to_pandas()
    np.testing.assert_allclose(want["Y"].to_numpy(float),
                               got["Y"].to_numpy(float), atol=1e-6)
    # standalone runtime: nested base MOJOs + metalearner, no server
    pred = gm.load_mojo(mojo.export_mojo_bytes(se))
    out = pred.score({"a": X[:, 0], "b": X[:, 1], "c": X[:, 2]})
    np.testing.assert_allclose(out["Y"], want["Y"].to_numpy(float),
                               atol=1e-5)
    assert (out["predict"].astype(str) ==
            want["predict"].to_numpy().astype(str)).mean() > 0.99


def test_targetencoder_mojo_roundtrip_and_runtime(cl):
    from h2o3_tpu.models.target_encoder import TargetEncoder

    rng = np.random.default_rng(4)
    n = 500
    g = np.asarray(["u", "v", "w"])[rng.integers(0, 3, n)]
    y = np.where(rng.random(n) < np.where(g == "u", 0.8, 0.3), "Y", "N")
    fr = Frame()
    fr.add("g", Column.from_numpy(g, ctype=T_CAT))
    fr.add("y", Column.from_numpy(y, ctype=T_CAT))
    te = TargetEncoder(noise=0.0, blending=True).train(
        y="y", training_frame=fr)
    want = te.transform(fr).to_pandas()
    loaded = mojo.read_mojo(mojo.export_mojo_bytes(te))
    got = loaded.transform(fr).to_pandas()
    np.testing.assert_allclose(want["g_te"].to_numpy(float),
                               got["g_te"].to_numpy(float), atol=1e-10)
    pred = gm.load_mojo(mojo.export_mojo_bytes(te))
    out = pred.score({"g": g})
    np.testing.assert_allclose(out["g_te"], want["g_te"].to_numpy(float),
                               atol=1e-10)
    # unseen level scores as the prior
    out2 = pred.score({"g": np.asarray(["zzz"])})
    assert out2["g_te"][0] == pytest.approx(float(loaded.prior))


def test_coxph_mojo_roundtrip_and_runtime(cl):
    from h2o3_tpu.models.coxph import CoxPH

    rng = np.random.default_rng(5)
    n = 300
    x1, x2 = rng.normal(size=n), rng.normal(size=n)
    hazard = np.exp(0.8 * x1 - 0.5 * x2)
    t = rng.exponential(1.0 / hazard)
    event = (rng.random(n) < 0.8).astype(np.float64)
    fr = Frame.from_numpy(np.stack([x1, x2, t], 1),
                          names=["x1", "x2", "time"])
    fr.add("event", Column.from_numpy(np.where(event > 0, "1", "0"),
                                      ctype=T_CAT))
    m = CoxPH(stop_column="time", ties="efron").train(
        y="event", training_frame=fr)
    want = m.predict(fr).to_pandas()
    loaded = mojo.read_mojo(mojo.export_mojo_bytes(m))
    got = loaded.predict(fr).to_pandas()
    np.testing.assert_allclose(want["predict"].to_numpy(float),
                               got["predict"].to_numpy(float), atol=1e-5)
    assert loaded.coefficients.keys() == m.coefficients.keys()
    pred = gm.load_mojo(mojo.export_mojo_bytes(m))
    out = pred.score({"x1": x1, "x2": x2, "time": t})
    np.testing.assert_allclose(out["predict"],
                               want["predict"].to_numpy(float), atol=1e-4)
