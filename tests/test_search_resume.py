"""Durable AutoML/grid search engine (PR 18): the SearchState store's
torn-write discipline, member-crash quarantine, save-fault resilience, and
the watchdog's kill-mid-grid search resume — the library-level half of the
acceptance drills (the REST arc lives in test_supervision.py)."""

import json
import os
import time

import numpy as np
import pytest

from h2o3_tpu.automl import search
from h2o3_tpu.core import failure
from h2o3_tpu.core.dkv import DKV
from h2o3_tpu.core.frame import Column, Frame, T_CAT
from h2o3_tpu.core.job import Job
from h2o3_tpu.parallel import ckpt
from h2o3_tpu.parallel import distributed as D
from h2o3_tpu.parallel import oplog, supervisor, watchdog
from h2o3_tpu.parallel.watchdog import MAX_ATTEMPTS


class _FakeModel:
    def __init__(self, key="FakeModel_1"):
        self.key = key


def _state(key="SearchT", done=("m1",), pending=("m2",)):
    members = {}
    order = []
    for n in done:
        members[n] = {"name": n, "status": "done", "attempts": 1,
                      "model_id": f"Model_{n}", "score": 0.9, "error": None}
        order.append(n)
    for n in pending:
        members[n] = {"name": n, "status": "pending", "attempts": 0,
                      "model_id": None, "score": None, "error": None}
        order.append(n)
    return {"search": key, "kind": "grid",
            "spec": {"kind": "grid", "dest": "d"},
            "members": members, "order": order, "saves": 1, "dest": "d"}


# ---------------------------------------------------------------------------
# the durable store: atomic rotation, torn-file refusal, record listing
# ---------------------------------------------------------------------------

class TestSearchStateStore:
    def test_roundtrip_records_and_delete(self, cl, tmp_path, monkeypatch):
        monkeypatch.setenv("H2O_TPU_OPLOG_CKPT_DIR", str(tmp_path))
        ckpt.save_search_state("S_rt", _state("S_rt"))
        recs = [r for r in ckpt.search_state_records()
                if r["search"] == "S_rt"]
        assert recs and recs[0]["kind"] == "grid"
        assert recs[0]["members"] == {"done": 1, "pending": 1}
        data = ckpt.load_search_state("S_rt")
        assert data["state"]["members"]["m1"]["model_id"] == "Model_m1"
        ckpt.delete_search_state("S_rt")
        assert ckpt.load_search_state("S_rt") is None
        assert not [r for r in ckpt.search_state_records()
                    if r["search"] == "S_rt"]

    def test_torn_current_refused_previous_snapshot_wins(
            self, cl, tmp_path, monkeypatch):
        """Satellite (b): a torn current file is refused LOUDLY and the
        rotated previous generation is served instead."""
        import logging

        from h2o3_tpu.utils.log import get_logger

        monkeypatch.setenv("H2O_TPU_OPLOG_CKPT_DIR", str(tmp_path))
        ckpt.save_search_state("S_torn",
                               _state("S_torn", done=(),
                                      pending=("m1", "m2")))
        ckpt.save_search_state("S_torn", _state("S_torn", done=("m1",)))
        path = ckpt._search_path("S_torn")
        assert os.path.exists(path + ".prev")
        with open(path, "wb") as f:
            f.write(b"\x80\x04 torn mid-write")
        # the repo logger does not propagate: hook it directly
        msgs = []
        h = logging.Handler()
        h.emit = lambda rec: msgs.append(rec.getMessage())
        lg = get_logger()
        lg.addHandler(h)
        try:
            data = ckpt.load_search_state("S_torn")
        finally:
            lg.removeHandler(h)
        assert any("torn/corrupt" in m for m in msgs)
        # the previous generation (first save: m1 still pending) stands
        assert data is not None
        assert data["state"]["members"]["m1"]["status"] == "pending"
        ckpt.delete_search_state("S_torn")

    def test_both_generations_torn_returns_none(self, cl, tmp_path,
                                                monkeypatch):
        monkeypatch.setenv("H2O_TPU_OPLOG_CKPT_DIR", str(tmp_path))
        ckpt.save_search_state("S_gone", _state("S_gone"))
        ckpt.save_search_state("S_gone", _state("S_gone"))
        path = ckpt._search_path("S_gone")
        for p in (path, path + ".prev"):
            with open(p, "wb") as f:
                f.write(b"not a pickle")
        assert ckpt.load_search_state("S_gone") is None
        ckpt.delete_search_state("S_gone")


# ---------------------------------------------------------------------------
# the engine: quarantine, retry-in-place, save faults, restore semantics
# ---------------------------------------------------------------------------

class TestEngineQuarantine:
    def test_injected_crashes_park_at_max_attempts_search_completes(
            self, cl):
        """Acceptance: a member that crashes on EVERY attempt parks at
        MAX_ATTEMPTS while the rest of the search finishes normally."""
        eng = search.SearchEngine("SQ_park", "grid", {"kind": "grid"},
                                  persist=False)
        bad = eng.member("bad", "glm", {"alpha": 0.0})
        good = eng.member("good", "glm", {"alpha": 1.0})
        built = []

        def build(m):
            built.append(m["name"])
            return _FakeModel(f"Fake_{m['name']}")

        with failure.inject("search.member_train", times=MAX_ATTEMPTS):
            ok = eng.run([bad, good], build, concurrency=1)
        assert ok is True                       # the search itself succeeded
        assert bad["status"] == "parked"
        assert bad["attempts"] == MAX_ATTEMPTS
        assert "injected" in (bad["error"] or "").lower()
        assert good["status"] == "done"
        assert good["model_id"] == "Fake_good"
        assert built == ["good"]                # bad never reached build_fn

    def test_crash_burns_attempt_then_retries_in_place(self, cl):
        eng = search.SearchEngine("SQ_retry", "grid", {"kind": "grid"},
                                  persist=False)
        m = eng.member("flaky", "glm", {})
        with failure.inject("search.member_train", times=1):
            assert eng.run([m], lambda _m: _FakeModel(), concurrency=1)
        assert m["status"] == "done"
        assert m["attempts"] == 2               # crash + clean retry

    def test_deterministic_config_error_parks_first_attempt(self, cl):
        eng = search.SearchEngine("SQ_det", "grid", {"kind": "grid"},
                                  persist=False)
        m = eng.member("poisoned", "glm", {})

        def build(_m):
            raise ValueError("family nosuchfamily")

        assert eng.run([m], build, concurrency=1) is True
        assert m["status"] == "parked" and m["attempts"] == 1
        assert "nosuchfamily" in m["error"]

    def test_state_save_fault_never_fails_the_search(self, cl, tmp_path):
        eng = search.SearchEngine("SQ_save", "grid", {"kind": "grid"},
                                  sdir=str(tmp_path))
        eng.member("m", "glm", {})
        before = search.stats()["state_save_errors"]
        with failure.inject("search.state_save", times=1):
            eng.save()                          # swallowed, counted
        assert search.stats()["state_save_errors"] == before + 1
        eng.save()                              # next save lands
        assert ckpt.load_search_state("SQ_save",
                                      sdir=str(tmp_path)) is not None

    def test_restored_running_member_burns_attempt(self, cl):
        st = _state("SQ_restore", done=("m1",), pending=())
        st["members"]["m2"] = {"name": "m2", "status": "running",
                               "attempts": 2, "model_id": None,
                               "score": None, "error": None}
        st["order"].append("m2")
        eng = search.SearchEngine("SQ_restore", "grid", state=st,
                                  persist=False)
        assert eng.resumed is True
        m2 = eng.members["m2"]
        assert m2["status"] == "failed"         # retryable, not parked
        assert m2["attempts"] == 3              # in-flight attempt burned
        assert "coordinator died" in m2["error"]

    def test_concurrent_members_overlap(self, cl):
        """Two collective-free members at width 2 genuinely overlap (the
        gauge the chaos drill asserts over REST)."""
        eng = search.SearchEngine("SQ_conc", "grid", {"kind": "grid"},
                                  persist=False)
        ms = [eng.member(f"m{i}", "glm", {}) for i in range(2)]
        import threading
        gate = threading.Barrier(2, timeout=30)

        def build(_m):
            gate.wait()                         # both in flight at once
            return _FakeModel(f"Fake_{_m['name']}")

        search.reset_stats()
        assert eng.run(ms, build, concurrency=2)
        assert all(m["status"] == "done" for m in ms)
        assert search.stats()["overlap"] >= 2


class TestMirroredDiscipline:
    def test_scrub_clears_wallclock_budget_when_oplog_active(
            self, cl, monkeypatch):
        monkeypatch.setattr(oplog, "active", lambda: True)
        out = search._scrub_params({"max_runtime_secs": 5.0, "seed": 1})
        assert out["max_runtime_secs"] == 0.0 and out["seed"] == 1

    def test_concurrency_and_deadline_forced_off_on_oplog_cloud(
            self, cl, monkeypatch):
        monkeypatch.setenv("H2O_TPU_SEARCH_CONCURRENCY", "4")
        monkeypatch.setenv("H2O_TPU_SEARCH_MEMBER_DEADLINE_S", "9")
        monkeypatch.setattr(oplog, "active", lambda: True)
        assert search.search_concurrency() == 1
        assert search.member_deadline_s() == 0.0
        monkeypatch.setattr(oplog, "active", lambda: False)
        assert search.search_concurrency() == 4
        assert search.member_deadline_s() == 9.0


# ---------------------------------------------------------------------------
# watchdog search resume: kill mid-grid, zero manual recovery calls
# ---------------------------------------------------------------------------

def _frame(n=1200, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 3))
    yv = np.where(X[:, 0] + 0.5 * X[:, 1] +
                  rng.normal(scale=0.3, size=n) > 0, "Y", "N")
    fr = Frame.from_numpy(X, names=["a", "b", "c"])
    fr.add("y", Column.from_numpy(yv, ctype=T_CAT))
    return fr


class TestWatchdogSearchResume:
    def test_kill_mid_grid_watchdog_resumes_under_original_key(
            self, cl, monkeypatch, tmp_path):
        """The library half of the acceptance drill: a grid dies with two
        combos left, only durable state survives (the Job object is gone),
        and one watchdog tick re-dispatches the search under the ORIGINAL
        job key until the leaderboard completes."""
        monkeypatch.setenv("H2O_TPU_OPLOG_CKPT_DIR", str(tmp_path))
        monkeypatch.setenv("H2O_TPU_AUTO_RECOVER", "1")
        from h2o3_tpu.grid import H2OGridSearch
        from h2o3_tpu.models.model_builder import BUILDERS
        from h2o3_tpu.utils import timeline

        with D.memory_kv():
            oplog.reset()
            supervisor.reset()
            watchdog.reset()
            search.reset_stats()
            fr = _frame()
            fr.install()
            job = Job(description="glm Grid Build", dest="wd_resume_grid")
            grid = H2OGridSearch(BUILDERS["glm"](family="binomial"),
                                 {"alpha": [0.0, 0.5, 1.0]},
                                 grid_id="wd_resume_grid")
            grid._search_job = job

            settled = {"n": 0}
            orig = search.SearchEngine._build_one

            def dying(self, m, build_fn, score_fn=None):
                if settled["n"] >= 1:
                    raise RuntimeError("simulated coordinator loss")
                settled["n"] += 1
                return orig(self, m, build_fn, score_fn)

            monkeypatch.setattr(search.SearchEngine, "_build_one", dying)
            with pytest.raises(RuntimeError, match="coordinator loss"):
                grid.train(y="y", training_frame=fr)
            monkeypatch.setattr(search.SearchEngine, "_build_one", orig)
            data = ckpt.load_search_state(str(job.key))
            assert data is not None
            done0 = sum(1 for m in data["state"]["members"].values()
                        if m["status"] == "done")
            assert done0 == 1
            # the Job object dies with its coordinator
            DKV.remove(str(job.key))

            wd = watchdog.Watchdog(interval=3600, follow=False)
            tag = wd.tick()
            assert tag.startswith("resumed searches"), tag
            deadline = time.monotonic() + 120
            j2 = None
            while time.monotonic() < deadline:
                j2 = DKV.get(str(job.key))
                if isinstance(j2, Job) and j2.status == Job.DONE:
                    break
                time.sleep(0.05)
            assert isinstance(j2, Job) and j2.status == Job.DONE, \
                getattr(j2, "exception", j2)
            assert j2.attempt == 2              # original + one resume
            assert j2.resumed_from_iteration == done0
            st = search.stats()
            assert st["searches_resumed"] == 1
            assert st["members_done"] >= 3      # 1 pre-kill + 2 resumed
            # completion supersedes the durable record
            assert ckpt.load_search_state(str(job.key)) is None
            kinds = [e for e in timeline.events()
                     if e.get("kind") == "search"
                     and e.get("what") == "resumed"]
            assert kinds and kinds[-1]["search"] == str(job.key)

    def test_done_job_search_record_is_gcd(self, cl, monkeypatch,
                                           tmp_path):
        monkeypatch.setenv("H2O_TPU_OPLOG_CKPT_DIR", str(tmp_path))
        with D.memory_kv():
            watchdog.reset()
            job = Job(description="done search", dest="gc_dest")
            job.status = Job.DONE
            ckpt.save_search_state(str(job.key), _state(str(job.key)))
            assert search.resume_orphaned() == []
            assert ckpt.load_search_state(str(job.key)) is None
            DKV.remove(str(job.key))

    def test_unreadable_state_strikes_out_after_max_attempts(
            self, cl, monkeypatch, tmp_path):
        """A record whose BOTH snapshot generations are gone can never be
        resumed: MAX_ATTEMPTS strikes drop it instead of looping forever."""
        monkeypatch.setenv("H2O_TPU_OPLOG_CKPT_DIR", str(tmp_path))
        with D.memory_kv():
            watchdog.reset()
            ckpt.save_search_state("S_strike", _state("S_strike"))
            path = ckpt._search_path("S_strike")
            os.unlink(path)
            for i in range(MAX_ATTEMPTS):
                assert [r for r in ckpt.search_state_records()
                        if r["search"] == "S_strike"], f"gone at strike {i}"
                assert search.resume_orphaned() == []
            assert not [r for r in ckpt.search_state_records()
                        if r["search"] == "S_strike"]


# ---------------------------------------------------------------------------
# grid recovery dirs: unified store + legacy format
# ---------------------------------------------------------------------------

class TestGridRecoveryStore:
    def test_legacy_grid_json_dir_still_loads(self, cl, tmp_path):
        """Satellite (a): dirs exported by the pre-engine grid code (one
        grid.json + models/*.bin) load through the legacy path and resume
        the remaining combos."""
        import pickle

        from h2o3_tpu.grid import H2OGridSearch

        fr = _frame(n=800, seed=3)
        g0 = H2OGridSearch("glm", {"alpha": [0.0, 1.0]},
                           grid_id="legacy_src")
        g0.train(y="y", training_frame=fr, family="binomial")
        assert len(g0.models) == 2
        legacy = tmp_path / "legacy_grid"
        mdir = legacy / "models"
        mdir.mkdir(parents=True)
        kept = g0.models[0]
        with open(mdir / f"{kept.key}.bin", "wb") as f:
            pickle.dump(kept, f)
        meta = {"grid_id": "legacy_grid", "algo": "glm",
                "base_params": {"family": "binomial"},
                "hyper_params": {"alpha": [0.0, 1.0]},
                "search_criteria": {"strategy": "Cartesian"},
                "done": [{"combo_key": H2OGridSearch._combo_key(
                    {"alpha": 0.0})}],
                "models": [str(kept.key)],
                "grid_params": {str(kept.key): {"alpha": 0.0}},
                "failed": []}
        with open(legacy / "grid.json", "w") as f:
            json.dump(meta, f)

        g = H2OGridSearch.load(str(legacy))
        assert len(g.models) == 1
        assert getattr(g.models[0], "_grid_params", {}) == {"alpha": 0.0}
        g.train(y="y", training_frame=fr, family="binomial")
        assert len(g.models) == 2               # only alpha=1.0 retrained
        combos = sorted(m._grid_params["alpha"] for m in g.models)
        assert combos == [0.0, 1.0]

    def test_new_recovery_dir_keeps_files_after_finish(self, cl, tmp_path):
        """recovery_dir doubles as the export surface: a COMPLETED grid's
        state files stay on disk (only the cloud KV record drops) so
        H2OGridSearch.load keeps working after success."""
        from h2o3_tpu.grid import H2OGridSearch

        fr = _frame(n=800, seed=4)
        rec = str(tmp_path / "rec")
        g0 = H2OGridSearch("glm", {"alpha": [0.0, 1.0]},
                           grid_id="keepfiles_grid")
        g0.train(y="y", training_frame=fr, family="binomial",
                 recovery_dir=rec)
        assert len(g0.models) == 2
        assert [n for n in os.listdir(rec)
                if n.startswith("searchckpt_") and n.endswith(".pkl")]
        g = H2OGridSearch.load(rec)
        assert len(g.models) == 2
        assert {str(m.key) for m in g.models} == \
            {str(m.key) for m in g0.models}
