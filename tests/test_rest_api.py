"""REST API round-trip tests: server + thin client over real HTTP.

Reference analog: h2o-py pyunits driven through the REST layer (SURVEY.md §4
tier 3) — here the client and server run in one process over loopback."""

import numpy as np
import pytest

from h2o3_tpu.api.server import start_server
from h2o3_tpu import client


@pytest.fixture(scope="module")
def server(cl):
    srv = start_server(port=0)        # ephemeral port
    client.connect(port=srv.port)
    yield srv
    srv.stop()


@pytest.fixture()
def csv_path(tmp_path):
    rng = np.random.default_rng(0)
    p = tmp_path / "api_test.csv"
    with open(p, "w") as f:
        f.write("g,x,y\n")
        for i in range(500):
            g = ["a", "b", "c"][i % 3]
            x = rng.normal()
            f.write(f"{g},{x:.4f},{'YES' if x + rng.normal()*0.3 > 0 else 'NO'}\n")
    return str(p)


def test_cloud_status(server):
    cloud = client.cluster_status()
    assert cloud["cloud_healthy"]
    assert cloud["cloud_size"] >= 1


def test_import_parse_frames(server, csv_path):
    fr = client.import_file(csv_path)
    assert fr.nrows == 500
    assert fr.names == ["g", "x", "y"]
    head = fr.head(5)
    assert len(head) == 5 and set(head[0]) == {"g", "x", "y"}
    summ = fr.summary()
    assert summ["x"]["type"] == "real"
    fr.delete()


def test_rapids_over_http(server, csv_path):
    fr = client.import_file(csv_path)
    m = fr.mean("x")
    assert abs(m) < 0.2
    sub = fr.cols(["g", "x"])
    assert sub.ncols == 2
    out = client.rapids(f"(tmp= filt (rows {fr.frame_id} (> (cols_py {fr.frame_id} 'x') 0)))")
    assert 0 < out["num_rows"] < 500


def test_train_predict_over_http(server, csv_path):
    fr = client.import_file(csv_path)
    m = client.train("gbm", y="y", training_frame=fr, ntrees=10, max_depth=3)
    info = m.info()
    assert info["output"]["model_category"] == "Binomial"
    assert info["output"]["training_metrics"]["AUC"] > 0.7
    pred = m.predict(fr)
    assert pred.nrows == 500
    assert "predict" in pred.names
    assert m.model_id in client.list_models()


def test_glm_over_http(server, csv_path):
    fr = client.import_file(csv_path)
    m = client.train("glm", y="y", training_frame=fr, family="binomial")
    assert m.info()["output"]["training_metrics"]["AUC"] > 0.7


def test_error_paths(server):
    with pytest.raises(client.H2OServerError):
        client.train("nosuchalgo", y="y",
                     training_frame=client.RemoteFrame("nope"))
    with pytest.raises(FileNotFoundError):
        client.import_file("/does/not/exist.csv")


def test_estimator_aliases(cl):
    import h2o3_tpu as h2o

    cls = h2o.H2OGradientBoostingEstimator
    assert cls.algo_name == "gbm"
    assert h2o.H2OKMeansEstimator.algo_name == "kmeans"
    assert h2o.H2OXGBoostEstimator.algo_name == "xgboost"


def test_xgboost_param_mapping(cl):
    import numpy as np

    from h2o3_tpu.models.xgboost import XGBoost

    rng = np.random.default_rng(0)
    X = rng.normal(size=(1500, 4))
    y = X[:, 0] * 2 + np.sin(X[:, 1]) + 0.1 * rng.normal(size=1500)
    from h2o3_tpu.core.frame import Frame

    fr = Frame.from_numpy(np.column_stack([X, y]), names=["a", "b", "c", "d", "y"])
    m = XGBoost(n_estimators=30, eta=0.2, subsample=0.8,
                colsample_bytree=0.8, reg_lambda=1.0, seed=1).train(
        y="y", training_frame=fr)
    assert m.algo_name == "xgboost"
    assert m._output.training_metrics.r2 > 0.85


def test_create_and_split_frame_routes(server):
    """POST /3/CreateFrame + /3/SplitFrame (CreateFrameHandler /
    SplitFrameHandler analogs)."""
    body = client._req("POST", "/3/CreateFrame",
                       data={"rows": "200", "cols": "3", "seed": "7",
                             "dest": "cf_test"})
    assert body["job"]["status"] == "DONE"
    info = client._req("GET", "/3/Frames/cf_test/light")
    assert info["frames"][0]["rows"] == 200
    body = client._req("POST", "/3/SplitFrame",
                       data={"dataset": "cf_test", "ratios": "[0.5]"})
    keys = [k["name"] for k in body["destination_frames"]]
    assert len(keys) == 2
    n0 = client._req("GET", f"/3/Frames/{keys[0]}/light")["frames"][0]["rows"]
    n1 = client._req("GET", f"/3/Frames/{keys[1]}/light")["frames"][0]["rows"]
    assert n0 + n1 == 200


def test_export_file(server, tmp_path, csv_path):
    import h2o3_tpu as h2o

    fr = h2o.import_file(csv_path)
    out = str(tmp_path / "exported.csv")
    h2o.export_file(fr, out)
    fr2 = h2o.import_file(out)
    assert fr2.nrows == fr.nrows and fr2.ncols == fr.ncols
    import pytest

    with pytest.raises(FileExistsError):
        h2o.export_file(fr, out)


def test_create_frame_fractions_and_sentinel_seed(server):
    body = client._req("POST", "/3/CreateFrame",
                       data={"rows": "50", "cols": "4", "seed": "-1",
                             "categorical_fraction": "0.5",
                             "real_fraction": "0.5", "factors": "3",
                             "dest": "cf_frac"})
    assert body["job"]["status"] == "DONE"
    cols = client._req("GET", "/3/Frames/cf_frac")["frames"][0]["columns"]
    types = {c["type"] for c in cols}
    assert "enum" in types     # categorical_fraction honored


def test_import_sql_and_network_test_routes(server, tmp_path):
    import sqlite3

    db = str(tmp_path / "r.db")
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE t (a REAL, b TEXT)")
    conn.executemany("INSERT INTO t VALUES (?,?)",
                     [(i, "xy"[i % 2]) for i in range(40)])
    conn.commit(); conn.close()
    body = client._req("POST", "/99/ImportSQLTable",
                       data={"connection_url": f"sqlite:///{db}",
                             "table": "t"})
    key = body["key"]["name"]
    info = client._req("GET", f"/3/Frames/{key}/light")["frames"][0]
    assert info["rows"] == 40
    bench = client._req("GET", "/3/NetworkTest", query={"size": "128"})["bench"]
    assert bench["matmul_gflops"] > 0 and bench["psum_latency_us"] > 0
