"""ISSUE-20 memory-safety suite: HBM budget planner, chunk-streamed
fused programs, and the OOM degradation ladder.

Acceptance surface:

- the planner (memory/budget.py) is a pure function of budget knobs,
  headroom and live residency — unbudgeted (CPU default) plans are
  always ``full`` so the engine stays byte-for-byte its pre-planner
  self;
- chunk-streamed dispatch (memory/stream.run_windows) is BITWISE
  identical to single dispatch for every integrated family — scoring
  (binomial + multinomial, NA paths), rapids fused statements, the
  sharded bin pack and the fused munge→score pipeline — across chunk
  sizes {1 row, ragged tail, full}, with ``gathered_rows`` unchanged;
- chaos: an injected ``mem.exhausted`` fault walks the ladder (sweep,
  halve, bounded backoff) and completes with ZERO client-visible
  errors while the retry budget suffices; an exhausted ladder surfaces
  a typed 503 + Retry-After and a ``mem_pressure`` flight record, and
  admission sheds until the cooldown lapses;
- spilled columns reload through a sha256 checksum gate and the SAME
  bounded retry budget as DKV blob fetches.
"""

import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

from h2o3_tpu.core import failure
from h2o3_tpu.core.frame import Column, Frame
from h2o3_tpu.memory import MemoryPressureError, budget, stream

RFR = "mem_rapids_fr"


@pytest.fixture(autouse=True)
def _pressure_clean():
    """Pressure state must never leak across tests — a flagged cooldown
    would shed every later REST/admission call in the session."""
    budget.reset_pressure()
    yield
    budget.reset_pressure()


def _force_chunk(monkeypatch, family, chunk):
    """Pin `family`'s plan to `chunk`-row windows regardless of the
    process budget — the deterministic way to drive the streaming path
    on an unbudgeted CPU mesh."""
    orig = budget.plan

    def fake(fam, rows, row_bytes=None):
        if fam == family and rows > chunk:
            return budget.Plan("chunked", chunk, rows, 4.0, 1 << 20)
        return orig(fam, rows, row_bytes)

    monkeypatch.setattr(budget, "plan", fake)


def _mem_flights():
    from h2o3_tpu.obs import flight

    return sum(1 for r in flight.list_records()
               if (r.get("reason") or "").startswith("mem_pressure"))


# ---------------------------------------------------------------------------
# planner unit surface
# ---------------------------------------------------------------------------

class TestBudgetPlanner:
    def test_unbudgeted_cpu_plans_full(self, cl, monkeypatch):
        """No knob + CPU backend (no bytes_limit) → every plan is full;
        the data plane never windows."""
        monkeypatch.delenv("H2O_TPU_MEM_BUDGET_MB", raising=False)
        assert budget.budget_bytes() is None
        p = budget.plan("scoring", 10_000_000)
        assert p.mode == "full" and p.chunk_rows == 10_000_000

    def test_pinned_budget_chunks(self, monkeypatch):
        monkeypatch.setenv("H2O_TPU_MEM_BUDGET_MB", "1")
        monkeypatch.setenv("H2O_TPU_MEM_HEADROOM", "0")
        monkeypatch.setattr(budget, "live_bytes", lambda: 0)
        p = budget.plan("unit_fam_a", 1_000_000, row_bytes=64.0)
        assert p.mode == "chunked"
        assert p.chunk_rows == (1 << 20) // 64
        # small enough requests still fit whole
        assert budget.plan("unit_fam_a", 100, row_bytes=64.0).mode == "full"

    def test_refuse_when_not_one_row_fits(self, monkeypatch):
        monkeypatch.setenv("H2O_TPU_MEM_BUDGET_MB", "1")
        monkeypatch.setattr(budget, "live_bytes", lambda: 0)
        p = budget.plan("unit_fam_b", 10, row_bytes=float(4 << 20))
        assert p.mode == "refuse" and p.chunk_rows == 0

    def test_headroom_clamped(self, monkeypatch):
        monkeypatch.setenv("H2O_TPU_MEM_HEADROOM", "2.5")
        assert budget.headroom() == 0.9
        monkeypatch.setenv("H2O_TPU_MEM_HEADROOM", "-1")
        assert budget.headroom() == 0.0

    def test_residency_shrinks_free_budget(self, monkeypatch):
        monkeypatch.setenv("H2O_TPU_MEM_BUDGET_MB", "1")
        monkeypatch.setenv("H2O_TPU_MEM_HEADROOM", "0")
        monkeypatch.setattr(budget, "live_bytes", lambda: (1 << 20) - 1024)
        assert budget.free_bytes() == 1024

    def test_note_compiled_seeds_row_bytes(self):
        class _MA:
            argument_size_in_bytes = 800
            output_size_in_bytes = 200
            temp_size_in_bytes = 0
            generated_code_size_in_bytes = 0

        class _Exe:
            def memory_analysis(self):
                return _MA()

        budget.note_compiled("unit_fam_c", 100, _Exe())
        assert budget.row_bytes_estimate("unit_fam_c") == 10.0
        # the estimate is a max: a smaller later program never shrinks it
        budget.note_compiled("unit_fam_c", 1000, _Exe())
        assert budget.row_bytes_estimate("unit_fam_c") == 10.0
        # floor: one float32 lane, so plans can never divide by zero
        assert budget.row_bytes_estimate("never_compiled") == 4.0

    def test_snapshot_shape(self, cl):
        snap = budget.snapshot()
        for k in ("budget_bytes", "headroom", "free_bytes", "live_bytes",
                  "evicted_columns", "row_bytes_estimates",
                  "pressure_active", "pressure_count", "stream"):
            assert k in snap
        assert set(snap["stream"]) == set(stream.counters())


# ---------------------------------------------------------------------------
# run_windows unit surface (fake dispatch — no device programs involved)
# ---------------------------------------------------------------------------

class TestRunWindows:
    def test_full_plan_is_one_window(self, monkeypatch):
        monkeypatch.delenv("H2O_TPU_MEM_BUDGET_MB", raising=False)
        calls = []
        out = stream.run_windows(
            "unit_fam_d", 100,
            lambda pos, m: calls.append((pos, m)) or np.arange(pos, pos + m),
            max_window=100)
        assert calls == [(0, 100)]
        assert np.array_equal(np.concatenate(out), np.arange(100))

    def test_chunked_windows_bitwise_row_order(self, monkeypatch):
        _force_chunk(monkeypatch, "unit_fam_d", 7)
        c0 = stream.counters()
        fetched = []
        out = stream.run_windows(
            "unit_fam_d", 30, lambda pos, m: np.arange(pos, pos + m),
            max_window=30,
            fetch=lambda o, m: fetched.append(len(o)) or o)
        c1 = stream.counters()
        assert np.array_equal(np.concatenate(out), np.arange(30))
        assert fetched == [7, 7, 7, 7, 2]       # every window fetched once
        assert c1["chunked_runs"] - c0["chunked_runs"] == 1
        assert c1["windows"] - c0["windows"] == 5

    @pytest.mark.chaos
    def test_injected_oom_walks_ladder_and_recovers(self, monkeypatch):
        """Two injected OOMs on a full-plan run: the ladder sweeps,
        halves and completes — the caller sees NO error and bitwise
        output."""
        monkeypatch.delenv("H2O_TPU_MEM_BUDGET_MB", raising=False)
        c0 = stream.counters()
        with failure.inject("mem.exhausted", times=2):
            out = stream.run_windows(
                "unit_fam_d", 64, lambda pos, m: np.arange(pos, pos + m),
                max_window=64)
        c1 = stream.counters()
        assert np.array_equal(np.concatenate(out), np.arange(64))
        assert c1["ladder_halvings"] - c0["ladder_halvings"] >= 1
        assert c1["ladder_recoveries"] - c0["ladder_recoveries"] == 1
        assert c1["pressure_failures"] == c0["pressure_failures"]
        assert not budget.pressure_active()

    @pytest.mark.chaos
    def test_fetch_oom_retries_pending_window(self, monkeypatch):
        """RESOURCE_EXHAUSTED surfacing at the double-buffered FETCH is
        retried from the pending window's own start — no row is lost or
        duplicated."""
        monkeypatch.delenv("H2O_TPU_MEM_BUDGET_MB", raising=False)
        _force_chunk(monkeypatch, "unit_fam_d", 8)
        boom = {"left": 1}

        def fetch(o, m):
            if boom["left"]:
                boom["left"] -= 1
                raise RuntimeError("RESOURCE_EXHAUSTED: synthetic OOM")
            return o

        c0 = stream.counters()
        out = stream.run_windows(
            "unit_fam_d", 20, lambda pos, m: np.arange(pos, pos + m),
            max_window=20, fetch=fetch)
        c1 = stream.counters()
        assert np.array_equal(np.concatenate(out), np.arange(20))
        assert c1["ladder_recoveries"] - c0["ladder_recoveries"] == 1

    @pytest.mark.chaos
    def test_exhausted_ladder_503_and_flight_record(self, monkeypatch):
        """More OOMs than the bounded retry budget: a typed 503 with the
        family + attempted chunk sizes, a ``mem_pressure`` flight record
        and the admission pressure flag — never a hang, never a crash."""
        monkeypatch.delenv("H2O_TPU_MEM_BUDGET_MB", raising=False)
        f0 = _mem_flights()
        c0 = stream.counters()
        with failure.inject("mem.exhausted", times=64):
            with pytest.raises(MemoryPressureError) as ei:
                stream.run_windows(
                    "unit_fam_d", 64,
                    lambda pos, m: np.arange(pos, pos + m), max_window=64)
        e = ei.value
        assert e.status == 503
        assert e.retry_after_s >= 0.1
        assert e.family == "unit_fam_d"
        assert len(e.attempts) >= 1 and e.attempts[0] == 64
        c1 = stream.counters()
        assert c1["pressure_failures"] - c0["pressure_failures"] == 1
        assert budget.pressure_active()
        assert _mem_flights() - f0 >= 1

    def test_non_oom_exceptions_pass_through(self, monkeypatch):
        monkeypatch.delenv("H2O_TPU_MEM_BUDGET_MB", raising=False)

        def boom(pos, m):
            raise ValueError("not a memory error")

        c0 = stream.counters()
        with pytest.raises(ValueError):
            stream.run_windows("unit_fam_d", 10, boom, max_window=10)
        assert stream.counters()["ladder_halvings"] == c0["ladder_halvings"]

    def test_refuse_plan_raises_before_dispatch(self, monkeypatch):
        monkeypatch.setattr(
            budget, "plan",
            lambda fam, rows, row_bytes=None: budget.Plan(
                "refuse", 0, rows, 1e9, 0))
        calls = []
        with pytest.raises(MemoryPressureError):
            stream.run_windows("unit_fam_d", 10,
                               lambda pos, m: calls.append(pos),
                               max_window=10)
        assert calls == []      # a doomed dispatch is never burned


# ---------------------------------------------------------------------------
# chunked scoring parity (binomial + multinomial, NA paths)
# ---------------------------------------------------------------------------

def _train_frame(n=1200, seed=0, classes=2):
    rng = np.random.default_rng(seed)
    fr = Frame()
    x1 = rng.standard_normal(n)
    x2 = rng.standard_normal(n)
    g = np.array(["a", "b", "c"])[rng.integers(0, 3, n)]
    fr.add("x1", Column.from_numpy(x1))
    fr.add("x2", Column.from_numpy(x2))
    fr.add("g", Column.from_numpy(g, ctype="enum"))
    logit = 1.2 * x1 - x2 + (g == "a") * 0.5
    if classes == 2:
        y = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "Y", "N")
    else:
        y = np.array(["r", "s", "t"])[
            np.clip((logit + rng.normal(0, 0.5, n) + 1.5).astype(int), 0,
                    classes - 1)]
    fr.add("y", Column.from_numpy(y, ctype="enum"))
    return fr


def _score_frame(n, seed, with_nas=True, key=None):
    rng = np.random.default_rng(seed)
    fr = Frame(key=key)
    x1 = rng.standard_normal(n)
    x2 = rng.standard_normal(n)
    if with_nas:
        x1[::7] = np.nan
    fr.add("x1", Column.from_numpy(x1))
    fr.add("x2", Column.from_numpy(x2))
    fr.add("g", Column.from_numpy(
        np.array(["a", "b", "c"])[rng.integers(0, 3, n)], ctype="enum"))
    return fr


@pytest.fixture(scope="module")
def gbm2(cl):
    from h2o3_tpu.models.tree.gbm import GBM

    return GBM(ntrees=5, max_depth=3, seed=1).train(
        y="y", training_frame=_train_frame())


@pytest.fixture(scope="module")
def gbm3(cl):
    from h2o3_tpu.models.tree.gbm import GBM

    return GBM(ntrees=4, max_depth=3, seed=2).train(
        y="y", training_frame=_train_frame(seed=5, classes=3))


def _pred_arrays(ssn, fr):
    out = ssn.predict(fr)
    return [np.asarray(out.col(i).data)[:fr.nrows]
            for i in range(len(out.names))]


def _assert_preds_bitwise(a, b):
    assert len(a) == len(b)
    for i, (x, y) in enumerate(zip(a, b)):
        assert x.dtype == y.dtype
        assert np.array_equal(x, y, equal_nan=True), f"output col {i}"


class TestChunkedScoringParity:
    # {1-row windows, ragged tail, chunk == n (full plan untouched)}
    @pytest.mark.parametrize("n,chunk", [(23, 1), (37, 8), (64, 64)])
    def test_chunked_binomial_bitwise(self, cl, gbm2, monkeypatch, n,
                                      chunk):
        from h2o3_tpu import scoring
        from h2o3_tpu.core import sharded_frame

        ssn = scoring.session_for(gbm2)
        fr = _score_frame(n, seed=n)
        g0 = sharded_frame.counters()["gathered_rows"]
        base = _pred_arrays(ssn, fr)
        g_base = sharded_frame.counters()["gathered_rows"] - g0
        _force_chunk(monkeypatch, "scoring", chunk)
        c0 = stream.counters()
        g1 = sharded_frame.counters()["gathered_rows"]
        chunked = _pred_arrays(ssn, fr)
        c1 = stream.counters()
        _assert_preds_bitwise(base, chunked)
        # chunking must not ADD coordinator gathers over the baseline
        assert (sharded_frame.counters()["gathered_rows"] - g1) == g_base
        if chunk < n:
            assert c1["chunked_runs"] > c0["chunked_runs"]
            assert c1["windows"] - c0["windows"] > 1
        else:
            assert c1["chunked_runs"] == c0["chunked_runs"]

    def test_chunked_multinomial_bitwise(self, cl, gbm3, monkeypatch):
        from h2o3_tpu import scoring

        ssn = scoring.session_for(gbm3)
        fr = _score_frame(41, seed=17)
        base = _pred_arrays(ssn, fr)
        _force_chunk(monkeypatch, "scoring", 8)
        c0 = stream.counters()
        chunked = _pred_arrays(ssn, fr)
        assert stream.counters()["chunked_runs"] > c0["chunked_runs"]
        _assert_preds_bitwise(base, chunked)

    def test_env_budget_pins_chunked_scoring(self, cl, gbm2, monkeypatch):
        """The operator knob end-to-end: a frame far bigger than
        ``H2O_TPU_MEM_BUDGET_MB`` scores through row-chunk windows,
        bitwise-identical to the unbudgeted single dispatch."""
        from h2o3_tpu import scoring

        ssn = scoring.session_for(gbm2)
        fr = _score_frame(4096, seed=3)
        base = _pred_arrays(ssn, fr)
        monkeypatch.setenv("H2O_TPU_MEM_BUDGET_MB", "0.05")
        c0 = stream.counters()
        chunked = _pred_arrays(ssn, fr)
        c1 = stream.counters()
        _assert_preds_bitwise(base, chunked)
        assert c1["chunked_runs"] > c0["chunked_runs"]
        assert c1["windows"] - c0["windows"] > 1


# ---------------------------------------------------------------------------
# chunked rapids fused statements
# ---------------------------------------------------------------------------

@pytest.fixture()
def rfr(cl):
    rng = np.random.default_rng(23)
    f = Frame(key=RFR)
    a = rng.standard_normal(40)
    a[[3, 17, 29]] = np.nan
    f.add("a", Column.from_numpy(a))
    f.add("b", Column.from_numpy(rng.standard_normal(40)))
    c = rng.uniform(-2.0, 2.0, 40)
    c[7] = np.nan
    f.add("c", Column.from_numpy(c))
    f.install()
    yield f
    f.delete()


class TestChunkedRapidsParity:
    @pytest.mark.parametrize("chunk", [1, 17])
    def test_chunked_statements_bitwise(self, cl, rfr, monkeypatch, chunk):
        from h2o3_tpu.core import sharded_frame
        from h2o3_tpu.rapids import Session, exec_rapids, fusion

        stmts = (f"(+ (* (cols {RFR} [0]) 2) (cols {RFR} [1]))",
                 f"(ifelse (> (cols {RFR} [2]) 0) (cols {RFR} [0]) "
                 f"(sqrt (abs (cols {RFR} [1]))))",
                 f"(is.na (+ (cols {RFR} [0]) (cols {RFR} [2])))")
        s = Session("mem_rapids")
        try:
            base, eager = [], []
            for stmt in stmts:
                with fusion.force(True):
                    base.append(exec_rapids(stmt, s).col(0).to_numpy())
                with fusion.force(False):
                    eager.append(exec_rapids(stmt, s).col(0).to_numpy())
            _force_chunk(monkeypatch, "rapids", chunk)
            c0 = stream.counters()
            g0 = sharded_frame.counters()["gathered_rows"]
            for i, stmt in enumerate(stmts):
                with fusion.force(True):
                    got = exec_rapids(stmt, s).col(0).to_numpy()
                assert got.dtype == base[i].dtype
                assert np.array_equal(got, base[i], equal_nan=True), stmt
                assert np.array_equal(got, eager[i], equal_nan=True), stmt
            c1 = stream.counters()
            # fused statements stay on the sharded data plane when chunked
            assert sharded_frame.counters()["gathered_rows"] == g0
            assert c1["chunked_runs"] - c0["chunked_runs"] >= len(stmts)
        finally:
            s.end()


# ---------------------------------------------------------------------------
# chunked sharded bin pack (training input path)
# ---------------------------------------------------------------------------

class TestChunkedBinningParity:
    def test_chunked_bin_pack_bitwise(self, cl, monkeypatch):
        from h2o3_tpu.models.tree.binning import BinSpec

        rng = np.random.default_rng(31)
        fr = Frame(key="mem_bin_fr")
        x0 = rng.standard_normal(500)
        x0[::11] = np.nan
        fr.add("x0", Column.from_numpy(x0))
        fr.add("x1", Column.from_numpy(rng.standard_normal(500)))
        fr.add("g", Column.from_numpy(
            np.array(["u", "v", "w"])[rng.integers(0, 3, 500)],
            ctype="enum"))
        fr.install()
        try:
            spec = BinSpec.build(fr, list(fr.names))
            base = np.asarray(spec.bin_columns(fr))
            _force_chunk(monkeypatch, "binning", 64)
            c0 = stream.counters()
            chunked = np.asarray(spec.bin_columns(fr))
            c1 = stream.counters()
            assert base.dtype == chunked.dtype
            assert np.array_equal(base, chunked)
            assert c1["chunked_runs"] > c0["chunked_runs"]
            assert c1["windows"] - c0["windows"] > 1
        finally:
            fr.delete()


# ---------------------------------------------------------------------------
# chunked fused munge→score pipeline
# ---------------------------------------------------------------------------

class TestChunkedPipelineParity:
    def test_chunked_pipeline_bitwise(self, cl, monkeypatch):
        from h2o3_tpu import pipeline, scoring
        from h2o3_tpu.models.tree.gbm import GBM
        from h2o3_tpu.rapids import Session, exec_rapids, fusion, planner

        model = GBM(ntrees=3, max_depth=3, seed=4).train(
            y="y", training_frame=_train_frame(n=700, seed=3))
        with planner.force(True), fusion.force(True), pipeline.force(True):
            s = Session("mem_pl")
            rng = np.random.default_rng(41)
            raw = Frame(key="mem_pl_raw")
            r1 = rng.standard_normal(257)
            r1[::9] = np.nan
            raw.add("r1", Column.from_numpy(r1))
            raw.add("r2", Column.from_numpy(rng.standard_normal(257)))
            g = np.array(["a", "b", "c"])[rng.integers(0, 3, 257)]
            g[:3] = ["a", "b", "c"]
            raw.add("g", Column.from_numpy(g, ctype="enum"))
            raw.install()
            try:
                exec_rapids(
                    f'(tmp= mp_x1 (+ (cols {raw.key} [0]) 0.5))', s)
                exec_rapids(
                    f'(tmp= mp_x2 (ifelse (> (cols {raw.key} [1]) 0) '
                    f'(cols {raw.key} [1]) (cols {raw.key} [0])))', s)
                pf = exec_rapids(
                    f'(tmp= mp_pf (colnames= (cbind mp_x1 mp_x2 '
                    f'(cols {raw.key} [2])) [0 1 2] ["x1" "x2" "g"]))', s)
                ssn = scoring.session_for(model)
                base = _pred_arrays(ssn, pf)
                _force_chunk(monkeypatch, "pipeline", 32)
                c0 = stream.counters()
                p0 = pipeline.counters()
                chunked = _pred_arrays(ssn, pf)
                c1 = stream.counters()
                p1 = pipeline.counters()
                _assert_preds_bitwise(base, chunked)
                assert c1["chunked_runs"] > c0["chunked_runs"]
                assert c1["windows"] - c0["windows"] > 1
                # still the fused pipeline path, not a staged fallback
                assert p1["fused_dispatches"] > p0["fused_dispatches"]
            finally:
                s.end()
                raw.delete()


# ---------------------------------------------------------------------------
# spill tier: sha256 gate + shared bounded retry budget
# ---------------------------------------------------------------------------

class TestSpillChecksum:
    def _spilled_col(self, tmp_path, monkeypatch, name, n=1000):
        from h2o3_tpu import persist
        from h2o3_tpu.persist import spill

        monkeypatch.setattr(persist, "_CACHE_DIR", str(tmp_path))
        arr = np.arange(n, dtype=np.float32)
        arr[7] = np.nan
        col = Column.from_numpy(arr.copy())
        assert col.data is not None             # device resident
        freed = spill.spill_column(col, name)
        assert freed > 0
        assert col.is_evicted and callable(col._evicted)
        paths = [os.path.join(spill.spill_dir(), f)
                 for f in os.listdir(spill.spill_dir())
                 if f.startswith(name + "_")]
        assert len(paths) == 1
        return col, arr, paths[0]

    def test_spill_reload_roundtrip_bitwise(self, cl, tmp_path,
                                            monkeypatch):
        col, arr, _ = self._spilled_col(tmp_path, monkeypatch, "rt")
        got = np.asarray(col.data)[:len(arr)]
        assert got.dtype == arr.dtype
        assert np.array_equal(got, arr, equal_nan=True)

    def test_corrupt_spill_fails_checksum_gate(self, cl, tmp_path,
                                               monkeypatch):
        from h2o3_tpu.persist import spill

        col, arr, path = self._spilled_col(tmp_path, monkeypatch, "corrupt")
        with open(path, "r+b") as f:            # bit rot mid-buffer
            f.seek(os.path.getsize(path) // 2)
            f.write(b"\xde\xad\xbe\xef")
        with pytest.raises(spill.SpillCorrupt):
            col.data

    @pytest.mark.chaos
    def test_missing_spill_retries_bounded_then_raises(self, cl, tmp_path,
                                                       monkeypatch):
        from h2o3_tpu.persist import spill

        col, arr, path = self._spilled_col(tmp_path, monkeypatch, "gone")
        os.remove(path)
        c0 = stream.counters()["spill_retries"]
        with pytest.raises(spill.SpillCorrupt):
            col.data
        # the read walked the SAME bounded budget as DKV blob fetches
        assert stream.counters()["spill_retries"] - c0 >= 1


# ---------------------------------------------------------------------------
# admission shed under pressure
# ---------------------------------------------------------------------------

class TestAdmissionShed:
    def test_pressure_sheds_503_with_retry_after(self, cl, monkeypatch):
        from h2o3_tpu.admission import AdmissionController, AdmissionRejected

        monkeypatch.setenv("H2O_TPU_MEM_PRESSURE_COOLDOWN_S", "30")
        ctl = AdmissionController()
        budget.note_pressure()
        with pytest.raises(AdmissionRejected) as ei:
            with ctl.slot("m"):
                pass
        assert ei.value.status == 503
        assert ei.value.retry_after_s >= 1.0
        with pytest.raises(AdmissionRejected):
            ctl.check("m")
        assert ctl.snapshot()["shed_mem"] == 2
        # cooldown lapse (reset): the same controller admits again
        budget.reset_pressure()
        with ctl.slot("m"):
            pass
        assert ctl.snapshot()["shed_mem"] == 2


# ---------------------------------------------------------------------------
# REST surface: zero-5xx recovery, clean 503 when the ladder exhausts
# ---------------------------------------------------------------------------

@pytest.mark.chaos
class TestRestMemoryPressure:
    def _post(self, url, timeout=120):
        req = urllib.request.Request(url, data=b"", method="POST")
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read())

    def test_rest_oom_recovery_and_exhaustion(self, cl, gbm2):
        from h2o3_tpu.api.server import start_server

        test = _score_frame(40, seed=71, key="mem_rest_fr")
        test.install()
        srv = start_server(port=0)
        try:
            url = (f"http://127.0.0.1:{srv.port}/3/Predictions/models/"
                   f"{gbm2.key}/frames/{test.key}")
            assert self._post(url)              # warm, clean baseline

            # two injected OOMs: the ladder absorbs both inside the
            # bounded retry budget — the client sees 200, not 5xx
            c0 = stream.counters()
            with failure.inject("mem.exhausted", times=2):
                assert self._post(url)
            c1 = stream.counters()
            assert c1["ladder_recoveries"] - c0["ladder_recoveries"] >= 1

            # exhausted ladder: typed 503 + Retry-After + flight record
            f0 = _mem_flights()
            with failure.inject("mem.exhausted", times=256):
                with pytest.raises(urllib.error.HTTPError) as ei:
                    self._post(url)
            assert ei.value.code == 503
            assert int(ei.value.headers["Retry-After"]) >= 1
            assert _mem_flights() - f0 >= 1
            assert budget.pressure_active()

            # pressure flagged: admission sheds the NEXT request as 503
            # + Retry-After without burning a dispatch
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._post(url)
            assert ei.value.code == 503
            assert int(ei.value.headers["Retry-After"]) >= 1

            # cooldown lapses → the same server serves again
            budget.reset_pressure()
            assert self._post(url)
        finally:
            srv.stop()
            test.delete()


# ---------------------------------------------------------------------------
# consistency guard: budgeted families feed the planner's estimates
# ---------------------------------------------------------------------------

class TestConsistencyGuard:
    def test_budgeted_families_are_ledgered_families(self):
        from h2o3_tpu.obs import compiles

        assert set(budget.BUDGETED_FAMILIES) <= set(compiles.FAMILIES)

    def test_dispatched_families_record_row_bytes(self, cl, gbm2, rfr):
        """Every budgeted family that dispatched records a non-null HBM
        bytes/row estimate through note_compiled — the planner never
        plans a dispatched family blind."""
        from h2o3_tpu import scoring
        from h2o3_tpu.rapids import Session, exec_rapids, fusion

        scoring.session_for(gbm2).predict(_score_frame(19, seed=1))
        s = Session("mem_guard")
        try:
            with fusion.force(True):
                exec_rapids(f"(+ (cols {RFR} [0]) 1)", s)
        finally:
            s.end()
        est = budget.snapshot()["row_bytes_estimates"]
        for fam in ("scoring", "rapids"):
            assert est.get(fam, 0) > 0, f"{fam} never fed note_compiled"
