"""Crash-survivable training jobs (ISSUE 5 tentpole): iterative trainers
persist durable per-iteration progress (`H2O_TPU_JOB_CKPT_ITERS` through
parallel/ckpt.py's job-progress store) and a re-dispatched build
fast-forwards from it. The tree path's continuation must be
BITWISE-identical to an uninterrupted train — margins, packed per-tree
tables and the host RNG stream are restored exactly; GLM/KMeans/DL resume
their exact chunk/epoch trajectories.
"""

import numpy as np
import pytest

from h2o3_tpu.core.frame import Column, Frame
from h2o3_tpu.core.job import Job
from h2o3_tpu.models.model_builder import ModelBuilder
from h2o3_tpu.parallel import ckpt


class _Interrupted(Exception):
    """Stands in for the process dying mid-train."""


@pytest.fixture()
def jobckpt(monkeypatch, tmp_path):
    """Durable job progress every 2 iterations into a temp checkpoint dir."""
    monkeypatch.setenv("H2O_TPU_JOB_CKPT_ITERS", "2")
    monkeypatch.setenv("H2O_TPU_OPLOG_CKPT_DIR", str(tmp_path))
    return 2


def _train_frame(n=260, classes=0, seed=7):
    rng = np.random.default_rng(seed)
    fr = Frame()
    x1 = rng.standard_normal(n)
    x2 = rng.standard_normal(n)
    fr.add("x1", Column.from_numpy(x1))
    fr.add("x2", Column.from_numpy(x2))
    raw = x1 - 0.5 * x2 + 0.3 * rng.standard_normal(n)
    if classes == 2:
        fr.add("y", Column.from_numpy(np.where(raw > 0, "Y", "N"),
                                      ctype="enum"))
    elif classes > 2:
        labs = np.array([f"c{i}" for i in range(classes)])
        fr.add("y", Column.from_numpy(
            labs[np.clip(np.digitize(raw, [-0.5, 0.5]), 0, classes - 1)],
            ctype="enum"))
    else:
        fr.add("y", Column.from_numpy(raw))
    return fr


def _score_frame(n=64, seed=8):
    rng = np.random.default_rng(seed)
    fr = Frame()
    fr.add("x1", Column.from_numpy(rng.standard_normal(n)))
    fr.add("x2", Column.from_numpy(rng.standard_normal(n)))
    return fr


def _attach_progress_job(builder, fr):
    job = Job(description=f"{builder.algo_name} train")
    job.resume_spec = {"algo": builder.algo_name, "params": {},
                       "training_frame": str(fr.key), "y": "y"}
    builder._progress_job = job
    return job


def _interrupt_after(monkeypatch, at_iter):
    """Kill the build right after the durable save at `at_iter`. Returns a
    callable that removes ONLY this patch (monkeypatch.undo would also
    strip the jobckpt env the resumed run still needs)."""
    orig = ModelBuilder._tick_job_progress

    def tick_boom(self, done, fn):
        orig(self, done, fn)
        if done >= at_iter:
            raise _Interrupted()

    monkeypatch.setattr(ModelBuilder, "_tick_job_progress", tick_boom)
    return lambda: monkeypatch.setattr(ModelBuilder, "_tick_job_progress",
                                       orig)


def _preds(model, score):
    p = model.predict(score)
    return {c: np.asarray(p.col(c).data).copy() for c in p.names}


def _assert_same(a, b, exact=True):
    assert set(a) == set(b)
    for c in a:
        if exact:
            assert np.array_equal(a[c], b[c]), c
        else:
            np.testing.assert_allclose(a[c], b[c], rtol=1e-6, atol=1e-7, err_msg=c)


def _interrupt_resume_roundtrip(cl, monkeypatch, builder_cls, params, fr,
                                at_iter=4):
    """Interrupt a durable-progress build at `at_iter`, assert the file is
    there, resume a fresh builder from it; returns the resumed model."""
    b1 = builder_cls(**params)
    job = _attach_progress_job(b1, fr)
    unpatch = _interrupt_after(monkeypatch, at_iter)
    with pytest.raises(_Interrupted):
        b1.train(y="y", training_frame=fr)
    unpatch()
    assert b1.job.status == Job.FAILED        # worker-side verdict recorded
    data = ckpt.load_job_progress(str(job.key))
    assert data is not None
    assert data["iteration"] == at_iter
    assert data["spec"]["algo"] == builder_cls.algo_name
    b2 = builder_cls(**params)
    b2._resume_state = data["state"]
    return b2.train(y="y", training_frame=fr)


class TestTreeResumeBitwise:
    def test_gbm_binomial_resume_is_bitwise_identical(self, cl, monkeypatch,
                                                      jobckpt):
        from h2o3_tpu.models.tree.gbm import GBM

        fr = _train_frame(classes=2)
        score = _score_frame()
        params = dict(ntrees=8, max_depth=3, seed=11)
        base = _preds(GBM(**params).train(y="y", training_frame=fr), score)
        m2 = _interrupt_resume_roundtrip(cl, monkeypatch, GBM, params, fr)
        _assert_same(base, _preds(m2, score))
        # the resumed model's history covers the FULL run, not the suffix
        assert m2._output.scoring_history[-1]["tree"] == 8

    def test_gbm_multinomial_resume_is_bitwise_identical(self, cl,
                                                         monkeypatch,
                                                         jobckpt):
        from h2o3_tpu.models.tree.gbm import GBM

        fr = _train_frame(classes=3)
        score = _score_frame()
        params = dict(ntrees=6, max_depth=3, seed=12)
        base = _preds(GBM(**params).train(y="y", training_frame=fr), score)
        m2 = _interrupt_resume_roundtrip(cl, monkeypatch, GBM, params, fr,
                                         at_iter=2)
        _assert_same(base, _preds(m2, score))

    def test_drf_resume_restores_rng_and_oob_bitwise(self, cl, monkeypatch,
                                                     jobckpt):
        """DRF consumes host RNG per node (mtries masks) and device
        sampling per tree: the restored bit-generator state + OOB
        accumulators must reproduce the uninterrupted forest exactly,
        including the OOB training metrics."""
        from h2o3_tpu.models.tree.drf import DRF

        fr = _train_frame(classes=2, seed=9)
        score = _score_frame()
        params = dict(ntrees=8, max_depth=4, seed=13)
        m0 = DRF(**params).train(y="y", training_frame=fr)
        base = _preds(m0, score)
        m2 = _interrupt_resume_roundtrip(cl, monkeypatch, DRF, params, fr)
        _assert_same(base, _preds(m2, score))
        assert np.isclose(m0._output.training_metrics.auc,
                          m2._output.training_metrics.auc)


class TestIterativeResume:
    def test_glm_chunked_irls_resume_matches_uninterrupted(self, cl,
                                                           monkeypatch,
                                                           jobckpt):
        from h2o3_tpu.models.glm import GLM

        # binomial: logistic Newton steps genuinely iterate (gaussian IRLS
        # solves in one step and would finish before the interrupt point);
        # the tight beta_epsilon keeps every run walking the same chunk
        # boundaries, so betas must agree exactly
        fr = _train_frame(classes=2)
        params = dict(family="binomial", max_iterations=8,
                      beta_epsilon=1e-12, seed=3)
        b0 = GLM(**params)
        _attach_progress_job(b0, fr)
        m0 = b0.train(y="y", training_frame=fr)
        m2 = _interrupt_resume_roundtrip(cl, monkeypatch, GLM, params, fr)
        assert np.array_equal(np.asarray(m0.beta), np.asarray(m2.beta))
        assert m0.iterations == m2.iterations

    def test_kmeans_chunked_lloyd_resume_matches_uninterrupted(
            self, cl, monkeypatch, jobckpt):
        from h2o3_tpu.models.kmeans import KMeans

        fr = _train_frame()
        params = dict(k=3, max_iterations=8, seed=5,
                      ignored_columns=["y"])
        b0 = KMeans(**params)
        _attach_progress_job(b0, fr)
        m0 = b0.train(training_frame=fr)

        b1 = KMeans(**params)
        job = _attach_progress_job(b1, fr)
        unpatch = _interrupt_after(monkeypatch, 4)
        with pytest.raises(_Interrupted):
            b1.train(training_frame=fr)
        unpatch()
        data = ckpt.load_job_progress(str(job.key))
        assert data is not None and data["iteration"] >= 2
        b2 = KMeans(**params)
        b2._resume_state = data["state"]
        m2 = b2.train(training_frame=fr)
        np.testing.assert_allclose(np.sort(m0.centers, axis=0),
                                   np.sort(m2.centers, axis=0),
                                   rtol=1e-6, atol=1e-6)

    def test_deeplearning_epoch_resume_matches_uninterrupted(
            self, cl, monkeypatch, jobckpt):
        from h2o3_tpu.models.deeplearning import DeepLearning

        pytest.importorskip("optax")
        fr = _train_frame(classes=2)
        score = _score_frame()
        params = dict(hidden=[5], epochs=4, seed=21, mini_batch_size=32,
                      variable_importances=False)
        base = _preds(DeepLearning(**params).train(y="y", training_frame=fr),
                      score)
        m2 = _interrupt_resume_roundtrip(cl, monkeypatch, DeepLearning,
                                         params, fr, at_iter=2)
        _assert_same(base, _preds(m2, score), exact=False)
        assert m2.epochs_trained == 4


class TestProgressStoreMechanics:
    def test_no_progress_without_resume_spec_or_env(self, cl, monkeypatch,
                                                    tmp_path):
        """Library-mode training (no REST job / knob off) persists nothing
        — the hot path stays cost-free."""
        from h2o3_tpu.models.tree.gbm import GBM

        monkeypatch.setenv("H2O_TPU_OPLOG_CKPT_DIR", str(tmp_path))
        monkeypatch.setenv("H2O_TPU_JOB_CKPT_ITERS", "2")
        fr = _train_frame(classes=2)
        GBM(ntrees=4, max_depth=2, seed=1).train(y="y", training_frame=fr)
        assert not list(tmp_path.glob("jobckpt_*.pkl"))
        monkeypatch.setenv("H2O_TPU_JOB_CKPT_ITERS", "0")
        b = GBM(ntrees=4, max_depth=2, seed=1)
        _attach_progress_job(b, fr)
        b.train(y="y", training_frame=fr)
        assert not list(tmp_path.glob("jobckpt_*.pkl"))

    def test_completed_build_gcs_its_progress(self, cl, monkeypatch,
                                              jobckpt, tmp_path):
        from h2o3_tpu.models.tree.gbm import GBM

        fr = _train_frame(classes=2)
        b = GBM(ntrees=4, max_depth=2, seed=1)
        job = _attach_progress_job(b, fr)
        b.train(y="y", training_frame=fr)
        # ticks fired mid-train, but success deleted the file + record
        assert ckpt.load_job_progress(str(job.key)) is None
        assert not list(tmp_path.glob("jobckpt_*.pkl"))

    def test_external_fail_racing_completion_keeps_progress(self, cl,
                                                            monkeypatch,
                                                            jobckpt):
        """The supervisor fails the cloud while the train is finishing:
        complete() loses the verdict race, and the durable progress must
        SURVIVE — it is exactly what the watchdog needs to resume the job
        (an unconditional clear would kill the feature in the one race it
        exists for)."""
        from h2o3_tpu.models.tree.gbm import GBM

        fr = _train_frame(classes=2)
        b = GBM(ntrees=4, max_depth=2, seed=1)
        job = _attach_progress_job(b, fr)
        orig = ModelBuilder._tick_job_progress

        def tick_then_cloud_dies(self, done, fn):
            orig(self, done, fn)
            if done >= 4:                 # the supervisor's external verdict
                self.job.fail("cloud FAILED while the build was finishing")
                job.fail("cloud FAILED while the build was finishing")

        monkeypatch.setattr(ModelBuilder, "_tick_job_progress",
                            tick_then_cloud_dies)
        b.train(y="y", training_frame=fr)
        monkeypatch.setattr(ModelBuilder, "_tick_job_progress", orig)
        assert b.job.status == Job.FAILED and b.job.failed_externally
        data = ckpt.load_job_progress(str(job.key))
        assert data is not None and data["iteration"] == 4

    def test_progress_save_failure_does_not_fail_the_build(self, cl,
                                                           monkeypatch,
                                                           jobckpt):
        from h2o3_tpu.models.tree.gbm import GBM

        fr = _train_frame(classes=2)
        b = GBM(ntrees=4, max_depth=2, seed=1)
        _attach_progress_job(b, fr)
        monkeypatch.setattr(ckpt, "save_job_progress",
                            lambda *a, **k: 1 / 0)
        m = b.train(y="y", training_frame=fr)   # durability is best-effort
        assert m is not None
