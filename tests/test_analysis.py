"""Static-analyzer suite (ISSUE 11): per-pass fixtures (one positive +
one near-miss negative each), baseline mechanics, and the regression
fixtures for the REAL defects the analyzer surfaced in this repo:

- the REST train and grid handlers broadcast ``max_runtime_secs`` in the
  op payload — each process measures its own wall clock, so mirrored fit
  loops would stop at DIFFERENT iterations (desynced device collectives);
  both handlers now clear it like the AutoML handler always did;
- ``Model.load`` / ``H2OAssembly.load`` / the DKV blob fetch raw-
  unpickled external bytes — all three now refuse non-framework types
  through the shared restricted unpickler (utils/unpickle.py).

Fixture snippets are tiny synthetic projects under tmp_path; the
analyzer's faultpoint scan excludes this file by registry declaration
(the snippets deliberately contain armed-looking text).
"""

import base64
import json
import pickle
import struct
import textwrap
import types
from pathlib import Path

import pytest

from h2o3_tpu import analysis
from h2o3_tpu.analysis import core as acore

pytestmark = pytest.mark.analysis

REPO = Path(__file__).resolve().parent.parent


def mini_ctx(tmp_path, files, **reg):
    """Context over a synthetic project tree with a stand-in registry."""
    (tmp_path / "h2o3_tpu").mkdir(parents=True, exist_ok=True)
    (tmp_path / "h2o3_tpu" / "__init__.py").write_text("")
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    defaults = dict(
        MIRRORED_ROOTS=(), KNOB_HELPERS=frozenset(), GUARDED={},
        HOST_SIDE_MODULES={}, LOCK_SCOPE=("h2o3_tpu/",), LOCK_ORDER=(),
        PICKLE_ALLOWED=(), COMPAT_MODULE="h2o3_tpu/compat.py",
        DEVICE_ONLY_APIS={"jax.experimental.pallas": "tpu-only",
                          "jax.profiler": "version-mobile"},
        SWALLOW_SCOPE=(), FAULTPOINT_SCAN_EXCLUDE=())
    defaults.update(reg)
    return acore.make_context(tmp_path,
                              registry=types.SimpleNamespace(**defaults))


def run_pass(ctx, name):
    return analysis.run(ctx, [name])


# ---------------------------------------------------------------------------
# mirrored-program pass
# ---------------------------------------------------------------------------

class TestMirroredPass:
    def test_wallclock_in_control_flow_flagged_metadata_not(self, tmp_path):
        ctx = mini_ctx(tmp_path, {"h2o3_tpu/work.py": """
            import time

            def handler(p):
                helper()
                meta()

            def helper():
                deadline = time.time() + 5
                while time.time() < deadline:
                    pass

            def meta():
                t0 = time.time()
                return time.time() - t0

            def unreachable():
                if time.time() > 0:
                    pass
        """}, MIRRORED_ROOTS=("h2o3_tpu.work.handler",))
        got = run_pass(ctx, "mirrored")
        syms = {f.symbol for f in got}
        assert any("helper" in s for s in syms), got
        # near-misses: wall-clock as pure metadata; divergence outside the
        # reachable closure
        assert not any("meta" in s for s in syms)
        assert not any("unreachable" in s for s in syms)

    def test_fresh_prng_flagged_seeded_rng_not(self, tmp_path):
        ctx = mini_ctx(tmp_path, {"h2o3_tpu/work.py": """
            import numpy as np

            def handler(p):
                bad = np.random.default_rng()
                ok = np.random.default_rng(42)
                import jax
                k2 = jax.random.split(p["key"])   # functional: key-driven
                return bad, ok, k2
        """}, MIRRORED_ROOTS=("h2o3_tpu.work.handler",))
        got = run_pass(ctx, "mirrored")
        assert len(got) == 1 and "default_rng" in got[0].message, got

    def test_raw_env_flagged_knob_helper_exempt(self, tmp_path):
        files = {"h2o3_tpu/work.py": """
            import os

            def handler(p):
                if os.environ.get("H2O_TPU_X"):
                    return 1
                if knob():
                    return 2

            def knob():
                v = os.environ.get("H2O_TPU_X")
                if v is None:
                    return 0
                return int(v)
        """}
        ctx = mini_ctx(tmp_path, files,
                       MIRRORED_ROOTS=("h2o3_tpu.work.handler",),
                       KNOB_HELPERS=frozenset({"h2o3_tpu.work.knob"}))
        got = run_pass(ctx, "mirrored")
        assert len(got) == 1 and "handler" in got[0].symbol, got

    def test_guarded_and_host_side_suppress(self, tmp_path):
        files = {"h2o3_tpu/work.py": """
            import time
            from h2o3_tpu import hostmod

            def handler(p):
                audited()
                hostmod.hosty()

            def audited():
                if time.time() > 1:
                    pass
        """, "h2o3_tpu/hostmod.py": """
            import time

            def hosty():
                if time.time() > 1:
                    pass
        """}
        ctx = mini_ctx(tmp_path, files,
                       MIRRORED_ROOTS=("h2o3_tpu.work.handler",),
                       GUARDED={"h2o3_tpu.work.audited": "audited: safe"},
                       HOST_SIDE_MODULES={"h2o3_tpu/hostmod.py": "host"})
        assert run_pass(ctx, "mirrored") == []


# ---------------------------------------------------------------------------
# lock-order pass
# ---------------------------------------------------------------------------

class TestLockOrderPass:
    def test_ab_ba_cycle_reported(self, tmp_path):
        ctx = mini_ctx(tmp_path, {"h2o3_tpu/locks.py": """
            import threading
            A = threading.Lock()
            B = threading.Lock()

            def ab():
                with A:
                    with B:
                        pass

            def ba():
                with B:
                    with A:
                        pass
        """})
        got = run_pass(ctx, "lock-order")
        assert any("cycle" in f.message for f in got), got

    def test_consistent_order_clean(self, tmp_path):
        ctx = mini_ctx(tmp_path, {"h2o3_tpu/locks.py": """
            import threading
            A = threading.Lock()
            B = threading.Lock()

            def ab():
                with A:
                    with B:
                        pass

            def ab2():
                with A:
                    with B:
                        pass
        """})
        assert run_pass(ctx, "lock-order") == []

    def test_interprocedural_nesting_seen(self, tmp_path):
        """with A: f() where f takes B, plus the direct B->A nesting,
        closes the AB/BA cycle through the call graph."""
        ctx = mini_ctx(tmp_path, {"h2o3_tpu/locks.py": """
            import threading
            A = threading.Lock()
            B = threading.Lock()

            def outer():
                with A:
                    inner()

            def inner():
                with B:
                    pass

            def reversed_path():
                with B:
                    with A:
                        pass
        """})
        got = run_pass(ctx, "lock-order")
        assert any("cycle" in f.message for f in got), got

    def test_declared_order_reversal(self, tmp_path):
        ctx = mini_ctx(tmp_path, {"h2o3_tpu/locks.py": """
            import threading
            A = threading.Lock()
            B = threading.Lock()

            def ba():
                with B:
                    with A:
                        pass
        """}, LOCK_ORDER=(("locks.A", "locks.B"),))
        got = run_pass(ctx, "lock-order")
        assert any("reversed" in f.message for f in got), got

    def test_nonreentrant_self_nesting(self, tmp_path):
        ctx = mini_ctx(tmp_path, {"h2o3_tpu/locks.py": """
            import threading
            A = threading.Lock()
            R = threading.RLock()

            def bad():
                with A:
                    with A:
                        pass

            def fine():
                with R:
                    with R:
                        pass
        """})
        got = run_pass(ctx, "lock-order")
        assert len(got) == 1 and "self-deadlock" in got[0].message, got


# ---------------------------------------------------------------------------
# serialization pass
# ---------------------------------------------------------------------------

class TestSerializationPass:
    SRC = {"h2o3_tpu/io2.py": """
        import pickle
        import numpy as np

        def bad(f):
            return pickle.load(f)

        def bad2(path):
            return np.load(path, allow_pickle=True)

        def fine(path):
            return np.load(path, allow_pickle=False)
    """}

    def test_raw_loads_flagged(self, tmp_path):
        got = run_pass(mini_ctx(tmp_path, self.SRC), "serialization")
        msgs = " ".join(f.message for f in got)
        assert len(got) == 2 and "pickle.load" in msgs and \
            "allow_pickle" in msgs, got

    def test_no_module_escapes_the_raw_load_ban(self, tmp_path):
        """PICKLE_ALLOWED bounds Unpickler DEFINITIONS — it never exempts
        a raw load (review finding: an allowlist hole would silently
        reopen the artifact-ingest pickle door)."""
        ctx = mini_ctx(tmp_path, self.SRC,
                       PICKLE_ALLOWED=("h2o3_tpu/io2.py",))
        got = run_pass(ctx, "serialization")
        assert any("pickle.load" in f.message for f in got), got

    def test_bare_reference_default_is_flagged(self, tmp_path):
        """`loads = loads or pickle.loads` — a non-call reference is the
        same RCE door (review finding: the dkv restore default)."""
        ctx = mini_ctx(tmp_path, {"h2o3_tpu/io3.py": """
            import pickle

            def restore(blob, loads=None):
                loads = loads or pickle.loads
                return loads(blob)
        """})
        got = run_pass(ctx, "serialization")
        assert len(got) == 1 and "pickle.loads" in got[0].message, got

    def test_unpickler_subclass_outside_sanctioned_home(self, tmp_path):
        files = {"h2o3_tpu/fork.py": """
            import pickle

            class MyUnpickler(pickle.Unpickler):
                def find_class(self, module, name):
                    return super().find_class(module, name)
        """}
        got = run_pass(mini_ctx(tmp_path, files), "serialization")
        assert len(got) == 1 and "Unpickler subclass" in got[0].message
        ctx = mini_ctx(tmp_path, files,
                       PICKLE_ALLOWED=("h2o3_tpu/fork.py",))
        assert run_pass(ctx, "serialization") == []


# ---------------------------------------------------------------------------
# compat-routing pass
# ---------------------------------------------------------------------------

class TestCompatPass:
    def test_direct_device_api_flagged(self, tmp_path):
        ctx = mini_ctx(tmp_path, {"h2o3_tpu/kern.py": """
            import jax
            from jax.experimental import pallas as pl

            def cap(d):
                jax.profiler.start_trace(d)
        """})
        got = run_pass(ctx, "compat-routing")
        apis = " ".join(f.message for f in got)
        assert "pallas" in apis and "jax.profiler" in apis, got

    def test_compat_module_itself_exempt(self, tmp_path):
        ctx = mini_ctx(tmp_path, {"h2o3_tpu/compat.py": """
            def pallas_modules():
                from jax.experimental import pallas as pl
                return pl

            def profiler_start(d):
                import jax
                jax.profiler.start_trace(d)
        """})
        assert run_pass(ctx, "compat-routing") == []


# ---------------------------------------------------------------------------
# sync-hygiene pass
# ---------------------------------------------------------------------------

class TestSyncHygienePass:
    def test_sync_inside_span_flagged_outside_not(self, tmp_path):
        ctx = mini_ctx(tmp_path, {"h2o3_tpu/hot.py": """
            import numpy as np
            from h2o3_tpu.obs import tracing

            def instrumented(out):
                with tracing.span("dispatch"):
                    got = np.asarray(out)
                return got

            def plain(out):
                return np.asarray(out)
        """})
        got = run_pass(ctx, "sync-hygiene")
        assert len(got) == 1 and "numpy.asarray" in got[0].message, got

    def test_block_until_ready_in_span(self, tmp_path):
        ctx = mini_ctx(tmp_path, {"h2o3_tpu/hot.py": """
            from h2o3_tpu.obs import tracing

            def instrumented(out):
                with tracing.span("dispatch"):
                    out.block_until_ready()
        """})
        got = run_pass(ctx, "sync-hygiene")
        assert len(got) == 1 and "block_until_ready" in got[0].message

    def test_swallowed_exception_in_tick_scope(self, tmp_path):
        files = {"h2o3_tpu/wd.py": """
            def tick():
                try:
                    work()
                except Exception:
                    pass

            def logged():
                try:
                    work()
                except Exception as e:
                    log(e)
        """}
        ctx = mini_ctx(tmp_path, files, SWALLOW_SCOPE=("h2o3_tpu/wd.py",))
        got = run_pass(ctx, "sync-hygiene")
        assert len(got) == 1 and "swallowed" in got[0].message, got
        # same file outside the declared scope: clean
        ctx2 = mini_ctx(tmp_path, files, SWALLOW_SCOPE=())
        assert run_pass(ctx2, "sync-hygiene") == []


# ---------------------------------------------------------------------------
# registry passes (folded consistency guards)
# ---------------------------------------------------------------------------

class TestCompileLedgerPass:
    """ISSUE-12 chokepoint invariant: no module outside obs/compiles.py
    may run `.lower(...).compile(`, call `compile_stablehlo`, or write
    the legacy `note_compile` counter directly."""

    def test_chained_lower_compile_flagged(self, tmp_path):
        ctx = mini_ctx(tmp_path, {"h2o3_tpu/work.py": """
            def f(jfn, args):
                return jfn.lower(*args).compile()
            """}, COMPILE_LEDGER_MODULES=())
        got = run_pass(ctx, "compile-ledger")
        assert len(got) == 1 and "obs/compiles.py" in got[0].message, got

    def test_two_step_lowered_name_flagged(self, tmp_path):
        ctx = mini_ctx(tmp_path, {"h2o3_tpu/work.py": """
            def f(jfn, x):
                lowered = jfn.lower(x)
                text = lowered.as_text()
                return lowered.compile(), text
            """}, COMPILE_LEDGER_MODULES=())
        got = run_pass(ctx, "compile-ledger")
        assert len(got) == 1 and "ledger" in got[0].message, got

    def test_attribute_target_two_step_flagged(self, tmp_path):
        """A lowering cached on an attribute must not evade the ban."""
        ctx = mini_ctx(tmp_path, {"h2o3_tpu/work.py": """
            class C:
                def prep(self, jfn, x):
                    self._lowered = jfn.lower(x)

                def go(self):
                    return self._lowered.compile()
            """}, COMPILE_LEDGER_MODULES=())
        got = run_pass(ctx, "compile-ledger")
        assert len(got) == 1 and "ledger" in got[0].message, got

    def test_stablehlo_and_note_compile_flagged(self, tmp_path):
        ctx = mini_ctx(tmp_path, {"h2o3_tpu/work.py": """
            from h2o3_tpu import compat
            from h2o3_tpu.artifact import compile_cache

            def f(text, ms):
                compile_cache.note_compile(ms)
                return compat.compile_stablehlo(text)
            """}, COMPILE_LEDGER_MODULES=())
        got = run_pass(ctx, "compile-ledger")
        msgs = " ".join(f.message for f in got)
        assert len(got) == 2, got
        assert "compile_stablehlo" in msgs and "note_compile" in msgs

    def test_blessed_ledger_wrapper_not_flagged(self, tmp_path):
        """The remediation the finding recommends — calling the ledger's
        own compile_stablehlo(family, text) — must itself be clean."""
        ctx = mini_ctx(tmp_path, {"h2o3_tpu/work.py": """
            from h2o3_tpu.obs import compiles

            def f(text):
                return compiles.compile_stablehlo("scoring", text)
            """}, COMPILE_LEDGER_MODULES=())
        assert run_pass(ctx, "compile-ledger") == []

    def test_chokepoint_and_genmodel_exempt_string_lower_not_flagged(
            self, tmp_path):
        ctx = mini_ctx(tmp_path, {
            # the ledger itself may compile
            "h2o3_tpu/obs/compiles.py": """
                def compile_jit(family, jfn, args):
                    return jfn.lower(*args).compile()
                """,
            # framework-free standalone runner: raw client is its contract
            "h2o3_genmodel/aot.py": """
                def load(client, text):
                    return client.compile(text)
                """,
            # str.lower() + re.compile near-misses must stay clean
            "h2o3_tpu/clean.py": """
                import re

                def g(name, pat):
                    low = name.lower()
                    return re.compile(pat), low
                """,
        }, COMPILE_LEDGER_MODULES=("h2o3_tpu/obs/compiles.py",))
        assert run_pass(ctx, "compile-ledger") == []

    def test_stale_chokepoint_registry_path_is_a_finding(self, tmp_path):
        ctx = mini_ctx(tmp_path, {"h2o3_tpu/clean.py": "x = 1\n"},
                       COMPILE_LEDGER_MODULES=("h2o3_tpu/obs/gone.py",))
        got = run_pass(ctx, "compile-ledger")
        assert len(got) == 1 and "stale registry path" in got[0].message

    def test_bare_jit_banned_inside_ledgered_scope(self, tmp_path):
        """ISSUE-17: inside a JIT_LEDGER_SCOPE prefix every jit must go
        through obs/compiles.ledgered_jit — a bare jax.jit (decorator,
        call, or `from jax import jit` alias) bypasses the `tree`
        family ledger. All three spellings must be flagged."""
        ctx = mini_ctx(tmp_path, {"h2o3_tpu/models/tree/work.py": """
            import jax
            from jax import jit

            @jax.jit
            def deco(x):
                return x + 1

            def call(fn):
                return jax.jit(fn)

            def aliased(fn):
                return jit(fn)
            """}, COMPILE_LEDGER_MODULES=(),
            JIT_LEDGER_SCOPE=("h2o3_tpu/models/tree/",))
        got = run_pass(ctx, "compile-ledger")
        assert len(got) == 3, got
        assert all("ledgered_jit" in f.message for f in got), got

    def test_ledgered_jit_and_out_of_scope_jit_not_flagged(self, tmp_path):
        ctx = mini_ctx(tmp_path, {
            # in scope, but routed through the ledger: clean
            "h2o3_tpu/models/tree/good.py": """
                from h2o3_tpu.obs import compiles

                def build(fn):
                    return compiles.ledgered_jit("tree", fn, program="p")
                """,
            # bare jit OUTSIDE the scope prefix: not this pass's business
            "h2o3_tpu/elsewhere.py": """
                import jax

                @jax.jit
                def f(x):
                    return x * 2
                """,
        }, COMPILE_LEDGER_MODULES=(),
            JIT_LEDGER_SCOPE=("h2o3_tpu/models/tree/",))
        assert run_pass(ctx, "compile-ledger") == []

    def test_stale_jit_scope_prefix_is_a_finding(self, tmp_path):
        ctx = mini_ctx(tmp_path, {"h2o3_tpu/clean.py": "x = 1\n"},
                       COMPILE_LEDGER_MODULES=(),
                       JIT_LEDGER_SCOPE=("h2o3_tpu/models/gone/",))
        got = run_pass(ctx, "compile-ledger")
        assert len(got) == 1 and "stale registry path" in got[0].message


class TestRegistryPasses:
    def test_faultpoint_drift(self, tmp_path):
        files = {
            "h2o3_tpu/faults.py": 'def f():\n    faultpoint("real.point")\n',
            "tests/test_x.py": 'def test_a():\n    inject("gone.point")\n',
        }
        got = run_pass(mini_ctx(tmp_path, files), "faultpoints")
        assert len(got) == 1 and "gone.point" in got[0].message, got
        files["h2o3_tpu/faults.py"] = \
            'def f():\n    faultpoint("gone.point")\n'
        assert run_pass(mini_ctx(tmp_path, files), "faultpoints") == []

    def test_timeline_kind_drift(self, tmp_path):
        files = {
            "h2o3_tpu/utils/timeline.py":
                'KINDS = frozenset({"alpha"})\n',
            "h2o3_tpu/user.py":
                'from h2o3_tpu.utils import timeline\n'
                'def f():\n    timeline.record("beta", "x")\n',
        }
        got = run_pass(mini_ctx(tmp_path, files), "timeline-kinds")
        msgs = " ".join(f.message for f in got)
        assert "beta" in msgs and "alpha" in msgs, got   # drift + dead
        files["h2o3_tpu/user.py"] = (
            'from h2o3_tpu.utils import timeline\n'
            'def f():\n    timeline.record("alpha", "x")\n')
        assert run_pass(mini_ctx(tmp_path, files), "timeline-kinds") == []

    def test_phase_name_drift(self, tmp_path):
        """ISSUE-12 half of the timeline-kinds guard: enter() literals vs
        the obs/phases.py PHASES closed enumeration, both directions."""
        files = {
            "h2o3_tpu/obs/phases.py":
                'PHASES = frozenset({"backend_init", "mesh_init"})\n',
            "h2o3_tpu/boot.py":
                'from h2o3_tpu.obs import phases\n'
                'def f():\n'
                '    with phases.enter("warp_init"):\n'
                '        pass\n',
        }
        got = run_pass(mini_ctx(tmp_path, files), "timeline-kinds")
        msgs = " ".join(f.message for f in got)
        # undeclared use + two dead declared phases
        assert "warp_init" in msgs
        assert "backend_init" in msgs and "mesh_init" in msgs
        files["h2o3_tpu/boot.py"] = (
            'from h2o3_tpu.obs import phases\n'
            'def f():\n'
            '    with phases.enter("backend_init"):\n'
            '        pass\n'
            '    with phases.enter("mesh_init"):\n'
            '        pass\n')
        assert run_pass(mini_ctx(tmp_path, files), "timeline-kinds") == []

    def test_knob_docs(self, tmp_path):
        files = {"h2o3_tpu/k.py":
                 'import os\ndef f():\n'
                 '    return os.environ.get("H2O_TPU_SECRET_KNOB")\n'}
        got = run_pass(mini_ctx(tmp_path, files), "knob-docs")
        assert len(got) == 1 and "H2O_TPU_SECRET_KNOB" in got[0].message
        (tmp_path / "README.md").write_text("docs: H2O_TPU_SECRET_KNOB\n")
        assert run_pass(mini_ctx(tmp_path, files), "knob-docs") == []

    def test_metric_duplicate_and_bad_name(self, tmp_path):
        files = {"h2o3_tpu/m.py": """
            def reg(r):
                r.counter("h2o3_good_total")
                r.counter("h2o3_good_total")
                r.gauge("BadName")
        """}
        got = run_pass(mini_ctx(tmp_path, files), "metric-registry")
        msgs = " ".join(f.message for f in got)
        assert "registered 2 times" in msgs and "BadName" in msgs, got


# ---------------------------------------------------------------------------
# baseline mechanics
# ---------------------------------------------------------------------------

class TestBaseline:
    def _one_finding_ctx(self, tmp_path):
        return mini_ctx(tmp_path, {"h2o3_tpu/hot.py": """
            import numpy as np
            from h2o3_tpu.obs import tracing

            def instrumented(out):
                with tracing.span("d"):
                    return np.asarray(out)
        """})

    def test_roundtrip(self, tmp_path):
        ctx = self._one_finding_ctx(tmp_path)
        got = run_pass(ctx, "sync-hygiene")
        assert len(got) == 1
        bl = tmp_path / "BL.json"
        analysis.save_baseline(bl, got,
                               notes={got[0].fingerprint: "audited ok"})
        entries = analysis.load_baseline(bl)
        new, problems = analysis.apply_baseline(got, entries)
        assert new == [] and problems == []
        assert got[0].note == "audited ok"

    def test_stale_entry_is_a_problem(self, tmp_path):
        ctx = self._one_finding_ctx(tmp_path)
        got = run_pass(ctx, "sync-hygiene")
        entries = [{"fingerprint": "deadbeef0000", "pass": "sync-hygiene",
                    "file": "gone.py", "note": "was ok"}]
        new, problems = analysis.apply_baseline(got, entries)
        assert len(new) == 1                      # finding NOT covered
        assert len(problems) == 1 and "stale" in problems[0].message

    def test_non_baselineable_pass_rejected(self, tmp_path):
        f = acore.Finding("mirrored", "x.py", 1, "m", snippet="s")
        with pytest.raises(ValueError, match="not\\s+baselineable"):
            analysis.save_baseline(tmp_path / "b.json", [f])
        _new, problems = analysis.apply_baseline(
            [], [{"fingerprint": "abc", "pass": "mirrored", "note": "n"}])
        assert len(problems) == 1 and "mirrored" in problems[0].message

    def test_missing_note_is_a_problem(self):
        _new, problems = analysis.apply_baseline(
            [], [{"fingerprint": "abc", "pass": "sync-hygiene",
                  "note": "TODO: one-line justification"}])
        assert any("no justification" in p.message for p in problems)

    def test_repo_baseline_has_no_stale_entries_and_notes(self):
        """The checked-in baseline only references findings that still
        exist, every entry carries a real note, and only baselineable
        passes appear (the satellite's no-stale-baseline guard)."""
        new, baselined, problems = analysis.run_repo(root=REPO)
        assert problems == [], [p.message for p in problems]
        for f in baselined:
            assert f.note and not f.note.startswith("TODO")
            assert f.pass_id in analysis.BASELINEABLE


# ---------------------------------------------------------------------------
# regression fixtures: the real defects this analyzer surfaced
# ---------------------------------------------------------------------------

@pytest.fixture()
def mem_cloud(monkeypatch):
    """2-process memory-KV cloud (same shape as test_supervision's):
    oplog.active() becomes True so handler broadcasts really publish."""
    from h2o3_tpu.core import failure
    from h2o3_tpu.parallel import distributed as D
    from h2o3_tpu.parallel import oplog, supervisor

    with D.memory_kv() as kv:
        monkeypatch.setattr(D, "process_count", lambda: 2)
        monkeypatch.setattr(D, "is_coordinator", lambda: True)
        monkeypatch.setenv("H2O_TPU_RETRY_BASE_MS", "1")
        monkeypatch.setenv("H2O_TPU_OP_ACK_TIMEOUT_S", "1")
        monkeypatch.setenv("H2O_TPU_OPLOG_CHECKPOINT_OPS", "0")
        monkeypatch.setenv("H2O_TPU_AUTO_RECOVER", "0")
        failure.set_incarnation(0)
        D.reset_leadership()
        oplog._DEMOTED = False
        oplog.reset()
        supervisor.reset()
        yield kv
    failure.set_incarnation(0)
    D.reset_leadership()
    oplog._DEMOTED = False
    oplog.reset()
    supervisor.reset()


def _tiny_frame(cl, key="analysis_train_frame"):
    import numpy as np

    from h2o3_tpu.core.frame import Column, Frame

    rng = np.random.default_rng(5)
    fr = Frame(key=key)
    fr.add("x1", Column.from_numpy(rng.standard_normal(40)))
    fr.add("y", Column.from_numpy(
        np.array(["a", "b"])[rng.integers(0, 2, 40)], ctype="enum"))
    fr.install()
    return fr


class TestRealDefectRegressions:
    """REAL defects surfaced by the mirrored pass (time.time() control
    flow in `_out_of_time` / the grid budget loop, reachable from the
    broadcast-train root): train and grid broadcasts shipped a per-
    process wall-clock budget. The handlers must zero it before the op
    ships — exactly what the AutoML handler has always done."""

    def test_train_broadcast_clears_wallclock_budget(self, cl, mem_cloud,
                                                     monkeypatch):
        from h2o3_tpu.api import server as srv
        from h2o3_tpu.core.dkv import DKV
        from h2o3_tpu.core.job import Job

        fr = _tiny_frame(cl)
        # broadcast happens synchronously in the handler; the training
        # job itself is irrelevant here — don't start its thread
        monkeypatch.setattr(Job, "start",
                            lambda self, fn, background=True: self)
        try:
            srv.h_modelbuilder_train(srv.Ctx(
                {"algo": "gbm"}, {},
                {"training_frame": str(fr.key), "response_column": "y",
                 "ntrees": 1, "max_depth": 2, "seed": -1,
                 "max_runtime_secs": 30.0}, None))
            op = json.loads(mem_cloud["oplog/0"])
            assert op["kind"] == "train"
            wire = op["payload"]["params"]
            assert float(wire["max_runtime_secs"]) == 0.0, (
                "train broadcast still ships a per-process wall-clock "
                "budget — mirrored fit loops would stop at different "
                "iterations")
            assert int(wire["seed"]) >= 0      # wildcard seed pinned too
        finally:
            DKV.remove(str(fr.key))

    def test_grid_broadcast_clears_wallclock_budget(self, cl, mem_cloud,
                                                    monkeypatch):
        from h2o3_tpu.api import server as srv
        from h2o3_tpu.core.dkv import DKV
        from h2o3_tpu.core.job import Job

        fr = _tiny_frame(cl, key="analysis_grid_frame")
        monkeypatch.setattr(Job, "start",
                            lambda self, fn, background=True: self)
        try:
            srv.h_grid_build(srv.Ctx(
                {"algo": "gbm"}, {},
                {"training_frame": str(fr.key), "response_column": "y",
                 "hyper_parameters": {"max_depth": [2, 3]},
                 "search_criteria": {"strategy": "RandomDiscrete",
                                     "max_models": 2,
                                     "max_runtime_secs": 60.0},
                 "ntrees": 1, "max_runtime_secs": 30.0}, None))
            op = json.loads(mem_cloud["oplog/0"])
            assert op["kind"] == "grid"
            assert float(op["payload"]["params"]["max_runtime_secs"]) == 0.0
            crit = op["payload"]["criteria"]
            assert float(crit["max_runtime_secs"]) == 0.0, (
                "grid broadcast still ships the walker's wall-clock "
                "budget — processes would walk different combo prefixes")
            assert int(crit["seed"]) >= 0      # RandomDiscrete seed pinned
        finally:
            DKV.remove(str(fr.key))


class _Evil:
    def __reduce__(self):
        return (eval, ("1+1",))


class TestRestrictedUnpicklerRegressions:
    """Serialization-pass defects fixed in this PR: every external-bytes
    load refuses non-framework types instead of executing them."""

    def test_restricted_loads_refuses_callables_allows_framework(self):
        import numpy as np

        from h2o3_tpu.core.dkv import Key
        from h2o3_tpu.utils.unpickle import restricted_loads

        with pytest.raises(pickle.UnpicklingError, match="disallowed"):
            restricted_loads(pickle.dumps(_Evil()))
        ok = restricted_loads(pickle.dumps(
            {"a": np.arange(3), "k": Key("x"), "s": {1, 2}}))
        assert list(ok["a"]) == [0, 1, 2] and str(ok["k"]) == "x"

    def test_model_load_refuses_malicious_artifact(self, tmp_path):
        from h2o3_tpu.models.model import Model

        p = tmp_path / "evil_model.bin"
        with open(p, "wb") as f:
            f.write(Model._SAVE_MAGIC)
            f.write(struct.pack("<H", Model._SAVE_VERSION))
            f.write(pickle.dumps(_Evil()))
        with pytest.raises(Exception, match="disallowed"):
            Model.load(str(p))

    def test_assembly_load_refuses_malicious_artifact(self, tmp_path):
        from h2o3_tpu.assembly import H2OAssembly

        p = tmp_path / "evil_assembly.bin"
        with open(p, "wb") as f:
            f.write(H2OAssembly._SAVE_MAGIC)
            f.write(struct.pack("<H", H2OAssembly._SAVE_VERSION))
            f.write(pickle.dumps(_Evil()))
        with pytest.raises(Exception, match="disallowed"):
            H2OAssembly.load(str(p))

    def test_dkv_blob_fetch_refuses_malicious_payload(self, mem_cloud):
        from h2o3_tpu.core.dkv import DKV
        from h2o3_tpu.parallel import distributed as D

        D.kv_put(DKV._BLOB_PREFIX + "evil_key",
                 base64.b64encode(pickle.dumps(_Evil())).decode())
        with pytest.raises(pickle.UnpicklingError, match="disallowed"):
            DKV.fetch_remote("evil_key")


# ---------------------------------------------------------------------------
# CLI + whole-repo invariants
# ---------------------------------------------------------------------------

class TestCli:
    def test_json_output_and_exit_codes(self, tmp_path, capsys):
        from h2o3_tpu.analysis.__main__ import main

        # a dirty mini repo exits 1 with machine-readable findings
        mini_ctx(tmp_path, {"h2o3_tpu/io2.py":
                            "import pickle\n\n"
                            "def bad(f):\n    return pickle.load(f)\n"})
        rc = main([str(tmp_path), "--json", "--select", "serialization"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 1 and len(out["findings"]) == 1
        assert out["findings"][0]["pass"] == "serialization"

    def test_list_passes(self, capsys):
        from h2o3_tpu.analysis.__main__ import main

        assert main(["--list"]) == 0
        listed = set(capsys.readouterr().out.split())
        assert {"mirrored", "lock-order", "serialization",
                "compat-routing", "sync-hygiene"} <= listed

    def test_unknown_pass_is_usage_error(self, tmp_path):
        from h2o3_tpu.analysis.__main__ import main

        mini_ctx(tmp_path, {})
        assert main([str(tmp_path), "--select", "nope"]) == 2

    def test_partial_update_preserves_unselected_entries(self, tmp_path):
        """Review finding: `--select X --update-baseline` must not delete
        the audited entries of unselected passes, and a partial run must
        not misreport them as stale."""
        from h2o3_tpu.analysis.__main__ import main

        mini_ctx(tmp_path, {"h2o3_tpu/hot.py": """
            import numpy as np
            from h2o3_tpu.obs import tracing

            def instrumented(out):
                with tracing.span("d"):
                    return np.asarray(out)
        """})
        bl = tmp_path / "BL.json"
        bl.write_text(json.dumps({"version": 1, "entries": [
            {"fingerprint": "aaaaaaaaaaaa", "pass": "compat-routing",
             "file": "x.py", "note": "audited compat leftover"}]}))
        # partial serialization-only run: the compat entry is untouched
        # and NOT reported stale
        rc = main([str(tmp_path), "--select", "serialization",
                   "--baseline", str(bl), "--update-baseline"])
        assert rc == 0
        entries = analysis.load_baseline(bl)
        assert any(e["fingerprint"] == "aaaaaaaaaaaa" for e in entries), \
            "partial --update-baseline dropped an unselected pass's entry"


class TestRegistrySelfChecks:
    """Review finding: an unresolvable registry qualname must be a
    finding, not a silent green no-op (the renamed-faultpoint failure
    mode applied to the analyzer's own registry)."""

    def test_unresolvable_mirrored_root_is_a_finding(self, tmp_path):
        ctx = mini_ctx(tmp_path, {"h2o3_tpu/work.py": "def f():\n  pass\n"},
                       MIRRORED_ROOTS=("h2o3_tpu.work.renamed_away",))
        got = run_pass(ctx, "mirrored")
        assert len(got) == 1 and "MIRRORED_ROOTS" in got[0].message, got

    def test_stale_guarded_and_helper_entries_flagged(self, tmp_path):
        ctx = mini_ctx(tmp_path, {"h2o3_tpu/work.py": "def f():\n  pass\n"},
                       MIRRORED_ROOTS=("h2o3_tpu.work.f",),
                       GUARDED={"h2o3_tpu.work.gone": "stale audit"},
                       KNOB_HELPERS=frozenset({"h2o3_tpu.work.gone2"}))
        msgs = " ".join(f.message for f in run_pass(ctx, "mirrored"))
        assert "GUARDED" in msgs and "KNOB_HELPERS" in msgs

    def test_stale_swallow_scope_flagged(self, tmp_path):
        ctx = mini_ctx(tmp_path, {"h2o3_tpu/work.py": "def f():\n  pass\n"},
                       SWALLOW_SCOPE=("h2o3_tpu/renamed_watchdog.py",))
        got = run_pass(ctx, "sync-hygiene")
        assert len(got) == 1 and "SWALLOW_SCOPE" in got[0].message, got

    def test_stale_lock_scope_flagged(self, tmp_path):
        ctx = mini_ctx(tmp_path, {"h2o3_tpu/work.py": "def f():\n  pass\n"},
                       LOCK_SCOPE=("h2o3_tpu/gone_dir/",))
        got = run_pass(ctx, "lock-order")
        assert len(got) == 1 and "LOCK_SCOPE" in got[0].message, got
