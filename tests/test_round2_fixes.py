"""Regression tests for the round-1 verdict/advice findings.

Covers: /3/Cloud field mismatch (W3), POST /4/sessions handshake, AutoML
leaderboard_frame ranking (W4), SE fold-assignment verification + metric
provenance, exclude_algos honoring StackedEnsemble, XGBoost reference
defaults, validation-based early stopping (W8)."""

import json
import urllib.request

import numpy as np
import pytest

from h2o3_tpu.core.frame import Column, Frame


def _binary(n=1500, seed=3):
    rng = np.random.default_rng(seed)
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    logit = 1.5 * x1 - 1.0 * x2
    y = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "YES", "NO")
    fr = Frame()
    fr.add("x1", Column.from_numpy(x1))
    fr.add("x2", Column.from_numpy(x2))
    fr.add("y", Column.from_numpy(y, ctype="enum"))
    return fr


@pytest.fixture(scope="module")
def server(cl):
    from h2o3_tpu.api.server import start_server

    srv = start_server(port=0)
    yield srv
    srv.stop()


def _get(srv, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{srv.port}{path}") as r:
        return json.loads(r.read())


def _post(srv, path, data=b""):
    req = urllib.request.Request(f"http://127.0.0.1:{srv.port}{path}",
                                 data=data, method="POST")
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def test_cloud_reports_real_size(cl, server):
    out = _get(server, "/3/Cloud")
    assert out["cloud_size"] == cl.n_devices == 8
    assert out["cloud_name"] == cl.args.name
    assert len(out["nodes"]) == 8


def test_post_sessions_handshake(server):
    out = _post(server, "/4/sessions")
    assert out["session_key"].startswith("_sid")


def test_xgboost_reference_defaults():
    from h2o3_tpu.models.xgboost import XGBoost

    p = XGBoost.default_params()
    assert p["learn_rate"] == 0.3          # eta
    assert p["min_rows"] == 1.0            # min_child_weight
    assert p["sample_rate"] == 1.0         # subsample
    assert p["col_sample_rate_per_tree"] == 1.0
    assert p["max_depth"] == 6


def test_se_rejects_mismatched_folds(cl):
    from h2o3_tpu.models.ensemble import StackedEnsemble
    from h2o3_tpu.models.glm import GLM
    from h2o3_tpu.models.tree.gbm import GBM

    fr = _binary()
    m1 = GLM(family="binomial", nfolds=3, seed=1,
             keep_cross_validation_predictions=True).train(y="y", training_frame=fr)
    m2 = GBM(ntrees=5, max_depth=3, nfolds=3, seed=2,
             keep_cross_validation_predictions=True).train(y="y", training_frame=fr)
    assert m1._output.fold_assignment_digest != m2._output.fold_assignment_digest
    with pytest.raises(ValueError, match="fold"):
        StackedEnsemble(base_models=[m1, m2]).train(y="y", training_frame=fr)
    # same seed → same folds → stacking works
    m3 = GBM(ntrees=5, max_depth=3, nfolds=3, seed=1,
             keep_cross_validation_predictions=True).train(y="y", training_frame=fr)
    assert m1._output.fold_assignment_digest == m3._output.fold_assignment_digest
    se = StackedEnsemble(base_models=[m1, m3]).train(y="y", training_frame=fr)
    assert se._output.training_metrics.auc > 0.5


def test_se_cv_metric_provenance(cl):
    from h2o3_tpu.models.ensemble import StackedEnsemble
    from h2o3_tpu.models.glm import GLM
    from h2o3_tpu.models.tree.gbm import GBM

    fr = _binary()
    kw = dict(nfolds=3, seed=7, keep_cross_validation_predictions=True)
    m1 = GLM(family="binomial", **kw).train(y="y", training_frame=fr)
    m2 = GBM(ntrees=5, max_depth=3, **kw).train(y="y", training_frame=fr)
    se = StackedEnsemble(base_models=[m1, m2], metalearner_nfolds=3,
                         seed=7).train(y="y", training_frame=fr)
    # SE ranks on CV metrics like the base models, not in-sample training
    assert se._output.cross_validation_metrics is not None
    assert np.isfinite(se._output.cross_validation_metrics.auc)


def test_automl_excludes_stackedensemble(cl):
    from h2o3_tpu.automl.automl import H2OAutoML

    fr = _binary(800)
    aml = H2OAutoML(max_models=2, nfolds=2, seed=5,
                    exclude_algos=["StackedEnsemble"]).train(
        y="y", training_frame=fr)
    assert all(m.algo_name != "stackedensemble" for m in aml.models)


def test_automl_leaderboard_frame_ranks(cl):
    from h2o3_tpu.automl.automl import H2OAutoML

    fr = _binary(800, seed=1)
    lb = _binary(400, seed=99)
    aml = H2OAutoML(max_models=2, nfolds=2, seed=5,
                    exclude_algos=["StackedEnsemble"]).train(
        y="y", training_frame=fr, leaderboard_frame=lb)
    rows = aml.leaderboard
    assert len(rows) >= 2
    # metric in the leaderboard equals model_performance on the lb frame
    m = aml.leader
    mm = m.model_performance(lb)
    lead_row = next(r for r in rows
                    if r["model_id"] in (str(m.key), getattr(m, "_se_name", "")))
    assert lead_row["auc"] == pytest.approx(float(mm.auc), abs=1e-9)


def test_gbm_validation_early_stopping(cl):
    from h2o3_tpu.models.tree.gbm import GBM

    rng = np.random.default_rng(0)
    n = 2000
    x = rng.normal(size=(n, 3))
    y = x[:, 0] + 0.1 * rng.normal(size=n)          # near-pure signal
    fr = Frame.from_numpy(np.column_stack([x, y]), names=["a", "b", "c", "y"])
    # tiny validation set with DIFFERENT noise — overfitting shows quickly
    nv = 150
    xv = rng.normal(size=(nv, 3))
    yv = xv[:, 0] + 2.0 * rng.normal(size=nv)
    va = Frame.from_numpy(np.column_stack([xv, yv]), names=["a", "b", "c", "y"])
    m = GBM(ntrees=200, max_depth=5, learn_rate=0.5, seed=1,
            stopping_rounds=2, stopping_tolerance=1e-3,
            score_each_iteration=True).train(
        y="y", training_frame=fr, validation_frame=va)
    hist = m._output.scoring_history
    assert "validation_deviance" in hist[0]
    # stopped on the validation metric well before the 200-tree budget
    assert len(hist) < 200
    # and the validation series is what drove the stop: training deviance was
    # still improving at the end
    assert hist[-1]["training_deviance"] < hist[0]["training_deviance"]


def test_drf_validation_early_stopping(cl):
    from h2o3_tpu.models.tree.drf import DRF

    rng = np.random.default_rng(2)
    n = 1500
    x = rng.normal(size=(n, 3))
    y = x[:, 0] + 0.1 * rng.normal(size=n)
    fr = Frame.from_numpy(np.column_stack([x, y]), names=["a", "b", "c", "y"])
    xv = rng.normal(size=(200, 3))
    yv = xv[:, 0] + 0.1 * rng.normal(size=200)
    va = Frame.from_numpy(np.column_stack([xv, yv]), names=["a", "b", "c", "y"])
    m = DRF(ntrees=20, max_depth=4, seed=1, stopping_rounds=2,
            score_each_iteration=True).train(
        y="y", training_frame=fr, validation_frame=va)
    hist = m._output.scoring_history
    assert any("validation_rmse" in h for h in hist)
