"""Observability: TimeLine ring, MRTask phase profiling, boot probes,
profiler REST surfaces.

Reference: water/TimeLine.java:22, water/MRTask.java:188-192 (.profile),
water/init/Linpack.java / MemoryBandwidth.java / NetworkBench.java,
water/api/TimelineHandler + ProfilerHandler.
"""

import os

import numpy as np
import pytest

from h2o3_tpu.utils import timeline


class TestRing:
    def test_record_and_fetch(self):
        timeline.clear()
        timeline.record("test", "hello", ms=1.5, extra=7)
        evs = timeline.events()
        assert evs[-1]["kind"] == "test" and evs[-1]["extra"] == 7

    def test_task_context(self):
        timeline.clear()
        with timeline.task("phase", "work"):
            pass
        ev = timeline.events()[-1]
        assert ev["what"] == "work" and ev["ms"] >= 0


class TestTaskProfiling:
    def test_map_reduce_phases(self, cl, monkeypatch):
        monkeypatch.setenv("H2O_TPU_PROFILE", "1")
        timeline.clear()
        import jax.numpy as jnp

        from h2o3_tpu.core.frame import Column
        from h2o3_tpu.core.mrtask import map_reduce

        c = Column.from_numpy(np.arange(64, dtype=np.float64))
        total = map_reduce(lambda x: jnp.nansum(x), [c])
        assert float(total) == float(np.arange(64).sum())
        profs = [e for e in timeline.events() if e["kind"] == "task_profile"]
        assert profs, timeline.events()
        p = profs[-1]
        assert {"build_ms", "run_ms", "sync_ms"} <= set(p)


class TestBootProbes:
    def test_self_benchmark(self, cl):
        b = cl.self_benchmark(size=256)
        assert b["matmul_gflops"] > 0
        assert b["membw_gbps"] > 0
        assert b["psum_latency_us"] > 0
        assert any(e["kind"] == "self_benchmark" for e in timeline.events())


class TestDeviceMemory:
    def test_gauges_shape(self, cl):
        mem = timeline.device_memory()
        assert len(mem) >= 1
        assert "device" in mem[0]


class TestRESTSurfaces:
    def test_timeline_and_profiler(self, cl):
        from h2o3_tpu import client
        from h2o3_tpu.api.server import start_server

        srv = start_server(port=0)
        try:
            client.connect(port=srv.port)
            timeline.record("marker", "from_test")
            body = client._req("GET", "/3/Timeline")
            kinds = {e.get("kind") for e in body["events"]}
            assert "marker" in kinds and "rest" in kinds
            body = client._req("GET", "/3/Profiler")
            assert body["nodes"]
        finally:
            srv.stop()


class TestXLATrace:
    def test_trace_writes_files(self, cl, tmp_path):
        import jax.numpy as jnp

        d = str(tmp_path / "prof")
        with timeline.trace(d):
            (jnp.ones((64, 64)) @ jnp.ones((64, 64))).block_until_ready()
        assert os.path.isdir(d) and os.listdir(d)
        assert any(e["kind"] == "xla_trace" for e in timeline.events())


def test_tls_rest_bind(tmp_path, cl):
    """TLS on the REST bind (water/network/SSLProperties analog): https
    serves, plain http against the TLS port fails."""
    import json
    import ssl
    import subprocess
    import urllib.request

    from h2o3_tpu.api.server import start_server

    cert = tmp_path / "cert.pem"
    key = tmp_path / "key.pem"
    subprocess.run(["openssl", "req", "-x509", "-newkey", "rsa:2048",
                    "-keyout", str(key), "-out", str(cert), "-days", "1",
                    "-nodes", "-subj", "/CN=localhost"],
                   check=True, capture_output=True)
    srv = start_server(port=0, ssl_certfile=str(cert), ssl_keyfile=str(key))
    try:
        assert srv.scheme == "https"
        sctx = ssl.create_default_context()
        sctx.check_hostname = False
        sctx.verify_mode = ssl.CERT_NONE        # self-signed test cert
        with urllib.request.urlopen(f"https://127.0.0.1:{srv.port}/3/Cloud",
                                    context=sctx, timeout=30) as r:
            cloud = json.loads(r.read())
        assert cloud["cloud_healthy"] is True
        import pytest as _pytest

        with _pytest.raises(Exception):
            urllib.request.urlopen(f"http://127.0.0.1:{srv.port}/3/Cloud",
                                   timeout=5)
    finally:
        srv.stop()


def test_pluggable_login_module(tmp_path, cl, monkeypatch):
    """H2O_TPU_LOGIN_MODULE (JAAS login-module analog, h2o-security
    LDAP/PAM realms): any module:callable authenticates Basic creds."""
    import json
    import sys
    import types
    import urllib.request

    from h2o3_tpu.api.server import start_server

    mod = types.ModuleType("_test_authmod")
    mod.check = lambda user, pw: user == "ldapuser" and pw == "s3cret"
    sys.modules["_test_authmod"] = mod
    monkeypatch.setenv("H2O_TPU_LOGIN_MODULE", "_test_authmod:check")
    srv = start_server(port=0)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        import base64

        def get(creds=None):
            req = urllib.request.Request(base + "/3/Cloud")
            if creds:
                req.add_header("Authorization", "Basic "
                               + base64.b64encode(creds.encode()).decode())
            try:
                with urllib.request.urlopen(req, timeout=30) as r:
                    return r.status, json.loads(r.read())
            except urllib.error.HTTPError as e:
                return e.code, None

        assert get()[0] == 401                      # no creds
        assert get("ldapuser:wrong")[0] == 401
        code, cloud = get("ldapuser:s3cret")
        assert code == 200 and cloud["cloud_healthy"] is True
    finally:
        srv.stop()
        del sys.modules["_test_authmod"]
