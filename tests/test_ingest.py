"""Parse/ingest tests (water/parser test family analog)."""

import gzip
import os

import numpy as np
import pytest


def test_parse_setup_guess(cl, airlines_csv):
    from h2o3_tpu.ingest.parse_setup import guess_setup

    s = guess_setup(airlines_csv)
    assert s.separator == ","
    assert s.check_header == 1
    assert s.column_names == ["DayOfWeek", "Carrier", "Distance", "DepTime", "IsDepDelayed"]
    assert s.column_types[0] == "enum"
    assert s.column_types[2] == "real"


def test_import_file(cl, airlines_csv):
    import h2o3_tpu

    fr = h2o3_tpu.import_file(airlines_csv)
    assert fr.nrows == 2000
    assert fr.ncols == 5
    assert fr.col("Carrier").is_categorical
    assert sorted(fr.col("Carrier").domain) == ["AA", "DL", "UA", "WN"]
    assert fr.col("Distance").is_numeric
    assert fr.col("Distance").min() >= 50
    assert fr.col("IsDepDelayed").domain == ["NO", "YES"]


def test_import_gzip(cl, airlines_csv, tmp_path):
    import h2o3_tpu

    gz = tmp_path / "airlines.csv.gz"
    with open(airlines_csv, "rb") as f, gzip.open(gz, "wb") as g:
        g.write(f.read())
    fr = h2o3_tpu.import_file(str(gz))
    assert fr.nrows == 2000
    assert fr.ncols == 5


def test_na_strings(cl, tmp_path):
    import h2o3_tpu

    p = tmp_path / "nas.csv"
    p.write_text("a,b\n1,x\nNA,y\n3,NA\n")
    fr = h2o3_tpu.import_file(str(p))
    assert fr.col("a").na_count() == 1
    assert fr.col("b").na_count() == 1


def test_headerless(cl, tmp_path):
    import h2o3_tpu

    p = tmp_path / "nohdr.csv"
    p.write_text("1,2.5\n3,4.5\n5,6.5\n")
    fr = h2o3_tpu.import_file(str(p))
    assert fr.nrows == 3
    assert fr.names == ["C1", "C2"]
    np.testing.assert_allclose(fr.col("C1").to_numpy(), [1, 3, 5])


def test_multi_file_glob(cl, tmp_path):
    import h2o3_tpu

    for i in range(3):
        (tmp_path / f"part{i}.csv").write_text("x,y\n" + "".join(
            f"{j + i * 10},{j * 2.0}\n" for j in range(5)))
    fr = h2o3_tpu.import_file(str(tmp_path / "part*.csv"))
    assert fr.nrows == 15


def test_native_parser_numeric(cl, tmp_path):
    """Native C++ parser path (h2o3_tpu/native/csv_parser.cpp)."""
    from h2o3_tpu.native.loader import get_lib, native_parse_csv
    from h2o3_tpu.ingest.parse_setup import guess_setup

    p = tmp_path / "num.csv"
    n = 1000
    rng = np.random.default_rng(0)
    a = rng.normal(size=n)
    b = rng.integers(0, 100, n).astype(float)
    with open(p, "w") as f:
        f.write("a,b\n")
        for i in range(n):
            f.write(f"{a[i]:.6g},{b[i]:.1f}\n")
    setup = guess_setup(str(p))
    if get_lib() is None:
        pytest.skip("native lib unavailable")
    cols = native_parse_csv(str(p), setup)
    assert cols is not None
    np.testing.assert_allclose(cols["a"], a, rtol=1e-5)
    np.testing.assert_allclose(cols["b"], b)
