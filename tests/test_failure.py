"""Failure detection + fault injection (SURVEY §5.3).

Reference: water/HeartBeatThread.java (liveness gossip), the reference
test-tree chaos flags (kill-node runners). The 2-process tier
(tests/mp_worker.py) exercises the real heartbeat table; these tests cover
the injection hooks and failure propagation through the Job machinery.
"""

import numpy as np
import pytest

from h2o3_tpu.core import failure
from h2o3_tpu.core.frame import Column, Frame


def _frame(n=300, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal(n)
    y = np.where(rng.random(n) < 1 / (1 + np.exp(-2 * x)), "Y", "N")
    fr = Frame()
    fr.add("x", Column.from_numpy(x))
    fr.add("y", Column.from_numpy(y, ctype="enum"))
    return fr


class TestFaultInjection:
    def test_faultpoint_noop_when_unarmed(self):
        failure.faultpoint("never.armed")       # must be free + silent

    def test_inject_fires_n_times(self):
        with failure.inject("x.y", times=2):
            with pytest.raises(failure.InjectedFault):
                failure.faultpoint("x.y")
            with pytest.raises(failure.InjectedFault):
                failure.faultpoint("x.y")
            failure.faultpoint("x.y")           # disarmed after 2
        failure.faultpoint("x.y")               # context cleanup

    def test_tree_fit_failure_fails_job(self, cl):
        """An injected mid-training fault must surface as a FAILED job with
        the exception recorded (hex Job failure propagation)."""
        from h2o3_tpu.core.job import Job
        from h2o3_tpu.models.tree.gbm import GBM

        b = GBM(ntrees=5, max_depth=3, seed=1)
        with failure.inject("tree.fit_tree", times=1):
            with pytest.raises(failure.InjectedFault):
                b.train(y="y", training_frame=_frame())
        assert b.job.status == Job.FAILED
        assert "injected fault" in (b.job.exception or "")

    def test_mrtask_failure(self, cl):
        import jax.numpy as jnp

        from h2o3_tpu.core.mrtask import map_reduce

        c = Column.from_numpy(np.arange(32, dtype=np.float64))
        with failure.inject("mrtask.map_reduce"):
            with pytest.raises(failure.InjectedFault):
                map_reduce(lambda x: jnp.nansum(x), [c])
        # and the harness recovers afterwards
        assert float(map_reduce(lambda x: jnp.nansum(x), [c])) == \
            float(np.arange(32).sum())

    def test_automl_keeps_going_past_faulted_step(self, cl):
        """AutoML's fire-and-record loop must survive a model that dies
        mid-train (the reference logs the failure and moves on)."""
        from h2o3_tpu.automl.automl import H2OAutoML

        am = H2OAutoML(max_models=2, seed=3, nfolds=2,
                       include_algos=["gbm"])
        with failure.inject("tree.fit_tree", times=1):
            am.train(y="y", training_frame=_frame(600))
        assert am.leader is not None            # later steps still trained
        assert any("FAILED" in e["message"] for e in am.event_log)


class TestHealth:
    def test_single_process_health_empty(self, cl):
        assert failure.heartbeat() is False     # no cloud KV locally
        assert failure.cluster_health() == []

    def test_heartbeat_thread_lifecycle(self, cl):
        hb = failure.HeartbeatThread(interval_s=0.1).start()
        try:
            import time

            time.sleep(0.3)
        finally:
            hb.stop()
