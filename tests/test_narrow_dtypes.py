"""Narrow device dtypes: int8/int16 categorical codes, optional bfloat16
numerics (SURVEY §7 — the replacement for the reference's 19-codec chunk
zoo, water/fvec/NewChunk.java compress()). The -1 NA sentinel / NaN IS the
validity mask; ops upcast at their boundaries.
"""

import numpy as np
import pytest

from h2o3_tpu.core.frame import Column, Frame, _code_dtype


class TestCodeDtypes:
    def test_small_domain_int8(self, cl):
        g = np.array(["a", "b", "c"], object)[np.arange(300) % 3]
        c = Column.from_numpy(g, ctype="enum")
        assert c.data.dtype == np.int8
        assert c.domain == ["a", "b", "c"]
        # NA sentinel survives the narrow dtype
        g2 = g.copy()
        g2[5] = None
        c2 = Column.from_numpy(g2, ctype="enum")
        assert int(c2.to_numpy()[5]) < 0

    def test_medium_domain_int16(self, cl):
        vals = np.array([f"v{i:05d}" for i in range(200)], object)[
            np.random.default_rng(0).integers(0, 200, 1000)]
        c = Column.from_numpy(vals, ctype="enum")
        assert c.data.dtype == np.int16 or len(set(vals)) <= 126

    def test_dtype_ladder(self):
        assert _code_dtype(2) == np.int8
        assert _code_dtype(126) == np.int8
        assert _code_dtype(127) == np.int16
        assert _code_dtype(40000) == np.int32

    def test_training_still_works(self, cl):
        """int8 codes flow through binning/histograms/scoring unchanged."""
        from h2o3_tpu.models.tree.gbm import GBM

        rng = np.random.default_rng(1)
        n = 500
        g = np.array(["p", "q", "r", "s"], object)[rng.integers(0, 4, n)]
        x = rng.standard_normal(n)
        y = np.where(rng.random(n) < 1 / (1 + np.exp(-(2 * x + (g == "p")))),
                     "Y", "N")
        fr = Frame()
        fr.add("g", Column.from_numpy(g, ctype="enum"))
        fr.add("x", Column.from_numpy(x))
        fr.add("y", Column.from_numpy(y, ctype="enum"))
        assert fr.col("g").data.dtype == np.int8
        m = GBM(ntrees=5, max_depth=3, seed=1).train(y="y", training_frame=fr)
        assert float(m._output.training_metrics.auc) > 0.6
        p = m.predict(fr).col("Y").to_numpy()
        assert np.all(np.isfinite(p))


class TestBf16Numeric:
    def test_opt_in_halves_storage(self, cl):
        import ml_dtypes

        cl.args.numeric_dtype = "bfloat16"
        try:
            x = np.linspace(-3, 3, 1000)
            c = Column.from_numpy(x)
            assert c.data.dtype == ml_dtypes.bfloat16
            # NaN NA representation survives
            x2 = x.copy()
            x2[7] = np.nan
            c2 = Column.from_numpy(x2)
            assert np.isnan(c2.to_numpy()[7])
            # stats still compute (upcast at op boundary)
            assert abs(float(c.mean())) < 0.01
        finally:
            cl.args.numeric_dtype = "float32"

    def test_bf16_training(self, cl):
        from h2o3_tpu.models.glm import GLM

        cl.args.numeric_dtype = "bfloat16"
        try:
            rng = np.random.default_rng(3)
            n = 600
            X = rng.standard_normal((n, 4))
            yv = np.where(rng.random(n) < 1 / (1 + np.exp(-(2 * X[:, 0]))),
                          "Y", "N")
            fr = Frame.from_numpy(X, names=["a", "b", "c", "d"])
            fr.add("y", Column.from_numpy(yv, ctype="enum"))
            import ml_dtypes

            assert fr.col("a").data.dtype == ml_dtypes.bfloat16
            m = GLM(family="binomial", seed=1).train(y="y", training_frame=fr)
            assert float(m._output.training_metrics.auc) > 0.7
        finally:
            cl.args.numeric_dtype = "float32"

    def test_memory_halves_on_bench_frame(self, cl):
        """The HBM-savings measurement BASELINE.md cites."""
        rng = np.random.default_rng(0)
        x = rng.standard_normal(20000)
        f32 = Column.from_numpy(x).data.nbytes
        cl.args.numeric_dtype = "bfloat16"
        try:
            bf16 = Column.from_numpy(x).data.nbytes
        finally:
            cl.args.numeric_dtype = "float32"
        assert bf16 * 2 == f32
