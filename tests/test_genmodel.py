"""Standalone genmodel runtime: in-framework predictions must match the
numpy-only h2o3_genmodel scorer on the SAME mojo, including in a subprocess
that cannot import h2o3_tpu at all.

Reference contract: hex/genmodel/easy/EasyPredictModelWrapper.java:1 (row
scoring), hex/genmodel/tools/PredictCsv.java:1 (CLI), MojoModel.java:1
(artifact loading) — the dependency-free scoring product (VERDICT r3 #2).
"""

import csv
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

from h2o3_tpu.core.frame import Column, Frame

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def data(cl):
    rng = np.random.default_rng(5)
    n = 900
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    g = np.array(["a", "b", "c", "d"])[rng.integers(0, 4, n)]
    logit = 1.2 * x1 - x2 + (g == "a") * 1.0 - (g == "d") * 0.7
    ybin = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "Y", "N")
    ymul = np.array(["p", "q", "r"])[
        np.argmax(np.column_stack([x1, x2, -x1 - x2])
                  + rng.normal(0, .4, (n, 3)), axis=1)]
    yreg = logit + 0.2 * rng.normal(size=n)
    fr = Frame()
    fr.add("x1", Column.from_numpy(x1))
    fr.add("x2", Column.from_numpy(x2))
    fr.add("g", Column.from_numpy(g, ctype="enum"))
    fr.add("ybin", Column.from_numpy(ybin, ctype="enum"))
    fr.add("ymul", Column.from_numpy(ymul, ctype="enum"))
    fr.add("yreg", Column.from_numpy(yreg))
    raw = {"x1": x1, "x2": x2, "g": g}
    return fr, raw


def _compare(model, fr, raw, atol=1e-5):
    import h2o3_genmodel as gm

    from h2o3_tpu.models import mojo

    pred = gm.load_mojo(mojo.export_mojo_bytes(model))
    got = pred.score(raw)
    want = model.predict(fr)
    for name in want.names:
        if name not in got:
            continue
        col = want.col(name)
        a = np.asarray(col.to_numpy())
        if col.domain:                 # cat columns yield codes: decode
            a = np.asarray(col.domain, object)[a.astype(int)]
        b = np.asarray(got[name])
        if a.dtype.kind in "fc" and b.dtype.kind in "fc":
            np.testing.assert_allclose(a.astype(float), b.astype(float),
                                       atol=atol, rtol=1e-5)
        else:
            assert (a.astype(str) == b.astype(str)).all(), name
    return pred


def test_gbm_binomial_matches(data, cl):
    from h2o3_tpu.models.tree.gbm import GBM

    fr, raw = data
    m = GBM(ntrees=10, max_depth=4, seed=1).train(
        x=["x1", "x2", "g"], y="ybin", training_frame=fr)
    pred = _compare(m, fr, raw)
    one = pred.predict({"x1": 0.5, "x2": -1.0, "g": "a"})
    assert one.label in ("Y", "N")
    assert abs(sum(one.class_probabilities) - 1.0) < 1e-6


def test_gbm_multinomial_matches(data, cl):
    from h2o3_tpu.models.tree.gbm import GBM

    fr, raw = data
    m = GBM(ntrees=8, max_depth=3, seed=2).train(
        x=["x1", "x2", "g"], y="ymul", training_frame=fr)
    _compare(m, fr, raw)


def test_gbm_poisson_matches(data, cl):
    from h2o3_tpu.models.tree.gbm import GBM

    fr, raw = data
    rng = np.random.default_rng(0)
    fr2 = Frame()
    for nm in ("x1", "x2", "g"):
        fr2.add(nm, fr.col(nm))
    fr2.add("cnt", Column.from_numpy(
        rng.poisson(np.exp(0.3 * fr.col("x1").to_numpy())).astype(float)))
    m = GBM(ntrees=6, max_depth=3, seed=3, distribution="poisson").train(
        x=["x1", "x2", "g"], y="cnt", training_frame=fr2)
    _compare(m, fr2, raw)


def test_drf_binomial_and_regression_match(data, cl):
    from h2o3_tpu.models.tree.drf import DRF

    fr, raw = data
    m = DRF(ntrees=10, max_depth=6, seed=1).train(
        x=["x1", "x2", "g"], y="ybin", training_frame=fr)
    _compare(m, fr, raw)
    r = DRF(ntrees=8, max_depth=6, seed=2).train(
        x=["x1", "x2", "g"], y="yreg", training_frame=fr)
    _compare(r, fr, raw)


def test_drf_multinomial_matches(data, cl):
    from h2o3_tpu.models.tree.drf import DRF

    fr, raw = data
    m = DRF(ntrees=6, max_depth=5, seed=4).train(
        x=["x1", "x2", "g"], y="ymul", training_frame=fr)
    _compare(m, fr, raw)


def test_isolation_forest_matches(data, cl):
    from h2o3_tpu.models.tree.isofor import IsolationForest

    fr, raw = data
    m = IsolationForest(ntrees=20, seed=1).train(training_frame=fr,
                                                 x=["x1", "x2", "g"])
    _compare(m, fr, raw)


def test_xgboost_matches(data, cl):
    from h2o3_tpu.models.xgboost import XGBoost

    fr, raw = data
    m = XGBoost(ntrees=8, max_depth=4, seed=1).train(
        x=["x1", "x2", "g"], y="ybin", training_frame=fr)
    _compare(m, fr, raw)


def test_glm_binomial_and_regression_match(data, cl):
    from h2o3_tpu.models.glm import GLM

    fr, raw = data
    m = GLM(family="binomial").train(x=["x1", "x2", "g"], y="ybin",
                                     training_frame=fr)
    _compare(m, fr, raw)
    r = GLM(family="gaussian").train(x=["x1", "x2", "g"], y="yreg",
                                     training_frame=fr)
    _compare(r, fr, raw)


def test_glm_multinomial_matches(data, cl):
    from h2o3_tpu.models.glm import GLM

    fr, raw = data
    m = GLM(family="multinomial").train(x=["x1", "x2", "g"], y="ymul",
                                        training_frame=fr)
    _compare(m, fr, raw)


def test_kmeans_matches(data, cl):
    from h2o3_tpu.models.kmeans import KMeans

    fr, raw = data
    m = KMeans(k=3, seed=1).train(training_frame=fr, x=["x1", "x2"])
    _compare(m, fr, {"x1": raw["x1"], "x2": raw["x2"]})


def test_deeplearning_matches(data, cl):
    from h2o3_tpu.models.deeplearning import DeepLearning

    fr, raw = data
    m = DeepLearning(hidden=[8, 8], epochs=3, seed=1).train(
        x=["x1", "x2", "g"], y="ybin", training_frame=fr)
    _compare(m, fr, raw, atol=1e-4)


def test_unseen_level_and_missing_column_score_as_na(data, cl):
    """EasyPredictModelWrapper contract: unknown categorical levels and
    absent columns do not crash — they score through the NA path."""
    import h2o3_genmodel as gm

    from h2o3_tpu.models import mojo
    from h2o3_tpu.models.tree.gbm import GBM

    fr, raw = data
    m = GBM(ntrees=5, max_depth=3, seed=1).train(
        x=["x1", "x2", "g"], y="ybin", training_frame=fr)
    pred = gm.load_mojo(mojo.export_mojo_bytes(m))
    one = pred.predict({"x1": 0.1, "x2": 0.2, "g": "NEVER_SEEN"})
    assert one.label in ("Y", "N")
    two = pred.predict({"x1": 0.1})        # x2 and g missing entirely
    assert two.label in ("Y", "N")


def test_predictcsv_subprocess_no_framework(data, tmp_path, cl):
    """The PredictCsv CLI must run where h2o3_tpu does NOT exist: copy
    h2o3_genmodel alone into a tmp dir, clear PYTHONPATH down to it, verify
    `import h2o3_tpu` fails there, and check predictions byte-match the
    server-side scorer (VERDICT r3 'Done =' criterion)."""
    from h2o3_tpu.models import mojo
    from h2o3_tpu.models.tree.gbm import GBM

    fr, raw = data
    m = GBM(ntrees=8, max_depth=4, seed=1).train(
        x=["x1", "x2", "g"], y="ybin", training_frame=fr)
    mz = tmp_path / "model.zip"
    mz.write_bytes(mojo.export_mojo_bytes(m))

    iso = tmp_path / "iso"
    iso.mkdir()
    shutil.copytree(os.path.join(REPO, "h2o3_genmodel"),
                    iso / "h2o3_genmodel")
    csv_in = tmp_path / "in.csv"
    with open(csv_in, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["x1", "x2", "g"])
        for i in range(len(raw["x1"])):
            w.writerow([raw["x1"][i], raw["x2"][i], raw["g"][i]])
    csv_out = tmp_path / "out.csv"

    env = {k: v for k, v in os.environ.items()
           if k not in ("PYTHONPATH",)}
    env["PYTHONPATH"] = str(iso)
    env["PYTHONSAFEPATH"] = "1"          # no cwd fallback onto the repo
    env.setdefault("PALLAS_AXON_POOL_IPS", "")
    code = (
        "import sys, importlib.util as u\n"
        "assert u.find_spec('h2o3_tpu') is None, 'framework leaked in'\n"
        "from h2o3_genmodel.predict_csv import main\n"
        f"rc = main(['--mojo', {str(mz)!r}, '--input', {str(csv_in)!r}, "
        f"'--output', {str(csv_out)!r}])\n"
        "assert 'jax' not in sys.modules and 'h2o3_tpu' not in sys.modules\n"
        "sys.exit(rc)\n")
    proc = subprocess.run([sys.executable, "-c", code], cwd=str(iso),
                          env=env, capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, proc.stderr

    with open(csv_out) as f:
        rows = list(csv.DictReader(f))
    want = m.predict(fr)
    pc = want.col("predict")
    wl = np.asarray(pc.domain, object)[
        np.asarray(pc.to_numpy()).astype(int)].astype(str)
    wp = np.asarray(want.col("Y").to_numpy()).astype(float)
    assert len(rows) == len(wl)
    got_l = np.asarray([r["predict"] for r in rows])
    got_p = np.asarray([float(r["Y"]) for r in rows])
    assert (got_l == wl).all()
    np.testing.assert_allclose(got_p, wp, atol=1e-5, rtol=1e-5)


def test_drf_double_trees_matches(data, cl):
    """binomial_double_trees: per-class trees must keep their class slots
    in the standalone runtime too (round-5 fix, mirrors compressed.py)."""
    from h2o3_tpu.models.tree.drf import DRF

    fr, raw = data
    m = DRF(ntrees=10, max_depth=5, binomial_double_trees=True,
            seed=4).train(x=["x1", "x2", "g"], y="ybin", training_frame=fr)
    _compare(m, fr, raw)
