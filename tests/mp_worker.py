"""Worker for the 2-process jax.distributed localhost tier (run via
test_multiprocess.py; reference analog: the 4-JVM localhost cloud of
multiNodeUtils.sh + water.TestUtil.stall_till_cloudsize).

Each process hosts 2 virtual CPU devices; the cloud is the 4-device global
mesh. Training runs the REAL framework paths: Frame construction with
per-process shard materialization, GLM IRLS (per-shard Gram + psum across
process boundaries), and metric reduction to replicated scalars."""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
# 2 local virtual CPU devices; jax<0.5 only honors the XLA flag (set before
# backend init), newer jax the config option — apply whichever exists
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2")

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:      # jax<0.5: the XLA flag above already did it
    pass
try:
    # jax<0.5 CPU backend needs gloo for cross-process collectives
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except (AttributeError, ValueError):
    pass

import numpy as np


def main():
    port, pid = sys.argv[1], int(sys.argv[2])
    from h2o3_tpu.parallel import distributed

    distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                           num_processes=2, process_id=pid)
    assert jax.process_count() == 2, jax.process_count()
    assert distributed.process_count() == 2
    assert distributed.is_coordinator() == (pid == 0)
    devs = jax.devices()
    assert len(devs) == 4, devs          # 2 local + 2 remote

    import h2o3_tpu
    from h2o3_tpu.core.frame import Column, Frame

    cl = h2o3_tpu.init()
    assert cl.n_devices == 4
    assert int(cl.mesh.shape["rows"]) == 4

    # identical host data in both processes (the parse layer would hand each
    # process the same logical rows); shards materialize per process
    rng = np.random.default_rng(7)
    n = 512
    X = rng.standard_normal((n, 4))
    logit = 2.0 * X[:, 0] - X[:, 1]
    y = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "Y", "N")
    fr = Frame.from_numpy(X, names=["a", "b", "c", "d"])
    fr.add("y", Column.from_numpy(y, ctype="enum"))
    col = fr.col("a").data
    assert len(col.sharding.device_set) == 4
    assert len(col.addressable_shards) == 2      # only local shards held

    # cross-process reduction through the framework's rollup path
    mean = float(fr.col("a").mean())
    assert abs(mean - X[:, 0].mean()) < 1e-4, (mean, X[:, 0].mean())

    from h2o3_tpu.models.glm import GLM

    m = GLM(family="binomial", lambda_=0.0, seed=1).train(
        y="y", training_frame=fr)
    auc = float(m._output.training_metrics.auc)
    assert np.isfinite(auc) and auc > 0.8, auc

    # scoring path: adapt_test + predict across the process boundary
    preds = m.predict(fr)
    s = float(preds.col("Y").data.sum())       # replicated reduction
    assert np.isfinite(s)

    # GBM: the flagship device tree grower (histogram matmuls + split search
    # + routing in one shard_map program) across the SAME process boundary —
    # round-2 weakness W2 was that trees never crossed one. Includes the
    # device validation-margin path (apply_packed) via early stopping.
    from h2o3_tpu.models.tree.gbm import GBM

    vr = np.random.default_rng(11)
    Xv = vr.standard_normal((256, 4))
    yv = np.where(vr.random(256) < 1 / (1 + np.exp(-(2.0 * Xv[:, 0] - Xv[:, 1]))),
                  "Y", "N")
    vfr = Frame.from_numpy(Xv, names=["a", "b", "c", "d"])
    vfr.add("y", Column.from_numpy(yv, ctype="enum"))
    gm = GBM(ntrees=8, max_depth=3, seed=2, stopping_rounds=2,
             score_tree_interval=2).train(y="y", training_frame=fr,
                                          validation_frame=vfr)
    gauc = float(gm._output.training_metrics.auc)
    assert np.isfinite(gauc) and gauc > 0.8, gauc
    assert gm._output.validation_metrics is not None
    assert any("validation_deviance" in h for h in gm._output.scoring_history)
    gp = gm.predict(fr)
    gs = float(gp.col("Y").data.sum())
    assert np.isfinite(gs)

    # cross-process DKV control plane (round-2 weakness W4): keys announce
    # cloud-wide over the coordination-service KV; small host objects opt
    # into payload replication and any process can fetch them
    from h2o3_tpu.core.dkv import DKV

    assert DKV.publish(m.key)          # metadata announce (distributed mode)
    if pid == 0:
        cfg = {"alpha": 0.5, "origin": 0}
        DKV.put("shared_cfg", cfg)
        DKV.publish("shared_cfg", cfg, replicate=True)
    else:
        assert not DKV.contains("shared_cfg")      # not local before fetch
    cfg = DKV.fetch_remote("shared_cfg", timeout_ms=60000)
    assert cfg is not None and cfg["alpha"] == 0.5, cfg
    gk = DKV.global_keys()
    assert "shared_cfg" in gk and str(m.key) in gk

    # heartbeat table (water/HeartBeatThread analog): both processes beat,
    # health shows 2 live rows
    import time as _time

    from h2o3_tpu.core import failure

    assert failure.heartbeat()
    deadline = _time.time() + 30
    while _time.time() < deadline:
        health = failure.cluster_health()
        if len(health) >= 2:
            break
        _time.sleep(0.25)
    assert len(health) >= 2 and all(r["healthy"] for r in health), health
    # REST across the process boundary (round-3 weakness W6): the
    # coordinator serves HTTP; its handlers broadcast each op over the
    # oplog control plane (parallel/oplog.py) and the follower replays
    # them — so a REST-initiated parse/train/predict runs the SAME
    # shard_map collectives on every process of the cloud.
    import json as _json
    import urllib.request as _rq

    from h2o3_tpu.parallel import oplog

    csvp = f"/tmp/h2o3_mp_rest_{port}.csv"
    if pid == 0:
        rng2 = np.random.default_rng(3)
        with open(csvp, "w") as f:
            f.write("a,b,yy\n")
            for i in range(400):
                a, b = rng2.normal(), rng2.normal()
                pr = 1 / (1 + np.exp(-(1.5 * a - b)))
                f.write(f"{a:.5f},{b:.5f},{'YN'[int(rng2.random() < pr)]}\n")

        from h2o3_tpu.api.server import start_server

        srv = start_server(port=0)
        base = f"http://127.0.0.1:{srv.port}"

        def post(path, data):
            body = "&".join(f"{k}={_rq.quote(str(v))}"
                            for k, v in data.items()).encode()
            req = _rq.Request(base + path, data=body, method="POST")
            with _rq.urlopen(req, timeout=120) as r:
                return _json.loads(r.read())

        def wait_job(key):
            for _ in range(600):
                with _rq.urlopen(f"{base}/3/Jobs/{_rq.quote(key, safe='')}",
                                 timeout=60) as r:
                    j = _json.loads(r.read())["jobs"][0]
                if j["status"] in ("DONE", "FAILED", "CANCELLED"):
                    assert j["status"] == "DONE", j
                    return
                _time.sleep(0.1)
            raise AssertionError("job hung")

        out = post("/3/Parse", {"source_frames": f'["{csvp}"]',
                                "destination_frame": "mp_rest.hex"})
        wait_job(out["job"]["key"]["name"])
        out = post("/3/ModelBuilders/gbm", {
            "training_frame": "mp_rest.hex", "response_column": "yy",
            "ntrees": 3, "max_depth": 3, "seed": 5,
            "model_id": "mp_rest_gbm"})
        wait_job(out["job"]["key"]["name"])
        post("/3/Predictions/models/mp_rest_gbm/frames/mp_rest.hex", {})
        oplog.publish("shutdown", {})
        srv.stop()
        rest_ops = 3
    else:
        rest_ops = oplog.follower_loop(idle_timeout_s=180)
        assert rest_ops == 3, rest_ops
    from h2o3_tpu.core.dkv import DKV as _DKV

    rfr = _DKV.get("mp_rest.hex")
    assert rfr is not None and rfr.nrows == 400
    rmodel = _DKV.get("mp_rest_gbm")
    assert rmodel is not None
    rauc = float(rmodel._output.training_metrics.auc)
    assert np.isfinite(rauc) and rauc > 0.7, rauc

    print(f"proc {pid}: OK auc={auc:.4f} gbm_auc={gauc:.4f} "
          f"dkv_keys={len(gk)} rest_ops={rest_ops} rest_auc={rauc:.4f}",
          flush=True)


if __name__ == "__main__":
    main()
