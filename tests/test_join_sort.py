"""Device sort-merge join + shard-aware sample sort vs pandas ground truth.

Reference: water/rapids/RadixOrder.java:20 (MSB radix + splitters),
BinaryMerge.java (sorted-side matching). VERDICT r2 task #7 acceptance:
a large inner join on the 8-device mesh, correctness vs pandas.
"""

import numpy as np
import pandas as pd
import pytest

from h2o3_tpu.core.frame import Column, Frame
from h2o3_tpu.ops.merge import merge


def _to_pd(fr):
    return fr.to_pandas()


def _cmp_join(lfr, rfr, ldf, rdf, on, how, **kw):
    got = _to_pd(merge(lfr, rfr, **kw)).sort_values(
        on + [c for c in ldf.columns if c not in on])[
        lambda d: sorted(d.columns)].reset_index(drop=True)
    want = ldf.merge(rdf, on=on, how=how).sort_values(
        on + [c for c in ldf.columns if c not in on])[
        lambda d: sorted(d.columns)].reset_index(drop=True)
    assert len(got) == len(want), (len(got), len(want))
    for c in want.columns:
        g = got[c].to_numpy()
        w = want[c].to_numpy()
        if w.dtype.kind in "fc":
            np.testing.assert_allclose(
                np.sort(g.astype(float)), np.sort(w.astype(float)),
                atol=1e-5, equal_nan=True)
        else:
            assert sorted(map(str, g.tolist())) == sorted(map(str, w.tolist()))


@pytest.fixture()
def joinset(cl):
    rng = np.random.default_rng(3)
    nl, nr = 700, 500
    lk = rng.integers(0, 200, nl).astype(float)
    rk = rng.integers(0, 200, nr).astype(float)
    # one-sided NA key only: pandas merges NaN==NaN, H2O does not — the
    # H2O no-NA-match semantics get their own test below
    lk[5] = np.nan
    lfr = Frame()
    lfr.add("k", Column.from_numpy(lk))
    lfr.add("lv", Column.from_numpy(rng.normal(size=nl)))
    rfr = Frame()
    rfr.add("k", Column.from_numpy(rk))
    rfr.add("rv", Column.from_numpy(rng.normal(size=nr)))
    ldf = pd.DataFrame({"k": lk, "lv": np.asarray(lfr.col("lv").to_numpy(),
                                                  float)})
    rdf = pd.DataFrame({"k": rk, "rv": np.asarray(rfr.col("rv").to_numpy(),
                                                  float)})
    return lfr, rfr, ldf, rdf


def test_inner_join(joinset):
    lfr, rfr, ldf, rdf = joinset
    _cmp_join(lfr, rfr, ldf, rdf, ["k"], "inner")


def test_left_join(joinset):
    lfr, rfr, ldf, rdf = joinset
    _cmp_join(lfr, rfr, ldf, rdf, ["k"], "left", all_x=True)


def test_right_join(joinset):
    lfr, rfr, ldf, rdf = joinset
    _cmp_join(lfr, rfr, ldf, rdf, ["k"], "right", all_y=True)


def test_full_join(joinset):
    lfr, rfr, ldf, rdf = joinset
    _cmp_join(lfr, rfr, ldf, rdf, ["k"], "outer", all_x=True, all_y=True)


def test_na_keys_never_match(cl):
    """H2O semantics (BinaryMerge): NA join keys match NOTHING — including
    the other side's NAs (pandas differs: it merges NaN with NaN)."""
    lfr = Frame()
    lfr.add("k", Column.from_numpy(np.array([1.0, np.nan])))
    lfr.add("lv", Column.from_numpy(np.array([10.0, 20.0])))
    rfr = Frame()
    rfr.add("k", Column.from_numpy(np.array([np.nan, 1.0])))
    rfr.add("rv", Column.from_numpy(np.array([7.0, 8.0])))
    inner = merge(lfr, rfr)
    assert inner.nrows == 1
    assert float(np.asarray(inner.col("rv").to_numpy())[0]) == 8.0
    full = merge(lfr, rfr, all_x=True, all_y=True)
    assert full.nrows == 3               # match + left-NA row + right-NA row


def test_multikey_join(cl):
    rng = np.random.default_rng(5)
    nl, nr = 400, 300
    l1 = rng.integers(0, 12, nl).astype(float)
    l2 = rng.integers(0, 9, nl).astype(float)
    r1 = rng.integers(0, 12, nr).astype(float)
    r2 = rng.integers(0, 9, nr).astype(float)
    lfr = Frame()
    lfr.add("a", Column.from_numpy(l1))
    lfr.add("b", Column.from_numpy(l2))
    lfr.add("lv", Column.from_numpy(np.arange(nl, dtype=float)))
    rfr = Frame()
    rfr.add("a", Column.from_numpy(r1))
    rfr.add("b", Column.from_numpy(r2))
    rfr.add("rv", Column.from_numpy(np.arange(nr, dtype=float)))
    ldf = pd.DataFrame({"a": l1, "b": l2, "lv": np.arange(nl, dtype=float)})
    rdf = pd.DataFrame({"a": r1, "b": r2, "rv": np.arange(nr, dtype=float)})
    _cmp_join(lfr, rfr, ldf, rdf, ["a", "b"], "inner")


def test_categorical_key_join_disjoint_domains(cl):
    """Domains interned in different orders on the two sides must still join
    by LABEL (union-domain remap)."""
    lfr = Frame()
    lfr.add("g", Column.from_numpy(np.array(["a", "b", "c", "a"]), ctype="enum"))
    lfr.add("lv", Column.from_numpy(np.arange(4.0)))
    rfr = Frame()
    rfr.add("g", Column.from_numpy(np.array(["c", "d", "a"]), ctype="enum"))
    rfr.add("rv", Column.from_numpy(np.array([10.0, 20.0, 30.0])))
    out = _to_pd(merge(lfr, rfr))
    got = sorted(zip(out["g"], out["rv"]))
    assert got == [("a", 30.0), ("a", 30.0), ("c", 10.0)]


def test_large_mesh_join_vs_pandas(cl):
    """The VERDICT acceptance shape (scaled to CI budget): a large inner
    join on the 8-device mesh, exact row-count and aggregate parity."""
    rng = np.random.default_rng(11)
    n = 200_000
    lk = rng.integers(0, 50_000, n).astype(float)
    rk = rng.integers(0, 50_000, n).astype(float)
    lfr = Frame()
    lfr.add("k", Column.from_numpy(lk))
    lfr.add("lv", Column.from_numpy(np.ones(n)))
    rfr = Frame()
    rfr.add("k", Column.from_numpy(rk))
    rfr.add("rv", Column.from_numpy(np.full(n, 2.0)))
    out = merge(lfr, rfr)
    want = pd.DataFrame({"k": lk}).merge(pd.DataFrame({"k": rk}), on="k")
    assert out.nrows == len(want)
    s = float(np.asarray(out.col("rv").to_numpy()).sum())
    assert s == 2.0 * len(want)


def test_sample_sort_matches_numpy(cl):
    from h2o3_tpu.ops.sort import sample_sort_order
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from h2o3_tpu.core.runtime import cluster

    cl_ = cluster()
    rng = np.random.default_rng(0)
    n = 64_000
    x = rng.normal(size=n).astype(np.float32)
    x[::97] = np.nan                     # NAs sort last
    key = jax.device_put(jnp.asarray(x), NamedSharding(cl_.mesh, P("rows")))
    order = sample_sort_order(key, n)
    assert len(order) == n and len(set(order.tolist())) == n
    got = x[order]
    finite = got[~np.isnan(got)]
    assert (np.diff(finite) >= 0).all()
    assert np.isnan(got[len(finite):]).all()


def test_sort_frame_sample_path(cl, monkeypatch):
    import h2o3_tpu.ops.sort as S

    monkeypatch.setattr(S, "SAMPLE_SORT_MIN_ROWS", 1000)
    rng = np.random.default_rng(2)
    n = 30_000
    fr = Frame()
    fr.add("k", Column.from_numpy(rng.normal(size=n)))
    fr.add("v", Column.from_numpy(np.arange(n, dtype=float)))
    out = S.sort_frame(fr, "k")
    k = np.asarray(out.col("k").to_numpy())
    assert (np.diff(k) >= 0).all()
    # permutation integrity: every original row appears once
    v = np.asarray(out.col("v").to_numpy())
    assert len(set(v.astype(int).tolist())) == n
