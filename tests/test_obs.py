"""Observability plane (ISSUE 8): metrics registry + cluster aggregation,
trace-span propagation across the oplog, flight recorder, profiler REST.

Cheap tier by design (the satellite pins this suite to conftest's
cheap-first phase): no model training here — the fused-scoring span-tree
and /3/Metrics-over-REST assertions that need a trained forest ride
tests/test_sharded_frame.py's existing REST test (same heavy-tail slot).
Cross-process behavior is driven on the supervision tier's mem_cloud
harness (dict KV + monkeypatched 2-process topology): deterministic, no
gloo."""

import json
import re

import pytest

from h2o3_tpu.core import failure
from h2o3_tpu.obs import flight, metrics, tracing
from h2o3_tpu.parallel import distributed as D
from h2o3_tpu.parallel import oplog, supervisor
from h2o3_tpu.utils import timeline

pytestmark = pytest.mark.obs


@pytest.fixture()
def mem_cloud(monkeypatch):
    """Simulated 2-process cloud (the test_supervision harness shape)."""
    with D.memory_kv() as kv:
        monkeypatch.setattr(D, "process_count", lambda: 2)
        monkeypatch.setattr(D, "is_coordinator", lambda: True)
        monkeypatch.setenv("H2O_TPU_RETRY_BASE_MS", "1")
        monkeypatch.setenv("H2O_TPU_OP_ACK_TIMEOUT_S", "30")
        monkeypatch.setenv("H2O_TPU_OPLOG_CHECKPOINT_OPS", "0")
        monkeypatch.setenv("H2O_TPU_AUTO_RECOVER", "0")
        failure.set_incarnation(0)
        D.reset_leadership()
        oplog._DEMOTED = False
        oplog.reset()
        supervisor.reset()
        yield kv
    failure.set_incarnation(0)
    D.reset_leadership()
    oplog._DEMOTED = False
    oplog.reset()
    supervisor.reset()


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------

_PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z0-9_]+=\"[^\"]*\""
    r"(,[a-zA-Z0-9_]+=\"[^\"]*\")*\})? -?\S+$")


class TestRegistry:
    def test_names_and_duplicate_registration(self):
        with pytest.raises(ValueError):
            metrics.Registry().counter("Bad-Name", "x")
        r = metrics.Registry()
        r.counter("h2o3_t_dup", "x")
        with pytest.raises(ValueError):
            r.counter("h2o3_t_dup", "again")

    def test_inc_observe_and_unknown_names_never_raise(self):
        metrics.inc("h2o3_rest_requests_total", status="2xx")
        metrics.observe("h2o3_rest_request_seconds", 0.01)
        metrics.inc("h2o3_no_such_metric")         # silently dropped
        snap = {m["name"]: m for m in metrics.REGISTRY.snapshot()}
        vals = {tuple(sorted(s["labels"].items())): s["value"]
                for s in snap["h2o3_rest_requests_total"]["samples"]}
        assert vals[(("status", "2xx"),)] >= 1
        h = snap["h2o3_rest_request_seconds"]["samples"][0]
        assert h["count"] >= 1 and h["sum"] > 0

    def test_label_cardinality_bounded(self):
        r = metrics.Registry()
        m = r.counter("h2o3_t_cardinality", "x")
        for i in range(200):
            m.inc(model=f"m{i}")
        assert len(m._values) <= metrics._LABEL_CAP + 1
        snap = m.snapshot()
        overflow = [s for s in snap["samples"]
                    if s["labels"].get("overflow") == "true"]
        assert overflow and overflow[0]["value"] > 0

    def test_default_registry_has_twenty_plus_series(self):
        assert len(metrics.REGISTRY.names()) >= 20
        for name in metrics.REGISTRY.names():
            assert metrics.NAME_RE.match(name), name

    def test_prometheus_text_is_valid_exposition(self):
        text = metrics.prometheus_text(
            metrics.aggregate([{"metrics": metrics.REGISTRY.snapshot()}]))
        names = set()
        for ln in text.splitlines():
            if not ln.strip():
                continue
            if ln.startswith("#"):
                assert ln.startswith("# HELP ") or ln.startswith("# TYPE ")
                continue
            assert _PROM_LINE.match(ln), ln
            names.add(re.split(r"[{ ]", ln, 1)[0])
        assert len(names) >= 20

    def test_broken_collector_degrades_one_series_not_the_scrape(self):
        r = metrics.Registry()
        r.counter_fn("h2o3_t_broken", "x",
                     lambda: (_ for _ in ()).throw(RuntimeError("boom")))
        r.counter("h2o3_t_fine", "x").inc()
        snap = {m["name"]: m for m in r.snapshot()}
        assert snap["h2o3_t_broken"]["samples"] == []
        assert snap["h2o3_t_fine"]["samples"][0]["value"] == 1


class TestClusterAggregation:
    def test_kv_published_snapshots_sum_with_live(self, mem_cloud):
        """The coordinator's cluster view = its LIVE registry + every
        other process's KV-published snapshot; counters sum (the
        per-process data_plane counters are the satellite's example)."""
        from h2o3_tpu.core import sharded_frame

        def dp_packed(series):
            m = next(s for s in series
                     if s["name"] == "h2o3_data_plane_packed_rows_total")
            return sum(s["value"] for s in m["samples"])

        live0 = dp_packed(metrics.aggregate(
            [{"metrics": metrics.REGISTRY.snapshot()}]))
        # "process 1" publishes its snapshot (same registry — what matters
        # is that the coordinator merges the KV row it did NOT serve live)
        sharded_frame.note_packed(70)
        assert metrics.publish_snapshot(proc=1)
        sharded_frame.note_packed(30)         # coordinator-local growth
        total = dp_packed(metrics.cluster_aggregate())
        assert total == pytest.approx((live0 + 70) + (live0 + 100))

    def test_own_kv_row_is_not_double_counted(self, mem_cloud):
        metrics.publish_snapshot()            # proc 0 == this process
        series = metrics.cluster_aggregate()
        m = next(s for s in series
                 if s["name"] == "h2o3_process_uptime_seconds")
        assert len(m["samples"]) == 1         # live snapshot only


# ---------------------------------------------------------------------------
# trace spans: publish -> replay -> ack in ONE tree across the oplog
# ---------------------------------------------------------------------------

class TestSpanPropagation:
    def test_span_is_noop_without_active_trace(self):
        before = len(tracing.recent_traces(500))
        with tracing.span("pack") as sp:
            assert not sp and tracing.context() is None
        assert len(tracing.recent_traces(500)) == before

    def test_mirrored_op_yields_one_span_tree(self, mem_cloud):
        """A mirrored op on the mem_cloud: the coordinator publishes under
        an ingress trace, the follower replays + acks — and all of it
        lands in ONE tree (ingress -> oplog.publish -> oplog.replay ->
        oplog.ack), the replay/ack spans having crossed the KV."""
        with tracing.root_span("ingress", path="/test") as root:
            tid = root.span["trace_id"]
            seq = oplog.publish("noop", {})
        oplog.publish("shutdown", {})
        oplog.follower_loop(idle_timeout_s=5.0)
        spans = tracing.get_trace(tid)
        by_name = {s["name"]: s for s in spans}
        assert {"ingress", "oplog.publish", "oplog.replay",
                "oplog.ack"} <= set(by_name)
        pub, rep, ack = (by_name["oplog.publish"], by_name["oplog.replay"],
                         by_name["oplog.ack"])
        assert pub["parent_id"] == by_name["ingress"]["span_id"]
        assert rep["parent_id"] == pub["span_id"]
        assert ack["parent_id"] == rep["span_id"]
        assert rep["attrs"]["seq"] == seq
        # the follower-side spans crossed the KV (remote_span publishes)
        assert any(k.startswith(f"obs/span/{tid}/") for k in mem_cloud)
        # and the tree nests accordingly
        tree = tracing.span_tree(spans)
        assert tree[0]["name"] == "ingress"
        assert tree[0]["children"][0]["name"] == "oplog.publish"
        assert tree[0]["children"][0]["children"][0]["name"] == \
            "oplog.replay"

    def test_untraced_op_record_carries_no_trace(self, mem_cloud):
        oplog.publish("noop", {})
        rec = json.loads(mem_cloud["oplog/0"])
        assert "trace" not in rec

    def test_store_is_bounded(self, monkeypatch):
        monkeypatch.setenv("H2O_TPU_OBS_TRACE_CAP", "4")
        tracing.clear()
        tids = []
        for i in range(8):
            with tracing.root_span(f"t{i}") as r:
                tids.append(r.span["trace_id"])
        alive = [t for t in tids
                 if tracing.get_trace(t, include_remote=False)]
        assert len(alive) <= 4 and tids[-1] in alive


# ---------------------------------------------------------------------------
# timeline satellite: reserved keys win over caller meta
# ---------------------------------------------------------------------------

class TestTimelineReservedKeys:
    def test_meta_cannot_clobber_reserved_keys(self):
        timeline.clear()
        timeline.record("scoring", "w", ms=1.0, **{"time_ms": -5,
                                                   "rows": 3})
        ev = timeline.events()[-1]
        assert ev["time_ms"] > 0            # real timestamp intact
        assert ev["meta_time_ms"] == -5     # caller meta kept, prefixed
        assert ev["rows"] == 3              # non-colliding meta unprefixed
        assert ev["ms"] == 1.0

    def test_kind_enumeration_is_exported(self):
        assert "scoring" in timeline.KINDS and "flight" in timeline.KINDS


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

class TestFlightRecorder:
    def test_record_roundtrip_and_gc(self, tmp_path, monkeypatch):
        monkeypatch.setenv("H2O_TPU_OBS_FLIGHT_DIR", str(tmp_path))
        monkeypatch.setenv("H2O_TPU_OBS_FLIGHT_KEEP", "3")
        timeline.record("scoring", "evidence", rows=1)
        paths = [flight.record_flight(f"unit_reason_{i}", extra={"i": i})
                 for i in range(5)]
        assert all(paths)
        recs = flight.list_records()
        assert len(recs) == 3               # GC kept the newest 3
        body = json.loads(flight.read_record(recs[0]["name"]))
        assert body["reason"].startswith("unit_reason")
        assert any(e.get("what") == "evidence" for e in body["timeline"])
        assert isinstance(body["metrics"], list) and body["metrics"]

    def test_unsafe_names_refused(self, tmp_path, monkeypatch):
        monkeypatch.setenv("H2O_TPU_OBS_FLIGHT_DIR", str(tmp_path))
        assert flight.read_record("../../../etc/passwd") is None
        assert flight.read_record("nope.json") is None

    def test_forced_watchdog_recovery_leaves_a_record(
            self, mem_cloud, tmp_path, monkeypatch):
        """ISSUE 8 acceptance: a forced watchdog recovery action produces
        a flight record, and it is listed. Same drill as the bench
        `recover` stage: dead recorded leader, this process's watchdog
        wins the election."""
        import time as _t

        from h2o3_tpu.parallel import watchdog

        monkeypatch.setenv("H2O_TPU_OBS_FLIGHT_DIR", str(tmp_path))
        monkeypatch.setenv("H2O_TPU_AUTO_RECOVER", "1")
        monkeypatch.setenv("H2O_TPU_ELECTION_GRACE_S", "0.1")
        monkeypatch.setenv("H2O_TPU_HEARTBEAT_STALE_S", "0.5")
        monkeypatch.setattr(D, "is_coordinator",
                            lambda: D.leader() == 0 and D.epoch() > 0)
        D.write_epoch_record(0, 1)          # process 1 led ...
        D.set_leader(1, 0)                  # ... and is long dead
        mem_cloud["h2o3/heartbeat/1"] = json.dumps(
            {"ts": _t.time() - 999, "proc": 1})
        failure.heartbeat()
        watchdog.reset()
        wd = watchdog.Watchdog(interval=3600, follow=False)
        tag = wd.tick()
        assert tag == "elected", tag
        recs = flight.list_records()
        assert recs and recs[0]["reason"] == "watchdog_election"


# ---------------------------------------------------------------------------
# /3/Metrics + /3/Profiler over the wire (single-process server)
# ---------------------------------------------------------------------------

class TestObsRest:
    def test_metrics_trace_list_and_profiler_routes(self, cl, tmp_path):
        import urllib.request

        from h2o3_tpu.api.server import start_server

        srv = start_server(port=0)
        try:
            base = f"http://127.0.0.1:{srv.port}"
            with urllib.request.urlopen(base + "/3/Metrics",
                                        timeout=30) as r:
                assert r.headers["Content-Type"].startswith("text/plain")
                text = r.read().decode()
            series = {ln.split("{")[0].split(" ")[0]
                      for ln in text.splitlines()
                      if ln.strip() and not ln.startswith("#")}
            assert len(series) >= 20
            with urllib.request.urlopen(base + "/3/Metrics?format=json",
                                        timeout=30) as r:
                mj = json.loads(r.read())
            assert mj["__meta"]["schema_name"] == "MetricsV3"
            assert mj["series_count"] >= 20
            with urllib.request.urlopen(base + "/3/Trace", timeout=30) as r:
                assert "traces" in json.loads(r.read())
            # profiler start -> stop writes an XLA trace dir
            pdir = str(tmp_path / "prof")
            req = urllib.request.Request(
                base + "/3/Profiler/start",
                data=json.dumps({"dir": pdir}).encode(),
                headers={"Content-Type": "application/json"}, method="POST")
            with urllib.request.urlopen(req, timeout=30) as r:
                assert json.loads(r.read())["status"] == "capturing"
            # double-start refused with 409 while capturing
            import urllib.error

            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(urllib.request.Request(
                    base + "/3/Profiler/start", data=b"", method="POST"),
                    timeout=30)
            assert ei.value.code == 409
            req = urllib.request.Request(base + "/3/Profiler/stop",
                                         data=b"", method="POST")
            with urllib.request.urlopen(req, timeout=30) as r:
                out = json.loads(r.read())
            assert out["status"] == "stopped" and out["captured_ms"] >= 0
            import os

            assert os.path.isdir(pdir) and os.listdir(pdir)
            # stop with nothing running is a clean 400
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(urllib.request.Request(
                    base + "/3/Profiler/stop", data=b"", method="POST"),
                    timeout=30)
            assert ei.value.code == 400
        finally:
            srv.stop()
