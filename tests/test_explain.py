"""Explanation suite: PDP, TreeSHAP contributions, feature interactions,
multi-model matrices.

Reference: hex/PartialDependence.java, genmodel algos/tree/TreeSHAP.java
(local accuracy: contributions + bias == raw margin), hex/tree
FeatureInteraction, h2o-py explanation/_explain.py.
"""

import numpy as np
import pytest

from h2o3_tpu import explain
from h2o3_tpu.core.frame import Column, Frame
from h2o3_tpu.models.glm import GLM
from h2o3_tpu.models.tree.drf import DRF
from h2o3_tpu.models.tree.gbm import GBM


@pytest.fixture(scope="module")
def setup(cl):
    rng = np.random.default_rng(5)
    n = 800
    x1 = rng.standard_normal(n)
    x2 = rng.standard_normal(n)
    g = np.array(["a", "b"], object)[rng.integers(0, 2, n)]
    logit = 2.0 * x1 + 0.5 * x2 * (g == "a")
    y = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "Y", "N")
    fr = Frame()
    fr.add("x1", Column.from_numpy(x1))
    fr.add("x2", Column.from_numpy(x2))
    fr.add("g", Column.from_numpy(g, ctype="enum"))
    fr.add("y", Column.from_numpy(y, ctype="enum"))
    gbm = GBM(ntrees=10, max_depth=3, seed=1).train(y="y", training_frame=fr)
    return fr, gbm


class TestPDP:
    def test_tables_and_monotonicity(self, setup):
        fr, gbm = setup
        tables = gbm.partial_plot(fr, cols=["x1", "g"], nbins=8)
        assert [t["column"] for t in tables] == ["x1", "g"]
        t1 = tables[0]
        assert len(t1["values"]) == 8
        # response is P(Y): must rise with x1 (the dominant positive effect)
        assert t1["mean_response"][-1] > t1["mean_response"][0] + 0.3
        tg = tables[1]
        assert tg["values"] == ["a", "b"]

    def test_ice_row(self, setup):
        fr, gbm = setup
        tables = gbm.partial_plot(fr, cols=["x1"], nbins=5, row_index=3)
        assert len(tables[0]["mean_response"]) == 5
        assert all(s == 0.0 for s in tables[0]["stddev_response"])

    def test_2d(self, setup):
        fr, gbm = setup
        tabs = gbm.partial_plot(fr, col_pairs_2dpdp=[("x1", "g")], nbins=4)
        assert tabs[0]["columns"] == ("x1", "g")
        assert len(tabs[0]["rows"]) == 4 * 2


class TestTreeSHAP:
    def test_local_accuracy_gbm(self, setup):
        """Lundberg local accuracy: sum(phi) + bias == margin, per row."""
        fr, gbm = setup
        sub = 40
        from h2o3_tpu.ops.filters import take_rows

        fs = take_rows(fr, np.arange(sub))
        contribs = gbm.predict_contributions(fs)
        assert contribs.names == ["x1", "x2", "g", "BiasTerm"]
        mat = np.stack([contribs.col(c).to_numpy() for c in contribs.names], 1)
        total = mat.sum(axis=1)
        binned = gbm.spec.bin_columns(gbm.adapt_test(fs))
        margin = np.asarray(gbm.forest.predict_binned(binned))[:sub] + 0.0
        np.testing.assert_allclose(total, margin, atol=2e-3)
        # x1 drives the signal: its mean |phi| dominates
        ax1 = np.abs(contribs.col("x1").to_numpy()).mean()
        ax2 = np.abs(contribs.col("x2").to_numpy()).mean()
        assert ax1 > 3 * ax2

    def test_local_accuracy_drf_regression(self, cl):
        rng = np.random.default_rng(9)
        n = 400
        X = rng.standard_normal((n, 3))
        yv = 3 * X[:, 0] - X[:, 1] + rng.normal(0, 0.1, n)
        fr = Frame.from_numpy(X, names=["a", "b", "c"])
        fr.add("y", Column.from_numpy(yv))
        m = DRF(ntrees=5, max_depth=4, seed=2, sample_rate=1.0,
                mtries=3).train(y="y", training_frame=fr)
        from h2o3_tpu.ops.filters import take_rows

        fs = take_rows(fr, np.arange(25))
        contribs = m.predict_contributions(fs)
        mat = np.stack([contribs.col(c).to_numpy() for c in contribs.names], 1)
        binned = m.spec.bin_columns(m.adapt_test(fs))
        margin = np.asarray(m.forest.predict_binned(binned))[:25]
        np.testing.assert_allclose(mat.sum(axis=1), margin, atol=2e-3)

    def test_rejects_non_tree(self, setup):
        fr, _ = setup
        glm = GLM(family="binomial", seed=1).train(y="y", training_frame=fr)
        with pytest.raises(ValueError, match="tree model"):
            glm.predict_contributions(fr)


class TestFeatureInteraction:
    def test_ranked_table(self, setup):
        fr, gbm = setup
        rows = gbm.feature_interaction()
        assert rows and rows[0]["gain"] >= rows[-1]["gain"]
        singles = {r["interaction"] for r in rows if r["depth"] == 0}
        assert "x1" in singles
        # x2 only matters jointly with g: a pair row must exist
        pairs = {r["interaction"] for r in rows if r["depth"] == 1}
        assert pairs, rows[:5]

    def test_singleton_gain_exact(self, setup):
        """Singleton rows must sum exactly to the per-feature split gains
        (no path double counting)."""
        fr, gbm = setup
        rows = gbm.feature_interaction()
        f = gbm.forest
        expect = {}
        names = gbm._output.names
        for t in range(f.n_trees):
            for node in range(f.feat.shape[1]):
                ft = f.feat[t, node]
                if ft >= 0:
                    expect[names[ft]] = expect.get(names[ft], 0.0) \
                        + float(f.gain[t, node])
        got = {r["interaction"]: r["gain"] for r in rows if r["depth"] == 0}
        for k, v in expect.items():
            assert abs(got[k] - v) < 1e-6 * max(1.0, abs(v)), (k, got[k], v)


class TestMultiModel:
    def test_varimp_matrix_and_correlation(self, setup):
        fr, gbm = setup
        drf = DRF(ntrees=5, max_depth=5, seed=2).train(y="y", training_frame=fr)
        vm = explain.varimp_matrix([gbm, drf])
        assert vm["matrix"].shape == (len(vm["features"]), 2)
        assert "x1" in vm["features"]
        mc = explain.model_correlation([gbm, drf], fr)
        C = mc["matrix"]
        assert C.shape == (2, 2)
        np.testing.assert_allclose(np.diag(C), 1.0, atol=1e-6)
        assert C[0, 1] > 0.7      # both models learn the same signal


class TestExplainREST:
    def test_pdp_and_contributions_endpoints(self, setup):
        fr, gbm = setup
        fr.install()
        from h2o3_tpu import client
        from h2o3_tpu.api.server import start_server

        srv = start_server(port=0)
        try:
            client.connect(port=srv.port)
            body = client._req(
                "POST", "/3/PartialDependences",
                data={"model_id": str(gbm.key), "frame_id": str(fr.key),
                      "cols": '["x1"]', "nbins": "5"})
            dest = body["destination_key"]
            body = client._req("GET", f"/3/PartialDependences/{dest}")
            assert len(body["partial_dependence_data"]) == 1
            body = client._req(
                "POST", f"/3/Predictions/models/{gbm.key}/frames/{fr.key}",
                data={"predict_contributions": "true"})
            assert body["predictions_frame"]["name"]
            body = client._req(
                "POST", "/3/FeatureInteraction",
                data={"model_id": str(gbm.key), "max_interaction_depth": "2"})
            assert body["feature_interaction"]
        finally:
            srv.stop()


class TestNativeTreeSHAP:
    def test_native_matches_python(self, setup):
        """C++ walk must agree with the Python algorithm-of-record."""
        from h2o3_tpu.native.loader import native_treeshap

        fr, gbm = setup
        binned = np.asarray(gbm.spec.bin_columns(gbm.adapt_test(fr)))[:30]
        phi_native = native_treeshap(binned, gbm.forest)
        assert phi_native is not None, "native lib should build in this env"
        F = len(gbm._output.names)
        phi_py = np.zeros((30, F + 1), np.float64)
        from h2o3_tpu.explain import _shap_one_tree

        for t in range(gbm.forest.n_trees):
            for r in range(30):
                _shap_one_tree(binned[r], t, gbm.forest, phi_py[r])
        # differences are float-accumulation order only (observed ~2e-8)
        np.testing.assert_allclose(phi_native, phi_py, rtol=1e-5, atol=1e-7)

    def test_throughput_sane(self, setup):
        import time

        from h2o3_tpu.native.loader import native_treeshap

        fr, gbm = setup
        binned = np.asarray(gbm.spec.bin_columns(gbm.adapt_test(fr)))
        big = np.tile(binned, (5, 1))[:4000]
        t0 = time.perf_counter()
        phi = native_treeshap(big, gbm.forest)
        dt = time.perf_counter() - t0
        assert phi is not None and dt < 10.0, dt
