"""Checkpoint/resume training continuation.

Reference: hex/Model.java:365 (_checkpoint), :387 (_export_checkpoints_dir),
hex/util/CheckpointUtils.java (param compatibility), hex/tree/SharedTree.java
:131-134 (tree-count validation). resume(n1 then n2 total) must equal
train(n2) when the algorithm path is deterministic (no row/col sampling).
"""

import os

import numpy as np
import pytest

from h2o3_tpu.core.frame import Column, Frame
from h2o3_tpu.models.deeplearning import DeepLearning
from h2o3_tpu.models.model import Model
from h2o3_tpu.models.tree.drf import DRF
from h2o3_tpu.models.tree.gbm import GBM


def _frame(n=400, p=4, seed=7, nclasses=2):
    rng = np.random.default_rng(seed)
    fr = Frame()
    X = rng.standard_normal((n, p))
    for i in range(p):
        fr.add(f"x{i}", Column.from_numpy(X[:, i]))
    logit = 1.3 * X[:, 0] - 0.8 * X[:, 1] + 0.4 * X[:, 2]
    if nclasses == 2:
        y = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "Y", "N")
        fr.add("y", Column.from_numpy(y, ctype="enum"))
    elif nclasses > 2:
        y = (np.digitize(logit, np.quantile(logit, [0.33, 0.66]))).astype(int)
        fr.add("y", Column.from_numpy(np.array("abc")[y] if False else
                                      np.array(list("abc"))[y], ctype="enum"))
    else:
        fr.add("y", Column.from_numpy(logit + rng.normal(0, 0.1, n)))
    return fr


def _p1(model, fr):
    return model.predict(fr).col("Y").to_numpy()


class TestGBMCheckpoint:
    def test_resume_equals_fresh(self, cl):
        """No sampling ⇒ boosting is deterministic: 6+6 ≡ 12."""
        fr = _frame()
        a = GBM(ntrees=6, max_depth=3, learn_rate=0.3, seed=5).train(
            y="y", training_frame=fr)
        b = GBM(ntrees=12, max_depth=3, learn_rate=0.3, seed=5,
                checkpoint=a).train(y="y", training_frame=fr)
        c = GBM(ntrees=12, max_depth=3, learn_rate=0.3, seed=5).train(
            y="y", training_frame=fr)
        assert b.forest.n_trees == 12
        np.testing.assert_allclose(_p1(b, fr), _p1(c, fr), atol=1e-4)
        # resumed model strictly extends the checkpoint
        assert b._output.scoring_history[-1]["tree"] == 12

    def test_resume_by_key(self, cl):
        fr = _frame()
        a = GBM(ntrees=4, max_depth=3, seed=5).train(y="y", training_frame=fr)
        b = GBM(ntrees=8, max_depth=3, seed=5, checkpoint=str(a.key)).train(
            y="y", training_frame=fr)
        assert b.forest.n_trees == 8

    def test_multinomial_resume(self, cl):
        fr = _frame(nclasses=3)
        a = GBM(ntrees=4, max_depth=3, learn_rate=0.3, seed=5).train(
            y="y", training_frame=fr)
        b = GBM(ntrees=8, max_depth=3, learn_rate=0.3, seed=5,
                checkpoint=a).train(y="y", training_frame=fr)
        c = GBM(ntrees=8, max_depth=3, learn_rate=0.3, seed=5).train(
            y="y", training_frame=fr)
        assert b.forest.n_trees == 8 * 3
        pb = b.predict(fr).col("predict").to_numpy()
        pc = c.predict(fr).col("predict").to_numpy()
        assert np.mean(pb == pc) > 0.98

    def test_param_guards(self, cl):
        fr = _frame()
        a = GBM(ntrees=4, max_depth=3, seed=5).train(y="y", training_frame=fr)
        with pytest.raises(ValueError, match="cannot be modified"):
            GBM(ntrees=8, max_depth=5, seed=5, checkpoint=a).train(
                y="y", training_frame=fr)
        with pytest.raises(ValueError, match="must be greater"):
            GBM(ntrees=4, max_depth=3, seed=5, checkpoint=a).train(
                y="y", training_frame=fr)
        with pytest.raises(ValueError, match="cross-validation"):
            GBM(ntrees=8, max_depth=3, seed=5, nfolds=3, checkpoint=a).train(
                y="y", training_frame=fr)

    def test_validation_stopping_continues(self, cl):
        """Resume with a validation frame keeps scoring on it."""
        fr, va = _frame(seed=7), _frame(seed=11)
        a = GBM(ntrees=5, max_depth=3, seed=5).train(
            y="y", training_frame=fr, validation_frame=va)
        b = GBM(ntrees=10, max_depth=3, seed=5, checkpoint=a,
                score_each_iteration=True).train(
            y="y", training_frame=fr, validation_frame=va)
        hist = b._output.scoring_history
        assert hist[0]["tree"] == 6 and hist[-1]["tree"] == 10
        assert all("validation_deviance" in h for h in hist)


class TestDRFCheckpoint:
    def test_deterministic_resume_preserves_mean(self, cl):
        """With sample_rate=1 and mtries=F every tree is identical, so the
        5-tree and 10-tree averages must agree — this pins the leaf
        rescaling (prev/new tree-count weights) in the concat."""
        fr = _frame(nclasses=1)
        kw = dict(max_depth=4, sample_rate=1.0, mtries=4, min_rows=5.0, seed=3)
        a = DRF(ntrees=5, **kw).train(y="y", training_frame=fr)
        b = DRF(ntrees=10, checkpoint=a, **kw).train(y="y", training_frame=fr)
        assert b.forest.n_trees == 10
        pa = a.predict(fr).col("predict").to_numpy()
        pb = b.predict(fr).col("predict").to_numpy()
        np.testing.assert_allclose(pa, pb, atol=1e-4)

    def test_binomial_resume(self, cl):
        fr = _frame()
        kw = dict(max_depth=4, seed=3)
        a = DRF(ntrees=5, **kw).train(y="y", training_frame=fr)
        b = DRF(ntrees=10, checkpoint=a, **kw).train(y="y", training_frame=fr)
        assert b.forest.n_trees == 10
        pb = _p1(b, fr)
        assert np.all(np.isfinite(pb)) and pb.min() >= 0 and pb.max() <= 1
        assert float(b._output.training_metrics.auc) > 0.6


class TestDLCheckpoint:
    def test_resume_continues_epochs(self, cl):
        fr = _frame()
        kw = dict(hidden=[16], mini_batch_size=64, seed=9,
                  activation="Rectifier")
        a = DeepLearning(epochs=3, **kw).train(y="y", training_frame=fr)
        assert a.epochs_trained == 3
        b = DeepLearning(epochs=6, checkpoint=a, **kw).train(
            y="y", training_frame=fr)
        assert b.epochs_trained == 6
        # resumed training starts from a's weights: first resumed-epoch loss
        # must be ≤ a's FIRST epoch loss (training from scratch would not be)
        assert (b._output.scoring_history[0]["training_loss"]
                <= a._output.scoring_history[0]["training_loss"] + 1e-6)
        assert float(b._output.training_metrics.auc) > 0.5

    def test_param_guard(self, cl):
        fr = _frame()
        a = DeepLearning(epochs=2, hidden=[8], mini_batch_size=64,
                         seed=9).train(y="y", training_frame=fr)
        with pytest.raises(ValueError, match="cannot be modified"):
            DeepLearning(epochs=4, hidden=[16], mini_batch_size=64, seed=9,
                         checkpoint=a).train(y="y", training_frame=fr)
        with pytest.raises(ValueError, match="must be greater"):
            DeepLearning(epochs=2, hidden=[8], mini_batch_size=64, seed=9,
                         checkpoint=a).train(y="y", training_frame=fr)


class TestExportCheckpointsDir:
    def test_auto_export_and_reload(self, cl, tmp_path):
        fr = _frame()
        d = str(tmp_path / "ckpts")
        a = GBM(ntrees=4, max_depth=3, seed=5,
                export_checkpoints_dir=d).train(y="y", training_frame=fr)
        path = os.path.join(d, f"{a.key}.bin")
        assert os.path.exists(path)
        re = Model.load(path)
        np.testing.assert_allclose(_p1(a, fr), _p1(re, fr), atol=1e-6)
