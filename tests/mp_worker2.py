"""Second 2-process worker: op families beyond GLM/GBM (VERDICT r4 item 4).

Covers, across a REAL process boundary (2 procs × 2 virtual CPU devices):
  - device sample sort (ops/sort.py — the all_to_all path that can deadlock
    under multi-controller if programs diverge)
  - sort-merge join (ops/merge.py)
  - DeepLearning training (jax.grad MLP under shard_map)
  - Rapids over REST (coordinator broadcasts the AST, follower replays)
  - AutoML over REST (one deterministic 'automl' op; nested base-model
    programs line up because broadcast() is reentrancy-guarded)

Reference analog: the 4-JVM localhost cloud of multiNodeUtils.sh:22-27.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
# 2 local virtual CPU devices; jax<0.5 only honors the XLA flag (set before
# backend init), newer jax the config option — apply whichever exists
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2")

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:      # jax<0.5: the XLA flag above already did it
    pass
try:
    # jax<0.5 CPU backend needs gloo for cross-process collectives
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except (AttributeError, ValueError):
    pass

import numpy as np


def main():
    port, pid = sys.argv[1], int(sys.argv[2])
    from h2o3_tpu.parallel import distributed

    distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                           num_processes=2, process_id=pid)
    import h2o3_tpu
    from h2o3_tpu.core.frame import Column, Frame

    cl = h2o3_tpu.init()
    assert cl.n_devices == 4

    rng = np.random.default_rng(13)
    n = 512

    # --- device sample sort across the process boundary -------------------
    from h2o3_tpu.ops.sort import sort_frame

    xs = rng.standard_normal(n)
    fr = Frame.from_numpy(xs.reshape(-1, 1), names=["k"])
    fr.add("v", Column.from_numpy(np.arange(n, dtype=np.float64)))
    sfr = sort_frame(fr, "k")
    got = np.asarray(sfr.col("k").to_numpy(), dtype=np.float64)
    want = np.sort(xs)
    assert np.allclose(got, want, atol=1e-6), "sort mismatch across procs"
    # permutation column must follow the keys
    gv = np.asarray(sfr.col("v").to_numpy(), dtype=np.int64)
    assert np.array_equal(gv, np.argsort(xs, kind="stable")), "sort payload"

    # --- sort-merge join across the process boundary -----------------------
    from h2o3_tpu.ops.merge import merge

    lk = rng.integers(0, 50, n).astype(np.float64)
    rk = np.arange(50, dtype=np.float64)
    lfr = Frame.from_numpy(np.stack([lk, rng.standard_normal(n)], 1),
                           names=["id", "a"])
    rfr = Frame.from_numpy(np.stack([rk, rk * 10.0], 1), names=["id", "b"])
    jfr = merge(lfr, rfr)
    assert jfr.nrows == n, jfr.nrows
    jb = np.asarray(jfr.col("b").to_numpy(), dtype=np.float64)
    jid = np.asarray(jfr.col("id").to_numpy(), dtype=np.float64)
    assert np.allclose(jb, jid * 10.0), "join payload mismatch"

    # --- DeepLearning across the process boundary --------------------------
    from h2o3_tpu.models.deeplearning import DeepLearning

    X = rng.standard_normal((n, 4))
    logit = 2.0 * X[:, 0] - X[:, 1]
    y = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "Y", "N")
    dfr = Frame.from_numpy(X, names=["a", "b", "c", "d"])
    dfr.add("y", Column.from_numpy(y, ctype="enum"))
    dl = DeepLearning(hidden=[8], epochs=20, seed=3).train(
        y="y", training_frame=dfr)
    dauc = float(dl._output.training_metrics.auc)
    assert np.isfinite(dauc) and dauc > 0.7, dauc
    dp = dl.predict(dfr)
    assert np.isfinite(float(dp.col("Y").data.sum()))

    # --- REST tier: Rapids + AutoML broadcast over the oplog ----------------
    import json as _json
    import time as _time
    import urllib.request as _rq

    from h2o3_tpu.core.dkv import DKV
    from h2o3_tpu.parallel import oplog

    csvp = f"/tmp/h2o3_mp2_rest_{port}.csv"
    if pid == 0:
        rng2 = np.random.default_rng(5)
        with open(csvp, "w") as f:
            f.write("a,b,yy\n")
            for i in range(300):
                a, b = rng2.normal(), rng2.normal()
                pr = 1 / (1 + np.exp(-(1.5 * a - b)))
                f.write(f"{a:.5f},{b:.5f},{'YN'[int(rng2.random() < pr)]}\n")

        from h2o3_tpu.api.server import start_server

        srv = start_server(port=0)
        base = f"http://127.0.0.1:{srv.port}"

        def post(path, data, as_json=False):
            if as_json:
                body = _json.dumps(data).encode()
                req = _rq.Request(base + path, data=body, method="POST",
                                  headers={"Content-Type": "application/json"})
            else:
                body = "&".join(f"{k}={_rq.quote(str(v))}"
                                for k, v in data.items()).encode()
                req = _rq.Request(base + path, data=body, method="POST")
            with _rq.urlopen(req, timeout=180) as r:
                return _json.loads(r.read())

        def wait_job(key):
            for _ in range(1800):
                with _rq.urlopen(f"{base}/3/Jobs/{_rq.quote(key, safe='')}",
                                 timeout=60) as r:
                    j = _json.loads(r.read())["jobs"][0]
                if j["status"] in ("DONE", "FAILED", "CANCELLED"):
                    assert j["status"] == "DONE", j
                    return
                _time.sleep(0.1)
            raise AssertionError("job hung")

        out = post("/3/Parse", {"source_frames": f'["{csvp}"]',
                                "destination_frame": "mp2.hex"})
        wait_job(out["job"]["key"]["name"])
        # rapids op: derived column on every process via AST replay
        post("/99/Rapids",
             {"ast": "(assign mp2b.hex (* (cols mp2.hex [0]) 2))",
              "session_id": "mp2"})
        # AutoML: ONE deterministic op, nested model programs in lockstep
        out = post("/99/AutoMLBuilder", {
            "input_spec": {"training_frame": "mp2.hex",
                           "response_column": "yy"},
            "build_control": {"project_name": "mp2_aml",
                              "nfolds": 0,
                              "stopping_criteria": {"max_models": 2,
                                                    "seed": 11}},
            "build_models": {"include_algos": ["GLM", "GBM"]}}, as_json=True)
        wait_job(out["job"]["key"]["name"])
        oplog.publish("shutdown", {})
        srv.stop()
        rest_ops = 3
    else:
        rest_ops = oplog.follower_loop(idle_timeout_s=300)
        assert rest_ops == 3, rest_ops

    rfr = DKV.get("mp2.hex")
    assert rfr is not None and rfr.nrows == 300
    dfr2 = DKV.get("mp2b.hex")
    assert dfr2 is not None and dfr2.nrows == 300
    aml = DKV.get("mp2_aml")
    assert aml is not None and len(aml.models) >= 2, aml

    print(f"proc {pid}: OK sort/join/dl dl_auc={dauc:.4f} "
          f"rest_ops={rest_ops} aml_models={len(aml.models)}", flush=True)


if __name__ == "__main__":
    main()
