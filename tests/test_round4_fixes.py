"""Regression tests for round-4 advisor findings (ADVICE.md)."""

import numpy as np
import pytest

from h2o3_tpu.core.frame import Column, Frame


def _multi(n=600, seed=3):
    rng = np.random.default_rng(seed)
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    y = np.array(["a", "b", "c"])[
        np.argmax(np.column_stack([x1, x2, -x1 - x2]) +
                  rng.normal(0, .3, (n, 3)), axis=1)]
    fr = Frame()
    fr.add("x1", Column.from_numpy(x1))
    fr.add("x2", Column.from_numpy(x2))
    fr.add("y", Column.from_numpy(y, ctype="enum"))
    return fr


def test_drf_multinomial_deep_truncation_scale(cl, monkeypatch):
    """max_runtime_secs break in the deep multinomial path must divide
    leaves by trees BUILT, not trees requested (ADVICE round-4 #1)."""
    from h2o3_tpu.models.tree import drf as drf_mod

    calls = {"n": 0}

    def fake_oot(self):
        calls["n"] += 1
        return calls["n"] >= 2   # stop after 2 of 6 iterations

    monkeypatch.setattr(drf_mod.DRF, "_out_of_time", fake_oot)
    fr = _multi()
    m = drf_mod.DRF(ntrees=6, max_depth=12, seed=1).train(
        y="y", training_frame=fr)
    # class-indicator means sum to ~1 per iteration; with the correct
    # 1/total denominator the raw margin rows sum to ~1, with the buggy
    # 1/ntrees denominator they'd sum to ~built/ntrees = 1/3
    f = np.asarray(m._margin(fr))
    assert f.shape[1] == 3
    assert abs(float(np.mean(f.sum(axis=1))) - 1.0) < 0.15


def test_native_treeshap_depth_gate():
    """Forests deeper than the C++ unique-path buffer must fall back to
    Python TreeSHAP, not overflow the stack (ADVICE round-4 #2)."""
    from h2o3_tpu.native import loader

    class DeepForest:
        max_depth = 80

    out = loader.native_treeshap(np.zeros((1, 2), np.int32), DeepForest())
    assert out is None


def test_v4_contributions_size_cap(cl):
    """/4/Predictions with predict_contributions must enforce the same
    row cap as the sync v3 route (ADVICE round-4 #3)."""
    from h2o3_tpu.api import server as srv

    fake = type("F", (), {"nrows": 10_000_001, "nrow": 10_000_001,
                          "ncol": 3, "ncols": 3})()
    with pytest.raises(srv.ApiError):
        srv._check_contributions_size(fake)
    ok = type("F", (), {"nrows": 10, "nrow": 10, "ncol": 3, "ncols": 3})()
    srv._check_contributions_size(ok)   # under the cap: no raise


def test_file_backed_column_setter_clears_loader(tmp_path, cl):
    """Rebinding .data on a file-backed column must drop the disk loader so
    evict/fault-in keeps the new values (ADVICE round-4 #4)."""
    col = Column.from_numpy(np.arange(8, dtype=np.float64))
    col._loader = lambda: np.zeros(8)   # simulate file-backed source
    col.data = np.full(8, 7.0)
    col.evict()
    got = col.to_numpy()
    assert np.allclose(got, 7.0), "evict restored stale disk values"


def test_basic_auth_uses_constant_time_compare():
    import inspect

    from h2o3_tpu.api import server as srv

    src = inspect.getsource(srv)
    assert "compare_digest" in src
