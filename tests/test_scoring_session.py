"""Serving fast path (scoring.py): shape-bucketed fused scoring sessions.

Covers the ISSUE-2 acceptance bar: scoring requests with distinct row
counts against one trained GBM compiles at most len(buckets) traversal
programs (asserted with JAX's compilation counters), and padded rows never
leak — the bucketed path returns BITWISE-identical predictions to the
per-request unbatched path."""

import numpy as np
import pytest

from h2o3_tpu.core.frame import Column, Frame


def _train_frame(n=1500, seed=0, classes=2):
    rng = np.random.default_rng(seed)
    fr = Frame()
    x1 = rng.standard_normal(n)
    x2 = rng.standard_normal(n)
    g = np.array(["a", "b", "c"])[rng.integers(0, 3, n)]
    fr.add("x1", Column.from_numpy(x1))
    fr.add("x2", Column.from_numpy(x2))
    fr.add("g", Column.from_numpy(g, ctype="enum"))
    logit = 1.2 * x1 - x2 + (g == "a") * 0.5
    if classes == 2:
        y = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "Y", "N")
    else:
        y = np.array(["r", "s", "t"])[
            np.clip((logit + rng.normal(0, 0.5, n) + 1.5).astype(int), 0,
                    classes - 1)]
    fr.add("y", Column.from_numpy(y, ctype="enum"))
    return fr


def _score_frame(n, seed, with_nas=False):
    rng = np.random.default_rng(seed)
    fr = Frame()
    x1 = rng.standard_normal(n)
    x2 = rng.standard_normal(n)
    if with_nas:
        x1[:: 7] = np.nan
    fr.add("x1", Column.from_numpy(x1))
    fr.add("x2", Column.from_numpy(x2))
    fr.add("g", Column.from_numpy(
        np.array(["a", "b", "c"])[rng.integers(0, 3, n)], ctype="enum"))
    return fr


@pytest.fixture(scope="module")
def gbm(cl):
    from h2o3_tpu.models.tree.gbm import GBM

    return GBM(ntrees=8, max_depth=3, seed=1).train(
        y="y", training_frame=_train_frame())


def _assert_frames_bitwise(a, b, n):
    assert a.names == b.names
    for name in a.names:
        av = np.asarray(a.col(name).data)[:n]
        bv = np.asarray(b.col(name).data)[:n]
        assert np.array_equal(av, bv), (name, av[:5], bv[:5])


class TestCompileStability:
    SIZES = (17, 300, 1000, 4096, 9999)

    def test_at_most_len_buckets_traversal_traces(self, cl, gbm):
        """5 distinct request row counts → ≤ len(buckets) compiled
        programs, counted with JAX's own jit-lowering counter over the
        bucketed dispatch (the only jitted program on that path)."""
        import jax._src.test_util as jtu

        from h2o3_tpu import scoring

        sess = scoring.ScoringSession(gbm)      # fresh: nothing traced yet
        feats = {n: sess._features(gbm.adapt_test(_score_frame(n, n)), n)
                 for n in self.SIZES}
        with jtu.count_jit_and_pmap_lowerings() as lowerings:
            margins = {n: sess._margin_x(feats[n]) for n in self.SIZES}
        assert lowerings[0] <= len(sess.buckets), (lowerings[0], sess.buckets)
        assert sess.traversal_compiles <= len(sess.buckets)
        # margins are exact vs the unbatched binned traversal
        for n, mg in margins.items():
            ref = np.asarray(gbm._margin(gbm.adapt_test(_score_frame(n, n))))
            assert np.array_equal(mg[:n], ref[:n]), n

        # NEW row counts that land in warm buckets compile AND retrace
        # nothing — the per-request-shape jit cost is gone entirely
        feats2 = {n: sess._features(gbm.adapt_test(_score_frame(n, 99 + n)),
                                    n) for n in (60, 900, 2222)}
        with jtu.count_jit_and_pmap_lowerings() as lowerings, \
                jtu.count_jit_tracing_cache_miss() as misses:
            for n, x in feats2.items():
                sess._margin_x(x)
        assert lowerings[0] == 0, lowerings[0]
        assert misses[0] == 0, misses[0]

    def test_padded_rows_never_leak(self, cl, gbm):
        """Bucket padding must be invisible: bucketed predictions are
        bitwise-identical to the per-request unbatched path, including
        frames with NAs."""
        from h2o3_tpu import scoring

        sess = scoring.session_for(gbm)
        for n in self.SIZES:
            fr = _score_frame(n, n, with_nas=True)
            _assert_frames_bitwise(gbm.predict(fr), sess.predict(fr), n)


class TestBucketConfig:
    def test_env_buckets_and_chunking(self, cl, gbm, monkeypatch):
        """H2O_TPU_SCORE_BUCKETS overrides the ladder; requests above the
        largest bucket chunk at it instead of compiling new shapes."""
        from h2o3_tpu import scoring

        monkeypatch.setenv("H2O_TPU_SCORE_BUCKETS", "64,256")
        sess = scoring.ScoringSession(gbm)
        assert sess.buckets == (64, 256)
        fr = _score_frame(700, 5)     # 700 > 256 → 3 chunks of ≤256
        _assert_frames_bitwise(gbm.predict(fr), sess.predict(fr), 700)
        assert sess.traversal_compiles <= 2

    def test_bad_env_falls_back(self, cl, monkeypatch):
        from h2o3_tpu import scoring

        monkeypatch.setenv("H2O_TPU_SCORE_BUCKETS", "nope")
        assert scoring._env_buckets() == scoring._DEFAULT_BUCKETS


class TestModelFamilies:
    def test_multinomial_bitwise(self, cl):
        from h2o3_tpu import scoring
        from h2o3_tpu.models.tree.gbm import GBM

        m = GBM(ntrees=4, max_depth=3, seed=2).train(
            y="y", training_frame=_train_frame(seed=3, classes=3))
        assert scoring.supports(m)
        sess = scoring.session_for(m)
        fr = _score_frame(333, 11)
        _assert_frames_bitwise(m.predict(fr), sess.predict(fr), 333)

    def test_regression_bitwise(self, cl):
        from h2o3_tpu import scoring
        from h2o3_tpu.models.tree.gbm import GBM

        rng = np.random.default_rng(4)
        n = 1200
        fr = Frame()
        x = rng.standard_normal(n)
        fr.add("x1", Column.from_numpy(x))
        fr.add("x2", Column.from_numpy(rng.standard_normal(n)))
        fr.add("g", Column.from_numpy(
            np.array(["a", "b"])[rng.integers(0, 2, n)], ctype="enum"))
        fr.add("y", Column.from_numpy(2 * x + rng.normal(0, 0.1, n)))
        m = GBM(ntrees=5, max_depth=3, seed=2).train(y="y",
                                                     training_frame=fr)
        sess = scoring.session_for(m)
        tf = _score_frame(97, 7)
        _assert_frames_bitwise(m.predict(tf), sess.predict(tf), 97)

    def test_drf_supported_isofor_not(self, cl, gbm):
        from h2o3_tpu import scoring
        from h2o3_tpu.models.tree.drf import DRF
        from h2o3_tpu.models.tree.isofor import IsolationForest

        drf = DRF(ntrees=4, max_depth=4, seed=5).train(
            y="y", training_frame=_train_frame(seed=6))
        assert scoring.supports(drf)
        fr = _score_frame(150, 8)
        _assert_frames_bitwise(drf.predict(fr),
                               scoring.session_for(drf).predict(fr), 150)
        isf = IsolationForest(ntrees=4, max_depth=4, seed=5).train(
            training_frame=_score_frame(300, 9))
        # IsolationForest overrides _predict_raw (mean_length output) →
        # generic path, fast path refuses it
        assert not scoring.supports(isf)

    def test_kill_switch(self, cl, gbm, monkeypatch):
        from h2o3_tpu import scoring

        monkeypatch.setenv("H2O_TPU_SCORE_FAST", "0")
        assert not scoring.supports(gbm)


class _NoMeshCluster:
    """Cluster proxy whose global-mesh entry points trip an assertion:
    degraded-cloud local dispatch must never reach them (a sharded
    device_put / put_rows against the global mesh is an SPMD program a
    dead follower never joins)."""

    def __init__(self, cl):
        self._real = cl

    def pad_rows(self, n):                   # pure arithmetic: allowed
        return self._real.pad_rows(n)

    def row_sharding(self):
        raise AssertionError("local dispatch touched the global mesh "
                             "(row_sharding)")

    def put_rows(self, buf):
        raise AssertionError("local dispatch touched the global mesh "
                             "(put_rows)")


class TestDegradedLocalDispatch:
    def test_local_dispatch_never_touches_global_mesh(self, cl, gbm):
        """`local=True` (degraded-cloud serving) computes margins and raw
        predictions entirely on this process's devices — and stays
        bitwise-identical to the normal bucketed path."""
        from h2o3_tpu import scoring

        sess = scoring.ScoringSession(gbm)
        n = 300
        fr = _score_frame(n, 5, with_nas=True)
        X = sess._features(gbm.adapt_test(fr), n)
        ref_margin = sess._margin_x(X)
        ref_raw = sess._raw_for_slice(ref_margin, n)

        sess._cl = _NoMeshCluster(sess._cl)
        local_margin = sess._margin_x(X, local=True)
        assert np.array_equal(local_margin, ref_margin)
        raw = sess._raw_for_slice(local_margin, n, local=True)
        for k, ref in ref_raw.items():
            assert np.array_equal(np.asarray(raw[k])[:n],
                                  np.asarray(ref)[:n]), k

    def test_local_arrays_guard_non_addressable_model(self, cl, gbm):
        """Forest arrays the coordinator cannot fully read (shards homed on
        the dead peer) must refuse local serving with a clear error, not
        crash inside a host transfer."""
        from h2o3_tpu import scoring
        from h2o3_tpu.core.failure import CloudUnhealthyError

        sess = scoring.ScoringSession(gbm)

        class _Remote:                 # quacks like a non-addressable array
            is_fully_addressable = False

        sess._arrays = (_Remote(),)
        with pytest.raises(CloudUnhealthyError, match="forest arrays"):
            sess._local_arrays()


class TestSessionRegistry:
    def test_reuse_and_purge(self, cl, gbm):
        from h2o3_tpu import scoring

        s1 = scoring.session_for(gbm)
        assert scoring.session_for(gbm) is s1
        scoring.purge(str(gbm.key))
        assert scoring.session_for(gbm) is not s1

    def test_metrics_snapshot_shape(self, cl, gbm):
        from h2o3_tpu import scoring

        sess = scoring.session_for(gbm)
        sess.predict(_score_frame(40, 12))
        snap = [e for e in scoring.metrics_snapshot()
                if e["model"] == str(gbm.key)]
        assert snap and snap[0]["requests"] >= 1
        assert "p50_ms" in snap[0] and snap[0]["buckets"] == list(sess.buckets)
