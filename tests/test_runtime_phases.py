"""Runtime phase tracker + compile ledger (ISSUE 12).

Cheap tier by design: the ledger tests compile one scalar program, the
deadline drill is pure host machinery driven by the ``phases.deadline``
faultpoint (no device work — counter-asserted below, like PR 8's
tracing test), and the REST test reuses the running session cluster.
The "every fused compile lands in the ledger" integration evidence
rides the trained-forest suites (test_sharded_frame / test_artifact)
whose counters are now ledger views."""

import json
import time
import urllib.request

import pytest

from h2o3_tpu.core import failure
from h2o3_tpu.obs import compiles, flight, metrics, phases
from h2o3_tpu.utils import timeline

pytestmark = pytest.mark.obs


def _metric_value(name, **labels):
    m = metrics.REGISTRY.get(name)
    snap = m.snapshot()
    want = {str(k): str(v) for k, v in labels.items()}
    for s in snap["samples"]:
        if s["labels"] == want:
            return s["value"]
    return 0.0


# ---------------------------------------------------------------------------
# phase tracker
# ---------------------------------------------------------------------------

class TestPhases:
    def test_enumeration_is_closed(self):
        with pytest.raises(ValueError, match="closed"):
            with phases.enter("warp_drive_init"):
                pass

    def test_normal_phase_records_history_timeline_and_metrics(self):
        before_done = _metric_value("h2o3_phase_completed_total",
                                    phase="server_start")
        with phases.enter("server_start", port=0) as rec:
            assert rec["status"] == "running"
        hist = phases.history()
        mine = [r for r in hist if r["phase"] == "server_start"]
        assert mine and mine[-1]["status"] == "ok"
        assert mine[-1]["ms"] is not None and mine[-1]["ms"] >= 0
        evs = [e for e in timeline.events() if e["kind"] == "phase"
               and e["what"] == "server_start"]
        # begin event + completion event (with ms)
        assert any(e.get("status") == "begin" for e in evs)
        assert any(e.get("ms") is not None for e in evs)
        assert _metric_value("h2o3_phase_completed_total",
                             phase="server_start") == before_done + 1
        assert phases.phase_report().get("server_start") is not None

    def test_deadline_map_parsing(self, monkeypatch):
        monkeypatch.setenv("H2O_TPU_PHASE_DEADLINE_S",
                           "backend_init=45,first_compile=90,bogus=3")
        d = phases.deadlines()
        assert d == {"backend_init": 45.0, "first_compile": 90.0}
        monkeypatch.setenv("H2O_TPU_PHASE_DEADLINE_S", "12")
        assert phases.deadlines() == {p: 12.0 for p in phases.PHASES}
        monkeypatch.setenv("H2O_TPU_PHASE_DEADLINE_S", "not-a-number")
        assert phases.deadlines() == {}
        monkeypatch.delenv("H2O_TPU_PHASE_DEADLINE_S")
        assert phases.deadlines() == {}

    def test_wedged_backend_init_deadline_drill(self, tmp_path,
                                                monkeypatch):
        """The ISSUE-12 satellite: a faked wedged backend_init must leave
        a flight record NAMING the phase, engage the CPU fallback well
        inside the stage budget, and add zero device work (ledger rows
        and data-plane counters unchanged — the PR-8 counter-assertion
        style)."""
        from h2o3_tpu.core import sharded_frame

        monkeypatch.setenv("H2O_TPU_OBS_FLIGHT_DIR", str(tmp_path))
        monkeypatch.setenv("H2O_TPU_PHASE_DEADLINE_S", "backend_init=0.2")
        rows_before = len(compiles.ledger_rows())
        dp_before = sharded_frame.counters()
        exceeded_before = _metric_value(
            "h2o3_phase_deadline_exceeded_total", phase="backend_init")
        fb_before = _metric_value("h2o3_phase_cpu_fallbacks_total",
                                  phase="backend_init")
        engaged = []
        t0 = time.perf_counter()
        with failure.inject("phases.deadline"):
            with phases.enter(
                    "backend_init",
                    fallback=lambda name: engaged.append(
                        (name, time.perf_counter() - t0))):
                pass
        # the fallback engaged promptly after the 0.2 s deadline — not
        # after some stage-budget-sized timeout
        assert engaged and engaged[0][0] == "backend_init"
        assert engaged[0][1] < 2.0
        # the flight record names the wedged phase
        recs = flight.list_records()
        assert recs and recs[0]["reason"] == "phase_deadline_backend_init"
        corpse = json.loads(flight.read_record(recs[0]["name"]))
        assert corpse["extra"]["phase"] == "backend_init"
        assert any(r["phase"] == "backend_init"
                   for r in corpse["extra"]["phase_history"])
        # history shows the expiry (the phase body itself completed —
        # the record keeps the deadline verdict, not a retroactive ok)
        mine = [r for r in phases.history()
                if r["phase"] == "backend_init"][-1]
        assert mine["status"] == "deadline"
        assert _metric_value("h2o3_phase_deadline_exceeded_total",
                             phase="backend_init") == exceeded_before + 1
        assert _metric_value("h2o3_phase_cpu_fallbacks_total",
                             phase="backend_init") == fb_before + 1
        # no new device syncs / compiles: the drill is pure host work
        assert len(compiles.ledger_rows()) == rows_before
        assert sharded_frame.counters() == dp_before

    def test_completed_phase_cancels_the_timer(self, monkeypatch):
        monkeypatch.setenv("H2O_TPU_PHASE_DEADLINE_S", "mesh_init=0.2")
        before = _metric_value("h2o3_phase_deadline_exceeded_total",
                               phase="mesh_init")
        with phases.enter("mesh_init"):
            pass
        time.sleep(0.35)        # past the would-be deadline
        assert _metric_value("h2o3_phase_deadline_exceeded_total",
                             phase="mesh_init") == before
        assert [r for r in phases.history()
                if r["phase"] == "mesh_init"][-1]["status"] == "ok"

    def test_phase_report_survives_ring_churn(self):
        """The boot durations must outlive the bounded history ring: a
        long-lived server's recurring phases (server_start, cache loads)
        must not evict backend_init from phase_report."""
        assert "backend_init" in phases.phase_report() or \
            "server_start" in phases.phase_report()
        baseline = dict(phases.phase_report())
        for _ in range(300):        # > the ring's maxlen
            with phases.enter("mesh_init"):
                pass
        report = phases.phase_report()
        for name, ms in baseline.items():
            if name != "mesh_init":
                assert name in report, (name, report)

    def test_wedged_phase_names_the_oldest_open_phase(self):
        # NO reset: the boot history must survive for the REST test, and
        # the earlier deadline drill's record (expired but completed)
        # must not read as wedged forever
        assert phases.wedged_phase() is None
        with phases.enter("device_discovery"):
            # a freshly-running phase is NOT wedged on a live endpoint
            # (grace window) — only one running past its deadline/grace
            assert phases.wedged_phase() is None
            assert phases.wedged_phase(grace_s=0.0) == "device_discovery"
        assert phases.wedged_phase(grace_s=0.0) is None


# ---------------------------------------------------------------------------
# compile ledger
# ---------------------------------------------------------------------------

class TestCompileLedger:
    def test_family_enumeration_is_closed(self):
        with pytest.raises(ValueError, match="closed"):
            compiles.record_compile("quantum", "sig", 1.0)
        with pytest.raises(ValueError):
            compiles.record_hit("scoring", "sig", "l5_cache")

    def test_compile_jit_records_row_and_feeds_legacy_counter(self, cl):
        import jax
        import jax.numpy as jnp

        from h2o3_tpu.artifact import compile_cache

        cc_before = compile_cache.stats()
        rows_before = len(compiles.ledger_rows())
        sig = ("test", "ledger", time.time())
        exe = compiles.compile_jit(
            "scoring", jax.jit(lambda x: x * jnp.float32(2)),
            (jax.ShapeDtypeStruct((), jnp.float32),),
            signature=sig, program="test_scalar")
        assert float(exe(jnp.float32(3))) == 6.0
        rows = compiles.ledger_rows()
        assert len(rows) == rows_before + 1
        row = rows[-1]
        assert row["family"] == "scoring" and row["cache"] == "compile"
        assert row["ms"] > 0 and len(row["signature"]) == 16
        assert row["device_kind"] and row["device_kind"].startswith("cpu")
        # the legacy note_compile counter is a view over the ledger: same
        # count AND the same milliseconds (zero drift by construction)
        cc = compile_cache.stats()
        assert cc["compiles"] == cc_before["compiles"] + 1
        assert cc["compile_ms_total"] == pytest.approx(
            cc_before["compile_ms_total"] + row["ms"])

    def test_probe_family_does_not_feed_the_fused_counter(self, cl):
        import jax
        import jax.numpy as jnp

        from h2o3_tpu.artifact import compile_cache

        before = compile_cache.fused_compile_count()
        compiles.compile_jit(
            "probe", jax.jit(lambda x: x - jnp.float32(1)),
            (jax.ShapeDtypeStruct((), jnp.float32),),
            signature=("probe", time.time()))
        assert compile_cache.fused_compile_count() == before

    def test_hits_and_family_table_and_slowest(self):
        t = time.time()
        compiles.record_compile("rapids", ("a", t), 50.0, program="p1")
        compiles.record_compile("rapids", ("b", t), 10.0, program="p2")
        rows_before = len(compiles.ledger_rows())
        compiles.record_hit("rapids", ("a", t), "memory")
        compiles.record_hit("rapids", ("a", t), "disk")
        # hits bump aggregates ONLY — they must never consume the
        # bounded compile-row ring (warm traffic would evict the
        # compile rows and empty slowest-N on long-lived clusters)
        assert len(compiles.ledger_rows()) == rows_before
        tab = compiles.family_table()["rapids"]
        assert tab["compiles"] >= 2 and tab["ms_max"] >= 50.0
        assert tab["hits_memory"] >= 1 and tab["hits_disk"] >= 1
        slow = compiles.slowest(3)
        assert slow == sorted(slow, key=lambda r: r["ms"], reverse=True)
        assert all(r["cache"] == "compile" for r in slow)

    def test_warm_scoring_hits_land_in_the_family_table(self, cl):
        """The in-memory executable tier is the dominant warm serving
        path — /3/Runtime's scoring hit ratio must count it."""
        before = compiles.family_table().get("scoring", {}).get(
            "hits_memory", 0)
        compiles.record_hit("scoring", tier="memory")
        assert compiles.family_table()["scoring"]["hits_memory"] == \
            before + 1

    def test_merge_family_tables(self):
        merged = compiles.merge_family_tables([
            {"scoring": {"compiles": 1, "hits_memory": 0, "hits_disk": 2,
                         "ms_total": 10.0, "ms_max": 10.0}},
            {"scoring": {"compiles": 3, "hits_memory": 1, "hits_disk": 0,
                         "ms_total": 5.0, "ms_max": 4.0}},
        ])
        assert merged["scoring"]["compiles"] == 4
        assert merged["scoring"]["hits_disk"] == 2
        assert merged["scoring"]["ms_total"] == 15.0
        assert merged["scoring"]["ms_max"] == 10.0

    def test_boot_first_compile_is_in_the_ledger(self, cl):
        # the supervised boot probe (core/runtime.py first_compile phase)
        assert "probe" in compiles.family_table()
        assert "first_compile" in phases.phase_report()
        assert "backend_init" in phases.phase_report()


# ---------------------------------------------------------------------------
# histogram quantiles (/3/Metrics?format=json satellite)
# ---------------------------------------------------------------------------

class TestHistogramQuantiles:
    def test_interpolates_inside_the_owning_bucket(self):
        # 10 observations, all cumulative counts known exactly
        q = metrics.histogram_quantiles(
            [0.1, 0.5, 1.0], [2, 8, 10], 10)
        # p50: target 5 -> bucket (0.1, 0.5], frac (5-2)/6
        assert q["p50"] == pytest.approx(0.1 + 0.4 * 3 / 6)
        # p95: target 9.5 -> bucket (0.5, 1.0], frac (9.5-8)/2
        assert q["p95"] == pytest.approx(0.5 + 0.5 * 1.5 / 2)

    def test_empty_histogram_reports_none(self):
        q = metrics.histogram_quantiles([0.1, 1.0], [0, 0], 0)
        assert q == {"p50": None, "p95": None, "p99": None}

    def test_overflow_lands_on_last_finite_bucket(self):
        # every observation beyond the largest bucket (+Inf territory)
        q = metrics.histogram_quantiles([0.1, 1.0], [0, 0], 5)
        assert q["p99"] == 1.0


# ---------------------------------------------------------------------------
# GET /3/Runtime + /3/Metrics quantiles over the wire
# ---------------------------------------------------------------------------

class TestRuntimeRest:
    def test_runtime_route_and_metrics_quantiles(self, cl):
        from h2o3_tpu.api.server import start_server

        srv = start_server(port=0)
        try:
            base = f"http://127.0.0.1:{srv.port}"
            r = urllib.request.urlopen(base + "/3/Runtime", timeout=30)
            # the satellite: /3/Runtime responses carry the trace id
            assert r.headers.get("X-H2O3-Trace-Id")
            out = json.loads(r.read())
            assert out["__meta"]["schema_name"] == "RuntimeV3"
            # complete boot phase history: backend_init .. first_compile
            for p in ("backend_init", "device_discovery", "mesh_init",
                      "first_compile", "server_start"):
                assert p in out["phase_report"], p
            # the boot probe compile is in the cluster-wide family table
            assert "probe" in out["compile_families"]
            slow = out["slowest_compiles"]
            assert slow and all("signature" in r_ and "ms" in r_
                                for r_ in slow)
            assert out["processes"] and out["processes"][0]["proc"] == 0
            # ?slowest=1 narrows the slow list
            out1 = json.loads(urllib.request.urlopen(
                base + "/3/Runtime?slowest=1", timeout=30).read())
            assert len(out1["slowest_compiles"]) <= 1
            # /3/Metrics?format=json histograms carry computed quantiles
            mj = json.loads(urllib.request.urlopen(
                base + "/3/Metrics?format=json", timeout=30).read())
            hists = [m for m in mj["series"] if m["type"] == "histogram"]
            assert hists
            for m in hists:
                for s in m["samples"]:
                    assert set(s["quantiles"]) == {"p50", "p95", "p99"}
            # a populated histogram reports real numbers
            rest = next(m for m in hists
                        if m["name"] == "h2o3_rest_request_seconds")
            s0 = rest["samples"][0]
            assert s0["count"] > 0 and s0["quantiles"]["p50"] is not None
        finally:
            srv.stop()
