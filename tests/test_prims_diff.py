"""Round-4 prim-diff closure: the last 13 reference prims, plus the
registry-vs-reference audit (every Ast*.java with a str() registered).

Reference: water/rapids/ast/prims/ (205 files; 186 named prims, the rest
abstract bases)."""

import glob
import os
import re

import numpy as np
import pytest

from h2o3_tpu.core.frame import Column, Frame
from h2o3_tpu.rapids import exec_rapids
from h2o3_tpu.rapids.eval import PRIMS

REF_PRIMS = "/root/reference/h2o-core/src/main/java/water/rapids/ast/prims"


def test_every_named_reference_prim_registered(cl):
    missing = []
    for f in glob.glob(REF_PRIMS + "/*/*.java"):
        src = open(f, encoding="utf-8", errors="replace").read()
        m = re.search(r'String\s+str\(\)\s*\{[^}]*?return\s+"([^"]+)"',
                      src, re.S)
        if m and m.group(1) not in PRIMS:
            missing.append((os.path.basename(f), m.group(1)))
    assert missing == [], f"unregistered reference prims: {missing}"


@pytest.fixture()
def fr(cl):
    f = Frame(key="pd_fr")
    f.add("x", Column.from_numpy(np.asarray([3.0, 1.0, 2.0, 5.0, 4.0])))
    f.install()
    return f


def test_none_and_comma(fr):
    out = exec_rapids("(none pd_fr)")
    assert out.nrows == 5
    assert float(exec_rapids("(, 1 2 7)")) == 7.0


def test_setproperty_and_rename(fr):
    exec_rapids('(setproperty "foo.bar" "baz")')
    from h2o3_tpu.rapids.prims_ext import _PROPERTIES

    assert _PROPERTIES["foo.bar"] == "baz"
    from h2o3_tpu.core.dkv import DKV

    exec_rapids('(rename "pd_fr" "pd_fr2")')
    assert DKV.get("pd_fr") is None and DKV.get("pd_fr2") is not None
    exec_rapids('(rename "pd_fr2" "pd_fr")')


def test_mad_and_na_rollups(fr, cl):
    got = exec_rapids('(h2o.mad pd_fr "interpolate" 1.4826)')
    x = np.asarray([3, 1, 2, 5, 4], float)
    want = 1.4826 * np.median(np.abs(x - np.median(x)))
    assert abs(float(got) - want) < 1e-9
    assert float(exec_rapids("(maxNA pd_fr)")) == 5.0
    assert float(exec_rapids("(minNA pd_fr)")) == 1.0
    f2 = Frame(key="pd_na")
    f2.add("x", Column.from_numpy(np.asarray([1.0, np.nan, 3.0])))
    f2.install()
    assert np.isnan(float(exec_rapids("(maxNA pd_na)")))


def test_perfect_auc(cl):
    f = Frame(key="pa_p")
    f.add("p", Column.from_numpy(np.asarray([0.1, 0.4, 0.35, 0.8])))
    f.install()
    a = Frame(key="pa_a")
    a.add("y", Column.from_numpy(np.asarray([0.0, 0.0, 1.0, 1.0])))
    a.install()
    out = exec_rapids("(perfectAUC pa_p pa_a)")
    auc = float(np.asarray(out.col(out.names[0]).to_numpy())[0])
    # sklearn-verified value for this classic example
    assert abs(auc - 0.75) < 1e-9


def test_model_reset_threshold(cl):
    from h2o3_tpu.models.tree.gbm import GBM

    rng = np.random.default_rng(0)
    f = Frame(key="thr_fr")
    x = rng.normal(size=300)
    f.add("x", Column.from_numpy(x))
    f.add("y", Column.from_numpy(
        np.where(x + rng.normal(0, .5, 300) > 0, "Y", "N"), ctype="enum"))
    f.install()
    m = GBM(ntrees=3, max_depth=3, seed=1).train(y="y", training_frame=f)
    m.install()
    old = float(m._output.training_metrics.auc_data.max_f1_threshold)
    out = exec_rapids(f'(model.reset.threshold "{m.key}" 0.42)')
    returned = float(np.asarray(out.col(out.names[0]).to_numpy())[0])
    assert abs(returned - old) < 1e-6
    assert abs(float(m._output.training_metrics.auc_data.max_f1_threshold)
               - 0.42) < 1e-6


def test_isax(cl):
    rng = np.random.default_rng(3)
    f = Frame(key="ts_fr")
    for i in range(16):
        f.add(f"t{i}", Column.from_numpy(
            np.sin(np.arange(4) + i / 3.0) + rng.normal(0, .05, 4)))
    f.install()
    out = exec_rapids("(isax ts_fr 4 8 0)")
    assert out.names[0] == "iSax_index"
    assert out.ncols == 5
    syms = np.column_stack([np.asarray(out.col(f"c{i}").to_numpy())
                            for i in range(4)])
    assert syms.min() >= 0 and syms.max() < 8


def test_tfidf(cl):
    f = Frame(key="corpus")
    f.add("doc", Column.from_numpy(np.asarray([0.0, 1.0])))
    f.add("text", Column.from_numpy(
        np.asarray(["a b a", "b c"], object).astype(str), ctype="enum"))
    f.install()
    out = exec_rapids("(tf-idf corpus 0 1 1 1)")
    assert set(out.names) == {"DocID", "Word", "TF", "IDF", "TF-IDF"}
    words = [list(out.col("Word").domain)[int(c)]
             for c in np.asarray(out.col("Word").to_numpy())]
    tfs = np.asarray(out.col("TF").to_numpy())
    pairs = dict(zip(zip(np.asarray(out.col("DocID").to_numpy()), words),
                     tfs))
    assert pairs[(0.0, "a")] == 2.0        # 'a' twice in doc 0
    assert pairs[(1.0, "c")] == 1.0


def test_grouped_permute(cl):
    f = Frame(key="gp_fr")
    f.add("grp", Column.from_numpy(np.asarray([1.0, 1.0, 1.0, 2.0, 2.0])))
    f.add("acct", Column.from_numpy(np.asarray([10.0, 11.0, 12.0, 20.0, 21.0])))
    f.add("dc", Column.from_numpy(
        np.asarray(["D", "C", "C", "D", "C"], object).astype(str),
        ctype="enum"))
    f.add("amt", Column.from_numpy(np.asarray([5.0, 6.0, 7.0, 8.0, 9.0])))
    f.install()
    out = exec_rapids("(grouped_permute gp_fr 1 [0] 2 3)")
    assert out.names == ["grp", "In", "Out", "InAmnt", "OutAmnt"]
    # group 1: one D row (acct 10) paired with 2 C rows; group 2: 1x1
    assert out.nrows == 3
    ins = np.asarray(out.col("In").to_numpy(), float)
    assert set(ins.tolist()) == {10.0, 20.0}


def test_segment_models_as_frame(cl):
    from h2o3_tpu.models.segments import SegmentModels

    sm = DKV_key = None
    try:
        from h2o3_tpu.core.dkv import DKV
        from h2o3_tpu.models.tree.gbm import GBM

        rng = np.random.default_rng(1)
        f = Frame(key="seg_fr")
        f.add("g", Column.from_numpy(
            np.asarray(["a", "b"] * 100, object).astype(str), ctype="enum"))
        f.add("x", Column.from_numpy(rng.normal(size=200)))
        f.add("y", Column.from_numpy(rng.normal(size=200)))
        f.install()
        from h2o3_tpu.models.segments import train_segments

        sm = train_segments(GBM, {"ntrees": 2, "max_depth": 2}, f, ["g"],
                            y="y")
        out = exec_rapids(f'(segment_models_as_frame "{sm.key}")')
        assert "model" in out.names and out.nrows == 2
    finally:
        pass
