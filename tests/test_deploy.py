"""Deployment artifacts sanity (reference analogs: h2o-helm, docker)."""

import os
import re

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHART = os.path.join(REPO, "deploy", "helm", "h2o3-tpu")


def test_helm_chart_layout():
    assert os.path.exists(os.path.join(CHART, "Chart.yaml"))
    chart = open(os.path.join(CHART, "Chart.yaml")).read()
    assert "name: h2o3-tpu" in chart and "apiVersion: v2" in chart
    values = open(os.path.join(CHART, "values.yaml")).read()
    for key in ("replicaCount", "auth:", "tls:", "cpuMode:"):
        assert key in values, key
    for tpl in ("statefulset.yaml", "service.yaml", "_helpers.tpl"):
        assert os.path.exists(os.path.join(CHART, "templates", tpl)), tpl


def test_helm_templates_braces_balanced():
    """Every {{ has its }} and the security env plumbing is present."""
    tdir = os.path.join(CHART, "templates")
    for f in os.listdir(tdir):
        src = open(os.path.join(tdir, f)).read()
        assert src.count("{{") == src.count("}}"), f
        # every if has an end
        assert len(re.findall(r"{{-? if ", src)) == \
            len(re.findall(r"{{-? end ?}}", src)) - \
            len(re.findall(r"{{-? range ", src)), f
    ss = open(os.path.join(tdir, "statefulset.yaml")).read()
    for needle in ("H2O_TPU_COORDINATOR", "H2O_TPU_NUM_PROCESSES",
                   "H2O_TPU_AUTH_FILE", "H2O_TPU_SSL_CERT", "/3/Ping"):
        assert needle in ss, needle
