"""Extended Rapids prim suites (water/rapids/ast/prims/{advmath,time,string,
search,mungers,matrix,repeaters,timeseries}) — evaluated through the same
exec_rapids entry h2o-py's POST /99/Rapids reaches."""

import numpy as np
import pytest

from h2o3_tpu.core.frame import Column, Frame
from h2o3_tpu.rapids import Session, exec_rapids


@pytest.fixture()
def sess(cl):
    s = Session("t")
    yield s
    s.end()


@pytest.fixture()
def fr(cl, sess):
    rng = np.random.default_rng(0)
    n = 200
    f = Frame(key="ext.hex")
    f.add("a", Column.from_numpy(rng.normal(size=n)))
    f.add("b", Column.from_numpy(2.0 * np.arange(n, dtype=float)))
    f.add("g", Column.from_numpy(
        np.array(["x", "y", "z"])[np.arange(n) % 3], ctype="enum"))
    f.install()
    return f


def _run(sess, expr):
    return exec_rapids(expr, sess)


def test_cor_matches_numpy(fr, sess):
    out = _run(sess, '(cor ext.hex ext.hex "complete.obs" "pearson")')
    a = np.asarray(fr.col("a").to_numpy())
    b = np.asarray(fr.col("b").to_numpy())
    want = np.corrcoef(a, b)[0, 1]
    got = np.asarray(out.col("b").to_numpy())[0]
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_distance_euclidean(fr, sess):
    sub = fr.subframe(["a", "b"], key="dist.hex")
    sub.install()
    out = _run(sess, '(distance dist.hex dist.hex "l2")')
    D = np.column_stack([np.asarray(out.col(i).to_numpy())
                         for i in range(min(out.ncols, 5))])
    assert abs(float(D[0, 0])) < 1e-4          # self-distance 0


def test_hist(fr, sess):
    out = _run(sess, '(hist (cols_py ext.hex "a") 10)')
    counts = np.asarray(out.col("counts").to_numpy())
    assert counts.sum() == 200


def test_skew_kurt_mode(fr, sess):
    from scipy import stats

    a = np.asarray(fr.col("a").to_numpy())
    sk = _run(sess, '(skewness (cols_py ext.hex "a") True)')
    np.testing.assert_allclose(sk, stats.skew(a, bias=False) /
                               (1 if True else 1), atol=0.05)
    mode = _run(sess, '(mode (cols_py ext.hex "g"))')
    assert mode in (0.0, 1.0, 2.0)


def test_kfold_columns(fr, sess):
    out = _run(sess, "(kfold_column ext.hex 5 42)")
    v = np.asarray(out.col(0).to_numpy())
    assert set(np.unique(v)) <= set(range(5))
    out2 = _run(sess, "(modulo_kfold_column ext.hex 4)")
    v2 = np.asarray(out2.col(0).to_numpy())
    assert (v2 == np.arange(200) % 4).all()
    out3 = _run(sess, '(stratified_kfold_column (cols_py ext.hex "g") 3 7)')
    assert out3.nrows == 200


def test_matrix_ops(fr, sess):
    sub = fr.subframe(["a", "b"], key="m.hex")
    sub.install()
    t = _run(sess, "(t m.hex)")
    assert t.nrows == 2 and t.ncols == 200
    mm = _run(sess, "(x (t m.hex) m.hex)")
    assert mm.nrows == 2 and mm.ncols == 2
    M = np.column_stack([np.asarray(sub.col(i).to_numpy()) for i in range(2)])
    want = M.T @ M
    got = np.column_stack([np.asarray(mm.col(i).to_numpy()) for i in range(2)])
    np.testing.assert_allclose(got, want, rtol=1e-4)


def test_repeaters(sess, cl):
    s = _run(sess, "(seq 1 5 1)")
    np.testing.assert_allclose(np.asarray(s.col(0).to_numpy()),
                               [1, 2, 3, 4, 5])
    sl = _run(sess, "(seq_len 4)")
    np.testing.assert_allclose(np.asarray(sl.col(0).to_numpy()), [1, 2, 3, 4])
    rl = _run(sess, "(rep_len 7 3)")
    np.testing.assert_allclose(np.asarray(rl.col(0).to_numpy()), [7, 7, 7])


def test_search(fr, sess):
    w = _run(sess, '(which (> (cols_py ext.hex "a") 100))')
    assert w.nrows == 0
    m = _run(sess, '(match (cols_py ext.hex "g") ["y"] NaN _)')
    v = np.asarray(m.col(0).to_numpy())
    g = fr.col("g").values()
    assert np.isfinite(v[g == "y"]).all() and (v[g == "y"] == 1).all()
    assert np.isnan(v[g == "x"]).all()
    wm = _run(sess, "(which.max ext.hex True 1)")
    assert wm.nrows == 200


def test_string_suite(sess, cl):
    f = Frame(key="str.hex")
    f.add("s", Column.from_numpy(np.array(["  Apple ", "banana", "Cherry"]),
                                 ctype="enum"))
    f.install()
    lo = _run(sess, "(tolower str.hex)")
    assert set(lo.col("s").values()) == {"  apple ", "banana", "cherry"}
    tr = _run(sess, "(trim (tolower str.hex))")
    assert set(tr.col("s").values()) == {"apple", "banana", "cherry"}
    ln = _run(sess, "(strlen str.hex)")
    assert sorted(np.asarray(ln.col(0).to_numpy()).tolist()) == [6.0, 6.0, 8.0]
    sub = _run(sess, "(substring (tolower (trim str.hex)) 0 3)")
    assert "app" in set(sub.col("s").values())
    ent = _run(sess, "(entropy str.hex)")
    assert (np.asarray(ent.col(0).to_numpy()) > 0).all()
    cm = _run(sess, '(countmatches str.hex ["an"])')
    v = np.asarray(cm.col(0).to_numpy())
    assert v.max() == 2.0            # "banana" has 2 "an"
    g = _run(sess, '(grep str.hex "an" 0 0 1)')
    assert np.asarray(g.col(0).to_numpy()).sum() == 1.0
    sp = _run(sess, '(strsplit str.hex "n")')
    assert sp.ncols >= 2
    d = _run(sess, '(strDistance str.hex str.hex "lev" 1)')
    np.testing.assert_allclose(np.asarray(d.col(0).to_numpy()), 0.0)


def test_time_suite(sess, cl):
    import datetime as dt

    ts = [dt.datetime(2020, 3, 15, 14, 30, 45, tzinfo=dt.timezone.utc),
          dt.datetime(1999, 12, 31, 23, 59, 59, tzinfo=dt.timezone.utc)]
    ms = np.asarray([int(t.timestamp() * 1000) for t in ts], np.int64)
    f = Frame(key="time.hex")
    f.add("t", Column.from_numpy(ms, ctype="time"))
    f.install()
    assert np.allclose(np.asarray(_run(sess, "(year time.hex)").col(0).to_numpy()),
                       [2020, 1999])
    assert np.allclose(np.asarray(_run(sess, "(month time.hex)").col(0).to_numpy()),
                       [3, 12])
    assert np.allclose(np.asarray(_run(sess, "(day time.hex)").col(0).to_numpy()),
                       [15, 31])
    assert np.allclose(np.asarray(_run(sess, "(hour time.hex)").col(0).to_numpy()),
                       [14, 23])
    assert np.allclose(np.asarray(_run(sess, "(minute time.hex)").col(0).to_numpy()),
                       [30, 59])
    assert np.allclose(np.asarray(_run(sess, "(second time.hex)").col(0).to_numpy()),
                       [45, 59])
    # 2020-03-15 is a Sunday → reference convention Monday=0 ⇒ 6
    assert np.allclose(np.asarray(_run(sess, "(dayOfWeek time.hex)").col(0).to_numpy()),
                       [6, 4])
    mk = _run(sess, "(mktime 2020 2 14 14 30 45 0)")   # month/day 0-based
    np.testing.assert_allclose(np.asarray(mk.col(0).to_numpy())[0], ms[0],
                               atol=1.0)


def test_timeseries_difflag(fr, sess):
    d = _run(sess, '(difflag1 (cols_py ext.hex "b"))')
    v = np.asarray(d.col(0).to_numpy())
    assert np.isnan(v[0]) and np.allclose(v[1:], 2.0)


def test_cut(fr, sess):
    out = _run(sess, '(cut (cols_py ext.hex "b") [0 100 400] ["lo" "hi"] 1 1 3)')
    c = out.col(0)
    assert c.is_categorical
    vals = c.values()
    b = np.asarray(fr.col("b").to_numpy())
    assert all(v == "lo" for v in vals[(b > 0) & (b <= 100)])


def test_fillna(sess, cl):
    x = np.array([1.0, np.nan, np.nan, 4.0, np.nan])
    f = Frame(key="na.hex")
    f.add("x", Column.from_numpy(x))
    f.install()
    out = _run(sess, '(h2o.fillna na.hex "forward" 0 1)')
    v = np.asarray(out.col(0).to_numpy())
    np.testing.assert_allclose(v[[0, 1, 3, 4]], [1, 1, 4, 4])
    assert np.isnan(v[2])            # maxlen=1 stops the fill


def test_melt_pivot_roundtrip(sess, cl):
    f = Frame(key="mp.hex")
    f.add("id", Column.from_numpy(np.array(["r1", "r2"]), ctype="enum"))
    f.add("c1", Column.from_numpy(np.array([1.0, 2.0])))
    f.add("c2", Column.from_numpy(np.array([3.0, 4.0])))
    f.install()
    m = _run(sess, '(melt mp.hex [0] [1 2] "variable" "value" 0)')
    assert m.nrows == 4 and set(m.names) == {"id", "variable", "value"}
    m.key_str = str(m.key)
    m.install()
    p = _run(sess, f'(pivot {m.key} "id" "variable" "value")')
    assert p.nrows == 2
    assert set(p.names) == {"id", "c1", "c2"}
    got = {(r, c): np.asarray(p.col(c).to_numpy())[i]
           for i, r in enumerate(p.col("id").values()) for c in ("c1", "c2")}
    assert got[("r1", "c1")] == 1.0 and got[("r2", "c2")] == 4.0


def test_ddply_and_apply(fr, sess):
    out = _run(sess, '(ddply ext.hex [2] { x . (mean (cols_py x "b") True 0) })')
    assert out.nrows == 3            # three g levels
    ap = _run(sess, '(apply (cols_py ext.hex [0 1]) 2 { x . (sd x) })')
    assert ap.nrows == 1 and ap.ncols == 2


def test_rank_within_groupby(fr, sess):
    out = _run(sess, '(rank_within_groupby ext.hex [2] [1] [1] "rnk" 0)')
    rnk = np.asarray(out.col("rnk").to_numpy())
    g = fr.col("g").values()
    b = np.asarray(fr.col("b").to_numpy())
    sel = rnk[g == "x"]
    assert sel.min() == 1.0 and len(set(sel.tolist())) == len(sel)


def test_misc_mungers(fr, sess):
    assert _run(sess, "(any.factor ext.hex)") == 1.0
    isf = _run(sess, "(is.factor ext.hex)")
    assert isf == [0.0, 0.0, 1.0]
    nlv = _run(sess, "(nlevels ext.hex)")
    assert nlv == [0.0, 0.0, 3.0]
    cbt = _run(sess, '(columnsByType ext.hex "numeric")')
    assert cbt == [0.0, 1.0]
    fl = _run(sess, "(flatten (rows (cols_py ext.hex [1]) [0]))")
    assert fl == 0.0
    sig = _run(sess, "(signif (cols_py ext.hex [1]) 1)")
    v = np.asarray(sig.col(0).to_numpy())
    assert v[7] == 10.0              # 14 -> 1 sig digit -> 10
    na = _run(sess, "(any.na ext.hex)")
    assert na == 0.0


def test_dropdup(sess, cl):
    f = Frame(key="dd.hex")
    f.add("k", Column.from_numpy(np.array([1.0, 1.0, 2.0, 2.0, 3.0])))
    f.add("v", Column.from_numpy(np.arange(5.0)))
    f.install()
    out = _run(sess, '(dropdup dd.hex [0] "first")')
    np.testing.assert_allclose(np.asarray(out.col("v").to_numpy()), [0, 2, 4])


def test_topn(fr, sess):
    out = _run(sess, "(topn ext.hex 1 5 1)")
    vals = np.asarray(out.col(1).to_numpy())
    b = np.asarray(fr.col("b").to_numpy())
    assert vals[0] == b.max()
    assert len(vals) == 10           # 5% of 200


def test_session_refcounts(fr, cl):
    s = Session("rc")
    exec_rapids("(tmp= rc1 (cols_py ext.hex [0]))", s)
    exec_rapids("(tmp= rc2 (cols_py ext.hex [0]))", s)
    col = fr.col("a")
    assert s.column_refs(col) == 2
    exec_rapids("(rm rc1)", s)
    assert s.column_refs(col) == 1
    s.end()
    assert s.column_refs(col) == 0


def test_unary_extensions(fr, sess):
    out = _run(sess, "(asinh (cols_py ext.hex [0]))")
    a = np.asarray(fr.col("a").to_numpy())
    np.testing.assert_allclose(np.asarray(out.col(0).to_numpy()),
                               np.arcsinh(a), atol=1e-5)
    tg = _run(sess, "(trigamma (cols_py ext.hex [1]))")
    from scipy.special import polygamma

    b = np.asarray(fr.col("b").to_numpy())
    want = polygamma(1, np.where(b > 0, b, np.nan))
    got = np.asarray(tg.col(0).to_numpy())
    # central-difference approximation in f32 (elementwise.py trigamma note)
    np.testing.assert_allclose(got[2:10], want[2:10], rtol=1e-2)
