"""Columnar/structured format ingest + persist URI registry + parallel parse.

Reference: h2o-parsers/h2o-parquet-parser/, h2o-orc-parser/,
water/parser/ARFFParser.java, SVMLightParser.java,
water/persist/PersistManager.java (+ PersistHTTP).
"""

import http.server
import os
import threading

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu import persist
from h2o3_tpu.ingest.parser import import_file


@pytest.fixture(autouse=True)
def _boot(cl):
    pass


def _pq_file(tmp_path, name="t.parquet", n=500):
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(0)
    cats = np.array(["lo", "mid", "hi"], object)[rng.integers(0, 3, n)]
    cats[5] = None
    x = rng.standard_normal(n)
    x[3] = np.nan
    table = pa.table({
        "x": pa.array(x),
        "i": pa.array(rng.integers(0, 100, n)),
        "b": pa.array(rng.random(n) < 0.5),
        "cat": pa.array(cats),
        "ts": pa.array(np.array(["2024-01-01", "2024-06-15"], "datetime64[ms]")[
            rng.integers(0, 2, n)]),
    })
    p = str(tmp_path / name)
    pq.write_table(table, p)
    return p, table


class TestParquet:
    def test_roundtrip(self, tmp_path):
        p, table = _pq_file(tmp_path)
        fr = import_file(p)
        assert fr.nrows == 500 and fr.ncols == 5
        assert fr.col("cat").is_categorical
        assert sorted(fr.col("cat").domain) == ["hi", "lo", "mid"]
        x = fr.col("x").to_numpy()
        np.testing.assert_allclose(
            np.nanmean(x), np.nanmean(table["x"].to_numpy(zero_copy_only=False)),
            rtol=1e-5)
        assert np.isnan(x[3])
        assert fr.col("ts").ctype == "time"
        # bool -> numeric 0/1
        b = fr.col("b").to_numpy()
        assert set(np.unique(b)) <= {0.0, 1.0}

    def test_trains_a_model(self, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        from h2o3_tpu.models.tree.gbm import GBM

        rng = np.random.default_rng(1)
        n = 600
        x1 = rng.standard_normal(n)
        y = np.where(rng.random(n) < 1 / (1 + np.exp(-2 * x1)), "Y", "N")
        p = str(tmp_path / "train.parquet")
        pq.write_table(pa.table({"x1": x1, "y": y}), p)
        fr = import_file(p)
        m = GBM(ntrees=5, max_depth=3, seed=1).train(y="y", training_frame=fr)
        assert float(m._output.training_metrics.auc) > 0.7


class TestOrcFeather:
    def test_orc(self, tmp_path):
        import pyarrow as pa
        import pyarrow.orc as orc

        p = str(tmp_path / "t.orc")
        orc.write_table(pa.table({"a": [1.0, 2.0, 3.5],
                                  "s": ["u", "v", "u"]}), p)
        fr = import_file(p)
        assert fr.nrows == 3
        np.testing.assert_allclose(fr.col("a").to_numpy(), [1.0, 2.0, 3.5])
        assert fr.col("s").domain == ["u", "v"]

    def test_feather(self, tmp_path):
        import pyarrow as pa
        import pyarrow.feather as feather

        p = str(tmp_path / "t.feather")
        feather.write_feather(pa.table({"a": [1, 2, 3]}), p)
        fr = import_file(p)
        assert fr.nrows == 3 and fr.col("a").to_numpy()[2] == 3.0


class TestArff:
    def test_parse(self, tmp_path):
        p = str(tmp_path / "t.arff")
        with open(p, "w") as f:
            f.write("% comment\n@relation demo\n"
                    "@attribute age numeric\n"
                    "@attribute grade {A,B,C}\n"
                    "@attribute note string\n"
                    "@data\n"
                    "34,A,'hello'\n?,B,'x'\n12,?,'y'\n")
        fr = import_file(p)
        assert fr.names == ["age", "grade", "note"]
        a = fr.col("age").to_numpy()
        assert a[0] == 34 and np.isnan(a[1])
        assert fr.col("grade").is_categorical
        g = fr.col("grade").to_numpy()
        assert g[2] < 0        # '?' -> NA


class TestSVMLight:
    def test_parse(self, tmp_path):
        p = str(tmp_path / "t.svm")
        with open(p, "w") as f:
            f.write("1 1:0.5 3:2.0 # comment\n-1 2:1.5\n1 qid:4 1:1.0\n")
        fr = import_file(p)
        assert fr.ncols == 4         # label + 3 features
        np.testing.assert_allclose(fr.col("C1").to_numpy(), [1, -1, 1])
        np.testing.assert_allclose(fr.col("C2").to_numpy(), [0.5, 0.0, 1.0])
        np.testing.assert_allclose(fr.col("C4").to_numpy(), [2.0, 0.0, 0.0])


class TestPersist:
    def test_http_import(self, tmp_path):
        csv = tmp_path / "web.csv"
        csv.write_text("a,b\n1,x\n2,y\n3,x\n")
        handler = lambda *a, **kw: http.server.SimpleHTTPRequestHandler(  # noqa: E731
            *a, directory=str(tmp_path), **kw)
        srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), handler)
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        try:
            uri = f"http://127.0.0.1:{srv.server_port}/web.csv"
            fr = import_file(uri)
            assert fr.nrows == 3
            assert fr.col("b").domain == ["x", "y"]
            # second fetch hits the cache (same resolved path)
            assert persist.resolve(uri) == persist.resolve(uri)
        finally:
            srv.shutdown()

    def test_gated_schemes(self):
        # s3 is a REAL backend since round 4 (persist/s3.py); gs/hdfs
        # remain gated on their SDKs
        with pytest.raises(NotImplementedError, match="google-cloud"):
            persist.resolve("gs://bucket/key.csv")
        with pytest.raises(ValueError, match="no persist backend"):
            persist.resolve("weird://x")

    def test_custom_scheme(self, tmp_path):
        p = tmp_path / "c.csv"
        p.write_text("a\n5\n")
        persist.register_scheme("unittest", lambda uri: str(p))
        try:
            fr = import_file("unittest://anything")
            assert fr.nrows == 1 and fr.col("a").to_numpy()[0] == 5.0
        finally:
            persist._SCHEMES.pop("unittest", None)


class TestParallelMultiFile:
    def test_glob_parse_matches_sequential_order(self, tmp_path):
        for i in range(6):
            (tmp_path / f"part{i}.csv").write_text(
                "v,g\n" + "".join(f"{i * 100 + j},g{j % 2}\n" for j in range(50)))
        fr = import_file(str(tmp_path / "part*.csv"))
        assert fr.nrows == 300
        v = fr.col("v").to_numpy()
        # files concatenate in sorted order regardless of thread timing
        expect = np.concatenate([i * 100 + np.arange(50) for i in range(6)])
        np.testing.assert_allclose(v, expect)

    def test_mismatched_columns_raise(self, tmp_path):
        (tmp_path / "a1.csv").write_text("x,y\n1,2\n")
        (tmp_path / "a2.csv").write_text("x,z\n1,2\n")
        with pytest.raises(ValueError, match="column mismatch"):
            import_file(str(tmp_path / "a?.csv"))

    def test_custom_col_names_multi_file(self, tmp_path):
        # user col_names override must not trip the cross-file header check
        (tmp_path / "b1.csv").write_text("x,y\n1,2\n")
        (tmp_path / "b2.csv").write_text("x,y\n3,4\n")
        fr = import_file(str(tmp_path / "b?.csv"), col_names=["a", "b"])
        assert fr.names == ["a", "b"] and fr.nrows == 2


class TestReviewFixes:
    def test_parquet_col_names_rename(self, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        p = str(tmp_path / "r.parquet")
        pq.write_table(pa.table({"k": [1.0], "j": [2.0]}), p)
        fr = import_file(p, col_names=["a", "b"])
        assert fr.names == ["a", "b"]

    def test_svmlight_multifile_widths(self, tmp_path):
        (tmp_path / "s1.svm").write_text("1 1:1.0 5:2.0\n")
        (tmp_path / "s2.svm").write_text("0 2:3.0\n")
        fr = import_file(str(tmp_path / "s?.svm"))
        assert fr.ncols == 6 and fr.nrows == 2
        np.testing.assert_allclose(fr.col("C6").to_numpy(), [2.0, 0.0])


class TestOverridesAndTime:
    def test_parquet_col_types_override(self, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        p = str(tmp_path / "o.parquet")
        pq.write_table(pa.table({"k": [1.0, 2.0, 1.0, np.nan]}), p)
        fr = import_file(p, col_types={"k": "enum"})
        c = fr.col("k")
        assert c.is_categorical and c.domain == ["1", "2"]
        assert c.to_numpy()[3] < 0          # NaN -> NA code

    def test_csv_time_is_epoch_millis(self, tmp_path, cl):
        p = tmp_path / "t.csv"
        p.write_text("d,v\n2024-01-01,1\n2024-06-15 12:00:00,2\n")
        fr = import_file(str(p))
        assert fr.col("d").ctype == "time"
        ms = fr.col("d").to_numpy()
        # 2024-01-01 epoch ms ≈ 1.704e12 (a ns value would be ≈1.7e18)
        assert abs(ms[0] - 1704067200000.0) < 1e6

    def test_arff_date_is_epoch_millis(self, tmp_path):
        p = str(tmp_path / "d.arff")
        with open(p, "w") as f:
            f.write("@relation r\n@attribute when date\n@attribute v numeric\n"
                    "@data\n2024-01-01,1\n?,2\n")
        fr = import_file(p)
        ms = fr.col("when").to_numpy()
        assert abs(ms[0] - 1704067200000.0) < 1e6
        assert np.isnan(ms[1])


class TestGatedBinaryFormats:
    def test_xls_fails_fast_and_corrupt_binaries_raise(self, tmp_path):
        # legacy BIFF .xls stays gated; .xlsx/.avro parse natively since
        # round 4 and CORRUPT files raise real parse errors, not CSV soup
        p = tmp_path / "d.xls"
        p.write_bytes(b"\x00\x01binary")
        with pytest.raises(NotImplementedError, match="decoder"):
            import_file(str(p))
        bad_avro = tmp_path / "d.avro"
        bad_avro.write_bytes(b"\x00\x01binary")
        with pytest.raises(ValueError, match="avro"):
            import_file(str(bad_avro))
        bad_xlsx = tmp_path / "d.xlsx"
        bad_xlsx.write_bytes(b"\x00\x01binary")
        with pytest.raises(Exception):
            import_file(str(bad_xlsx))


class TestFileBackedVecs:
    def test_lazy_parquet_columns_materialize_on_touch(self, tmp_path, cl):
        """water/fvec/FileVec analog: numeric columns stay on disk until
        first access; enums load eagerly for their domains."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        from h2o3_tpu.ingest.parser import lazy_import_parquet

        rng = np.random.default_rng(0)
        n = 400
        p = str(tmp_path / "lazy.parquet")
        pq.write_table(pa.table({
            "a": rng.standard_normal(n),
            "b": rng.standard_normal(n),
            "g": np.array(["u", "v"], object)[rng.integers(0, 2, n)],
        }), p)
        fr = lazy_import_parquet(p)
        assert fr.nrows == n
        ca, cb = fr._cols["a"], fr._cols["b"]
        assert ca._data is None and callable(ca._evicted)   # still on disk
        assert fr._cols["g"].domain == ["u", "v"]           # eager enum
        # touching a materializes a ONLY
        va = ca.to_numpy()
        assert ca._data is not None and cb._data is None
        assert np.isfinite(va).all()
        # frame ops work transparently on the lazy column
        assert abs(float(fr.col("b").mean())) < 0.2
        assert cb._data is not None                          # now faulted in

    def test_lazy_frame_trains(self, tmp_path, cl):
        import pyarrow as pa
        import pyarrow.parquet as pq

        from h2o3_tpu.ingest.parser import lazy_import_parquet
        from h2o3_tpu.models.tree.gbm import GBM

        rng = np.random.default_rng(1)
        n = 500
        x = rng.standard_normal(n)
        y = np.where(rng.random(n) < 1 / (1 + np.exp(-2 * x)), "Y", "N")
        p = str(tmp_path / "t.parquet")
        pq.write_table(pa.table({"x": x, "y": y}), p)
        fr = lazy_import_parquet(p)
        m = GBM(ntrees=4, max_depth=3, seed=1).train(y="y", training_frame=fr)
        assert float(m._output.training_metrics.auc) > 0.7

    def test_evicted_lazy_column_reverts_to_disk(self, tmp_path, cl):
        """Evicting a file-backed column must NOT pin a host copy — it
        reverts to the loader and re-reads from the parquet source."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        from h2o3_tpu.ingest.parser import lazy_import_parquet

        p = str(tmp_path / "ev.parquet")
        x = np.arange(300, dtype=np.float64)
        pq.write_table(pa.table({"x": x}), p)
        fr = lazy_import_parquet(p)
        c = fr._cols["x"]
        _ = c.data                      # materialize
        assert c.evict() > 0
        assert callable(c._evicted)     # back to the disk loader, not RAM
        np.testing.assert_allclose(c.to_numpy(), x)   # re-reads fine
