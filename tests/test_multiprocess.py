"""Tier-2 multi-process tests: 2 jax.distributed processes on localhost.

Reference analog: SURVEY.md §4 tier 2 — the 4-JVM localhost cloud
(multiNodeUtils.sh:22-27). Here: 2 OS processes × 2 virtual CPU devices
form a 4-device global mesh; collectives cross the process boundary over
the jax.distributed transport."""

import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_workers(script: str, timeout: int = 480):
    port = _free_port()
    worker = os.path.join(os.path.dirname(__file__), script)
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS",)}          # workers pick their own count
    env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(worker)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen([sys.executable, worker, str(port), str(i)],
                              stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                              env=env, cwd=os.path.dirname(os.path.dirname(worker)))
             for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out.decode())
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"multi-process workers hung; partial output: {outs}")
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out[-3000:]}"
        assert f"proc {i}: OK" in out


def test_two_process_cloud_trains_glm():
    _run_workers("mp_worker.py")


def test_two_process_sort_join_dl_rapids_automl():
    """Round-5 widening (VERDICT r4 item 4): sort/join all_to_all,
    DeepLearning, Rapids replay, and a broadcast AutoML build — all across
    a real jax.distributed process boundary."""
    _run_workers("mp_worker2.py", timeout=600)
