"""SharedTree family tests (reference test model: h2o-algos/src/test/java
hex/tree/gbm/GBMTest.java, drf/DRFTest.java, isofor/IsolationForestTest.java)."""

import numpy as np
import pytest

from h2o3_tpu.core.frame import Column, Frame


def _friedman(n=3000, seed=7):
    """Friedman #1 regression surface — standard tree benchmark."""
    rng = np.random.default_rng(seed)
    X = rng.random((n, 5))
    y = (10 * np.sin(np.pi * X[:, 0] * X[:, 1]) + 20 * (X[:, 2] - 0.5) ** 2
         + 10 * X[:, 3] + 5 * X[:, 4] + rng.normal(0, 1, n))
    fr = Frame.from_numpy(np.column_stack([X, y]),
                          names=["x1", "x2", "x3", "x4", "x5", "y"])
    return fr, y


def _binary(n=3000, seed=11):
    rng = np.random.default_rng(seed)
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    g = np.array(["a", "b", "c"])[rng.integers(0, 3, n)]
    eff = {"a": 1.2, "b": -0.8, "c": 0.0}
    logit = 1.3 * x1 - 0.9 * x2 + np.array([eff[v] for v in g])
    y = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "YES", "NO")
    fr = Frame()
    fr.add("x1", Column.from_numpy(x1))
    fr.add("x2", Column.from_numpy(x2))
    fr.add("g", Column.from_numpy(g, ctype="enum"))
    fr.add("y", Column.from_numpy(y, ctype="enum"))
    return fr


def test_gbm_regression_beats_constant(cl):
    from h2o3_tpu.models.tree.gbm import GBM

    fr, y = _friedman()
    m = GBM(ntrees=30, max_depth=4, learn_rate=0.2).train(y="y", training_frame=fr)
    mm = m._output.training_metrics
    assert mm.rmse < 0.5 * np.std(y)
    pred = m.predict(fr).col("predict").to_numpy()
    assert np.corrcoef(pred, y)[0, 1] > 0.9


def test_gbm_binomial_auc(cl):
    from h2o3_tpu.models.tree.gbm import GBM

    fr = _binary()
    m = GBM(ntrees=25, max_depth=3).train(y="y", training_frame=fr)
    assert m._output.training_metrics.auc > 0.80
    pr = m.predict(fr)
    assert pr.col("predict").domain == ["NO", "YES"]
    p = pr.col("YES").to_numpy()
    assert np.all((p >= 0) & (p <= 1))


def test_gbm_varimp_finds_signal(cl):
    from h2o3_tpu.models.tree.gbm import GBM

    fr, _ = _friedman()
    m = GBM(ntrees=15, max_depth=4).train(y="y", training_frame=fr)
    vi = m.varimp()
    assert vi is not None
    # x4 carries the strongest linear signal; x5 the weakest of the real ones
    assert list(vi)[0] in ("x4", "x1", "x2")


def test_gbm_multinomial(cl):
    from h2o3_tpu.models.tree.gbm import GBM

    rng = np.random.default_rng(5)
    n = 2400
    x1, x2 = rng.normal(size=n), rng.normal(size=n)
    cls = np.where(x1 + x2 > 0.8, "hi", np.where(x1 - x2 < -0.8, "lo", "mid"))
    fr = Frame()
    fr.add("x1", Column.from_numpy(x1))
    fr.add("x2", Column.from_numpy(x2))
    fr.add("y", Column.from_numpy(cls, ctype="enum"))
    m = GBM(ntrees=10, max_depth=3).train(y="y", training_frame=fr)
    mm = m._output.training_metrics
    assert mm.mean_per_class_error < 0.2
    probs = m.predict(fr)
    assert set(probs.names) >= {"predict", "hi", "lo", "mid"}


def test_gbm_early_stopping(cl):
    from h2o3_tpu.models.tree.gbm import GBM

    fr, _ = _friedman(1500)
    m = GBM(ntrees=200, max_depth=3, stopping_rounds=2, stopping_tolerance=0.5,
            score_each_iteration=True).train(y="y", training_frame=fr)
    assert len(m._output.scoring_history) < 200


def test_gbm_weights_na_response(cl):
    """NA responses drop out; zero-weight rows don't influence the fit."""
    from h2o3_tpu.models.tree.gbm import GBM

    rng = np.random.default_rng(2)
    n = 1000
    x = rng.normal(size=n)
    y = 2 * x + rng.normal(0, 0.1, n)
    y[::10] = np.nan
    fr = Frame.from_numpy(np.column_stack([x, y]), names=["x", "y"])
    m = GBM(ntrees=10, max_depth=3).train(y="y", training_frame=fr)
    assert np.isfinite(m._output.training_metrics.rmse)


def test_drf_regression(cl):
    from h2o3_tpu.models.tree.drf import DRF

    fr, y = _friedman(2000)
    m = DRF(ntrees=20, max_depth=10).train(y="y", training_frame=fr)
    pred = m.predict(fr).col("predict").to_numpy()
    assert np.corrcoef(pred, y)[0, 1] > 0.85


def test_drf_binomial(cl):
    from h2o3_tpu.models.tree.drf import DRF

    fr = _binary(2000)
    m = DRF(ntrees=20, max_depth=8).train(y="y", training_frame=fr)
    assert m._output.training_metrics.auc > 0.75


def test_isolation_forest_separates_outliers(cl):
    from h2o3_tpu.models.tree.isofor import IsolationForest

    rng = np.random.default_rng(9)
    inliers = rng.normal(0, 1, (950, 2))
    outliers = rng.uniform(6, 9, (50, 2))
    X = np.vstack([inliers, outliers])
    fr = Frame.from_numpy(X, names=["a", "b"])
    m = IsolationForest(ntrees=40, sample_size=200).train(training_frame=fr)
    sc = m.predict(fr)
    s = sc.col("predict").to_numpy()
    assert s[950:].mean() > s[:950].mean() + 0.1
    assert "mean_length" in sc.names


def test_gbm_gaussian_large_mean(cl):
    """Identity-link init must not clip large response means (review fix)."""
    from h2o3_tpu.models.tree.gbm import GBM

    rng = np.random.default_rng(3)
    X = rng.normal(size=(2000, 3))
    y = 1e6 + 100 * X[:, 0] + rng.normal(0, 10, 2000)
    fr = Frame.from_numpy(np.column_stack([X, y]), names=["a", "b", "c", "y"])
    m = GBM(ntrees=20, max_depth=3).train(y="y", training_frame=fr)
    pred = m.predict(fr).col("predict").to_numpy()
    assert abs(pred.mean() - 1e6) < 1e3
    assert m._output.training_metrics.rmse < 500


def test_drf_training_metrics_are_oob(cl):
    """DRF training metrics come from out-of-bag predictions (review fix)."""
    from h2o3_tpu.models.tree.drf import DRF

    fr = _binary()
    m = DRF(ntrees=30, max_depth=10, seed=5).train(y="y", training_frame=fr)
    mm = m._output.training_metrics
    # in-bag AUC of a depth-10 forest is ~1.0; OOB must be meaningfully lower
    raw = m._predict_raw(m.adapt_test(fr))
    inbag = m._make_metrics(fr, raw)
    assert mm.auc < inbag.auc
    assert 0.6 < mm.auc <= 1.0


def test_gbm_annealing_and_leaf_clip(cl):
    from h2o3_tpu.models.tree.gbm import GBM

    fr, _ = _friedman()
    m = GBM(ntrees=10, max_depth=3, learn_rate=0.5, learn_rate_annealing=0.5,
            max_abs_leafnode_pred=0.1).train(y="y", training_frame=fr)
    # all leaf contributions bounded by max_abs_leafnode_pred * learn_rate
    assert float(np.abs(np.asarray(m.forest.leaf_val)).max()) <= 0.05 + 1e-6


def test_drf_binomial_double_trees(cl):
    from h2o3_tpu.models.tree.drf import DRF

    fr = _binary()
    m = DRF(ntrees=20, max_depth=8, binomial_double_trees=True, seed=2).train(
        y="y", training_frame=fr)
    assert m._output.training_metrics.auc > 0.75
    pred = m.predict(fr)
    p = np.column_stack([pred.col(c).to_numpy() for c in pred.names[1:]])
    assert np.allclose(p.sum(1), 1.0, atol=1e-5)
    # the PREDICT path must be discriminative too, not just the OOB
    # metrics — round-5 regression: per-class trees were summed into one
    # slot by the traversal (compressed.py per_class_trees)
    yv = fr.col("y").to_numpy()
    p1 = p[:, 1]
    corr = np.corrcoef(p1, (yv == 1).astype(float))[0, 1]
    assert corr > 0.5, corr


class TestXGBoostBoosters:
    """booster='dart' (DartBooster, normalize_type=tree) and
    booster='gblinear' (linear boosting == elastic-net GLM limit)."""

    @staticmethod
    def _frame(n=1200, seed=4):
        import numpy as np

        from h2o3_tpu.core.frame import Column, Frame

        rng = np.random.default_rng(seed)
        x1, x2 = rng.standard_normal((2, n))
        y = np.where(rng.random(n) < 1 / (1 + np.exp(-(2 * x1 - x2))),
                     "Y", "N")
        fr = Frame()
        fr.add("x1", Column.from_numpy(x1))
        fr.add("x2", Column.from_numpy(x2))
        fr.add("y", Column.from_numpy(y, ctype="enum"))
        return fr

    def test_dart_trains_and_drops(self, cl):
        import numpy as np

        from h2o3_tpu.models.xgboost import XGBoost

        fr = self._frame()
        m = XGBoost(booster="dart", ntrees=12, max_depth=3, rate_drop=0.3,
                    seed=1, score_each_iteration=True).train(
            y="y", training_frame=fr)
        assert m.forest.n_trees == 12
        hist = m._output.scoring_history
        assert any(h["dropped"] > 0 for h in hist)    # dropout actually fired
        assert float(m._output.training_metrics.auc) > 0.8
        p = m.predict(fr).col("Y").to_numpy()
        assert np.all(np.isfinite(p))
        # deviance still decreases overall despite dropout
        assert hist[-1]["training_deviance"] < hist[0]["training_deviance"]

    def test_dart_zero_drop_matches_gbtree(self, cl):
        import numpy as np

        from h2o3_tpu.models.xgboost import XGBoost

        fr = self._frame()
        kw = dict(ntrees=6, max_depth=3, seed=2)
        a = XGBoost(booster="dart", rate_drop=0.0, **kw).train(
            y="y", training_frame=fr)
        b = XGBoost(booster="gbtree", **kw).train(y="y", training_frame=fr)
        pa = a.predict(fr).col("Y").to_numpy()
        pb = b.predict(fr).col("Y").to_numpy()
        np.testing.assert_allclose(pa, pb, atol=1e-5)

    def test_gblinear_delegates_to_elastic_net(self, cl):
        import numpy as np

        from h2o3_tpu.models.xgboost import XGBoost

        fr = self._frame()
        m = XGBoost(booster="gblinear", reg_lambda=1.0, reg_alpha=0.0,
                    seed=3).train(y="y", training_frame=fr)
        assert m._parms["booster"] == "gblinear"
        assert float(m._output.training_metrics.auc) > 0.8
        coefs = m.coef()
        assert abs(coefs["x1"]) > abs(coefs["x2"]) > 0   # linear recovery

    def test_dart_validation_stopping_and_guards(self, cl):
        import numpy as np
        import pytest

        from h2o3_tpu.models.xgboost import XGBoost

        fr = self._frame()
        va = self._frame(seed=9)
        m = XGBoost(booster="dart", ntrees=20, max_depth=3, rate_drop=0.2,
                    seed=1, stopping_rounds=2, score_each_iteration=True,
                    ).train(y="y", training_frame=fr, validation_frame=va)
        hist = m._output.scoring_history
        assert all("validation_deviance" in h for h in hist)
        with pytest.raises(ValueError, match="unknown booster"):
            XGBoost(booster="gblineer", ntrees=2).train(
                y="y", training_frame=fr)
        # multinomial dart rejected, not silently gbtree
        from h2o3_tpu.core.frame import Column, Frame

        rng = np.random.default_rng(0)
        f3 = Frame()
        f3.add("x", Column.from_numpy(rng.standard_normal(200)))
        f3.add("y", Column.from_numpy(
            np.array(list("abc"))[rng.integers(0, 3, 200)], ctype="enum"))
        with pytest.raises(ValueError, match="binomial/regression"):
            XGBoost(booster="dart", ntrees=2, rate_drop=0.5).train(
                y="y", training_frame=f3)


def test_gbm_tweedie_trains(cl):
    """Tweedie GBM: init_f aliasing to the 4-arg gamma_num crashed training
    at startup (round-5 fix); distribution now trains, beats the mean-only
    model, and round-trips through the MOJO."""
    from h2o3_tpu.models import mojo
    from h2o3_tpu.models.tree.gbm import GBM

    rng = np.random.default_rng(6)
    n = 600
    X = rng.normal(size=(n, 3))
    y = rng.poisson(np.exp(0.5 * X[:, 0] + 0.3 * X[:, 1])).astype(float)
    fr = Frame.from_numpy(np.column_stack([X, y]),
                          names=["a", "b", "c", "y"])
    m = GBM(ntrees=5, max_depth=3, distribution="tweedie", seed=1).train(
        y="y", training_frame=fr)
    p = np.asarray(m.predict(fr).col("predict").to_numpy(), float)
    assert np.isfinite(p).all() and (p > 0).all()
    assert np.mean((p - y) ** 2) < np.var(y)
    lm = mojo.read_mojo(mojo.export_mojo_bytes(m))
    p2 = np.asarray(lm.predict(fr).col("predict").to_numpy(), float)
    np.testing.assert_allclose(p, p2, atol=1e-7)
    # nonzero OFFSET exercises the init_f_num exponent itself: a constant
    # log(2) offset must shift the whole fit down by EXACTLY that margin
    # (rate predictions halve, per row) relative to the no-offset model —
    # init and every tree see the same shifted margin
    # (TweedieDistribution.initFNum parity)
    off = np.log(np.full(n, 2.0))
    fro = Frame.from_numpy(np.column_stack([X, off, y]),
                           names=["a", "b", "c", "off", "y"])
    mo = GBM(ntrees=5, max_depth=3, distribution="tweedie",
             offset_column="off", seed=1).train(y="y", training_frame=fro)
    po = np.asarray(mo.predict(fro).col("predict").to_numpy(), float)
    assert np.isfinite(po).all() and (po > 0).all()
    np.testing.assert_allclose(p / po, 2.0, rtol=1e-5)
