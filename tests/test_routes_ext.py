"""Extended REST surface tests (routes_ext.py) — every new route family
exercised over real HTTP.

Reference: water/api/RegisterV3Api.java:23 route table; the route-diff
against it must be empty (asserted below)."""

import json
import re
import subprocess
import urllib.request

import numpy as np
import pytest

from h2o3_tpu import client
from h2o3_tpu.api.server import start_server
from h2o3_tpu.core.frame import Column, Frame


@pytest.fixture(scope="module")
def server(cl):
    srv = start_server(port=0)
    client.connect(port=srv.port)
    yield srv
    srv.stop()


@pytest.fixture(scope="module")
def frame(server, cl):
    rng = np.random.default_rng(3)
    n = 400
    fr = Frame(key="ext_fr")
    fr.add("x", Column.from_numpy(rng.normal(size=n)))
    fr.add("x2", Column.from_numpy(rng.normal(size=n)))
    fr.add("g", Column.from_numpy(
        np.array(["u", "v", "w"])[rng.integers(0, 3, n)], ctype="enum"))
    fr.add("y", Column.from_numpy(
        np.where(rng.random(n) > 0.5, "Y", "N"), ctype="enum"))
    fr.install()
    return fr


@pytest.fixture(scope="module")
def model(server, frame, cl):
    from h2o3_tpu.models.tree.gbm import GBM

    m = GBM(ntrees=5, max_depth=3, seed=1).train(
        x=["x", "x2", "g"], y="y", training_frame=frame)
    m.install()
    return m


def _get(path, query=None):
    return client._req("GET", path, query=query)


def _post(path, data=None, query=None):
    return client._req("POST", path, data=data, query=query)


def _raw(path):
    import h2o3_tpu.client as C

    with urllib.request.urlopen(C._BASE + path, timeout=120) as r:
        return r.read()


def test_route_diff_vs_reference_empty(server):
    ref = subprocess.run(
        ["grep", "-oE", '"(GET|POST|DELETE|PUT|HEAD) [^"]+"',
         "/root/reference/h2o-core/src/main/java/water/api/"
         "RegisterV3Api.java"], capture_output=True, text=True).stdout
    refset = set()
    for ln in ref.splitlines():
        m, p = ln.strip('"').split(" ", 1)
        refset.add((m, re.sub(r"\{[^}]+\}", "{}", p)))
    from h2o3_tpu.api.server import ROUTES

    ours = set((m, re.sub(r"\{[^}]+\}", "{}", p)) for m, p, _, _ in ROUTES)
    assert refset - ours == set()


def test_capabilities(server):
    out = _get("/3/Capabilities")
    names = [c["name"] for c in out["capabilities"]]
    assert "MOJO" in names and "AutoML" in names
    api = _get("/3/Capabilities/API")
    assert len(api["capabilities"]) > 100
    core = _get("/3/Capabilities/Core")
    assert core["capabilities"]


def test_frame_columns_family(server, frame):
    cols = _get("/3/Frames/ext_fr/columns")
    assert cols["frames"][0]["column_names"] == ["x", "x2", "g", "y"]
    one = _get("/3/Frames/ext_fr/columns/x")
    assert one["frames"][0]["columns"][0]["label"] == "x"
    dom = _get("/3/Frames/ext_fr/columns/g/domain")
    assert dom["domain"][0] == ["u", "v", "w"]
    summ = _get("/3/Frames/ext_fr/columns/x/summary")
    assert "percentiles" in summ["frames"][0]["columns"][0]
    chunks = _get("/3/FrameChunks/ext_fr")
    assert sum(c["row_count"] for c in chunks["chunks"]) == 400


def test_frame_export_and_binary_save_load(server, frame, tmp_path):
    p = tmp_path / "out.csv"
    _post("/3/Frames/ext_fr/export", data={"path": str(p), "force": True})
    assert p.exists() and p.read_text().startswith("x,")
    d = tmp_path / "frames"
    _post("/3/Frames/ext_fr/save", data={"dir": str(d)})
    # rename on disk so load produces a fresh key
    out = _post("/3/Frames/load", data={"dir": str(d), "frame_id": "ext_fr"})
    assert out["job"]["status"] == "DONE"


def test_model_binary_roundtrip(server, model, frame, tmp_path):
    blob = _raw(f"/3/Models.fetch.bin/{model.key}")
    assert len(blob) > 500
    d = tmp_path / "models"
    _post(f"/99/Models.bin/{model.key}", data={"dir": str(d)})
    from h2o3_tpu.core.dkv import DKV

    DKV.remove(str(model.key))
    out = _post("/99/Models.bin/", data={"dir": str(d / str(model.key))})
    assert out["models"][0]["model_id"]["name"] == str(model.key)
    assert DKV.get(str(model.key)) is not None


def test_pojo_export(server, model):
    src = _raw(f"/3/Models.java/{model.key}").decode()
    assert "public class" in src
    assert "score0" in src
    assert "static final int[][] FEAT" in src
    prev = _raw(f"/3/Models.java/{model.key}/preview").decode()
    assert "public class" in prev


def test_modelmetrics_family(server, model, frame):
    out = _post(f"/3/ModelMetrics/models/{model.key}/frames/ext_fr")
    assert out["model_metrics"]
    lst = _get("/3/ModelMetrics")
    assert any(mm.get("frame", {}) and
               (mm.get("frame") or {}).get("name") == "ext_fr"
               for mm in lst["model_metrics"])
    per_model = _get(f"/3/ModelMetrics/models/{model.key}")
    assert per_model["model_metrics"]
    client._req("DELETE", f"/3/ModelMetrics/models/{model.key}/frames/ext_fr")
    lst2 = _get(f"/3/ModelMetrics/frames/ext_fr")
    assert not lst2["model_metrics"]


def test_metrics_from_predictions_frame(server, model, frame):
    pred = model.predict(frame, key="ext_pred")
    pred.install()
    # build an actuals frame holding just the response
    actual = Frame(key="ext_actual")
    actual.add("y", frame.col("y"))
    actual.install()
    out = _post("/3/ModelMetrics/predictions_frame/ext_pred/"
                "actuals_frame/ext_actual")
    mm = out["model_metrics"][0]
    assert 0.0 <= mm["AUC"] <= 1.0


def test_nps(server):
    assert _get("/3/NodePersistentStorage/configured")["configured"]
    _post("/3/NodePersistentStorage/testcat/alpha", data={"value": "hello"})
    got = _raw("/3/NodePersistentStorage/testcat/alpha")
    assert got == b"hello"
    lst = _get("/3/NodePersistentStorage/testcat")
    assert any(e["name"] == "alpha" for e in lst["entries"])
    assert _get("/3/NodePersistentStorage/categories/testcat/exists")["exists"]
    assert _get("/3/NodePersistentStorage/categories/testcat/names/alpha"
                "/exists")["exists"]
    client._req("DELETE", "/3/NodePersistentStorage/testcat/alpha")
    assert not _get("/3/NodePersistentStorage/categories/testcat/names/alpha"
                    "/exists")["exists"]


def test_admin_diagnostics(server):
    js = _get("/3/JStack")
    assert js["traces"][0]["thread_traces"]
    _get("/3/KillMinus3")
    echo = _post("/3/LogAndEcho", data={"message": "routes-ext-test"})
    assert echo["message"] == "routes-ext-test"
    ticks = _get("/3/WaterMeterCpuTicks/0")
    assert "cpu_ticks" in ticks
    io_ = _get("/3/WaterMeterIo")
    assert "persist_stats" in io_
    steam = _get("/3/SteamMetrics")
    assert steam["cloud_size"] >= 1
    _post("/3/GarbageCollect")
    _post("/3/UnlockKeys")
    _post("/3/CloudLock", data={"reason": "test"})


def test_typeahead_and_find(server, frame, tmp_path):
    (tmp_path / "ta_one.csv").write_text("a\n1\n")
    out = _get("/3/Typeahead/files",
               query={"src": str(tmp_path / "ta_"), "limit": 10})
    assert any("ta_one.csv" in m for m in out["matches"])
    hit = _get("/3/Find", query={"key": "ext_fr", "column": "g",
                                 "row": 0, "match": "w"})
    assert hit["next"] >= 0


def test_rapids_help_and_sample(server, frame):
    out = _get("/99/Rapids/help")
    assert "cumsum" in out["syntax"]
    samp = _get("/99/Sample", query={"dataset": "ext_fr", "rows": 50,
                                     "seed": 7})
    assert samp["frames"][0]["rows"] == 50


def test_missing_inserter(server, cl):
    rng = np.random.default_rng(0)
    fr = Frame(key="mi_fr")
    fr.add("x", Column.from_numpy(rng.normal(size=300)))
    fr.install()
    _post("/3/MissingInserter", data={"dataset": "mi_fr", "fraction": 0.3,
                                      "seed": 1})
    na = int(np.isnan(np.asarray(fr.col("x").to_numpy())).sum())
    assert 40 < na < 160


def test_interaction(server, frame, cl):
    out = _post("/3/Interaction", data={
        "source_frame": "ext_fr", "factor_columns": ["g", "y"],
        "pairwise": False, "max_factors": 100, "dest": "gxy"})
    assert out["job"]["status"] == "DONE"
    from h2o3_tpu.core.dkv import DKV

    inter = DKV.get("gxy")
    assert inter.ncols == 1
    assert inter.col(inter.names[0]).cardinality <= 6


def test_dct_and_tabulate(server, frame):
    out = _post("/99/DCTTransformer", data={
        "dataset": "ext_fr", "dimensions": [2, 1, 1],
        "destination_frame": "dct_out"})
    from h2o3_tpu.core.dkv import DKV

    dct = DKV.get("dct_out")
    assert dct.ncols == 2
    tab = _post("/99/Tabulate", data={"dataset": "ext_fr", "predictor": "g",
                                      "response": "x"})
    assert tab["count_table"]["name"].startswith("Tabulate")


def test_svmlight_over_rest(server, tmp_path):
    p = tmp_path / "small.svm"
    p.write_text("1 1:0.5 3:1.5\n0 2:2.0\n")
    out = _post("/3/ParseSVMLight", data={"source_frames": [str(p)]})
    assert out["job"]["status"] == "DONE"


def test_grid_export_import(server, frame, tmp_path, cl):
    from h2o3_tpu.grid import H2OGridSearch
    from h2o3_tpu.models.tree.gbm import GBM

    grid = H2OGridSearch(GBM(seed=1, ntrees=3),
                         {"max_depth": [2, 3]}, grid_id="ext_grid")
    grid.train(y="y", training_frame=frame)
    grid.install()
    d = tmp_path / "grids"
    _post("/3/Grid.bin/ext_grid/export", data={"grid_directory": str(d)})
    from h2o3_tpu.core.dkv import DKV

    DKV.remove("ext_grid")
    out = _post("/3/Grid.bin/import",
                data={"grid_path": str(d / "ext_grid")})
    assert out["grid_id"]["name"] == "ext_grid"
    lst = _get("/99/Grids")
    assert any(g["grid_id"]["name"] == "ext_grid" for g in lst["grids"])


def test_assembly_over_rest(server, frame):
    steps = ["colSel__H2OColSelect__(cols_py dummy ['x','g'])__False__|"]
    out = _post("/99/Assembly", data={"frame": "ext_fr",
                                      "steps": steps,
                                      "assembly_id": "asm1"})
    assert out["assembly"]["name"] == "asm1"
    from h2o3_tpu.core.dkv import DKV

    res = DKV.get(out["result"]["name"])
    assert res.names == ["x", "g"]
    src = _raw("/99/Assembly.java/asm1/MyPipe").decode()
    assert "MyPipe" in src or "step" in src


def test_metadata_detail_and_gated_routes(server):
    ep = _get("/3/Metadata/endpoints/cloud")
    assert ep["endpoints"][0]["url_pattern"] == "/3/Cloud"
    sc = _get("/3/Metadata/schemaclasses/water.api.schemas3.CloudV3")
    assert sc["schemas"][0]["name"] == "CloudV3"
    with pytest.raises(client.H2OServerError):
        _post("/3/SaveToHiveTable", data={"table_name": "t"})
    out = _post("/3/DecryptionSetup", data={
        "decrypt_tool": "water.parser.NullDecryptionTool",
        "decrypt_impl": "nulltool"})
    assert out["decrypt_tool_id"]["name"] == "nulltool"


def test_upload_bin_rejects_malicious_pickle(server):
    """Pickle payloads referencing non-framework callables must be
    rejected, not executed (restricted unpickler)."""
    import pickle

    class Evil:
        def __reduce__(self):
            return (print, ("pwned",))

    payload = pickle.dumps(Evil())
    import h2o3_tpu.client as C

    req = urllib.request.Request(
        C._BASE + "/99/Models.upload.bin/evil", data=payload,
        headers={"Content-Type": "application/octet-stream"}, method="POST")
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=60)
    assert ei.value.code == 400


def test_drf_binomial_pojo_clips_not_sigmoid(server, frame, cl):
    from h2o3_tpu.models import pojo
    from h2o3_tpu.models.tree.drf import DRF

    m = DRF(ntrees=5, max_depth=4, seed=1).train(
        x=["x", "x2", "g"], y="y", training_frame=frame)
    src = pojo.pojo_source(m)
    assert "Math.exp(-f)" not in src          # DRF votes are probabilities
    assert "Math.min(Math.max(f, 0.0), 1.0)" in src


def test_find_skips_na_and_nonnumeric(server, frame, cl):
    import jax.numpy as jnp

    from h2o3_tpu.core.dkv import DKV

    # the binary save/load test re-installs "ext_fr": mutate the LIVE one
    g = DKV.get("ext_fr").col("g")
    data = g.data
    g.data = jnp.where(jnp.arange(data.shape[0]) == 0, -1, data)  # NA row 0
    hit = _get("/3/Find", query={"key": "ext_fr", "column": "g",
                                 "row": 0, "match": "u"})
    assert hit["next"] != 0                   # NA row must not match 'u'
    out = _get("/3/Find", query={"key": "ext_fr", "column": "x",
                                 "row": 0, "match": "abc"})
    assert out["next"] == -1                  # non-numeric needle: no 500


def test_drf_double_trees_pojo_per_class(cl):
    """POJO for binomial_double_trees keeps per-class accumulators and
    labels with the model threshold (round-5 fix, third runtime)."""
    import numpy as np

    from h2o3_tpu.core.frame import Column, Frame
    from h2o3_tpu.models import pojo
    from h2o3_tpu.models.mojo import _default_threshold
    from h2o3_tpu.models.tree.drf import DRF

    rng = np.random.default_rng(4)
    n = 400
    X = rng.normal(size=(n, 2))
    y = np.where(rng.random(n) < 1 / (1 + np.exp(-2 * X[:, 0])), "Y", "N")
    fr = Frame.from_numpy(X, names=["a", "b"])
    fr.add("y", Column.from_numpy(y, ctype="enum"))
    m = DRF(ntrees=6, max_depth=4, binomial_double_trees=True,
            seed=4).train(y="y", training_frame=fr)
    src = pojo.pojo_source(m)
    assert "NCLASSES = 2" in src
    assert "acc[TREE_CLASS[t]]" in src          # per-class accumulation
    thr = _default_threshold(m)
    assert f"preds[2] >= {thr!r}" in src        # threshold, not argmax
