"""GLRM + Word2Vec tests."""

import numpy as np
import pytest

from h2o3_tpu.core.frame import Column, Frame


def test_glrm_low_rank_recovery(cl):
    from h2o3_tpu.models.glrm import GLRM

    rng = np.random.default_rng(0)
    Xt = rng.normal(size=(1500, 3))
    Yt = rng.normal(size=(3, 8))
    A = Xt @ Yt + 0.01 * rng.normal(size=(1500, 8))
    fr = Frame.from_numpy(A, names=[f"c{i}" for i in range(8)])
    m = GLRM(k=3, loss="Quadratic", max_iterations=300, seed=1).train(
        training_frame=fr)
    recon = m.predict(fr).to_numpy()
    rel = np.linalg.norm(recon - A) / np.linalg.norm(A)
    assert rel < 0.05
    assert m.archetypes.shape == (3, 8)


def test_glrm_nonneg_regularization(cl):
    from h2o3_tpu.core.dkv import DKV
    from h2o3_tpu.models.glrm import GLRM

    rng = np.random.default_rng(1)
    A = np.abs(rng.normal(size=(800, 5)))
    fr = Frame.from_numpy(A, names=[f"c{i}" for i in range(5)])
    m = GLRM(k=2, regularization_x="NonNegative", regularization_y="NonNegative",
             max_iterations=200, seed=2).train(training_frame=fr)
    X = DKV.get(m.x_key)
    xv = X.to_numpy()
    assert xv.min() >= 0.0
    assert m.archetypes.min() >= 0.0


def test_word2vec_synonyms(cl):
    from h2o3_tpu.models.word2vec import Word2Vec

    rng = np.random.default_rng(3)
    # synthetic corpus: "cat"/"dog" share contexts; "car"/"truck" share others
    animals = ["cat", "dog"]
    vehicles = ["car", "truck"]
    a_ctx = ["fur", "paw", "meow", "pet"]
    v_ctx = ["road", "wheel", "engine", "drive"]
    words = []
    for _ in range(3000):
        if rng.random() < 0.5:
            words += [rng.choice(animals)] + list(rng.choice(a_ctx, 2))
        else:
            words += [rng.choice(vehicles)] + list(rng.choice(v_ctx, 2))
        words.append(None)   # sentence break
    fr = Frame()
    fr.add("word", Column.from_numpy(np.asarray(words, object)))
    m = Word2Vec(vec_size=16, epochs=8, min_word_freq=5, window_size=2,
                 seed=4).train(training_frame=fr)
    syn = m.find_synonyms("cat", 3)
    assert "dog" in list(syn)[:2]
    syn_v = m.find_synonyms("car", 3)
    assert "truck" in list(syn_v)[:2]


def test_word2vec_transform_average(cl):
    from h2o3_tpu.models.word2vec import Word2Vec

    words = (["alpha", "beta", None] * 200) + (["alpha", None] * 100)
    fr = Frame()
    fr.add("word", Column.from_numpy(np.asarray(words, object)))
    m = Word2Vec(vec_size=8, epochs=3, min_word_freq=2, window_size=2,
                 sent_sample_rate=0.0, seed=5).train(training_frame=fr)
    emb = m.transform(fr, aggregate_method="AVERAGE")
    assert emb.ncols == 8
    assert emb.nrows == 300
    v = m.word_vec("alpha")
    assert v is not None and v.shape == (8,)


def test_glrm_mixed_losses_and_categoricals(cl):
    """Loss grid (GlrmLoss.java): categorical one-hot block under the
    Categorical multi-loss, numeric columns under per-column overrides
    (loss_by_col/loss_by_col_idx in frame order)."""
    import numpy as np

    from h2o3_tpu.core.frame import Column, Frame, T_CAT
    from h2o3_tpu.models.glrm import GLRM

    rng = np.random.default_rng(9)
    n = 200
    g = np.asarray(["a", "b", "c"])[rng.integers(0, 3, n)]
    x1 = rng.normal(size=n) + (g == "a") * 2.0
    x2 = rng.normal(size=n) - (g == "b") * 1.5
    fr = Frame()
    fr.add("g", Column.from_numpy(g, ctype=T_CAT))
    fr.add("x1", Column.from_numpy(x1))
    fr.add("x2", Column.from_numpy(x2))
    m = GLRM(k=2, loss="Quadratic", multi_loss="Categorical",
             loss_by_col=["Huber"], loss_by_col_idx=[2],   # x2 → Huber
             max_iterations=200, seed=1).train(training_frame=fr)
    rec = m.predict(fr)
    assert set(rec.names) == {"reconstr_g", "reconstr_x1", "reconstr_x2"}
    # the categorical reconstruction should beat chance by a wide margin
    acc = (rec.col("reconstr_g").values() == g).mean()
    assert acc > 0.6, acc
    err = float(np.mean((np.asarray(rec.col("reconstr_x1").to_numpy())
                         - x1) ** 2))
    assert err < 1.0, err


def test_glrm_ordinal_multiloss_rejected(cl):
    import numpy as np
    import pytest

    from h2o3_tpu.core.frame import Frame
    from h2o3_tpu.models.glrm import GLRM

    fr = Frame.from_numpy(np.random.default_rng(0).normal(size=(50, 3)),
                          names=["a", "b", "c"])
    with pytest.raises(NotImplementedError):
        GLRM(k=2, multi_loss="Ordinal").train(training_frame=fr)
