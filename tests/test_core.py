"""Core runtime tests: cluster boot, DKV, Frame/Column, rollups, MRTask.

Mirrors the reference's h2o-core test families: KVTest/DKVTest (DKV verbs),
MRTaskTest (map/reduce), RollupStats tests."""

import numpy as np
import pytest


def test_cluster_boot(cl):
    info = cl.info()
    assert info["cloud_size"] == 8
    assert info["cloud_healthy"]
    assert cl.mesh.shape["rows"] == 8


def test_dkv_verbs(cl):
    from h2o3_tpu.core.dkv import DKV, Key, Scope

    k = Key.make("t")
    DKV.put(k, {"a": 1})
    assert DKV.get(k) == {"a": 1}
    DKV.atomic(k, lambda old: {**old, "b": 2})
    assert DKV.get(k)["b"] == 2
    DKV.remove(k)
    assert DKV.get(k) is None

    with Scope():
        k2 = Key.make("scoped")
        DKV.put(k2, 42)
        assert DKV.get(k2) == 42
    assert DKV.get(k2) is None  # RAII cleanup


def test_column_roundtrip(cl):
    from h2o3_tpu.core.frame import Column

    v = np.array([1.0, 2.0, np.nan, 4.0, 5.0])
    c = Column.from_numpy(v)
    assert c.nrows == 5
    assert c.padded_rows % 8 == 0
    back = c.to_numpy()
    np.testing.assert_allclose(back[[0, 1, 3, 4]], v[[0, 1, 3, 4]])
    assert np.isnan(back[2])


def test_rollups(cl):
    from h2o3_tpu.core.frame import Column

    v = np.array([1.0, 2.0, np.nan, 4.0, 0.0, -3.0])
    c = Column.from_numpy(v)
    r = c.rollups
    assert r.min == -3.0
    assert r.max == 4.0
    assert r.na_count == 1
    assert r.nz_count == 4
    np.testing.assert_allclose(r.mean, np.nanmean(v), rtol=1e-6)
    np.testing.assert_allclose(r.sigma, np.nanstd(v, ddof=1), rtol=1e-5)


def test_categorical_column(cl):
    from h2o3_tpu.core.frame import Column, T_CAT

    v = np.array(["b", "a", "c", "a", None], dtype=object)
    c = Column.from_numpy(v, ctype=T_CAT)
    assert c.domain == ["a", "b", "c"]
    codes = c.to_numpy()
    assert list(codes) == [1, 0, 2, 0, -1]
    vals = c.values()
    assert list(vals[:4]) == ["b", "a", "c", "a"]
    assert vals[4] is None
    assert c.rollups.na_count == 1


def test_map_reduce_sum(cl):
    import jax.numpy as jnp
    from h2o3_tpu.core.frame import Column
    from h2o3_tpu.core import mrtask

    v = np.arange(100, dtype=np.float64)
    c = Column.from_numpy(v)

    def partial_sum(x):
        return jnp.nansum(x)

    total = mrtask.map_reduce(partial_sum, [c])
    assert float(total) == v.sum()


def test_map_chunks_elementwise(cl):
    from h2o3_tpu.core.frame import Column
    from h2o3_tpu.core import mrtask

    v = np.arange(10, dtype=np.float64)
    c = Column.from_numpy(v)

    def double(x):
        return x * 2

    out = mrtask.new_column(double, [c])
    np.testing.assert_allclose(out.to_numpy(), v * 2)


def test_frame_basic(cl):
    from h2o3_tpu.core.frame import Frame

    fr = Frame.from_numpy(np.arange(12, dtype=np.float64).reshape(4, 3), names=["a", "b", "c"])
    assert fr.ncols == 3
    assert fr.nrows == 4
    assert fr.names == ["a", "b", "c"]
    sub = fr.subframe(["a", "c"])
    assert sub.names == ["a", "c"]
    np.testing.assert_allclose(fr.col("b").to_numpy(), [1, 4, 7, 10])


def test_job_lifecycle(cl):
    from h2o3_tpu.core.job import Job

    j = Job("test job")
    j.start(lambda job: (job.update(0.5), 41 + 1)[-1])
    j.join()
    assert j.status == Job.DONE
    assert j.result == 42
    assert j.progress == 1.0


def test_job_failure(cl):
    from h2o3_tpu.core.job import Job

    def boom(job):
        raise ValueError("nope")

    j = Job("failing").start(boom)
    with pytest.raises(RuntimeError):
        j.join()
    assert j.status == Job.FAILED


def test_self_benchmark(cl):
    b = cl.self_benchmark(size=256)
    assert b["matmul_gflops"] > 0


def test_dkv_control_plane_local_mode(cl):
    """publish/global_keys/fetch_remote degrade gracefully without a
    multi-process cloud (water/DKV.java distributed half; the 2-process
    tier exercises the real coordination-service KV)."""
    from h2o3_tpu.core.dkv import DKV

    DKV.put("local_thing", {"v": 1})
    try:
        assert DKV.publish("local_thing", {"v": 1}) is False   # no cloud KV
        assert "local_thing" in DKV.global_keys()              # local merge
        assert DKV.fetch_remote("local_thing") == {"v": 1}     # local hit
        assert DKV.fetch_remote("never_existed", timeout_ms=10) is None
    finally:
        DKV.remove("local_thing")


def test_dkv_blob_size_cap(cl, monkeypatch):
    """The size check must fire BEFORE the meta announce (no ghost keys)."""
    import numpy as np
    import pytest

    from h2o3_tpu.core.dkv import DKV
    from h2o3_tpu.parallel import distributed as D

    calls = []
    monkeypatch.setattr(D, "kv_put", lambda k, v: calls.append(k) or True)
    big = np.zeros(3_000_000)          # pickles to ~24 MB > 8 MiB cap
    with pytest.raises(ValueError, match="too large"):
        DKV.publish("big_thing", big, replicate=True)
    assert calls == []                 # nothing announced for the ghost key
