"""AOT scoring artifacts, persistent compile cache, admission control.

The PR-6 subsystem contracts:
- export -> (fresh-process) standalone-runner predictions are BITWISE
  identical to in-process fused serving;
- a second server start against a warm $H2O_TPU_COMPILE_CACHE_DIR compiles
  ZERO fused programs (counter-asserted);
- admission-control overflow returns 429/503 + Retry-After while admitted/
  queued requests still complete;
- corrupt/truncated artifacts (and tampered executable blobs) are rejected
  through the schema-validated manifest / restricted unpickler, never
  half-loaded.
"""

import json
import os
import subprocess
import sys
import threading
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pytest

from h2o3_tpu.core.frame import Column, Frame


def _bits(a):
    return np.ascontiguousarray(np.asarray(a, np.float32)).view(np.uint32)


def _train_frame(n=500, classes=2, seed=11):
    rng = np.random.default_rng(seed)
    fr = Frame()
    logit = np.zeros(n)
    for i in range(4):
        x = rng.standard_normal(n)
        logit += x * ((-1) ** i) * 0.7
        fr.add(f"n{i}", Column.from_numpy(x))
    codes = rng.integers(0, 3, n)
    fr.add("c0", Column.from_numpy(np.array(["a", "b", "c"])[codes],
                                   ctype="enum"))
    if classes == 2:
        y = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "Y", "N")
    else:
        y = np.array(["c%d" % (v % classes) for v in
                      rng.integers(0, classes, n)])
    fr.add("y", Column.from_numpy(y, ctype="enum"))
    return fr


def _test_frame(n=80, seed=13):
    rng = np.random.default_rng(seed)
    fr = Frame()
    for i in range(4):
        fr.add(f"n{i}", Column.from_numpy(rng.standard_normal(n)))
    fr.add("c0", Column.from_numpy(
        np.array(["a", "b", "c"])[rng.integers(0, 3, n)], ctype="enum"))
    return fr


def _frame_to_csv(fr, path, n):
    cols = []
    for nm in fr.names:
        c = fr.col(nm)
        vals = np.asarray(c.data)[:n]
        if c.is_categorical:
            vals = np.asarray(c.domain, object)[vals]
        cols.append((nm, vals))
    with open(path, "w") as f:
        f.write(",".join(nm for nm, _ in cols) + "\n")
        for i in range(n):
            f.write(",".join(str(v[i]) for _, v in cols) + "\n")


@pytest.fixture(scope="module")
def gbm(cl):
    from h2o3_tpu.models.tree.gbm import GBM

    return GBM(ntrees=5, max_depth=3, seed=7).train(
        y="y", training_frame=_train_frame())


@pytest.fixture(scope="module")
def gbm_multi(cl):
    from h2o3_tpu.models.tree.gbm import GBM

    return GBM(ntrees=3, max_depth=3, seed=9).train(
        y="y", training_frame=_train_frame(classes=3, seed=21))


class TestExportImportRoundtrip:
    def test_loader_roundtrip_is_bitwise_identical(self, cl, gbm, tmp_path):
        from h2o3_tpu import artifact, scoring

        art = str(tmp_path / "art")
        man = artifact.export_model(gbm, art, buckets=[128])
        assert man["model_checksum"]
        loaded = artifact.load_model(art, model_id="art_rt_model")
        test = _test_frame()
        p0 = scoring.session_for(gbm).predict(test)
        p1 = scoring.session_for(loaded).predict(test)
        for col in p0.names:
            assert np.array_equal(_bits(p0.col(col).data),
                                  _bits(p1.col(col).data)), col
        loaded.delete()

    def test_describe_summarizes_manifest(self, cl, gbm, tmp_path):
        from h2o3_tpu import artifact

        art = str(tmp_path / "art")
        artifact.export_model(gbm, art, buckets=[128])
        info = artifact.describe(art)
        assert info["algo"] == "gbm"
        assert info["buckets"] == [128]
        assert info["n_features"] == 5

    def test_unsupported_model_refused(self, cl, tmp_path):
        from h2o3_tpu import artifact
        from h2o3_tpu.models.kmeans import KMeans

        km = KMeans(k=2, seed=3, max_iterations=3).train(
            training_frame=_test_frame(60))
        with pytest.raises(artifact.ArtifactError, match="SharedTree"):
            artifact.export_model(km, str(tmp_path / "km"))
        km.delete()


class TestStandaloneRunner:
    def test_fresh_process_predictions_bitwise(self, cl, gbm, tmp_path):
        """Export -> score in a FRESH python process through the genmodel
        runner -> margins AND probabilities bitwise-equal to the server's
        fused session."""
        from h2o3_tpu import artifact, scoring

        art = str(tmp_path / "art")
        artifact.export_model(gbm, art, buckets=[128])
        test = _test_frame()
        n = test.nrows
        csv = str(tmp_path / "in.csv")
        _frame_to_csv(test, csv, n)

        sess = scoring.session_for(gbm)
        X = sess._features(gbm.adapt_test(test), n)
        ref_marg = np.asarray(sess._margin_x(X))
        import jax.numpy as jnp

        ref_probs = np.asarray(
            gbm._margin_to_raw(jnp.asarray(ref_marg))["probs"])

        raw_npz = str(tmp_path / "raw.npz")
        out_csv = str(tmp_path / "out.csv")
        proc = subprocess.run(
            [sys.executable, "-m", "h2o3_genmodel.aot_predict",
             "--artifact", art, "--input", csv, "--output", out_csv,
             "--raw-npz", raw_npz],
            capture_output=True, text=True, timeout=300)
        assert proc.returncode == 0, proc.stderr[-2000:]
        with np.load(raw_npz) as z:
            assert np.array_equal(_bits(z["margins"]), _bits(ref_marg))
            assert np.array_equal(_bits(z["probs"]), _bits(ref_probs))

    def test_multinomial_runner_in_process_bitwise(self, cl, gbm_multi,
                                                   tmp_path):
        from h2o3_genmodel.aot import load_artifact
        from h2o3_tpu import artifact, scoring

        art = str(tmp_path / "artm")
        artifact.export_model(gbm_multi, art, buckets=[128])
        test = _test_frame(50, seed=31)
        sess = scoring.session_for(gbm_multi)
        X = sess._features(gbm_multi.adapt_test(test), 50)
        ref = np.asarray(sess._margin_x(X))
        s = load_artifact(art)
        got = s.margins(s.pack_features({
            nm: (np.asarray(test.col(nm).data)[:50]
                 if not test.col(nm).is_categorical else
                 np.asarray(test.col(nm).domain,
                            object)[np.asarray(test.col(nm).data)[:50]])
            for nm in test.names}))
        assert np.array_equal(_bits(got), _bits(ref))

    def test_stablehlo_fallback_bitwise(self, cl, gbm, tmp_path):
        """With every serialized executable stripped, the runner compiles
        the shipped StableHLO — the identical program — and stays
        bitwise-equal."""
        from h2o3_genmodel.aot import load_artifact
        from h2o3_tpu import artifact, scoring

        art = str(tmp_path / "arth")
        artifact.export_model(gbm, art, buckets=[128])
        mpath = os.path.join(art, "manifest.json")
        m = json.load(open(mpath))
        m["executables"] = []
        json.dump(m, open(mpath, "w"))
        test = _test_frame(40, seed=41)
        sess = scoring.session_for(gbm)
        X = sess._features(gbm.adapt_test(test), 40)
        ref = np.asarray(sess._margin_x(X))
        s = load_artifact(art)
        got = s.margins(X)
        assert s.loaded_from == {128: "hlo"}
        assert np.array_equal(_bits(got), _bits(ref))


class TestPersistentCompileCache:
    def test_warm_restart_compiles_zero_programs(self, cl, gbm, tmp_path,
                                                 monkeypatch):
        """First session populates $H2O_TPU_COMPILE_CACHE_DIR; a fresh
        session (the 'second server start') must dispatch entirely from
        the cache — fused compile counter stays at zero."""
        from h2o3_tpu import scoring
        from h2o3_tpu.artifact import compile_cache

        monkeypatch.setenv("H2O_TPU_COMPILE_CACHE_DIR",
                           str(tmp_path / "cc"))
        test = _test_frame(30, seed=51)
        compile_cache.reset_stats()
        cold = scoring.ScoringSession(gbm)
        cold.predict(test)
        assert cold.fused_compiles >= 1
        assert compile_cache.fused_compile_count() == cold.fused_compiles
        stored = compile_cache.stats()["stores"]
        assert stored >= 1

        scoring.purge()                   # "server restart": sessions gone
        compile_cache.reset_stats()
        warm = scoring.ScoringSession(gbm)
        p_warm = warm.predict(test)
        assert compile_cache.fused_compile_count() == 0
        assert warm.fused_compiles == 0
        assert warm.cache_hits >= 1
        # and the cached executable scores identically
        p_cold = cold.predict(test)
        for col in p_cold.names:
            assert np.array_equal(_bits(p_cold.col(col).data),
                                  _bits(p_warm.col(col).data))

    def test_cache_disabled_without_env(self, cl, gbm, monkeypatch):
        from h2o3_tpu import scoring
        from h2o3_tpu.artifact import compile_cache

        monkeypatch.delenv("H2O_TPU_COMPILE_CACHE_DIR", raising=False)
        assert not compile_cache.enabled()
        sess = scoring.ScoringSession(gbm)
        sess.predict(_test_frame(10, seed=61))
        assert sess.fused_compiles >= 1    # compiled, nothing persisted
        assert compile_cache.stats()["stores"] == 0


class TestCorruptArtifactRejection:
    def _export(self, gbm, tmp_path):
        from h2o3_tpu import artifact

        art = str(tmp_path / "art")
        artifact.export_model(gbm, art, buckets=[64])
        return art

    def test_truncated_payload_rejected(self, cl, gbm, tmp_path):
        from h2o3_tpu import artifact

        art = self._export(gbm, tmp_path)
        p = os.path.join(art, "forest.npz")
        data = open(p, "rb").read()
        open(p, "wb").write(data[: len(data) // 2])
        with pytest.raises(artifact.ArtifactError, match="checksum"):
            artifact.load_model(art, model_id="nope")

    def test_future_format_version_rejected(self, cl, gbm, tmp_path):
        from h2o3_tpu import artifact

        art = self._export(gbm, tmp_path)
        mpath = os.path.join(art, "manifest.json")
        m = json.load(open(mpath))
        m["format_version"] = 99
        json.dump(m, open(mpath, "w"))
        with pytest.raises(artifact.ArtifactError, match="format_version"):
            artifact.describe(art)

    def test_path_traversal_in_manifest_rejected(self, cl, gbm, tmp_path):
        from h2o3_tpu import artifact

        art = self._export(gbm, tmp_path)
        mpath = os.path.join(art, "manifest.json")
        m = json.load(open(mpath))
        m["files"]["forest"]["name"] = "../../etc/passwd"
        json.dump(m, open(mpath, "w"))
        with pytest.raises(artifact.ArtifactError, match="illegal"):
            artifact.load_model(art)

    def test_tampered_exec_blob_refused_by_restricted_unpickler(
            self, cl, gbm, tmp_path):
        """A checksum-consistent but malicious executable blob (pickle
        smuggling os.system) must be refused by the restricted unpickler,
        not executed and not silently skipped."""
        import hashlib
        import pickle

        from h2o3_genmodel.aot import load_artifact

        art = self._export(gbm, tmp_path)
        evil = pickle.dumps({"v": 1, "payload": b"",
                             "in_tree": os.system, "out_tree": None})
        mpath = os.path.join(art, "manifest.json")
        m = json.load(open(mpath))
        assert m["executables"], "export produced no serialized executable"
        entry = m["executables"][0]
        open(os.path.join(art, entry["name"]), "wb").write(evil)
        entry["sha256"] = hashlib.sha256(evil).hexdigest()
        entry["bytes"] = len(evil)
        json.dump(m, open(mpath, "w"))
        s = load_artifact(art)
        with pytest.raises(pickle.UnpicklingError, match="disallowed"):
            s.margins(np.zeros((4, 5), np.float32))

    def test_missing_manifest_rejected(self, cl, tmp_path):
        from h2o3_tpu import artifact

        with pytest.raises(artifact.ArtifactError, match="manifest"):
            artifact.describe(str(tmp_path / "empty"))


class TestAdmissionControl:
    def test_queue_then_reject_then_timeout(self, cl, monkeypatch):
        from h2o3_tpu import admission

        monkeypatch.setenv("H2O_TPU_SCORE_MAX_INFLIGHT", "1")
        monkeypatch.setenv("H2O_TPU_SCORE_QUEUE_CAP", "1")
        monkeypatch.setenv("H2O_TPU_SCORE_QUEUE_TIMEOUT_S", "0.3")
        ctl = admission.AdmissionController()
        release = threading.Event()
        inside = threading.Event()
        results = {}

        def holder():
            with ctl.slot("m"):
                inside.set()
                release.wait(10)

        t_hold = threading.Thread(target=holder)
        t_hold.start()
        assert inside.wait(5)

        def queued():
            try:
                with ctl.slot("m"):
                    results["queued"] = "ran"
            except admission.AdmissionRejected as e:
                results["queued"] = e.status

        t_q = threading.Thread(target=queued)
        t_q.start()
        # wait until the queued request is actually parked
        for _ in range(100):
            if ctl.snapshot()["models"].get("m", {}).get("queue_depth"):
                break
            import time

            time.sleep(0.01)
        # queue is full now: the next request overflows with 429
        with pytest.raises(admission.AdmissionRejected) as ei:
            with ctl.slot("m"):
                pass
        assert ei.value.status == 429
        assert ei.value.retry_after_s >= 0.1
        release.set()                      # holder exits -> queued one runs
        t_hold.join(5)
        t_q.join(5)
        assert results["queued"] == "ran"
        snap = ctl.snapshot()
        assert snap["rejected"] == 1 and snap["admitted"] == 2

    def test_queue_timeout_maps_to_503(self, cl, monkeypatch):
        from h2o3_tpu import admission

        monkeypatch.setenv("H2O_TPU_SCORE_MAX_INFLIGHT", "1")
        monkeypatch.setenv("H2O_TPU_SCORE_QUEUE_CAP", "4")
        monkeypatch.setenv("H2O_TPU_SCORE_QUEUE_TIMEOUT_S", "0.2")
        ctl = admission.AdmissionController()
        release = threading.Event()
        inside = threading.Event()

        def holder():
            with ctl.slot("m"):
                inside.set()
                release.wait(10)

        t = threading.Thread(target=holder)
        t.start()
        assert inside.wait(5)
        with pytest.raises(admission.AdmissionRejected) as ei:
            with ctl.slot("m"):
                pass
        assert ei.value.status == 503
        release.set()
        t.join(5)

    def test_disabled_by_default(self, cl, monkeypatch):
        from h2o3_tpu import admission

        monkeypatch.delenv("H2O_TPU_SCORE_MAX_INFLIGHT", raising=False)
        ctl = admission.AdmissionController()
        with ctl.slot("m"):
            pass
        assert ctl.snapshot()["admitted"] == 0     # passthrough, no gate

    def test_rest_predict_returns_429_with_retry_after(self, cl, gbm,
                                                       monkeypatch):
        """Hold the single slot, then hit POST /3/Predictions over real
        HTTP: 429 + Retry-After while the admitted request still
        completes."""
        from h2o3_tpu import admission
        from h2o3_tpu.api.server import start_server

        monkeypatch.setenv("H2O_TPU_SCORE_MAX_INFLIGHT", "1")
        monkeypatch.setenv("H2O_TPU_SCORE_QUEUE_CAP", "0")
        test = _test_frame(20, seed=71)
        test.install()
        srv = start_server(port=0)
        try:
            url = (f"http://127.0.0.1:{srv.port}/3/Predictions/models/"
                   f"{gbm.key}/frames/{test.key}")
            release = threading.Event()
            inside = threading.Event()

            def holder():
                with admission.CONTROLLER.slot(str(gbm.key)):
                    inside.set()
                    release.wait(10)

            t = threading.Thread(target=holder)
            t.start()
            assert inside.wait(5)
            req = urllib.request.Request(url, data=b"", method="POST")
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=30)
            assert ei.value.code == 429
            assert int(ei.value.headers["Retry-After"]) >= 1
            release.set()
            t.join(5)
            # slot free again: the same request now succeeds end-to-end
            with urllib.request.urlopen(req, timeout=60) as r:
                body = json.loads(r.read())
            assert body["predictions_frame"]["name"]
        finally:
            srv.stop()
            test.delete()


class TestArtifactRestRoutes:
    def test_export_inspect_import_over_http(self, cl, gbm, tmp_path):
        from h2o3_tpu import scoring
        from h2o3_tpu.api.server import start_server
        from h2o3_tpu.core.dkv import DKV

        srv = start_server(port=0)
        art = str(tmp_path / "rest_art")
        try:
            base = f"http://127.0.0.1:{srv.port}"
            body = urllib.parse.urlencode(
                {"dir": art, "buckets": "[128]"}).encode()
            with urllib.request.urlopen(urllib.request.Request(
                    f"{base}/3/Artifacts/models/{gbm.key}", data=body,
                    method="POST"), timeout=120) as r:
                out = json.loads(r.read())
            assert out["model_checksum"] and out["buckets"] == [128]

            with urllib.request.urlopen(
                    f"{base}/3/Artifacts?dir={urllib.parse.quote(art)}",
                    timeout=30) as r:
                info = json.loads(r.read())
            assert info["algo"] == "gbm"

            body = urllib.parse.urlencode(
                {"dir": art, "model_id": "rest_art_model"}).encode()
            with urllib.request.urlopen(urllib.request.Request(
                    f"{base}/3/Artifacts/import", data=body,
                    method="POST"), timeout=120) as r:
                out = json.loads(r.read())
            assert out["model_id"] == "rest_art_model"
            loaded = DKV.get("rest_art_model")
            assert loaded is not None
            test = _test_frame(25, seed=81)
            p0 = scoring.session_for(gbm).predict(test)
            p1 = scoring.session_for(loaded).predict(test)
            assert np.array_equal(_bits(p0.col("Y").data),
                                  _bits(p1.col("Y").data))
            loaded.delete()
        finally:
            srv.stop()

    def test_import_rejects_bad_dir_with_400(self, cl, tmp_path):
        from h2o3_tpu.api.server import start_server

        srv = start_server(port=0)
        try:
            body = urllib.parse.urlencode(
                {"dir": str(tmp_path / "nothing")}).encode()
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(urllib.request.Request(
                    f"http://127.0.0.1:{srv.port}/3/Artifacts/import",
                    data=body, method="POST"), timeout=30)
            assert ei.value.code == 400
        finally:
            srv.stop()


class TestTreeProgressChunks:
    def test_chunk_roundtrip_and_gc(self, cl, tmp_path, monkeypatch):
        from h2o3_tpu.parallel import ckpt

        monkeypatch.setenv("H2O_TPU_OPLOG_CKPT_DIR", str(tmp_path))
        rng = np.random.default_rng(0)
        packs = [rng.standard_normal((3, 4)).astype(np.float32)
                 for _ in range(3)]
        lv = [rng.standard_normal(5).astype(np.float32) for _ in range(3)]
        lw = [rng.standard_normal((5, 2)).astype(np.float32)
              for _ in range(3)]
        p0 = ckpt.append_job_tree_chunk("jobA", 0, packs[:2], lv[:2],
                                        lw[:2])
        p1 = ckpt.append_job_tree_chunk("jobA", 1, packs[2:], lv[2:],
                                        lw[2:])
        rp, rlv, rlw = ckpt.load_job_tree_chunks([p0, p1])
        assert len(rp) == 3
        for a, b in zip(rp, packs):
            assert np.array_equal(a, b)
        for a, b in zip(rlw, lw):
            assert np.array_equal(a, b)
        ckpt.delete_job_progress("jobA")
        assert not os.path.exists(p0) and not os.path.exists(p1)

    def test_gbm_progress_saves_are_append_only(self, cl, tmp_path,
                                                monkeypatch):
        """A training run's progress states reference suffix chunks, not
        inline forests: each save appends exactly one chunk holding only
        the new trees."""
        from h2o3_tpu.core.job import Job
        from h2o3_tpu.models.tree.gbm import GBM
        from h2o3_tpu.parallel import ckpt

        monkeypatch.setenv("H2O_TPU_JOB_CKPT_ITERS", "2")
        monkeypatch.setenv("H2O_TPU_OPLOG_CKPT_DIR", str(tmp_path))
        captured = []
        orig = ckpt.save_job_progress

        def spy(job_key, iteration, spec, state):
            captured.append((iteration, state))
            return orig(job_key, iteration, spec, state)

        monkeypatch.setattr(ckpt, "save_job_progress", spy)
        fr = _train_frame(200, seed=91)
        b = GBM(ntrees=6, max_depth=2, seed=5)
        job = Job(description="gbm train")
        job.resume_spec = {"algo": "gbm", "params": {},
                           "training_frame": str(fr.key), "y": "y"}
        b._progress_job = job
        b.train(y="y", training_frame=fr)
        assert len(captured) >= 2
        for i, (iteration, state) in enumerate(captured):
            assert "packs" not in state, "inline O(forest) state is back"
            assert len(state["tree_chunks"]) == i + 1     # ONE new chunk
            assert state["n_tree_entries"] == iteration
        # chunks from save k are a strict prefix of save k+1's
        assert captured[0][1]["tree_chunks"] == \
            captured[1][1]["tree_chunks"][:1]
        fr.delete()


class TestAdaptiveReplayIdleTimeout:
    def test_env_pin_wins(self, monkeypatch):
        from h2o3_tpu.parallel import watchdog

        monkeypatch.setenv("H2O_TPU_REPLAY_IDLE_S", "777")
        assert watchdog.replay_idle_timeout_s() == 777.0

    def test_default_before_traffic(self, monkeypatch):
        from h2o3_tpu.parallel import oplog, watchdog

        monkeypatch.delenv("H2O_TPU_REPLAY_IDLE_S", raising=False)
        monkeypatch.setattr(oplog, "_OP_TIMES", type(oplog._OP_TIMES)(
            maxlen=32))
        assert watchdog.replay_idle_timeout_s() == \
            watchdog._REPLAY_IDLE_DEFAULT_S

    def test_adapts_to_op_gap_with_clamps(self, monkeypatch):
        from h2o3_tpu.parallel import oplog, watchdog

        monkeypatch.delenv("H2O_TPU_REPLAY_IDLE_S", raising=False)

        def set_gaps(gap_s, n=8):
            q = type(oplog._OP_TIMES)(maxlen=32)
            t = 1000.0
            for _ in range(n):
                q.append(t)
                t += gap_s
            monkeypatch.setattr(oplog, "_OP_TIMES", q)

        set_gaps(30.0)                                   # 20x30 = 600 s
        assert watchdog.replay_idle_timeout_s() == 600.0
        set_gaps(0.01)                                   # clamped low
        assert watchdog.replay_idle_timeout_s() == \
            watchdog._REPLAY_IDLE_MIN_S
        set_gaps(1000.0)                                 # clamped high
        assert watchdog.replay_idle_timeout_s() == \
            watchdog._REPLAY_IDLE_MAX_S


class TestGlmArtifact:
    """ISSUE-13 satellite: the first non-forest class through
    artifact/export + h2o3_genmodel.aot. The exported program IS the
    in-process ``_glm_predict`` jit program (lowered per bucket), so the
    standalone runner is bitwise-identical to ``GLMModel.predict`` —
    including the StableHLO fallback path."""

    def _glm_frames(self, n=600, seed=31):
        rng = np.random.default_rng(seed)
        fr = Frame()
        x1 = rng.standard_normal(n)
        x1[::9] = np.nan
        fr.add("x1", Column.from_numpy(x1))
        fr.add("x2", Column.from_numpy(rng.standard_normal(n)))
        fr.add("g", Column.from_numpy(
            np.array(["a", "b", "c"])[rng.integers(0, 3, n)],
            ctype="enum"))
        y = np.where(rng.random(n) < 1 / (1 + np.exp(
            -np.nan_to_num(x1))), "Y", "N")
        fr.add("y", Column.from_numpy(y, ctype="enum"))
        tn = 150
        tx1 = rng.standard_normal(tn)
        tx1[::5] = np.nan
        test = Frame()
        test.add("x1", Column.from_numpy(tx1))
        test.add("x2", Column.from_numpy(rng.standard_normal(tn)))
        gv = np.array(["a", "b", "c", "zz"])[rng.integers(0, 4, tn)]
        test.add("g", Column.from_numpy(gv, ctype="enum"))
        cols = {"x1": tx1, "x2": np.asarray(test.col("x2").data)[:tn],
                "g": gv}
        return fr, test, cols, tn

    def test_binomial_glm_bitwise_incl_hlo_fallback(self, cl, tmp_path):
        from h2o3_genmodel.aot import load_artifact
        from h2o3_tpu import artifact
        from h2o3_tpu.models.glm import GLM

        fr, test, cols, tn = self._glm_frames()
        m = GLM(family="binomial").train(y="y", training_frame=fr)
        art = str(tmp_path / "glm_art")
        man = artifact.export_model(m, art, buckets=[256])
        assert man["model_type"] == "glm"
        ref = m.predict(test)
        s = load_artifact(art)
        out = s.score(cols)
        for lvl in ("N", "Y"):
            assert np.array_equal(_bits(ref.col(lvl).data[:tn]),
                                  _bits(out[lvl])), lvl
        dom = ref.col("predict").domain
        lab = [dom[i] for i in np.asarray(ref.col("predict").data)[:tn]]
        assert lab == [str(v) for v in out["predict"]]
        # the StableHLO fallback executes the exporter's exact program:
        # margins stay bitwise without a loadable serialized executable
        s2 = load_artifact(art)
        s2.manifest["executables"] = []
        out2 = s2.score(cols)
        assert s2.loaded_from == {256: "hlo"}
        assert np.array_equal(_bits(out["Y"]), _bits(out2["Y"]))
        m.delete()

    def test_regression_and_multinomial_glm_bitwise(self, cl, tmp_path):
        from h2o3_genmodel.aot import load_artifact
        from h2o3_tpu import artifact
        from h2o3_tpu.models.glm import GLM

        rng = np.random.default_rng(33)
        n = 500
        fr = Frame()
        x = rng.standard_normal(n)
        fr.add("x1", Column.from_numpy(x))
        fr.add("x2", Column.from_numpy(rng.standard_normal(n)))
        fr.add("y", Column.from_numpy(2 * x + rng.normal(0, 0.1, n)))
        mr = GLM(family="gaussian").train(y="y", training_frame=fr)
        art = str(tmp_path / "glm_reg")
        artifact.export_model(mr, art, buckets=[128])
        t = {"x1": rng.standard_normal(90), "x2": rng.standard_normal(90)}
        tf = Frame()
        tf.add("x1", Column.from_numpy(t["x1"]))
        tf.add("x2", Column.from_numpy(t["x2"]))
        ref = mr.predict(tf)
        out = load_artifact(art).score(t)
        assert np.array_equal(_bits(ref.col("predict").data[:90]),
                              _bits(out["predict"]))
        mr.delete()

        fr3 = Frame()
        fr3.add("x1", Column.from_numpy(x))
        fr3.add("x2", Column.from_numpy(rng.standard_normal(n)))
        fr3.add("y", Column.from_numpy(
            np.array(["r", "s", "t"])[np.clip((x + 1.2).astype(int), 0,
                                              2)], ctype="enum"))
        mm = GLM(family="multinomial").train(y="y", training_frame=fr3)
        art3 = str(tmp_path / "glm_multi")
        artifact.export_model(mm, art3, buckets=[128])
        ref3 = mm.predict(tf)
        out3 = load_artifact(art3).score(t)
        for lvl in ("r", "s", "t"):
            assert np.array_equal(_bits(ref3.col(lvl).data[:90]),
                                  _bits(out3[lvl])), lvl
        mm.delete()

    def test_glm_artifact_server_import_bitwise(self, cl, tmp_path):
        """GLM artifacts re-import through the /3/Artifacts path: the
        loader rebuilds coefficients, the DataInfo layout, and the
        threshold metrics, and the imported model's predictions are
        bitwise-identical to the exporting model's."""
        from h2o3_tpu import artifact
        from h2o3_tpu.models.glm import GLM

        fr, test, _cols, tn = self._glm_frames(seed=35)
        m = GLM(family="binomial").train(y="y", training_frame=fr)
        art = str(tmp_path / "glm_imp")
        artifact.export_model(m, art, buckets=[128])
        ref = m.predict(test)
        loaded = artifact.load_model(art, model_id="glm_reimported")
        assert loaded.key == "glm_reimported"
        out = loaded.predict(test)
        for lvl in ("N", "Y"):
            assert np.array_equal(
                _bits(np.asarray(ref.col(lvl).data)[:tn]),
                _bits(np.asarray(out.col(lvl).data)[:tn])), lvl
        assert (np.asarray(ref.col("predict").data)[:tn].tolist()
                == np.asarray(out.col("predict").data)[:tn].tolist())
        loaded.delete()
        m.delete()

    def test_unsupported_glm_shapes_refused(self, cl, tmp_path):
        from h2o3_tpu import artifact
        from h2o3_tpu.models.glm import GLM

        fr, _t, _c, _n = self._glm_frames(seed=37)
        m = GLM(family="binomial", interactions=["x1", "x2"]).train(
            y="y", training_frame=fr)
        with pytest.raises(artifact.ArtifactError, match="interaction"):
            artifact.export_model(m, str(tmp_path / "glm_bad"))
        m.delete()
