"""Deep-tree device grower (round-4): depth>10 trains in the SAME
one-dispatch dense-frontier program — no host-orchestrated fallback.

Reference shape: hex/tree/DHistogram.java:33-44 level-wise growth at DRF's
default depth 20; VERDICT r3 #4 acceptance: depth-20 DRF with no per-level
host sync."""

import numpy as np
import pytest

from h2o3_tpu.core.frame import Column, Frame


def _data(n=2500, seed=9):
    rng = np.random.default_rng(seed)
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    g = np.array(["a", "b", "c"])[rng.integers(0, 3, n)]
    logit = 1.4 * x1 - x2 + (g == "a") * 1.0
    y = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "Y", "N")
    fr = Frame()
    fr.add("x1", Column.from_numpy(x1))
    fr.add("x2", Column.from_numpy(x2))
    fr.add("g", Column.from_numpy(g, ctype="enum"))
    fr.add("y", Column.from_numpy(y, ctype="enum"))
    fr.add("yreg", Column.from_numpy(logit + 0.2 * rng.normal(size=n)))
    return fr


def test_depth20_drf_no_host_fallback(cl, monkeypatch):
    """DRF at its default depth 20 must use the device grower exclusively:
    the host-orchestrated level loop (host_grow) is poisoned to prove no
    per-level host sync remains."""
    from h2o3_tpu.models.tree import host_grow
    from h2o3_tpu.models.tree.drf import DRF

    def boom(*a, **k):
        raise AssertionError("host_grow called: deep path fell off device")

    monkeypatch.setattr(host_grow, "grow_tree_host", boom)
    fr = _data()
    m = DRF(ntrees=8, max_depth=20, seed=1).train(
        x=["x1", "x2", "g"], y="y", training_frame=fr)
    assert m._output.training_metrics.auc > 0.75
    pred = m.predict(fr)
    p = np.asarray(pred.col("Y").to_numpy())
    assert np.all((p >= 0) & (p <= 1))


def test_depth20_drf_multinomial_device(cl, monkeypatch):
    from h2o3_tpu.models.tree import host_grow
    from h2o3_tpu.models.tree.drf import DRF

    monkeypatch.setattr(host_grow, "grow_tree_host",
                        lambda *a, **k: (_ for _ in ()).throw(
                            AssertionError("host fallback")))
    rng = np.random.default_rng(2)
    n = 1200
    x1, x2 = rng.normal(size=n), rng.normal(size=n)
    ym = np.array(["p", "q", "r"])[np.argmax(
        np.column_stack([x1, x2, -x1 - x2]) + rng.normal(0, .4, (n, 3)), 1)]
    fr = Frame()
    fr.add("x1", Column.from_numpy(x1))
    fr.add("x2", Column.from_numpy(x2))
    fr.add("ym", Column.from_numpy(ym, ctype="enum"))
    m = DRF(ntrees=5, max_depth=14, seed=3).train(
        x=["x1", "x2"], y="ym", training_frame=fr)
    acc = (np.asarray(m.predict(fr).col("predict").to_numpy())
           == np.asarray(fr.col("ym").to_numpy())).mean()
    assert acc > 0.7


def test_deep_gbm_beats_shallow_underfit(cl):
    """Depth-12 GBM on a deep interaction surface must at least match a
    depth-2 model — proves deep levels actually split on device."""
    from h2o3_tpu.models.tree.gbm import GBM

    fr = _data()
    deep = GBM(ntrees=10, max_depth=12, seed=1, learn_rate=0.3).train(
        x=["x1", "x2", "g"], y="yreg", training_frame=fr)
    shallow = GBM(ntrees=10, max_depth=1, seed=1, learn_rate=0.3).train(
        x=["x1", "x2", "g"], y="yreg", training_frame=fr)
    assert deep._output.training_metrics.rmse < \
        shallow._output.training_metrics.rmse


def test_frontier_cap_binds_gracefully(cl, monkeypatch):
    """With a tiny frontier cap the grower keeps the best-gain splits and
    still produces a working model (greedy-best under the width budget)."""
    monkeypatch.setenv("H2O_TPU_FRONTIER_CAP", "16")
    from h2o3_tpu.models.tree import device_tree

    device_tree._grow_fn.cache_clear()
    device_tree._apply_fn.cache_clear()
    try:
        from h2o3_tpu.models.tree.gbm import GBM

        fr = _data(n=1200)
        m = GBM(ntrees=5, max_depth=8, seed=1).train(
            x=["x1", "x2", "g"], y="y", training_frame=fr)
        assert m._output.training_metrics.auc > 0.7
        widths = device_tree.level_widths(8, 16)
        assert max(widths) == 16                   # cap actually bound
    finally:
        device_tree._grow_fn.cache_clear()
        device_tree._apply_fn.cache_clear()


def test_deep_mojo_and_genmodel_roundtrip(cl):
    """Deep forests survive the MOJO container and the standalone numpy
    scorer (global-slot leaf ids are part of the artifact contract)."""
    import h2o3_genmodel as gm

    from h2o3_tpu.models import mojo
    from h2o3_tpu.models.tree.drf import DRF

    fr = _data(n=1500)
    m = DRF(ntrees=6, max_depth=15, seed=5).train(
        x=["x1", "x2", "g"], y="y", training_frame=fr)
    loaded = mojo.read_mojo(mojo.export_mojo_bytes(m))
    p0 = np.asarray(m.predict(fr).col("Y").to_numpy())
    p1 = np.asarray(loaded.predict(fr).col("Y").to_numpy())
    np.testing.assert_allclose(p0, p1, atol=0, rtol=0)
    pred = gm.load_mojo(mojo.export_mojo_bytes(m))
    got = pred.score({"x1": fr.col("x1").to_numpy(),
                      "x2": fr.col("x2").to_numpy(),
                      "g": np.asarray(["a", "b", "c"], object)[
                          np.asarray(fr.col("g").to_numpy())]})
    np.testing.assert_allclose(np.asarray(got["Y"], float), p0,
                               atol=1e-5, rtol=1e-5)


def test_validation_scoring_deep(cl):
    """apply_packed (in-training validation traversal) works at depth>10."""
    from h2o3_tpu.models.tree.gbm import GBM

    fr = _data(n=2000)
    tr_rows = np.arange(1500)
    va_rows = np.arange(1500, 2000)

    def subset(rows):
        out = Frame()
        for nm in fr.names:
            c = fr.col(nm)
            out.add(nm, Column.from_numpy(
                np.asarray(c.to_numpy())[rows], ctype="enum" if c.domain else None,
                domain=list(c.domain) if c.domain else None))
        return out

    tr, va = subset(tr_rows), subset(va_rows)
    m = GBM(ntrees=8, max_depth=12, seed=1,
            score_each_iteration=True).train(
        x=["x1", "x2", "g"], y="y", training_frame=tr, validation_frame=va)
    hist = m._output.scoring_history
    assert any("validation_deviance" in h for h in hist)
