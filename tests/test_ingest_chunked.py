"""Chunked sharded ingest (ISSUE 15): chunk-boundary correctness, the
zero-coordinator-bytes contract, streaming append over /3/ParseStream, and
the lazy-parquet batched first-touch loads.

The boundary suite is the satellite's randomized split-point property test:
quoted fields containing newlines, CRLF endings and multi-byte UTF-8
sequences must parse BITWISE-identically to the whole-file path no matter
where a ~chunk edge falls — the splitter may only cut on true record ends.
"""

import os

import numpy as np
import pytest


def _set_env(monkeypatch, **kw):
    for k, v in kw.items():
        if v is None:
            monkeypatch.delenv(k, raising=False)
        else:
            monkeypatch.setenv(k, str(v))


def _import(path, dest):
    import h2o3_tpu

    return h2o3_tpu.import_file(str(path), destination_frame=dest)


def _assert_frames_bitwise(a, b, ctx=""):
    """Rows, types, domains, NAs and the PADDED device buffers must agree
    exactly (floats NaN-equal, dtype included)."""
    assert a.nrows == b.nrows, ctx
    assert a.names == b.names, ctx
    assert a.types == b.types, ctx
    for nm in a.names:
        ca, cb = a.col(nm), b.col(nm)
        assert (ca.domain or []) == (cb.domain or []), (ctx, nm)
        if ca.data is None:
            assert list(ca.host_data[:ca.nrows]) == \
                list(cb.host_data[:cb.nrows]), (ctx, nm)
            continue
        x, y = np.asarray(ca.data), np.asarray(cb.data)
        assert x.dtype == y.dtype, (ctx, nm, x.dtype, y.dtype)
        assert np.array_equal(x, y, equal_nan=(x.dtype.kind == "f")), \
            (ctx, nm)


def _nasty_csv(path, n=240, seed=0):
    """CSV engineered so byte-range edges land inside every hard case:
    quoted embedded '\\n' and '\\r\\n', commas, doubled quotes, quoted
    empty strings (NA), multi-byte UTF-8 (2-4 bytes), CRLF line endings
    for half the file, blank lines, and no trailing newline."""
    rng = np.random.default_rng(seed)
    motifs = ['plain', '"with,comma"', '"multi\nline"', '"crlf\r\nfield"',
              '"héllo🎉"', '"dbl""quote"', '""', '"日本語テキスト"']
    rows = []
    for i in range(n):
        v = "" if i % 17 == 0 else f"{rng.normal():.6g}"
        rows.append(f"{i},{motifs[i % len(motifs)]},{v}")
    body = ("id,txt,val\n" + "\r\n".join(rows[: n // 2]) + "\n"
            + "\n\n".join(rows[n // 2:]))         # blanks + no final \n
    with open(path, "w", encoding="utf-8", newline="") as f:
        f.write(body)
    return str(path)


def test_randomized_split_points_bitwise(cl, tmp_path, monkeypatch):
    """The satellite's property test: parse the nasty file under a sweep
    of randomized chunk sizes (forcing edges into quoted newlines, CRLF
    pairs and multi-byte sequences) and require bitwise identity with the
    monolithic path every time."""
    from h2o3_tpu.ingest import chunked

    p = _nasty_csv(tmp_path / "nasty.csv")
    _set_env(monkeypatch, H2O_TPU_INGEST_CHUNKED="0")
    ref = _import(p, "chunk_ref")
    rng = np.random.default_rng(1234)
    sizes = [1024, 1031] + [int(s) for s in rng.integers(1024, 6000, 8)]
    for cb in sizes:
        _set_env(monkeypatch, H2O_TPU_INGEST_CHUNKED="1",
                 H2O_TPU_INGEST_CHUNK_BYTES=cb)
        before = chunked.counters()
        fr = _import(p, f"chunk_{cb}")
        after = chunked.counters()
        _assert_frames_bitwise(ref, fr, ctx=f"chunk_bytes={cb}")
        assert after["chunk_rows"] > before["chunk_rows"]
        assert after["coordinator_ingest_bytes"] == \
            before["coordinator_ingest_bytes"], f"chunk_bytes={cb}"
        fr.delete()
    ref.delete()


def test_splitter_cuts_only_on_record_ends(cl, tmp_path):
    """Direct splitter unit: every chunk edge must be a true record end —
    never inside a quoted field's newline — and the per-chunk row counts
    must sum to the data row count."""
    from h2o3_tpu.ingest.chunked import split_file
    from h2o3_tpu.ingest.parse_setup import ParseSetup

    p = tmp_path / "quoted.csv"
    rows = [f'{i},"line\nbreak {i}"' for i in range(50)]
    text = "a,b\n" + "\n".join(rows) + "\n"
    p.write_text(text)
    setup = ParseSetup(column_names=["a", "b"],
                       column_types=["real", "enum"])
    chunks, total = split_file(str(p), setup, 256)
    assert total == 50
    assert sum(nr for _s, _e, nr in chunks) == 50
    assert len(chunks) >= 2
    raw = text.encode()
    pos = chunks[0][0]
    for (s, e, _nr) in chunks:
        assert s == pos, "chunks must tile the data region"
        pos = e
        # a record end: preceded by a newline with EVEN quote count before
        assert e == len(raw) or raw[e - 1:e] == b"\n"
        assert raw[:e].count(b'"') % 2 == 0, \
            "edge landed inside a quoted field"


def test_windowed_scan_carries_quote_parity(cl, tmp_path, monkeypatch):
    """The splitter scans in fixed windows with a running quote-count
    carry (flat memory on huge files) — force a tiny window so edges land
    INSIDE quoted fields spanning windows and require identical record
    layout to the one-shot scan."""
    from h2o3_tpu.ingest import chunked
    from h2o3_tpu.ingest.parse_setup import ParseSetup

    p = tmp_path / "windowed.csv"
    rows = [f'{i},"quoted\nnewline {i}"' for i in range(40)]
    text = "a,b\n" + "\n".join(rows) + "\n"
    p.write_text(text)
    setup = ParseSetup(column_names=["a", "b"],
                       column_types=["real", "enum"])
    big = chunked.split_file(str(p), setup, 128)
    monkeypatch.setattr(chunked, "_SCAN_WINDOW", 7)
    small = chunked.split_file(str(p), setup, 128)
    assert big == small
    raw = text.encode()
    for (s, e, _n) in small[0]:
        assert raw[:e].count(b'"') % 2 == 0, (s, e)


def test_headerless_and_blank_lines(cl, tmp_path, monkeypatch):
    # the reference is the PANDAS whole-file path (blank lines skipped —
    # the semantics the chunked splitter mirrors); the native C parser,
    # when built, emits NaN rows for blanks instead, a pre-existing
    # native-vs-pandas divergence this suite does not inherit
    from h2o3_tpu.native import loader as native_loader

    monkeypatch.setattr(native_loader, "native_parse_csv",
                        lambda *_a, **_k: None)
    p = tmp_path / "nohdr.csv"
    p.write_text("1,2.5\n\n3,4.5\n\r\n5,6.5")
    _set_env(monkeypatch, H2O_TPU_INGEST_CHUNKED="0")
    ref = _import(p, "nohdr_ref")
    _set_env(monkeypatch, H2O_TPU_INGEST_CHUNKED="1",
             H2O_TPU_INGEST_CHUNK_BYTES=1024)
    fr = _import(p, "nohdr_chunk")
    assert fr.nrows == 3 and fr.names == ["C1", "C2"]
    _assert_frames_bitwise(ref, fr)
    ref.delete()
    fr.delete()


def test_multi_file_chunked(cl, tmp_path, monkeypatch):
    for i in range(3):
        (tmp_path / f"part{i}.csv").write_text("x,y\n" + "".join(
            f"{j + i * 10},{j * 2.0}\n" for j in range(5)))
    glob = str(tmp_path / "part*.csv")
    _set_env(monkeypatch, H2O_TPU_INGEST_CHUNKED="0")
    ref = _import(glob, "multi_ref")
    _set_env(monkeypatch, H2O_TPU_INGEST_CHUNKED="1")
    fr = _import(glob, "multi_chunk")
    assert fr.nrows == 15
    _assert_frames_bitwise(ref, fr)
    ref.delete()
    fr.delete()


def test_intern_chunk_matches_reference_interning(cl):
    """The vectorized per-chunk interner must reproduce
    core.frame._intern_domain exactly (None/NaN/"" are NA, sorted
    domain) — it is the two-pass resolution's correctness anchor."""
    from h2o3_tpu.core.frame import _intern_domain
    from h2o3_tpu.ingest.chunked import _intern_chunk

    a = np.array(["b", None, "", "a", float("nan"), "b", "héllo🎉",
                  "z\nq", "a ", "A", "10", "9"], object)
    d_ref, c_ref = _intern_domain(a)
    d_new, c_new = _intern_chunk(a)
    assert d_ref == d_new
    assert np.array_equal(c_ref, c_new)


def test_time_columns_resolve_column_wide_format(cl, tmp_path, monkeypatch):
    """T_TIME regression guard: datetime format inference must run over
    the WHOLE column (resolve pass), never per chunk — a chunk whose
    first date is unambiguous (13/01/2020) would otherwise flip the
    inferred format for the ambiguous rows (01/02/2020) inside it."""
    import pandas as pd

    p = tmp_path / "dates.csv"
    rows = []
    for i in range(120):
        d = f"2023-11-{(i % 27) + 1:02d} 0{i % 9}:15:00"
        rows.append(f"{d},{i * 1.5}")
    p.write_text("t,v\n" + "\n".join(rows) + "\n")
    _set_env(monkeypatch, H2O_TPU_INGEST_CHUNKED="0")
    ref = _import(p, "time_ref")
    _set_env(monkeypatch, H2O_TPU_INGEST_CHUNKED="1",
             H2O_TPU_INGEST_CHUNK_BYTES=1024)
    fr = _import(p, "time_chunk")
    assert fr.types["t"] == "time"
    _assert_frames_bitwise(ref, fr)
    # spot-check the decoded epoch-millis against pandas directly
    want = (pd.Timestamp("2023-11-01 00:15:00").value // 10**6)
    got = float(np.asarray(fr.col("t").data)[0])
    assert got == np.float32(np.float64(want))
    ref.delete()
    fr.delete()


def test_time_columns_numeric_tokens_parse_as_dates(cl, tmp_path,
                                                    monkeypatch):
    """Review hardening: numeric-LOOKING date tokens ('20231105') must
    read as raw strings (csv_read_kwargs forces str for T_TIME) — pandas
    per-chunk type inference would otherwise hand a floats-only chunk to
    to_datetime as epoch-ns, silently diverging from the whole-file
    read. Chunked and monolithic must agree bitwise AND both decode the
    tokens as real dates."""
    import h2o3_tpu
    import pandas as pd

    p = tmp_path / "numdates.csv"
    rows = [f"2023110{(i % 9) + 1},{i * 0.5}" for i in range(120)]
    p.write_text("t,v\n" + "\n".join(rows) + "\n")

    def imp(dest):
        return h2o3_tpu.import_file(str(p), destination_frame=dest,
                                    col_types={"t": "time"})

    _set_env(monkeypatch, H2O_TPU_INGEST_CHUNKED="0")
    ref = imp("numtime_ref")
    _set_env(monkeypatch, H2O_TPU_INGEST_CHUNKED="1",
             H2O_TPU_INGEST_CHUNK_BYTES=1024)
    fr = imp("numtime_chunk")
    _assert_frames_bitwise(ref, fr)
    want = pd.Timestamp("2023-11-01").value // 10**6
    assert float(np.asarray(fr.col("t").data)[0]) == \
        np.float32(np.float64(want))
    ref.delete()
    fr.delete()


def test_custom_quote_char_consistent(cl, tmp_path, monkeypatch):
    """Review hardening: a non-default quote_char must reach pandas
    (csv_read_kwargs), not just the splitter's parity scan and the
    stream arity check — otherwise every such import pays the
    ChunkLayoutError fallback and a stream batch quoted with it would
    arity-pass but row-shift in the parse."""
    from h2o3_tpu.ingest import parser
    from h2o3_tpu.ingest.parse_setup import ParseSetup

    p = tmp_path / "squote.csv"
    p.write_text("x,s\n1.0,'a,b'\n2.0,'c\nd'\n3.5,plain\n")
    setup = ParseSetup(separator=",", check_header=1,
                       column_names=["x", "s"],
                       column_types=["real", "string"], quote_char="'")
    _set_env(monkeypatch, H2O_TPU_INGEST_CHUNKED="0")
    ref = parser.parse([str(p)], setup, destination_frame="squote_ref")
    _set_env(monkeypatch, H2O_TPU_INGEST_CHUNKED="1",
             H2O_TPU_INGEST_CHUNK_BYTES=1024)
    fr = parser.parse([str(p)], setup, destination_frame="squote_chunk")
    assert fr.nrows == 3
    assert list(fr.col("s").host_data[:3]) == ["a,b", "c\nd", "plain"]
    _assert_frames_bitwise(ref, fr, ctx="quote_char")
    ref.delete()
    fr.delete()


def test_legacy_paths_count_coordinator_bytes(cl, tmp_path, monkeypatch):
    """The counter contract's other half: a gzip CSV (byte ranges are not
    addressable) must ride the monolithic path and move
    coordinator_ingest_bytes."""
    import gzip

    from h2o3_tpu.ingest import chunked

    src = tmp_path / "z.csv"
    src.write_text("a,b\n" + "".join(f"{i},{i * 2}\n" for i in range(200)))
    gz = tmp_path / "z.csv.gz"
    with open(src, "rb") as f, gzip.open(gz, "wb") as g:
        g.write(f.read())
    _set_env(monkeypatch, H2O_TPU_INGEST_CHUNKED="1")
    before = chunked.counters()
    fr = _import(gz, "gz_frame")
    after = chunked.counters()
    assert after["coordinator_ingest_bytes"] > \
        before["coordinator_ingest_bytes"]
    assert fr.nrows == 200
    fr.delete()


def test_mis_split_file_falls_back_to_monolithic(cl, tmp_path, monkeypatch):
    """A stray literal quote in an unquoted field flips the scan's parity
    so a later quoted embedded newline looks like a record end — the
    chunk then fails to parse mid-record. ANY chunk-parse failure must
    wrap into ChunkLayoutError and reach the monolithic fallback, which
    parses the file exactly as before the chunked path existed."""
    p = tmp_path / "missplit.csv"
    with open(p, "w") as f:
        f.write("a,b\n")
        f.write('1,x"y\n')                    # parity-flipping stray quote
        for i in range(60):
            f.write(f'{i},"emb\nedded {i}"\n')
    _set_env(monkeypatch, H2O_TPU_INGEST_CHUNKED="1",
             H2O_TPU_INGEST_CHUNK_BYTES=1024)
    fr = _import(p, "missplit_fr")
    assert fr.nrows == 61
    fr.delete()


def test_streaming_append_bitwise_vs_cold_parse(cl, tmp_path, monkeypatch):
    """Acceptance: micro-batches appended through the shard-tail path —
    including one that grows the categorical domain — leave the frame
    BITWISE what a cold parse of the concatenated data produces, and the
    freshly appended rows score through the fused path bitwise too."""
    from h2o3_tpu import scoring
    from h2o3_tpu.ingest import chunked
    from h2o3_tpu.models.tree.gbm import GBM
    from h2o3_tpu.ops.rollups import compute_rollups

    _set_env(monkeypatch, H2O_TPU_INGEST_CHUNKED="1")
    rng = np.random.default_rng(5)
    n = 400

    def rows_text(count, start, levels="ab"):
        out = []
        for i in range(count):
            x1 = rng.normal()
            x2 = rng.normal()
            g = levels[(start + i) % len(levels)]
            y = "Y" if x1 + 0.5 * x2 > 0 else "N"
            out.append(f"{x1:.6f},{x2:.6f},{g},{y}")
        return "\n".join(out) + "\n"

    base = "x1,x2,g,y\n" + rows_text(n, 0)
    p = tmp_path / "stream_base.csv"
    p.write_text(base)
    fr = _import(p, "stream_live")
    model = GBM(ntrees=3, max_depth=3, seed=9).train(
        y="y", training_frame=fr)
    _ = fr.col("x1").rollups              # cache → incremental merge path

    b1 = rows_text(16, n)
    b2 = rows_text(24, n + 16, levels="abc")      # new level 'c'
    assert chunked.append_csv(fr, b1) == 16
    assert chunked.append_csv(fr, b2) == 24
    assert fr.nrows == n + 40

    # steady-state appends (same batch size, no new labels, padded
    # capacity unchanged) must reuse the traced-n compiled programs — the
    # production streaming path cannot pay a trace per append. b3 primes
    # the (padded, padded, 3) keys; b4 must add ZERO new program builds.
    b3 = rows_text(3, n + 40, levels="abc")
    b4 = rows_text(3, n + 43, levels="abc")
    assert chunked.append_csv(fr, b3) == 3
    misses_before = chunked._append_fast_fn.cache_info().misses
    assert chunked.append_csv(fr, b4) == 3
    assert chunked._append_fast_fn.cache_info().misses == misses_before, \
        "a steady-state append built a new program (traced-n cache broken)"

    cold_p = tmp_path / "stream_cold.csv"
    cold_p.write_text(base + b1 + b2 + b3 + b4)
    cold = _import(cold_p, "stream_cold")
    _assert_frames_bitwise(cold, fr, ctx="streamed vs cold")

    # incremental rollups agree with a cold device reduction
    r_inc = fr.col("x1")._rollups
    assert r_inc is not None, "append must merge cached rollups in place"
    r_cold = compute_rollups(cold.col("x1"))
    assert r_inc.rows == r_cold.rows and r_inc.na_count == r_cold.na_count
    assert r_inc.min == r_cold.min and r_inc.max == r_cold.max
    np.testing.assert_allclose(r_inc.mean, r_cold.mean, rtol=1e-4)
    np.testing.assert_allclose(r_inc.sigma, r_cold.sigma, rtol=1e-3)

    # train-on-static + score-on-streaming: the appended tail scores
    # through the fused session bitwise vs the cold frame's tail
    sess = scoring.session_for(model)
    tail_live = fr[n:n + 40, ["x1", "x2", "g"]]
    tail_cold = cold[n:n + 40, ["x1", "x2", "g"]]
    pl = sess.predict(tail_live)
    pc = sess.predict(tail_cold)
    for cname in pl.names:
        a, b = pl.col(cname), pc.col(cname)
        if a.data is None:
            assert list(a.values()) == list(b.values())
        else:
            assert np.array_equal(np.asarray(a.data), np.asarray(b.data),
                                  equal_nan=True), cname
    fr.delete()
    cold.delete()


def test_stream_append_rejects_malformed_batches(cl, tmp_path):
    """Review hardening: arity mismatches and unconvertible tokens must be
    clean errors BEFORE any mutation — pandas would otherwise silently
    consume an extra leading field as the index (shifting the whole row)
    or NA-fill short rows, corrupting every subsequent scoring result."""
    from h2o3_tpu.ingest import chunked

    p = tmp_path / "strict.csv"
    p.write_text("x,g,y\n" + "".join(
        f"{i * 0.5},{'ab'[i % 2]},{'YN'[i % 2]}\n" for i in range(20)))
    fr = _import(p, "strict_fr")
    base = np.asarray(fr.col("x").data).copy()
    with pytest.raises(ValueError, match="4 fields"):
        chunked.append_csv(fr, "1.5,2.5,a,Y\n")      # would index-shift
    with pytest.raises(ValueError, match="1 fields"):
        chunked.append_csv(fr, "1.5\n")              # would NA-fill g/y
    with pytest.raises(ValueError):
        chunked.validate_batch(fr, "oops,a,Y\n")     # numeric conversion
    # a space before a quoted field is ONE field to the pandas parser
    # (skipinitialspace) — the arity check must agree, not false-reject
    chunked.validate_batch(fr, '1.5, "a",Y\n')
    # csv.Error inputs (NUL byte) must be ValueError -> clean 400, not 500
    with pytest.raises(ValueError, match="CSV field scan"):
        chunked.validate_batch(fr, "1.5,a\x00b,Y\n")
    assert fr.nrows == 20
    assert np.array_equal(np.asarray(fr.col("x").data), base,
                          equal_nan=True)
    fr.delete()


def test_stream_append_uses_frame_separator(cl, tmp_path):
    """Review hardening: a frame imported with a non-comma separator
    streams batches in its OWN separator by default — /3/ParseStream
    must not require every call to repeat it."""
    import h2o3_tpu
    from h2o3_tpu.ingest import chunked

    p = tmp_path / "semi.csv"
    p.write_text("x;g\n1.0;a\n2.0;b\n")
    fr = h2o3_tpu.import_file(str(p), destination_frame="semi_fr")
    assert fr.nrows == 2
    assert chunked.append_csv(fr, "3.5;b\n") == 1    # no separator arg
    assert np.asarray(fr.col("x").data)[2] == np.float32(3.5)
    fr.delete()


def test_stream_append_honors_frame_na_strings(cl, tmp_path):
    """Review hardening: a frame imported with custom ``na_strings`` must
    read streamed tokens exactly as a cold parse of the concatenated data
    would — '?' is NA here, never a new categorical level."""
    import h2o3_tpu
    from h2o3_tpu.ingest import chunked

    p = tmp_path / "nas.csv"
    p.write_text("x,g\n1.0,a\n?,b\n2.0,a\n")
    fr = h2o3_tpu.import_file(str(p), destination_frame="nas_fr",
                              na_strings=["?"])
    assert fr.col("x").ctype != "string"             # '?' classified NA
    assert chunked.append_csv(fr, "?,?\n3.5,b\n") == 2
    x = np.asarray(fr.col("x").data)[:5]
    assert np.isnan(x[3]) and x[4] == np.float32(3.5)
    g = fr.col("g")
    assert g.domain == ["a", "b"]
    assert int(np.asarray(g.data)[3]) == -1
    fr.delete()


def test_stream_append_preserves_exact_time_host_copy(cl):
    """Review hardening: a T_TIME column carrying the exact epoch-millis
    host copy (datetime/int-sourced frames, e.g. parquet) must keep — and
    grow — it across appends: dropping it would downgrade every
    pre-existing timestamp to f32 device granularity (~2e5 ms at modern
    epochs) for the rapids time prims."""
    from h2o3_tpu.core.frame import Column, Frame, T_TIME
    from h2o3_tpu.ingest import chunked

    ms = np.array(["2026-08-01T10:00:00.123", "2026-08-02T11:30:00.456"],
                  dtype="datetime64[ms]")
    fr = Frame()
    fr.add("t", Column.from_numpy(ms, ctype=T_TIME))
    fr.add("x", Column.from_numpy(np.array([1.0, 2.0])))
    assert fr.col("t").host_data is not None
    assert chunked.append_csv(fr, "2026-08-03 12:00:00.789,3.0\n") == 1
    h = fr.col("t").host_data
    assert h is not None and h.dtype.kind == "M"
    exact = h.astype("datetime64[ms]").astype(np.int64)
    assert exact[0] == ms.astype(np.int64)[0]
    assert exact[2] == np.datetime64("2026-08-03T12:00:00.789", "ms") \
        .astype(np.int64)


@pytest.fixture(scope="module")
def stream_server(cl):
    from h2o3_tpu import client
    from h2o3_tpu.api.server import start_server

    srv = start_server(port=0)
    client.connect(port=srv.port)
    yield srv
    srv.stop()


def test_parse_stream_rest_roundtrip(stream_server, tmp_path):
    """POST /3/ParseStream appends micro-batches to an installed frame;
    totals and appended values are visible over the same REST surface,
    and the ingest metric family lands on GET /3/Metrics."""
    from h2o3_tpu import client
    from h2o3_tpu.core.dkv import DKV

    p = tmp_path / "rest_stream.csv"
    p.write_text("a,g\n" + "".join(
        f"{i * 1.5},{'uv'[i % 2]}\n" for i in range(60)))
    fr = client.import_file(str(p), destination_frame="rest_stream_fr")
    assert fr.nrows == 60
    out = client._req("POST", "/3/ParseStream", {
        "destination_frame": "rest_stream_fr",
        "data": "90.5,u\n91.5,w\n"})
    assert out["rows_appended"] == 2
    assert out["total_rows"] == 62
    live = DKV.get("rest_stream_fr")
    assert live.nrows == 62
    assert live.col("g").domain == ["u", "v", "w"]   # sorted, grown
    assert float(np.asarray(live.col("a").data)[61]) == np.float32(91.5)

    # 404 for an unknown frame, 400 for a missing body
    with pytest.raises(client.H2OServerError):
        client._req("POST", "/3/ParseStream",
                    {"destination_frame": "nope", "data": "1,u\n"})
    with pytest.raises(client.H2OServerError):
        client._req("POST", "/3/ParseStream",
                    {"destination_frame": "rest_stream_fr"})

    series = client._req("GET", "/3/Metrics", query={"format": "json"})
    by_name = {m["name"]: m for m in series["series"]}
    for name in ("h2o3_ingest_chunk_rows_total",
                 "h2o3_ingest_coordinator_bytes_total",
                 "h2o3_ingest_stream_rows_total",
                 "h2o3_ingest_parse_seconds",
                 "h2o3_ingest_overlap_ratio"):
        assert name in by_name, name
    stream_rows = by_name["h2o3_ingest_stream_rows_total"]["samples"]
    assert sum(s["value"] for s in stream_rows) >= 2
    fr.delete()


def test_parse_stream_rejects_bad_batch_over_rest(stream_server, tmp_path):
    """Review hardening: the handler preflights the batch BEFORE the oplog
    broadcast (the h_predict_v3 pattern) — a stray delimiter or a
    non-numeric token returns 400 and the frame is untouched; it must
    never raise inside the followers' mirrored replay."""
    from h2o3_tpu import client
    from h2o3_tpu.core.dkv import DKV

    p = tmp_path / "rest_strict.csv"
    p.write_text("a,g\n1.0,u\n2.0,v\n")
    fr = client.import_file(str(p), destination_frame="rest_strict_fr")
    for bad in ("1.0,u,extra\n", "7\n", "oops,u\n"):
        with pytest.raises(client.H2OServerError):
            client._req("POST", "/3/ParseStream",
                        {"destination_frame": "rest_strict_fr",
                         "data": bad})
    live = DKV.get("rest_strict_fr")
    assert live.nrows == 2
    assert live.col("g").domain == ["u", "v"]
    fr.delete()


def test_lazy_parquet_batches_first_touch_reads(cl, tmp_path, monkeypatch):
    """The lazy_import_parquet satellite: first touch of a numeric column
    must fetch a WINDOW of adjacent pending columns through one
    column-pruned read_table instead of re-opening the file per column."""
    pq = pytest.importorskip("pyarrow.parquet")
    import pyarrow as pa

    from h2o3_tpu.ingest.parser import lazy_import_parquet

    n = 64
    rng = np.random.default_rng(3)
    cols = {f"n{i}": rng.normal(size=n) for i in range(6)}
    cols["g"] = np.array(["a", "b"] * (n // 2))
    path = tmp_path / "lazy.parquet"
    pq.write_table(pa.table(cols), path)

    calls = []
    real_read = pq.read_table

    def counting_read(src, columns=None, **kw):
        calls.append(list(columns or []))
        return real_read(src, columns=columns, **kw)

    monkeypatch.setattr(pq, "read_table", counting_read)
    fr = lazy_import_parquet(str(path), destination_frame="lazy_pq")
    eager_calls = len(calls)          # the one cat/str eager read
    # touching every numeric column must cost ONE batched read, not six
    for i in range(6):
        got = fr.col(f"n{i}").to_numpy()
        np.testing.assert_allclose(got, cols[f"n{i}"], rtol=1e-6)
    lazy_calls = calls[eager_calls:]
    assert len(lazy_calls) == 1, calls
    assert sorted(lazy_calls[0]) == [f"n{i}" for i in range(6)]
    fr.delete()


def test_stream_append_refuses_domainless_cat(cl):
    """Review hardening: a categorical column with NO domain
    (integer-coded) must refuse streaming appends — _grow_domain's
    empty-old-domain perm would otherwise silently remap every existing
    code to 0 on device."""
    from h2o3_tpu.core.frame import Column, Frame, T_CAT
    from h2o3_tpu.ingest import chunked

    fr = Frame()
    fr.add("g", Column.from_numpy(np.array([0, 1, 2, 1]), ctype=T_CAT))
    fr.add("x", Column.from_numpy(np.array([1.0, 2.0, 3.0, 4.0])))
    assert fr.col("g").domain is None
    before = np.asarray(fr.col("g").data).copy()
    with pytest.raises(ValueError, match="no domain"):
        chunked.append_csv(fr, "a,5.0\n")
    assert np.array_equal(np.asarray(fr.col("g").data), before)
    assert fr.nrows == 4


def test_lazy_parquet_concurrent_first_touch(cl, tmp_path, monkeypatch):
    """Review hardening: the batch loader must not hold its lock across
    the disk read — concurrent first-touches stay correct, duplicate
    window reads are suppressed (a toucher of an in-flight column waits
    for the install instead of re-reading), and the read count stays at
    ceil(columns / batch)."""
    pq = pytest.importorskip("pyarrow.parquet")
    from concurrent.futures import ThreadPoolExecutor

    import pyarrow as pa

    from h2o3_tpu.ingest.parser import lazy_import_parquet

    monkeypatch.setenv("H2O_TPU_INGEST_PARQUET_BATCH", "4")
    n = 48
    rng = np.random.default_rng(11)
    cols = {f"n{i}": rng.normal(size=n) for i in range(8)}
    path = tmp_path / "lazy_mt.parquet"
    pq.write_table(pa.table(cols), path)

    calls = []
    real_read = pq.read_table

    def counting_read(src, columns=None, **kw):
        calls.append(list(columns or []))
        return real_read(src, columns=columns, **kw)

    monkeypatch.setattr(pq, "read_table", counting_read)
    fr = lazy_import_parquet(str(path), destination_frame="lazy_mt_pq")
    with ThreadPoolExecutor(max_workers=8) as pool:
        got = list(pool.map(
            lambda i: fr.col(f"n{i}").to_numpy(), range(8)))
    for i in range(8):
        np.testing.assert_allclose(got[i], cols[f"n{i}"], rtol=1e-6)
    # every column read exactly ONCE (windows depend on which touch wins
    # the claim race, but the in-flight wait forbids duplicate reads) and
    # batching holds: >= batch-width fewer reads than columns
    flat = sorted(nm for c in calls for nm in c)
    assert flat == sorted(cols), calls
    assert len(calls) <= 8 - (4 - 1), calls
    fr.delete()
