"""Flow SPA: serve + scripted walk of the exact REST loop the page drives
(VERDICT r4 item 6 acceptance: import → parse → train → leaderboard →
predict completes through the Flow surface)."""

import json
import time
import urllib.parse
import urllib.request

import numpy as np
import pytest

import h2o3_tpu


@pytest.fixture(scope="module")
def server():
    from h2o3_tpu.api.server import start_server

    h2o3_tpu.init()
    srv = start_server(port=0)
    yield f"http://127.0.0.1:{srv.port}"
    srv.stop()


def _post(base, path, data=None, js=None):
    if js is not None:
        body = json.dumps(js).encode()
        req = urllib.request.Request(base + path, data=body, method="POST",
                                     headers={"Content-Type":
                                              "application/json"})
    else:
        body = urllib.parse.urlencode(data or {}).encode()
        req = urllib.request.Request(base + path, data=body, method="POST")
    with urllib.request.urlopen(req, timeout=120) as r:
        return json.loads(r.read())


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=60) as r:
        return json.loads(r.read())


def _wait_job(base, key):
    for _ in range(600):
        j = _get(base, "/3/Jobs/" + urllib.parse.quote(key, safe=""))["jobs"][0]
        if j["status"] in ("DONE", "FAILED", "CANCELLED"):
            assert j["status"] == "DONE", j
            return
        time.sleep(0.2)
    raise AssertionError("job hung")


def test_flow_page_served(server):
    with urllib.request.urlopen(server + "/flow/index.html") as r:
        html = r.read().decode()
    assert r.headers["Content-Type"].startswith("text/html")
    # the SPA, not the fallback status page: its JS drives these routes
    for needle in ("h2o3-tpu Flow", "/3/Parse", "/3/ModelBuilders/",
                   "/99/AutoMLBuilder", "/3/Predictions/models/"):
        assert needle in html, needle
    with urllib.request.urlopen(server + "/") as r2:
        assert b"h2o3-tpu Flow" in r2.read()


def test_flow_loop_import_train_leaderboard_predict(server, tmp_path):
    # 1 import+parse (the SPA's importFile())
    rng = np.random.default_rng(7)
    csv = tmp_path / "flow_walk.csv"
    with open(csv, "w") as f:
        f.write("a,b,y\n")
        for _ in range(400):
            a, b = rng.normal(), rng.normal()
            pr = 1 / (1 + np.exp(-(2 * a - b)))
            f.write(f"{a:.4f},{b:.4f},{'YN'[int(rng.random() < pr)]}\n")
    out = _post(server, "/3/Parse",
                {"source_frames": json.dumps([str(csv)]),
                 "destination_frame": "flow_walk.hex"})
    _wait_job(server, out["job"]["key"]["name"])
    frames = [f["frame_id"]["name"] for f in _get(server, "/3/Frames")["frames"]]
    assert "flow_walk.hex" in frames

    # frame preview (the SPA's preview())
    fg = _get(server, "/3/Frames/flow_walk.hex?row_count=5")["frames"][0]
    assert [c["label"] for c in fg["columns"]] == ["a", "b", "y"]
    assert len(fg["columns"][0]["data"]) >= 5

    # 2 train (the SPA's train())
    out = _post(server, "/3/ModelBuilders/gbm",
                {"training_frame": "flow_walk.hex", "response_column": "y",
                 "ntrees": 5, "max_depth": 3, "model_id": "flow_gbm"})
    _wait_job(server, out["job"]["key"]["name"])
    models = [m["model_id"]["name"] for m in _get(server, "/3/Models")["models"]]
    assert "flow_gbm" in models

    # 3 AutoML + leaderboard (the SPA's automl())
    out = _post(server, "/99/AutoMLBuilder", js={
        "input_spec": {"training_frame": "flow_walk.hex",
                       "response_column": "y"},
        "build_control": {"project_name": "flow_aml", "nfolds": 0,
                          "stopping_criteria": {"max_models": 2}},
        "build_models": {"include_algos": ["GLM", "GBM"]}})
    _wait_job(server, out["job"]["key"]["name"])
    lb = _get(server, "/99/Leaderboards/flow_aml")
    t = lb.get("table") or lb.get("leaderboard_table")
    assert t and t["columns"] and t["data"] and len(t["data"][0]) >= 2

    # 4 predict (the SPA's predict()) + prediction preview
    out = _post(server, "/3/Predictions/models/flow_gbm/frames/flow_walk.hex",
                {})
    pf = out["predictions_frame"]["name"]
    assert out["model_metrics"], "v3 predict returns metrics for the SPA"
    pg = _get(server, "/3/Frames/" + urllib.parse.quote(pf) +
              "?row_count=5")["frames"][0]
    assert any(c["label"] == "predict" for c in pg["columns"])
