"""MOJO export/import round trip + Generic estimator.

Reference: hex/genmodel ModelMojoReader/MojoModel (artifact contract) and
hex/generic/Generic.java (MOJO as first-class model). Acceptance (VERDICT
r2 task #3): export → reimport → IDENTICAL predictions per algo, phantom
H2OGenericEstimator entry replaced by a real implementation.
"""

import io
import zipfile

import numpy as np
import pytest

from h2o3_tpu.core.frame import Column, Frame
from h2o3_tpu.models import mojo


@pytest.fixture(scope="module")
def data(cl):
    rng = np.random.default_rng(7)
    n = 1200
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    g = np.array(["a", "b", "c", "d"])[rng.integers(0, 4, n)]
    logit = 1.3 * x1 - x2 + (g == "a") * 1.0 - (g == "d") * 0.7
    ybin = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "Y", "N")
    yreg = logit + 0.2 * rng.normal(size=n)
    fr = Frame()
    fr.add("x1", Column.from_numpy(x1))
    fr.add("x2", Column.from_numpy(x2))
    fr.add("g", Column.from_numpy(g, ctype="enum"))
    fr.add("ybin", Column.from_numpy(ybin, ctype="enum"))
    fr.add("yreg", Column.from_numpy(yreg))
    return fr


def _roundtrip_identical(model, fr, tmp_path, pred_cols=None):
    path = model.download_mojo(str(tmp_path / f"{model.algo_name}.zip"))
    loaded = mojo.read_mojo(path)
    p0 = model.predict(fr).to_pandas()
    p1 = loaded.predict(fr).to_pandas()
    assert list(p0.columns) == list(p1.columns)
    for c in (pred_cols or p0.columns):
        a, b = p0[c].to_numpy(), p1[c].to_numpy()
        if a.dtype.kind in "fc":
            np.testing.assert_allclose(a.astype(float), b.astype(float),
                                       rtol=0, atol=0)
        else:
            assert (a == b).all()
    return path


def test_mojo_container_layout(data, tmp_path, cl):
    from h2o3_tpu.models.tree.gbm import GBM

    m = GBM(ntrees=5, max_depth=3, seed=1).train(y="ybin", training_frame=data)
    blob = mojo.export_mojo_bytes(m)
    with zipfile.ZipFile(io.BytesIO(blob)) as z:
        names = z.namelist()
        assert "model.ini" in names
        ini = z.read("model.ini").decode()
        assert "[info]" in ini and "[columns]" in ini and "[domains]" in ini
        assert "algo = gbm" in ini
        assert "category = Binomial" in ini
        # domains files referenced by the ini exist
        assert any(n.startswith("domains/") for n in names)


def test_gbm_roundtrip(data, tmp_path, cl):
    from h2o3_tpu.models.tree.gbm import GBM

    m = GBM(ntrees=8, max_depth=4, seed=1).train(y="ybin", training_frame=data)
    _roundtrip_identical(m, data, tmp_path)


def test_gbm_regression_roundtrip(data, tmp_path, cl):
    from h2o3_tpu.models.tree.gbm import GBM

    m = GBM(ntrees=6, max_depth=3, seed=2).train(y="yreg", training_frame=data)
    _roundtrip_identical(m, data, tmp_path)


def test_drf_roundtrip(data, tmp_path, cl):
    from h2o3_tpu.models.tree.drf import DRF

    m = DRF(ntrees=6, max_depth=5, seed=3).train(y="ybin", training_frame=data)
    _roundtrip_identical(m, data, tmp_path)


def test_isofor_roundtrip(data, tmp_path, cl):
    from h2o3_tpu.models.tree.isofor import IsolationForest

    m = IsolationForest(ntrees=10, seed=4).train(
        training_frame=data.subframe(["x1", "x2", "g"]))
    _roundtrip_identical(m, data.subframe(["x1", "x2", "g"]), tmp_path)


def test_xgboost_roundtrip(data, tmp_path, cl):
    from h2o3_tpu.models.xgboost import XGBoost

    m = XGBoost(ntrees=6, max_depth=3, seed=5).train(y="ybin",
                                                     training_frame=data)
    _roundtrip_identical(m, data, tmp_path)


def test_glm_roundtrip(data, tmp_path, cl):
    from h2o3_tpu.models.glm import GLM

    m = GLM(family="binomial", lambda_=0.0).train(y="ybin",
                                                  training_frame=data)
    _roundtrip_identical(m, data, tmp_path)


def test_kmeans_roundtrip(data, tmp_path, cl):
    from h2o3_tpu.models.kmeans import KMeans

    sub = data.subframe(["x1", "x2"])
    m = KMeans(k=3, seed=6).train(training_frame=sub)
    _roundtrip_identical(m, sub, tmp_path)


def test_deeplearning_roundtrip(data, tmp_path, cl):
    from h2o3_tpu.models.deeplearning import DeepLearning

    m = DeepLearning(hidden=[8, 8], epochs=3, seed=7).train(
        y="ybin", training_frame=data)
    _roundtrip_identical(m, data, tmp_path)


def test_generic_estimator(data, tmp_path, cl):
    import h2o3_tpu
    from h2o3_tpu.models.tree.gbm import GBM

    m = GBM(ntrees=5, max_depth=3, seed=8).train(y="ybin", training_frame=data)
    path = m.download_mojo(str(tmp_path / "for_generic.zip"))
    # the public entry that was a phantom for two rounds
    est = h2o3_tpu.H2OGenericEstimator(path=path)
    gm = est.train()
    assert gm.algo_name == "generic"
    assert gm.inner_algo == "gbm"
    p0 = m.predict(data).to_pandas()
    p1 = gm.predict(data).to_pandas()
    np.testing.assert_allclose(p0["Y"].to_numpy(), p1["Y"].to_numpy())
    mm = gm.model_performance(data)
    assert mm is not None and np.isfinite(mm.auc)


def test_mojo_rest_endpoint(data, cl):
    from h2o3_tpu.api.server import start_server
    import urllib.request

    from h2o3_tpu.models.tree.gbm import GBM

    m = GBM(ntrees=4, max_depth=3, seed=9).train(y="ybin", training_frame=data)
    srv = start_server(port=0)
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/3/Models/{m.key}/mojo") as r:
            blob = r.read()
        loaded = mojo.read_mojo(blob)
        p0 = np.asarray(m.predict(data).col("Y").to_numpy())
        p1 = np.asarray(loaded.predict(data).col("Y").to_numpy())
        np.testing.assert_allclose(p0, p1)
    finally:
        srv.stop()
