"""Installable R client (h2o-r-tpu/): real-Rscript smoke when an R runtime
exists, plus an always-on consistency tier binding the package's wire
strings to the replayed transcript in test_h2or_wire.py (VERDICT r4 #7).

Reference: h2o-r/h2o-package/R/connection.R, frame.R, models.R."""

import os
import re
import shutil
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RPKG = os.path.join(REPO, "h2o-r-tpu")


def test_r_package_layout():
    """An installable R source package: DESCRIPTION + NAMESPACE + R/."""
    desc = open(os.path.join(RPKG, "DESCRIPTION")).read()
    assert "Package: h2o3tpu" in desc
    ns = open(os.path.join(RPKG, "NAMESPACE")).read()
    for fn in ("h2o.init", "h2o.importFile", "h2o.gbm", "h2o.predict",
               "h2o.performance", "h2o.automl"):
        assert f"export({fn})" in ns, fn
    for f in ("connection.R", "frame.R", "models.R"):
        assert os.path.exists(os.path.join(RPKG, "R", f)), f


def _r_source() -> str:
    out = []
    rdir = os.path.join(RPKG, "R")
    for f in sorted(os.listdir(rdir)):
        out.append(open(os.path.join(rdir, f)).read())
    return "\n".join(out)


def test_r_package_routes_match_wire_replay():
    """Every route the recorded-transcript test replays appears verbatim in
    the package source — the replay stays an honest proxy for the package."""
    src = _r_source()
    for route in ("/3/Cloud", "/3/InitID", "/3/Parse", "/3/Jobs/",
                  "/3/ModelBuilders/", "/3/Models/", "/4/Predictions/models/",
                  "/3/Predictions/models/", "/3/Frames/", "/3/DownloadDataset",
                  "/99/AutoMLBuilder", "/99/Leaderboards/"):
        assert route in src, f"R package no longer uses {route}"
    # v4 predict contract: dest read at the TOP level (models.R:679)
    assert "res$dest" in src and "res$key$name" in src
    # urlencoded POST bodies, NOT json (communication.R curlPerform)
    assert "application/x-www-form-urlencoded" in src


@pytest.mark.skipif(shutil.which("Rscript") is None,
                    reason="no R runtime in this image")
def test_r_package_live_smoke(tmp_path):
    """The REAL package drives a live server end-to-end via Rscript."""
    import h2o3_tpu
    from h2o3_tpu.api.server import start_server

    h2o3_tpu.init()
    srv = start_server(port=0)
    try:
        rng = np.random.default_rng(5)
        csv = tmp_path / "r_smoke.csv"
        with open(csv, "w") as f:
            f.write("a,b,y\n")
            for _ in range(300):
                a, b = rng.normal(), rng.normal()
                pr = 1 / (1 + np.exp(-(2 * a - b)))
                f.write(f"{a:.4f},{b:.4f},{'YN'[int(rng.random() < pr)]}\n")
        proc = subprocess.run(
            ["Rscript", os.path.join(RPKG, "tests", "smoke.R"),
             str(srv.port), str(csv)],
            capture_output=True, text=True, timeout=600)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        for marker in ("IMPORT_OK", "TRAIN_OK", "PREDICT_OK", "R_SMOKE_DONE"):
            assert marker in proc.stdout, proc.stdout
    finally:
        srv.stop()
