"""Cross-scoring parity vs REAL reference MOJO artifacts (VERDICT r4 #5).

Ground truth = the hard-coded expectations of the reference's own genmodel
tests (GbmMojoModelTest.java, GlmMojoModelTest.java), scored here against
the UNMODIFIED artifacts shipped in the reference test resources — no JVM
involved; the importer (models/mojo_java.py) decodes the compressed-tree
byte format and scores through device arrays.
"""

import os

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu.core.frame import Column, Frame

REF = "/root/reference/h2o-genmodel/src/test/resources/hex/genmodel/algos"
GBM_FIXTURE = os.path.join(REF, "gbm", "calibrated")
GLM_FIXTURE = os.path.join(REF, "glm", "prostate")

pytestmark = pytest.mark.skipif(
    not os.path.isdir(GBM_FIXTURE),
    reason="reference genmodel fixtures not present")


@pytest.fixture(scope="module", autouse=True)
def _cluster():
    h2o3_tpu.init()
    yield


def _read_domain(fixture, fname):
    with open(os.path.join(fixture, "domains", fname)) as f:
        return [ln.rstrip("\n") for ln in f if ln != "\n"]


def test_gbm_reference_mojo_parity():
    """GbmMojoModelTest.testScore0/testPredict: row → [0.5416688,
    0.4583312], label '1', calibrated [0.3920402, 0.6079598]."""
    from h2o3_tpu.models.generic import Generic

    model = Generic(path=GBM_FIXTURE).train()
    num_cols = ["SegSumT", "SegTSeas", "SegLowFlow", "DSDist", "DSMaxSlope",
                "USAvgT", "USRainDays", "USSlope", "USNative", "DSDam"]
    vals = [18.7, 1.51, 1.003, 132.53, 1.15, 0.2, 1.153, 8.3, 0.34, 0.0]
    fr = Frame()
    for c, v in zip(num_cols, vals):
        fr.add(c, Column.from_numpy(np.asarray([v], np.float64)))
    fr.add("Method", Column.from_numpy(np.asarray(["electric"]),
                                       ctype="enum"))
    pred = model.predict(fr)
    p0 = float(pred.col("0").to_numpy()[0])
    p1 = float(pred.col("1").to_numpy()[0])
    assert p0 == pytest.approx(0.5416688, abs=1e-5)
    assert p1 == pytest.approx(0.4583312, abs=1e-5)
    lbl = pred.col("predict").values()[0]
    assert str(lbl) == "1"          # p1 >= default_threshold 0.29007…
    cal1 = float(pred.col("cal_1").to_numpy()[0])
    cal0 = float(pred.col("cal_0").to_numpy()[0])
    assert cal1 == pytest.approx(0.6079598, abs=1e-5)
    assert cal0 == pytest.approx(0.3920402, abs=1e-5)


def test_glm_reference_mojo_parity():
    """GlmMojoModelTest: 12 prostate rows (incl. one NaN needing mean
    imputation) → exact probabilities to 1e-7."""
    from h2o3_tpu.models import mojo

    model = mojo.read_mojo(GLM_FIXTURE)
    race_dom = _read_domain(GLM_FIXTURE, "d000.txt")
    data = np.asarray([
        [2, 73, 2, 1, 7.9, 18, 6],
        [1, 51, 3, 1, 8.9, 0, 6],
        [2, 57, 3, 1, 3.4, 30.8, 6],
        [1, 65, 4, 1, 6.3, 0, 6],
        [1, 61, 3, 1, 1.5, 0, 5],
        [1, 56, 2, 2, 58, 0, 6],
        [1, 72, 2, 1, 1.4, 24.2, 6],
        [1, 54, 2, 1, 18, 43, 9],
        [1, 62, 2, 1, 7.3, 0, 7],
        [2, 63, 3, 1, 14.3, 16, 7],
        [1, 68, 1, 1, 5.4, 34, 5],
        [1, np.nan, 1, 1, 5.4, 34, 5],
    ])
    exp = np.asarray([
        [0.0, 0.883740206424754, 0.11625979357524593],
        [1.0, 0.5591006829867439, 0.44089931701325613],
        [0.0, 0.8200793110208472, 0.1799206889791528],
        [1.0, 0.4855023555733662, 0.5144976444266338],
        [0.0, 0.8260781970262484, 0.17392180297375157],
        [1.0, 0.2685796973779421, 0.7314203026220579],
        [0.0, 0.8265057623033865, 0.1734942376966135],
        [1.0, 0.1332488800455477, 0.8667511199544523],
        [1.0, 0.5038183003787983, 0.49618169962120173],
        [1.0, 0.5384202639029669, 0.46157973609703307],
        [0.0, 0.9543248143434919, 0.04567518565650803],
        [0.0, 0.9531416700165544, 0.046858329983445586],
    ])
    fr = Frame()
    fr.add("RACE", Column.from_numpy(
        np.asarray([race_dom[int(c)] for c in data[:, 0]]), ctype="enum"))
    for j, name in enumerate(["AGE", "DPROS", "DCAPS", "PSA", "VOL",
                              "GLEASON"], start=1):
        fr.add(name, Column.from_numpy(data[:, j]))
    pred = model.predict(fr)
    got0 = np.asarray(pred.col("0").to_numpy(), np.float64)
    got1 = np.asarray(pred.col("1").to_numpy(), np.float64)
    np.testing.assert_allclose(got0, exp[:, 1], atol=1e-6)
    np.testing.assert_allclose(got1, exp[:, 2], atol=1e-6)
    lbl = pred.col("predict").values()
    assert [str(x) for x in lbl] == [str(int(e)) for e in exp[:, 0]]


def test_rest_import_reference_mojo(tmp_path):
    """The /3/ModelBuilders/generic REST path accepts a zipped reference
    MOJO (hex/generic/Generic.java parity at the API surface)."""
    import shutil
    import zipfile

    from h2o3_tpu.models import mojo

    zpath = tmp_path / "ref_gbm.zip"
    with zipfile.ZipFile(zpath, "w") as z:
        for root, _, files in os.walk(GBM_FIXTURE):
            for f in files:
                full = os.path.join(root, f)
                z.write(full, os.path.relpath(full, GBM_FIXTURE))
    model = mojo.read_mojo(str(zpath))
    assert model.algo_name == "gbm"
    assert model._output.response_domain == ["0", "1"]


def _train_data(seed=0, n=500):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 3))
    g = np.asarray(["p", "q", "r"])[rng.integers(0, 3, n)]
    logit = 1.5 * X[:, 0] - X[:, 1] + (g == "p") * 1.0
    fr = Frame.from_numpy(X, names=["a", "b", "c"])
    fr.add("g", Column.from_numpy(g, ctype="enum"))
    ybin = np.where(np.random.default_rng(seed + 1).random(n)
                    < 1 / (1 + np.exp(-logit)), "Y", "N")
    yreg = logit + rng.normal(0, 0.2, n)
    ymul = np.asarray(["u", "v", "w"])[
        np.argmax(np.stack([logit, -logit, X[:, 2]], 1), 1)]
    return fr, ybin, yreg, ymul


def _export_roundtrip(model, fr, prob_cols):
    """Export in the REFERENCE byte format, re-import through the reader
    that is itself validated against real h2o-3 artifacts, compare."""
    from h2o3_tpu.models.mojo_java import export_java_mojo_bytes

    from h2o3_tpu.models import mojo

    blob = export_java_mojo_bytes(model)
    loaded = mojo.read_mojo(blob)           # dispatches to the java reader
    want = model.predict(fr).to_pandas()
    got = loaded.predict(fr).to_pandas()
    for c in prob_cols:
        np.testing.assert_allclose(want[c].to_numpy(float),
                                   got[c].to_numpy(float), atol=2e-5)
    try:
        want["predict"].to_numpy(float)
        numeric_predict = True
    except (ValueError, TypeError):
        numeric_predict = False
    if numeric_predict:     # regression: allclose above already covers it
        np.testing.assert_allclose(want["predict"].to_numpy(float),
                                   got["predict"].to_numpy(float), atol=2e-5)
    else:
        agree = (want["predict"].astype(str).to_numpy()
                 == got["predict"].astype(str).to_numpy()).mean()
        assert agree > 0.995, agree


def test_export_reference_format_gbm_binomial():
    from h2o3_tpu.models.tree.gbm import GBM

    fr, ybin, _, _ = _train_data(1)
    tr = fr.subframe(fr.names)
    tr.add("y", Column.from_numpy(ybin, ctype="enum"))
    m = GBM(ntrees=8, max_depth=4, seed=1).train(y="y", training_frame=tr)
    _export_roundtrip(m, tr, ["Y", "N"])


def test_export_reference_format_gbm_regression():
    from h2o3_tpu.models.tree.gbm import GBM

    fr, _, yreg, _ = _train_data(2)
    tr = fr.subframe(fr.names)
    tr.add("y", Column.from_numpy(yreg))
    m = GBM(ntrees=6, max_depth=3, seed=2).train(y="y", training_frame=tr)
    _export_roundtrip(m, tr, ["predict"])


def test_export_reference_format_gbm_multinomial():
    from h2o3_tpu.models.tree.gbm import GBM

    fr, _, _, ymul = _train_data(3)
    tr = fr.subframe(fr.names)
    tr.add("y", Column.from_numpy(ymul, ctype="enum"))
    m = GBM(ntrees=5, max_depth=3, seed=3).train(y="y", training_frame=tr)
    _export_roundtrip(m, tr, ["u", "v", "w"])


def test_export_reference_format_drf():
    from h2o3_tpu.models.tree.drf import DRF

    fr, ybin, yreg, _ = _train_data(4)
    tr = fr.subframe(fr.names)
    tr.add("y", Column.from_numpy(ybin, ctype="enum"))
    m = DRF(ntrees=10, max_depth=5, seed=4).train(y="y", training_frame=tr)
    _export_roundtrip(m, tr, ["Y", "N"])
    tr2 = fr.subframe(fr.names)
    tr2.add("y", Column.from_numpy(yreg))
    m2 = DRF(ntrees=8, max_depth=4, seed=5).train(y="y", training_frame=tr2)
    _export_roundtrip(m2, tr2, ["predict"])


def test_export_reference_format_glm():
    """GLM → reference model.ini (GlmMojoReader fields), re-imported by
    the reader already pinned to GlmMojoModelTest ground truth; includes
    a categorical + standardized numerics so beta de-standardization and
    the cat_offsets layout are both exercised."""
    from h2o3_tpu.models.glm import GLM

    fr, ybin, yreg, _ = _train_data(6)
    tr = fr.subframe(fr.names)
    tr.add("y", Column.from_numpy(ybin, ctype="enum"))
    m = GLM(family="binomial", lambda_=0.0, seed=1).train(
        y="y", training_frame=tr)
    _export_roundtrip(m, tr, ["Y", "N"])
    tr2 = fr.subframe(fr.names)
    tr2.add("y", Column.from_numpy(yreg))
    m2 = GLM(family="gaussian", lambda_=0.0, seed=1).train(
        y="y", training_frame=tr2)
    _export_roundtrip(m2, tr2, ["predict"])


def test_export_reference_format_glm_gates_and_tweedie():
    """Unsupported GLM variants are rejected loudly; tweedie round-trips
    with its link power instead of silently degenerating to identity."""
    from h2o3_tpu.models.glm import GLM
    from h2o3_tpu.models.mojo_java import export_java_mojo_bytes

    rng = np.random.default_rng(8)
    n = 300
    X = rng.normal(size=(n, 2))
    mu = np.exp(0.8 * X[:, 0] - 0.3 * X[:, 1] + 1.0)
    ytw = rng.poisson(mu).astype(np.float64)       # tweedie-ish positives
    fr = Frame.from_numpy(np.column_stack([X, ytw]), names=["a", "b", "y"])
    m = GLM(family="tweedie", lambda_=0.0, seed=1).train(
        y="y", training_frame=fr)
    _export_roundtrip(m, fr, ["predict"])

    off = Frame.from_numpy(np.column_stack([X, np.ones(n), ytw]),
                           names=["a", "b", "off", "y"])
    m2 = GLM(family="poisson", lambda_=0.0, offset_column="off",
             seed=1).train(y="y", training_frame=off)
    with pytest.raises(ValueError, match="offset"):
        export_java_mojo_bytes(m2)


def test_export_reference_format_drf_double_trees():
    """binomial_double_trees DRF: per-class trees export with tpc=2 and
    the multinomial-style accumulate, matching the format's semantics."""
    from h2o3_tpu.models.tree.drf import DRF

    fr, ybin, _, _ = _train_data(9)
    tr = fr.subframe(fr.names)
    tr.add("y", Column.from_numpy(ybin, ctype="enum"))
    m = DRF(ntrees=6, max_depth=4, seed=9, binomial_double_trees=True).train(
        y="y", training_frame=tr)
    from h2o3_tpu.models.mojo_java import export_java_mojo_bytes
    import io as _io
    import zipfile as _zf

    blob = export_java_mojo_bytes(m)
    with _zf.ZipFile(_io.BytesIO(blob)) as z:
        names = z.namelist()
        ini = z.read("model.ini").decode()
    assert "binomial_double_trees = true" in ini
    assert "n_trees_per_class = 2" in ini
    assert any(n.startswith("trees/t01_") for n in names)  # class-1 trees
    _export_roundtrip(m, tr, ["Y", "N"])
