"""AutoML WorkAllocations + step registry, max_runtime_secs enforcement,
bindings codegen, client-mode init.

Reference: ai.h2o.automl.WorkAllocations/ModelingStepsRegistry,
hex/ModelBuilder _max_runtime_secs, h2o-bindings/bin/gen_python.py,
H2O client mode (-client) / h2o-py h2o.init(url=...).
"""

import time

import numpy as np
import pytest

from h2o3_tpu.core.frame import Column, Frame


def _frame(n=600, seed=0):
    rng = np.random.default_rng(seed)
    x1, x2 = rng.standard_normal((2, n))
    y = np.where(rng.random(n) < 1 / (1 + np.exp(-(2 * x1 - x2))), "Y", "N")
    fr = Frame()
    fr.add("x1", Column.from_numpy(x1))
    fr.add("x2", Column.from_numpy(x2))
    fr.add("y", Column.from_numpy(y, ctype="enum"))
    return fr


class TestWorkAllocations:
    def test_plan_and_allocations(self, cl):
        from h2o3_tpu.automl.automl import H2OAutoML

        am = H2OAutoML(max_models=3, max_runtime_secs=120, seed=42, nfolds=2,
                       include_algos=["gbm", "glm"])
        am.train(y="y", training_frame=_frame())
        assert am.leader is not None
        plan = am.modeling_plan
        assert plan and all("weight" in st for st in plan)
        # built steps record their model; allocation messages logged
        built = [st for st in plan if st.get("model_id")]
        assert built
        assert any("allocated" in e["message"] for e in am.event_log)

    def test_te_predict_preprocesses(self, cl):
        """The (previously shadowed) predict() must apply TE before the
        leader scores."""
        from h2o3_tpu.automl.automl import H2OAutoML

        rng = np.random.default_rng(1)
        n = 400
        g = np.array(["a", "b", "c"], object)[rng.integers(0, 3, n)]
        y = np.where(rng.random(n) < (0.2 + 0.3 * (g == "a")), "Y", "N")
        fr = Frame()
        fr.add("g", Column.from_numpy(g, ctype="enum"))
        fr.add("y", Column.from_numpy(y, ctype="enum"))
        am = H2OAutoML(max_models=1, seed=7, nfolds=2,
                       include_algos=["gbm"],
                       preprocessing=["target_encoding"])
        am.train(y="y", training_frame=fr)
        preds = am.predict(fr)          # must not raise on raw (un-encoded) frame
        assert preds.nrows == n


class TestMaxRuntime:
    def test_gbm_budget_truncates(self, cl):
        from h2o3_tpu.models.tree.gbm import GBM

        fr = _frame(2000)
        m = GBM(ntrees=2000, max_depth=3, seed=1,
                max_runtime_secs=3.0).train(y="y", training_frame=fr)
        # far fewer trees than requested, and a working model
        assert 0 < m.forest.n_trees < 2000
        assert float(m._output.training_metrics.auc) > 0.5

    def test_dl_budget_truncates(self, cl):
        from h2o3_tpu.models.deeplearning import DeepLearning

        fr = _frame(1500)
        m = DeepLearning(epochs=100000, hidden=[16], seed=1,
                         max_runtime_secs=3.0).train(y="y", training_frame=fr)
        assert m.epochs_trained < 100000


class TestBindings:
    def test_generate_and_train(self, cl):
        from h2o3_tpu import bindings

        src = bindings.generate_python()
        assert "class H2OGradientBoostingEstimator" in src
        classes = bindings.load_generated()
        est = classes["H2OGradientBoostingEstimator"](ntrees=3, max_depth=3,
                                                      seed=1)
        m = est.train(y="y", training_frame=_frame())
        assert float(m._output.training_metrics.auc) > 0.5

    def test_write_module(self, cl, tmp_path):
        from h2o3_tpu import bindings

        p = bindings.write_python(str(tmp_path / "estimators_gen.py"))
        text = open(p).read()
        assert "__all__" in text and "H2OKMeansEstimator" in text


class TestClientModeInit:
    def test_init_url_connects(self, cl):
        import h2o3_tpu
        from h2o3_tpu import client
        from h2o3_tpu.api.server import start_server

        srv = start_server(port=0)
        try:
            c = h2o3_tpu.init(url=f"http://127.0.0.1:{srv.port}")
            assert c.cluster_status()["cloud_healthy"]
            c2 = h2o3_tpu.connect(port=srv.port)
            assert c2 is client
        finally:
            srv.stop()
