"""North-star compatibility: GENUINE h2o-py drives this server unchanged.

SURVEY §7: serve the /3/* contracts "so h2o-py works unchanged". These tests
import the real reference client (h2o-py/h2o, loaded read-only via
tests/h2opy_support.py) and run the canonical user journey against our REST
server: connect → import_file → munge → train GBM/GLM → predict →
model_performance → AUC.

Reference flows exercised:
- H2OConnection.open handshake (backend/connection.py:260: GET /3/Cloud
  with CloudV3 schema, POST /4/sessions)
- import_file (h2o.py:401: POST /3/ImportFilesMulti → POST /3/ParseSetup →
  POST /3/Parse → job poll → GET /3/Frames/{id})
- estimator.train (estimators/estimator_base.py:190: POST
  /3/ModelBuilders/{algo} → job poll → GET /3/Models/{id})
- predict (model/model_base.py:236: POST /4/Predictions → job → frame)
- model_performance (model_base.py:383: POST /3/ModelMetrics)
- Rapids exprs from the client-side lazy AST (expr.py:258: POST /99/Rapids)
"""

import numpy as np
import pytest

from tests.h2opy_support import ensure_h2opy


@pytest.fixture(scope="module")
def h2o(cl):
    from h2o3_tpu.api.server import start_server

    srv = start_server(port=0)
    h2o = ensure_h2opy()
    h2o.connect(url=f"http://127.0.0.1:{srv.port}", verbose=False)
    # don't let the progress bar spam test output
    h2o.no_progress()
    yield h2o
    srv.stop()


@pytest.fixture(scope="module")
def air(h2o, airlines_csv):
    return h2o.import_file(airlines_csv, destination_frame="air.hex")


def test_connect_handshake(h2o):
    cl = h2o.cluster()
    assert cl.cloud_healthy
    assert cl.cloud_size >= 1
    assert cl.version


def test_import_file_frame_metadata(h2o, air):
    assert air.nrows == 2000
    assert air.ncols == 5
    assert air.names == ["DayOfWeek", "Carrier", "Distance", "DepTime",
                         "IsDepDelayed"]
    types = air.types
    assert types["DayOfWeek"] == "enum"
    assert types["Distance"] in ("int", "real")
    assert types["IsDepDelayed"] == "enum"


def test_frame_munging_rapids(h2o, air):
    # column select + filter through the client's lazy AST
    sub = air[air["Distance"] > 1000, :]
    assert 0 < sub.nrows < 2000
    m = air["Distance"].mean()
    mval = m[0] if isinstance(m, list) else m
    assert 100 < float(mval) < 3000
    # factor levels
    levels = air["DayOfWeek"].levels()[0]
    assert set(levels) == {"Mon", "Tue", "Wed", "Thu", "Fri", "Sat", "Sun"}


def test_gbm_end_to_end(h2o, air):
    from h2o.estimators.gbm import H2OGradientBoostingEstimator

    train, test = air.split_frame(ratios=[0.8], seed=17)
    gbm = H2OGradientBoostingEstimator(ntrees=20, max_depth=4, seed=42)
    gbm.train(x=["DayOfWeek", "Carrier", "Distance", "DepTime"],
              y="IsDepDelayed", training_frame=train)
    # in-sample quality sanity (delay is a deterministic-ish function)
    perf_train = gbm.model_performance(train=True)
    assert perf_train.auc() > 0.8
    # holdout metrics through POST /3/ModelMetrics
    perf = gbm.model_performance(test)
    assert 0.6 < perf.auc() <= 1.0
    assert perf.logloss() > 0
    # prediction frame through POST /4/Predictions
    preds = gbm.predict(test)
    assert preds.nrows == test.nrows
    assert "predict" in preds.names
    pdf = preds.as_data_frame(use_pandas=True)
    assert set(pdf["predict"].unique()) <= {"YES", "NO"}
    # varimp present and DepTime/Distance dominate
    vi = gbm.varimp()
    assert len(vi) == 4


def test_glm_end_to_end(h2o, air):
    from h2o.estimators.glm import H2OGeneralizedLinearEstimator

    glm = H2OGeneralizedLinearEstimator(family="binomial", lambda_=0.0)
    glm.train(x=["Distance", "DepTime"], y="IsDepDelayed", training_frame=air)
    assert glm.model_performance(train=True).auc() > 0.7


def test_confusion_matrix_and_thresholds(h2o, air):
    from h2o.estimators.gbm import H2OGradientBoostingEstimator

    gbm = H2OGradientBoostingEstimator(ntrees=10, max_depth=3, seed=1)
    gbm.train(x=["Distance", "DepTime"], y="IsDepDelayed", training_frame=air)
    perf = gbm.model_performance(train=True)
    cm = perf.confusion_matrix()           # uses thresholds_and_metric_scores
    tbl = cm.table
    assert tbl is not None
    thr = perf.find_threshold_by_max_metric("f1")
    assert 0.0 <= thr <= 1.0


def test_frame_delete_and_list(h2o, airlines_csv):
    fr = h2o.import_file(airlines_csv, destination_frame="todelete.hex")
    ids = [f for f in h2o.ls()["key"].tolist()] if hasattr(h2o.ls(), "key") else []
    h2o.remove(fr)
    fr2 = h2o.get_frame("todelete.hex")
    assert fr2 is None


def test_create_frame_via_h2opy(h2o):
    """h2o.create_frame drives POST /3/CreateFrame + job poll + get_frame
    (h2o-py h2o.py:1744)."""
    fr = h2o.create_frame(frame_id="cfpy.hex", rows=300, cols=4,
                          categorical_fraction=0.25, factors=4,
                          integer_fraction=0.25, seed=11)
    assert fr.nrows == 300 and fr.ncols == 4
    assert "enum" in fr.types.values()


def test_predict_contributions_via_h2opy(h2o, air):
    """Genuine h2o-py TreeSHAP flow: POST /4/Predictions + flag -> job ->
    contributions frame with BiasTerm; local accuracy spot check."""
    from h2o.estimators import H2OGradientBoostingEstimator

    m = H2OGradientBoostingEstimator(ntrees=5, max_depth=3, seed=1)
    m.train(y="IsDepDelayed", training_frame=air)
    contribs = m.predict_contributions(air)
    assert contribs.ncols == 5            # 4 predictors + BiasTerm
    assert "BiasTerm" in contribs.names
    df = contribs.as_data_frame()
    assert np.isfinite(df.to_numpy(dtype=float)).all()


def test_grid_search_via_h2opy(h2o, air):
    """Genuine h2o-py H2OGridSearch: POST /99/Grid/{algo} -> job poll ->
    GET /99/Grids/{id} -> ranked models (grid/grid_search.py:383-420)."""
    from h2o.estimators import H2OGradientBoostingEstimator
    from h2o.grid.grid_search import H2OGridSearch

    gs = H2OGridSearch(
        H2OGradientBoostingEstimator(seed=7),
        hyper_params={"max_depth": [2, 4], "ntrees": [3, 5]})
    gs.train(y="IsDepDelayed", training_frame=air)
    assert len(gs.model_ids) == 4
    best = gs.get_grid(sort_by="auc", decreasing=True)
    aucs = [m.auc() for m in best.models]
    assert aucs == sorted(aucs, reverse=True)
    assert aucs[0] > 0.55


def test_automl_via_h2opy(h2o, air):
    """Genuine h2o-py H2OAutoML: POST /99/AutoMLBuilder -> job poll ->
    GET /99/AutoML/{id} state (leaderboard/event-log TwoDimTables) ->
    leader predict (autoh2o.py:471-525)."""
    from h2o.automl import H2OAutoML

    aml = H2OAutoML(max_models=2, seed=5, nfolds=2,
                    include_algos=["GBM"], verbosity=None)
    aml.train(y="IsDepDelayed", training_frame=air)
    assert aml.leader is not None
    lb = aml.leaderboard
    assert lb.nrows >= 2 and "model_id" in lb.names
    preds = aml.predict(air)
    assert preds.nrows == air.nrows


def test_varimp_and_mojo_download_via_h2opy(h2o, air, tmp_path):
    """Genuine h2o-py varimp table parse + MOJO artifact download
    (model_base.py:525 varimp, :969 download_mojo save_to)."""
    import os
    import zipfile

    from h2o.estimators import H2OGradientBoostingEstimator

    m = H2OGradientBoostingEstimator(ntrees=4, max_depth=3, seed=2)
    m.train(y="IsDepDelayed", training_frame=air)
    vi = m.varimp()
    assert vi and len(vi[0]) == 4            # (variable, rel, scaled, pct)
    names = [row[0] for row in vi]
    assert set(names) <= {"DayOfWeek", "Carrier", "Distance", "DepTime"}
    assert abs(sum(row[3] for row in vi) - 1.0) < 1e-6   # percentages
    path = m.download_mojo(path=str(tmp_path))
    assert os.path.exists(path)
    with zipfile.ZipFile(path) as z:
        assert "model.ini" in z.namelist()


def test_gains_lift_via_h2opy(h2o, air):
    """Genuine h2o-py gains/lift table (metrics_base.py:1724)."""
    from h2o.estimators import H2OGradientBoostingEstimator

    m = H2OGradientBoostingEstimator(ntrees=5, max_depth=3, seed=3)
    m.train(y="IsDepDelayed", training_frame=air)
    gl = m.model_performance().gains_lift()
    assert gl is not None
    rows = gl.cell_values
    assert rows
    hdr = gl.col_header
    assert "lift" in hdr and "cumulative_capture_rate" in hdr
    ccr = [r[hdr.index("cumulative_capture_rate")] for r in rows]
    assert abs(float(ccr[-1]) - 1.0) < 1e-6


def test_import_reference_mojo_via_h2opy(h2o, air, tmp_path):
    """h2o.import_mojo on a REFERENCE-format artifact (the byte format the
    stock genmodel jar reads): train → download ?format=reference →
    re-import through genuine h2o-py → predictions match the original."""
    from h2o.estimators import H2OGradientBoostingEstimator

    m = H2OGradientBoostingEstimator(ntrees=4, max_depth=3, seed=1,
                                     model_id="pymojo_gbm")
    m.train(y="IsDepDelayed", training_frame=air)
    # download the reference-format MOJO over REST, as a Java consumer would
    import urllib.request

    conn = h2o.connection()
    url = (conn.base_url +
           "/3/Models/pymojo_gbm/mojo?format=reference")
    path = str(tmp_path / "ref_mojo.zip")
    with urllib.request.urlopen(url, timeout=120) as r:
        blob = r.read()
    with open(path, "wb") as f:
        f.write(blob)
    import zipfile

    with zipfile.ZipFile(path) as z:
        assert "model.ini" in z.namelist()

    generic = h2o.import_mojo(path)
    p0 = m.predict(air).as_data_frame()
    p1 = generic.predict(air).as_data_frame()
    import numpy as np

    np.testing.assert_allclose(p0["YES"].to_numpy(float),
                               p1["YES"].to_numpy(float), atol=2e-5)
    agree = (p0["predict"].astype(str) == p1["predict"].astype(str)).mean()
    assert agree > 0.995


def test_leaf_node_assignment_via_h2opy(h2o, air):
    """ModelBase.predict_leaf_node_assignment (Path + Node_ID) through
    genuine h2o-py (model_base.py:148 posts leaf_node_assignment=True)."""
    from h2o.estimators import H2OGradientBoostingEstimator

    m = H2OGradientBoostingEstimator(ntrees=3, max_depth=3, seed=1)
    m.train(y="IsDepDelayed", training_frame=air)
    la = m.predict_leaf_node_assignment(air, type="Path")
    df = la.as_data_frame()
    assert df.shape == (air.nrow, 3)
    assert list(df.columns) == ["T1", "T2", "T3"]
    # every path is a root-to-leaf L/R walk within depth
    assert df["T1"].astype(str).str.fullmatch(r"[LR]{1,3}|\(root\)").all()
    ni = m.predict_leaf_node_assignment(air, type="Node_ID").as_data_frame()
    assert (ni >= 0).all().all()


def test_staged_predict_proba_via_h2opy(h2o, air):
    """ModelBase.staged_predict_proba through genuine h2o-py: per-stage
    probabilities converge to the final prediction's p0."""
    import numpy as np

    from h2o.estimators import H2OGradientBoostingEstimator

    m = H2OGradientBoostingEstimator(ntrees=4, max_depth=3, seed=1)
    m.train(y="IsDepDelayed", training_frame=air)
    st = m.staged_predict_proba(air).as_data_frame()
    assert list(st.columns) == ["T1.C1", "T2.C1", "T3.C1", "T4.C1"]
    final = m.predict(air).as_data_frame()
    # last stage == the full model's p0 (reference contract: C1 carries p0)
    np.testing.assert_allclose(st["T4.C1"].to_numpy(float),
                               final["NO"].to_numpy(float), atol=1e-5)
    # stages actually differ (the model is learning)
    assert not np.allclose(st["T1.C1"], st["T4.C1"])
