"""Round-4: Rapids prims that used to drop to host numpy now run on
device — cor / distance / mmult / table / cumsum complete with ZERO
full-column Column.to_numpy() fetches (VERDICT r3 #6 acceptance), results
unchanged vs the host reference computation.

Reference: water/rapids/ast/prims/advmath/AstCorrelation.java:1,
AstDistance.java, matrix/AstMMult.java, mungers/AstTable.java."""

import contextlib

import numpy as np
import pytest

from h2o3_tpu.core.frame import Column, Frame
from h2o3_tpu.rapids import exec_rapids

N = 100_000


@contextlib.contextmanager
def no_host_fetch():
    """Poison Column.to_numpy — any device→host column fetch fails."""
    orig = Column.to_numpy

    def boom(self, *a, **k):
        raise AssertionError("Column.to_numpy() called on the device path")

    Column.to_numpy = boom
    try:
        yield
    finally:
        Column.to_numpy = orig


@pytest.fixture(scope="module")
def big(cl):
    rng = np.random.default_rng(11)
    f = Frame(key="dev_fr")
    x = rng.normal(size=N)
    y = 0.6 * x + 0.8 * rng.normal(size=N)
    z = rng.normal(size=N)
    f.add("x", Column.from_numpy(x))
    f.add("y", Column.from_numpy(y))
    f.add("z", Column.from_numpy(z))
    f.add("g", Column.from_numpy(
        np.asarray(["a", "b", "c"], object)[rng.integers(0, 3, N)]
        .astype(str), ctype="enum"))
    f.install()
    return f, x, y, z


def test_cor_pearson_on_device(big):
    f, x, y, z = big
    sub = Frame(key="dev_xy")
    sub.add("x", f.col("x"))
    sub.add("y", f.col("y"))
    sub.install()
    with no_host_fetch():
        got = exec_rapids('(cor dev_xy dev_xy "everything" "pearson")')
        C = np.asarray([np.asarray(got.col(n).data)[:2] for n in got.names])
    want = np.corrcoef(x, y)
    np.testing.assert_allclose(np.asarray(C, float), want, atol=1e-5)


def test_cor_spearman_matches_scipy(big):
    f, x, y, z = big
    sub = Frame(key="dev_xy2")
    sub.add("x", f.col("x"))
    sub.add("y", f.col("y"))
    sub.install()
    with no_host_fetch():
        got = exec_rapids('(cor dev_xy2 dev_xy2 "complete.obs" "spearman")')
        C01 = float(np.asarray(got.col("y").data)[0])
    from scipy import stats as st

    want = st.spearmanr(x, y).statistic
    assert abs(C01 - want) < 1e-5


def test_cor_complete_obs_with_nas(cl):
    rng = np.random.default_rng(2)
    x = rng.normal(size=5000)
    y = 0.5 * x + rng.normal(size=5000)
    x[::17] = np.nan
    f = Frame(key="dev_na")
    f.add("x", Column.from_numpy(x))
    f.add("y", Column.from_numpy(y))
    f.install()
    with no_host_fetch():
        got = exec_rapids('(cor dev_na dev_na "complete.obs" "pearson")')
        c = float(np.asarray(got.col("y").data)[0])
    keep = ~np.isnan(x)
    want = np.corrcoef(x[keep], y[keep])[0, 1]
    assert abs(c - want) < 1e-5


def test_cumsum_on_device(big):
    f, x, *_ = big
    sub = Frame(key="dev_x")
    sub.add("x", f.col("x"))
    sub.install()
    with no_host_fetch():
        got = exec_rapids("(cumsum dev_x 0)")
        head = np.asarray(got.col(got.names[0]).data)[:1000]
    np.testing.assert_allclose(head, np.cumsum(x)[:1000], rtol=1e-4,
                               atol=1e-3)


def test_table_on_device(big):
    f, *_ = big
    sub = Frame(key="dev_g")
    sub.add("g", f.col("g"))
    sub.install()
    with no_host_fetch():
        got = exec_rapids("(table dev_g)")
    counts = np.asarray(got.col("nrow").to_numpy(), float)
    assert counts.sum() == N


def test_mmult_and_distance_on_device(cl):
    rng = np.random.default_rng(4)
    A = rng.normal(size=(2000, 3))
    B = rng.normal(size=(3, 2))
    fa = Frame(key="dev_A")
    for j in range(3):
        fa.add(f"a{j}", Column.from_numpy(A[:, j]))
    fa.install()
    fb = Frame(key="dev_B")
    for j in range(2):
        fb.add(f"b{j}", Column.from_numpy(B[:, j]))
    fb.install()
    with no_host_fetch():
        got = exec_rapids("(x dev_A dev_B)")
        M = np.column_stack([np.asarray(got.col(n).data)[:2000]
                             for n in got.names])
    np.testing.assert_allclose(M, A @ B, rtol=1e-4, atol=1e-4)

    fc = Frame(key="dev_C")
    for j in range(3):
        fc.add(f"c{j}", Column.from_numpy(A[:5, j]))
    fc.install()
    with no_host_fetch():
        got = exec_rapids('(distance dev_A dev_C "l2")')
        D = np.column_stack([np.asarray(got.col(n).data)[:2000]
                             for n in got.names])
    want = np.sqrt(((A[:, None, :] - A[None, :5, :]) ** 2).sum(-1))
    np.testing.assert_allclose(D, want, rtol=1e-3, atol=1e-3)
