"""Coverage sweep A: GainsLift/KS, TwoDimTable, basic auth, Flow landing.

Reference: hex/GainsLift.java, water/util/TwoDimTable.java, water.webserver
hash-file basic auth, h2o-web Flow.
"""

import hashlib
import urllib.request

import numpy as np
import pytest

from h2o3_tpu.core.frame import Column, Frame
from h2o3_tpu.models.tree.gbm import GBM
from h2o3_tpu.utils.twodim import TwoDimTable


@pytest.fixture(scope="module")
def model(cl):
    rng = np.random.default_rng(4)
    n = 2000
    x = rng.standard_normal(n)
    y = np.where(rng.random(n) < 1 / (1 + np.exp(-2 * x)), "Y", "N")
    fr = Frame()
    fr.add("x", Column.from_numpy(x))
    fr.add("y", Column.from_numpy(y, ctype="enum"))
    return GBM(ntrees=8, max_depth=3, seed=1).train(y="y", training_frame=fr), fr


class TestGainsLift:
    def test_table_invariants(self, model):
        m, fr = model
        t = m.gains_lift()
        assert t is not None and len(t) > 0
        frac = t.col("cumulative_data_fraction")
        assert frac == sorted(frac) and frac[-1] == pytest.approx(1.0)
        # capture rates sum to 1; cumulative capture ends at 1
        assert sum(t.col("capture_rate")) == pytest.approx(1.0, abs=1e-6)
        assert t.col("cumulative_capture_rate")[-1] == pytest.approx(1.0)
        # a discriminative model lifts the top group well above 1
        assert t.col("lift")[0] > 1.5
        # cumulative lift decays toward 1
        cl_ = t.col("cumulative_lift")
        assert cl_[0] >= cl_[-1] and cl_[-1] == pytest.approx(1.0, abs=1e-6)

    def test_ks_statistic(self, model):
        m, fr = model
        ks = m.kolmogorov_smirnov()
        assert 0.3 < ks <= 1.0     # strongly separable synthetic task
        # KS equals the max group-level KS within table resolution
        t = m.gains_lift()
        assert max(t.col("kolmogorov_smirnov")) <= ks + 1e-9

    def test_on_new_frame(self, model):
        m, fr = model
        t = m.gains_lift(fr)
        assert len(t) > 0


class TestTwoDimTable:
    def test_roundtrip(self):
        t = TwoDimTable("T", ["a", "b"], ["int", "double"])
        t.add_row(1, 0.5).add_row(2, 0.25)
        d = t.to_dict()
        assert d["columns"][0]["name"] == "a"
        assert d["data"] == [[1, 2], [0.5, 0.25]]
        df = t.as_data_frame()
        assert list(df["b"]) == [0.5, 0.25]


class TestAuth:
    def test_basic_auth_gate(self, cl, tmp_path):
        from h2o3_tpu import client
        from h2o3_tpu.api.server import start_server

        pw_hash = hashlib.sha256(b"secret").hexdigest()
        af = tmp_path / "realm.properties"
        af.write_text(f"# users\nalice:{pw_hash}\n")
        srv = start_server(port=0, auth_file=str(af))
        try:
            url = f"http://127.0.0.1:{srv.port}/3/Cloud"
            with pytest.raises(urllib.request.HTTPError):
                urllib.request.urlopen(url, timeout=10)
            cloud = client.connect(port=srv.port, username="alice",
                                   password="secret")
            assert cloud["cloud_healthy"]
            with pytest.raises(Exception):
                client.connect(port=srv.port, username="alice",
                               password="wrong")
        finally:
            client._AUTH = None
            srv.stop()

    def test_no_auth_by_default(self, cl):
        from h2o3_tpu import client
        from h2o3_tpu.api.server import start_server

        srv = start_server(port=0)
        try:
            assert client.connect(port=srv.port)["cloud_healthy"]
        finally:
            srv.stop()


class TestFlowLanding:
    def test_dashboard_html(self, cl):
        from h2o3_tpu.api.server import start_server

        srv = start_server(port=0)
        try:
            for path in ("/", "/flow/index.html"):
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{srv.port}{path}", timeout=10) as r:
                    body = r.read().decode()
                    assert "h2o3-tpu" in body and "/3/Cloud" in body
        finally:
            srv.stop()
