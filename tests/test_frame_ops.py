"""H2OFrame munging surface tests (h2o-py frame.py semantics subset)."""

import numpy as np
import pytest


@pytest.fixture()
def fr(cl):
    from h2o3_tpu import H2OFrame

    return H2OFrame({
        "a": [1.0, 2.0, 3.0, 4.0, 5.0],
        "b": [10.0, 20.0, np.nan, 40.0, 50.0],
        "c": ["x", "y", "x", "z", "y"],
    }, column_types={"c": "enum"})


def test_arith(cl, fr):
    out = fr["a"] + 5
    np.testing.assert_allclose(out.col(0).to_numpy(), [6, 7, 8, 9, 10])
    out = fr["a"] * fr["a"]
    np.testing.assert_allclose(out.col(0).to_numpy(), [1, 4, 9, 16, 25])
    out = 2 / fr["a"]
    np.testing.assert_allclose(out.col(0).to_numpy(), [2, 1, 2 / 3, 0.5, 0.4], rtol=1e-6)


def test_compare_and_filter(cl, fr):
    mask = fr["a"] > 2
    np.testing.assert_allclose(mask.col(0).to_numpy(), [0, 0, 1, 1, 1])
    sub = fr[mask]
    assert sub.nrows == 3
    np.testing.assert_allclose(sub.col("a").to_numpy(), [3, 4, 5])
    # enum column survives filtering with domain intact
    assert sub.col("c").domain == ["x", "y", "z"]
    assert list(sub.col("c").values()) == ["x", "z", "y"]


def test_na_propagation(cl, fr):
    out = fr["b"] + 1
    v = out.col(0).to_numpy()
    assert np.isnan(v[2])
    np.testing.assert_allclose(v[[0, 1, 3, 4]], [11, 21, 41, 51])
    assert int(fr["b"].isna().col(0).to_numpy().sum()) == 1


def test_reductions(cl, fr):
    assert fr["a"].mean() == 3.0
    assert fr["a"].min() == 1.0
    assert fr["a"].max() == 5.0
    assert fr["a"].sum() == 15.0
    np.testing.assert_allclose(fr["b"].mean(), 30.0)


def test_slicing(cl, fr):
    h = fr.head(2)
    assert h.nrows == 2
    t = fr.tail(2)
    np.testing.assert_allclose(t.col("a").to_numpy(), [4, 5])
    two = fr[["a", "c"]]
    assert two.names == ["a", "c"]


def test_split_frame(cl):
    from h2o3_tpu import H2OFrame

    fr = H2OFrame({"x": np.arange(1000.0)})
    tr, te = fr.split_frame(ratios=[0.8], seed=7)
    assert tr.nrows + te.nrows == 1000
    assert 700 < tr.nrows < 900
    # no overlap
    s1 = set(tr.col(0).to_numpy().tolist())
    s2 = set(te.col(0).to_numpy().tolist())
    assert not (s1 & s2)


def test_asfactor_levels(cl):
    from h2o3_tpu import H2OFrame

    fr = H2OFrame({"g": [1.0, 2.0, 1.0, 3.0]})
    f = fr["g"].asfactor()
    assert f.col(0).is_categorical
    assert f.nlevels() == [3]


def test_ifelse(cl, fr):
    out = (fr["a"] > 3).ifelse(1.0, 0.0)
    np.testing.assert_allclose(out.col(0).to_numpy(), [0, 0, 0, 1, 1])


def test_cbind_rbind(cl, fr):
    wide = fr.cbind(fr[["a"]])
    assert wide.ncols == 4
    tall = fr.rbind(fr)
    assert tall.nrows == 10
    assert tall.col("c").domain == ["x", "y", "z"]


def test_quantile_median(cl):
    from h2o3_tpu import H2OFrame

    rng = np.random.default_rng(3)
    v = rng.normal(size=5000)
    fr = H2OFrame({"x": v})
    med = fr["x"].median()
    assert abs(med - np.median(v)) < 1e-3
    q = fr["x"].quantile(prob=[0.25, 0.75])
    got = q.col("xQuantiles").to_numpy()
    np.testing.assert_allclose(got, np.quantile(v, [0.25, 0.75]), atol=2e-3)


def test_groupby(cl, fr):
    g = fr.group_by("c").count().sum("a").mean("a").get_frame()
    rows = {v: (cnt, s, m) for v, cnt, s, m in zip(
        g.col("c").values(), g.col("nrow").to_numpy(),
        g.col("sum_a").to_numpy(), g.col("mean_a").to_numpy())}
    assert rows["x"] == (2, 4.0, 2.0)
    assert rows["y"] == (2, 7.0, 3.5)
    assert rows["z"] == (1, 4.0, 4.0)


def test_sort(cl, fr):
    s = fr.sort("a", ascending=False)
    np.testing.assert_allclose(s.col("a").to_numpy(), [5, 4, 3, 2, 1])
    assert list(s.col("c").values()) == ["y", "z", "x", "y", "x"]


def test_merge(cl):
    from h2o3_tpu import H2OFrame

    left = H2OFrame({"k": ["a", "b", "c"], "v": [1.0, 2.0, 3.0]}, column_types={"k": "enum"})
    right = H2OFrame({"k": ["b", "c", "d"], "w": [20.0, 30.0, 40.0]}, column_types={"k": "enum"})
    m = left.merge(right)
    assert m.nrows == 2
    ks = list(m.col("k").values())
    assert sorted(ks) == ["b", "c"]


def test_impute(cl, fr):
    fr.impute("b", method="mean")
    v = fr.col("b").to_numpy()
    np.testing.assert_allclose(v[2], 30.0)


def test_create_frame(cl):
    from h2o3_tpu import create_frame

    fr = create_frame(rows=100, cols=6, categorical_fraction=0.3, real_fraction=0.5,
                      missing_fraction=0.05, seed=1, has_response=True)
    assert fr.nrows == 100
    assert fr.ncols >= 6
    assert any(fr.col(n).is_categorical for n in fr.names)
