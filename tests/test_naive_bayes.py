"""NaiveBayes tests vs sklearn GaussianNB/CategoricalNB oracles."""

import numpy as np
import pytest

from h2o3_tpu.core.frame import Column, Frame, T_CAT


def test_nb_gaussian_matches_sklearn(cl):
    from sklearn.naive_bayes import GaussianNB

    from h2o3_tpu.models.naive_bayes import NaiveBayes

    rng = np.random.default_rng(0)
    n = 2000
    y = rng.integers(0, 3, n)
    X = rng.normal(size=(n, 4)) + y[:, None] * np.array([1.0, -1.0, 0.5, 0.0])
    fr = Frame.from_numpy(X, names=["a", "b", "c", "d"])
    fr.add("y", Column.from_numpy(np.array([f"c{v}" for v in y]), ctype=T_CAT))

    m = NaiveBayes().train(y="y", training_frame=fr)
    probs = np.column_stack([m.predict(fr).col(f"c{j}").to_numpy() for j in range(3)])

    sk = GaussianNB().fit(X, y)
    sk_probs = sk.predict_proba(X)
    assert (np.argmax(probs, 1) == np.argmax(sk_probs, 1)).mean() > 0.99
    assert np.abs(probs - sk_probs).max() < 0.05
    mm = m._output.training_metrics
    assert mm.logloss < 1.0


def test_nb_categorical_laplace(cl):
    from h2o3_tpu.models.naive_bayes import NaiveBayes

    rng = np.random.default_rng(1)
    n = 3000
    y = rng.integers(0, 2, n)
    # categorical predictor correlated with y
    x = np.where(rng.random(n) < 0.8, y, 1 - y)
    fr = Frame()
    fr.add("x", Column.from_numpy(np.array(["lo", "hi"])[x], ctype=T_CAT))
    fr.add("y", Column.from_numpy(np.array(["n", "p"])[y], ctype=T_CAT))
    m = NaiveBayes(laplace=1.0).train(y="y", training_frame=fr)
    assert m._output.training_metrics.auc > 0.75
    # P(x=hi | y=p) ≈ 0.8 with laplace pull toward 0.5
    t = m.cat_tables[0]
    assert abs(t[1, np.argmax(t[1])] - 0.8) < 0.05


def test_nb_handles_nas(cl):
    from h2o3_tpu.models.naive_bayes import NaiveBayes

    rng = np.random.default_rng(2)
    n = 1000
    y = rng.integers(0, 2, n)
    x = y + rng.normal(0, 0.5, n)
    x[::7] = np.nan
    fr = Frame.from_numpy(x.reshape(-1, 1), names=["x"])
    fr.add("y", Column.from_numpy(np.array(["a", "b"])[y], ctype=T_CAT))
    m = NaiveBayes().train(y="y", training_frame=fr)
    assert m._output.training_metrics.auc > 0.8
