"""Sharded data plane (ISSUE 7): per-process feature packing over
addressable row shards + shard_map fused scoring.

Run on the conftest's virtual 8-device CPU mesh (single process, ≥2
devices — the proof platform the issue names; gloo 2-process clouds abort
in this environment). Covers:

- ShardedFrame packing is bitwise-identical to the host-packed matrix and
  keeps the named-row-axis sharding (no coordinator column staging).
- Sharded fused predictions are bitwise-identical to the host-packed path
  AND the generic predict path, including chunked (> max bucket) requests
  and multinomial forests.
- data-plane counters: packed_rows covers every sharded-path row,
  gathered_rows stays 0 on the sharded path and increments only on the
  host-gather fallbacks; surfaced on GET /3/ScoringMetrics.
- degraded-mode serving (satellite): coordinator-addressable sharded
  frames SERVE under local_only on a simulated multi-process cloud; the
  two ShardUnavailableError sites (non-addressable frame columns,
  non-addressable forest arrays) stay the exceptional path.
"""

import numpy as np
import pytest

from h2o3_tpu.core.frame import Column, Frame

pytestmark = pytest.mark.sharded


def _train_frame(n=1500, seed=0, classes=2):
    rng = np.random.default_rng(seed)
    fr = Frame()
    x1 = rng.standard_normal(n)
    x2 = rng.standard_normal(n)
    x1[::11] = np.nan
    g = np.array(["a", "b", "c"])[rng.integers(0, 3, n)]
    fr.add("x1", Column.from_numpy(x1))
    fr.add("x2", Column.from_numpy(x2))
    fr.add("g", Column.from_numpy(g, ctype="enum"))
    logit = np.where(np.isnan(x1), 0.0, 1.2 * x1) - x2 + (g == "a") * 0.5
    if classes == 2:
        y = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "Y", "N")
    else:
        y = np.array(["r", "s", "t"])[
            np.clip((logit + 1.5).astype(int), 0, classes - 1)]
    fr.add("y", Column.from_numpy(y, ctype="enum"))
    return fr


def _score_frame(n, seed, with_nas=True, unseen=False):
    rng = np.random.default_rng(seed)
    fr = Frame()
    x1 = rng.standard_normal(n)
    if with_nas:
        x1[::7] = np.nan
    fr.add("x1", Column.from_numpy(x1))
    fr.add("x2", Column.from_numpy(rng.standard_normal(n)))
    dom = ["a", "b", "c", "zz"] if unseen else ["a", "b", "c"]
    fr.add("g", Column.from_numpy(
        np.array(dom)[rng.integers(0, len(dom), n)], ctype="enum"))
    return fr


@pytest.fixture(scope="module")
def gbm(cl):
    from h2o3_tpu.models.tree.gbm import GBM

    return GBM(ntrees=6, max_depth=3, seed=1).train(
        y="y", training_frame=_train_frame())


@pytest.fixture(scope="module")
def gbm3(cl):
    from h2o3_tpu.models.tree.gbm import GBM

    return GBM(ntrees=4, max_depth=3, seed=2).train(
        y="y", training_frame=_train_frame(seed=3, classes=3))


def _counters():
    from h2o3_tpu.core import sharded_frame

    return sharded_frame.counters()


def _assert_frames_bitwise(a, b, n):
    assert a.names == b.names
    for name in a.names:
        av = np.asarray(a.col(name).data)[:n]
        bv = np.asarray(b.col(name).data)[:n]
        assert np.array_equal(av, bv, equal_nan=True), name


class TestShardedView:
    def test_view_holds_and_names_row_axis(self, cl, gbm):
        fr = _score_frame(300, 4)
        sf = fr.sharded_view()
        assert sf is not None
        assert sf.row_axis == "rows"
        assert sf.padded_rows % cl.row_shards == 0
        from jax.sharding import NamedSharding

        assert isinstance(sf.row_sharding(), NamedSharding)

    def test_view_refuses_host_resident_columns(self, cl):
        fr = Frame()
        fr.add("s", Column.from_numpy(np.array(["u", "v", "w"], object)))
        assert fr.sharded_view() is None

    def test_view_respects_plane_switch(self, cl, monkeypatch):
        fr = _score_frame(100, 5)
        monkeypatch.setenv("H2O_TPU_SHARDED_PLANE", "0")
        assert fr.sharded_view() is None
        monkeypatch.delenv("H2O_TPU_SHARDED_PLANE")
        assert fr.sharded_view() is not None

    def test_dkv_resolved_view(self, cl):
        from h2o3_tpu.core.sharded_frame import ShardedFrame

        fr = _score_frame(64, 6)
        fr._key = type(fr._key)("sharded_view_dkv.hex")
        fr.install()
        try:
            sf = ShardedFrame.for_key("sharded_view_dkv.hex")
            assert sf is not None and sf.frame is fr
            assert ShardedFrame.for_key("never_installed.hex") is None
        finally:
            fr.delete()

    def test_pack_features_matches_host_matrix(self, cl, gbm):
        from h2o3_tpu import scoring

        fr = _score_frame(333, 7, unseen=True)
        sess = scoring.ScoringSession(gbm)
        adapted = gbm.adapt_test(fr)
        sf = sess._sharded_view(adapted)
        assert sf is not None
        bucket = sess._bucket_for(fr.nrows)
        Xd = np.asarray(sf.pack_features(0, fr.nrows, bucket))
        Xh = sess._features(adapted, fr.nrows)
        assert np.array_equal(Xd[: fr.nrows], Xh, equal_nan=True)
        assert not np.isnan(Xd[fr.nrows:]).any()
        assert (Xd[fr.nrows:] == 0).all()      # zero pad, like the host path


class TestBinnedPack:
    def test_binned_pack_matches_legacy_and_stays_sharded(self, cl, gbm,
                                                          monkeypatch):
        fr = _score_frame(500, 8)
        adapted = gbm.adapt_test(fr)
        binned_sharded = gbm.spec.bin_columns(adapted)
        from jax.sharding import NamedSharding

        assert isinstance(binned_sharded.sharding, NamedSharding)
        spec_names = {ax for ax in (binned_sharded.sharding.spec or ())
                      if ax is not None}
        assert "rows" in spec_names
        monkeypatch.setenv("H2O_TPU_SHARDED_PLANE", "0")
        binned_legacy = gbm.spec.bin_columns(adapted)
        assert np.array_equal(np.asarray(binned_sharded),
                              np.asarray(binned_legacy))
        assert binned_sharded.dtype == binned_legacy.dtype

    def test_training_counts_packed_rows(self, cl):
        from h2o3_tpu.models.tree.gbm import GBM

        before = _counters()
        GBM(ntrees=2, max_depth=2, seed=9).train(
            y="y", training_frame=_train_frame(n=400, seed=10))
        after = _counters()
        assert after["packed_rows"] > before["packed_rows"]
        assert after["gathered_rows"] == before["gathered_rows"]


class TestShardedScoring:
    def _ab(self, model, fr, monkeypatch=None, buckets=None):
        """Score `fr` through the sharded plane and the host-packed path
        (plane off) with fresh sessions; return both prediction frames."""
        import os

        from h2o3_tpu import scoring

        if buckets:
            os.environ["H2O_TPU_SCORE_BUCKETS"] = buckets
        try:
            pred_s = scoring.ScoringSession(model).predict(fr)
            os.environ["H2O_TPU_SHARDED_PLANE"] = "0"
            try:
                pred_h = scoring.ScoringSession(model).predict(fr)
            finally:
                del os.environ["H2O_TPU_SHARDED_PLANE"]
        finally:
            if buckets:
                del os.environ["H2O_TPU_SCORE_BUCKETS"]
        return pred_s, pred_h

    def test_binomial_bitwise_vs_host_path(self, cl, gbm):
        fr = _score_frame(777, 11, unseen=True)
        before = _counters()
        pred_s, pred_h = self._ab(gbm, fr)
        after = _counters()
        _assert_frames_bitwise(pred_s, pred_h, fr.nrows)
        # sharded run packed its rows without a gather; the host-path
        # run is the one that gathered
        assert after["packed_rows"] - before["packed_rows"] == fr.nrows
        assert after["gathered_rows"] - before["gathered_rows"] == fr.nrows

    def test_binomial_bitwise_vs_generic_path(self, cl, gbm):
        from h2o3_tpu import scoring

        fr = _score_frame(420, 12)
        pred_s = scoring.ScoringSession(gbm).predict(fr)
        pred_g = gbm.predict(fr)
        for name in pred_s.names:
            assert np.array_equal(
                np.asarray(pred_s.col(name).data)[: fr.nrows],
                np.asarray(pred_g.col(name).data)[: fr.nrows],
                equal_nan=True), name

    def test_multinomial_bitwise(self, cl, gbm3):
        fr = _score_frame(513, 13)
        pred_s, pred_h = self._ab(gbm3, fr)
        _assert_frames_bitwise(pred_s, pred_h, fr.nrows)

    def test_chunked_request_bitwise(self, cl, gbm):
        """Requests above the largest bucket chunk at it on BOTH paths;
        the sharded assembly (concat + reshard) stays bitwise."""
        fr = _score_frame(1000, 14)
        pred_s, pred_h = self._ab(gbm, fr, buckets="256")
        _assert_frames_bitwise(pred_s, pred_h, fr.nrows)

    def test_compiles_bounded_by_buckets(self, cl, gbm):
        from h2o3_tpu import scoring

        sess = scoring.ScoringSession(gbm)
        for n, seed in ((100, 20), (300, 21), (900, 22), (1100, 23),
                        (140, 24)):
            sess.predict(_score_frame(n, seed))
        assert sess.traversal_compiles <= len(sess.buckets)

    def test_batch_mixes_sharded_and_fallback_entries(self, cl, gbm):
        """One coalesced batch where an entry is sharded-eligible and
        another carries a padded layout the view refuses — results stay
        per-entry correct and in order."""
        from h2o3_tpu import scoring

        fr_ok = _score_frame(200, 25)
        fr_ragged = _score_frame(150, 26)
        fr_clean = _score_frame(150, 26)     # same values, legal layout
        # forcing one column's padded length out of agreement makes the
        # view refuse (ragged layout) without touching the logical values
        import jax.numpy as jnp

        c = fr_ragged.col("x2")
        longer = jnp.pad(c.data, (0, cl.pad_rows(c.data.shape[0] + 1)
                                  - c.data.shape[0]), constant_values=np.nan)
        c.data = longer
        assert fr_ragged.sharded_view() is None
        sess = scoring.ScoringSession(gbm)
        before = _counters()
        out = sess.predict_batch([(fr_ok, None, False),
                                  (fr_ragged, None, False)])
        after = _counters()
        assert len(out) == 2
        # first entry packed shard-locally; the ragged one fell back to
        # the host-gather path
        assert after["packed_rows"] - before["packed_rows"] == fr_ok.nrows
        assert after["gathered_rows"] - before["gathered_rows"] == \
            fr_ragged.nrows
        for fr, ref_fr, (pred, _mm) in zip(
                (fr_ok, fr_ragged), (fr_ok, fr_clean), out):
            ref = gbm.predict(ref_fr)
            for name in ref.names:
                assert np.array_equal(
                    np.asarray(pred.col(name).data)[: fr.nrows],
                    np.asarray(ref.col(name).data)[: fr.nrows],
                    equal_nan=True), name


class _NonAddressable:
    """Stand-in for a device array whose shards live on a dead peer."""

    is_fully_addressable = False
    shape = (64,)

    @property
    def sharding(self):            # _shard_owners introspection: best-effort
        raise RuntimeError("no sharding: peer is gone")


class TestDegradedServing:
    """Satellite: degraded-mode serving on sharded frames. Addressable
    shards SERVE; the two ShardUnavailableError sites in scoring.py are
    the exceptional path (one test per branch)."""

    def test_local_only_serves_addressable_sharded_frame(self, cl, gbm,
                                                         monkeypatch):
        """Simulated multi-process degraded cloud (process_count > 1,
        local_only): a frame whose shards are all coordinator-addressable
        must serve — via the host-packed LOCAL dispatch, never the global
        mesh — with predictions bitwise-identical to the healthy path."""
        import jax

        from h2o3_tpu import scoring

        fr = _score_frame(210, 30)
        healthy = scoring.ScoringSession(gbm).predict(fr)
        sess = scoring.ScoringSession(gbm)
        before = _counters()
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        try:
            (pred, _mm), = sess.predict_batch([(fr, None, False)],
                                              local_only=True)
        finally:
            monkeypatch.undo()
        after = _counters()
        _assert_frames_bitwise(pred, healthy, fr.nrows)
        # degraded-local serving is the documented host-gather fallback
        assert after["gathered_rows"] - before["gathered_rows"] == fr.nrows

    def test_local_only_unaddressable_frame_raises(self, cl, gbm,
                                                   monkeypatch):
        """scoring.predict_batch's frame-shard check: a column homed on a
        dead peer refuses with ShardUnavailableError (503 surface)."""
        import jax

        from h2o3_tpu import scoring
        from h2o3_tpu.core.failure import ShardUnavailableError

        fr = _score_frame(100, 31)
        fr.col("x2")._data = _NonAddressable()
        sess = scoring.ScoringSession(gbm)
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        with pytest.raises(ShardUnavailableError) as ei:
            sess.predict_batch([(fr, None, False)], local_only=True)
        assert "x2" in str(ei.value)

    def test_local_only_unaddressable_forest_raises(self, cl, gbm,
                                                    monkeypatch):
        """scoring._local_arrays' forest-shard check: model arrays laid
        out over the global mesh with a dead owner refuse with
        ShardUnavailableError instead of entering a doomed collective."""
        from h2o3_tpu import scoring
        from h2o3_tpu.core.failure import ShardUnavailableError

        sess = scoring.ScoringSession(gbm)
        sess._arrays = (_NonAddressable(),) + tuple(sess._arrays[1:])
        sess._local_cache = None
        with pytest.raises(ShardUnavailableError):
            sess._local_arrays()


class TestScoringMetricsRest:
    def test_data_plane_counters_on_rest(self, cl, gbm):
        """GET /3/ScoringMetrics carries the per-process data_plane block;
        after a REST-scored sharded request, gathered_rows has not moved
        and packed_rows covers the scored frame (the issue's counter
        assertion, over the real wire).

        ISSUE-8 extension, same request: (a) the response's trace id
        resolves on GET /3/Trace/{id} to the COMPLETE fused-path span
        tree — ingress -> queue_wait -> pack -> dispatch -> fetch — and
        the unchanged gathered_rows / fused-compile counters are the
        proof that tracing added no device sync or path change; (b)
        GET /3/Metrics serves the cluster-aggregated
        h2o3_data_plane_* series in Prometheus text exposition with the
        same values the data_plane block reports."""
        import json
        import re
        import urllib.request

        from h2o3_tpu import scoring
        from h2o3_tpu.api.server import start_server
        from h2o3_tpu.core import sharded_frame

        fr = _score_frame(160, 32)
        fr._key = type(fr._key)("sharded_metrics.hex")
        fr.install()
        srv = start_server(port=0)
        try:
            base = f"http://127.0.0.1:{srv.port}"
            # warm the session so the traced request compiles nothing (the
            # no-new-compiles assertion below needs a warm bucket)
            scoring.session_for(gbm).predict(fr)
            compiles0 = scoring.session_for(gbm).fused_compiles
            before = sharded_frame.counters()
            req = urllib.request.Request(
                base + f"/3/Predictions/models/{gbm.key}/frames/"
                f"{fr.key}?predictions_frame=sharded_metrics_pred",
                data=b"", method="POST")
            with urllib.request.urlopen(req, timeout=120) as r:
                trace_id = r.headers.get("X-H2O3-Trace-Id")
                json.loads(r.read())
            with urllib.request.urlopen(base + "/3/ScoringMetrics",
                                        timeout=30) as r:
                sm = json.loads(r.read())
            dp = sm["data_plane"]
            assert dp["gathered_rows"] == before["gathered_rows"]
            assert dp["packed_rows"] >= before["packed_rows"] + fr.nrows
            # -- span tree (ISSUE 8 acceptance): complete fused-path
            #    phases, and zero new fused compiles / gathers while
            #    traced (tracing must not change the dispatch path)
            assert trace_id
            assert scoring.session_for(gbm).fused_compiles == compiles0
            with urllib.request.urlopen(base + f"/3/Trace/{trace_id}",
                                        timeout=30) as r:
                tr = json.loads(r.read())
            names = {s["name"] for s in tr["spans"]}
            assert {"ingress", "queue_wait", "pack", "dispatch",
                    "fetch"} <= names, names
            roots = tr["tree"]
            assert roots[0]["name"] == "ingress"
            child_names = {c["name"] for c in roots[0]["children"]}
            assert {"queue_wait", "pack", "dispatch",
                    "fetch"} <= child_names
            # -- cluster /3/Metrics agrees with the data_plane block
            with urllib.request.urlopen(base + "/3/Metrics",
                                        timeout=30) as r:
                text = r.read().decode()
            m = re.search(r"^h2o3_data_plane_packed_rows_total (\S+)$",
                          text, re.M)
            assert m and float(m.group(1)) == dp["packed_rows"]
            m = re.search(r"^h2o3_data_plane_gathered_rows_total (\S+)$",
                          text, re.M)
            assert m and float(m.group(1)) == dp["gathered_rows"]
            series = {ln.split("{")[0].split(" ")[0]
                      for ln in text.splitlines()
                      if ln.strip() and not ln.startswith("#")}
            assert len(series) >= 20
        finally:
            srv.stop()
            fr.delete()
