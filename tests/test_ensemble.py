"""StackedEnsemble tests (reference pyunits testdir_algos/stackedensemble)."""

import numpy as np
import pytest

from h2o3_tpu.core.frame import Column, Frame, T_CAT


def _data(n=3000, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 4))
    logit = 1.5 * X[:, 0] - X[:, 1] + 0.5 * X[:, 2] * X[:, 3]
    y = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "Y", "N")
    fr = Frame.from_numpy(X, names=["a", "b", "c", "d"])
    fr.add("y", Column.from_numpy(y, ctype=T_CAT))
    return fr


def test_stacked_ensemble_binomial(cl):
    from h2o3_tpu.models.ensemble import StackedEnsemble
    from h2o3_tpu.models.glm import GLM
    from h2o3_tpu.models.tree.gbm import GBM

    fr = _data()
    gbm = GBM(ntrees=20, max_depth=3, nfolds=3, seed=1,
              keep_cross_validation_predictions=True).train(y="y", training_frame=fr)
    glm = GLM(family="binomial", nfolds=3, seed=1,
              keep_cross_validation_predictions=True).train(y="y", training_frame=fr)
    se = StackedEnsemble(base_models=[gbm, glm], seed=1).train(
        y="y", training_frame=fr)
    auc_se = se._output.training_metrics.auc
    assert auc_se > 0.80
    # ensemble should roughly match or beat the best base CV AUC
    base_cv = max(gbm._output.cross_validation_metrics.auc,
                  glm._output.cross_validation_metrics.auc)
    assert auc_se > base_cv - 0.02
    pred = se.predict(fr)
    assert set(pred.names) == {"predict", "N", "Y"}


def test_stacked_ensemble_requires_cv_preds(cl):
    from h2o3_tpu.models.ensemble import StackedEnsemble
    from h2o3_tpu.models.tree.gbm import GBM

    fr = _data(n=800, seed=1)
    gbm = GBM(ntrees=5, max_depth=3, seed=1).train(y="y", training_frame=fr)
    with pytest.raises(ValueError, match="cross-validation"):
        StackedEnsemble(base_models=[gbm]).train(y="y", training_frame=fr)


def test_stacked_ensemble_regression(cl):
    from h2o3_tpu.models.ensemble import StackedEnsemble
    from h2o3_tpu.models.glm import GLM
    from h2o3_tpu.models.tree.drf import DRF

    rng = np.random.default_rng(2)
    X = rng.normal(size=(2000, 3))
    y = 2 * X[:, 0] + np.sin(3 * X[:, 1]) + 0.1 * rng.normal(size=2000)
    fr = Frame.from_numpy(np.column_stack([X, y]), names=["a", "b", "c", "y"])
    drf = DRF(ntrees=20, nfolds=3, seed=3,
              keep_cross_validation_predictions=True).train(y="y", training_frame=fr)
    glm = GLM(family="gaussian", nfolds=3, seed=3,
              keep_cross_validation_predictions=True).train(y="y", training_frame=fr)
    se = StackedEnsemble(base_models=[drf, glm], seed=3).train(
        y="y", training_frame=fr)
    assert se._output.training_metrics.r2 > 0.85
