"""Grid search tests (reference: hex/grid pyunits)."""

import numpy as np
import pytest

from h2o3_tpu.core.frame import Column, Frame, T_CAT
from h2o3_tpu.grid import H2OGridSearch


def _data(n=1500, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 3))
    logit = 1.5 * X[:, 0] - X[:, 1]
    y = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "Y", "N")
    fr = Frame.from_numpy(X, names=["a", "b", "c"])
    fr.add("y", Column.from_numpy(y, ctype=T_CAT))
    return fr


def test_cartesian_grid(cl):
    from h2o3_tpu.models.tree.gbm import GBM

    fr = _data()
    g = H2OGridSearch(GBM, {"max_depth": [2, 4], "learn_rate": [0.05, 0.3]},
                      search_criteria={"strategy": "Cartesian"})
    g.train(y="y", training_frame=fr, ntrees=10, seed=1)
    assert len(g) == 4
    table = g.sorted_metric_table("auc")
    assert table[0]["auc"] >= table[-1]["auc"]
    best = g.best_model("auc")
    assert best._output.training_metrics.auc >= 0.8


def test_random_discrete_budget(cl):
    from h2o3_tpu.models.glm import GLM

    fr = _data(n=800, seed=1)
    g = H2OGridSearch(GLM, {"alpha": [0.0, 0.5, 1.0],
                            "lambda_": [0.0, 0.001, 0.01, 0.1]},
                      search_criteria={"strategy": "RandomDiscrete",
                                       "max_models": 5, "seed": 42})
    g.train(y="y", training_frame=fr, family="binomial")
    assert len(g) == 5


def test_grid_survives_failures(cl):
    from h2o3_tpu.models.glm import GLM

    fr = _data(n=500, seed=2)
    g = H2OGridSearch(GLM, {"family": ["binomial", "nosuchfamily"]})
    g.train(y="y", training_frame=fr)
    assert len(g) == 1
    assert len(g.failed) == 1


def test_parallel_grid(cl):
    """GridSearch.java parallelism: k concurrent builds produce the same
    model set as the sequential walk."""
    from h2o3_tpu.models.glm import GLM

    fr = _data(n=600, seed=2)
    hp = {"alpha": [0.0, 0.5, 1.0], "lambda_": [0.0, 0.01]}
    seq = H2OGridSearch(GLM, hp).train(y="y", training_frame=fr, seed=1)
    par = H2OGridSearch(GLM, hp).train(y="y", training_frame=fr, seed=1,
                                       parallelism=3)
    assert len(par) == len(seq) == 6
    def combos(g):
        return sorted(str(sorted(m._grid_params.items())) for m in g.models)
    assert combos(par) == combos(seq)
    # same ranking metric values regardless of build order
    sa = sorted(round(r["auc"], 6) for r in seq.sorted_metric_table("auc"))
    pa = sorted(round(r["auc"], 6) for r in par.sorted_metric_table("auc"))
    assert sa == pa


def test_grid_kill_and_resume(cl, tmp_path):
    """Grid auto-recovery (hex/grid Grid.exportBinary + resume): persist
    per-model, 'crash' mid-walk, load from disk, finish the remaining
    combos only."""
    from h2o3_tpu.core.dkv import DKV
    from h2o3_tpu.models.tree.gbm import GBM

    fr = _data(n=600, seed=3)
    rec = str(tmp_path / "grid_rec")
    hp = {"max_depth": [2, 3], "learn_rate": [0.1, 0.3]}
    g = H2OGridSearch(GBM, hp, grid_id="resume_grid",
                      search_criteria={"max_models": 2})
    g.train(y="y", training_frame=fr, ntrees=3, seed=1, recovery_dir=rec)
    assert len(g) == 2                      # budget stopped the walk early
    trained_first = {str(m.key) for m in g.models}

    # simulate process death: wipe the in-memory grid + its models
    for m in g.models:
        DKV.remove(str(m.key))
    DKV.remove("resume_grid")

    g2 = H2OGridSearch.load(rec)
    assert len(g2) == 2                     # models restored from disk
    assert {str(m.key) for m in g2.models} == trained_first
    g2.search_criteria["max_models"] = 0    # lift the cap, finish the walk
    g2.train(y="y", training_frame=fr, ntrees=3, seed=1, recovery_dir=rec)
    assert len(g2) == 4
    done = {str(sorted(m._grid_params.items())) for m in g2.models}
    assert len(done) == 4                   # no combo trained twice
    # restored models score (full model round-trip, not just metadata)
    best = g2.best_model("auc")
    preds = best.predict(fr)
    assert preds.nrows == fr.nrows


def test_parallel_grid_honors_max_models(cl):
    """max_models counts in-flight builds: parallelism must not overshoot
    the budget the way a submit-then-check loop would."""
    from h2o3_tpu.models.glm import GLM

    fr = _data(n=400, seed=4)
    g = H2OGridSearch(GLM, {"alpha": [0.0, 0.25, 0.5, 1.0]},
                      search_criteria={"max_models": 1})
    g.train(y="y", training_frame=fr, seed=1, parallelism=4)
    assert len(g) == 1
