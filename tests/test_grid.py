"""Grid search tests (reference: hex/grid pyunits)."""

import numpy as np
import pytest

from h2o3_tpu.core.frame import Column, Frame, T_CAT
from h2o3_tpu.grid import H2OGridSearch


def _data(n=1500, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 3))
    logit = 1.5 * X[:, 0] - X[:, 1]
    y = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "Y", "N")
    fr = Frame.from_numpy(X, names=["a", "b", "c"])
    fr.add("y", Column.from_numpy(y, ctype=T_CAT))
    return fr


def test_cartesian_grid(cl):
    from h2o3_tpu.models.tree.gbm import GBM

    fr = _data()
    g = H2OGridSearch(GBM, {"max_depth": [2, 4], "learn_rate": [0.05, 0.3]},
                      search_criteria={"strategy": "Cartesian"})
    g.train(y="y", training_frame=fr, ntrees=10, seed=1)
    assert len(g) == 4
    table = g.sorted_metric_table("auc")
    assert table[0]["auc"] >= table[-1]["auc"]
    best = g.best_model("auc")
    assert best._output.training_metrics.auc >= 0.8


def test_random_discrete_budget(cl):
    from h2o3_tpu.models.glm import GLM

    fr = _data(n=800, seed=1)
    g = H2OGridSearch(GLM, {"alpha": [0.0, 0.5, 1.0],
                            "lambda_": [0.0, 0.001, 0.01, 0.1]},
                      search_criteria={"strategy": "RandomDiscrete",
                                       "max_models": 5, "seed": 42})
    g.train(y="y", training_frame=fr, family="binomial")
    assert len(g) == 5


def test_grid_survives_failures(cl):
    from h2o3_tpu.models.glm import GLM

    fr = _data(n=500, seed=2)
    g = H2OGridSearch(GLM, {"family": ["binomial", "nosuchfamily"]})
    g.train(y="y", training_frame=fr)
    assert len(g) == 1
    assert len(g.failed) == 1
