"""Micro-batcher for /3/Predictions (scoring.ScoreBatcher).

Concurrent requests against the same model coalesce into one dispatch and
get their exact per-request slices back; requests against different models
ride independent queues. The REST fast path returns the same payload shape
(and bitwise-identical frames) as the legacy per-request route."""

import threading
import time

import numpy as np
import pytest

from h2o3_tpu.core.frame import Column, Frame


def _train_frame(n=1200, seed=0):
    rng = np.random.default_rng(seed)
    fr = Frame()
    x1 = rng.standard_normal(n)
    x2 = rng.standard_normal(n)
    fr.add("x1", Column.from_numpy(x1))
    fr.add("x2", Column.from_numpy(x2))
    y = np.where(rng.random(n) < 1 / (1 + np.exp(-(1.2 * x1 - x2))),
                 "Y", "N")
    fr.add("y", Column.from_numpy(y, ctype="enum"))
    return fr


def _score_frame(n, seed):
    rng = np.random.default_rng(seed)
    fr = Frame()
    fr.add("x1", Column.from_numpy(rng.standard_normal(n)))
    fr.add("x2", Column.from_numpy(rng.standard_normal(n)))
    return fr


@pytest.fixture(scope="module")
def gbm(cl):
    from h2o3_tpu.models.tree.gbm import GBM

    return GBM(ntrees=6, max_depth=3, seed=1).train(
        y="y", training_frame=_train_frame())


@pytest.fixture(scope="module")
def gbm2(cl):
    from h2o3_tpu.models.tree.gbm import GBM

    return GBM(ntrees=4, max_depth=2, seed=2).train(
        y="y", training_frame=_train_frame(seed=5))


def _assert_frames_bitwise(a, b, n):
    assert a.names == b.names
    for name in a.names:
        av = np.asarray(a.col(name).data)[:n]
        bv = np.asarray(b.col(name).data)[:n]
        assert np.array_equal(av, bv), name


def _concurrent_scores(model, frames, n_threads=None):
    """Submit every frame from its own thread through the micro-batcher;
    returns predictions in frame order (raises the first worker error)."""
    from h2o3_tpu import scoring

    n_threads = n_threads or len(frames)
    results = [None] * len(frames)
    errors = []
    barrier = threading.Barrier(n_threads)

    def worker(i):
        try:
            barrier.wait(timeout=30)
            pred, _mm = scoring.score_request(model, frames[i])
            results[i] = pred
        except BaseException as e:   # noqa: BLE001 — surfaced below
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(len(frames))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    if errors:
        raise errors[0]
    return results


class TestCoalescing:
    def test_concurrent_same_model_exact_slices(self, cl, gbm, monkeypatch):
        """Concurrent requests coalesce into fewer dispatches, and every
        request gets back exactly its own rows."""
        from h2o3_tpu import scoring

        sizes = (50, 120, 77, 333)
        frames = [_score_frame(s, s) for s in sizes]
        expected = [gbm.predict(fr) for fr in frames]
        # wide window so barrier-released threads land in ONE batch
        monkeypatch.setenv("H2O_TPU_SCORE_BATCH_WINDOW_MS", "250")
        scoring.purge(str(gbm.key))         # fresh stats
        sess = scoring.session_for(gbm)
        preds = _concurrent_scores(gbm, frames)
        for fr, exp, got in zip(frames, expected, preds):
            _assert_frames_bitwise(exp, got, fr.nrows)
        stats = sess.stats.snapshot()
        assert stats["requests"] == len(frames)
        assert stats["max_batch_requests"] >= 2, stats   # coalesced
        assert stats["batches"] < stats["requests"], stats

    def test_different_models_do_not_block(self, cl, gbm, gbm2,
                                           monkeypatch):
        """A leader sleeping out model A's window must not delay model B:
        B (window 0) completes while A's batch is still open."""
        from h2o3_tpu import scoring

        # warm both sessions so execution time is dispatch-only
        scoring.score_request(gbm, _score_frame(40, 1))
        scoring.score_request(gbm2, _score_frame(40, 2))

        monkeypatch.setenv("H2O_TPU_SCORE_BATCH_WINDOW_MS", "1500")
        a_done = threading.Event()
        a_res = {}

        def run_a():
            a_res["pred"], _ = scoring.score_request(gbm, _score_frame(64, 3))
            a_done.set()

        ta = threading.Thread(target=run_a)
        ta.start()
        time.sleep(0.2)          # A's leader is inside its window now
        monkeypatch.setenv("H2O_TPU_SCORE_BATCH_WINDOW_MS", "0")
        pred_b, _ = scoring.score_request(gbm2, _score_frame(32, 4))
        assert pred_b.nrows == 32
        assert not a_done.is_set(), \
            "model B's request should finish while model A's batch is open"
        assert a_done.wait(timeout=60)
        ta.join(timeout=30)
        assert a_res["pred"].nrows == 64

    def test_batch_error_propagates_to_each_request(self, cl, gbm,
                                                    monkeypatch):
        """A failing frame inside a batch must fail its request (and not
        strand the batcher's leader slot for later requests)."""
        from h2o3_tpu import scoring

        monkeypatch.setenv("H2O_TPU_SCORE_BATCH_WINDOW_MS", "0")
        bad = Frame()
        bad.add("x1", Column.from_numpy(np.array(["a", "b"] * 8),
                                        ctype="enum"))
        bad.add("x2", Column.from_numpy(np.zeros(16)))
        with pytest.raises(ValueError):
            scoring.score_request(gbm, bad)
        # batcher recovered: next request works
        pred, _ = scoring.score_request(gbm, _score_frame(20, 6))
        assert pred.nrows == 20


class TestRestFastPath:
    def test_predictions_route_fast_vs_legacy(self, cl, gbm, monkeypatch):
        import json
        import urllib.request

        from h2o3_tpu.api.server import start_server
        from h2o3_tpu.core.dkv import DKV

        rng = np.random.default_rng(7)
        fr = Frame(key="score_batch_rest.hex")
        fr.add("x1", Column.from_numpy(rng.standard_normal(210)))
        fr.add("x2", Column.from_numpy(rng.standard_normal(210)))
        fr.install()
        srv = start_server(port=0)
        try:
            base = f"http://127.0.0.1:{srv.port}"

            def post(path):
                req = urllib.request.Request(base + path, data=b"",
                                             method="POST")
                with urllib.request.urlopen(req, timeout=120) as r:
                    return json.loads(r.read())

            fkey = str(fr.key)
            out = post(f"/3/Predictions/models/{gbm.key}/frames/{fkey}"
                       "?predictions_frame=fastpred")
            assert out["predictions_frame"]["name"] == "fastpred"
            monkeypatch.setenv("H2O_TPU_SCORE_FAST", "0")
            post(f"/3/Predictions/models/{gbm.key}/frames/{fkey}"
                 "?predictions_frame=slowpred")
            monkeypatch.delenv("H2O_TPU_SCORE_FAST")
            _assert_frames_bitwise(DKV.get("fastpred"), DKV.get("slowpred"),
                                   fr.nrows)
            # observability: the session shows up in /3/ScoringMetrics
            with urllib.request.urlopen(base + "/3/ScoringMetrics",
                                        timeout=30) as r:
                sm = json.loads(r.read())
            assert any(e["model"] == str(gbm.key) for e in sm["models"])
        finally:
            srv.stop()

    def test_incompatible_columns_rejected_before_broadcast(self, cl, gbm):
        """Satellite: column-compat validation happens pre-broadcast and
        returns 400 (not a 500 from inside adapt_test)."""
        import json
        import urllib.error
        import urllib.request

        from h2o3_tpu.api.server import start_server

        bad = Frame()
        bad.add("x1", Column.from_numpy(np.array(["a", "b"] * 30),
                                        ctype="enum"))
        bad.add("x2", Column.from_numpy(np.zeros(60)))
        bad.install()
        srv = start_server(port=0)
        try:
            base = f"http://127.0.0.1:{srv.port}"
            for route in ("/3/Predictions", "/4/Predictions"):
                req = urllib.request.Request(
                    f"{base}{route}/models/{gbm.key}/frames/{bad.key}",
                    data=b"", method="POST")
                with pytest.raises(urllib.error.HTTPError) as ei:
                    urllib.request.urlopen(req, timeout=60)
                assert ei.value.code == 400
                body = json.loads(ei.value.read())
                assert "numeric in training, enum in test" \
                    in json.dumps(body)
        finally:
            srv.stop()


@pytest.mark.slow
class TestBatchingStress:
    def test_many_concurrent_mixed_sizes(self, cl, gbm, monkeypatch):
        """Soak: 24 concurrent mixed-size requests through the batcher —
        every response is the exact per-request slice."""
        rng = np.random.default_rng(11)
        sizes = [int(s) for s in rng.integers(5, 2000, 24)]
        frames = [_score_frame(s, 1000 + i) for i, s in enumerate(sizes)]
        expected = [gbm.predict(fr) for fr in frames]
        monkeypatch.setenv("H2O_TPU_SCORE_BATCH_WINDOW_MS", "20")
        preds = _concurrent_scores(gbm, frames)
        for fr, exp, got in zip(frames, expected, preds):
            _assert_frames_bitwise(exp, got, fr.nrows)
